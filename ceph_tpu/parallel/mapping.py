"""Bulk PG mapping: the whole cluster's PG->OSD table in one device pass.

Replaces the reference's ParallelPGMapper thread pool
(src/osd/OSDMapMapping.h:18-120, used by the mgr and by OSDMonitor to
prime pg_temp at OSDMonitor.cc:728-735,1067): instead of sharding PG
ranges over threads, all PGs of a pool become one vector batch through
the jitted CRUSH kernel; the sparse exception tables (pg_temp, upmaps)
and the up-filter/affinity steps are applied on the host, where they
are cheap and data-dependent.

Falls back to the scalar pipeline per-PG when the crush map is outside
the device scope (non-straw2 buckets, multi-choose rules).
"""

from __future__ import annotations

import numpy as np

from ..models.crushmap import ITEM_NONE
from ..ops.crush.hashes import hash32_2_v
from ..osd.osdmap import OSDMap, PGPool, pg_t, ceph_stable_mod


class OSDMapMapping:
    """Caches up/acting for every PG of every pool (OSDMapMapping.h:174)."""

    def __init__(self, osdmap: OSDMap):
        self.epoch = osdmap.epoch
        self.up: dict[pg_t, list[int]] = {}
        self.up_primary: dict[pg_t, int] = {}
        self.acting: dict[pg_t, list[int]] = {}
        self.acting_primary: dict[pg_t, int] = {}
        self._build(osdmap)

    def _build(self, osdmap: OSDMap) -> None:
        for pool in osdmap.pools.values():
            try:
                self._build_pool_device(osdmap, pool)
            except ValueError:
                self._build_pool_scalar(osdmap, pool)

    # -- vectorized pool mapping ------------------------------------------

    def _build_pool_device(self, osdmap: OSDMap, pool: PGPool) -> None:
        from ..ops.crush.device import DeviceMapper

        dm = DeviceMapper(osdmap.crush)
        pgs = [pg_t(pool.id, ps) for ps in range(pool.pg_num)]
        pps = pps_for_pool(pool, np.arange(pool.pg_num))
        raw = dm.do_rule_batch(pool.crush_rule, pps, pool.size,
                               osdmap.osd_weight)
        raw = np.asarray(raw)
        for i, pg in enumerate(pgs):
            row = [int(v) for v in raw[i]]
            self._finish_pg(osdmap, pool, pg, int(pps[i]), row)

    # -- scalar fallback ---------------------------------------------------

    def _build_pool_scalar(self, osdmap: OSDMap, pool: PGPool) -> None:
        for ps in range(pool.pg_num):
            pg = pg_t(pool.id, ps)
            raw, pps = osdmap._pg_to_raw_osds(pool, pg)
            self._finish_pg(osdmap, pool, pg, pps, raw)

    def _finish_pg(self, osdmap: OSDMap, pool: PGPool, pg: pg_t,
                   pps: int, raw: list[int]) -> None:
        osdmap._remove_nonexistent_osds(pool, raw)
        osdmap._apply_upmap(pool, pg, raw)
        up = osdmap._raw_to_up_osds(pool, raw)
        up_primary = osdmap._pick_primary(up)
        up_primary = osdmap._apply_primary_affinity(pps, pool, up,
                                                    up_primary)
        acting, acting_primary = osdmap._get_temp_osds(pool, pg)
        if not acting:
            acting = list(up)
            if acting_primary == -1:
                acting_primary = up_primary
        self.up[pg] = up
        self.up_primary[pg] = up_primary
        self.acting[pg] = acting
        self.acting_primary[pg] = acting_primary

    def get(self, pg: pg_t) -> tuple[list[int], int, list[int], int]:
        return (self.up.get(pg, []), self.up_primary.get(pg, -1),
                self.acting.get(pg, []), self.acting_primary.get(pg, -1))


def pps_for_pool(pool: PGPool, ps: np.ndarray) -> np.ndarray:
    """Vectorized raw_pg_to_pps over a pool's ps range
    (osd_types.cc:1815-1831)."""
    b, bmask = pool.pgp_num, pool.pgp_num_mask
    masked = np.where((ps & bmask) < b, ps & bmask, ps & (bmask >> 1))
    from ..osd.osdmap import FLAG_HASHPSPOOL

    if pool.flags & FLAG_HASHPSPOOL:
        return hash32_2_v(masked.astype(np.uint32),
                          np.uint32(pool.id)).astype(np.int64)
    return masked.astype(np.int64) + pool.id
