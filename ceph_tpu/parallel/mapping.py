"""Bulk PG mapping: the whole cluster's PG->OSD table in one device pass.

Replaces the reference's ParallelPGMapper thread pool
(src/osd/OSDMapMapping.h:18-120, used by the mgr and by OSDMonitor to
prime pg_temp at OSDMonitor.cc:728-735,1067): instead of sharding PG
ranges over threads, all PGs of a pool become one vector batch through
one jitted program that fuses do_rule with the whole post-CRUSH
pipeline (up-filter, compaction, primary pick, primary affinity —
OSDMap.cc:2626-2802).  Results stay dense numpy arrays per pool; the
sparse exception tables (pg_upmap*, pg_temp, primary_temp) are applied
by recomputing only the excepted PGs through the host scalar pipeline.

Falls back to the scalar pipeline per-PG when the crush map is outside
the device scope (non-straw2 buckets, multi-choose rules).

Device dispatches route through the shared device runtime
(ceph_tpu.device.runtime) onto one mesh chip — the caller's affinity
chip when given (an OSD passes its bound chip so per-chip isolation
holds for mapping too), else the first available chip: each pool pass
is admitted under the "mapping" class (weight below client/recovery
EC, so a full-cluster remap cannot starve EC writes of the
accelerator), carries a DispatchTicket for the exporter, and degrades
to the scalar host pipeline when admission pushes back (DeviceBusy)
or the chip is in device-loss fallback.  A dispatch failure poisons
only the chip it ran on and this build finishes on the host path.
"""

from __future__ import annotations

import numpy as np

from ..device.runtime import DeviceBusy, DeviceRuntime, K_MAPPING
from ..models.crushmap import ITEM_NONE
from ..ops.crush.hashes import hash32_2_v
from ..osd.osdmap import OSD_EXISTS, OSD_UP, OSDMap, PGPool, pg_t

class PoolMapping:
    """Dense up/acting arrays for one pool ([pg_num, size] int32 with
    ITEM_NONE holes; compacted rows for replicated pools)."""

    __slots__ = ("pool_id", "can_shift", "up", "up_primary", "acting",
                 "acting_primary")

    def __init__(self, pool: PGPool, up: np.ndarray,
                 up_primary: np.ndarray):
        self.pool_id = pool.id
        self.can_shift = pool.can_shift_osds()
        self.up = up
        self.up_primary = up_primary
        self.acting = up.copy()
        self.acting_primary = up_primary.copy()

    def _row(self, arr: np.ndarray, ps: int) -> list[int]:
        row = arr[ps].tolist()
        if self.can_shift:
            return [v for v in row if v != ITEM_NONE]
        return row

    def get(self, ps: int) -> tuple[list[int], int, list[int], int]:
        return (self._row(self.up, ps), int(self.up_primary[ps]),
                self._row(self.acting, ps), int(self.acting_primary[ps]))


class OSDMapMapping:
    """Caches up/acting for every PG of every pool (OSDMapMapping.h:174)
    as dense arrays."""

    def __init__(self, osdmap: OSDMap, device_mapper=None,
                 runtime=None, chip: int | None = None):
        self.epoch = osdmap.epoch
        self.pools: dict[int, PoolMapping] = {}
        self.device_pools = 0      # pools mapped on device this build
        self.scalar_pools = 0      # pools that fell back to host
        self._build(osdmap, device_mapper, runtime, chip)

    def _build(self, osdmap: OSDMap, device_mapper, runtime,
               chip: int | None) -> None:
        state = np.asarray(osdmap.osd_state, dtype=np.int32)
        exists = (state & OSD_EXISTS) != 0
        isup = (state & OSD_UP) != 0
        aff = (np.asarray(osdmap.osd_primary_affinity, dtype=np.int32)
               if osdmap.osd_primary_affinity is not None else None)
        dm = device_mapper
        rt = runtime or DeviceRuntime.get()
        for pool in osdmap.pools.values():
            try:
                target = rt.route(chip)
                if target is None or not target.available:
                    raise ValueError("mapping chip in fallback")
                if dm is None:
                    dm = osdmap.device_mapper()
                up, prim = self._map_pool_ticketed(
                    osdmap, pool, dm, target, exists, isup, aff)
            except (ValueError, DeviceBusy):
                # outside device scope, admission pushback, or
                # device-loss fallback: the scalar pipeline is the
                # always-correct degradation
                up, prim = self._map_pool_scalar(osdmap, pool)
                self.scalar_pools += 1
            else:
                self.device_pools += 1
            pm = PoolMapping(pool, up, prim)
            self._apply_exceptions(osdmap, pool, pm)
            self.pools[pool.id] = pm

    def _map_pool_ticketed(self, osdmap, pool, dm, chip,
                           exists, isup, aff):
        """One pool pass under a mapping-class dispatch ticket on the
        routed chip.  Sync context (map advance runs outside any op
        coroutine), so admission is the non-blocking form — a full
        dispatch queue degrades this pass to the scalar path rather
        than queueing device work behind EC flushes."""
        ticket = chip.open_ticket(K_MAPPING,
                                  chip.rt.bucket_for(pool.pg_num),
                                  pool.pg_num * pool.size * 4)
        chip.try_admit(ticket)
        try:
            chip.launch(ticket)     # injected-fault hook
            up, prim = self._map_pool_device(osdmap, pool, dm,
                                             exists, isup, aff)
        except ValueError:
            # map outside device scope: a scalar-fallback condition,
            # not a device loss
            chip.finish(ticket, ok=False)
            raise
        except Exception as e:      # DeviceLost + real device faults
            chip.finish(ticket, ok=False, error=e)
            chip.poison(e)
            raise ValueError("device mapping dispatch failed") from e
        chip.finish(ticket, ok=True)
        return up, prim

    # -- vectorized pool mapping ------------------------------------------

    def _map_pool_device(self, osdmap: OSDMap, pool: PGPool, dm,
                         exists, isup, aff):
        from ..osd.osdmap import FLAG_HASHPSPOOL
        return dm.map_pool_batch(
            pool.crush_rule, pool.size, pool.pg_num, pool.pgp_num,
            pool.pgp_num_mask, pool.id,
            bool(pool.flags & FLAG_HASHPSPOOL), osdmap.osd_weight,
            exists, isup, aff, can_shift=pool.can_shift_osds())

    # -- scalar fallback ---------------------------------------------------

    def _map_pool_scalar(self, osdmap: OSDMap, pool: PGPool):
        up = np.full((pool.pg_num, pool.size), ITEM_NONE, np.int32)
        prim = np.full((pool.pg_num,), -1, np.int32)
        for ps in range(pool.pg_num):
            pg = pg_t(pool.id, ps)
            raw, pps = osdmap._pg_to_raw_osds(pool, pg)
            row = osdmap._raw_to_up_osds(pool, raw)
            p = osdmap._pick_primary(row)
            p = osdmap._apply_primary_affinity(pps, pool, row, p)
            up[ps, :len(row)] = row
            prim[ps] = p
        return up, prim

    # -- sparse exceptions -------------------------------------------------

    def _apply_exceptions(self, osdmap: OSDMap, pool: PGPool,
                          pm: PoolMapping) -> None:
        """Recompute the (few) PGs carrying upmap/temp entries through
        the exact scalar pipeline and overwrite their rows."""
        excepted: set[int] = set()
        for table in (osdmap.pg_upmap, osdmap.pg_upmap_items,
                      osdmap.pg_upmap_primaries, osdmap.pg_temp,
                      osdmap.primary_temp):
            for pg in table:
                if pg.pool == pool.id and pg.ps < pool.pg_num:
                    excepted.add(pg.ps)
        for ps in excepted:
            pg = pg_t(pool.id, ps)
            up, upp, acting, actingp = osdmap.pg_to_up_acting_osds(pg)
            self._write_row(pm.up, ps, up)
            pm.up_primary[ps] = upp
            self._write_row(pm.acting, ps, acting)
            pm.acting_primary[ps] = actingp

    @staticmethod
    def _write_row(arr: np.ndarray, ps: int, vals: list[int]) -> None:
        n = min(len(vals), arr.shape[1])
        arr[ps, :n] = vals[:n]
        arr[ps, n:] = ITEM_NONE

    # -- lookup ------------------------------------------------------------

    def get(self, pg: pg_t) -> tuple[list[int], int, list[int], int]:
        pm = self.pools.get(pg.pool)
        if pm is None or pg.ps >= pm.up.shape[0]:
            return [], -1, [], -1
        return pm.get(pg.ps)


def pps_for_pool(pool: PGPool, ps: np.ndarray) -> np.ndarray:
    """Vectorized raw_pg_to_pps over a pool's ps range."""
    from ..ops.crush.hashes import pps_seed_v
    from ..osd.osdmap import FLAG_HASHPSPOOL
    return pps_seed_v(ps, pool.pgp_num, pool.pgp_num_mask, pool.id,
                      bool(pool.flags & FLAG_HASHPSPOOL))
