"""Manager daemon: cluster-wide aggregation + autonomous balancing.

Condensed analog of src/mgr/ (DaemonServer.cc receiving every
daemon's perf-counter reports, ClusterState caching maps) plus the two
mgr python modules the survey calls first-class:

* prometheus — ONE scrape endpoint exposing per-OSD op counters and a
  PG-state summary for the whole cluster (pybind/mgr/prometheus);
* balancer  — a timer loop running the upmap optimizer
  (pybind/mgr/balancer/module.py Module.serve) and committing the
  computed pg_upmap_items through the monitor, so a skewed cluster
  converges without operator action.

Registration rides the map: `mgr register` stores this daemon's
address in OSDMap.mgr_addr (the MgrMap role) and every OSD's
heartbeat loop ships MMgrReport there (OSD::ms_handle ->
MgrClient::send_report in the reference).
"""

from __future__ import annotations

import asyncio

from ..msg import Messenger
from ..msg.messenger import ms_compress_from_conf
from ..msg.messages import (MConfig, MMgrReport, MMonCommand, MMonCommandAck,
                            MMonGetMap, MMonMgrDigest, MMonSubscribe,
                            MOSDMapMsg)
from ..osd.osdmap import OSDMap, consume_map_payload
from ..utils.context import Context
from ..utils.exporter import PrometheusExporter
from .pgmap import PGMap, RATE_KEYS


def _fam_header(lines: list, fam: str, kind: str,
                desc: str) -> None:
    """Append one family's `# HELP` + `# TYPE` header (the
    exposition-format pair the exporter lint requires)."""
    lines.append("# HELP %s %s" % (fam, desc))
    lines.append("# TYPE %s %s" % (fam, kind))


def ingest_prom_lines(pgmap) -> list[str]:
    """Telemetry-fabric ingest families rendered from a PGMap's
    accounting (module-level so `bench.py --scale`'s ingest leg can
    lint the exposition without a live Manager): per-format report
    row/byte counters, the apply-latency histogram, the row-loop
    fallback counter, and the visible prune counters."""
    from ..utils.exporter import hist_lines
    ing = pgmap.ingest
    lines: list[str] = []
    for fam, key in (("ceph_tpu_mgr_report_rows_total", "rows"),
                     ("ceph_tpu_mgr_report_bytes_total", "bytes")):
        _fam_header(lines, fam, "counter",
                    "MMgrReport stat %s ingested by wire format"
                    % key)
        for fmt in ("columnar", "legacy"):
            lines.append('%s{format="%s"} %d'
                         % (fam, fmt, ing[key][fmt]))
    lines.extend(hist_lines("ceph_tpu_mgr_ingest_seconds",
                            ing["seconds_hist"],
                            desc="per-report PGMap apply latency"))
    _fam_header(lines, "ceph_tpu_mgr_ingest_fallback_rows_total",
                "counter",
                "stat rows that fell back to the legacy row loop")
    lines.append("ceph_tpu_mgr_ingest_fallback_rows_total %d"
                 % ing["fallback_rows"])
    _fam_header(lines, "ceph_tpu_mgr_rows_pruned_total", "counter",
                "PGMap rows reclaimed, by prune reason")
    for reason, count in (("stale", pgmap.pruned_stale),
                          ("pool", pgmap.pruned_pool),
                          ("daemon", pgmap.pruned_daemons)):
        lines.append(
            'ceph_tpu_mgr_rows_pruned_total{reason="%s"} %d'
            % (reason, count))
    return lines


class Manager:
    def __init__(self, mon_addr, ctx: Context | None = None,
                 balance_interval: float = 5.0):
        self.mon_addrs = ([mon_addr] if isinstance(mon_addr, str)
                          else list(mon_addr))
        self.ctx = ctx or Context("mgr")
        from ..msg.auth import AuthContext
        self.msgr = Messenger(
            "mgr", auth=AuthContext.from_conf(self.ctx.conf),
            compress=ms_compress_from_conf(self.ctx.conf))
        self.msgr.add_dispatcher(self)
        self.osdmap: OSDMap = OSDMap()
        self.balance_interval = balance_interval
        self.balancer_enabled = True
        self.balancer_rounds = 0
        self.balancer_changes = 0
        # daemon -> {"perf": .., "pg_states": .., "stamp": ..}
        self.daemon_reports: dict[str, dict] = {}
        # cluster statistics plane: per-PG stat rows folded into the
        # PGMap; a periodic digest feeds the monitors (status/df/
        # pool-stats + PG_* health)
        self.pgmap = PGMap(stale_after=float(
            self.ctx.conf.get("mgr_stats_stale_after", 15.0)))
        self.stats_period = float(
            self.ctx.conf.get("mgr_stats_period", 1.0))
        self.digests_sent = 0
        # tenant SLO plane: multi-window burn-rate engine over the
        # per-tenant stage histograms the OSDs report; its verdicts
        # ride the digest into the mon's SLO_LATENCY/SLO_BURN checks
        from .slo import SLOEngine
        self.slo = SLOEngine(self.ctx)
        # history plane: fixed-memory downsampled rings fed each
        # stats tick from the folded digest, plus the EWMA/z-score
        # anomaly rules whose verdicts ride the digest into the
        # mon's committed PERF_ANOMALY edge
        from .history import AnomalyEngine, HistoryStore
        self.history = HistoryStore(self.ctx)
        self.anomaly = AnomalyEngine(self.ctx)
        self.history_ingest_s = 0.0
        self.exporter = PrometheusExporter(self.ctx)
        # cluster-log handle: mgr events ride the same
        # LogClient -> MLog -> LogMonitor pipeline as OSD events
        from ..trace import LogClient
        self.clog = LogClient(self.ctx, "mgr",
                              send_fn=self._broadcast_mons)
        self._tid = 0
        self._cmd_futures: dict[int, asyncio.Future] = {}
        self._tasks: list = []

    def _broadcast_mons(self, msg) -> None:
        for i, addr in enumerate(self.mon_addrs):
            self.msgr.send_to(addr, msg, entity_hint="mon.%d" % i)

    # -- lifecycle ---------------------------------------------------------

    async def start(self, host: str = "127.0.0.1", port: int = 0,
                    http_port: int = 0) -> str:
        addr = await self.msgr.bind(host, port)
        mon = self.msgr.connect_to(self.mon_addrs[0],
                                   entity_hint="mon.0")
        mon.send(MMonSubscribe(start=1))
        await self._register()
        self.clog.info("mgr active at %s" % self.msgr.addr)
        self.http_addr = await self.exporter.start(host, http_port)
        self._register_cluster_gauges()
        self._tasks.append(self.msgr.spawn(self._balancer_loop()))
        self._tasks.append(self.msgr.spawn(self._stats_loop()))
        self.ctx.log.info("mgr", "mgr serving at %s (metrics %s)"
                          % (addr, self.http_addr))
        return addr

    async def shutdown(self) -> None:
        await self.exporter.stop()
        await self.msgr.shutdown()

    async def _register(self) -> None:
        await self.mon_command("mgr register", addr=self.msgr.addr)

    # -- dispatch ----------------------------------------------------------

    def ms_dispatch(self, conn, msg) -> bool:
        if isinstance(msg, MConfig):
            self.ctx.conf.apply_mon_values(msg.values or {})
            return True
        from ..msg.messages import MLogAck
        if isinstance(msg, MLogAck):
            self.clog.handle_ack(msg.who, int(msg.last or 0),
                                 inc=getattr(msg, "inc", None))
            return True
        if isinstance(msg, MOSDMapMsg):
            self.osdmap, _ = consume_map_payload(
                self.osdmap, msg.full, msg.incrementals)
            return True
        if isinstance(msg, MMgrReport):
            now = asyncio.get_event_loop().time()
            self.daemon_reports[msg.daemon] = {
                "perf": msg.perf or {},
                "pg_states": msg.pg_states or {},
                "num_pgs": msg.num_pgs or 0,
                "num_objects": msg.num_objects or 0,
                "epoch": msg.epoch,
                "stamp": now,
            }
            self.pgmap.apply_report(
                msg.daemon, msg.pg_stats, msg.osd_stats, now,
                pg_stats_cols=getattr(msg, "pg_stats_cols", None),
                nbytes=getattr(msg, "wire_bytes", None))
            return True
        if isinstance(msg, MMonCommandAck):
            fut = self._cmd_futures.pop(msg.tid, None)
            if fut is not None and not fut.done():
                if msg.result == 0:
                    fut.set_result(msg.out or {})
                else:
                    fut.set_exception(IOError(msg.result, msg.out))
            return True
        return False

    async def mon_command(self, prefix: str, timeout: float = 10.0,
                          **args) -> dict:
        cmd = {"prefix": prefix}
        cmd.update(args)
        self._tid += 1
        tid = self._tid
        fut = asyncio.get_event_loop().create_future()
        self._cmd_futures[tid] = fut
        self.msgr.send_to(self.mon_addrs[0],
                          MMonCommand(tid=tid, cmd=cmd),
                          entity_hint="mon.0")
        return await asyncio.wait_for(fut, timeout)

    # -- prometheus surface ------------------------------------------------

    def _register_cluster_gauges(self) -> None:
        exp = self.exporter
        exp.add_gauge("cluster_osdmap_epoch",
                      lambda: self.osdmap.epoch, "map epoch")
        exp.add_gauge("cluster_num_osds",
                      lambda: self.osdmap.max_osd, "osds in map")
        exp.add_gauge(
            "cluster_num_up_osds",
            lambda: sum(1 for o in range(self.osdmap.max_osd)
                        if self.osdmap.is_up(o)), "up osds")
        exp.add_gauge("cluster_num_pools",
                      lambda: len(self.osdmap.pools), "pools")
        exp.add_gauge("mgr_daemons_reporting",
                      lambda: len(self.daemon_reports),
                      "daemons with a live report")
        exp.add_gauge("cluster_slow_ops", self._total_slow_ops,
                      "slow ops summed over every daemon's report")
        exp.add_gauge("balancer_rounds",
                      lambda: self.balancer_rounds,
                      "balancer optimizer runs")
        exp.add_gauge("balancer_changes",
                      lambda: self.balancer_changes,
                      "upmap items committed by the balancer")
        exp.add_gauge("history_cells",
                      lambda: self.history.cell_count(),
                      "retained history ring cells (bounded)")
        exp.add_gauge("history_ticks",
                      lambda: self.history.ticks,
                      "digest ticks folded into the history rings")
        exp.add_gauge("history_ingest_seconds",
                      lambda: round(self.history_ingest_s, 6),
                      "cumulative history-plane ingest time")
        exp.add_gauge("history_anomalies_active",
                      lambda: len(self.anomaly.active),
                      "series currently flagged by the anomaly rules")
        exp.add_renderer(self._render_reports)
        exp.add_renderer(self._render_pgmap)
        exp.add_renderer(self._render_event_plane)
        exp.add_renderer(self._render_tenants)
        exp.add_renderer(self._render_ingest)
        exp.add_renderer(self._render_net)

    def _total_slow_ops(self) -> int:
        """Cluster-wide slow-op count aggregated from the per-daemon
        reports (the mgr-side mirror of the mon's SLOW_OPS input)."""
        total = 0
        for rep in self.daemon_reports.values():
            osd_grp = (rep.get("perf") or {}).get("osd") or {}
            v = osd_grp.get("slow_ops", 0)
            if isinstance(v, (int, float)):
                total += int(v)
        return total

    def _render_reports(self) -> list[str]:
        """Per-daemon series from the MMgrReports (the prometheus
        module's per-daemon metric families).  Stage-latency
        histograms (PerfCounters pow2 buckets) render as labeled
        Prometheus histogram series.  Every family gets exactly one
        `# TYPE` line (the exposition-format requirement the exporter
        lint pins)."""
        from ..utils.exporter import hist_lines
        lines: list[str] = []
        typed: set[str] = set()

        def emit(family: str, label: str, value, kind="gauge",
                 desc=None):
            if family not in typed:
                typed.add(family)
                _fam_header(lines, family, kind,
                            desc or "per-daemon %s from MMgrReports"
                            % family.split("ceph_tpu_daemon_")[-1])
            lines.append("%s%s %g" % (family, label, value))

        pg_totals: dict[str, int] = {}
        for daemon in sorted(self.daemon_reports):
            rep = self.daemon_reports[daemon]
            label = '{daemon="%s"}' % daemon
            for grp, counters in sorted(
                    (rep.get("perf") or {}).items()):
                if not isinstance(counters, dict):
                    continue
                for cname, val in sorted(counters.items()):
                    if isinstance(val, (int, float)):
                        emit("ceph_tpu_daemon_%s_%s" % (grp, cname),
                             label, val, kind="counter")
                    elif isinstance(val, dict) \
                            and "buckets_us_pow2" in val:
                        lines.extend(hist_lines(
                            "ceph_tpu_daemon_%s_%s" % (grp, cname),
                            val["buckets_us_pow2"],
                            labels='daemon="%s"' % daemon,
                            typed=typed,
                            desc="per-daemon %s.%s latency "
                                 "histogram (us pow2 buckets)"
                                 % (grp, cname)))
            emit("ceph_tpu_daemon_num_pgs", label,
                 rep.get("num_pgs") or 0)
            emit("ceph_tpu_daemon_num_objects", label,
                 rep.get("num_objects") or 0)
            for state, n in (rep.get("pg_states") or {}).items():
                pg_totals[state] = pg_totals.get(state, 0) + n
        for state in sorted(pg_totals):
            emit("ceph_tpu_pg_state", '{state="%s"}' % state,
                 pg_totals[state],
                 desc="cluster PG count by state")
        return lines

    def _render_pgmap(self) -> list[str]:
        """PGMap-derived families: per-pool usage + IO/recovery rates
        and cluster totals — the `ceph -s` io:/recovery: lines and
        `df` columns as scrapeable series, plus the cluster op-size
        histogram the workload-aware warmup feeds on."""
        now = asyncio.get_event_loop().time()
        pools = set(self.osdmap.pools)
        per_pool = self.pgmap.pool_totals(now, pools)
        lines: list[str] = []
        gauges = ("objects", "bytes", "degraded", "misplaced",
                  "unfound", "scrub_errors") + RATE_KEYS
        for g in gauges:
            fam = "ceph_tpu_pool_%s" % g
            _fam_header(lines, fam, "gauge",
                        "per-pool %s from the PGMap fold" % g)
            for pid in sorted(per_pool):
                name = (self.osdmap.pools[pid].name
                        if pid in self.osdmap.pools else str(pid))
                lines.append('%s{pool="%s",pool_id="%d"} %g'
                             % (fam, name, pid, per_pool[pid][g]))
        totals = {g: sum(r[g] for r in per_pool.values())
                  for g in gauges}
        for g in gauges:
            fam = "ceph_tpu_cluster_%s" % g
            _fam_header(lines, fam, "gauge",
                        "cluster-total %s from the PGMap fold" % g)
            lines.append("%s %g" % (fam, totals[g]))
        # repair-traffic plane: per-codec recovery bytes summed
        # across the live fleet (read from survivors via
        # minimum_to_decode's minimal sets / moved to rebuilt
        # shards) — the codec-labeled figure the LRC-vs-RS oracle
        # compares
        repair: dict[str, dict] = {}
        for row in self.pgmap.live_osd_stats(now).values():
            for cname, rrow in (row.get("repair") or {}).items():
                agg = repair.setdefault(str(cname),
                                        {"read": 0, "moved": 0})
                agg["read"] += int(rrow.get("read", 0) or 0)
                agg["moved"] += int(rrow.get("moved", 0) or 0)
        _fam_header(lines, "ceph_tpu_repair_bytes_read_total",
                    "counter",
                    "survivor shard bytes read by recovery, by codec")
        for cname in sorted(repair):
            lines.append(
                'ceph_tpu_repair_bytes_read_total{codec="%s"} %d'
                % (cname, repair[cname]["read"]))
        _fam_header(lines, "ceph_tpu_repair_bytes_moved_total",
                    "counter",
                    "rebuilt shard bytes moved by recovery, by codec")
        for cname in sorted(repair):
            lines.append(
                'ceph_tpu_repair_bytes_moved_total{codec="%s"} %d'
                % (cname, repair[cname]["moved"]))
        # data-reduction plane: per-pool dedup counters summed
        # across the live fleet (chunks newly stored vs answered by
        # an existing content address, logical bytes that never hit
        # the chunk store) — the pool-labeled figure bench --dedup
        # cross-checks against the chunk store's actual usage
        dedup: dict[str, dict] = {}
        for row in self.pgmap.live_osd_stats(now).values():
            for pid, drow in (row.get("dedup") or {}).items():
                agg = dedup.setdefault(
                    str(pid), {"chunks_stored": 0,
                               "chunks_deduped": 0, "bytes_saved": 0})
                for kk in agg:
                    agg[kk] += int(drow.get(kk, 0) or 0)
        _fam_header(lines, "ceph_tpu_dedup_chunks_stored_total",
                    "counter",
                    "chunks newly written to the chunk store")
        for pid in sorted(dedup):
            lines.append(
                'ceph_tpu_dedup_chunks_stored_total{pool_id="%s"} %d'
                % (pid, dedup[pid]["chunks_stored"]))
        _fam_header(lines, "ceph_tpu_dedup_chunks_deduped_total",
                    "counter",
                    "chunks answered by an existing content address")
        for pid in sorted(dedup):
            lines.append(
                'ceph_tpu_dedup_chunks_deduped_total{pool_id="%s"} %d'
                % (pid, dedup[pid]["chunks_deduped"]))
        _fam_header(lines, "ceph_tpu_dedup_bytes_saved_total",
                    "counter",
                    "logical bytes that never hit the chunk store")
        for pid in sorted(dedup):
            lines.append(
                'ceph_tpu_dedup_bytes_saved_total{pool_id="%s"} %d'
                % (pid, dedup[pid]["bytes_saved"]))
        # integrity-plane summary series (the scrub_* families the
        # exporter lint pins): damaged-PG count beside the summed
        # error total the pool/cluster gauges above already carry
        _fam_header(lines, "ceph_tpu_scrub_inconsistent_pgs",
                    "gauge",
                    "PGs with unrepaired scrub inconsistencies")
        lines.append("ceph_tpu_scrub_inconsistent_pgs %d"
                     % self.pgmap.inconsistent_pgs(now, pools))
        _fam_header(lines, "ceph_tpu_scrub_errors_total", "gauge",
                    "summed scrub error count across pools")
        lines.append("ceph_tpu_scrub_errors_total %d"
                     % totals.get("scrub_errors", 0))
        hist = self.pgmap.op_size_hist(now)
        if hist:
            fam = "ceph_tpu_cluster_op_size_bytes"
            _fam_header(lines, fam, "histogram",
                        "client write size distribution "
                        "(pow2 byte buckets)")
            cum = 0
            for i, n in enumerate(hist):
                cum += n
                lines.append('%s_bucket{le="%g"} %d'
                             % (fam, float(1 << (i + 1)), cum))
            lines.append('%s_bucket{le="+Inf"} %d' % (fam, cum))
            lines.append("%s_count %d" % (fam, cum))
        return lines

    def _render_event_plane(self) -> list[str]:
        """Cluster-log emission counters
        (ceph_tpu_log_messages_total{daemon,level}) from every
        daemon's clog handle (shipped in MMgrReport osd_stats; the
        mgr contributes its own handle directly) plus the per-OSD
        statfs axis (raw capacity/utilization)."""
        now = asyncio.get_event_loop().time()
        rows = self.pgmap.live_osd_stats(now)
        lines: list[str] = []
        fam = "ceph_tpu_log_messages_total"
        _fam_header(lines, fam, "counter",
                    "cluster-log emissions by daemon and level")
        clog_rows = {d: (row.get("log_messages") or {})
                     for d, row in rows.items()}
        clog_rows["mgr"] = self.clog.counts_wire()
        for daemon in sorted(clog_rows):
            for level in sorted(clog_rows[daemon]):
                lines.append(
                    '%s{daemon="%s",level="%s"} %d'
                    % (fam, daemon, level, clog_rows[daemon][level]))
        for fam, key in (("ceph_tpu_osd_statfs_total_bytes", "total"),
                         ("ceph_tpu_osd_statfs_used_bytes", "used")):
            _fam_header(lines, fam, "gauge",
                        "per-OSD store statfs %s bytes" % key)
            for daemon in sorted(rows):
                sf = rows[daemon].get("statfs")
                if sf:
                    lines.append('%s{daemon="%s"} %d'
                                 % (fam, daemon,
                                    int(sf.get(key) or 0)))
        return lines

    def _tenant_rows(self, now: float) -> dict[str, dict]:
        """Cluster-aggregate per-tenant counters from the live daemon
        reports, with label cardinality CAPPED at `tenant_label_max`:
        the busiest tenants keep their own rows, the tail folds into
        "other" — a tenant-id flood can never blow up the exporter's
        (or the digest's) label space."""
        agg: dict[str, dict] = {}
        for row in self.pgmap.live_osd_stats(now).values():
            for tenant, trow in (row.get("tenants") or {}).items():
                a = agg.setdefault(tenant, {
                    "ops": 0, "errors": 0, "total_hist": [0] * 32})
                a["ops"] += int(trow.get("ops") or 0)
                a["errors"] += int(trow.get("errors") or 0)
                th = (trow.get("stages") or {}).get("total")
                for i, v in enumerate((th or [])[:32]):
                    a["total_hist"][i] += int(v)
        cap = max(1, int(self.ctx.conf.get("tenant_label_max", 32)))
        if len(agg) <= cap:
            return agg
        keep = sorted(agg, key=lambda t: (-agg[t]["ops"], t))[:cap - 1]
        out = {t: agg[t] for t in keep}
        other = out.setdefault("other", {
            "ops": 0, "errors": 0, "total_hist": [0] * 32})
        for t, a in agg.items():
            if t in keep:
                continue
            other["ops"] += a["ops"]
            other["errors"] += a["errors"]
            for i, v in enumerate(a["total_hist"]):
                other["total_hist"][i] += v
        return out

    def _render_tenants(self) -> list[str]:
        """Tenant-labeled families (cardinality-capped): per-tenant
        op/error totals, the end-to-end latency histogram, and the
        SLO engine's burn figures — the scrape surface of the tenant
        SLO plane."""
        import asyncio as _aio

        from ..utils.exporter import hist_lines
        now = _aio.get_event_loop().time()
        rows = self._tenant_rows(now)
        if not rows:
            return []
        lines: list[str] = []
        for fam, key in (("ceph_tpu_tenant_ops_total", "ops"),
                         ("ceph_tpu_tenant_errors_total", "errors")):
            _fam_header(lines, fam, "counter",
                        "per-tenant %s (cardinality-capped)" % key)
            for t in sorted(rows):
                lines.append('%s{tenant="%s"} %d'
                             % (fam, t, rows[t][key]))
        typed: set[str] = set()
        for t in sorted(rows):
            lines.extend(hist_lines("ceph_tpu_tenant_op_seconds",
                                    rows[t]["total_hist"],
                                    labels='tenant="%s"' % t,
                                    typed=typed,
                                    desc="per-tenant end-to-end op "
                                         "latency (us pow2 buckets)"))
        slo = self.slo.evaluate(now)
        for fam, key in (("ceph_tpu_tenant_slo_burn_fast",
                          "burn_fast"),
                         ("ceph_tpu_tenant_slo_burn_slow",
                          "burn_slow"),
                         ("ceph_tpu_tenant_p99_ms", "p99_ms")):
            _fam_header(lines, fam, "gauge",
                        "per-tenant SLO engine %s" % key)
            for t in sorted(slo):
                if t not in rows:
                    continue    # capped out of the label space
                v = slo[t].get(key)
                if v is not None:
                    lines.append('%s{tenant="%s"} %g' % (fam, t, v))
        return lines

    def _render_ingest(self) -> list[str]:
        """Telemetry-fabric ingest observability: report rows/bytes
        by wire format, apply latency, fallback + prune counters —
        the stat pipeline measured like every other plane."""
        return ingest_prom_lines(self.pgmap)

    def _render_net(self) -> list[str]:
        """Network-plane families (NET_SERIES): per-daemon resend/
        replay/queue figures, per-peer wire byte totals and the
        heartbeat RTT matrix.  Peer cardinality is capped per daemon
        like tenant labels: the busiest peers keep their own rows,
        the tail folds into "other" — a client-entity flood can
        never blow up the exporter's label space."""
        import asyncio as _aio
        now = _aio.get_event_loop().time()
        rows: dict[str, dict] = {}
        for daemon, srow in sorted(
                self.pgmap.live_osd_stats(now).items()):
            nrow = srow.get("net")
            if nrow:
                rows[daemon] = nrow
        if not rows:
            return []
        cap = max(1, int(self.ctx.conf.get("net_label_max", 8)))
        lines: list[str] = []
        for fam, key, kind, desc in (
                ("ceph_tpu_net_resends_total", "resends", "counter",
                 "lossless payloads requeued for session replay"),
                ("ceph_tpu_net_replays_total", "replays", "counter",
                 "duplicate frames absorbed by seq dedup after"
                 " reconnect"),
                ("ceph_tpu_net_mark_downs_total", "mark_downs",
                 "counter", "administrative connection teardowns"),
                ("ceph_tpu_net_queue_depth", "queue_depth", "gauge",
                 "frames waiting in send queues")):
            _fam_header(lines, fam, kind, desc)
            for daemon in rows:
                lines.append('%s{daemon="%s"} %g'
                             % (fam, daemon,
                                float(rows[daemon].get(key, 0)
                                      or 0)))

        def folded(peers: dict) -> dict:
            if len(peers) <= cap:
                return peers
            keep = sorted(peers, key=lambda p:
                          (-int(peers[p].get("tx_bytes", 0) or 0),
                           p))[:cap - 1]
            out = {p: peers[p] for p in keep}
            other = {"tx_bytes": 0, "rx_bytes": 0}
            for p, r in peers.items():
                if p in out:
                    continue
                other["tx_bytes"] += int(r.get("tx_bytes", 0) or 0)
                other["rx_bytes"] += int(r.get("rx_bytes", 0) or 0)
            out["other"] = other
            return out

        for fam, key in (("ceph_tpu_net_peer_tx_bytes_total",
                          "tx_bytes"),
                         ("ceph_tpu_net_peer_rx_bytes_total",
                          "rx_bytes")):
            _fam_header(lines, fam, "counter",
                        "per-peer wire %s (peer labels capped)"
                        % key)
            for daemon, nrow in rows.items():
                for peer, prow in sorted(folded(
                        dict(nrow.get("peers") or {})).items()):
                    lines.append('%s{daemon="%s",peer="%s"} %d'
                                 % (fam, daemon, peer,
                                    int(prow.get(key, 0) or 0)))
        fam = "ceph_tpu_net_rtt_ms"
        _fam_header(lines, fam, "gauge",
                    "per-peer heartbeat RTT, 5s window (ms)")
        for daemon, nrow in rows.items():
            rtt = dict(nrow.get("rtt_peers") or {})
            worst = sorted(rtt, key=lambda p: (-rtt[p], p))[:cap]
            for peer in sorted(worst):
                lines.append('%s{daemon="%s",peer="osd.%s"} %g'
                             % (fam, daemon, peer, rtt[peer]))
        for fam, key, desc in (
                ("ceph_tpu_net_backoff_seconds", "backoff_s",
                 "active redial backoff ramp (worst peer)"),
                ("ceph_tpu_net_handshake_seconds", "handshake_s",
                 "last completed handshake latency (worst peer)")):
            _fam_header(lines, fam, "gauge", desc)
            for daemon, nrow in rows.items():
                peers = nrow.get("peers") or {}
                v = max((float(p.get(key, 0.0) or 0.0)
                         for p in peers.values()), default=0.0)
                lines.append('%s{daemon="%s"} %g'
                             % (fam, daemon, v))
        return lines

    # -- stats loop (PGMap digest -> monitors) -----------------------------

    async def _stats_loop(self) -> None:
        """Periodically fold the PGMap into a digest and broadcast it
        to every monitor (MgrStatMonitor's report flow, broadcast like
        beacons so whichever mon leads next already holds it)."""
        while True:
            await asyncio.sleep(self.stats_period)
            self.clog.flush()       # re-send unacked clog entries
            if not self.daemon_reports:
                continue
            now = asyncio.get_event_loop().time()
            try:
                # reclaim rows the folds already ignore: dead
                # primaries past the prune window + deleted pools —
                # counted (ceph_tpu_mgr_rows_pruned_total), never
                # silent.  The pool filter only engages once the mgr
                # holds a pool table (a lagging map must not wipe
                # fresh rows; they would be refiltered next tick).
                self.pgmap.prune(
                    now,
                    pools=(set(self.osdmap.pools)
                           if self.osdmap.pools else None),
                    after=float(self.ctx.conf.get(
                        "mgr_stats_prune_after", 60.0)))
                digest = self.pgmap.digest(now, self.osdmap)
                # tenant SLO plane: ingest this tick's cumulative
                # tenant rows, evaluate the burn windows, and ship
                # the verdicts in the digest (the mon commits the
                # raise/clear edges through paxos)
                self.slo.ingest(now,
                                self.pgmap.live_osd_stats(now))
                digest["slo"] = self.slo.evaluate(now)
                # history plane: one extraction pass feeds both the
                # downsampled rings and the anomaly rules; active
                # anomalies ride the digest so the mon can commit
                # the PERF_ANOMALY raise/clear edges through paxos
                import time as _wall
                t_h0 = _wall.perf_counter()
                from .history import extract_samples
                samples = extract_samples(digest)
                self.history.ingest(_wall.time(), digest,
                                    samples=samples)
                digest["anomalies"] = self.anomaly.observe(samples)
                self.history_ingest_s += _wall.perf_counter() - t_h0
            except Exception as e:
                self.ctx.log.info("mgr", "digest failed: %r" % e)
                continue
            msg_fields = dict(digest=digest, epoch=self.osdmap.epoch)
            for i, addr in enumerate(self.mon_addrs):
                self.msgr.send_to(addr, MMonMgrDigest(**msg_fields),
                                  entity_hint="mon.%d" % i)
            self.digests_sent += 1

    # -- balancer loop -----------------------------------------------------

    async def _balancer_loop(self) -> None:
        """pybind/mgr/balancer Module.serve: periodically run the
        upmap optimizer against the current map and commit its
        pg_upmap_items through the monitor."""
        while True:
            await asyncio.sleep(self.balance_interval)
            if not self.balancer_enabled or not self.osdmap.pools:
                continue
            try:
                await self.balancer_tick()
            except Exception as e:
                self.ctx.log.info("mgr", "balancer failed: %r" % e)

    async def balancer_tick(self) -> dict:
        """One optimizer round + commit (shared by the autonomous
        loop and `bench.py --scale`).  Mode rides
        `mgr_balancer_mode`: 'batched' generates every candidate move
        and scores them in bulk device dispatches
        (scale.balancer.batched_calc_pg_upmaps — the TPU-scored
        balancer); 'sequential' keeps the reference's greedy
        calc_pg_upmaps walk.  Both emit items through the identical
        validity rules, so the committed upmaps agree in effect."""
        from ..osd.balancer import calc_pg_upmaps

        mode = str(self.ctx.conf.get("mgr_balancer_mode", "batched"))
        inc = self.osdmap.new_incremental()
        info: dict = {"mode": mode}
        if mode == "batched":
            from ..scale.balancer import batched_calc_pg_upmaps

            def opt():
                return batched_calc_pg_upmaps(
                    self.osdmap, inc, max_deviation=1.0,
                    max_changes=int(self.ctx.conf.get(
                        "mgr_balancer_max_changes", 48)))

            if self.osdmap.max_osd >= 200:
                # big maps: the raw-row build + scoring is seconds of
                # synchronous work on a CPU backend — run it off-loop
                # so beacons/digests keep flowing (vstart-size maps
                # stay inline: cheap, and clear of any thread overlap
                # with live EC dispatch)
                res = await asyncio.get_event_loop() \
                    .run_in_executor(None, opt)
            else:
                res = opt()
            n = res.changes
            info.update(
                candidates_scored=res.candidates_scored,
                device_rounds=res.device_rounds,
                host_rounds=res.host_rounds,
                stddev_before=res.stddev_before,
                stddev_after=res.stddev_after)
        else:
            n = calc_pg_upmaps(self.osdmap, inc, max_deviation=1.0,
                               max_iterations=32)
        info["changes"] = n
        self.balancer_rounds += 1
        removals = [pgid for pgid in inc.old_pg_upmap_items
                    if pgid not in inc.new_pg_upmap_items]
        if n or removals:
            await self._commit_upmaps(inc, removals)
        return info

    async def _commit_upmaps(self, inc, removals) -> None:
        for pgid, items in inc.new_pg_upmap_items.items():
            try:
                if items:
                    await self.mon_command(
                        "osd pg-upmap-items", pool=pgid.pool,
                        ps=pgid.ps,
                        mappings=[list(t) for t in items])
                else:
                    await self.mon_command(
                        "osd rm-pg-upmap-items", pool=pgid.pool,
                        ps=pgid.ps)
                self.balancer_changes += 1
            except Exception as e:
                self.ctx.log.info(
                    "mgr", "upmap commit failed: %r" % e)
        for pgid in removals:
            # stale entries the optimizer retired (e.g. the source
            # osd left the raw set) — committed as removals too
            try:
                await self.mon_command(
                    "osd rm-pg-upmap-items", pool=pgid.pool,
                    ps=pgid.ps)
                self.balancer_changes += 1
            except Exception as e:
                self.ctx.log.info(
                    "mgr", "upmap removal failed: %r" % e)
