"""PGMap: the mgr's fold of every OSD's per-PG stat rows.

Condensed analog of src/mon/PGMap.{h,cc} as maintained by the
MgrStatMonitor pipeline (OSD MPGStats -> DaemonServer -> PGMap
apply_incremental): primaries ship a stat row per PG they serve
(object/byte counts, degraded/misplaced/unfound tallies, cumulative
client-IO and recovery counters) inside their MMgrReports; this class
keeps the latest row per PG, derives **rates** from the delta between
two consecutive reports of the same primary (PGMap's pool_statfs
delta machinery), and renders:

* per-pool and cluster-wide totals (objects, bytes, degraded,
  misplaced, unfound) — the `df` / `osd pool stats` surface;
* client read/write ops/s + bytes/s and recovery objects/s + bytes/s
  — the `ceph -s` io: / recovery: lines;
* the digest the mgr periodically sends the monitors (MMonMgrDigest),
  from which the mon serves `status`/`df` and raises PG_DEGRADED /
  PG_AVAILABILITY.

Counter resets (primary restart or failover) surface as negative
deltas and clamp to zero — exactly one digest period of undercounted
rate, never a negative or wildly inflated one.

**Columnar storage** (the scale-plane shape): at 100k-1M PG rows the
per-tick fold (pool totals + state counts + digest) dominates the
mgr, so rows live in flat numpy columns — one int64/float64 array per
stat — and every fold is a vectorized masked pass (staleness window,
pool filter, per-pool segment sums) instead of a python dict walk.
Ingest stays row-wise (one primary's report is small); the fold is
where the rows multiply.  `DictPGMap` below preserves the original
dict-of-rows implementation as the golden reference the columnar fold
is pinned against (and the fold micro-benchmark's baseline).
"""

from __future__ import annotations

import numpy as np

RATE_COUNTERS = ("read_ops", "read_bytes", "write_ops", "write_bytes",
                 "recovery_ops", "recovery_bytes")

# digest keys carrying the per-second forms of RATE_COUNTERS
RATE_KEYS = tuple(c + "_s" for c in RATE_COUNTERS)

# columnar int stats: (column name, wire/row key, output key)
_INT_COLS = (("pool", "pool", None),
             ("num_objects", "num_objects", "objects"),
             ("num_bytes", "num_bytes", "bytes"),
             ("degraded", "degraded", "degraded"),
             ("misplaced", "misplaced", "misplaced"),
             ("unfound", "unfound", "unfound"),
             ("log_size", "log_size", "log_size"),
             ("scrub_errors", "scrub_errors", "scrub_errors"))


class _RatesView:
    """Read-only dict-shaped view over the rate columns (the
    ``pm.rates[pgid]`` surface the stats tests and exporter keep)."""

    def __init__(self, pm: "PGMap"):
        self._pm = pm

    def _row(self, pgid) -> int | None:
        row = self._pm._idx.get(pgid)
        if row is None or not self._pm._has_rate[row]:
            return None
        return row

    def __contains__(self, pgid) -> bool:
        return self._row(pgid) is not None

    def __getitem__(self, pgid) -> dict:
        row = self._row(pgid)
        if row is None:
            raise KeyError(pgid)
        return {k: float(self._pm._rate[i][row])
                for i, k in enumerate(RATE_KEYS)}

    def get(self, pgid, default=None):
        return self[pgid] if pgid in self else default


class PGMap:
    def __init__(self, stale_after: float = 15.0):
        self.stale_after = float(stale_after)
        # pgid -> row index into the columns
        self._idx: dict[str, int] = {}
        self._n = 0
        self._cap = 0
        self._int: dict[str, np.ndarray] = {}       # int64 stats
        self._ctr: list[np.ndarray] = []            # RATE_COUNTERS
        self._rate: list[np.ndarray] = []           # RATE_KEYS
        self._stamp = np.empty(0, np.float64)
        self._from = np.empty(0, np.int32)          # interned daemon
        self._state = np.empty(0, np.int16)         # interned state
        self._has_rate = np.empty(0, bool)
        self._daemon_codes: dict[str, int] = {}
        self._state_codes: dict[str, int] = {}
        self._state_names: list[str] = []
        self.rates = _RatesView(self)
        # daemon -> {"op_size_hist_bytes_pow2": [...], "_stamp": t}
        # (bounded: one row per reporting daemon, never per-PG)
        self.osd_stats: dict[str, dict] = {}

    # -- column plumbing ---------------------------------------------------

    def _grow(self) -> None:
        new_cap = max(256, self._cap * 2)
        pad = new_cap - self._cap

        def ext(arr, fill=0):
            return np.concatenate(
                [arr, np.full(pad, fill, arr.dtype)])

        for k in list(self._int):
            self._int[k] = ext(self._int[k])
        self._ctr = [ext(a) for a in self._ctr]
        self._rate = [ext(a) for a in self._rate]
        self._stamp = ext(self._stamp)
        self._from = ext(self._from, -1)
        self._state = ext(self._state)
        self._has_rate = ext(self._has_rate, False)
        self._cap = new_cap

    def _alloc_row(self, pgid: str) -> int:
        if not self._cap:
            self._int = {c: np.zeros(256, np.int64)
                         for c, _w, _o in _INT_COLS}
            self._ctr = [np.zeros(256, np.float64)
                         for _ in RATE_COUNTERS]
            self._rate = [np.zeros(256, np.float64)
                          for _ in RATE_KEYS]
            self._stamp = np.zeros(256, np.float64)
            self._from = np.full(256, -1, np.int32)
            self._state = np.zeros(256, np.int16)
            self._has_rate = np.zeros(256, bool)
            self._cap = 256
        elif self._n >= self._cap:
            self._grow()
        row = self._n
        self._n += 1
        self._idx[pgid] = row
        return row

    def _daemon_code(self, daemon: str) -> int:
        code = self._daemon_codes.get(daemon)
        if code is None:
            code = len(self._daemon_codes)
            self._daemon_codes[daemon] = code
        return code

    def _state_code(self, state: str) -> int:
        code = self._state_codes.get(state)
        if code is None:
            code = len(self._state_names)
            self._state_codes[state] = code
            self._state_names.append(state)
        return code

    @property
    def num_rows(self) -> int:
        return self._n

    # -- ingest ------------------------------------------------------------

    def apply_report(self, daemon: str, pg_stats: list | None,
                     osd_stats: dict | None, stamp: float) -> None:
        """Fold one daemon's report in.  `stamp` is the receiver's
        clock at arrival (injectable for exact-delta tests)."""
        if osd_stats:
            row = dict(osd_stats)
            row["_stamp"] = stamp
            self.osd_stats[daemon] = row
        if not pg_stats:
            return
        did = self._daemon_code(daemon)
        for st in pg_stats:
            pgid = st.get("pgid")
            if not pgid:
                continue
            row = self._idx.get(pgid)
            fresh = row is None
            if fresh:
                row = self._alloc_row(pgid)
            same_primary = (not fresh and self._from[row] == did)
            if same_primary:
                dt = stamp - self._stamp[row]
                if dt > 0:
                    for i, c in enumerate(RATE_COUNTERS):
                        cur = float(st.get(c, 0))
                        self._rate[i][row] = max(
                            0.0, (cur - self._ctr[i][row]) / dt)
                    self._has_rate[row] = True
            else:
                # new PG or a primary change: no comparable base —
                # rates restart from the next delta
                self._has_rate[row] = False
                for i in range(len(RATE_KEYS)):
                    self._rate[i][row] = 0.0
            for c, w, _o in _INT_COLS:
                self._int[c][row] = int(st.get(w, 0))
            for i, c in enumerate(RATE_COUNTERS):
                self._ctr[i][row] = float(st.get(c, 0))
            self._state[row] = self._state_code(
                st.get("state", "unknown"))
            self._from[row] = did
            self._stamp[row] = stamp

    # -- vectorized fold ---------------------------------------------------

    def _live_mask(self, now: float, pools: set | None) -> np.ndarray:
        n = self._n
        live = (now - self._stamp[:n]) <= self.stale_after
        if pools is not None:
            live &= np.isin(self._int["pool"][:n],
                            np.fromiter((int(p) for p in pools),
                                        np.int64,
                                        count=len(pools)))
        return live

    def pool_totals(self, now: float,
                    pools: set | None = None) -> dict[int, dict]:
        """Per-pool sums of the live stat rows + their rates — one
        masked segment-sum pass over the columns."""
        if not self._n:
            return {}
        idx = np.nonzero(self._live_mask(now, pools))[0]
        if not idx.size:
            return {}
        uniq, inv = np.unique(self._int["pool"][idx],
                              return_inverse=True)
        k = uniq.size
        out = {int(p): {"num_pgs": 0, "objects": 0, "bytes": 0,
                        "degraded": 0, "misplaced": 0, "unfound": 0,
                        "log_size": 0, **{rk: 0.0 for rk in RATE_KEYS}}
               for p in uniq}
        counts = np.bincount(inv, minlength=k)
        for p, c in zip(uniq, counts):
            out[int(p)]["num_pgs"] = int(c)
        for c, _w, o in _INT_COLS:
            if o is None:
                continue
            acc = np.zeros(k, np.int64)
            np.add.at(acc, inv, self._int[c][idx])
            for p, v in zip(uniq, acc):
                out[int(p)][o] = int(v)
        for i, rk in enumerate(RATE_KEYS):
            acc = np.bincount(inv, weights=self._rate[i][idx],
                              minlength=k)
            for p, v in zip(uniq, acc):
                out[int(p)][rk] = float(v)
        return out

    def pg_state_counts(self, now: float,
                        pools: set | None = None) -> dict[str, int]:
        if not self._n:
            return {}
        idx = np.nonzero(self._live_mask(now, pools))[0]
        if not idx.size:
            return {}
        counts = np.bincount(self._state[idx],
                             minlength=len(self._state_names))
        return {self._state_names[i]: int(n)
                for i, n in enumerate(counts) if n}

    def inconsistent_pgs(self, now: float,
                         pools: set | None = None) -> int:
        """Live PGs whose last scrub left a nonzero residual error
        count — the PG_DAMAGED input (one vectorized mask pass)."""
        if not self._n:
            return 0
        mask = self._live_mask(now, pools)
        return int(np.count_nonzero(
            self._int["scrub_errors"][:self._n][mask]))

    # -- daemon-extra views (bounded dicts, unchanged shape) ---------------

    def live_osd_stats(self, now: float) -> dict[str, dict]:
        """Per-daemon extras (statfs, clog counters) from reports
        still within the staleness window."""
        return {d: row for d, row in self.osd_stats.items()
                if now - row["_stamp"] <= self.stale_after}

    def op_size_hist(self, now: float) -> list[int]:
        """Element-wise sum of every live daemon's op-size histogram
        (pow2 byte buckets)."""
        total: list[int] = []
        for row in self.osd_stats.values():
            if now - row["_stamp"] > self.stale_after:
                continue
            hist = row.get("op_size_hist_bytes_pow2") or []
            if len(hist) > len(total):
                total.extend([0] * (len(hist) - len(total)))
            for i, n in enumerate(hist):
                total[i] += n
        return total

    def digest(self, now: float, osdmap=None) -> dict:
        """The mon-bound digest (MMonMgrDigest payload): everything
        `status`/`df`/`osd pool stats` and the PG_* health checks
        need, with no raw per-PG rows (bounded size)."""
        pools = set(osdmap.pools) if osdmap is not None else None
        per_pool = self.pool_totals(now, pools)
        states = self.pg_state_counts(now, pools)
        totals = {
            "objects": 0, "bytes": 0, "degraded": 0,
            "misplaced": 0, "unfound": 0, "scrub_errors": 0,
            **{k: 0.0 for k in RATE_KEYS}}
        for row in per_pool.values():
            for k in totals:
                totals[k] += row[k]
        inactive = sum(n for s, n in states.items()
                       if s not in ("active", "replica"))
        # per-OSD raw capacity (the statfs axis `df` renders): bounded
        # — one small row per reporting daemon, never per-PG data
        osd_rows = {}
        # per-chip device utilization: each daemon reports ITS
        # affinity chip's integrals; fold one row per chip, freshest
        # report wins (co-located daemons share a chip and report
        # identical figures off the same ChipRuntime ring)
        device_util: dict[int, dict] = {}
        dev_stamp: dict[int, float] = {}
        for d, row in self.live_osd_stats(now).items():
            sf = row.get("statfs")
            if sf:
                osd_rows[d] = {"total": int(sf.get("total") or 0),
                               "used": int(sf.get("used") or 0)}
            du = row.get("device_util")
            if du and du.get("chip") is not None:
                chip = int(du["chip"])
                if row["_stamp"] >= dev_stamp.get(chip, -1.0):
                    dev_stamp[chip] = row["_stamp"]
                    device_util[chip] = {
                        k: v for k, v in du.items() if k != "chip"}
                    device_util[chip]["daemon"] = d
        return {
            "num_pgs": sum(r["num_pgs"] for r in per_pool.values()),
            "pg_states": states,
            "pools": {int(pid): row
                      for pid, row in per_pool.items()},
            "totals": totals,
            "inactive_pgs": inactive,
            # scrub surface: PGs with unrepaired inconsistencies
            # (PG_DAMAGED) beside the summed error count the totals
            # carry (OSD_SCRUB_ERRORS)
            "inconsistent_pgs": self.inconsistent_pgs(now, pools),
            "op_size_hist_bytes_pow2": self.op_size_hist(now),
            "osd_stats": osd_rows,
            # chip -> windowed busy/queue-wait/idle fractions (the
            # `status` device-utilization line + QoS oracles)
            "device_util": device_util,
        }


class DictPGMap:
    """The original dict-of-rows PGMap: the golden reference the
    columnar fold is pinned against (tests/test_scale.py) and the
    baseline for the `bench.py --scale` fold micro-benchmark.  Keep
    its fold semantics bit-for-bit when touching either class."""

    def __init__(self, stale_after: float = 15.0):
        self.stale_after = float(stale_after)
        # pgid -> latest stat row (+ "_from" daemon, "_stamp")
        self.pg_stats: dict[str, dict] = {}
        # pgid -> {counter_s: rate} derived from the last two reports
        self.rates: dict[str, dict] = {}
        # daemon -> {"op_size_hist_bytes_pow2": [...], "_stamp": t}
        self.osd_stats: dict[str, dict] = {}

    # -- ingest ------------------------------------------------------------

    def apply_report(self, daemon: str, pg_stats: list | None,
                     osd_stats: dict | None, stamp: float) -> None:
        if osd_stats:
            row = dict(osd_stats)
            row["_stamp"] = stamp
            self.osd_stats[daemon] = row
        for st in pg_stats or []:
            pgid = st.get("pgid")
            if not pgid:
                continue
            prev = self.pg_stats.get(pgid)
            cur = dict(st)
            cur["_from"] = daemon
            cur["_stamp"] = stamp
            if prev is not None and prev["_from"] == daemon:
                dt = stamp - prev["_stamp"]
                if dt > 0:
                    self.rates[pgid] = {
                        c + "_s": max(0.0, (cur.get(c, 0)
                                            - prev.get(c, 0)) / dt)
                        for c in RATE_COUNTERS}
            else:
                self.rates.pop(pgid, None)
            self.pg_stats[pgid] = cur

    # -- views -------------------------------------------------------------

    def _live_rows(self, now: float, pools: set | None):
        for pgid, st in self.pg_stats.items():
            if now - st["_stamp"] > self.stale_after:
                continue            # dead primary's last report
            if pools is not None and st.get("pool") not in pools:
                continue            # pool deleted since the report
            yield pgid, st

    def pool_totals(self, now: float,
                    pools: set | None = None) -> dict[int, dict]:
        out: dict[int, dict] = {}
        for pgid, st in self._live_rows(now, pools):
            row = out.setdefault(st["pool"], {
                "num_pgs": 0, "objects": 0, "bytes": 0,
                "degraded": 0, "misplaced": 0, "unfound": 0,
                "log_size": 0, "scrub_errors": 0,
                **{k: 0.0 for k in RATE_KEYS}})
            row["num_pgs"] += 1
            row["objects"] += st.get("num_objects", 0)
            row["bytes"] += st.get("num_bytes", 0)
            row["degraded"] += st.get("degraded", 0)
            row["misplaced"] += st.get("misplaced", 0)
            row["unfound"] += st.get("unfound", 0)
            row["log_size"] += st.get("log_size", 0)
            row["scrub_errors"] += st.get("scrub_errors", 0)
            rt = self.rates.get(pgid)
            if rt:
                for k in RATE_KEYS:
                    row[k] += rt.get(k, 0.0)
        return out

    def pg_state_counts(self, now: float,
                        pools: set | None = None) -> dict[str, int]:
        states: dict[str, int] = {}
        for _pgid, st in self._live_rows(now, pools):
            s = st.get("state", "unknown")
            states[s] = states.get(s, 0) + 1
        return states

    def inconsistent_pgs(self, now: float,
                         pools: set | None = None) -> int:
        return sum(1 for _p, st in self._live_rows(now, pools)
                   if st.get("scrub_errors", 0))

    live_osd_stats = PGMap.live_osd_stats
    op_size_hist = PGMap.op_size_hist
    digest = PGMap.digest
