"""PGMap: the mgr's fold of every OSD's per-PG stat rows.

Condensed analog of src/mon/PGMap.{h,cc} as maintained by the
MgrStatMonitor pipeline (OSD MPGStats -> DaemonServer -> PGMap
apply_incremental): primaries ship a stat row per PG they serve
(object/byte counts, degraded/misplaced/unfound tallies, cumulative
client-IO and recovery counters) inside their MMgrReports; this class
keeps the latest row per PG, derives **rates** from the delta between
two consecutive reports of the same primary (PGMap's pool_statfs
delta machinery), and renders:

* per-pool and cluster-wide totals (objects, bytes, degraded,
  misplaced, unfound) — the `df` / `osd pool stats` surface;
* client read/write ops/s + bytes/s and recovery objects/s + bytes/s
  — the `ceph -s` io: / recovery: lines;
* the digest the mgr periodically sends the monitors (MMonMgrDigest),
  from which the mon serves `status`/`df` and raises PG_DEGRADED /
  PG_AVAILABILITY.

Counter resets (primary restart or failover) surface as negative
deltas and clamp to zero — exactly one digest period of undercounted
rate, never a negative or wildly inflated one.
"""

from __future__ import annotations

RATE_COUNTERS = ("read_ops", "read_bytes", "write_ops", "write_bytes",
                 "recovery_ops", "recovery_bytes")

# digest keys carrying the per-second forms of RATE_COUNTERS
RATE_KEYS = tuple(c + "_s" for c in RATE_COUNTERS)


class PGMap:
    def __init__(self, stale_after: float = 15.0):
        self.stale_after = float(stale_after)
        # pgid -> latest stat row (+ "_from" daemon, "_stamp")
        self.pg_stats: dict[str, dict] = {}
        # pgid -> {counter_s: rate} derived from the last two reports
        self.rates: dict[str, dict] = {}
        # daemon -> {"op_size_hist_bytes_pow2": [...], "_stamp": t}
        self.osd_stats: dict[str, dict] = {}

    # -- ingest ------------------------------------------------------------

    def apply_report(self, daemon: str, pg_stats: list | None,
                     osd_stats: dict | None, stamp: float) -> None:
        """Fold one daemon's report in.  `stamp` is the receiver's
        clock at arrival (injectable for exact-delta tests)."""
        if osd_stats:
            row = dict(osd_stats)
            row["_stamp"] = stamp
            self.osd_stats[daemon] = row
        for st in pg_stats or []:
            pgid = st.get("pgid")
            if not pgid:
                continue
            prev = self.pg_stats.get(pgid)
            cur = dict(st)
            cur["_from"] = daemon
            cur["_stamp"] = stamp
            if prev is not None and prev["_from"] == daemon:
                dt = stamp - prev["_stamp"]
                if dt > 0:
                    self.rates[pgid] = {
                        c + "_s": max(0.0, (cur.get(c, 0)
                                            - prev.get(c, 0)) / dt)
                        for c in RATE_COUNTERS}
            else:
                # new PG or a primary change: no comparable base —
                # rates restart from the next delta
                self.rates.pop(pgid, None)
            self.pg_stats[pgid] = cur

    # -- views -------------------------------------------------------------

    def _live_rows(self, now: float, pools: set | None):
        for pgid, st in self.pg_stats.items():
            if now - st["_stamp"] > self.stale_after:
                continue            # dead primary's last report
            if pools is not None and st.get("pool") not in pools:
                continue            # pool deleted since the report
            yield pgid, st

    def pool_totals(self, now: float,
                    pools: set | None = None) -> dict[int, dict]:
        """Per-pool sums of the live stat rows + their rates."""
        out: dict[int, dict] = {}
        for pgid, st in self._live_rows(now, pools):
            row = out.setdefault(st["pool"], {
                "num_pgs": 0, "objects": 0, "bytes": 0,
                "degraded": 0, "misplaced": 0, "unfound": 0,
                "log_size": 0,
                **{k: 0.0 for k in RATE_KEYS}})
            row["num_pgs"] += 1
            row["objects"] += st.get("num_objects", 0)
            row["bytes"] += st.get("num_bytes", 0)
            row["degraded"] += st.get("degraded", 0)
            row["misplaced"] += st.get("misplaced", 0)
            row["unfound"] += st.get("unfound", 0)
            row["log_size"] += st.get("log_size", 0)
            rt = self.rates.get(pgid)
            if rt:
                for k in RATE_KEYS:
                    row[k] += rt.get(k, 0.0)
        return out

    def pg_state_counts(self, now: float,
                        pools: set | None = None) -> dict[str, int]:
        states: dict[str, int] = {}
        for _pgid, st in self._live_rows(now, pools):
            s = st.get("state", "unknown")
            states[s] = states.get(s, 0) + 1
        return states

    def live_osd_stats(self, now: float) -> dict[str, dict]:
        """Per-daemon extras (statfs, clog counters) from reports
        still within the staleness window."""
        return {d: row for d, row in self.osd_stats.items()
                if now - row["_stamp"] <= self.stale_after}

    def op_size_hist(self, now: float) -> list[int]:
        """Element-wise sum of every live daemon's op-size histogram
        (pow2 byte buckets)."""
        total: list[int] = []
        for row in self.osd_stats.values():
            if now - row["_stamp"] > self.stale_after:
                continue
            hist = row.get("op_size_hist_bytes_pow2") or []
            if len(hist) > len(total):
                total.extend([0] * (len(hist) - len(total)))
            for i, n in enumerate(hist):
                total[i] += n
        return total

    def digest(self, now: float, osdmap=None) -> dict:
        """The mon-bound digest (MMonMgrDigest payload): everything
        `status`/`df`/`osd pool stats` and the PG_* health checks
        need, with no raw per-PG rows (bounded size)."""
        pools = set(osdmap.pools) if osdmap is not None else None
        per_pool = self.pool_totals(now, pools)
        states = self.pg_state_counts(now, pools)
        totals = {
            "objects": 0, "bytes": 0, "degraded": 0,
            "misplaced": 0, "unfound": 0,
            **{k: 0.0 for k in RATE_KEYS}}
        for row in per_pool.values():
            for k in totals:
                totals[k] += row[k]
        inactive = sum(n for s, n in states.items()
                       if s not in ("active", "replica"))
        # per-OSD raw capacity (the statfs axis `df` renders): bounded
        # — one small row per reporting daemon, never per-PG data
        osd_rows = {}
        for d, row in self.live_osd_stats(now).items():
            sf = row.get("statfs")
            if sf:
                osd_rows[d] = {"total": int(sf.get("total") or 0),
                               "used": int(sf.get("used") or 0)}
        return {
            "num_pgs": sum(r["num_pgs"] for r in per_pool.values()),
            "pg_states": states,
            "pools": {int(pid): row
                      for pid, row in per_pool.items()},
            "totals": totals,
            "inactive_pgs": inactive,
            "op_size_hist_bytes_pow2": self.op_size_hist(now),
            "osd_stats": osd_rows,
        }
