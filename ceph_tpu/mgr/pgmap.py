"""PGMap: the mgr's fold of every OSD's per-PG stat rows.

Condensed analog of src/mon/PGMap.{h,cc} as maintained by the
MgrStatMonitor pipeline (OSD MPGStats -> DaemonServer -> PGMap
apply_incremental): primaries ship a stat row per PG they serve
(object/byte counts, degraded/misplaced/unfound tallies, cumulative
client-IO and recovery counters) inside their MMgrReports; this class
keeps the latest row per PG, derives **rates** from the delta between
two consecutive reports of the same primary (PGMap's pool_statfs
delta machinery), and renders:

* per-pool and cluster-wide totals (objects, bytes, degraded,
  misplaced, unfound) — the `df` / `osd pool stats` surface;
* client read/write ops/s + bytes/s and recovery objects/s + bytes/s
  — the `ceph -s` io: / recovery: lines;
* the digest the mgr periodically sends the monitors (MMonMgrDigest),
  from which the mon serves `status`/`df` and raises PG_DEGRADED /
  PG_AVAILABILITY.

Counter resets (primary restart or failover) surface as negative
deltas and clamp to zero — exactly one digest period of undercounted
rate, never a negative or wildly inflated one.

**Columnar storage + columnar ingest** (the telemetry fabric): at
100k-1M PG rows both the per-tick fold AND the per-report merge
dominate the mgr, so rows live in flat numpy columns — one
int64/float64 array per stat — keyed by the integer pgid key
``pool << 32 | seed`` rather than the pgid string.  Folds are
vectorized masked passes (staleness window, pool filter, per-pool
segment sums), and a packed columnar report block
(``msg.statblock``: the MMgrReport ``pg_stats_cols`` field) merges as
ONE searchsorted + masked scatter per report — rate derivation,
counter-reset clamping and primary-change resets included — instead
of a python loop per row.  Legacy dict-shaped ``pg_stats`` rows take
the original row-wise path into the same columns, so mixed fleets
converge to one digest.  `DictPGMap` below preserves the original
dict-of-rows implementation as the golden reference both paths are
pinned against (and the ingest/fold micro-benchmarks' baseline).

**Pruning**: stale rows (dead primaries past the prune window) and
deleted-pool rows compact OUT of the column store as a vectorized
keep-mask pass, with visible counters (``pruned_stale`` /
``pruned_pool`` / ``pruned_daemons`` — the exporter's
``ceph_tpu_mgr_rows_pruned_total``) instead of silent drops; the
staleness *fold* masks are unchanged, pruning only reclaims rows the
folds already ignore.
"""

from __future__ import annotations

import time as _time

import numpy as np

from ..msg import statblock

RATE_COUNTERS = ("read_ops", "read_bytes", "write_ops", "write_bytes",
                 "recovery_ops", "recovery_bytes")

# digest keys carrying the per-second forms of RATE_COUNTERS
RATE_KEYS = tuple(c + "_s" for c in RATE_COUNTERS)

# columnar int stats: (column name, wire/row key, output key)
_INT_COLS = (("pool", "pool", None),
             ("num_objects", "num_objects", "objects"),
             ("num_bytes", "num_bytes", "bytes"),
             ("degraded", "degraded", "degraded"),
             ("misplaced", "misplaced", "misplaced"),
             ("unfound", "unfound", "unfound"),
             ("log_size", "log_size", "log_size"),
             ("scrub_errors", "scrub_errors", "scrub_errors"))

# the packed wire block's column orders must mirror the store's (the
# scatter assigns positionally); a drift here is a bug, not a skew
assert statblock.STAT_CTR_COLS == RATE_COUNTERS
assert statblock.STAT_INT_COLS == tuple(w for _c, w, _o in _INT_COLS)


def _new_ingest() -> dict:
    """Ingest accounting shared by PGMap and DictPGMap: reports/rows/
    bytes per wire format, the apply-latency pow2-µs histogram
    (``ceph_tpu_mgr_ingest_seconds``), and the count of block rows
    that had to fall back to the row-wise loop (the fast-path
    coverage oracle — 0 in a healthy fleet)."""
    return {"reports": {"columnar": 0, "legacy": 0},
            "rows": {"columnar": 0, "legacy": 0},
            "bytes": {"columnar": 0, "legacy": 0},
            "fallback_rows": 0,
            "seconds_hist": [0] * 32}


def _note_ingest(ing: dict, fmt: str, cols_rows: int,
                 legacy_rows: int, nbytes: int,
                 seconds: float) -> None:
    """One report's accounting.  The report counts once under its
    dominant format (columnar if a block is present); row counts
    split by the wire shape each row actually arrived in, so a
    mixed-field report never skews the per-format rows series."""
    ing["reports"][fmt] += 1
    ing["rows"]["columnar"] += cols_rows
    ing["rows"]["legacy"] += legacy_rows
    ing["bytes"][fmt] += int(nbytes)
    us = int(seconds * 1e6)
    ing["seconds_hist"][max(0, min(31, us.bit_length() - 1))] += 1


class _RatesView:
    """Read-only dict-shaped view over the rate columns (the
    ``pm.rates[pgid]`` surface the stats tests and exporter keep)."""

    def __init__(self, pm: "PGMap"):
        self._pm = pm

    def _row(self, pgid) -> int | None:
        row = self._pm._row_of(pgid)
        if row is None or not self._pm._has_rate[row]:
            return None
        return row

    def __contains__(self, pgid) -> bool:
        return self._row(pgid) is not None

    def __getitem__(self, pgid) -> dict:
        row = self._row(pgid)
        if row is None:
            raise KeyError(pgid)
        return {k: float(self._pm._rate[i][row])
                for i, k in enumerate(RATE_KEYS)}

    def get(self, pgid, default=None):
        return self[pgid] if pgid in self else default


class PGMap:
    def __init__(self, stale_after: float = 15.0):
        self.stale_after = float(stale_after)
        self._n = 0
        self._cap = 0
        self._int: dict[str, np.ndarray] = {}       # int64 stats
        self._ctr: list[np.ndarray] = []            # RATE_COUNTERS
        self._rate: list[np.ndarray] = []           # RATE_KEYS
        self._keys = np.empty(0, np.int64)          # pool<<32|seed
        self._stamp = np.empty(0, np.float64)
        self._from = np.empty(0, np.int32)          # interned daemon
        self._state = np.empty(0, np.int16)         # interned state
        self._has_rate = np.empty(0, bool)
        # (sorted key array, row-of-sorted-position) — the searchsorted
        # index; None = dirty.  Rows allocated since the last rebuild
        # sit in _pending so scalar lookups never force a resort.
        self._sorted: tuple[np.ndarray, np.ndarray] | None = None
        self._pending: dict[int, int] = {}
        # daemon code -> (last block's key array, resolved rows):
        # the steady-state ingest shortcut (cleared on compaction)
        self._daemon_rows: dict[int, tuple] = {}
        # pgids outside the canonical "pool.seed" shape get synthetic
        # negative keys (never collide with parsed keys, which are >=0)
        self._str_keys: dict[str, int] = {}
        self._daemon_codes: dict[str, int] = {}
        self._state_codes: dict[str, int] = {}
        self._state_names: list[str] = []
        self.rates = _RatesView(self)
        # daemon -> {"op_size_hist_bytes_pow2": [...], "_stamp": t}
        # (bounded: one row per reporting daemon, never per-PG)
        self.osd_stats: dict[str, dict] = {}
        # daemon -> stamp of its last report of ANY shape (freshness
        # axis: shells report pg rows with osd_stats=None)
        self.report_stamps: dict[str, float] = {}
        self.ingest = _new_ingest()
        self.pruned_stale = 0
        self.pruned_pool = 0
        self.pruned_daemons = 0

    # -- column plumbing ---------------------------------------------------

    def _grow(self, need: int) -> None:
        new_cap = max(256, self._cap)
        while new_cap < need:
            new_cap *= 2
        pad = new_cap - self._cap

        def ext(arr, fill=0):
            return np.concatenate(
                [arr, np.full(pad, fill, arr.dtype)])

        if not self._cap:
            self._int = {c: np.zeros(new_cap, np.int64)
                         for c, _w, _o in _INT_COLS}
            self._ctr = [np.zeros(new_cap, np.float64)
                         for _ in RATE_COUNTERS]
            self._rate = [np.zeros(new_cap, np.float64)
                          for _ in RATE_KEYS]
            self._keys = np.zeros(new_cap, np.int64)
            self._stamp = np.zeros(new_cap, np.float64)
            self._from = np.full(new_cap, -1, np.int32)
            self._state = np.zeros(new_cap, np.int16)
            self._has_rate = np.zeros(new_cap, bool)
        else:
            for k in list(self._int):
                self._int[k] = ext(self._int[k])
            self._ctr = [ext(a) for a in self._ctr]
            self._rate = [ext(a) for a in self._rate]
            self._keys = ext(self._keys)
            self._stamp = ext(self._stamp)
            self._from = ext(self._from, -1)
            self._state = ext(self._state)
            self._has_rate = ext(self._has_rate, False)
        self._cap = new_cap

    def _pgid_key(self, pgid: str) -> int:
        try:
            pool_s, dot, seed_s = pgid.partition(".")
            if dot:
                pool = int(pool_s)
                seed = int(seed_s, 16)
                if (0 <= pool <= statblock._POOL_MAX
                        and 0 <= seed <= statblock._SEED_MAX):
                    return (pool << 32) | seed
            raise ValueError(pgid)
        except ValueError:
            k = self._str_keys.get(pgid)
            if k is None:
                k = -(len(self._str_keys) + 1)
                self._str_keys[pgid] = k
            return k

    def _ensure_index(self) -> None:
        if self._sorted is not None and not self._pending:
            return
        keys = self._keys[:self._n]
        order = np.argsort(keys, kind="stable").astype(np.int64)
        self._sorted = (keys[order], order)
        self._pending.clear()

    def _row_of_key(self, key: int) -> int | None:
        row = self._pending.get(key)
        if row is not None:
            return row
        if self._sorted is None:
            self._ensure_index()
        sk, sr = self._sorted
        i = int(np.searchsorted(sk, key))
        if i < sk.size and sk[i] == key:
            return int(sr[i])
        return None

    def _row_of(self, pgid: str) -> int | None:
        return self._row_of_key(self._pgid_key(pgid))

    def _alloc_row(self, key: int) -> int:
        if self._n >= self._cap:
            self._grow(self._n + 1)
        row = self._n
        self._n += 1
        self._keys[row] = key
        self._pending[key] = row
        return row

    def _alloc_rows(self, new_keys: np.ndarray) -> None:
        """Bulk allocation for a columnar block's unseen pgids: one
        capacity growth, one key scatter, and an O(n+m) merge of the
        (sorted) new keys into the sorted index — never a resort, so
        a fleet's worth of first-sight blocks stays linear."""
        m = new_keys.size
        need = self._n + m
        if need > self._cap:
            self._grow(need)
        new_rows = np.arange(self._n, need, dtype=np.int64)
        self._keys[self._n:need] = new_keys
        self._n = need
        sk, sr = self._sorted
        # one manual two-array merge (np.insert would re-derive the
        # destination mask per array): new keys land at their sorted
        # positions, the old index shifts around them
        dest = np.searchsorted(sk, new_keys) + np.arange(m)
        total = sk.size + m
        out_k = np.empty(total, np.int64)
        out_r = np.empty(total, np.int64)
        hole = np.ones(total, bool)
        hole[dest] = False
        out_k[dest] = new_keys
        out_r[dest] = new_rows
        out_k[hole] = sk
        out_r[hole] = sr
        self._sorted = (out_k, out_r)

    def _daemon_code(self, daemon: str) -> int:
        code = self._daemon_codes.get(daemon)
        if code is None:
            code = len(self._daemon_codes)
            self._daemon_codes[daemon] = code
        return code

    def _state_code(self, state: str) -> int:
        code = self._state_codes.get(state)
        if code is None:
            code = len(self._state_names)
            self._state_codes[state] = code
            self._state_names.append(state)
        return code

    @property
    def num_rows(self) -> int:
        return self._n

    # -- ingest ------------------------------------------------------------

    def apply_report(self, daemon: str, pg_stats: list | None,
                     osd_stats: dict | None, stamp: float,
                     pg_stats_cols: dict | None = None,
                     nbytes: int | None = None) -> None:
        """Fold one daemon's report in.  `stamp` is the receiver's
        clock at arrival (injectable for exact-delta tests).
        ``pg_stats_cols`` is the packed columnar block (statblock) the
        vectorized merge ingests; dict-shaped ``pg_stats`` rows keep
        the row-wise path.  A malformed block falls back to the row
        loop (counted in ``ingest["fallback_rows"]``) — never raises.
        """
        t0 = _time.perf_counter()
        self.report_stamps[daemon] = stamp
        if osd_stats:
            row = dict(osd_stats)
            row["_stamp"] = stamp
            self.osd_stats[daemon] = row
        fmt = "legacy"
        cols_rows = 0
        if pg_stats_cols is not None:
            fmt = "columnar"
            did = self._daemon_code(daemon)
            try:
                cols_rows = self._apply_cols(did, pg_stats_cols,
                                             stamp)
            except Exception:
                try:
                    rows = statblock.unpack_stat_rows(pg_stats_cols)
                except Exception:
                    rows = []
                self.ingest["fallback_rows"] += len(rows)
                cols_rows = len(rows)
                self._apply_rows(did, rows, stamp)
        if pg_stats:
            self._apply_rows(self._daemon_code(daemon), pg_stats,
                             stamp)
        if nbytes is None:
            nbytes = (statblock.block_nbytes(pg_stats_cols)
                      if pg_stats_cols is not None else 0)
        _note_ingest(self.ingest, fmt, cols_rows,
                     len(pg_stats or ()), nbytes,
                     _time.perf_counter() - t0)

    def _apply_rows(self, did: int, pg_stats: list,
                    stamp: float) -> None:
        """The original row-wise merge (legacy dict rows + the
        malformed-block fallback)."""
        for st in pg_stats:
            pgid = st.get("pgid")
            if not pgid:
                continue
            key = self._pgid_key(pgid)
            row = self._row_of_key(key)
            fresh = row is None
            if fresh:
                row = self._alloc_row(key)
            same_primary = (not fresh and self._from[row] == did)
            if same_primary:
                dt = stamp - self._stamp[row]
                if dt > 0:
                    for i, c in enumerate(RATE_COUNTERS):
                        cur = float(st.get(c, 0))
                        self._rate[i][row] = max(
                            0.0, (cur - self._ctr[i][row]) / dt)
                    self._has_rate[row] = True
            else:
                # new PG or a primary change: no comparable base —
                # rates restart from the next delta
                self._has_rate[row] = False
                for i in range(len(RATE_KEYS)):
                    self._rate[i][row] = 0.0
            for c, w, _o in _INT_COLS:
                self._int[c][row] = int(st.get(w, 0))
            for i, c in enumerate(RATE_COUNTERS):
                self._ctr[i][row] = float(st.get(c, 0))
            self._state[row] = self._state_code(
                st.get("state", "unknown"))
            self._from[row] = did
            self._stamp[row] = stamp

    def _apply_cols(self, did: int, block: dict, stamp: float) -> int:
        """The vectorized merge: one searchsorted over the int64 pgid
        keys, bulk allocation for unseen PGs, then masked column
        scatters reproducing the row loop's exact semantics — rate
        derivation over the per-row dt, counter-reset clamping at 0,
        rate reset on primary change, state dictionary translation."""
        cols = statblock.block_cols(block)
        n = cols["n"]
        if not n:
            return 0
        keys = (cols["pg_pool"] << 32) | cols["pg_seed"]
        # steady-state shortcut: a primary's PG set rarely changes
        # between reports, so its key->row resolution is cached and
        # revalidated with one vector compare (row indices are stable
        # until a prune compaction, which clears the cache)
        cached = self._daemon_rows.get(did)
        if cached is not None and cached[0].size == n \
                and np.array_equal(cached[0], keys):
            rows = cached[1]
        else:
            # duplicate pgids within one block would hit the masked
            # scatters with repeated indices (last-write-wins) and a
            # single rate derivation — not the row loop's
            # per-occurrence semantics.  Producers mint unique pgids;
            # a malformed block takes the row-wise fallback.  (A cache
            # hit implies the key set already passed this check.)
            ks = np.sort(keys)
            if n > 1 and (ks[1:] == ks[:-1]).any():
                raise ValueError("duplicate pgids in block")
            self._ensure_index()
            sk, sr = self._sorted
            rows = np.empty(n, np.int64)
            if sk.size:
                pos = np.minimum(np.searchsorted(sk, keys),
                                 sk.size - 1)
                found = sk[pos] == keys
                rows[found] = sr[pos[found]]
            else:
                found = np.zeros(n, bool)
            if not found.all():
                miss = ~found
                # allocation order == sorted key order, so unique's
                # inverse indexes the new rows directly (no re-search)
                uniq, inv = np.unique(keys[miss],
                                      return_inverse=True)
                base = self._n
                self._alloc_rows(uniq)
                rows[miss] = base + inv
            self._daemon_rows[did] = (keys, rows)
        # rate semantics, row-loop exact: same primary + dt>0 derives
        # clamped rates; a primary change (or fresh row: _from == -1)
        # zeroes them; same primary with dt<=0 leaves them untouched
        same = self._from[rows] == did
        dt = stamp - self._stamp[rows]
        rate_ok = same & (dt > 0)
        if rate_ok.any():
            rr = rows[rate_ok]
            dtv = dt[rate_ok]
            for i in range(len(RATE_COUNTERS)):
                cur = cols["ctrs"][i][rate_ok].astype(np.float64)
                self._rate[i][rr] = np.maximum(
                    0.0, (cur - self._ctr[i][rr]) / dtv)
            self._has_rate[rr] = True
        reset = ~same
        if reset.any():
            rr = rows[reset]
            self._has_rate[rr] = False
            for i in range(len(RATE_KEYS)):
                self._rate[i][rr] = 0.0
        for (c, _w, _o), arr in zip(_INT_COLS, cols["ints"]):
            self._int[c][rows] = arr
        for i in range(len(RATE_COUNTERS)):
            self._ctr[i][rows] = cols["ctrs"][i].astype(np.float64)
        names = cols["state_names"]
        if names:
            trans = np.asarray([self._state_code(s) for s in names],
                               np.int16)
            self._state[rows] = trans[cols["state"]]
        self._from[rows] = did
        self._stamp[rows] = stamp
        return n

    # -- pruning -----------------------------------------------------------

    def prune(self, now: float, pools: set | None = None,
              after: float | None = None) -> dict:
        """Compact stale rows (no report within `after`, default the
        staleness window) and deleted-pool rows out of the column
        store, and expire per-daemon extras the same way.  Every drop
        is counted (``pruned_stale`` / ``pruned_pool`` /
        ``pruned_daemons`` -> ``ceph_tpu_mgr_rows_pruned_total``) —
        rows leave the mgr visibly, never silently.  The fold masks
        are unchanged; pruning reclaims rows they already ignore."""
        after = self.stale_after if after is None else float(after)
        n = self._n
        dropped_stale = dropped_pool = 0
        if n:
            fresh = (now - self._stamp[:n]) <= after
            keep = fresh
            if pools is not None:
                in_pool = np.isin(
                    self._int["pool"][:n],
                    np.fromiter((int(p) for p in pools), np.int64,
                                count=len(pools)))
                dropped_pool = int(np.count_nonzero(fresh & ~in_pool))
                keep = fresh & in_pool
            dropped_stale = int(np.count_nonzero(~fresh))
            k = int(np.count_nonzero(keep))
            if k < n:
                idx = np.nonzero(keep)[0]
                for c in self._int:
                    self._int[c][:k] = self._int[c][idx]
                for arr in self._ctr:
                    arr[:k] = arr[idx]
                for arr in self._rate:
                    arr[:k] = arr[idx]
                self._keys[:k] = self._keys[idx]
                self._stamp[:k] = self._stamp[idx]
                self._from[:k] = self._from[idx]
                self._state[:k] = self._state[idx]
                self._has_rate[:k] = self._has_rate[idx]
                # reset the freed tail: _alloc_row/_alloc_rows only
                # write _keys, so a PG later allocated onto a recycled
                # slot must read _from == -1 (fresh), never a dead
                # row's primary — else the merge would derive a rate
                # from the dead row's counters/stamp
                self._from[k:n] = -1
                self._stamp[k:n] = 0.0
                self._has_rate[k:n] = False
                self._n = k
                self._sorted = None
                self._pending.clear()
                self._daemon_rows.clear()   # row indices moved
                self.pruned_stale += dropped_stale
                self.pruned_pool += dropped_pool
            else:
                dropped_stale = dropped_pool = 0
        dropped_daemons = 0
        for d in [d for d, t in self.report_stamps.items()
                  if now - t > after]:
            del self.report_stamps[d]
            self.osd_stats.pop(d, None)
            dropped_daemons += 1
        self.pruned_daemons += dropped_daemons
        return {"stale": dropped_stale, "pool": dropped_pool,
                "daemons": dropped_daemons}

    # -- vectorized fold ---------------------------------------------------

    def _live_mask(self, now: float, pools: set | None) -> np.ndarray:
        n = self._n
        live = (now - self._stamp[:n]) <= self.stale_after
        if pools is not None:
            live &= np.isin(self._int["pool"][:n],
                            np.fromiter((int(p) for p in pools),
                                        np.int64,
                                        count=len(pools)))
        return live

    def pool_totals(self, now: float,
                    pools: set | None = None) -> dict[int, dict]:
        """Per-pool sums of the live stat rows + their rates — one
        masked segment-sum pass over the columns."""
        if not self._n:
            return {}
        idx = np.nonzero(self._live_mask(now, pools))[0]
        if not idx.size:
            return {}
        uniq, inv = np.unique(self._int["pool"][idx],
                              return_inverse=True)
        k = uniq.size
        out = {int(p): {"num_pgs": 0, "objects": 0, "bytes": 0,
                        "degraded": 0, "misplaced": 0, "unfound": 0,
                        "log_size": 0, **{rk: 0.0 for rk in RATE_KEYS}}
               for p in uniq}
        counts = np.bincount(inv, minlength=k)
        for p, c in zip(uniq, counts):
            out[int(p)]["num_pgs"] = int(c)
        for c, _w, o in _INT_COLS:
            if o is None:
                continue
            acc = np.zeros(k, np.int64)
            np.add.at(acc, inv, self._int[c][idx])
            for p, v in zip(uniq, acc):
                out[int(p)][o] = int(v)
        for i, rk in enumerate(RATE_KEYS):
            acc = np.bincount(inv, weights=self._rate[i][idx],
                              minlength=k)
            for p, v in zip(uniq, acc):
                out[int(p)][rk] = float(v)
        return out

    def pg_state_counts(self, now: float,
                        pools: set | None = None) -> dict[str, int]:
        if not self._n:
            return {}
        idx = np.nonzero(self._live_mask(now, pools))[0]
        if not idx.size:
            return {}
        counts = np.bincount(self._state[idx],
                             minlength=len(self._state_names))
        return {self._state_names[i]: int(n)
                for i, n in enumerate(counts) if n}

    def inconsistent_pgs(self, now: float,
                         pools: set | None = None) -> int:
        """Live PGs whose last scrub left a nonzero residual error
        count — the PG_DAMAGED input (one vectorized mask pass)."""
        if not self._n:
            return 0
        mask = self._live_mask(now, pools)
        return int(np.count_nonzero(
            self._int["scrub_errors"][:self._n][mask]))

    # -- daemon-extra views (bounded dicts, unchanged shape) ---------------

    def live_osd_stats(self, now: float) -> dict[str, dict]:
        """Per-daemon extras (statfs, clog counters) from reports
        still within the staleness window."""
        return {d: row for d, row in self.osd_stats.items()
                if now - row["_stamp"] <= self.stale_after}

    def op_size_hist(self, now: float) -> list[int]:
        """Element-wise sum of every live daemon's op-size histogram
        (pow2 byte buckets)."""
        total: list[int] = []
        for row in self.osd_stats.values():
            if now - row["_stamp"] > self.stale_after:
                continue
            hist = row.get("op_size_hist_bytes_pow2") or []
            if len(hist) > len(total):
                total.extend([0] * (len(hist) - len(total)))
            for i, n in enumerate(hist):
                total[i] += n
        return total

    def report_freshness(self, now: float) -> dict:
        """Per-daemon report-age summary (bounded: one scalar pass
        over the stamps, never per-PG data): daemon count, the worst
        age + its daemon, how many daemons are past the staleness
        window, and the cumulative prune counters — the digest's
        `reports` section `status` renders as its max-age/stale line.
        """
        out = {"daemons": len(self.report_stamps),
               "max_age": 0.0, "max_age_daemon": None, "stale": 0,
               "pruned_stale_rows": self.pruned_stale,
               "pruned_pool_rows": self.pruned_pool,
               "pruned_daemons": self.pruned_daemons}
        for d, t in self.report_stamps.items():
            age = max(0.0, now - t)
            if age > out["max_age"] or out["max_age_daemon"] is None:
                out["max_age"] = round(age, 3)
                out["max_age_daemon"] = d
            if age > self.stale_after:
                out["stale"] += 1
        return out

    def digest(self, now: float, osdmap=None) -> dict:
        """The mon-bound digest (MMonMgrDigest payload): everything
        `status`/`df`/`osd pool stats` and the PG_* health checks
        need, with no raw per-PG rows (bounded size)."""
        pools = set(osdmap.pools) if osdmap is not None else None
        per_pool = self.pool_totals(now, pools)
        states = self.pg_state_counts(now, pools)
        totals = {
            "objects": 0, "bytes": 0, "degraded": 0,
            "misplaced": 0, "unfound": 0, "scrub_errors": 0,
            **{k: 0.0 for k in RATE_KEYS}}
        for row in per_pool.values():
            for k in totals:
                totals[k] += row[k]
        inactive = sum(n for s, n in states.items()
                       if s not in ("active", "replica"))
        # per-OSD raw capacity (the statfs axis `df` renders): bounded
        # — one small row per reporting daemon, never per-PG data
        osd_rows = {}
        # per-chip device utilization: each daemon reports ITS
        # affinity chip's integrals; fold one row per chip, freshest
        # report wins (co-located daemons share a chip and report
        # identical figures off the same ChipRuntime ring)
        device_util: dict[int, dict] = {}
        dev_stamp: dict[int, float] = {}
        # per-codec repair traffic: each daemon reports cumulative
        # counters, the digest sums across the live fleet (the
        # repair-bytes comparison oracle's committed surface)
        repair_traffic: dict[str, dict] = {}
        # per-pool dedup totals: each primary reports its cumulative
        # data-reduction counters, the digest sums across the fleet
        # (the `status` dedup panel + bench --dedup oracle surface)
        dedup_pools: dict[str, dict] = {}
        # long-flow progress rows (recovery drains, scrub sweeps):
        # keyed "daemon:flowid" so two OSDs' drains never collide —
        # `status` renders them and the mon leader diffs them into
        # progress_start/finish bus events
        progress: dict[str, dict] = {}
        # network plane: one bounded row per reporting daemon — wire
        # rates the producer computed over its own report interval,
        # the RTT rollup, and the per-peer 5s RTTs (the cluster RTT
        # matrix row); the full per-peer wire detail stays in
        # osd_stats for the exporter and never rides the digest
        net: dict[str, dict] = {}
        for d, row in self.live_osd_stats(now).items():
            nrow = row.get("net")
            if nrow:
                net[d] = {
                    "tx_Bps": float(nrow.get("tx_Bps", 0.0) or 0.0),
                    "rx_Bps": float(nrow.get("rx_Bps", 0.0) or 0.0),
                    "resends": int(nrow.get("resends", 0) or 0),
                    "replays": int(nrow.get("replays", 0) or 0),
                    "queue_depth": int(
                        nrow.get("queue_depth", 0) or 0),
                    "resend_rate": float(
                        nrow.get("resend_rate", 0.0) or 0.0),
                    "rtt_avg_ms": float(
                        (nrow.get("rtt") or {}).get(
                            "rtt_avg_ms", 0.0) or 0.0),
                    "rtt_max_ms": float(
                        (nrow.get("rtt") or {}).get(
                            "rtt_max_ms", 0.0) or 0.0),
                    "rtt_peers": dict(nrow.get("rtt_peers") or {}),
                }
            sf = row.get("statfs")
            if sf:
                osd_rows[d] = {"total": int(sf.get("total") or 0),
                               "used": int(sf.get("used") or 0)}
            du = row.get("device_util")
            if du and du.get("chip") is not None:
                chip = int(du["chip"])
                if row["_stamp"] >= dev_stamp.get(chip, -1.0):
                    dev_stamp[chip] = row["_stamp"]
                    device_util[chip] = {
                        k: v for k, v in du.items() if k != "chip"}
                    device_util[chip]["daemon"] = d
            for cname, rrow in (row.get("repair") or {}).items():
                agg = repair_traffic.setdefault(
                    str(cname), {"read": 0, "moved": 0,
                                 "objects": 0, "targeted": 0,
                                 "full": 0})
                for kk in agg:
                    agg[kk] += int(rrow.get(kk, 0) or 0)
            for pid, drow in (row.get("dedup") or {}).items():
                agg = dedup_pools.setdefault(
                    str(pid), {"chunks_stored": 0,
                               "chunks_deduped": 0,
                               "bytes_stored": 0, "bytes_saved": 0})
                for kk in agg:
                    agg[kk] += int(drow.get(kk, 0) or 0)
            for fid, prow in (row.get("progress") or {}).items():
                progress["%s:%s" % (d, fid)] = dict(prow)
        return {
            "num_pgs": sum(r["num_pgs"] for r in per_pool.values()),
            "pg_states": states,
            "pools": {int(pid): row
                      for pid, row in per_pool.items()},
            "totals": totals,
            "inactive_pgs": inactive,
            # scrub surface: PGs with unrepaired inconsistencies
            # (PG_DAMAGED) beside the summed error count the totals
            # carry (OSD_SCRUB_ERRORS)
            "inconsistent_pgs": self.inconsistent_pgs(now, pools),
            "op_size_hist_bytes_pow2": self.op_size_hist(now),
            "osd_stats": osd_rows,
            # chip -> windowed busy/queue-wait/idle fractions (the
            # `status` device-utilization line + QoS oracles)
            "device_util": device_util,
            # codec -> summed recovery traffic counters (what the
            # locality-aware codecs measurably save)
            "repair_traffic": repair_traffic,
            # pool -> summed dedup counters (what the data-reduction
            # plane measurably saves)
            "dedup_pools": dedup_pools,
            # daemon:flowid -> fraction-complete rows for long
            # background flows (the `status` progress section)
            "progress": progress,
            # daemon -> wire rates + RTT matrix row (`net status`,
            # the net.* history series, the slow-ping soft detail)
            "net": net,
            # per-daemon report freshness + prune visibility (the
            # `status` max-age/stale-count line)
            "reports": self.report_freshness(now),
        }


class DictPGMap:
    """The original dict-of-rows PGMap: the golden reference the
    columnar fold AND the columnar ingest path are pinned against
    (tests/test_scale.py, tests/test_ingest.py) and the baseline for
    the `bench.py --scale` ingest/fold micro-benchmarks.  Keep its
    semantics bit-for-bit when touching either class."""

    def __init__(self, stale_after: float = 15.0):
        self.stale_after = float(stale_after)
        # pgid -> latest stat row (+ "_from" daemon, "_stamp")
        self.pg_stats: dict[str, dict] = {}
        # pgid -> {counter_s: rate} derived from the last two reports
        self.rates: dict[str, dict] = {}
        # daemon -> {"op_size_hist_bytes_pow2": [...], "_stamp": t}
        self.osd_stats: dict[str, dict] = {}
        self.report_stamps: dict[str, float] = {}
        self.ingest = _new_ingest()
        self.pruned_stale = 0
        self.pruned_pool = 0
        self.pruned_daemons = 0

    # -- ingest ------------------------------------------------------------

    def apply_report(self, daemon: str, pg_stats: list | None,
                     osd_stats: dict | None, stamp: float,
                     pg_stats_cols: dict | None = None,
                     nbytes: int | None = None) -> None:
        t0 = _time.perf_counter()
        self.report_stamps[daemon] = stamp
        if osd_stats:
            row = dict(osd_stats)
            row["_stamp"] = stamp
            self.osd_stats[daemon] = row
        fmt = "legacy"
        legacy_rows = list(pg_stats or ())
        rows = legacy_rows
        cols_rows = 0
        if pg_stats_cols is not None:
            # the golden reference has no fast path: unpack and walk
            fmt = "columnar"
            unpacked = statblock.unpack_stat_rows(pg_stats_cols)
            cols_rows = len(unpacked)
            rows = unpacked + legacy_rows
        for st in rows:
            pgid = st.get("pgid")
            if not pgid:
                continue
            prev = self.pg_stats.get(pgid)
            cur = dict(st)
            cur["_from"] = daemon
            cur["_stamp"] = stamp
            if prev is not None and prev["_from"] == daemon:
                dt = stamp - prev["_stamp"]
                if dt > 0:
                    self.rates[pgid] = {
                        c + "_s": max(0.0, (cur.get(c, 0)
                                            - prev.get(c, 0)) / dt)
                        for c in RATE_COUNTERS}
            else:
                self.rates.pop(pgid, None)
            self.pg_stats[pgid] = cur
        if nbytes is None:
            nbytes = (statblock.block_nbytes(pg_stats_cols)
                      if pg_stats_cols is not None else 0)
        _note_ingest(self.ingest, fmt, cols_rows, len(legacy_rows),
                     nbytes, _time.perf_counter() - t0)

    # -- pruning -----------------------------------------------------------

    def prune(self, now: float, pools: set | None = None,
              after: float | None = None) -> dict:
        after = self.stale_after if after is None else float(after)
        dropped_stale = dropped_pool = 0
        for pgid, st in list(self.pg_stats.items()):
            if now - st["_stamp"] > after:
                dropped_stale += 1
            elif pools is not None and st.get("pool") not in pools:
                dropped_pool += 1
            else:
                continue
            del self.pg_stats[pgid]
            self.rates.pop(pgid, None)
        self.pruned_stale += dropped_stale
        self.pruned_pool += dropped_pool
        dropped_daemons = 0
        for d in [d for d, t in self.report_stamps.items()
                  if now - t > after]:
            del self.report_stamps[d]
            self.osd_stats.pop(d, None)
            dropped_daemons += 1
        self.pruned_daemons += dropped_daemons
        return {"stale": dropped_stale, "pool": dropped_pool,
                "daemons": dropped_daemons}

    # -- views -------------------------------------------------------------

    def _live_rows(self, now: float, pools: set | None):
        for pgid, st in self.pg_stats.items():
            if now - st["_stamp"] > self.stale_after:
                continue            # dead primary's last report
            if pools is not None and st.get("pool") not in pools:
                continue            # pool deleted since the report
            yield pgid, st

    def pool_totals(self, now: float,
                    pools: set | None = None) -> dict[int, dict]:
        out: dict[int, dict] = {}
        for pgid, st in self._live_rows(now, pools):
            row = out.setdefault(st["pool"], {
                "num_pgs": 0, "objects": 0, "bytes": 0,
                "degraded": 0, "misplaced": 0, "unfound": 0,
                "log_size": 0, "scrub_errors": 0,
                **{k: 0.0 for k in RATE_KEYS}})
            row["num_pgs"] += 1
            row["objects"] += st.get("num_objects", 0)
            row["bytes"] += st.get("num_bytes", 0)
            row["degraded"] += st.get("degraded", 0)
            row["misplaced"] += st.get("misplaced", 0)
            row["unfound"] += st.get("unfound", 0)
            row["log_size"] += st.get("log_size", 0)
            row["scrub_errors"] += st.get("scrub_errors", 0)
            rt = self.rates.get(pgid)
            if rt:
                for k in RATE_KEYS:
                    row[k] += rt.get(k, 0.0)
        return out

    def pg_state_counts(self, now: float,
                        pools: set | None = None) -> dict[str, int]:
        states: dict[str, int] = {}
        for _pgid, st in self._live_rows(now, pools):
            s = st.get("state", "unknown")
            states[s] = states.get(s, 0) + 1
        return states

    def inconsistent_pgs(self, now: float,
                         pools: set | None = None) -> int:
        return sum(1 for _p, st in self._live_rows(now, pools)
                   if st.get("scrub_errors", 0))

    live_osd_stats = PGMap.live_osd_stats
    op_size_hist = PGMap.op_size_hist
    report_freshness = PGMap.report_freshness
    digest = PGMap.digest
