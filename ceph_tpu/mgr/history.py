"""Cluster history plane: fixed-memory downsampled metric rings.

Every observability surface before this PR is point-in-time: the mgr
digest is soft state with a 30 s TTL, the flight-recorder rings are
bounded snapshots, and bench figures are one-shot.  Kim et al.
(arXiv:1709.05365, PAPERS.md) characterize EC-cluster behavior from
measurements *over time* — p99 trajectories, utilization shifts, the
moment a pathology starts — so this module retains exactly that: an
RRD-style multi-resolution ring store fed each stats tick from the
already-folded digest.

* **HistoryStore** — per (series, label) a small set of downsampling
  tiers (default 5 s x 120 / 30 s x 120 / 5 min x 288: ten minutes
  fine, an hour medium, a day coarse).  Each tier cell is keyed by
  its absolute bucket index ``int(t // width)`` and aggregates
  (count, min, max, sum, last).  Memory is bounded by construction:
  at most ``cap`` cells per tier per labeled series, label
  cardinality capped per series (overflow is *dropped and counted*,
  never silently folded), and a missing bucket index IS the record
  of a gap — a dead mgr leaves holes, never interpolated cells.

* **Two instances, one feed.**  The mgr owns one (ingested in
  `_stats_loop`, serving the anomaly engine + exporter families +
  bench --observe), and EVERY mon folds each arriving MMonMgrDigest
  into its own (serving `perf history` locally) — so the query
  surface needs no new mon<->mgr protocol and survives leader
  elections with whatever history that mon has witnessed.

* **AnomalyEngine** — per-series EWMA mean/variance with a one-sided
  (upper) z-score and sustained-window raise/clear rules.  The
  baseline freezes while a series is anomalous, so a sustained shift
  stays raised instead of being adapted away, and clears only when
  the signal actually returns.  Active anomalies ride the digest
  (``digest["anomalies"]``) and the mon commits them as the
  paxos-persisted PERF_ANOMALY health edge (the SLO_BURN idiom: a
  fresh leader still warns).

The series names live in ``trace.registry.HISTORY_SERIES``; the
drift lint cross-checks them against this module's extractors and
the bench/test consumers in both directions.
"""

from __future__ import annotations

import time

# default downsampling ladder: (cell width seconds, ring capacity)
HISTORY_TIERS = ((5.0, 120), (30.0, 120), (300.0, 288))


def parse_tiers(spec) -> tuple:
    """Tier ladder from conf: either the 'width_s:cells,...' string
    form the config schema carries or an already-structured
    sequence of (width, cells) pairs."""
    if isinstance(spec, str):
        return tuple(
            (float(part.split(":")[0]), int(part.split(":")[1]))
            for part in spec.split(",") if part.strip())
    return tuple((float(w), int(cap)) for w, cap in spec)

# per-cell aggregate slots
_COUNT, _MIN, _MAX, _SUM, _LAST = range(5)


def extract_samples(digest: dict) -> list:
    """Flatten one mgr digest into (series, label, value) samples —
    the single place the HISTORY_SERIES names are emitted from (the
    registry lint scans these literals).  Labels are strings (pool
    id, chip index, tenant) or None for cluster-wide series."""
    out: list = []
    totals = digest.get("totals") or {}
    for series, key in (("io.read_ops_s", "read_ops_s"),
                        ("io.write_ops_s", "write_ops_s"),
                        ("io.read_bytes_s", "read_bytes_s"),
                        ("io.write_bytes_s", "write_bytes_s"),
                        ("recovery.ops_s", "recovery_ops_s"),
                        ("recovery.bytes_s", "recovery_bytes_s")):
        out.append((series, None, float(totals.get(key) or 0.0)))
    for pid, row in (digest.get("pools") or {}).items():
        out.append(("pg.degraded", str(pid),
                    float(row.get("degraded") or 0)))
        out.append(("pg.misplaced", str(pid),
                    float(row.get("misplaced") or 0)))
    for chip, row in (digest.get("device_util") or {}).items():
        out.append(("device.busy_frac", str(chip),
                    float(row.get("busy_frac") or 0.0)))
        out.append(("device.queue_wait_frac", str(chip),
                    float(row.get("queue_wait_frac") or 0.0)))
    for tenant, row in (digest.get("slo") or {}).items():
        out.append(("tenant.p99_ms", str(tenant),
                    float(row.get("p99_ms") or 0.0)))
        burn = row.get("burn_fast")
        if burn is not None:
            out.append(("tenant.burn_fast", str(tenant),
                        float(burn)))
    repair_read = repair_moved = 0
    for row in (digest.get("repair_traffic") or {}).values():
        repair_read += int(row.get("read") or 0)
        repair_moved += int(row.get("moved") or 0)
    out.append(("repair.bytes_read", None, float(repair_read)))
    out.append(("repair.bytes_moved", None, float(repair_moved)))
    dd_stored = dd_saved = 0
    for row in (digest.get("dedup_pools") or {}).values():
        dd_stored += int(row.get("bytes_stored") or 0)
        dd_saved += int(row.get("bytes_saved") or 0)
    out.append(("dedup.bytes_stored", None, float(dd_stored)))
    out.append(("dedup.bytes_saved", None, float(dd_saved)))
    # network plane: per-daemon worst-peer RTT, send-queue depth and
    # lossless resend rate — the AnomalyEngine watches rtt/resends so
    # a degrading link pages like a degrading chip does
    for daemon, row in (digest.get("net") or {}).items():
        out.append(("net.rtt_ms", str(daemon),
                    float(row.get("rtt_max_ms") or 0.0)))
        out.append(("net.queue_depth", str(daemon),
                    float(row.get("queue_depth") or 0)))
        out.append(("net.resend_rate", str(daemon),
                    float(row.get("resend_rate") or 0.0)))
    return out


class HistoryStore:
    """The fixed-memory ring store.  `ingest` folds one digest tick;
    `query` renders downsampled rows for one labeled series over a
    window, picking the finest tier that still covers it."""

    def __init__(self, ctx=None, tiers=None):
        self.ctx = ctx
        self._tiers = parse_tiers(
            tiers or (ctx and ctx.conf.get("history_tiers"))
            or HISTORY_TIERS)
        # (series, label) -> [tier dict: bucket index -> cell list]
        self._rings: dict[tuple, list] = {}
        # series -> label set (cardinality guard)
        self._labels: dict[str, set] = {}
        self.dropped_labels = 0
        self.ticks = 0

    @property
    def tiers(self) -> tuple:
        return self._tiers

    @property
    def label_max(self) -> int:
        if self.ctx is None:
            return 32
        return int(self.ctx.conf.get("history_label_max", 32))

    # -- ingest ----------------------------------------------------------

    def ingest(self, now: float, digest: dict,
               samples: list | None = None) -> None:
        self.ticks += 1
        if samples is None:
            samples = extract_samples(digest)
        for series, label, value in samples:
            self.note(series, label, now, value)

    def note(self, series: str, label, now: float,
             value: float) -> None:
        labels = self._labels.setdefault(series, set())
        if label not in labels:
            if len(labels) >= self.label_max:
                self.dropped_labels += 1
                return
            labels.add(label)
        ring = self._rings.get((series, label))
        if ring is None:
            ring = [dict() for _ in self._tiers]
            self._rings[(series, label)] = ring
        for (width, cap), cells in zip(self._tiers, ring):
            b = int(now // width)
            cell = cells.get(b)
            if cell is None:
                cells[b] = [1, value, value, value, value]
                if len(cells) > cap:
                    floor = b - cap
                    for k in [k for k in cells if k <= floor]:
                        del cells[k]
            else:
                cell[_COUNT] += 1
                if value < cell[_MIN]:
                    cell[_MIN] = value
                if value > cell[_MAX]:
                    cell[_MAX] = value
                cell[_SUM] += value
                cell[_LAST] = value

    # -- views -----------------------------------------------------------

    def series_names(self) -> list:
        """Sorted (series, label) pairs with any retained data."""
        return sorted(self._rings,
                      key=lambda k: (k[0], k[1] or ""))

    def query(self, series: str, label=None, window: float = 600.0,
              now: float | None = None) -> dict:
        """Downsampled rows for one labeled series: the finest tier
        whose retained span covers `window`.  Rows are
        [t_bucket, count, min, max, avg, last] in time order; a
        missing bucket is a gap (the mgr was dead or the series
        unfed) — never an interpolated cell."""
        now = time.time() if now is None else now
        ring = self._rings.get((series, label))
        if ring is None:
            return {"series": series, "label": label, "rows": [],
                    "tier_s": None, "window": window}
        ti = len(self._tiers) - 1
        for i, (width, cap) in enumerate(self._tiers):
            if width * cap >= window:
                ti = i
                break
        width, _cap = self._tiers[ti]
        lo = int((now - window) // width)
        rows = []
        for b in sorted(k for k in ring[ti] if k >= lo):
            c = ring[ti][b]
            rows.append([round(b * width, 3), c[_COUNT],
                         round(c[_MIN], 6), round(c[_MAX], 6),
                         round(c[_SUM] / c[_COUNT], 6),
                         round(c[_LAST], 6)])
        return {"series": series, "label": label, "tier_s": width,
                "window": window, "rows": rows}

    def latest(self, series: str, label=None,
               now: float | None = None):
        """(last value, age seconds) of the newest retained cell for
        one labeled series across all tiers — the stale-`status`
        fallback serves it (annotated with its age) once the live
        digest passes its TTL.  None when the series was never
        fed."""
        now = time.time() if now is None else now
        ring = self._rings.get((series, label))
        if ring is None:
            return None
        best = None
        for (width, _cap), cells in zip(self._tiers, ring):
            if not cells:
                continue
            b = max(cells)
            t = (b + 1) * width
            if best is None or t > best[0]:
                best = (t, cells[b][_LAST])
        if best is None:
            return None
        return best[1], max(0.0, now - best[0])

    def labels_for(self, series: str) -> list:
        """Retained labels for one series (the stale-panel fallback
        enumerates device chips with it)."""
        return sorted((lb for s, lb in self._rings
                       if s == series and lb is not None),
                      key=str)

    def cell_count(self) -> int:
        return sum(len(cells) for ring in self._rings.values()
                   for cells in ring)

    def max_cells(self) -> int:
        """The hard cell ceiling implied by the tier caps and the
        per-series label cap — what the memory-bound test and the
        bench --observe gate assert against."""
        per_series = sum(cap for _w, cap in self._tiers)
        n_series = sum(max(1, len(v)) for v in self._labels.values())
        return per_series * n_series

    def stats(self) -> dict:
        return {"ticks": self.ticks,
                "series": len(self._rings),
                "cells": self.cell_count(),
                "dropped_labels": self.dropped_labels,
                "tiers": [[w, c] for w, c in self._tiers]}


class AnomalyEngine:
    """EWMA mean/variance per labeled series with one-sided z-score
    + sustained-window raise/clear — the committed PERF_ANOMALY
    feed.

    Defaults are deliberately deaf (z >= 6 sustained for 8 ticks
    after 60 warm-up samples): routine load swings never page; the
    planted sustained shifts the thrash oracles drive do.  The
    baseline does not absorb anomalous samples, so a persistent
    shift stays raised until the signal actually recedes."""

    def __init__(self, ctx=None):
        self.ctx = ctx
        # (series, label) -> [n, mean, var, hot, cold, active]
        self._state: dict[tuple, list] = {}
        # active anomaly name -> detail row
        self.active: dict[str, dict] = {}

    def _conf(self, key, default):
        if self.ctx is None:
            return default
        return self.ctx.conf.get(key, default)

    @property
    def watched(self) -> tuple:
        spec = self._conf("history_anomaly_series", (
            "device.busy_frac", "device.queue_wait_frac",
            "tenant.p99_ms", "tenant.burn_fast",
            "net.rtt_ms", "net.resend_rate"))
        if isinstance(spec, str):
            spec = [s.strip() for s in spec.split(",") if s.strip()]
        return tuple(spec)

    @staticmethod
    def name_of(series: str, label) -> str:
        return series if label is None else "%s[%s]" % (series, label)

    def observe(self, samples: list) -> dict:
        """Fold one tick of (series, label, value) samples; returns
        the active-anomaly map the digest carries."""
        z_raise = float(self._conf("history_anomaly_z", 6.0))
        z_clear = float(self._conf("history_anomaly_clear_z", 2.0))
        sustain = int(self._conf("history_anomaly_sustain", 8))
        clear_n = int(self._conf("history_anomaly_clear", 4))
        min_n = int(self._conf("history_anomaly_min_samples", 60))
        alpha = float(self._conf("history_anomaly_alpha", 0.05))
        watched = self.watched
        for series, label, value in samples:
            if series not in watched:
                continue
            key = (series, label)
            st = self._state.get(key)
            if st is None:
                st = self._state[key] = [0, value, 0.0, 0, 0, False]
            n, mean, var, hot, cold, active = st
            std = max(var, 1e-12) ** 0.5
            # one-sided: only a sustained INCREASE is an anomaly (a
            # cluster going idle is a non-event, not a page)
            z = (value - mean) / std if n >= min_n else 0.0
            name = self.name_of(series, label)
            if z >= z_raise:
                hot += 1
                cold = 0
                if not active and hot >= sustain:
                    active = True
                if active:
                    self.active[name] = {
                        "series": series, "label": label,
                        "value": round(value, 6),
                        "mean": round(mean, 6),
                        "z": round(z, 2)}
            else:
                hot = 0
                if active:
                    if z < z_clear:
                        cold += 1
                        if cold >= clear_n:
                            active = False
                            cold = 0
                            self.active.pop(name, None)
                    else:
                        cold = 0
            # freeze the baseline while the series runs hot, so a
            # sustained shift cannot train itself back to normal
            if z < z_clear:
                n += 1
                d = value - mean
                if n < min_n:
                    # warm-up: flat averages converge fast from the
                    # first sample instead of chasing EWMA lag
                    mean += d / n
                    var += (d * (value - mean) - var) / n
                else:
                    mean += alpha * d
                    var = (1 - alpha) * (var + alpha * d * d)
            st[0], st[1], st[2] = n, mean, var
            st[3], st[4], st[5] = hot, cold, active
        return {k: dict(v) for k, v in sorted(self.active.items())}
