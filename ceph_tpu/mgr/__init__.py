from .daemon import Manager

__all__ = ["Manager"]
