"""Per-tenant SLO engine: multi-window burn rates over the tenant
stage histograms the OSDs report.

The SRE-workbook alerting model (multiwindow, multi-burn-rate) applied
to the tenant plane Kim et al. (arXiv:1709.05365) motivates: each
tenant has a **latency objective** — `slo_latency_objective` (e.g.
99%) of its ops must finish under `slo_latency_target_ms` — and an
**availability objective** sharing the same error budget (an errored
op spends budget exactly like a too-slow one).  The budget is
`1 - objective`; the **burn rate** over a window is

    burn(W) = (bad ops in W / total ops in W) / (1 - objective)

so burn 1.0 spends the budget exactly at the sustainable rate and
burn 14.4 exhausts a 30-day budget in ~2 days.  SLO_BURN raises only
when BOTH the fast and the slow window burn past their thresholds
(`slo_burn_fast` / `slo_burn_slow`) — a lone spike never pages, a
sustained burn pages fast; SLO_LATENCY is the immediate p99-over-
target breach detail beside it.

Inputs are the cumulative per-tenant stage histograms (pow2 µs
buckets) and good/bad op counters each OSD ships in MMgrReport
``osd_stats["tenants"]`` — the engine keeps a bounded ring of
aggregate snapshots per tenant and derives every window figure from
snapshot deltas, so one mgr restart costs at most one window of
history and no daemon keeps per-window state.

"Bad" latency counting is bucket-resolution conservative: a pow2
bucket counts as over-target only when its LOWER bound already
exceeds the target, so the engine never over-reports a burn from
bucket granularity.
"""

from __future__ import annotations

N_BUCKETS = 32

# every tenant stage histogram family the OSDs emit (the registry
# drift lint cross-checks these against the note_tenant_stage call
# sites): queue_wait (mClock shard dequeue), subop_rtt (replicated
# commit round trip), ec_batch_wait (encode incl batch window),
# device_dispatch (the op's own flush ticket), total (end-to-end,
# the SLO engine's latency input)
TENANT_STAGES = ("queue_wait", "subop_rtt", "ec_batch_wait",
                 "device_dispatch", "total")


def _hist_add(acc: list[int], hist) -> None:
    for i, v in enumerate(hist[:N_BUCKETS]):
        acc[i] += int(v)


def _hist_sub(a: list[int], b: list[int]) -> list[int]:
    # counter resets (OSD restart) clamp at zero: one window of
    # undercounted rate, never a negative burn
    return [max(0, x - y) for x, y in zip(a, b)]


def hist_p_ms(hist: list[int], p: float) -> float:
    """The p-quantile's bucket UPPER bound in ms (pow2-µs buckets:
    bucket i counts samples in [2^i, 2^(i+1)) µs)."""
    total = sum(hist)
    if not total:
        return 0.0
    want = p * total
    cum = 0
    for i, n in enumerate(hist):
        cum += n
        if cum >= want:
            return float(1 << (i + 1)) / 1e3
    return float(1 << len(hist)) / 1e3


def hist_over_ms(hist: list[int], target_ms: float) -> int:
    """Samples in buckets whose lower bound exceeds target_ms
    (conservative: the bucket containing the target counts good)."""
    target_us = max(1.0, target_ms * 1e3)
    out = 0
    for i, n in enumerate(hist):
        if float(1 << i) >= target_us:
            out += n
    return out


class SLOEngine:
    """Aggregates the per-daemon tenant rows into per-tenant burn
    verdicts.  One instance on the mgr; `ingest` runs per stats tick,
    `evaluate` feeds the digest (and through it the mon's
    SLO_LATENCY / SLO_BURN health checks)."""

    RING_CAP = 2048

    def __init__(self, ctx):
        self.ctx = ctx
        # tenant -> list of (t, ops, errors, total_hist) snapshots
        self._rings: dict[str, list] = {}

    # -- live conf -------------------------------------------------------

    @property
    def target_ms(self) -> float:
        return float(self.ctx.conf.get("slo_latency_target_ms",
                                       100.0))

    @property
    def objective(self) -> float:
        return float(self.ctx.conf.get("slo_latency_objective", 0.99))

    @property
    def fast_window(self) -> float:
        return float(self.ctx.conf.get("slo_fast_window", 60.0))

    @property
    def slow_window(self) -> float:
        return float(self.ctx.conf.get("slo_slow_window", 300.0))

    @property
    def min_ops(self) -> int:
        return int(self.ctx.conf.get("slo_min_ops", 30))

    # -- ingest ----------------------------------------------------------

    def ingest(self, now: float, osd_stats_rows: dict) -> None:
        """Fold one stats tick: `osd_stats_rows` is the mgr's
        live_osd_stats view ({daemon: row}); each row's "tenants"
        map carries that daemon's cumulative tenant counters.  The
        cluster aggregate (sum over daemons) becomes one ring
        snapshot per tenant."""
        agg: dict[str, dict] = {}
        for row in osd_stats_rows.values():
            for tenant, trow in (row.get("tenants") or {}).items():
                a = agg.setdefault(tenant, {
                    "ops": 0, "errors": 0,
                    "hist": [0] * N_BUCKETS})
                a["ops"] += int(trow.get("ops") or 0)
                a["errors"] += int(trow.get("errors") or 0)
                total = (trow.get("stages") or {}).get("total")
                if total:
                    _hist_add(a["hist"], total)
        horizon = 2.0 * max(self.fast_window, self.slow_window)
        for tenant, a in agg.items():
            ring = self._rings.setdefault(tenant, [])
            ring.append((now, a["ops"], a["errors"], a["hist"]))
            while ring and (now - ring[0][0] > horizon
                            or len(ring) > self.RING_CAP):
                ring.pop(0)

    # -- evaluation ------------------------------------------------------

    def _window_delta(self, ring: list, now: float, window: float):
        """(ops, bad, hist) deltas between the newest snapshot and
        the oldest one inside the window (None without two points)."""
        newest = ring[-1]
        base = None
        for snap in ring:
            if now - snap[0] <= window:
                base = snap
                break
        if base is None or base is newest:
            return None
        hist = _hist_sub(newest[3], base[3])
        ops = max(0, newest[1] - base[1])
        errors = max(0, newest[2] - base[2])
        return ops, errors, hist

    def evaluate(self, now: float) -> dict[str, dict]:
        """Per-tenant verdicts for the digest: window p99, burn rates
        over both windows, and the two alert booleans the mon turns
        into paxos-committed health edges."""
        budget = max(1e-6, 1.0 - self.objective)
        out: dict[str, dict] = {}
        for tenant, ring in self._rings.items():
            if not ring:
                continue

            def burn(win):
                d = self._window_delta(ring, now, win)
                if d is None or d[0] <= 0:
                    return None, 0, None
                ops, errors, hist = d
                bad = hist_over_ms(hist, self.target_ms) + errors
                return (bad / ops) / budget, ops, hist

            burn_fast, ops_fast, hist_fast = burn(self.fast_window)
            burn_slow, ops_slow, _h = burn(self.slow_window)
            p99 = (hist_p_ms(hist_fast, 0.99)
                   if hist_fast is not None else 0.0)
            enough = ops_fast >= self.min_ops
            lat_violation = bool(enough and p99 > self.target_ms)
            burn_alert = bool(
                enough and burn_fast is not None
                and burn_slow is not None
                and burn_fast >= float(self.ctx.conf.get(
                    "slo_burn_fast", 14.4))
                and burn_slow >= float(self.ctx.conf.get(
                    "slo_burn_slow", 6.0)))
            out[tenant] = {
                "ops_total": int(ring[-1][1]),
                "errors_total": int(ring[-1][2]),
                "window_ops": int(ops_fast),
                "p99_ms": round(p99, 3),
                "target_ms": self.target_ms,
                "burn_fast": (round(burn_fast, 3)
                              if burn_fast is not None else None),
                "burn_slow": (round(burn_slow, 3)
                              if burn_slow is not None else None),
                "latency_violation": lat_violation,
                "burn_alert": burn_alert,
            }
        return out
