"""CRUSH map model: buckets, rules, tunables, choose_args.

A declarative description of the placement hierarchy, consumed by both
the host interpreter (ceph_tpu.ops.crush.host) and the vectorized JAX
kernel (ceph_tpu.ops.crush.jax_kernel).

Reference semantics: struct crush_map / crush_bucket / crush_rule
(src/crush/crush.h) and the construction rules in src/crush/builder.c —
list buckets carry cumulative sums, tree buckets a 1-indexed implicit
binary tree of node weights, straw buckets the v0/v1 straw-length
computation, straw2 plain 16.16 item weights.  All weights are 16.16
fixed point.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

# bucket algorithms
UNIFORM, LIST, TREE, STRAW, STRAW2 = 1, 2, 3, 4, 5
ALG_NAMES = {UNIFORM: "uniform", LIST: "list", TREE: "tree",
             STRAW: "straw", STRAW2: "straw2"}

# rule step opcodes
NOOP = 0
TAKE = 1
CHOOSE_FIRSTN = 2
CHOOSE_INDEP = 3
EMIT = 4
CHOOSELEAF_FIRSTN = 6
CHOOSELEAF_INDEP = 7
SET_CHOOSE_TRIES = 8
SET_CHOOSELEAF_TRIES = 9
SET_CHOOSE_LOCAL_TRIES = 10
SET_CHOOSE_LOCAL_FALLBACK_TRIES = 11
SET_CHOOSELEAF_VARY_R = 12
SET_CHOOSELEAF_STABLE = 13

ITEM_UNDEF = 0x7FFFFFFE  # internal: slot not yet decided (indep)
ITEM_NONE = 0x7FFFFFFF   # exported: no mapping for this slot

RJENKINS1 = 0


@dataclass
class Bucket:
    """One interior node of the hierarchy (negative id)."""

    id: int                      # < 0
    alg: int
    type: int                    # hierarchy level (e.g. 1=host, 2=rack...)
    items: list[int]             # child ids (devices >= 0, buckets < 0)
    weight: int = 0              # 16.16 total
    hash: int = RJENKINS1
    name: str = ""               # bucket name (compiler/tooling)
    # per-algorithm derived state
    item_weight: int = 0               # uniform: shared weight
    item_weights: list[int] = field(default_factory=list)  # list/straw/straw2
    sum_weights: list[int] = field(default_factory=list)   # list: cumulative
    node_weights: list[int] = field(default_factory=list)  # tree: 1-indexed
    straws: list[int] = field(default_factory=list)        # straw: lengths

    @property
    def size(self) -> int:
        return len(self.items)

    def to_dict(self) -> dict:
        return {
            "id": self.id, "alg": self.alg, "type": self.type,
            "items": self.items, "weight": self.weight, "hash": self.hash,
            "name": self.name,
            "item_weight": self.item_weight,
            "item_weights": self.item_weights,
            "sum_weights": self.sum_weights,
            "node_weights": self.node_weights,
            "straws": self.straws,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "Bucket":
        d = dict(d)
        d.setdefault("name", "")
        return cls(**d)


@dataclass
class Rule:
    """A placement rule: a short program over (op, arg1, arg2) steps."""

    id: int
    steps: list[tuple[int, int, int]]
    name: str = ""

    def to_dict(self) -> dict:
        return {"id": self.id, "name": self.name,
                "steps": [list(s) for s in self.steps]}

    @classmethod
    def from_dict(cls, d: dict) -> "Rule":
        return cls(id=d["id"], name=d.get("name", ""),
                   steps=[tuple(s) for s in d["steps"]])


@dataclass
class Tunables:
    """Retry-behaviour knobs.  Defaults = the reference's optimal profile."""

    choose_local_tries: int = 0
    choose_local_fallback_tries: int = 0
    choose_total_tries: int = 50
    chooseleaf_descend_once: int = 1
    chooseleaf_vary_r: int = 1
    chooseleaf_stable: int = 1
    straw_calc_version: int = 1

    @classmethod
    def legacy(cls) -> "Tunables":
        return cls(choose_local_tries=2, choose_local_fallback_tries=5,
                   choose_total_tries=19, chooseleaf_descend_once=0,
                   chooseleaf_vary_r=0, chooseleaf_stable=0,
                   straw_calc_version=0)

    def to_dict(self) -> dict:
        return self.__dict__.copy()

    @classmethod
    def from_dict(cls, d: dict) -> "Tunables":
        return cls(**d)


@dataclass
class WeightSet:
    """choose_args entry for one bucket: per-position weight vectors and
    optional id remapping (the balancer's retry-free lever)."""

    bucket_id: int
    weight_sets: list[list[int]] = field(default_factory=list)  # [pos][i]
    ids: list[int] | None = None

    def to_dict(self) -> dict:
        return {"bucket_id": self.bucket_id, "weight_sets": self.weight_sets,
                "ids": self.ids}

    @classmethod
    def from_dict(cls, d: dict) -> "WeightSet":
        return cls(**d)


class CrushMap:
    """The full placement map."""

    def __init__(self, tunables: Tunables | None = None):
        self.buckets: dict[int, Bucket] = {}       # id (<0) -> bucket
        self.rules: dict[int, Rule] = {}
        self.types: dict[int, str] = {0: "osd"}    # hierarchy level names
        self.tunables = tunables or Tunables()
        self.choose_args: dict[str, dict[int, WeightSet]] = {}
        self.device_classes: dict[int, str] = {}   # device id -> class name

    # -- derived ---------------------------------------------------------
    @property
    def max_devices(self) -> int:
        mx = 0
        for b in self.buckets.values():
            for item in b.items:
                if item >= mx:
                    mx = item + 1
        return mx

    @property
    def max_buckets(self) -> int:
        return max((-b for b in self.buckets), default=0)

    def bucket(self, item: int) -> Bucket | None:
        return self.buckets.get(item)

    # -- construction ----------------------------------------------------
    def add_bucket(
        self, alg: int, type: int, items: list[int], weights: list[int],
        id: int | None = None, hash: int = RJENKINS1, name: str = "",
    ) -> Bucket:
        """Create a bucket, deriving its per-algorithm state the same way
        the reference builder does (builder.c:190-639)."""
        if id is None:
            id = -(self.max_buckets + 1)
        assert id < 0 and id not in self.buckets
        assert len(items) == len(weights)
        b = Bucket(id=id, alg=alg, type=type, items=list(items), hash=hash,
                   name=name)
        if alg == UNIFORM:
            # uniform buckets share one item weight (first entry wins)
            b.item_weight = weights[0] if weights else 0
            b.weight = b.item_weight * len(items)
        elif alg == LIST:
            b.item_weights = list(weights)
            w = 0
            for wi in weights:
                w += wi
                b.sum_weights.append(w)
            b.weight = w
        elif alg == TREE:
            depth = _tree_depth(len(items))
            b.node_weights = [0] * (1 << depth)
            for i, wi in enumerate(weights):
                node = _tree_leaf_node(i)
                b.node_weights[node] = wi
                b.weight += wi
                for _ in range(1, depth):
                    node = _tree_parent(node)
                    b.node_weights[node] += wi
        elif alg == STRAW:
            b.item_weights = list(weights)
            b.weight = sum(weights)
            b.straws = _calc_straws(weights, self.tunables.straw_calc_version)
        elif alg == STRAW2:
            b.item_weights = list(weights)
            b.weight = sum(weights)
        else:
            raise ValueError(f"unknown bucket alg {alg}")
        self.buckets[id] = b
        return b

    def add_rule(self, steps: list[tuple[int, int, int]],
                 id: int | None = None, name: str = "") -> Rule:
        if id is None:
            id = max(self.rules, default=-1) + 1
        r = Rule(id=id, steps=[tuple(s) for s in steps], name=name)
        self.rules[id] = r
        return r

    # -- convenience hierarchy builder -----------------------------------
    def build_flat(self, n_osds: int, alg: int = STRAW2,
                   weights: list[int] | None = None) -> Bucket:
        """One root bucket over n_osds devices (weights 16.16, default 1.0)."""
        w = weights or [0x10000] * n_osds
        return self.add_bucket(alg, 1, list(range(n_osds)), w)

    # -- serialization ---------------------------------------------------
    def to_dict(self) -> dict:
        return {
            "buckets": [b.to_dict() for b in self.buckets.values()],
            "rules": [r.to_dict() for r in self.rules.values()],
            "types": self.types,
            "tunables": self.tunables.to_dict(),
            "choose_args": {
                name: [ws.to_dict() for ws in per.values()]
                for name, per in self.choose_args.items()
            },
            "device_classes": self.device_classes,
        }

    @classmethod
    def from_dict(cls, d: dict) -> "CrushMap":
        m = cls(Tunables.from_dict(d["tunables"]))
        for bd in d["buckets"]:
            b = Bucket.from_dict(bd)
            m.buckets[b.id] = b
        for rd in d["rules"]:
            r = Rule.from_dict(rd)
            m.rules[r.id] = r
        m.types = {int(k): v for k, v in d.get("types", {0: "osd"}).items()}
        for name, lst in d.get("choose_args", {}).items():
            m.choose_args[name] = {
                ws["bucket_id"]: WeightSet.from_dict(ws) for ws in lst
            }
        m.device_classes = {
            int(k): v for k, v in d.get("device_classes", {}).items()
        }
        return m


# -- tree bucket geometry (builder.c:294-327, crush.h:494) ----------------

def _tree_leaf_node(i: int) -> int:
    return ((i + 1) << 1) - 1


def _tree_height(n: int) -> int:
    h = 0
    while (n & 1) == 0:
        h += 1
        n >>= 1
    return h


def _tree_parent(n: int) -> int:
    h = _tree_height(n)
    if n & (1 << (h + 1)):
        return n - (1 << h)
    return n + (1 << h)


def _tree_depth(size: int) -> int:
    if size == 0:
        return 0
    depth = 1
    t = size - 1
    while t:
        t >>= 1
        depth += 1
    return depth


# -- legacy straw lengths (builder.c:430-546) -----------------------------

def _calc_straws(weights: list[int], version: int) -> list[int]:
    """Straw lengths for the legacy straw algorithm.

    Kept for map compatibility; the reference itself documents the
    approach as flawed and superseded by straw2.  Version 0 skips the
    numleft decrement for zero-weight items; version 1 decrements.
    """
    size = len(weights)
    straws = [0] * size
    # reverse = indices sorted ascending by weight (stable insertion order)
    reverse = sorted(range(size), key=lambda i: (weights[i], i))

    numleft = size
    straw = 1.0
    wbelow = 0.0
    lastw = 0.0
    i = 0
    while i < size:
        if weights[reverse[i]] == 0:
            straws[reverse[i]] = 0
            i += 1
            if version >= 1:
                numleft -= 1
            continue
        straws[reverse[i]] = int(straw * 0x10000)
        i += 1
        if i == size:
            break
        if version == 0:
            if weights[reverse[i]] == weights[reverse[i - 1]]:
                continue
            wbelow += (float(weights[reverse[i - 1]]) - lastw) * numleft
            j = i
            while j < size:
                if weights[reverse[j]] == weights[reverse[i]]:
                    numleft -= 1
                else:
                    break
                j += 1
            wnext = numleft * (weights[reverse[i]] - weights[reverse[i - 1]])
            pbelow = wbelow / (wbelow + wnext)
            straw *= math.pow(1.0 / pbelow, 1.0 / numleft)
            lastw = weights[reverse[i - 1]]
        else:
            wbelow += (float(weights[reverse[i - 1]]) - lastw) * numleft
            numleft -= 1
            wnext = numleft * (weights[reverse[i]] - weights[reverse[i - 1]])
            pbelow = wbelow / (wbelow + wnext)
            straw *= math.pow(1.0 / pbelow, 1.0 / numleft)
            lastw = weights[reverse[i - 1]]
    return straws
