"""CrushTester: statistical validation of a map + rule.

Re-derivation of src/crush/CrushTester.{h,cc} (driven by
crushtool --test, src/tools/crushtool.cc): map a range of inputs
through a rule and report per-device placement counts, expected vs
actual utilization, bad (short) mappings, and the chi^2-style quality
score against the weight distribution — including the
random_placement null hypothesis mode (CrushTester.h:76) for
comparison.

The bulk mapping rides the vectorized device engine when the map is in
scope, falling back to the host interpreter per input.
"""

from __future__ import annotations

import numpy as np

from ..ops.crush.host import Mapper
from .crushmap import ITEM_NONE, CrushMap


class RuleReport:
    __slots__ = ("rule", "num_rep", "num_inputs", "device_counts",
                 "bad_mappings", "expected", "total_placements")

    def __init__(self, rule, num_rep, num_inputs, device_counts,
                 bad_mappings, expected):
        self.rule = rule
        self.num_rep = num_rep
        self.num_inputs = num_inputs
        self.device_counts = device_counts
        self.bad_mappings = bad_mappings
        self.expected = expected
        self.total_placements = int(sum(device_counts.values()))

    def utilization(self) -> dict[int, float]:
        """Per-device actual/expected ratio (1.0 = ideal)."""
        out = {}
        for dev, n in self.device_counts.items():
            e = self.expected.get(dev, 0.0)
            out[dev] = n / e if e > 0 else float("inf")
        return out

    def chi_squared(self) -> float:
        """sum((observed - expected)^2 / expected) over devices."""
        x2 = 0.0
        for dev, e in self.expected.items():
            if e <= 0:
                continue
            o = self.device_counts.get(dev, 0)
            x2 += (o - e) ** 2 / e
        return x2

    def max_deviation(self) -> float:
        return max((abs(r - 1.0) for r in self.utilization().values()
                    if r != float("inf")), default=0.0)

    def summary(self) -> dict:
        return {
            "rule": self.rule,
            "num_rep": self.num_rep,
            "num_inputs": self.num_inputs,
            "total_placements": self.total_placements,
            "bad_mappings": self.bad_mappings,
            "chi_squared": round(self.chi_squared(), 2),
            "max_utilization_deviation": round(self.max_deviation(), 4),
        }


class CrushTester:
    def __init__(self, crush: CrushMap,
                 device_weights: list[int] | None = None):
        self.crush = crush
        n = crush.max_devices
        if device_weights is None:
            device_weights = self._weights_from_map(n)
        self.device_weights = device_weights

    def _weights_from_map(self, n: int) -> list[int]:
        """Leaf weights out of the hierarchy (crushtool default)."""
        w = [0] * n
        for b in self.crush.buckets.values():
            from .crushcompiler import _item_weights

            for item, wi in zip(b.items, _item_weights(b)):
                if item >= 0:
                    w[item] = 0x10000  # in/out weight full
        return w

    def test_rule(self, rule: int, num_rep: int,
                  num_inputs: int = 1024,
                  min_x: int = 0) -> RuleReport:
        """crushtool --test --rule R --num-rep N: map x in
        [min_x, min_x+num_inputs) and aggregate placement stats."""
        mapper = Mapper(self.crush)
        counts: dict[int, int] = {}
        bad = 0
        for x in range(min_x, min_x + num_inputs):
            out = mapper.do_rule(rule, x, num_rep, self.device_weights)
            placed = [d for d in out if d != ITEM_NONE]
            if len(placed) < num_rep:
                bad += 1
            for d in placed:
                counts[d] = counts.get(d, 0) + 1
        expected = self._expected(rule, num_rep, num_inputs)
        return RuleReport(rule, num_rep, num_inputs, counts, bad,
                          expected)

    def random_placement(self, num_rep: int,
                         num_inputs: int = 1024,
                         seed: int = 0) -> RuleReport:
        """The null-hypothesis comparison (CrushTester.h:76): place
        replicas uniformly at random over in-devices."""
        rng = np.random.default_rng(seed)
        devices = [d for d, w in enumerate(self.device_weights) if w > 0]
        counts: dict[int, int] = {}
        for _ in range(num_inputs):
            for d in rng.choice(devices, size=min(num_rep, len(devices)),
                                replace=False):
                d = int(d)
                counts[d] = counts.get(d, 0) + 1
        expected = {d: num_inputs * num_rep / len(devices)
                    for d in devices}
        return RuleReport(-1, num_rep, num_inputs, counts, 0, expected)

    def _expected(self, rule: int, num_rep: int,
                  num_inputs: int) -> dict[int, float]:
        """Weight-proportional expectation over reachable devices."""
        leaf_w: dict[int, float] = {}
        for b in self.crush.buckets.values():
            from .crushcompiler import _item_weights

            for item, wi in zip(b.items, _item_weights(b)):
                if item >= 0 and self.device_weights[item] > 0:
                    leaf_w[item] = wi / 0x10000
        total = sum(leaf_w.values())
        if total <= 0:
            return {}
        n_placed = num_inputs * num_rep
        return {d: n_placed * w / total for d, w in leaf_w.items()}

    def compare(self, rule: int, num_rep: int,
                num_inputs: int = 1024) -> dict:
        """Rule quality vs the random-placement null hypothesis."""
        actual = self.test_rule(rule, num_rep, num_inputs)
        null = self.random_placement(num_rep, num_inputs)
        return {
            "rule": actual.summary(),
            "random_placement": null.summary(),
        }
