"""CrushCompiler: the crush map text format, compile + decompile.

Re-derivation of src/crush/CrushCompiler.cc: the same section grammar
crushtool speaks —

    tunable <name> <value>
    device <num> osd.<num> [class <name>]
    type <num> <name>
    <typename> <bucketname> {
        id <num>
        alg uniform|list|tree|straw|straw2
        hash 0
        item <name> weight <float> [pos <n>]
    }
    rule <name> {
        id <num>
        type replicated|erasure
        step take <bucketname> [class <name>]
        step set_<tunable> <value>
        step choose|chooseleaf firstn|indep <n> type <typename>
        step emit
    }

compile() parses text into a CrushMap; decompile() emits text that
round-trips (compile(decompile(m)) maps identically to m).  Weights
are printed with 5 decimals of the 16.16 fixed point, exactly like the
reference's decompile output.
"""

from __future__ import annotations

from .crushmap import (CHOOSE_FIRSTN, CHOOSE_INDEP, CHOOSELEAF_FIRSTN,
                       CHOOSELEAF_INDEP, EMIT, LIST, STRAW, STRAW2,
                       TAKE, TREE, UNIFORM, CrushMap, Tunables)
from .crushmap import (SET_CHOOSE_LOCAL_FALLBACK_TRIES,
                       SET_CHOOSE_LOCAL_TRIES, SET_CHOOSE_TRIES,
                       SET_CHOOSELEAF_STABLE, SET_CHOOSELEAF_TRIES,
                       SET_CHOOSELEAF_VARY_R)

ALG_BY_NAME = {"uniform": UNIFORM, "list": LIST, "tree": TREE,
               "straw": STRAW, "straw2": STRAW2}
ALG_NAME = {v: k for k, v in ALG_BY_NAME.items()}

SET_STEPS = {
    "set_choose_tries": SET_CHOOSE_TRIES,
    "set_chooseleaf_tries": SET_CHOOSELEAF_TRIES,
    "set_choose_local_tries": SET_CHOOSE_LOCAL_TRIES,
    "set_choose_local_fallback_tries": SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    "set_chooseleaf_vary_r": SET_CHOOSELEAF_VARY_R,
    "set_chooseleaf_stable": SET_CHOOSELEAF_STABLE,
}
SET_STEP_NAME = {v: k for k, v in SET_STEPS.items()}

TUNABLES = ("choose_local_tries", "choose_local_fallback_tries",
            "choose_total_tries", "chooseleaf_descend_once",
            "chooseleaf_vary_r", "chooseleaf_stable",
            "straw_calc_version")


class CompileError(ValueError):
    pass


def _tokenize(text: str):
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if not line:
            continue
        yield lineno, line.replace("{", " { ").replace("}", " } ").split()


def compile(text: str) -> CrushMap:  # noqa: A001 (reference name)
    m = CrushMap(Tunables())
    m.types = {}
    devices: dict[str, int] = {}
    bucket_sections: list[tuple[int, list[list[str]]]] = []
    rule_sections: list[tuple[int, list[list[str]]]] = []
    toks = list(_tokenize(text))
    i = 0

    def collect_section(start: int):
        body = []
        j = start
        while j < len(toks) and "}" not in toks[j][1]:
            body.append(toks[j][1])
            j += 1
        if j >= len(toks):
            raise CompileError("unterminated section at line %d"
                               % toks[start - 1][0])
        return body, j + 1

    while i < len(toks):
        lineno, words = toks[i]
        head = words[0]
        if head == "tunable":
            if words[1] not in TUNABLES:
                raise CompileError("line %d: unknown tunable %r"
                                   % (lineno, words[1]))
            setattr(m.tunables, words[1], int(words[2]))
            i += 1
        elif head == "device":
            num = int(words[1])
            devices[words[2]] = num
            if len(words) >= 5 and words[3] == "class":
                m.device_classes[num] = words[4]
            i += 1
        elif head == "type":
            m.types[int(words[1])] = words[2]
            i += 1
        elif head == "rule" and words[-1] == "{":
            body, i = collect_section(i + 1)
            rule_sections.append((lineno, [words] + body))
        elif words[-1] == "{":
            body, i = collect_section(i + 1)
            bucket_sections.append((lineno, [words] + body))
        else:
            raise CompileError("line %d: cannot parse %r"
                               % (lineno, " ".join(words)))

    if 0 not in m.types:
        m.types[0] = "osd"
    type_by_name = {v: k for k, v in m.types.items()}
    names: dict[str, int] = dict(devices)

    # two passes so buckets can reference later-defined child buckets
    parsed = []
    for lineno, section in bucket_sections:
        head = section[0]
        tname, bname = head[0], head[1]
        if tname not in type_by_name:
            raise CompileError("line %d: unknown type %r"
                               % (lineno, tname))
        props = {"alg": "straw2", "hash": "0"}
        items: list[tuple[str, float]] = []
        bid = None
        for words in section[1:]:
            if words[0] == "id":
                bid = int(words[1])
            elif words[0] == "item":
                # None = unspecified: devices default to 1.0, bucket
                # children to their computed subtree weight — an
                # EXPLICIT "weight 1.00" on a bucket child must stick
                weight = None
                if "weight" in words:
                    weight = float(words[words.index("weight") + 1])
                items.append((words[1], weight))
            elif words[0] in ("alg", "hash"):
                props[words[0]] = words[1]
        if bid is None:
            bid = -(len(parsed) + 2)
        names[bname] = bid
        parsed.append((lineno, bname, bid, type_by_name[tname],
                       props, items))

    for lineno, bname, bid, btype, props, items in parsed:
        child_ids, weights = [], []
        for iname, w in items:
            if iname not in names:
                raise CompileError("line %d: unknown item %r"
                                   % (lineno, iname))
            cid = names[iname]
            if w is None:
                if cid < 0 and any(p[2] == cid for p in parsed):
                    pass     # bucket child: subtree weight, filled
                             # after children resolve
                else:
                    w = 1.0  # device default
            child_ids.append(cid)
            weights.append(w)
        parsed_w = []
        for cid, w in zip(child_ids, weights):
            if w is None:
                parsed_w.append(None)
            else:
                parsed_w.append(int(round(w * 0x10000)))
        names[bname] = bid
        alg = ALG_BY_NAME.get(props["alg"])
        if alg is None:
            raise CompileError("line %d: unknown alg %r"
                               % (lineno, props["alg"]))
        # resolve deferred bucket weights (children defined later):
        # process in dependency order by retrying
        deferred = [(bid, alg, btype, bname, child_ids, parsed_w,
                     int(props["hash"]))]
        while deferred:
            progress = False
            still = []
            for ent in deferred:
                bid2, alg2, btype2, bname2, cids, ws, h = ent
                resolved = []
                ok = True
                for cid, w in zip(cids, ws):
                    if w is not None:
                        resolved.append(w)
                    elif cid in m.buckets:
                        resolved.append(m.buckets[cid].weight)
                    else:
                        ok = False
                        break
                if ok:
                    m.add_bucket(alg2, btype2, cids, resolved, id=bid2,
                                 hash=h, name=bname2)
                    progress = True
                else:
                    still.append(ent)
            if still and not progress:
                raise CompileError(
                    "bucket %r references unresolved children"
                    % still[0][3])
            deferred = still

    for lineno, section in rule_sections:
        rname = section[0][1]
        rid = None
        steps: list[tuple[int, int, int]] = []
        for words in section[1:]:
            if words[0] == "id":
                rid = int(words[1])
            elif words[0] == "type":
                pass  # replicated/erasure: advisory in the text format
            elif words[0] in ("min_size", "max_size"):
                pass  # legacy, ignored like current reference versions
            elif words[0] == "step":
                steps.append(_parse_step(lineno, words[1:], names,
                                         type_by_name))
        m.add_rule(steps, id=rid, name=rname)
    return m


def _parse_step(lineno, words, names, type_by_name):
    op = words[0]
    if op == "take":
        if words[1] not in names:
            raise CompileError("line %d: unknown take target %r"
                               % (lineno, words[1]))
        return (TAKE, names[words[1]], 0)
    if op == "emit":
        return (EMIT, 0, 0)
    if op in SET_STEPS:
        return (SET_STEPS[op], int(words[1]), 0)
    if op in ("choose", "chooseleaf"):
        mode = words[1]
        n = int(words[2])
        tname = words[4] if len(words) > 4 and words[3] == "type" else "osd"
        if tname not in type_by_name:
            raise CompileError("line %d: unknown type %r"
                               % (lineno, tname))
        t = type_by_name[tname]
        opcode = {
            ("choose", "firstn"): CHOOSE_FIRSTN,
            ("choose", "indep"): CHOOSE_INDEP,
            ("chooseleaf", "firstn"): CHOOSELEAF_FIRSTN,
            ("chooseleaf", "indep"): CHOOSELEAF_INDEP,
        }.get((op, mode))
        if opcode is None:
            raise CompileError("line %d: bad step %s %s"
                               % (lineno, op, mode))
        return (opcode, n, t)
    raise CompileError("line %d: unknown step %r" % (lineno, op))


def decompile(m: CrushMap) -> str:
    out = ["# begin crush map"]
    t = m.tunables
    for name in TUNABLES:
        out.append("tunable %s %d" % (name, getattr(t, name)))
    out.append("")
    out.append("# devices")
    for d in range(m.max_devices):
        line = "device %d osd.%d" % (d, d)
        if d in m.device_classes:
            line += " class %s" % m.device_classes[d]
        out.append(line)
    out.append("")
    out.append("# types")
    types = dict(m.types) or {0: "osd"}
    if 0 not in types:
        types[0] = "osd"
    for num in sorted(types):
        out.append("type %d %s" % (num, types[num]))
    out.append("")
    out.append("# buckets")
    names = _bucket_names(m)
    # children before parents (the reference emits leaves first)
    emitted = set()

    def emit_bucket(bid: int):
        if bid in emitted:
            return
        b = m.buckets[bid]
        for item in b.items:
            if item < 0:
                emit_bucket(item)
        emitted.add(bid)
        tname = types.get(b.type, "type%d" % b.type)
        out.append("%s %s {" % (tname, names[bid]))
        out.append("\tid %d" % bid)
        out.append("\talg %s" % ALG_NAME[b.alg])
        out.append("\thash %d\t# rjenkins1" % b.hash)
        ws = _item_weights(b)
        for item, w in zip(b.items, ws):
            iname = "osd.%d" % item if item >= 0 else names[item]
            out.append("\titem %s weight %.5f" % (iname, w / 0x10000))
        out.append("}")

    for bid in sorted(m.buckets, reverse=True):
        emit_bucket(bid)
    out.append("")
    out.append("# rules")
    for rid in sorted(m.rules):
        r = m.rules[rid]
        out.append("rule %s {" % (r.name or "rule_%d" % rid))
        out.append("\tid %d" % rid)
        out.append("\ttype replicated")
        for op, a1, a2 in r.steps:
            if op == TAKE:
                out.append("\tstep take %s" % names[a1])
            elif op == EMIT:
                out.append("\tstep emit")
            elif op in SET_STEP_NAME:
                out.append("\tstep %s %d" % (SET_STEP_NAME[op], a1))
            else:
                verb, mode = {
                    CHOOSE_FIRSTN: ("choose", "firstn"),
                    CHOOSE_INDEP: ("choose", "indep"),
                    CHOOSELEAF_FIRSTN: ("chooseleaf", "firstn"),
                    CHOOSELEAF_INDEP: ("chooseleaf", "indep"),
                }[op]
                tname = types.get(a2, "type%d" % a2)
                out.append("\tstep %s %s %d type %s"
                           % (verb, mode, a1, tname))
        out.append("}")
    out.append("")
    out.append("# end crush map")
    return "\n".join(out) + "\n"


def _bucket_names(m: CrushMap) -> dict[int, str]:
    names = {}
    for bid, b in m.buckets.items():
        names[bid] = b.name or "bucket%d" % -bid
    return names


def _item_weights(b) -> list[int]:
    from .crushmap import LIST, STRAW, STRAW2, TREE, UNIFORM
    from .crushmap import _tree_leaf_node

    if b.alg == UNIFORM:
        return [b.item_weight] * len(b.items)
    if b.alg in (LIST, STRAW, STRAW2):
        return list(b.item_weights)
    if b.alg == TREE:
        return [b.node_weights[_tree_leaf_node(i)]
                for i in range(len(b.items))]
    return [0] * len(b.items)
