"""Block device layer (L2 substrate).

The analog of the reference's src/blk/ tier (BlockDevice.h:52
create/open/read/write/flush contract, KernelDevice for file-or-raw
targets): stores address a flat byte device in aligned blocks and never
touch the filesystem namespace themselves.

Two engines:

* FileBlockDevice — a (sparse) regular file driven with os.pread /
  os.pwrite + fdatasync.  This is the KernelDevice role; a raw block
  device path works identically since the API is offset-addressed.
* MemBlockDevice — RAM-backed, for tests and ephemeral OSDs.

Devices are dumb by design: no caching, no journaling — crash
semantics (COW + WAL) live in the store above, exactly as BlueStore
owns them above KernelDevice.
"""

from __future__ import annotations

import os
import threading


class BlockDeviceError(Exception):
    pass


class BlockDevice:
    """Flat, offset-addressed byte device (src/blk/BlockDevice.h)."""

    block_size = 4096

    def open(self) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    @property
    def size(self) -> int:
        raise NotImplementedError

    def extend(self, new_size: int) -> None:
        """Grow the device (thin-provisioned targets)."""
        raise NotImplementedError

    def read(self, offset: int, length: int) -> bytes:
        raise NotImplementedError

    def write(self, offset: int, data: bytes) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        """Durability barrier (fdatasync)."""
        raise NotImplementedError


class FileBlockDevice(BlockDevice):
    """KernelDevice analog over a sparse file / raw device path."""

    def __init__(self, path: str, size: int = 1 << 30):
        self.path = path
        self._size = size
        self._fd: int | None = None
        self._lock = threading.Lock()

    def open(self) -> None:
        self._fd = os.open(self.path, os.O_RDWR | os.O_CREAT, 0o600)
        st = os.fstat(self._fd)
        if st.st_size < self._size:
            os.ftruncate(self._fd, self._size)   # sparse: no real use
        else:
            self._size = st.st_size

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    @property
    def size(self) -> int:
        return self._size

    def extend(self, new_size: int) -> None:
        if new_size <= self._size:
            return
        assert self._fd is not None, "not open"
        os.ftruncate(self._fd, new_size)
        self._size = new_size

    def read(self, offset: int, length: int) -> bytes:
        assert self._fd is not None, "not open"
        with self._lock:
            data = os.pread(self._fd, length, offset)
        if len(data) < length:
            # reads beyond EOF of a sparse file: zero-fill like a disk
            data += b"\x00" * (length - len(data))
        return data

    def write(self, offset: int, data: bytes) -> None:
        assert self._fd is not None, "not open"
        if offset + len(data) > self._size:
            raise BlockDeviceError(
                "write beyond device (%d+%d > %d)"
                % (offset, len(data), self._size))
        with self._lock:
            os.pwrite(self._fd, data, offset)

    def flush(self) -> None:
        assert self._fd is not None, "not open"
        try:
            os.fdatasync(self._fd)
        except AttributeError:          # platforms without fdatasync
            os.fsync(self._fd)


class MemBlockDevice(BlockDevice):
    """RAM device for tests: same contract, no durability."""

    def __init__(self, size: int = 1 << 26):
        self._size = size
        self._buf = bytearray()

    def open(self) -> None:
        if len(self._buf) < self._size:
            self._buf.extend(b"\x00" * (self._size - len(self._buf)))

    def close(self) -> None:
        pass

    @property
    def size(self) -> int:
        return self._size

    def extend(self, new_size: int) -> None:
        if new_size > self._size:
            self._buf.extend(b"\x00" * (new_size - self._size))
            self._size = new_size

    def read(self, offset: int, length: int) -> bytes:
        return bytes(self._buf[offset:offset + length])

    def write(self, offset: int, data: bytes) -> None:
        if offset + len(data) > self._size:
            raise BlockDeviceError("write beyond device")
        self._buf[offset:offset + len(data)] = data

    def flush(self) -> None:
        pass
