"""Free-space allocators for the extent store.

Role of src/os/bluestore/Allocator.h + AvlAllocator.cc /
BitmapAllocator.cc: hand out aligned disk extents, take back released
ones, and survive being rebuilt from the store's metadata at mount
(the modern reference rebuilds the allocation map from onodes rather
than persisting a freelist; ExtentStore does the same, so allocators
here are purely in-RAM).

* ExtentAllocator — interval-set allocator: free space as merged
  (offset, length) runs in sorted order, first-fit allocation with a
  rotating hint to spread wear/fragmentation (AvlAllocator's behavior;
  the balanced tree is a Python sorted list + bisect — same O(log n)
  search, and mutation cost is fine at the fleet sizes one OSD holds).
* BitmapAllocator — one bit per alloc unit over a bytearray; dumb,
  dense, O(n) worst-case scan kept as the cross-check engine (its role
  in the reference test suite, store_test.cc's allocator grinds).

Both allocate whole alloc units (the store's block size); callers get
a list of (offset, length) extents summing to the request.
"""

from __future__ import annotations

import bisect


class AllocError(Exception):
    """ENOSPC analog."""


class Allocator:
    def init_add_free(self, offset: int, length: int) -> None:
        raise NotImplementedError

    def init_rm_free(self, offset: int, length: int) -> None:
        raise NotImplementedError

    def allocate(self, want: int) -> list[tuple[int, int]]:
        """Aligned extents totalling exactly ``want`` bytes (may be
        fragmented).  Raises AllocError when free space is short."""
        raise NotImplementedError

    def release(self, extents) -> None:
        for off, ln in extents:
            self.init_add_free(off, ln)

    @property
    def free_bytes(self) -> int:
        raise NotImplementedError


class ExtentAllocator(Allocator):
    """Interval-set first-fit allocator (AvlAllocator role)."""

    def __init__(self, alloc_unit: int = 4096):
        self.alloc_unit = alloc_unit
        self._offs: list[int] = []      # sorted run starts
        self._lens: dict[int, int] = {}  # start -> run length
        self._free = 0
        self._hint = 0                  # next-fit rotation point

    @property
    def free_bytes(self) -> int:
        return self._free

    def init_add_free(self, offset: int, length: int) -> None:
        assert offset % self.alloc_unit == 0
        assert length % self.alloc_unit == 0
        if length == 0:
            return
        i = bisect.bisect_left(self._offs, offset)
        # coalesce with predecessor / successor runs
        if i > 0:
            p = self._offs[i - 1]
            if p + self._lens[p] > offset:
                raise AllocError("double free at %d" % offset)
            if p + self._lens[p] == offset:
                offset = p
                length += self._lens[p]
                i -= 1
                del self._lens[p]
                del self._offs[i]
        if i < len(self._offs):
            n = self._offs[i]
            if offset + length > n:
                raise AllocError("double free at %d" % offset)
            if offset + length == n:
                length += self._lens[n]
                del self._lens[n]
                del self._offs[i]
        self._offs.insert(i, offset)
        self._lens[offset] = length
        self._free += length

    def init_rm_free(self, offset: int, length: int) -> None:
        """Carve [offset, offset+length) out of the free set (mount
        replay marking blocks an onode references)."""
        if length == 0:
            return
        i = bisect.bisect_right(self._offs, offset) - 1
        if i < 0:
            raise AllocError("rm_free: %d not free" % offset)
        start = self._offs[i]
        ln = self._lens[start]
        if offset + length > start + ln:
            raise AllocError("rm_free: %d+%d not free" % (offset, length))
        del self._offs[i]
        del self._lens[start]
        self._free -= ln
        if start < offset:
            self.init_add_free(start, offset - start)
        if offset + length < start + ln:
            self.init_add_free(offset + length,
                               start + ln - offset - length)

    def allocate(self, want: int) -> list[tuple[int, int]]:
        assert want % self.alloc_unit == 0
        if want > self._free:
            raise AllocError("ENOSPC: want %d free %d"
                             % (want, self._free))
        out: list[tuple[int, int]] = []
        remaining = want
        # next-fit: start at the hint, wrap once
        start_i = bisect.bisect_left(self._offs, self._hint)
        order = list(range(start_i, len(self._offs))) + \
            list(range(0, start_i))
        taken: list[tuple[int, int]] = []
        for i in order:
            off = self._offs[i]
            ln = self._lens[off]
            take = min(ln, remaining)
            taken.append((off, take))
            remaining -= take
            if remaining == 0:
                break
        assert remaining == 0
        for off, take in taken:
            self.init_rm_free(off, take)
            out.append((off, take))
        self._hint = out[-1][0] + out[-1][1]
        return out


class BitmapAllocator(Allocator):
    """One bit per alloc unit; linear next-fit scan."""

    def __init__(self, alloc_unit: int = 4096, size: int = 0):
        self.alloc_unit = alloc_unit
        self._bits = bytearray((size + alloc_unit - 1) // alloc_unit)
        self._free = 0
        self._hint = 0

    @property
    def free_bytes(self) -> int:
        return self._free

    def _grow(self, units: int) -> None:
        if units > len(self._bits):
            self._bits.extend(b"\x00" * (units - len(self._bits)))

    def init_add_free(self, offset: int, length: int) -> None:
        u0 = offset // self.alloc_unit
        n = length // self.alloc_unit
        self._grow(u0 + n)
        for u in range(u0, u0 + n):
            if self._bits[u]:
                raise AllocError("double free at unit %d" % u)
            self._bits[u] = 1
        self._free += n * self.alloc_unit

    def init_rm_free(self, offset: int, length: int) -> None:
        u0 = offset // self.alloc_unit
        n = length // self.alloc_unit
        for u in range(u0, u0 + n):
            if u >= len(self._bits) or not self._bits[u]:
                raise AllocError("rm_free: unit %d not free" % u)
            self._bits[u] = 0
        self._free -= n * self.alloc_unit

    def allocate(self, want: int) -> list[tuple[int, int]]:
        assert want % self.alloc_unit == 0
        n = want // self.alloc_unit
        if want > self._free:
            raise AllocError("ENOSPC: want %d free %d"
                             % (want, self._free))
        out: list[tuple[int, int]] = []
        got = 0
        total = len(self._bits)
        i = self._hint % max(1, total)
        run_start = -1
        scanned = 0
        while got < n and scanned <= total:
            free = i < total and self._bits[i]
            if free:
                if run_start < 0:
                    run_start = i
                got += 1
            if (not free or got == n) and run_start >= 0:
                run_len = (i - run_start) + (1 if free else 0)
                out.append((run_start * self.alloc_unit,
                            run_len * self.alloc_unit))
                run_start = -1
            i += 1
            scanned += 1
            if i >= total:
                i = 0
                if run_start >= 0:      # run cannot wrap the edge
                    out.append((run_start * self.alloc_unit,
                                (total - run_start) * self.alloc_unit))
                    run_start = -1
        assert got == n
        for off, ln in out:
            self.init_rm_free(off, ln)
        self._hint = (out[-1][0] + out[-1][1]) // self.alloc_unit
        return out
