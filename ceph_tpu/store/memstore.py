"""MemStore: complete in-RAM ObjectStore (the test backend).

Mirrors src/os/memstore/MemStore.cc's role: OSD logic runs against it
without disks; transactions apply atomically under one lock and
callbacks fire synchronously (commit == apply for RAM).
"""

from __future__ import annotations

import threading
from typing import Callable

from .objectstore import (
    OP_CLONE,
    OP_CLONERANGE2,
    OP_COLL_MOVE_RENAME,
    OP_CREATE,
    OP_MKCOLL,
    OP_NOP,
    OP_OMAP_CLEAR,
    OP_OMAP_RMKEYRANGE,
    OP_OMAP_RMKEYS,
    OP_OMAP_SETHEADER,
    OP_OMAP_SETKEYS,
    OP_REMOVE,
    OP_RMATTR,
    OP_RMATTRS,
    OP_RMCOLL,
    OP_SETATTR,
    OP_SETATTRS,
    OP_SPLIT_COLLECTION2,
    OP_TOUCH,
    OP_TRUNCATE,
    OP_TRY_RENAME,
    OP_WRITE,
    OP_ZERO,
    AlreadyExists,
    NotFound,
    ObjectStore,
    StoreError,
    Transaction,
    coll_t,
    hobject_t,
)


class _Object:
    __slots__ = ("data", "xattrs", "omap", "omap_header")

    def __init__(self):
        self.data = bytearray()
        self.xattrs: dict[str, bytes] = {}
        self.omap: dict[str, bytes] = {}
        self.omap_header = b""

    def clone(self) -> "_Object":
        o = _Object()
        o.data = bytearray(self.data)
        o.xattrs = dict(self.xattrs)
        o.omap = dict(self.omap)
        o.omap_header = self.omap_header
        return o

    def write(self, offset: int, data: bytes) -> None:
        end = offset + len(data)
        if len(self.data) < end:
            self.data.extend(b"\x00" * (end - len(self.data)))
        self.data[offset:end] = data


class _Collection:
    __slots__ = ("bits", "objects")

    def __init__(self, bits: int = 0):
        self.bits = bits
        self.objects: dict[hobject_t, _Object] = {}


class MemStore(ObjectStore):
    def __init__(self, path: str = "", device_bytes: int = 1 << 30):
        super().__init__(path)
        self._colls: dict[coll_t, _Collection] = {}
        self._lock = threading.RLock()
        self._mounted = False
        # nominal "device" size the statfs axis reports against (RAM
        # has no real capacity edge; df still needs a denominator)
        self.device_bytes = int(device_bytes)

    # -- lifecycle ---------------------------------------------------------

    def mkfs(self) -> None:
        self._colls = {}

    def mount(self) -> None:
        self._mounted = True

    def umount(self) -> None:
        self._mounted = False

    # -- transaction application ------------------------------------------

    def queue_transactions(
        self, txs: list[Transaction],
        on_applied: Callable[[], None] | None = None,
        on_commit: Callable[[], None] | None = None,
    ) -> None:
        with self._lock:
            for tx in txs:
                self._apply(tx)
        if on_applied:
            on_applied()
        if on_commit:
            on_commit()

    def _coll(self, cid: coll_t) -> _Collection:
        c = self._colls.get(cid)
        if c is None:
            raise NotFound("collection %s" % cid)
        return c

    def _obj(self, cid: coll_t, oid: hobject_t,
             create: bool = False) -> _Object:
        c = self._coll(cid)
        o = c.objects.get(oid)
        if o is None:
            if not create:
                raise NotFound("object %s/%s" % (cid, oid))
            o = _Object()
            c.objects[oid] = o
        return o

    def _apply(self, tx: Transaction) -> None:
        for op in tx.ops:
            self._apply_op(op)

    def _apply_op(self, op: tuple) -> None:
            code = op[0]
            if code == OP_NOP:
                pass
            elif code == OP_CREATE:
                _, cid, oid = op
                c = self._coll(cid)
                if oid in c.objects:
                    raise AlreadyExists("object %s/%s" % (cid, oid))
                c.objects[oid] = _Object()
            elif code == OP_TOUCH:
                _, cid, oid = op
                self._obj(cid, oid, create=True)
            elif code == OP_WRITE:
                _, cid, oid, offset, data = op
                self._obj(cid, oid, create=True).write(offset, data)
            elif code == OP_ZERO:
                _, cid, oid, offset, length = op
                self._obj(cid, oid, create=True).write(
                    offset, b"\x00" * length)
            elif code == OP_TRUNCATE:
                _, cid, oid, length = op
                o = self._obj(cid, oid)
                if len(o.data) > length:
                    del o.data[length:]
                else:
                    o.data.extend(b"\x00" * (length - len(o.data)))
            elif code == OP_REMOVE:
                # idempotent: a replica may apply a replicated delete
                # for an object it never held (sparse images, races
                # with recovery) — the primary existence-gates the
                # client-visible ENOENT
                _, cid, oid = op
                self._coll(cid).objects.pop(oid, None)
            elif code == OP_SETATTR:
                _, cid, oid, name, val = op
                self._obj(cid, oid, create=True).xattrs[name] = val
            elif code == OP_SETATTRS:
                _, cid, oid, attrs = op
                self._obj(cid, oid, create=True).xattrs.update(attrs)
            elif code == OP_RMATTR:
                _, cid, oid, name = op
                self._obj(cid, oid).xattrs.pop(name, None)
            elif code == OP_RMATTRS:
                _, cid, oid = op
                self._obj(cid, oid).xattrs.clear()
            elif code == OP_CLONE:
                _, cid, oid, newoid = op
                c = self._coll(cid)
                c.objects[newoid] = self._obj(cid, oid).clone()
            elif code == OP_CLONERANGE2:
                _, cid, oid, newoid, srcoff, length, dstoff = op
                src = self._obj(cid, oid)
                dst = self._obj(cid, newoid, create=True)
                dst.write(dstoff, bytes(src.data[srcoff:srcoff + length]))
            elif code == OP_OMAP_CLEAR:
                _, cid, oid = op
                o = self._obj(cid, oid)
                o.omap.clear()
            elif code == OP_OMAP_SETKEYS:
                _, cid, oid, kv = op
                self._obj(cid, oid, create=True).omap.update(kv)
            elif code == OP_OMAP_RMKEYS:
                _, cid, oid, keys = op
                o = self._obj(cid, oid)
                for k in keys:
                    o.omap.pop(k, None)
            elif code == OP_OMAP_RMKEYRANGE:
                _, cid, oid, first, last = op
                o = self._obj(cid, oid)
                for k in [k for k in o.omap if first <= k < last]:
                    del o.omap[k]
            elif code == OP_OMAP_SETHEADER:
                _, cid, oid, header = op
                self._obj(cid, oid, create=True).omap_header = header
            elif code == OP_MKCOLL:
                _, cid, bits = op
                if cid in self._colls:
                    raise AlreadyExists("collection %s" % cid)
                self._colls[cid] = _Collection(bits)
            elif code == OP_RMCOLL:
                _, cid = op
                c = self._colls.pop(cid, None)
                if c is None:
                    raise NotFound("collection %s" % cid)
            elif code == OP_SPLIT_COLLECTION2:
                _, cid, bits, rem, dest = op
                src = self._coll(cid)
                dst = self._coll(dest)
                mask = (1 << bits) - 1
                moving = [oid for oid in src.objects
                          if oid.hash & mask == rem]
                for oid in moving:
                    dst.objects[oid] = src.objects.pop(oid)
                src.bits = bits
                dst.bits = bits
            elif code == OP_COLL_MOVE_RENAME:
                _, oldcid, oldoid, newcid, newoid = op
                src = self._coll(oldcid)
                o = src.objects.pop(oldoid, None)
                if o is None:
                    raise NotFound("object %s/%s" % (oldcid, oldoid))
                self._coll(newcid).objects[newoid] = o
            elif code == OP_TRY_RENAME:
                _, cid, oldoid, newoid = op
                c = self._coll(cid)
                o = c.objects.pop(oldoid, None)
                if o is not None:
                    c.objects[newoid] = o
            else:
                raise StoreError("unknown op %r" % (code,))

    # -- statfs ------------------------------------------------------------

    def statfs(self) -> dict:
        """Bytes actually held (data + xattrs + omap) against the
        nominal device size."""
        used = 0
        with self._lock:
            for c in self._colls.values():
                for o in c.objects.values():
                    used += len(o.data) + len(o.omap_header)
                    for k, v in o.xattrs.items():
                        used += len(k) + len(v)
                    for k, v in o.omap.items():
                        used += len(k) + len(v)
        total = max(self.device_bytes, used)
        return {"total": total, "used": used,
                "available": total - used}

    # -- reads -------------------------------------------------------------

    def exists(self, cid: coll_t, oid: hobject_t) -> bool:
        with self._lock:
            c = self._colls.get(cid)
            return c is not None and oid in c.objects

    def stat(self, cid: coll_t, oid: hobject_t) -> int:
        with self._lock:
            return len(self._obj(cid, oid).data)

    def read(self, cid: coll_t, oid: hobject_t, offset: int = 0,
             length: int = -1) -> bytes:
        with self._lock:
            o = self._obj(cid, oid)
            if length < 0:
                return bytes(o.data[offset:])
            return bytes(o.data[offset:offset + length])

    def getattr(self, cid: coll_t, oid: hobject_t, name: str) -> bytes:
        with self._lock:
            try:
                return self._obj(cid, oid).xattrs[name]
            except KeyError:
                raise NotFound("xattr %s" % name) from None

    def getattrs(self, cid: coll_t, oid: hobject_t) -> dict:
        with self._lock:
            return dict(self._obj(cid, oid).xattrs)

    def omap_get_header(self, cid: coll_t, oid: hobject_t) -> bytes:
        with self._lock:
            return self._obj(cid, oid).omap_header

    def omap_get(self, cid: coll_t, oid: hobject_t) -> dict:
        with self._lock:
            return dict(sorted(self._obj(cid, oid).omap.items()))

    def omap_get_values(self, cid: coll_t, oid: hobject_t, keys) -> dict:
        with self._lock:
            omap = self._obj(cid, oid).omap
            return {k: omap[k] for k in keys if k in omap}

    # -- collections -------------------------------------------------------

    def list_collections(self) -> list[coll_t]:
        with self._lock:
            return sorted(self._colls, key=lambda c: c.name)

    def collection_exists(self, cid: coll_t) -> bool:
        with self._lock:
            return cid in self._colls

    def collection_empty(self, cid: coll_t) -> bool:
        with self._lock:
            return not self._coll(cid).objects

    def collection_bits(self, cid: coll_t) -> int:
        with self._lock:
            return self._coll(cid).bits

    def collection_list(self, cid: coll_t, start: hobject_t | None = None,
                        end: hobject_t | None = None,
                        max_count: int = -1) -> list[hobject_t]:
        with self._lock:
            objs = sorted(self._coll(cid).objects,
                          key=lambda o: o.sort_key())
        if start is not None:
            sk = start.sort_key()
            objs = [o for o in objs if o.sort_key() >= sk]
        if end is not None:
            ek = end.sort_key()
            objs = [o for o in objs if o.sort_key() < ek]
        if max_count >= 0:
            objs = objs[:max_count]
        return objs
