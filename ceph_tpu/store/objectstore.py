"""Transactional object store abstraction (L2).

Re-derivation of the reference's ObjectStore/Transaction contract
(src/os/ObjectStore.h, src/os/Transaction.h:110-155): collections
(one per PG plus 'meta') hold objects with three facets — byte data,
xattrs, and a sorted omap — and all mutation flows through
queue_transactions() applying a serialized op list atomically with
on_applied/on_commit notifications.

Objects are identified by ghobject_t analogs sorted in bitwise-reversed
hash order (the reference's hobject_t bitwise sort), which is what makes
collection_list() a stable scan for backfill/scrub.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Callable, Iterable

from ..ops.crush.hashes import str_hash_rjenkins


class StoreError(Exception):
    pass


class NotFound(StoreError):
    """ENOENT analog."""


class AlreadyExists(StoreError):
    """EEXIST analog."""


def _rev32(x: int) -> int:
    """Bit-reverse a 32-bit value (hobject_t::get_bitwise_key)."""
    x = ((x & 0x55555555) << 1) | ((x >> 1) & 0x55555555)
    x = ((x & 0x33333333) << 2) | ((x >> 2) & 0x33333333)
    x = ((x & 0x0F0F0F0F) << 4) | ((x >> 4) & 0x0F0F0F0F)
    x = ((x & 0x00FF00FF) << 8) | ((x >> 8) & 0x00FF00FF)
    return ((x << 16) | (x >> 16)) & 0xFFFFFFFF


NOSNAP = 0xFFFFFFFFFFFFFFFE  # CEPH_NOSNAP
SNAPDIR = 0xFFFFFFFFFFFFFFFF


@dataclass(frozen=True)
class hobject_t:
    """Object id: (pool, namespace, name, key, snap) + cached ps hash.
    Sort order is bitwise (reversed-hash-major), as in hobject_t's
    bitwise comparator."""

    name: str
    pool: int = 0
    nspace: str = ""
    key: str = ""
    snap: int = NOSNAP
    hash: int = -1  # computed from key-or-name when < 0

    def __post_init__(self):
        if self.hash < 0:
            h = str_hash_rjenkins((self.key or self.name).encode())
            object.__setattr__(self, "hash", h)

    def sort_key(self) -> tuple:
        return (self.pool, _rev32(self.hash), self.nspace, self.key,
                self.name, self.snap)

    def __lt__(self, other: "hobject_t") -> bool:
        return self.sort_key() < other.sort_key()

    def __str__(self) -> str:
        return "%d/%s/%s/%d" % (self.pool, self.nspace or "-",
                                self.name, self.snap)


@dataclass(frozen=True)
class coll_t:
    """Collection id: a PG ('<pool>.<ps-hex>') or 'meta'."""

    name: str = "meta"

    def __str__(self) -> str:
        return self.name

    @staticmethod
    def pg(pool: int, ps: int) -> "coll_t":
        return coll_t("%d.%x" % (pool, ps))

    def is_pg(self) -> bool:
        return self.name != "meta"


# Transaction op codes (subset of Transaction.h:110-155 that the data
# path and PG lifecycle use; same names for greppability)
OP_NOP = 0
OP_CREATE = 7
OP_TOUCH = 9
OP_WRITE = 10
OP_ZERO = 11
OP_TRUNCATE = 12
OP_REMOVE = 13
OP_SETATTR = 14
OP_SETATTRS = 15
OP_RMATTR = 16
OP_CLONE = 17
OP_CLONERANGE2 = 30
OP_MKCOLL = 20
OP_RMCOLL = 21
OP_RMATTRS = 28
OP_OMAP_CLEAR = 31
OP_OMAP_SETKEYS = 32
OP_OMAP_RMKEYS = 33
OP_OMAP_SETHEADER = 34
OP_SPLIT_COLLECTION2 = 36
OP_OMAP_RMKEYRANGE = 37
OP_COLL_MOVE_RENAME = 38
OP_TRY_RENAME = 41


class Transaction:
    """An ordered op list applied atomically (ObjectStore::Transaction).

    Builder methods append (op, args...) tuples; stores interpret them
    in order.  Transactions are value objects — they carry no store
    references and can be encoded for a WAL or wire transfer.
    """

    def __init__(self):
        self.ops: list[tuple] = []

    def __len__(self) -> int:
        return len(self.ops)

    def empty(self) -> bool:
        return not self.ops

    def append(self, other: "Transaction") -> None:
        self.ops.extend(other.ops)

    # -- wire form (Transaction::encode/decode analog) ---------------------

    def to_wire(self) -> list:
        """denc-encodable op list: coll_t -> name str, hobject_t ->
        [name, pool, nspace, key, snap] list; other args pass through."""
        out = []
        for op in self.ops:
            row = []
            for a in op:
                if isinstance(a, coll_t):
                    row.append(("C", a.name))
                elif isinstance(a, hobject_t):
                    row.append(("H", a.name, a.pool, a.nspace, a.key,
                                a.snap))
                else:
                    row.append(a)
            out.append(row)
        return out

    @classmethod
    def from_wire(cls, rows: list) -> "Transaction":
        t = cls()
        for row in rows:
            op = []
            for a in row:
                if isinstance(a, tuple) and a and a[0] == "C":
                    op.append(coll_t(a[1]))
                elif isinstance(a, tuple) and a and a[0] == "H":
                    op.append(hobject_t(a[1], pool=a[2], nspace=a[3],
                                        key=a[4], snap=a[5]))
                else:
                    op.append(a)
            t.ops.append(tuple(op))
        return t

    # -- object data -------------------------------------------------------

    def nop(self):
        self.ops.append((OP_NOP,))

    def create(self, cid: coll_t, oid: hobject_t):
        self.ops.append((OP_CREATE, cid, oid))

    def touch(self, cid: coll_t, oid: hobject_t):
        self.ops.append((OP_TOUCH, cid, oid))

    def write(self, cid: coll_t, oid: hobject_t, offset: int,
              length: int, data: bytes):
        assert length == len(data)
        self.ops.append((OP_WRITE, cid, oid, offset, bytes(data)))

    def zero(self, cid: coll_t, oid: hobject_t, offset: int, length: int):
        self.ops.append((OP_ZERO, cid, oid, offset, length))

    def truncate(self, cid: coll_t, oid: hobject_t, length: int):
        self.ops.append((OP_TRUNCATE, cid, oid, length))

    def remove(self, cid: coll_t, oid: hobject_t):
        self.ops.append((OP_REMOVE, cid, oid))

    def clone(self, cid: coll_t, oid: hobject_t, newoid: hobject_t):
        self.ops.append((OP_CLONE, cid, oid, newoid))

    def clone_range(self, cid: coll_t, oid: hobject_t, newoid: hobject_t,
                    srcoff: int, length: int, dstoff: int):
        self.ops.append((OP_CLONERANGE2, cid, oid, newoid, srcoff,
                         length, dstoff))

    # -- xattrs ------------------------------------------------------------

    def setattr(self, cid: coll_t, oid: hobject_t, name: str, val: bytes):
        self.ops.append((OP_SETATTR, cid, oid, name, bytes(val)))

    def setattrs(self, cid: coll_t, oid: hobject_t, attrs: dict):
        self.ops.append((OP_SETATTRS, cid, oid,
                         {k: bytes(v) for k, v in attrs.items()}))

    def rmattr(self, cid: coll_t, oid: hobject_t, name: str):
        self.ops.append((OP_RMATTR, cid, oid, name))

    def rmattrs(self, cid: coll_t, oid: hobject_t):
        self.ops.append((OP_RMATTRS, cid, oid))

    # -- omap --------------------------------------------------------------

    def omap_clear(self, cid: coll_t, oid: hobject_t):
        self.ops.append((OP_OMAP_CLEAR, cid, oid))

    def omap_setkeys(self, cid: coll_t, oid: hobject_t, kv: dict):
        # keys normalize to bytes here so MemStore and KStore agree
        # across remounts (a str key would silently change type after
        # a KStore reload)
        self.ops.append((OP_OMAP_SETKEYS, cid, oid,
                         {(k if isinstance(k, bytes) else k.encode()):
                          bytes(v) for k, v in kv.items()}))

    def omap_rmkeys(self, cid: coll_t, oid: hobject_t,
                    keys: Iterable):
        self.ops.append((OP_OMAP_RMKEYS, cid, oid,
                         [k if isinstance(k, bytes) else k.encode()
                          for k in keys]))

    def omap_rmkeyrange(self, cid: coll_t, oid: hobject_t,
                        first: str, last: str):
        self.ops.append((OP_OMAP_RMKEYRANGE, cid, oid, first, last))

    def omap_setheader(self, cid: coll_t, oid: hobject_t, header: bytes):
        self.ops.append((OP_OMAP_SETHEADER, cid, oid, bytes(header)))

    # -- collections -------------------------------------------------------

    def create_collection(self, cid: coll_t, bits: int = 0):
        self.ops.append((OP_MKCOLL, cid, bits))

    def remove_collection(self, cid: coll_t):
        self.ops.append((OP_RMCOLL, cid))

    def split_collection(self, cid: coll_t, bits: int, rem: int,
                         dest: coll_t):
        self.ops.append((OP_SPLIT_COLLECTION2, cid, bits, rem, dest))

    def collection_move_rename(self, oldcid: coll_t, oldoid: hobject_t,
                               newcid: coll_t, newoid: hobject_t):
        self.ops.append((OP_COLL_MOVE_RENAME, oldcid, oldoid, newcid,
                         newoid))

    def try_rename(self, cid: coll_t, oldoid: hobject_t,
                   newoid: hobject_t):
        self.ops.append((OP_TRY_RENAME, cid, oldoid, newoid))


class ObjectStore:
    """The store contract every backend implements
    (src/os/ObjectStore.h: mount/umount, queue_transactions, reads)."""

    def __init__(self, path: str = ""):
        self.path = path

    # lifecycle
    def mkfs(self) -> None:
        raise NotImplementedError

    def mount(self) -> None:
        raise NotImplementedError

    def umount(self) -> None:
        raise NotImplementedError

    # writes
    def queue_transactions(
        self, txs: list[Transaction],
        on_applied: Callable[[], None] | None = None,
        on_commit: Callable[[], None] | None = None,
    ) -> None:
        raise NotImplementedError

    def apply_transaction(self, tx: Transaction) -> None:
        self.queue_transactions([tx])

    def statfs(self) -> dict:
        """Raw-capacity view {"total", "used", "available"} in bytes
        (store_statfs_t): the per-OSD axis `df` renders and MMgrReport
        ships.  RAM engines report against a nominal device size;
        ExtentStore reports its real block device + allocator state."""
        raise NotImplementedError

    # reads
    def exists(self, cid: coll_t, oid: hobject_t) -> bool:
        raise NotImplementedError

    def stat(self, cid: coll_t, oid: hobject_t) -> int:
        """Returns object size in bytes (NotFound if absent)."""
        raise NotImplementedError

    def read(self, cid: coll_t, oid: hobject_t, offset: int = 0,
             length: int = -1) -> bytes:
        raise NotImplementedError

    def getattr(self, cid: coll_t, oid: hobject_t, name: str) -> bytes:
        raise NotImplementedError

    def getattrs(self, cid: coll_t, oid: hobject_t) -> dict:
        raise NotImplementedError

    def omap_get_header(self, cid: coll_t, oid: hobject_t) -> bytes:
        raise NotImplementedError

    def omap_get(self, cid: coll_t, oid: hobject_t) -> dict:
        raise NotImplementedError

    def omap_get_values(self, cid: coll_t, oid: hobject_t,
                        keys: Iterable[str]) -> dict:
        raise NotImplementedError

    # collections
    def list_collections(self) -> list[coll_t]:
        raise NotImplementedError

    def collection_exists(self, cid: coll_t) -> bool:
        raise NotImplementedError

    def collection_empty(self, cid: coll_t) -> bool:
        raise NotImplementedError

    def collection_bits(self, cid: coll_t) -> int:
        raise NotImplementedError

    def collection_list(self, cid: coll_t, start: hobject_t | None = None,
                        end: hobject_t | None = None,
                        max_count: int = -1) -> list[hobject_t]:
        """Objects in bitwise sort order, [start, end), up to
        max_count."""
        raise NotImplementedError


def pack_u64(v: int) -> bytes:
    return struct.pack(">Q", v)
