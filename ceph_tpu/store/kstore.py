"""KStore: durable ObjectStore over an ordered KeyValueDB.

The reference's KStore (src/os/kstore/) keeps everything — object
data, xattrs, omap, collection records — in RocksDB; this build keeps
the same design over the KeyValueDB abstraction (SQLite engine by
default, MemKV for tests).  One KV write batch per transaction gives
atomic commit; key encoding preserves hobject bitwise sort order so
object enumeration is a single range scan.

Reads are served from an in-RAM MemStore mirror rebuilt on mount (the
mirror IS the authoritative in-memory state; the KV holds its durable
image).  Writes apply to the mirror first, then the touched objects'
full KV images are rewritten in one atomic batch — simple, correct,
and sufficient for the PG-scale objects the OSD slice handles; a
BlueStore-class extent store refines this later.

Key layout (facet byte 'a' sorts first so a scan meets each object's
identity record before its facets):
  b'C' + 0x00 + cid-esc                      -> denc((cid, bits))
  b'O' + 0x00 + cid-esc + 0x00 + okey + 0x00 + facet
     facet b'a'            -> denc((cid, oid-tuple))
     facet b'd'            -> data blob
     facet b'h'            -> omap header
     facet b'x' + name-esc -> xattr value
     facet b'm' + key-esc  -> omap value
"""

from __future__ import annotations

import struct
from typing import Callable

from ..utils import denc
from .memstore import MemStore, _Collection, _Object
from .kv import KeyValueDB, SQLiteKV
from .objectstore import (
    OP_CLONE,
    OP_CLONERANGE2,
    OP_COLL_MOVE_RENAME,
    OP_MKCOLL,
    OP_RMCOLL,
    OP_SPLIT_COLLECTION2,
    OP_TRY_RENAME,
    ObjectStore,
    Transaction,
    coll_t,
    hobject_t,
    _rev32,
)


def _esc(b: bytes) -> bytes:
    """0x00-free escaping that preserves byte order."""
    return b.replace(b"\x00", b"\x00\xff")


def _unesc(b: bytes) -> bytes:
    return b.replace(b"\x00\xff", b"\x00")


def _okey(oid: hobject_t) -> bytes:
    return b"".join((
        struct.pack(">Q", oid.pool + (1 << 63)),
        struct.pack(">I", _rev32(oid.hash)),
        _esc(oid.nspace.encode()), b"\x00\x01",
        _esc(oid.key.encode()), b"\x00\x01",
        _esc(oid.name.encode()), b"\x00\x01",
        struct.pack(">Q", oid.snap),
    ))


def _oid_tuple(oid: hobject_t) -> tuple:
    return (oid.name, oid.pool, oid.nspace, oid.key, oid.snap, oid.hash)


def _oid_from_tuple(t) -> hobject_t:
    name, pool, nspace, key, snap, h = t
    return hobject_t(name=name, pool=pool, nspace=nspace, key=key,
                     snap=snap, hash=h)


_CPREF = b"C\x00"
_OPREF = b"O\x00"


def _ckey(cid: coll_t) -> bytes:
    return _CPREF + _esc(str(cid).encode())


def _obase(cid: coll_t, oid: hobject_t) -> bytes:
    return (_OPREF + _esc(str(cid).encode()) + b"\x00" + _okey(oid)
            + b"\x00")


def _ocollpref(cid: coll_t) -> bytes:
    return _OPREF + _esc(str(cid).encode()) + b"\x00"


class KStore(ObjectStore):
    def __init__(self, path: str, db: KeyValueDB | None = None):
        super().__init__(path)
        self.db = db if db is not None else SQLiteKV(path)
        self._mem = MemStore()

    # -- lifecycle ---------------------------------------------------------

    def mkfs(self) -> None:
        self.db.open()
        self.db.close()

    def mount(self) -> None:
        self.db.open()
        self._mem = MemStore()
        self._mem.mount()
        self._load()

    def umount(self) -> None:
        self.db.close()
        self._mem.umount()

    def statfs(self) -> dict:
        """The in-RAM image mirrors the KV contents exactly, so its
        usage accounting is this store's too."""
        return self._mem.statfs()

    def _load(self) -> None:
        for _k, v in self.db.iterate(_CPREF, _CPREF + b"\xff"):
            cidname, bits = denc.decode(v)
            self._mem._colls[coll_t(cidname)] = _Collection(bits)
        base: bytes | None = None
        obj: _Object | None = None
        for k, v in self.db.iterate(_OPREF, _OPREF + b"\xff"):
            if base is not None and k.startswith(base):
                facet = bytes(k[len(base):])
                if facet == b"d":
                    obj.data = bytearray(v)
                elif facet == b"h":
                    obj.omap_header = v
                elif facet[:1] == b"x":
                    obj.xattrs[_unesc(facet[1:]).decode()] = v
                elif facet[:1] == b"m":
                    obj.omap[_unesc(facet[1:])] = v
                continue
            if not k.endswith(b"\x00a"):
                raise ValueError("kstore: orphan facet key %r" % (k,))
            base = bytes(k[:-1])
            cidname, oid_t = denc.decode(v)
            obj = _Object()
            self._mem._colls[coll_t(cidname)].objects[
                _oid_from_tuple(oid_t)] = obj

    # -- writes ------------------------------------------------------------

    def queue_transactions(
        self, txs: list[Transaction],
        on_applied: Callable[[], None] | None = None,
        on_commit: Callable[[], None] | None = None,
    ) -> None:
        dirty: set[tuple[coll_t, hobject_t]] = set()
        dirty_colls: set[coll_t] = set()
        removed_colls: set[coll_t] = set()
        with self._mem._lock:
            for tx in txs:
                # note THEN apply per op, so a split sees exactly the
                # membership earlier ops in the same tx created
                for op in tx.ops:
                    self._note(op, dirty, dirty_colls, removed_colls)
                    self._mem._apply_op(op)
            batch = self.db.get_transaction()
            for cid in removed_colls:
                batch.rmkey(_ckey(cid))
                pref = _ocollpref(cid)
                batch.rm_range(pref, pref + b"\xff")
            for cid in dirty_colls:
                c = self._mem._colls.get(cid)
                if c is not None:
                    batch.set(_ckey(cid), denc.encode((str(cid), c.bits)))
            for cid, oid in sorted(
                    dirty, key=lambda t: (str(t[0]), t[1].sort_key())):
                self._persist(batch, cid, oid)
        if on_applied:
            on_applied()
        self.db.submit_transaction(batch)
        if on_commit:
            on_commit()

    def _note(self, op, dirty, dirty_colls, removed_colls) -> None:
        """Record which objects/collections an op touches (before it is
        applied, so splits can enumerate the pre-move membership)."""
        code = op[0]
        if code == OP_MKCOLL:
            dirty_colls.add(op[1])
            removed_colls.discard(op[1])
        elif code == OP_RMCOLL:
            removed_colls.add(op[1])
            dirty_colls.discard(op[1])
        elif code == OP_SPLIT_COLLECTION2:
            _, cid, bits, rem, dest = op
            c = self._mem._colls.get(cid)
            if c is not None:
                mask = (1 << bits) - 1
                for oid in c.objects:
                    if oid.hash & mask == rem:
                        dirty.add((cid, oid))
                        dirty.add((dest, oid))
            dirty_colls.add(cid)
            dirty_colls.add(dest)
        elif code == OP_COLL_MOVE_RENAME:
            _, oldcid, oldoid, newcid, newoid = op
            dirty.add((oldcid, oldoid))
            dirty.add((newcid, newoid))
        elif code == OP_TRY_RENAME:
            _, cid, oldoid, newoid = op
            dirty.add((cid, oldoid))
            dirty.add((cid, newoid))
        elif code in (OP_CLONE, OP_CLONERANGE2):
            dirty.add((op[1], op[2]))
            dirty.add((op[1], op[3]))
        elif len(op) >= 3 and isinstance(op[2], hobject_t):
            dirty.add((op[1], op[2]))

    def _persist(self, batch, cid: coll_t, oid: hobject_t) -> None:
        """Rewrite one object's full KV image (or clear it if gone)."""
        base = _obase(cid, oid)
        batch.rm_range(base, base + b"\xff")
        c = self._mem._colls.get(cid)
        o = c.objects.get(oid) if c is not None else None
        if o is None:
            return
        batch.set(base + b"a", denc.encode((str(cid), _oid_tuple(oid))))
        if o.data:
            batch.set(base + b"d", bytes(o.data))
        if o.omap_header:
            batch.set(base + b"h", o.omap_header)
        for name, val in o.xattrs.items():
            batch.set(base + b"x" + _esc(name.encode()), val)
        for key, val in o.omap.items():
            kb = key if isinstance(key, bytes) else key.encode()
            batch.set(base + b"m" + _esc(kb), val)

    # -- reads: delegate to the mirror ------------------------------------

    def exists(self, cid, oid):
        return self._mem.exists(cid, oid)

    def stat(self, cid, oid):
        return self._mem.stat(cid, oid)

    def read(self, cid, oid, offset=0, length=-1):
        return self._mem.read(cid, oid, offset, length)

    def getattr(self, cid, oid, name):
        return self._mem.getattr(cid, oid, name)

    def getattrs(self, cid, oid):
        return self._mem.getattrs(cid, oid)

    def omap_get_header(self, cid, oid):
        return self._mem.omap_get_header(cid, oid)

    def omap_get(self, cid, oid):
        return self._mem.omap_get(cid, oid)

    def omap_get_values(self, cid, oid, keys):
        return self._mem.omap_get_values(cid, oid, keys)

    def list_collections(self):
        return self._mem.list_collections()

    def collection_exists(self, cid):
        return self._mem.collection_exists(cid)

    def collection_empty(self, cid):
        return self._mem.collection_empty(cid)

    def collection_bits(self, cid):
        return self._mem.collection_bits(cid)

    def collection_list(self, cid, start=None, end=None, max_count=-1):
        return self._mem.collection_list(cid, start, end, max_count)
