"""ExtentStore: the production-class storage engine (BlueStore role).

Re-derivation of src/os/bluestore/BlueStore.cc's architecture for this
framework's L2 (queue_transactions pipeline BlueStore.cc:14141-14188,
deferred small writes, allocators, checksum-on-read), built on the
package's BlockDevice (blk.py = src/blk/) + KeyValueDB (kv.py) tiers:

* Object DATA lives on a flat block device in 4 KiB blocks; each
  onode's extent map points logical blocks at disk blocks, with a
  crc32 per block verified on every read (BlueStore csum_type crc32c).
* Object METADATA (onodes: size, extent map, xattrs, omap header) and
  omap keys live in the ordered KV, under the same bitwise-sorted key
  layout KStore uses, so collection_list is one range scan.
* BIG writes (whole blocks, large payloads) are copy-on-write: data
  goes to freshly allocated blocks and the device is flushed BEFORE
  the KV commit flips the extent map — a crash leaves the old object
  intact (BlueStore's unreferenced-space big-write path).
* SMALL writes are DEFERRED: the new whole-block images ride inside
  the same KV commit as a WAL record, and are applied to their final
  in-place location only after the commit lands (BlueStore deferred
  writes / bluestore_prefer_deferred_size).  A torn in-place block is
  unwindable because the WAL holds the full image; mount replays
  pending records idempotently.  WAL cleanup piggybacks on the next
  KV batch, which also closes the free-then-replay race: a record is
  always deleted in-or-before the batch that could recycle its blocks.
* The allocator (allocator.py) is rebuilt at mount from the onode
  extent maps — the modern reference's allocation-map-from-RocksDB
  recovery, which removes the persistent-freelist consistency problem.
* Free blocks from overwrites/removes are released only AFTER the KV
  commit that unreferences them, so committed metadata never points
  at recycled space.

Write amplification: a 4 KiB write to a 4 MiB object costs one 4 KiB
WAL record + one onode rewrite (~16 B/block of map) — not a 4 MiB
image rewrite (the KStore behavior this engine retires).
"""

from __future__ import annotations

import struct
import zlib
from typing import Callable

from ..utils import denc
from .allocator import AllocError, ExtentAllocator
from .blk import BlockDevice, FileBlockDevice, MemBlockDevice
from .kstore import _esc, _unesc, _obase, _ocollpref, _ckey, _CPREF, \
    _OPREF, _oid_tuple, _oid_from_tuple
from .kv import KeyValueDB, MemKV, SQLiteKV
from .objectstore import (
    OP_CLONE,
    OP_CLONERANGE2,
    OP_COLL_MOVE_RENAME,
    OP_CREATE,
    OP_MKCOLL,
    OP_NOP,
    OP_OMAP_CLEAR,
    OP_OMAP_RMKEYRANGE,
    OP_OMAP_RMKEYS,
    OP_OMAP_SETHEADER,
    OP_OMAP_SETKEYS,
    OP_REMOVE,
    OP_RMATTR,
    OP_RMATTRS,
    OP_RMCOLL,
    OP_SETATTR,
    OP_SETATTRS,
    OP_SPLIT_COLLECTION2,
    OP_TOUCH,
    OP_TRUNCATE,
    OP_TRY_RENAME,
    OP_WRITE,
    OP_ZERO,
    AlreadyExists,
    NotFound,
    ObjectStore,
    StoreError,
    Transaction,
    coll_t,
    hobject_t,
)

_WPREF = b"W\x00"
_SKEY = b"S\x00sb"
_BLOCK_REC = struct.Struct("<IIQ")      # bidx, crc32, disk offset


class ChecksumError(StoreError):
    """Data read back from the device failed its stored crc — the
    scrub tier treats this as a corrupt local shard."""


class Onode:
    """Object metadata record (BlueStore onode role): size, per-block
    extent map, xattrs, omap header.  Omap keys live beside it in the
    KV, not inside it."""

    __slots__ = ("size", "blocks", "xattrs", "omap_header")

    def __init__(self):
        self.size = 0
        self.blocks: dict[int, tuple[int, int]] = {}  # bidx->(doff,crc)
        self.xattrs: dict[str, bytes] = {}
        self.omap_header = b""

    def encode(self, cid: coll_t, oid: hobject_t) -> bytes:
        packed = b"".join(
            _BLOCK_REC.pack(b, crc, doff)
            for b, (doff, crc) in sorted(self.blocks.items()))
        return denc.encode((str(cid), _oid_tuple(oid), self.size,
                            packed, dict(self.xattrs),
                            self.omap_header))

    @classmethod
    def decode(cls, blob: bytes) -> tuple[str, hobject_t, "Onode"]:
        cidname, oid_t, size, packed, xattrs, hdr = denc.decode(blob)
        o = cls()
        o.size = size
        o.xattrs = dict(xattrs)
        o.omap_header = hdr
        for i in range(0, len(packed), _BLOCK_REC.size):
            b, crc, doff = _BLOCK_REC.unpack_from(packed, i)
            o.blocks[b] = (doff, crc)
        return cidname, _oid_from_tuple(oid_t), o


class _Coll:
    __slots__ = ("bits", "onodes")

    def __init__(self, bits: int = 0):
        self.bits = bits
        self.onodes: dict[hobject_t, Onode] = {}


class _TxContext:
    """Per-queue_transactions bookkeeping (BlueStore TransContext):
    which onodes/collections to persist, which blocks become free
    after commit, and the deferred (WAL) block images."""

    __slots__ = ("batch", "dirty", "dirty_colls", "released",
                 "deferred", "wrote_device", "omap_ops")

    def __init__(self, batch):
        self.batch = batch
        self.dirty: set[tuple[coll_t, hobject_t]] = set()
        self.dirty_colls: set[coll_t] = set()
        self.released: list[tuple[int, int]] = []
        self.deferred: dict[int, bytes] = {}    # doff -> block image
        self.wrote_device = False
        # staged omap mutations per object base key, in op order, so
        # a clone/move later in the SAME txn sees them (the committed
        # KV alone would miss same-txn omap writes)
        self.omap_ops: dict[bytes, list[tuple]] = {}

    def note_omap(self, base: bytes, op: tuple) -> None:
        self.omap_ops.setdefault(base, []).append(op)


class ExtentStore(ObjectStore):
    def __init__(self, path: str = "", db: KeyValueDB | None = None,
                 dev: BlockDevice | None = None,
                 dev_size: int = 1 << 30,
                 deferred_threshold: int = 65536):
        """``path`` is a directory holding ``block`` (the device file)
        and ``kv.db``; empty path = RAM device + RAM KV (ephemeral)."""
        super().__init__(path)
        if path:
            import os

            os.makedirs(path, exist_ok=True)
            self.db = db or SQLiteKV(path + "/kv.db")
            self.dev = dev or FileBlockDevice(path + "/block", dev_size)
        else:
            self.db = db or MemKV()
            self.dev = dev or MemBlockDevice(dev_size)
        self.bs = self.dev.block_size
        self.deferred_threshold = deferred_threshold
        self.alloc = ExtentAllocator(self.bs)
        self._colls: dict[coll_t, _Coll] = {}
        self._wal_seq = 0
        self._wal_cleanup: list[int] = []   # applied, key not yet rm'd
        self._overlay: dict[int, bytes] = {}  # committed, not applied
        # test hook: simulate a crash between KV commit and deferred
        # apply (the kill-point the WAL exists for)
        self.crash_before_deferred_apply = False

    # -- lifecycle ---------------------------------------------------------

    def mkfs(self) -> None:
        self.db.open()
        batch = self.db.get_transaction()
        batch.set(_SKEY, denc.encode({"block_size": self.bs,
                                      "dev_size": self.dev.size}))
        self.db.submit_transaction(batch)
        self.db.close()

    def mount(self) -> None:
        self.db.open()
        sb = self.db.get(_SKEY)
        if sb is not None:
            meta = denc.decode(sb)
            self.bs = meta["block_size"]
        self.dev.open()
        if sb is not None and meta["dev_size"] > self.dev.size:
            self.dev.extend(meta["dev_size"])
        self._replay_wal()
        self._load()

    def umount(self) -> None:
        self._flush_wal_cleanup()
        self.dev.flush()
        self.dev.close()
        self.db.close()
        self._colls = {}
        self._overlay = {}

    def statfs(self) -> dict:
        """Real device capacity vs the allocator's free-space view,
        PLUS the onode/omap KV footprint: metadata rides the DB, not
        the device, but it is real occupancy — `used` that omits it
        undercounts every omap-heavy workload (BlueStore folds its
        RocksDB usage into statfs the same way).  `kv_bytes` is
        broken out so `df` consumers can see the split; `available`
        stays the allocator's view of the block device (KV growth
        does not shrink extent space)."""
        total = int(self.dev.size)
        free = int(self.alloc.free_bytes)
        kv = 0
        for k, v in self.db.iterate():
            kv += len(k) + len(v)
        used = max(0, total - free) + kv
        return {"total": total, "used": used, "available": free,
                "kv_bytes": kv}

    def _replay_wal(self) -> None:
        """Apply committed-but-unapplied deferred writes.  Runs before
        the allocator rebuild, so a record targeting since-freed blocks
        just writes garbage into free space (harmless); records are
        deleted in one batch afterwards."""
        batch = self.db.get_transaction()
        n = 0
        for k, v in self.db.iterate(_WPREF, _WPREF + b"\xff"):
            (seq,) = struct.unpack(">Q", k[len(_WPREF):])
            self._wal_seq = max(self._wal_seq, seq + 1)
            for doff, data in denc.decode(v):
                if doff + len(data) > self.dev.size:
                    self.dev.extend(doff + len(data))
                self.dev.write(doff, data)
            batch.rmkey(bytes(k))
            n += 1
        if n:
            self.dev.flush()
            self.db.submit_transaction(batch)

    def _load(self) -> None:
        self._colls = {}
        for _k, v in self.db.iterate(_CPREF, _CPREF + b"\xff"):
            cidname, bits = denc.decode(v)
            self._colls[coll_t(cidname)] = _Coll(bits)
        self.alloc = ExtentAllocator(self.bs)
        self.alloc.init_add_free(0, (self.dev.size // self.bs) * self.bs)
        for k, v in self.db.iterate(_OPREF, _OPREF + b"\xff"):
            if not k.endswith(b"\x00a"):
                continue
            cidname, oid, onode = Onode.decode(v)
            self._colls[coll_t(cidname)].onodes[oid] = onode
            for doff, _crc in onode.blocks.values():
                self.alloc.init_rm_free(doff, self.bs)

    # -- helpers -----------------------------------------------------------

    def _coll(self, cid: coll_t) -> _Coll:
        c = self._colls.get(cid)
        if c is None:
            raise NotFound("collection %s" % cid)
        return c

    def _obj(self, cid: coll_t, oid: hobject_t,
             create: bool = False) -> Onode:
        c = self._coll(cid)
        o = c.onodes.get(oid)
        if o is None:
            if not create:
                raise NotFound("object %s/%s" % (cid, oid))
            o = Onode()
            c.onodes[oid] = o
        return o

    def _allocate(self, want: int) -> list[tuple[int, int]]:
        """Allocate ``want`` bytes of extents, thin-growing the device
        on ENOSPC."""
        try:
            return self.alloc.allocate(want)
        except AllocError:
            grown = max(self.dev.size * 2,
                        self.dev.size + max(want, 64 << 20))
            old = (self.dev.size // self.bs) * self.bs
            self.dev.extend(grown)
            self.alloc.init_add_free(
                old, (grown // self.bs) * self.bs - old)
            batch = self.db.get_transaction()
            batch.set(_SKEY, denc.encode({"block_size": self.bs,
                                          "dev_size": grown}))
            self.db.submit_transaction(batch)
            return self.alloc.allocate(want)

    def _allocate_block(self) -> int:
        [(off, _ln)] = self._allocate(self.bs)
        return off

    def _block_content(self, onode: Onode, bidx: int,
                       txc: _TxContext | None = None) -> bytes:
        """Current image of a logical block: staged-in-txn image wins,
        then the committed-not-applied overlay, then the device (crc
        verified), else zeros for holes."""
        m = onode.blocks.get(bidx)
        if m is None:
            return b"\x00" * self.bs
        doff, crc = m
        if txc is not None and doff in txc.deferred:
            return txc.deferred[doff]
        if doff in self._overlay:
            return self._overlay[doff]
        data = self.dev.read(doff, self.bs)
        if zlib.crc32(data) != crc:
            raise ChecksumError(
                "crc mismatch at disk off %d (block %d)" % (doff, bidx))
        return data

    # -- write pipeline ----------------------------------------------------

    def queue_transactions(
        self, txs: list[Transaction],
        on_applied: Callable[[], None] | None = None,
        on_commit: Callable[[], None] | None = None,
    ) -> None:
        batch = self.db.get_transaction()
        txc = _TxContext(batch)
        try:
            for tx in txs:
                for op in tx.ops:
                    self._apply_op(txc, op)
        except Exception:
            # a failed op must not leave RAM diverged from the KV
            # (phantom reads until restart, leaked allocations):
            # rebuild collections/onodes/allocator from committed
            # state — uncommitted COW grants return to free
            self._load()
            raise
        # persist dirty collections + onodes
        for cid in txc.dirty_colls:
            c = self._colls.get(cid)
            if c is not None:
                batch.set(_ckey(cid),
                          denc.encode((str(cid), c.bits)))
        for cid, oid in sorted(
                txc.dirty, key=lambda t: (str(t[0]), t[1].sort_key())):
            c = self._colls.get(cid)
            o = c.onodes.get(oid) if c is not None else None
            if o is not None:
                batch.set(_obase(cid, oid) + b"a", o.encode(cid, oid))
        wal_seq = -1
        if txc.deferred:
            wal_seq = self._wal_seq
            self._wal_seq += 1
            batch.set(_WPREF + struct.pack(">Q", wal_seq),
                      denc.encode(sorted(txc.deferred.items())))
        # piggyback cleanup of already-applied WAL records: they die
        # in-or-before any batch that could recycle their blocks
        for seq in self._wal_cleanup:
            batch.rmkey(_WPREF + struct.pack(">Q", seq))
        self._wal_cleanup = []
        if txc.wrote_device:
            # big-write barrier: data must be durable before the KV
            # commit makes the extent map point at it
            self.dev.flush()
        if on_applied:
            on_applied()
        self.db.submit_transaction(batch)
        # blocks unreferenced by this commit are now safe to recycle
        self.alloc.release(txc.released)
        if txc.deferred:
            self._overlay.update(txc.deferred)
            if not self.crash_before_deferred_apply:
                for doff, data in txc.deferred.items():
                    self.dev.write(doff, data)
                self.dev.flush()
                for doff in txc.deferred:
                    self._overlay.pop(doff, None)
                self._wal_cleanup.append(wal_seq)
        if on_commit:
            on_commit()

    def _flush_wal_cleanup(self) -> None:
        if not self._wal_cleanup:
            return
        batch = self.db.get_transaction()
        for seq in self._wal_cleanup:
            batch.rmkey(_WPREF + struct.pack(">Q", seq))
        self._wal_cleanup = []
        self.db.submit_transaction(batch)

    # -- op interpreter ----------------------------------------------------

    def _apply_op(self, txc: _TxContext, op: tuple) -> None:
        code = op[0]
        if code == OP_NOP:
            pass
        elif code == OP_CREATE:
            _, cid, oid = op
            c = self._coll(cid)
            if oid in c.onodes:
                raise AlreadyExists("object %s/%s" % (cid, oid))
            c.onodes[oid] = Onode()
            txc.dirty.add((cid, oid))
        elif code == OP_TOUCH:
            _, cid, oid = op
            self._obj(cid, oid, create=True)
            txc.dirty.add((cid, oid))
        elif code == OP_WRITE:
            _, cid, oid, offset, data = op
            self._do_write(txc, cid, oid, offset, data)
        elif code == OP_ZERO:
            _, cid, oid, offset, length = op
            self._do_zero(txc, cid, oid, offset, length)
        elif code == OP_TRUNCATE:
            _, cid, oid, length = op
            self._do_truncate(txc, cid, oid, length)
        elif code == OP_REMOVE:
            _, cid, oid = op
            self._do_remove(txc, cid, oid)
        elif code == OP_SETATTR:
            _, cid, oid, name, val = op
            self._obj(cid, oid, create=True).xattrs[name] = val
            txc.dirty.add((cid, oid))
        elif code == OP_SETATTRS:
            _, cid, oid, attrs = op
            self._obj(cid, oid, create=True).xattrs.update(attrs)
            txc.dirty.add((cid, oid))
        elif code == OP_RMATTR:
            _, cid, oid, name = op
            self._obj(cid, oid).xattrs.pop(name, None)
            txc.dirty.add((cid, oid))
        elif code == OP_RMATTRS:
            _, cid, oid = op
            self._obj(cid, oid).xattrs.clear()
            txc.dirty.add((cid, oid))
        elif code == OP_CLONE:
            _, cid, oid, newoid = op
            self._do_clone(txc, cid, oid, newoid)
        elif code == OP_CLONERANGE2:
            _, cid, oid, newoid, srcoff, length, dstoff = op
            src = self._obj(cid, oid)
            data = self._read_onode(src, srcoff, length, txc)
            self._do_write(txc, cid, newoid, dstoff, data)
        elif code == OP_OMAP_CLEAR:
            _, cid, oid = op
            self._obj(cid, oid)
            base = _obase(cid, oid)
            txc.batch.rm_range(base + b"m", base + b"m\xff")
            txc.note_omap(base, ("clear",))
        elif code == OP_OMAP_SETKEYS:
            _, cid, oid, kv = op
            self._obj(cid, oid, create=True)
            txc.dirty.add((cid, oid))
            base = _obase(cid, oid)
            for k, v in kv.items():
                txc.batch.set(base + b"m" + _esc(k), v)
                txc.note_omap(base, ("set", k, v))
        elif code == OP_OMAP_RMKEYS:
            _, cid, oid, keys = op
            self._obj(cid, oid)
            base = _obase(cid, oid)
            for k in keys:
                txc.batch.rmkey(base + b"m" + _esc(k))
                txc.note_omap(base, ("rm", k))
        elif code == OP_OMAP_RMKEYRANGE:
            _, cid, oid, first, last = op
            self._obj(cid, oid)
            base = _obase(cid, oid)
            fb = first if isinstance(first, bytes) else first.encode()
            lb = last if isinstance(last, bytes) else last.encode()
            txc.batch.rm_range(base + b"m" + _esc(fb),
                               base + b"m" + _esc(lb))
            txc.note_omap(base, ("range", fb, lb))
        elif code == OP_OMAP_SETHEADER:
            _, cid, oid, header = op
            self._obj(cid, oid, create=True).omap_header = header
            txc.dirty.add((cid, oid))
        elif code == OP_MKCOLL:
            _, cid, bits = op
            if cid in self._colls:
                raise AlreadyExists("collection %s" % cid)
            self._colls[cid] = _Coll(bits)
            txc.dirty_colls.add(cid)
        elif code == OP_RMCOLL:
            _, cid = op
            c = self._colls.pop(cid, None)
            if c is None:
                raise NotFound("collection %s" % cid)
            for oid, o in c.onodes.items():
                for doff, _crc in o.blocks.values():
                    txc.released.append((doff, self.bs))
            txc.batch.rmkey(_ckey(cid))
            pref = _ocollpref(cid)
            txc.batch.rm_range(pref, pref + b"\xff")
        elif code == OP_SPLIT_COLLECTION2:
            _, cid, bits, rem, dest = op
            src = self._coll(cid)
            dst = self._coll(dest)
            mask = (1 << bits) - 1
            moving = [oid for oid in src.onodes
                      if oid.hash & mask == rem]
            for oid in moving:
                self._move_object(txc, cid, oid, dest, oid)
            src.bits = bits
            dst.bits = bits
            txc.dirty_colls.add(cid)
            txc.dirty_colls.add(dest)
        elif code == OP_COLL_MOVE_RENAME:
            _, oldcid, oldoid, newcid, newoid = op
            if oldoid not in self._coll(oldcid).onodes:
                raise NotFound("object %s/%s" % (oldcid, oldoid))
            self._move_object(txc, oldcid, oldoid, newcid, newoid)
        elif code == OP_TRY_RENAME:
            _, cid, oldoid, newoid = op
            if oldoid in self._coll(cid).onodes:
                self._move_object(txc, cid, oldoid, cid, newoid)
        else:
            raise StoreError("unknown op %r" % (code,))

    # -- data-path internals ----------------------------------------------

    def _do_write(self, txc: _TxContext, cid: coll_t, oid: hobject_t,
                  offset: int, data: bytes) -> None:
        o = self._obj(cid, oid, create=True)
        txc.dirty.add((cid, oid))
        if not data:
            return
        end = offset + len(data)
        big = len(data) > self.deferred_threshold
        bs = self.bs
        b0, b1 = offset // bs, (end - 1) // bs if end else 0
        cow: list[tuple[int, bytes]] = []     # (bidx, block image)
        pos = 0
        for b in range(b0, b1 + 1):
            lo = max(offset, b * bs) - b * bs     # in-block bounds
            hi = min(end, (b + 1) * bs) - b * bs
            seg = data[pos:pos + (hi - lo)]
            pos += hi - lo
            full = (lo == 0 and hi == bs)
            if full and big:
                cow.append((b, seg))
            else:
                # deferred small path: RMW into a WAL block image
                if full:
                    img = seg
                else:
                    cur = bytearray(self._block_content(o, b, txc))
                    cur[lo:hi] = seg
                    img = bytes(cur)
                m = o.blocks.get(b)
                doff = m[0] if m is not None else self._allocate_block()
                txc.deferred[doff] = img
                o.blocks[b] = (doff, zlib.crc32(img))
        if cow:
            self._cow_write(txc, o, cow)
        if end > o.size:
            o.size = end

    def _cow_write(self, txc: _TxContext, o: Onode,
                   cow: list[tuple[int, bytes]]) -> None:
        """COW big path: ONE allocator request for all blocks, ONE
        device write per contiguous run, all pre-commit — fresh space
        only, so a lost commit leaves the old extents intact."""
        bs = self.bs
        runs = self._allocate(len(cow) * bs)
        offs = [roff + i
                for roff, rlen in runs
                for i in range(0, rlen, bs)]
        for (b, seg), doff in zip(cow, offs):
            old = o.blocks.get(b)
            if old is not None:
                txc.released.append((old[0], bs))
                txc.deferred.pop(old[0], None)
            o.blocks[b] = (doff, zlib.crc32(seg))
        i = 0
        for roff, rlen in runs:
            n = rlen // bs
            self.dev.write(roff, b"".join(seg for _b, seg
                                          in cow[i:i + n]))
            i += n
        txc.wrote_device = True

    def _do_zero(self, txc: _TxContext, cid: coll_t, oid: hobject_t,
                 offset: int, length: int) -> None:
        """Zero = punch: whole covered blocks are dropped from the map
        (reads of holes return zeros), edges are RMW-patched."""
        o = self._obj(cid, oid, create=True)
        txc.dirty.add((cid, oid))
        if length <= 0:
            return
        end = offset + length
        bs = self.bs
        for b in range(offset // bs, ((end - 1) // bs if end else 0) + 1):
            lo = max(offset, b * bs) - b * bs
            hi = min(end, (b + 1) * bs) - b * bs
            m = o.blocks.get(b)
            if lo == 0 and hi == bs:
                if m is not None:
                    txc.released.append((m[0], bs))
                    txc.deferred.pop(m[0], None)
                    del o.blocks[b]
            elif m is not None:
                cur = bytearray(self._block_content(o, b, txc))
                cur[lo:hi] = b"\x00" * (hi - lo)
                img = bytes(cur)
                txc.deferred[m[0]] = img
                o.blocks[b] = (m[0], zlib.crc32(img))
        if end > o.size:
            o.size = end

    def _do_truncate(self, txc: _TxContext, cid: coll_t,
                     oid: hobject_t, length: int) -> None:
        o = self._obj(cid, oid)
        txc.dirty.add((cid, oid))
        if length < o.size:
            bs = self.bs
            cut = (length + bs - 1) // bs
            for b in [b for b in o.blocks if b >= cut]:
                doff, _crc = o.blocks.pop(b)
                txc.released.append((doff, bs))
                txc.deferred.pop(doff, None)
            if length % bs:
                # zero the dropped tail of the keep-block so a later
                # re-extend reads zeros there (MemStore semantics)
                b = length // bs
                if b in o.blocks:
                    cur = bytearray(self._block_content(o, b, txc))
                    cur[length % bs:] = b"\x00" * (bs - length % bs)
                    img = bytes(cur)
                    doff = o.blocks[b][0]
                    txc.deferred[doff] = img
                    o.blocks[b] = (doff, zlib.crc32(img))
        o.size = length

    def _do_remove(self, txc: _TxContext, cid: coll_t,
                   oid: hobject_t) -> None:
        # idempotent, like MemStore: replicas may delete absentees
        c = self._coll(cid)
        o = c.onodes.pop(oid, None)
        if o is None:
            return
        for doff, _crc in o.blocks.values():
            txc.released.append((doff, self.bs))
            txc.deferred.pop(doff, None)
        base = _obase(cid, oid)
        txc.batch.rm_range(base, base + b"\xff")
        txc.dirty.discard((cid, oid))

    def _do_clone(self, txc: _TxContext, cid: coll_t, oid: hobject_t,
                  newoid: hobject_t) -> None:
        """Physical copy-on-clone: every mapped source block is copied
        to fresh space pre-commit.  (The reference shares blobs via
        SharedBlob refcounts; a copy is the simple correct form — the
        in-place deferred path stays free of refcount checks.)"""
        src = self._obj(cid, oid)
        if newoid in self._coll(cid).onodes:
            self._do_remove(txc, cid, newoid)
        dst = Onode()
        dst.size = src.size
        dst.xattrs = dict(src.xattrs)
        dst.omap_header = src.omap_header
        for b in src.blocks:
            img = self._block_content(src, b, txc)
            doff = self._allocate_block()
            self.dev.write(doff, img)
            txc.wrote_device = True
            dst.blocks[b] = (doff, zlib.crc32(img))
        self._coll(cid).onodes[newoid] = dst
        txc.dirty.add((cid, newoid))
        # omap copy: committed keys merged with same-txn staged ops
        sbase = _obase(cid, oid)
        dbase = _obase(cid, newoid)
        txc.batch.rm_range(dbase + b"m", dbase + b"m\xff")
        txc.note_omap(dbase, ("clear",))
        for k, v in self._omap_items(txc, sbase).items():
            txc.batch.set(dbase + b"m" + _esc(k), v)
            txc.note_omap(dbase, ("set", k, v))

    def _move_object(self, txc: _TxContext, oldcid: coll_t,
                     oldoid: hobject_t, newcid: coll_t,
                     newoid: hobject_t) -> None:
        """Rename/move: metadata re-keys; data blocks do not move."""
        src = self._coll(oldcid)
        o = src.onodes.pop(oldoid)
        dstc = self._coll(newcid)
        prev = dstc.onodes.pop(newoid, None)
        if prev is not None:
            for doff, _crc in prev.blocks.values():
                txc.released.append((doff, self.bs))
                txc.deferred.pop(doff, None)
        dstc.onodes[newoid] = o
        obase = _obase(oldcid, oldoid)
        nbase = _obase(newcid, newoid)
        txc.batch.rm_range(nbase, nbase + b"\xff")
        txc.note_omap(nbase, ("clear",))
        for k, v in self._omap_items(txc, obase).items():
            txc.batch.set(nbase + b"m" + _esc(k), v)
            txc.note_omap(nbase, ("set", k, v))
        txc.batch.rm_range(obase, obase + b"\xff")
        txc.note_omap(obase, ("clear",))
        txc.dirty.discard((oldcid, oldoid))
        txc.dirty.add((newcid, newoid))

    def _omap_items(self, txc: _TxContext, base: bytes) -> dict:
        """Committed omap of ``base`` with this txn's staged ops
        replayed on top, keyed by unescaped key bytes."""
        items = {_unesc(bytes(k[len(base) + 1:])): v
                 for k, v in self.db.iterate(base + b"m",
                                             base + b"m\xff")}
        for op in txc.omap_ops.get(base, ()):
            if op[0] == "set":
                items[op[1]] = op[2]
            elif op[0] == "rm":
                items.pop(op[1], None)
            elif op[0] == "clear":
                items.clear()
            else:
                for k in [k for k in items if op[1] <= k < op[2]]:
                    del items[k]
        return items

    def _read_onode(self, o: Onode, offset: int, length: int,
                    txc: _TxContext | None = None) -> bytes:
        if length < 0:
            length = max(0, o.size - offset)
        length = max(0, min(length, o.size - offset))
        if length == 0:
            return b""
        end = offset + length
        bs = self.bs
        parts = []
        for b in range(offset // bs, (end - 1) // bs + 1):
            img = self._block_content(o, b, txc)
            lo = max(offset, b * bs) - b * bs
            hi = min(end, (b + 1) * bs) - b * bs
            parts.append(img[lo:hi])
        return b"".join(parts)

    # -- reads -------------------------------------------------------------

    def exists(self, cid, oid):
        c = self._colls.get(cid)
        return c is not None and oid in c.onodes

    def stat(self, cid, oid):
        return self._obj(cid, oid).size

    def read(self, cid, oid, offset=0, length=-1):
        return self._read_onode(self._obj(cid, oid), offset, length)

    def getattr(self, cid, oid, name):
        try:
            return self._obj(cid, oid).xattrs[name]
        except KeyError:
            raise NotFound("xattr %s" % name) from None

    def getattrs(self, cid, oid):
        return dict(self._obj(cid, oid).xattrs)

    def omap_get_header(self, cid, oid):
        return self._obj(cid, oid).omap_header

    def omap_get(self, cid, oid):
        self._obj(cid, oid)
        base = _obase(cid, oid)
        return {_unesc(bytes(k[len(base) + 1:])): v
                for k, v in self.db.iterate(base + b"m",
                                            base + b"m\xff")}

    def omap_get_values(self, cid, oid, keys):
        self._obj(cid, oid)
        base = _obase(cid, oid)
        out = {}
        for k in keys:
            kb = k if isinstance(k, bytes) else k.encode()
            v = self.db.get(base + b"m" + _esc(kb))
            if v is not None:
                out[k] = v
        return out

    # -- collections -------------------------------------------------------

    def list_collections(self):
        return sorted(self._colls, key=lambda c: c.name)

    def collection_exists(self, cid):
        return cid in self._colls

    def collection_empty(self, cid):
        return not self._coll(cid).onodes

    def collection_bits(self, cid):
        return self._coll(cid).bits

    def collection_list(self, cid, start=None, end=None, max_count=-1):
        objs = sorted(self._coll(cid).onodes,
                      key=lambda o: o.sort_key())
        if start is not None:
            sk = start.sort_key()
            objs = [o for o in objs if o.sort_key() >= sk]
        if end is not None:
            ek = end.sort_key()
            objs = [o for o in objs if o.sort_key() < ek]
        if max_count >= 0:
            objs = objs[:max_count]
        return objs
