"""store subpackage — see ceph_tpu/__init__.py for the layer map."""

from __future__ import annotations


def create_store(conf, whoami: int = 0):
    """Conf-driven store factory (the osd_objectstore switch,
    src/os/ObjectStore.cc create() role).  An empty osd_data keeps
    every engine ephemeral (RAM KV / RAM block device) so test
    clusters need no directory management."""
    kind = conf["osd_objectstore"]
    data = conf["osd_data"]
    path = ("%s/osd.%d" % (data.rstrip("/"), whoami)) if data else ""
    if kind == "memstore":
        from .memstore import MemStore

        return MemStore(path,
                        device_bytes=conf["memstore_device_bytes"])
    if kind == "kstore":
        from .kstore import KStore
        from .kv import MemKV

        if path:
            import os

            os.makedirs(path, exist_ok=True)
            return KStore(path + "/kstore.db")
        return KStore("", db=MemKV())
    if kind == "extentstore":
        from .extentstore import ExtentStore

        return ExtentStore(
            path,
            dev_size=conf["extentstore_device_size"],
            deferred_threshold=conf["extentstore_deferred_threshold"])
    raise ValueError("unknown osd_objectstore %r" % kind)
