"""KeyValueDB: ordered KV abstraction + SQLite backend.

The reference wraps RocksDB behind KeyValueDB (src/kv/KeyValueDB.h,
src/kv/RocksDBStore.h:78) so stores and monitors are engine-agnostic.
Here the durable engine is SQLite in WAL mode (in the container there
is no RocksDB binding; SQLite gives the same contract: ordered byte
keys, atomic write batches, range scans).  The interface is kept so a
RocksDB/C++ engine can slot in without touching callers.
"""

from __future__ import annotations

import sqlite3
import threading
from typing import Iterator


class KVTransaction:
    """A write batch: set/rmkey/rm_range staged then submitted
    atomically (KeyValueDB::Transaction analog)."""

    def __init__(self):
        self.ops: list[tuple] = []

    def set(self, key: bytes, value: bytes) -> None:
        self.ops.append(("set", bytes(key), bytes(value)))

    def rmkey(self, key: bytes) -> None:
        self.ops.append(("rm", bytes(key)))

    def rm_range(self, first: bytes, last: bytes) -> None:
        """Removes keys in [first, last)."""
        self.ops.append(("rmrange", bytes(first), bytes(last)))


class KeyValueDB:
    """Ordered byte-key store contract."""

    def open(self) -> None:
        raise NotImplementedError

    def close(self) -> None:
        raise NotImplementedError

    def get_transaction(self) -> KVTransaction:
        return KVTransaction()

    def submit_transaction(self, tx: KVTransaction,
                           sync: bool = True) -> None:
        raise NotImplementedError

    def get(self, key: bytes) -> bytes | None:
        raise NotImplementedError

    def iterate(self, first: bytes = b"",
                last: bytes | None = None) -> Iterator[tuple[bytes, bytes]]:
        """Ordered scan over [first, last)."""
        raise NotImplementedError


class MemKV(KeyValueDB):
    """Dict-backed engine for tests."""

    def __init__(self):
        self._d: dict[bytes, bytes] = {}
        self._lock = threading.RLock()

    def open(self) -> None:
        pass

    def close(self) -> None:
        pass

    def submit_transaction(self, tx: KVTransaction,
                           sync: bool = True) -> None:
        with self._lock:
            for op in tx.ops:
                if op[0] == "set":
                    self._d[op[1]] = op[2]
                elif op[0] == "rm":
                    self._d.pop(op[1], None)
                else:
                    for k in [k for k in self._d if op[1] <= k < op[2]]:
                        del self._d[k]

    def get(self, key: bytes) -> bytes | None:
        with self._lock:
            return self._d.get(key)

    def iterate(self, first: bytes = b"", last: bytes | None = None):
        with self._lock:
            keys = sorted(k for k in self._d
                          if k >= first and (last is None or k < last))
            items = [(k, self._d[k]) for k in keys]
        return iter(items)


class SQLiteKV(KeyValueDB):
    """Durable engine: one ordered BLOB table, WAL journaling."""

    def __init__(self, path: str):
        self.path = path
        self._conn: sqlite3.Connection | None = None
        self._lock = threading.RLock()

    def open(self) -> None:
        self._conn = sqlite3.connect(self.path, check_same_thread=False)
        self._conn.execute("PRAGMA journal_mode=WAL")
        # FULL fsyncs the WAL on every commit: sync=True submits must
        # be power-loss durable (the extent store's deferred in-place
        # writes depend on the committed WAL record surviving reboot;
        # NORMAL could roll the commit back and strand a torn block)
        self._conn.execute("PRAGMA synchronous=FULL")
        self._sync = True
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS kv "
            "(k BLOB PRIMARY KEY, v BLOB NOT NULL) WITHOUT ROWID")
        self._conn.commit()

    def close(self) -> None:
        if self._conn is not None:
            self._conn.close()
            self._conn = None

    def submit_transaction(self, tx: KVTransaction,
                           sync: bool = True) -> None:
        assert self._conn is not None, "not open"
        with self._lock:
            if sync != self._sync:
                self._conn.execute("PRAGMA synchronous=%s"
                                   % ("FULL" if sync else "NORMAL"))
                self._sync = sync
            cur = self._conn.cursor()
            for op in tx.ops:
                if op[0] == "set":
                    cur.execute(
                        "INSERT INTO kv (k, v) VALUES (?, ?) "
                        "ON CONFLICT(k) DO UPDATE SET v = excluded.v",
                        (op[1], op[2]))
                elif op[0] == "rm":
                    cur.execute("DELETE FROM kv WHERE k = ?", (op[1],))
                else:
                    cur.execute("DELETE FROM kv WHERE k >= ? AND k < ?",
                                (op[1], op[2]))
            self._conn.commit()

    def get(self, key: bytes) -> bytes | None:
        assert self._conn is not None, "not open"
        with self._lock:
            row = self._conn.execute(
                "SELECT v FROM kv WHERE k = ?", (key,)).fetchone()
        return row[0] if row else None

    def iterate(self, first: bytes = b"", last: bytes | None = None):
        assert self._conn is not None, "not open"
        with self._lock:
            if last is None:
                rows = self._conn.execute(
                    "SELECT k, v FROM kv WHERE k >= ? ORDER BY k",
                    (first,)).fetchall()
            else:
                rows = self._conn.execute(
                    "SELECT k, v FROM kv WHERE k >= ? AND k < ? "
                    "ORDER BY k", (first, last)).fetchall()
        return iter(rows)
