"""rjenkins1 32-bit hash family used throughout placement.

Reference semantics: src/crush/hash.c (crush_hashmix + crush_hash32_[1-5])
and the string hash ceph_str_hash_rjenkins (src/common/ceph_hash.cc) used
by object_locator_to_pg.  Re-derived here in two forms:

* scalar python ints (host single-query path, bit-exact, masked to u32)
* numpy uint32 vectorized (feeds the JAX kernel and bulk host mapping)

Both forms share the same mixing schedule; the vectorized form is the
basis of the TPU kernel (same ops, jnp instead of np).
"""

from __future__ import annotations

import numpy as np

M32 = 0xFFFFFFFF
HASH_SEED = 1315423911
RJENKINS1 = 0  # the only hash id (CRUSH_HASH_RJENKINS1)


# -- scalar ---------------------------------------------------------------

def _mix(a: int, b: int, c: int) -> tuple[int, int, int]:
    a = (a - b) & M32; a = (a - c) & M32; a ^= c >> 13
    b = (b - c) & M32; b = (b - a) & M32; b = (b ^ (a << 8)) & M32
    c = (c - a) & M32; c = (c - b) & M32; c ^= b >> 13
    a = (a - b) & M32; a = (a - c) & M32; a ^= c >> 12
    b = (b - c) & M32; b = (b - a) & M32; b = (b ^ (a << 16)) & M32
    c = (c - a) & M32; c = (c - b) & M32; c ^= b >> 5
    a = (a - b) & M32; a = (a - c) & M32; a ^= c >> 3
    b = (b - c) & M32; b = (b - a) & M32; b = (b ^ (a << 10)) & M32
    c = (c - a) & M32; c = (c - b) & M32; c ^= b >> 15
    return a, b, c


def hash32(a: int) -> int:
    a &= M32
    h = (HASH_SEED ^ a) & M32
    b, x, y = a, 231232, 1232
    b, x, h = _mix(b, x, h)
    y, a, h = _mix(y, a, h)
    return h


def hash32_2(a: int, b: int) -> int:
    a &= M32; b &= M32
    h = (HASH_SEED ^ a ^ b) & M32
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h


def hash32_3(a: int, b: int, c: int) -> int:
    a &= M32; b &= M32; c &= M32
    h = (HASH_SEED ^ a ^ b ^ c) & M32
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h


def hash32_4(a: int, b: int, c: int, d: int) -> int:
    a &= M32; b &= M32; c &= M32; d &= M32
    h = (HASH_SEED ^ a ^ b ^ c ^ d) & M32
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    a, x, h = _mix(a, x, h)
    y, b, h = _mix(y, b, h)
    c, x, h = _mix(c, x, h)
    y, d, h = _mix(y, d, h)
    return h


def hash32_5(a: int, b: int, c: int, d: int, e: int) -> int:
    a &= M32; b &= M32; c &= M32; d &= M32; e &= M32
    h = (HASH_SEED ^ a ^ b ^ c ^ d ^ e) & M32
    x, y = 231232, 1232
    a, b, h = _mix(a, b, h)
    c, d, h = _mix(c, d, h)
    e, x, h = _mix(e, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    d, x, h = _mix(d, x, h)
    y, e, h = _mix(y, e, h)
    return h


# -- vectorized (numpy; mirrored 1:1 by the jnp kernel) -------------------

def _mix_v(a, b, c, xp=np):
    a = a - b; a = a - c; a = a ^ (c >> np.uint32(13))
    b = b - c; b = b - a; b = b ^ (a << np.uint32(8))
    c = c - a; c = c - b; c = c ^ (b >> np.uint32(13))
    a = a - b; a = a - c; a = a ^ (c >> np.uint32(12))
    b = b - c; b = b - a; b = b ^ (a << np.uint32(16))
    c = c - a; c = c - b; c = c ^ (b >> np.uint32(5))
    a = a - b; a = a - c; a = a ^ (c >> np.uint32(3))
    b = b - c; b = b - a; b = b ^ (a << np.uint32(10))
    c = c - a; c = c - b; c = c ^ (b >> np.uint32(15))
    return a, b, c


def hash32_3_v(a, b, c):
    """Vectorized hash32_3 over uint32 arrays (broadcasting)."""
    a = np.asarray(a, np.uint32)
    b = np.asarray(b, np.uint32)
    c = np.asarray(c, np.uint32)
    h = np.uint32(HASH_SEED) ^ a ^ b ^ c
    x = np.uint32(231232)
    y = np.uint32(1232)
    a, b, h = _mix_v(a, b, h)
    c, x, h = _mix_v(c, x, h)
    y, a, h = _mix_v(y, a, h)
    b, x, h = _mix_v(b, x, h)
    y, c, h = _mix_v(y, c, h)
    return h


def hash32_2_v(a, b):
    a = np.asarray(a, np.uint32)
    b = np.asarray(b, np.uint32)
    h = np.uint32(HASH_SEED) ^ a ^ b
    x = np.uint32(231232)
    y = np.uint32(1232)
    a, b, h = _mix_v(a, b, h)
    x, a, h = _mix_v(x, a, h)
    b, y, h = _mix_v(b, y, h)
    return h


# -- string hash (object name -> placement seed) --------------------------

def str_hash_rjenkins(key: bytes) -> int:
    """Object-name hash used for pg selection.

    Reference semantics: ceph_str_hash_rjenkins (src/common/ceph_hash.cc) —
    the classic Jenkins 96-bit mix over 12-byte blocks with golden-ratio
    initialisation and length folded into the tail block.
    """
    a = 0x9E3779B9
    b = a
    c = 0  # initval
    length = len(key)
    i = 0
    while length >= 12:
        a = (a + (key[i] | key[i + 1] << 8 | key[i + 2] << 16 | key[i + 3] << 24)) & M32
        b = (b + (key[i + 4] | key[i + 5] << 8 | key[i + 6] << 16 | key[i + 7] << 24)) & M32
        c = (c + (key[i + 8] | key[i + 9] << 8 | key[i + 10] << 16 | key[i + 11] << 24)) & M32
        a, b, c = _mix(a, b, c)
        i += 12
        length -= 12
    c = (c + len(key)) & M32
    # tail bytes fold into the high bytes of a/b/c (byte 8 is skipped:
    # that slot carries the length)
    if length >= 11:
        c = (c + (key[i + 10] << 24)) & M32
    if length >= 10:
        c = (c + (key[i + 9] << 16)) & M32
    if length >= 9:
        c = (c + (key[i + 8] << 8)) & M32
    if length >= 8:
        b = (b + (key[i + 7] << 24)) & M32
    if length >= 7:
        b = (b + (key[i + 6] << 16)) & M32
    if length >= 6:
        b = (b + (key[i + 5] << 8)) & M32
    if length >= 5:
        b = (b + key[i + 4]) & M32
    if length >= 4:
        a = (a + (key[i + 3] << 24)) & M32
    if length >= 3:
        a = (a + (key[i + 2] << 16)) & M32
    if length >= 2:
        a = (a + (key[i + 1] << 8)) & M32
    if length >= 1:
        a = (a + key[i]) & M32
    a, b, c = _mix(a, b, c)
    return c


def pps_seed_v(ps, pgp_num: int, pgp_mask: int, pool_id: int,
               hashpspool: bool):
    """Vectorized raw_pg_to_pps placement seed (osd_types.cc:1815-1831)
    — the single source for the stable-mod + pool-mix composition used
    by the host pipeline, the bulk mapper's patch path, and (mirrored
    in jnp inside DeviceMapper._compiled_pool) the device pass."""
    import numpy as np
    ps = np.asarray(ps, dtype=np.uint32)
    masked = np.where((ps & pgp_mask) < pgp_num, ps & pgp_mask,
                      ps & (pgp_mask >> 1)).astype(np.uint32)
    if hashpspool:
        return hash32_2_v(masked, np.uint32(pool_id)).astype(np.int64)
    return masked.astype(np.int64) + pool_id
