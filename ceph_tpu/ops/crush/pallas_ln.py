"""Pallas TPU kernels for the straw2 fixed-point log.

neg_ln(u) = 2^48 - crush_ln(u) for u in [0, 0xFFFF] — the inner-loop
table math of the straw2 exponential draw (mapper.c:226-268), which
dominates bulk mapping cost.  The XLA one-hot-matmul formulation
(device.neg_ln_mxu) materializes the [N, 129]/[N, 256] one-hots and the
int64 intermediate planes in HBM (~20 GB of traffic per 26M draws —
measured 52 ms); these kernels keep the one-hots, the MXU table fetches
and the 65-bit product chain in VMEM (~1 GB total), cutting the op to
a few ms.

Exactness: every step is integer; 64-bit quantities (rh < 2^48,
lh/ll < 2^49, the 65-bit product x2*rh) are carried as int32 hi/lo
limb pairs (u32 bit patterns) with explicit carries; verified
bit-exact against the host crush_ln for all 65536 inputs
(tests/test_crush_device.py).

Mosaic workarounds baked into the structure (this jax/libtpu version):
* int64 anywhere in a kernel recurses at lowering — all limb math is
  int32 with _ult/_lshr emulating unsigned semantics, and scalar
  operands are explicitly typed (a weak python literal inside
  where/maximum traces as i64[] under jax x64);
* combining values from two chained dot_generals, more than two kernel
  outputs, or combining dot-derived with compare-chain-derived values
  in one output expression all fail to legalize ('func.return') or
  crash the compile helper — hence THREE single-dot kernels
  (A: RH fetch + product chain -> LL index; C: LH-high fetch;
  B: LL fetch) with the cheap elementwise prep/combine left to XLA.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ._ln_tables import LL_TBL, RH_LH_TBL

R, W = 16, 512          # block: R sublanes x W lanes
BLOCK = R * W
K = 256                 # one-hot width (table rows, padded)
NL = 7                  # int8 limbs per 64-bit table value


NPLANES = 8  # 8-bit limb planes of the 64-bit table values


def _pack(table: np.ndarray) -> np.ndarray:
    """[rows] u64 -> [256, 8] f32 of 8-bit limb planes.

    8-bit values are exact even when the MXU runs the dot in bf16
    (8-bit mantissa), and a one-hot row selects a single value so no
    accumulation error exists — DEFAULT matmul precision stays exact."""
    out = np.zeros((K, NPLANES), dtype=np.float32)
    for i, v in enumerate(table):
        v = int(v)
        for j in range(NPLANES):
            out[i, j] = (v >> (8 * j)) & 0xFF
    return out


_RH_LIMBS = _pack(np.array(RH_LH_TBL[0::2], dtype=np.uint64))
_LH_LIMBS = _pack(np.array(RH_LH_TBL[1::2], dtype=np.uint64))
_LL_LIMBS = _pack(np.array(LL_TBL, dtype=np.uint64))


def _ult(a, b):
    """Unsigned a < b on int32 bit patterns: signed compare flipped
    when the sign bits differ."""
    return (a < b) ^ ((a < 0) ^ (b < 0))


def _lshr(x, s: int):
    """Logical right shift of int32 bits by static s > 0."""
    return (x >> s) & ((1 << (32 - s)) - 1)


def _onehot_dot(idx, tbl_ref):
    """f32 one-hot fetch: [R,W] indices -> [R,W,NPLANES] exact ints.
    (int8 dots also work here, but slicing their 3D result fails to
    legalize under a grid in this Mosaic version; f32 slices are fine.)"""
    oh = (idx[..., None] == jax.lax.broadcasted_iota(
        jnp.int32, (R, W, K), 2)).astype(jnp.float32)
    return jax.lax.dot_general(
        oh, tbl_ref[:], (((2,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def _plane(r, j):
    return r[..., j].astype(jnp.int32)


# one output per kernel: this Mosaic version also fails to legalize
# multi-output kernels under a grid


def _kernel_a(x2_ref, p_ref, rh_ref, i2_ref):
    """RH fetch + the 65-bit x2*rh product; emits the LL index."""
    x2 = x2_ref[:]
    rl = _onehot_dot(p_ref[:], rh_ref)
    # rh <= 2^48 as 16-bit pieces from the 8-bit limb planes (piece 3
    # is the single bit 48, set only for RH[0] = ceil(2^56/256)).
    # Combines are arithmetic (+/*), never or-of-shifts: Mosaic
    # miscompiles shift-or chains over f32-dot slices here, while the
    # disjoint-bit adds are exact and compile correctly.
    pieces = (_plane(rl, 0) + _plane(rl, 1) * 256,
              _plane(rl, 2) + _plane(rl, 3) * 256,
              _plane(rl, 4) + _plane(rl, 5) * 256,
              _plane(rl, 6))
    vhi = jnp.zeros((R, W), jnp.int32)
    vlo = jnp.zeros((R, W), jnp.int32)
    for i, piece in enumerate(pieces[:4]):
        term = x2 * piece                           # < 2^32 (wrap ok)
        off = 16 * i
        t_lo = term << off if off < 32 else jnp.zeros_like(term)
        if off == 0:
            t_hi = jnp.zeros_like(term)
        elif off < 32:
            t_hi = _lshr(term, 32 - off)
        else:
            t_hi = term << (off - 32)
        nlo = vlo + t_lo
        carry = _ult(nlo, vlo).astype(jnp.int32)
        vhi = vhi + t_hi + carry
        vlo = nlo
    # xl64 = (x2*rh) >> 48; only its low 8 bits index LL
    i2_ref[:] = _lshr(vhi, 16) & 0xFF


def _kernel_fetch_lo(idx_ref, tbl_ref, lo_ref):
    """Table fetch, low 32 bits (limb planes 0-3; arithmetic combine —
    see _kernel_a).  plane3 * 2^24 can exceed 2^31: the wrapped int32
    add keeps the correct u32 bit pattern."""
    r = _onehot_dot(idx_ref[:], tbl_ref)
    lo_ref[:] = (_plane(r, 0) + _plane(r, 1) * 256
                 + _plane(r, 2) * 65536 + _plane(r, 3) * 16777216)


def _kernel_fetch_hi(idx_ref, tbl_ref, hi_ref):
    """Table fetch, high 32 bits (limb planes 4-6)."""
    r = _onehot_dot(idx_ref[:], tbl_ref)
    hi_ref[:] = (_plane(r, 4) + _plane(r, 5) * 256
                 + _plane(r, 6) * 65536)


def _pair_to_i64(hi, lo):
    return (hi.astype(jnp.int64) << 32) | \
        (lo.astype(jnp.int64) & 0xFFFFFFFF)


@functools.partial(jax.jit, static_argnames=("n_pad",))
def _run_kernels(u_flat, rh_t, lh_t, ll_t, n_pad: int):
    """x64-DISABLED phase: under the repo's global jax x64 mode, the
    BlockSpec index maps trace as i64[] and Mosaic fails to legalize
    every kernel ('func.return'); the caller wraps this in
    jax.enable_x64(False).  All math here is int32/float32."""
    nblk = n_pad // BLOCK
    u2 = u_flat.reshape(nblk * R, W)
    shp = jax.ShapeDtypeStruct((nblk * R, W), jnp.int32)
    blk = pl.BlockSpec((R, W), lambda i: (i, 0))
    tblspec = pl.BlockSpec((K, NPLANES), lambda i: (0, 0))

    # elementwise normalization (mapper.c:239-247), fused by XLA
    x = u2 + 1
    bl = jnp.ones_like(x)
    for kbit in range(1, 17):
        bl = bl + (x >= (1 << kbit)).astype(jnp.int32)
    need = (x & 0x18000) == 0
    bits = jnp.maximum(16 - bl, 0)
    x2 = jnp.where(need, x << bits, x).astype(jnp.int32)
    iexpon = jnp.where(need, 15 - bits, 15).astype(jnp.int32)
    p = (x2 >> 8) - 128

    i2 = pl.pallas_call(
        _kernel_a, out_shape=shp, grid=(nblk,),
        in_specs=[blk, blk, tblspec], out_specs=blk,
    )(x2, p, rh_t)

    def fetch(idx, tbl):
        hi = pl.pallas_call(
            _kernel_fetch_hi, out_shape=shp, grid=(nblk,),
            in_specs=[blk, tblspec], out_specs=blk)(idx, tbl)
        lo = pl.pallas_call(
            _kernel_fetch_lo, out_shape=shp, grid=(nblk,),
            in_specs=[blk, tblspec], out_specs=blk)(idx, tbl)
        return hi, lo

    lh_hi, lh_lo = fetch(p, lh_t)
    ll_hi, ll_lo = fetch(i2, ll_t)
    return iexpon, lh_hi, lh_lo, ll_hi, ll_lo


@jax.jit
def _combine(iexpon, lh_hi, lh_lo, ll_hi, ll_lo):
    """x64 phase: assemble neg = 2^48 - ((iexpon<<44) + (lh+ll)>>4)."""
    lh2 = (_pair_to_i64(lh_hi, lh_lo) + _pair_to_i64(ll_hi, ll_lo)) >> 4
    return (jnp.int64(1) << 48) - \
        ((iexpon.astype(jnp.int64) << 44) + lh2)


class NegLnPallas:
    """Callable returning 2^48 - crush_ln(u) as int64 (bit-exact)."""

    def __init__(self):
        self.rh = jnp.asarray(_RH_LIMBS)
        self.lh = jnp.asarray(_LH_LIMBS)
        self.ll = jnp.asarray(_LL_LIMBS)

    def __call__(self, u):
        """u int array (any shape) in [0, 0xFFFF] -> int64 same shape."""
        shape = u.shape
        flat = u.reshape(-1).astype(jnp.int32)
        n = flat.shape[0]
        n_pad = -(-n // BLOCK) * BLOCK
        if n_pad != n:
            flat = jnp.pad(flat, (0, n_pad - n))
        with jax.enable_x64(False):
            parts = _run_kernels(flat, self.rh, self.lh, self.ll,
                                 n_pad)
        neg = _combine(*parts)
        return neg.reshape(-1)[:n].reshape(shape)
