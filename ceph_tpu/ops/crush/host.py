"""Host (scalar) CRUSH mapping engine — the bit-exact reference path.

Single-PG queries on the request-routing path use this engine (or its
C++ twin in src/native); bulk remaps use the vectorized JAX kernel.
All three produce identical mappings.

Reference semantics re-derived from src/crush/mapper.c: bucket choose
methods (:51-396), is_out (:402), crush_choose_firstn (:438),
crush_choose_indep (:633), and the crush_do_rule step VM (:878).
Structured here as a Mapper class over the declarative CrushMap model
rather than C workspaces; per-uniform-bucket permutation state lives in
a per-call dict.
"""

from __future__ import annotations

from ...models.crushmap import (
    CHOOSE_FIRSTN,
    CHOOSE_INDEP,
    CHOOSELEAF_FIRSTN,
    CHOOSELEAF_INDEP,
    EMIT,
    ITEM_NONE,
    ITEM_UNDEF,
    LIST,
    SET_CHOOSE_LOCAL_FALLBACK_TRIES,
    SET_CHOOSE_LOCAL_TRIES,
    SET_CHOOSE_TRIES,
    SET_CHOOSELEAF_STABLE,
    SET_CHOOSELEAF_TRIES,
    SET_CHOOSELEAF_VARY_R,
    STRAW,
    STRAW2,
    TAKE,
    TREE,
    UNIFORM,
    Bucket,
    CrushMap,
    WeightSet,
)
from ._ln_tables import LL_TBL, RH_LH_TBL
from .hashes import hash32_2, hash32_3, hash32_4

S64_MIN = -(1 << 63)
_U64 = (1 << 64) - 1


def crush_ln(xin: int) -> int:
    """2^44 * log2(xin + 1) in fixed point (mapper.c:226-268)."""
    x = xin + 1
    iexpon = 15
    if not (x & 0x18000):
        bits = 16 - x.bit_length()
        x <<= bits
        iexpon = 15 - bits
    index1 = (x >> 8) << 1
    rh = RH_LH_TBL[index1 - 256]
    lh = RH_LH_TBL[index1 + 1 - 256]
    xl64 = (x * rh) & _U64
    xl64 >>= 48
    index2 = xl64 & 0xFF
    lh = (lh + LL_TBL[index2]) >> 4
    return (iexpon << 44) + lh


def _div_s64(a: int, b: int) -> int:
    """C-style truncating signed 64-bit division."""
    q = abs(a) // abs(b)
    return -q if (a < 0) != (b < 0) else q


def _exponential_draw(x: int, y: int, z: int, weight: int) -> int:
    """Scaled exponential variate: ln(U)/weight, U ~ hash16 (mapper.c:312)."""
    u = hash32_3(x, y, z) & 0xFFFF
    ln = crush_ln(u) - 0x1000000000000
    return _div_s64(ln, weight)


class _PermWork:
    """Permutation state for one uniform bucket (mapper.c:51-109)."""

    __slots__ = ("perm_x", "perm_n", "perm")

    def __init__(self, size: int):
        self.perm_x = 0
        self.perm_n = 0
        self.perm = [0] * size


class Mapper:
    """Evaluates rules against a CrushMap for one input x at a time."""

    def __init__(self, crushmap: CrushMap):
        self.map = crushmap

    # -- bucket choose methods -------------------------------------------

    def _perm_choose(self, b: Bucket, work: dict, x: int, r: int) -> int:
        w = work.get(b.id)
        if w is None:
            w = work[b.id] = _PermWork(b.size)
        pr = r % b.size
        if w.perm_x != (x & 0xFFFFFFFF) or w.perm_n == 0:
            w.perm_x = x & 0xFFFFFFFF
            if pr == 0:
                s = hash32_3(x, b.id, 0) % b.size
                w.perm[0] = s
                w.perm_n = 0xFFFF  # marks the r=0 shortcut
                return b.items[s]
            w.perm = list(range(b.size))
            w.perm_n = 0
        elif w.perm_n == 0xFFFF:
            # expand the r=0 shortcut into a real partial permutation
            for i in range(1, b.size):
                w.perm[i] = i
            w.perm[w.perm[0]] = 0
            w.perm_n = 1
        while w.perm_n <= pr:
            p = w.perm_n
            if p < b.size - 1:
                i = hash32_3(x, b.id, p) % (b.size - p)
                if i:
                    w.perm[p + i], w.perm[p] = w.perm[p], w.perm[p + i]
            w.perm_n += 1
        return b.items[w.perm[pr]]

    def _list_choose(self, b: Bucket, x: int, r: int) -> int:
        for i in range(b.size - 1, -1, -1):
            w = hash32_4(x, b.items[i], r, b.id) & 0xFFFF
            w = (w * b.sum_weights[i]) >> 16
            if w < b.item_weights[i]:
                return b.items[i]
        return b.items[0]

    def _tree_choose(self, b: Bucket, x: int, r: int) -> int:
        n = len(b.node_weights) >> 1  # root
        while not (n & 1):
            w = b.node_weights[n]
            t = (hash32_4(x, n, r, b.id) * w) >> 32
            # descend left if the pick lands inside the left subtree
            h = _height(n)
            left = n - (1 << (h - 1))
            if t < b.node_weights[left]:
                n = left
            else:
                n = left + (1 << h)
        return b.items[n >> 1]

    def _straw_choose(self, b: Bucket, x: int, r: int) -> int:
        high, high_draw = 0, 0
        for i in range(b.size):
            draw = (hash32_3(x, b.items[i], r) & 0xFFFF) * b.straws[i]
            if i == 0 or draw > high_draw:
                high, high_draw = i, draw
        return b.items[high]

    def _straw2_choose(
        self, b: Bucket, x: int, r: int,
        arg: WeightSet | None, position: int,
    ) -> int:
        weights = b.item_weights
        ids = b.items
        if arg is not None:
            if arg.weight_sets:
                pos = min(position, len(arg.weight_sets) - 1)
                weights = arg.weight_sets[pos]
            if arg.ids is not None:
                ids = arg.ids
        high, high_draw = 0, 0
        for i in range(b.size):
            if weights[i]:
                draw = _exponential_draw(x, ids[i], r, weights[i])
            else:
                draw = S64_MIN
            if i == 0 or draw > high_draw:
                high, high_draw = i, draw
        return b.items[high]

    def _bucket_choose(
        self, b: Bucket, work: dict, x: int, r: int,
        arg: WeightSet | None, position: int,
    ) -> int:
        if b.alg == UNIFORM:
            return self._perm_choose(b, work, x, r)
        if b.alg == LIST:
            return self._list_choose(b, x, r)
        if b.alg == TREE:
            return self._tree_choose(b, x, r)
        if b.alg == STRAW:
            return self._straw_choose(b, x, r)
        if b.alg == STRAW2:
            return self._straw2_choose(b, x, r, arg, position)
        return b.items[0]

    # -- device reweight rejection (mapper.c:402-416) --------------------

    def _is_out(self, weights: list[int], item: int, x: int) -> bool:
        if item >= len(weights):
            return True
        w = weights[item]
        if w >= 0x10000:
            return False
        if w == 0:
            return True
        return (hash32_2(x, item) & 0xFFFF) >= w

    # -- depth-first choose with retries (mapper.c:438-626) --------------

    def _choose_firstn(
        self, bucket: Bucket, work: dict, weights: list[int],
        x: int, numrep: int, type: int,
        out: list[int], outpos: int, out_size: int,
        tries: int, recurse_tries: int,
        local_retries: int, local_fallback_retries: int,
        recurse_to_leaf: bool, vary_r: int, stable: int,
        out2: list[int] | None, parent_r: int,
        choose_args: dict[int, WeightSet] | None,
    ) -> int:
        m = self.map
        count = out_size
        rep = 0 if stable else outpos
        while rep < numrep and count > 0:
            ftotal = 0
            skip_rep = False
            retry_descent = True
            while retry_descent:
                retry_descent = False
                in_b = bucket
                flocal = 0
                retry_bucket = True
                while retry_bucket:
                    retry_bucket = False
                    collide = False
                    r = rep + parent_r + ftotal
                    if in_b.size == 0:
                        reject = True
                    else:
                        if (local_fallback_retries > 0
                                and flocal >= (in_b.size >> 1)
                                and flocal > local_fallback_retries):
                            item = self._perm_choose(in_b, work, x, r)
                        else:
                            item = self._bucket_choose(
                                in_b, work, x, r,
                                choose_args.get(in_b.id) if choose_args else None,
                                outpos)
                        if item >= m.max_devices:
                            skip_rep = True
                            break
                        itemtype = m.buckets[item].type if item < 0 else 0
                        if itemtype != type:
                            if item >= 0 or item not in m.buckets:
                                skip_rep = True
                                break
                            in_b = m.buckets[item]
                            retry_bucket = True
                            continue
                        for i in range(outpos):
                            if out[i] == item:
                                collide = True
                                break
                        reject = False
                        if not collide and recurse_to_leaf:
                            if item < 0:
                                sub_r = r >> (vary_r - 1) if vary_r else 0
                                got = self._choose_firstn(
                                    m.buckets[item], work, weights, x,
                                    1 if stable else outpos + 1, 0,
                                    out2, outpos, count,
                                    recurse_tries, 0,
                                    local_retries, local_fallback_retries,
                                    False, vary_r, stable, None, sub_r,
                                    choose_args)
                                if got <= outpos:
                                    reject = True  # didn't reach a leaf
                            else:
                                out2[outpos] = item
                        if not reject and not collide and itemtype == 0:
                            reject = self._is_out(weights, item, x)
                    if reject or collide:
                        ftotal += 1
                        flocal += 1
                        if collide and flocal <= local_retries:
                            retry_bucket = True
                        elif (local_fallback_retries > 0
                              and flocal <= in_b.size + local_fallback_retries):
                            retry_bucket = True
                        elif ftotal < tries:
                            retry_descent = True
                        else:
                            skip_rep = True
                        if not retry_bucket:
                            break
            if skip_rep:
                rep += 1
                continue
            out[outpos] = item
            outpos += 1
            count -= 1
            rep += 1
        return outpos

    # -- breadth-first positionally-stable choose (mapper.c:633-821) -----

    def _choose_indep(
        self, bucket: Bucket, work: dict, weights: list[int],
        x: int, left: int, numrep: int, type: int,
        out: list[int], outpos: int,
        tries: int, recurse_tries: int, recurse_to_leaf: bool,
        out2: list[int] | None, parent_r: int,
        choose_args: dict[int, WeightSet] | None,
    ) -> None:
        m = self.map
        endpos = outpos + left
        for rep in range(outpos, endpos):
            out[rep] = ITEM_UNDEF
            if out2 is not None:
                out2[rep] = ITEM_UNDEF
        ftotal = 0
        while left > 0 and ftotal < tries:
            for rep in range(outpos, endpos):
                if out[rep] != ITEM_UNDEF:
                    continue
                in_b = bucket
                while True:
                    r = rep + parent_r
                    if in_b.alg == UNIFORM and in_b.size % numrep == 0:
                        r += (numrep + 1) * ftotal
                    else:
                        r += numrep * ftotal
                    if in_b.size == 0:
                        break
                    item = self._bucket_choose(
                        in_b, work, x, r,
                        choose_args.get(in_b.id) if choose_args else None,
                        outpos)
                    if item >= m.max_devices:
                        out[rep] = ITEM_NONE
                        if out2 is not None:
                            out2[rep] = ITEM_NONE
                        left -= 1
                        break
                    itemtype = m.buckets[item].type if item < 0 else 0
                    if itemtype != type:
                        if item >= 0 or item not in m.buckets:
                            out[rep] = ITEM_NONE
                            if out2 is not None:
                                out2[rep] = ITEM_NONE
                            left -= 1
                            break
                        in_b = m.buckets[item]
                        continue
                    collide = False
                    for i in range(outpos, endpos):
                        if out[i] == item:
                            collide = True
                            break
                    if collide:
                        break
                    if recurse_to_leaf:
                        if item < 0:
                            self._choose_indep(
                                m.buckets[item], work, weights, x,
                                1, numrep, 0, out2, rep,
                                recurse_tries, 0, False, None, r,
                                choose_args)
                            if out2[rep] == ITEM_NONE:
                                break
                        elif out2 is not None:
                            out2[rep] = item
                    if itemtype == 0 and self._is_out(weights, item, x):
                        break
                    out[rep] = item
                    left -= 1
                    break
            ftotal += 1
        for rep in range(outpos, endpos):
            if out[rep] == ITEM_UNDEF:
                out[rep] = ITEM_NONE
            if out2 is not None and out2[rep] == ITEM_UNDEF:
                out2[rep] = ITEM_NONE

    # -- rule VM (mapper.c:878-1083) -------------------------------------

    def do_rule(
        self, ruleno: int, x: int, result_max: int,
        weights: list[int],
        choose_args: dict[int, WeightSet] | None = None,
    ) -> list[int]:
        """Map input x to a list of devices (may contain ITEM_NONE holes
        for indep/EC rules)."""
        m = self.map
        rule = m.rules.get(ruleno)
        if rule is None:
            return []
        t = m.tunables
        choose_tries = t.choose_total_tries + 1  # historical off-by-one
        choose_leaf_tries = 0
        choose_local_retries = t.choose_local_tries
        choose_local_fallback_retries = t.choose_local_fallback_tries
        vary_r = t.chooseleaf_vary_r
        stable = t.chooseleaf_stable

        work: dict = {}  # uniform-bucket permutation state, per call
        result: list[int] = []
        w: list[int] = [0] * result_max
        o: list[int] = [0] * result_max
        c: list[int] = [0] * result_max
        wsize = 0

        for op, arg1, arg2 in rule.steps:
            if op == TAKE:
                if (0 <= arg1 < m.max_devices) or arg1 in m.buckets:
                    w[0] = arg1
                    wsize = 1
            elif op == SET_CHOOSE_TRIES:
                if arg1 > 0:
                    choose_tries = arg1
            elif op == SET_CHOOSELEAF_TRIES:
                if arg1 > 0:
                    choose_leaf_tries = arg1
            elif op == SET_CHOOSE_LOCAL_TRIES:
                if arg1 >= 0:
                    choose_local_retries = arg1
            elif op == SET_CHOOSE_LOCAL_FALLBACK_TRIES:
                if arg1 >= 0:
                    choose_local_fallback_retries = arg1
            elif op == SET_CHOOSELEAF_VARY_R:
                if arg1 >= 0:
                    vary_r = arg1
            elif op == SET_CHOOSELEAF_STABLE:
                if arg1 >= 0:
                    stable = arg1
            elif op in (CHOOSE_FIRSTN, CHOOSE_INDEP,
                        CHOOSELEAF_FIRSTN, CHOOSELEAF_INDEP):
                if wsize == 0:
                    continue
                firstn = op in (CHOOSE_FIRSTN, CHOOSELEAF_FIRSTN)
                recurse_to_leaf = op in (CHOOSELEAF_FIRSTN, CHOOSELEAF_INDEP)
                osize = 0
                for i in range(wsize):
                    numrep = arg1
                    if numrep <= 0:
                        numrep += result_max
                        if numrep <= 0:
                            continue
                    bucket = m.buckets.get(w[i])
                    if bucket is None:
                        continue
                    # each take-item writes into a fresh window at o+osize
                    # (the C code passes pointer offsets; collision checks
                    # are local to the window)
                    avail = result_max - osize
                    o_win = [0] * avail
                    c_win = [0] * avail
                    if firstn:
                        if choose_leaf_tries:
                            recurse_tries = choose_leaf_tries
                        elif t.chooseleaf_descend_once:
                            recurse_tries = 1
                        else:
                            recurse_tries = choose_tries
                        n = self._choose_firstn(
                            bucket, work, weights, x, numrep, arg2,
                            o_win, 0, avail,
                            choose_tries, recurse_tries,
                            choose_local_retries,
                            choose_local_fallback_retries,
                            recurse_to_leaf, vary_r, stable,
                            c_win, 0, choose_args)
                    else:
                        n = min(numrep, avail)
                        self._choose_indep(
                            bucket, work, weights, x, n, numrep,
                            arg2, o_win, 0,
                            choose_tries,
                            choose_leaf_tries if choose_leaf_tries else 1,
                            recurse_to_leaf, c_win, 0, choose_args)
                    o[osize:osize + n] = o_win[:n]
                    c[osize:osize + n] = c_win[:n]
                    osize += n
                if recurse_to_leaf:
                    o[:osize] = c[:osize]
                w, o = o, w
                wsize = osize
            elif op == EMIT:
                for i in range(wsize):
                    if len(result) >= result_max:
                        break
                    result.append(w[i])
                wsize = 0
        return result


def _height(n: int) -> int:
    h = 0
    while (n & 1) == 0:
        h += 1
        n >>= 1
    return h
