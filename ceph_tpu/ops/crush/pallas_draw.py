"""Fused Pallas TPU kernel for the CRUSH bucket descent.

The XLA formulation of the f32 certainty draw (device.py `_straw2_choose`
/ `_descend`) materialises ~15 [L, S]-shaped f32/i32 temporaries per
draw in HBM — measured ~37 KB of HBM traffic per PG for the bulk-map
fast pass, which makes the 10M-PG remap bandwidth-bound (XLA cost
analysis: ~37 GB written per 1M-lane chunk).  This kernel runs the whole
multi-level descent — rjenkins hash, the f32 log approximation, the
per-item certainty intervals, winner select, child-bucket walk
(mapper.c:438-520 descent structure) — inside VMEM, so HBM traffic per
descend drops to the lane vectors themselves (~20 B/lane).

Layout: lanes ride the 128-wide lane axis in tiles of TL; bucket items
ride the sublane axis ([S_d, TL] per level).  Per-lane bucket rows are
fetched with one int8 one-hot MXU matmul per level from transposed limb
tables ([R_d, n_pos*B] int8, the same 8-bit-limb packing as
device.FlatMap) — gathers run at scalar rate on TPU, one-hot matmuls at
MXU rate, and integer matmuls are exact.

Semantics match device._descend with resolve=False bit-for-bit at the
*logic* level; the f32 draw values may differ across backends by FMA /
reassociation, which the doubled _G_DELTA headroom in the certainty
bound absorbs — an uncertain winner is flagged either way and settled
by the exact resolve pass, so end results stay bit-identical to the
host engine (verified by tests/test_crush_device.py on golden vectors).
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp

GW = 512           # lanes per sublane group (128-multiple)
TL = 8 * GW        # lanes per tile: 8 sublane rows of GW
_MAX_TABLE_BYTES = 6 << 20   # VMEM budget for the per-level limb tables
_S_BIG = 0x7FFF              # > any slot index; argmin-tiebreak sentinel


def pallas_enabled() -> bool:
    """Mosaic lowering needs a real TPU; tests force interpret mode via
    CEPH_TPU_PALLAS_INTERPRET=1 to cover the kernel logic on CPU."""
    if os.environ.get("CEPH_TPU_NO_PALLAS_CRUSH"):
        return False
    if os.environ.get("CEPH_TPU_PALLAS_INTERPRET"):
        return True
    return jax.default_backend() == "tpu"


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _limb_planes(vals: np.ndarray, n_limbs: int, offset: int = 0
                 ) -> np.ndarray:
    """[B, S] int -> [n_limbs*S, B] int8 limb planes (limb-major blocks,
    biased by -128), transposed for the [R, B] @ [B, TL] fetch."""
    v = vals.astype(np.int64) - offset
    assert (v >= 0).all() and (v < (1 << (8 * n_limbs))).all()
    planes = [(((v >> (8 * j)) & 0xFF) - 128).astype(np.int8)
              for j in range(n_limbs)]
    return np.concatenate([p.T for p in planes], axis=0)


def _unpack_rows(f, S: int, n_limbs: int, base: int, offset: int = 0):
    """[R, TL] i32 matmul result -> [S, TL] i32 from limb-plane rows
    starting at `base`."""
    acc = f[base:base + S, :] + 128
    for j in range(1, n_limbs):
        acc = acc + ((f[base + j * S:base + (j + 1) * S, :] + 128)
                     << (8 * j))
    if offset:
        acc = acc + offset
    return acc


class _LevelTables:
    """Static per-level fetch tables for one (fm, depth_sizes) pair."""

    def __init__(self, fm, depth_sizes):
        self.nl = nl = fm.nl_id
        self.dup = dup = 0 if fm.ids_equal_items else nl
        self.n_pos = n_pos = fm.n_pos
        self.B = B = fm.B
        self.tables = []
        nbytes = 0
        for S_d in depth_sizes:
            blocks = []
            ids = np.tile(fm._ids_np[:, :S_d], (n_pos, 1))
            blocks.append(_limb_planes(ids, nl, fm.id_offset))
            if dup:
                items = np.tile(fm._items_np[:, :S_d], (n_pos, 1))
                blocks.append(_limb_planes(items, nl, fm.id_offset))
            rb = fm._recipbits_np.reshape(n_pos * B, -1)[:, :S_d]
            blocks.append(_limb_planes(rb, 4))
            size = np.tile(fm._size_np[:, None], (n_pos, 1))
            blocks.append(_limb_planes(size, 2))
            tbl = np.concatenate(blocks, axis=0)
            nbytes += tbl.nbytes
            self.tables.append(tbl)
        # [4, B]: rows = [size limb0, size limb1, btype limb0, limb1]
        self.meta = np.concatenate(
            [_limb_planes(fm._size_np[:, None], 2),
             _limb_planes(fm._btype_np[:, None], 2)], axis=0)
        self.nbytes = nbytes + self.meta.nbytes

    def row_count(self, S_d: int) -> int:
        return (self.nl + self.dup + 4) * S_d + 2


def _hash_mix(a, b, c):
    u = np.uint32
    a = a - b; a = a - c; a = a ^ (c >> u(13))
    b = b - c; b = b - a; b = b ^ (a << u(8))
    c = c - a; c = c - b; c = c ^ (b >> u(13))
    a = a - b; a = a - c; a = a ^ (c >> u(12))
    b = b - c; b = b - a; b = b ^ (a << u(16))
    c = c - a; c = c - b; c = c ^ (b >> u(5))
    a = a - b; a = a - c; a = a ^ (c >> u(3))
    b = b - c; b = b - a; b = b ^ (a << u(10))
    c = c - a; c = c - b; c = c ^ (b >> u(15))
    return a, b, c


def _hash32_3(a, b, c, seed):
    u = np.uint32
    h = u(seed) ^ a ^ b ^ c
    x, y = u(231232), u(1232)
    a, b, h = _hash_mix(a, b, h)
    c, x, h = _hash_mix(c, x, h)
    y, a, h = _hash_mix(y, a, h)
    b, x, h = _hash_mix(b, x, h)
    y, c, h = _hash_mix(y, c, h)
    return h


def _g_poly(u, coef):
    """f32 approximation of 2^48 - crush_ln(u); mirrors device._g_f32."""
    x = (u + 1).astype(jnp.int32)
    xf = x.astype(jnp.float32)
    b = jax.lax.bitcast_convert_type(xf, jnp.int32)
    e = ((b >> 23) - 127).astype(jnp.float32)
    mm = jax.lax.bitcast_convert_type(
        (b & 0x7FFFFF) | 0x3F800000, jnp.float32) - np.float32(1.0)
    acc = jnp.full_like(mm, np.float32(coef[-1]))
    for c in coef[-2::-1]:
        acc = acc * mm + np.float32(c)
    return np.float32(2.0 ** 44) * ((np.float32(16.0) - e) - acc)


def make_descend_kernel(fm, depth_sizes: tuple, want_type: int):
    """Compiled fused descent: fn(x, r, bid, pos) -> (item, status) with
    x/r/bid/pos int32 [L] (L % TL == 0) and status bits
    ok=1 | perm=2 | flag=4.  Returns None when the map doesn't fit the
    kernel's budget (caller falls back to the XLA path)."""
    from jax.experimental import pallas as pl
    from . import device as dev
    from ...models.crushmap import ITEM_NONE

    lt = _LevelTables(fm, depth_sizes)
    if lt.nbytes > _MAX_TABLE_BYTES or lt.n_pos * lt.B > 4096:
        return None
    nl, dup, n_pos, B = lt.nl, lt.dup, lt.n_pos, lt.B
    max_devices = int(fm.max_devices)
    coef = dev._LOG2_COEF
    g_delta = float(dev._G_DELTA)
    eps_q = float(dev._EPS_Q)
    e_const = float(dev._E_CONST)
    big = float(3.0e38)
    seed = dev.HASH_SEED
    i8, i32, f32, u32 = jnp.int8, jnp.int32, jnp.float32, jnp.uint32
    c32, cf32, cu32 = np.int32, np.float32, np.uint32
    # keep tables as host numpy: make_descend_kernel is lazily reached
    # inside jit traces, where jnp.asarray would bind the constant to
    # the live trace and leak it into later traces (cf. FlatMap row
    # cache) — numpy inputs become ordinary jit constants instead
    tbls = [np.asarray(t) for t in lt.tables]
    meta_t = np.asarray(lt.meta)
    n_lvl = len(depth_sizes)

    # -- refine tables: crush_ln's own RH/LH/LL tables (mapper.c:226-268)
    # RH as three 16-bit limbs (for the exact 64-bit x2*rh product) and
    # f32(LH)/f32(LL) as bit-limbs.  The poly error bound is dominated
    # by the ln table's quantization noise (~2^30); evaluating the real
    # table in f32 brings the bound down to REF_DELTA ~ 2^25, settling
    # ~95% of poly-uncertain draws in-kernel instead of in the resolve
    # pass.
    rh_np = dev._RH_NP.astype(np.int64)                    # [129] <2^48
    lh_np = dev._LH_NP.astype(np.int64)
    ll_np = dev._LL_NP.astype(np.int64)
    rh16 = np.stack([(rh_np >> (16 * k)) & 0xFFFF
                     for k in range(3)], axis=0)           # [3, 129]
    lh_bits = lh_np.astype(np.float32).view(np.uint32).astype(np.int64)
    ll_bits = ll_np.astype(np.float32).view(np.uint32).astype(np.int64)
    refp_t = np.concatenate(
        [_limb_planes(rh16.T, 2),                          # rows 0..5
         _limb_planes(lh_bits[:, None], 4)], axis=0)       # rows 6..9
    refl_t = _limb_planes(ll_bits[:, None], 4)             # [4, 256]
    # error budget: f32 rounding of LH, LL (2^24 each at 2^48 scale),
    # their sum, and the final subtraction, plus floor slack — ~2^26;
    # doubled for margin
    REF_DELTA = float(2 ** 27)
    REF_EPS = float(2.0 ** -21)

    def refine(u, rf, refp_ref, refl_ref):
        """f32 evaluation of the EXACT crush_ln tables for one
        candidate: u [1,GW] i32 hash, rf [1,GW] f32 reciprocal.
        Returns q_ref with |q_ref - q_exact| <= REF_DELTA*rf +
        q*REF_EPS + const (mirrors neg_ln_mxu's structure,
        mapper.c:226-268)."""
        x = u + c32(1)
        bl = jnp.full(x.shape, c32(1), i32)
        for kbit in range(1, 17):
            bl = bl + (x >= c32(1 << kbit)).astype(i32)
        need = (x & c32(0x18000)) == 0
        bits = jnp.maximum(c32(16) - bl, c32(0))
        x2 = jnp.where(need, x << bits, x)
        iexp = jnp.where(need, c32(15) - bits, c32(15))
        p = (x2 >> 8) - c32(128)                     # [0, 128]
        iota_p = jax.lax.broadcasted_iota(i32, (129, GW), 0)
        ohp = (iota_p == p).astype(i8)
        fr = jax.lax.dot_general(
            refp_ref[...], ohp, (((1,), (0,)), ((), ())),
            preferred_element_type=i32)              # [10, GW]
        rh = _unpack_rows(fr, 3, 2, 0)               # [3, GW] 16b limbs
        lhf = jax.lax.bitcast_convert_type(
            _unpack_rows(fr, 1, 4, 6), f32)
        # exact bits 48..55 of x2*rh via 16-bit limb products (each
        # < 2^32: x2 <= 2^16, limbs <= 2^16-1)
        x2u = x2.astype(u32)
        t0 = x2u * rh[0:1, :].astype(u32)
        t1 = x2u * rh[1:2, :].astype(u32)
        t2 = x2u * rh[2:3, :].astype(u32)
        s1 = (t0 >> cu32(16)) + t1
        c1 = (s1 < t1).astype(u32)
        s2 = (s1 >> cu32(16)) + (c1 << cu32(16)) + t2
        i2x = ((s2 >> cu32(16)) & cu32(0xFF)).astype(i32)
        iota_l = jax.lax.broadcasted_iota(i32, (256, GW), 0)
        ohl = (iota_l == i2x).astype(i8)
        fl = jax.lax.dot_general(
            refl_ref[...], ohl, (((1,), (0,)), ((), ())),
            preferred_element_type=i32)              # [4, GW]
        llf = jax.lax.bitcast_convert_type(
            _unpack_rows(fl, 1, 4, 0), f32)
        neg = ((cf32(float(1 << 48))
                - iexp.astype(f32) * cf32(float(1 << 44)))
               - (lhf + llf) * cf32(1.0 / 16.0))
        return neg * rf

    def group(d, S_d, tbl_ref, meta_ref, refp_ref, refl_ref, xg, rg,
              posg, st):
        """One level advance for one GW-lane sublane group.
        xg/rg/posg [1, GW]; st = (cur, done, ok, perm, flag, item)."""
        cur, done, ok, perm, flag, item = st
        col = cur if n_pos == 1 else posg * c32(B) + cur
        iota_b = jax.lax.broadcasted_iota(i32, (n_pos * B, GW), 0)
        oh = (iota_b == col).astype(i8)
        f = jax.lax.dot_general(
            tbl_ref[...], oh, (((1,), (0,)), ((), ())),
            preferred_element_type=i32)            # [R_d, GW]
        ids = _unpack_rows(f, S_d, nl, 0, fm.id_offset)
        if dup:
            items_a = _unpack_rows(f, S_d, nl, nl * S_d, fm.id_offset)
        else:
            items_a = ids
        rbits = _unpack_rows(f, S_d, 4, (nl + dup) * S_d)
        recipf = jax.lax.bitcast_convert_type(rbits, f32)
        size = _unpack_rows(f, 1, 2, (nl + dup + 4) * S_d)   # [1, GW]
        iota_s = jax.lax.broadcasted_iota(i32, (S_d, GW), 0)
        valid = (iota_s < size) & (recipf > 0)
        u = (_hash32_3(xg, ids.astype(u32), rg, seed)
             & cu32(0xFFFF)).astype(i32)
        g = _g_poly(u, coef)
        q = jnp.where(valid, g * recipf, cf32(big))
        E = cf32(g_delta) * recipf + q * cf32(eps_q) + cf32(e_const)
        hi = jnp.where(valid, q + E, cf32(big))
        low = jnp.where(valid, q - E, cf32(big))
        min_hi = jnp.min(hi, axis=0, keepdims=True)
        contend = valid & (low <= min_hi)
        ncont = jnp.sum(contend.astype(i32), axis=0, keepdims=True,
                        dtype=i32)
        certain = ncont <= 1
        minq = jnp.min(q, axis=0, keepdims=True)
        i1 = jnp.min(jnp.where(q == minq, iota_s, c32(_S_BIG)),
                     axis=0, keepdims=True)
        winc = jnp.min(jnp.where(contend, iota_s, c32(_S_BIG)),
                       axis=0, keepdims=True)
        # refined top-3 resolution for uncertain draws: pick the three
        # smallest poly draws, re-evaluate them against the exact ln
        # tables (f32, REF_DELTA error), and accept when one candidate's
        # upper bound beats both others' lower bounds and no contender
        # lies outside the top-3.  Floor ties stay flagged (the exact
        # resolve pass settles slot tie-breaks).
        sel1 = iota_s == i1
        qm = jnp.where(sel1, cf32(big), q)
        minq2 = jnp.min(qm, axis=0, keepdims=True)
        i2 = jnp.min(jnp.where(qm == minq2, iota_s, c32(_S_BIG)),
                     axis=0, keepdims=True)
        sel2 = iota_s == i2
        qm2 = jnp.where(sel2, cf32(big), qm)
        minq3 = jnp.min(qm2, axis=0, keepdims=True)
        i3 = jnp.min(jnp.where(qm2 == minq3, iota_s, c32(_S_BIG)),
                     axis=0, keepdims=True)
        sel3 = iota_s == i3

        def pick_i(a, sel):
            return jnp.sum(jnp.where(sel, a, c32(0)), axis=0,
                           keepdims=True, dtype=i32)

        def pick_f(a, sel):
            return jnp.sum(jnp.where(sel, a, cf32(0.0)), axis=0,
                           keepdims=True)

        v2 = minq2 < cf32(big)
        v3 = minq3 < cf32(big)
        qr1 = refine(pick_i(u, sel1), pick_f(recipf, sel1),
                     refp_ref, refl_ref)
        qr2 = refine(pick_i(u, sel2), pick_f(recipf, sel2),
                     refp_ref, refl_ref)
        qr3 = refine(pick_i(u, sel3), pick_f(recipf, sel3),
                     refp_ref, refl_ref)

        def bounds(qr, rfk, vk):
            Ek = (cf32(REF_DELTA) * rfk + qr * cf32(REF_EPS)
                  + cf32(e_const))
            return (jnp.where(vk, qr + Ek, cf32(big)),
                    jnp.where(vk, qr - Ek, cf32(big)))

        ub1, lb1 = bounds(qr1, pick_f(recipf, sel1),
                          jnp.ones_like(v2))
        ub2, lb2 = bounds(qr2, pick_f(recipf, sel2), v2)
        ub3, lb3 = bounds(qr3, pick_f(recipf, sel3), v3)
        w1 = (ub1 < lb2) & (ub1 < lb3)
        w2 = (ub2 < lb1) & (ub2 < lb3)
        w3 = (ub3 < lb1) & (ub3 < lb2)
        outside = contend & ~(sel1 | sel2 | sel3)
        n_out = jnp.sum(outside.astype(i32), axis=0, keepdims=True,
                        dtype=i32)
        ref_ok = (w1 | w2 | w3) & (n_out == 0)
        ref_win = jnp.where(w1, i1, jnp.where(w2, i2, i3))
        win = jnp.where(ncont == 1, winc,
                        jnp.where(ref_ok, ref_win, i1))
        chosen = jnp.sum(jnp.where(iota_s == win, items_a, c32(0)),
                         axis=0, keepdims=True, dtype=i32)
        if d == 0:
            done = size == 0            # empty start bucket: retryable
        flag = flag | ((~done) & (~certain) & (~ref_ok))
        is_bucket = chosen < 0
        cbid = jnp.where(is_bucket, c32(-1) - chosen, c32(0))
        iota_mb = jax.lax.broadcasted_iota(i32, (B, GW), 0)
        ohc = (iota_mb == cbid).astype(i8)
        fm2 = jax.lax.dot_general(
            meta_ref[...], ohc, (((1,), (0,)), ((), ())),
            preferred_element_type=i32)            # [4, GW]
        csize = _unpack_rows(fm2, 1, 2, 0)
        cbtype = _unpack_rows(fm2, 1, 2, 2)
        ctype = jnp.where(is_bucket, cbtype, c32(0))
        oob = (~is_bucket) & (chosen >= c32(max_devices))
        reach = (~done) & (ctype == c32(want_type)) & (~oob)
        wrongdev = (~done) & (~reach) & ((~is_bucket) | oob)
        empty_next = (~done) & (~reach) & is_bucket & (csize == 0)
        item = jnp.where(reach, chosen, item)
        ok = ok | reach
        perm = perm | wrongdev
        done = done | reach | wrongdev | empty_next
        cur = jnp.where((~done) & is_bucket, cbid, cur)
        return cur, done, ok, perm, flag, item

    def kern(x_ref, r_ref, bid_ref, pos_ref, *refs):
        tbl_refs = refs[:n_lvl]
        meta_ref = refs[n_lvl]
        refp_ref, refl_ref = refs[n_lvl + 1], refs[n_lvl + 2]
        item_ref, status_ref = refs[n_lvl + 3], refs[n_lvl + 4]
        x = x_ref[...].astype(u32)                  # [8, GW]
        r = r_ref[...].astype(u32)
        bid = bid_ref[...]
        pos = (jnp.minimum(pos_ref[...], c32(n_pos - 1))
               if n_pos > 1 else bid)
        z = jnp.zeros((1, GW), jnp.bool_)
        states = [
            (bid[s:s + 1, :], z, z, z, z,
             jnp.full((1, GW), ITEM_NONE, i32))
            for s in range(8)
        ]
        for d, S_d in enumerate(depth_sizes):
            for s in range(8):
                states[s] = group(d, S_d, tbl_refs[d], meta_ref,
                                  refp_ref, refl_ref,
                                  x[s:s + 1, :], r[s:s + 1, :],
                                  pos[s:s + 1, :], states[s])
        item_ref[...] = jnp.concatenate([st[5] for st in states],
                                        axis=0)
        status_ref[...] = jnp.concatenate(
            [st[2].astype(i32) | (st[3].astype(i32) << 1)
             | (st[4].astype(i32) << 2) for st in states], axis=0)

    interp = _interpret()

    @jax.jit
    def run(x, r, bid, pos):
        L = x.shape[0]
        G = L // TL
        W = L // 8
        # index maps must yield int32 — under x64 plain ints trace as
        # i64, which mosaic cannot legalize (cf. ec/kernels.py)
        z2 = lambda i: (jnp.int32(0), jnp.int32(0))  # noqa: E731
        shp = jax.ShapeDtypeStruct((8, W), jnp.int32)
        lane = pl.BlockSpec((8, GW),
                            lambda i: (jnp.int32(0), jnp.int32(i)))
        full = [pl.BlockSpec(t.shape, z2) for t in tbls]
        mspec = pl.BlockSpec(meta_t.shape, z2)
        rpspec = pl.BlockSpec(refp_t.shape, z2)
        rlspec = pl.BlockSpec(refl_t.shape, z2)
        item, status = pl.pallas_call(
            kern,
            grid=(G,),
            in_specs=[lane, lane, lane, lane] + full
                     + [mspec, rpspec, rlspec],
            out_specs=(lane, lane),
            out_shape=(shp, shp),
            interpret=interp,
        )(x.reshape(8, W).astype(jnp.int32),
          r.reshape(8, W).astype(jnp.int32),
          bid.reshape(8, W).astype(jnp.int32),
          pos.reshape(8, W).astype(jnp.int32),
          *tbls, meta_t, refp_t, refl_t)
        return item.reshape(L), status.reshape(L)

    return run


def make_post_kernel(D: int, S: int, can_shift: bool):
    """Fused post-CRUSH pass (no primary-affinity form): up-filter
    against the exists&up bit per device + stable compaction + primary
    pick (OSDMap.cc:2626-2744) as one kernel over [L] lanes.

    Returns fn(raw [L, S] i32, keep [D] bool) -> (up [L, S] i32,
    prim [L] i32); the affinity path stays on the XLA `_post_process`.
    """
    from jax.experimental import pallas as pl
    from ...models.crushmap import ITEM_NONE

    HI = -(-D // 16)
    i8, i32 = jnp.int8, jnp.int32
    c32 = np.int32
    interp = _interpret()

    def kern(kp_ref, *refs):
        raw_refs = refs[:S]
        up_refs = refs[S:2 * S]
        prim_ref = refs[2 * S]
        iota_hi = jax.lax.broadcasted_iota(i32, (HI, GW), 0)
        iota_16 = jax.lax.broadcasted_iota(i32, (16, GW), 0)
        for s in range(8):
            rows = [r_ref[s:s + 1, :] for r_ref in raw_refs]
            keeps = []
            for rj in rows:
                idx = jnp.clip(rj, c32(0), c32(D - 1))
                oh = (iota_hi == (idx >> 4)).astype(i8)
                kf = jax.lax.dot_general(
                    kp_ref[...], oh, (((1,), (0,)), ((), ())),
                    preferred_element_type=i32)       # [16, GW]
                klo = jnp.sum(
                    jnp.where(iota_16 == (idx & 15), kf, c32(0)),
                    axis=0, keepdims=True, dtype=i32) + 128
                keeps.append((rj != c32(ITEM_NONE)) & (rj < c32(D))
                             & (klo > 0))
            if can_shift:
                ups = [jnp.full((1, GW), ITEM_NONE, i32)
                       for _ in range(S)]
                cnt = jnp.zeros((1, GW), i32)
                for j in range(S):
                    for t in range(j + 1):
                        put = keeps[j] & (cnt == c32(t))
                        ups[t] = jnp.where(put, rows[j], ups[t])
                    cnt = cnt + keeps[j].astype(i32)
            else:
                ups = [jnp.where(keeps[j], rows[j], c32(ITEM_NONE))
                       for j in range(S)]
            prim = jnp.full((1, GW), c32(-1), i32)
            for j in range(S - 1, -1, -1):
                prim = jnp.where(ups[j] != c32(ITEM_NONE), ups[j], prim)
            for j in range(S):
                up_refs[j][s:s + 1, :] = ups[j]
            prim_ref[s:s + 1, :] = prim

    @jax.jit
    def run(raw, keep):
        L = raw.shape[0]
        G = L // TL
        W = L // 8
        kp = ((keep.astype(jnp.int32) - 128).astype(jnp.int8))
        kp = jnp.pad(kp, (0, HI * 16 - D)).reshape(HI, 16).T
        z2 = lambda i: (jnp.int32(0), jnp.int32(0))  # noqa: E731
        lane = pl.BlockSpec((8, GW),
                            lambda i: (jnp.int32(0), jnp.int32(i)))
        shp = jax.ShapeDtypeStruct((8, W), jnp.int32)
        cols = [raw[:, j].reshape(8, W) for j in range(S)]
        outs = pl.pallas_call(
            kern,
            grid=(G,),
            in_specs=[pl.BlockSpec((16, HI), z2)] + [lane] * S,
            out_specs=tuple([lane] * S + [lane]),
            out_shape=tuple([shp] * S + [shp]),
            interpret=interp,
        )(kp, *cols)
        up = jnp.stack([o.reshape(L) for o in outs[:S]], axis=1)
        return up, outs[S].reshape(L)

    return run


def make_hitscan_kernel(D: int, S: int):
    """hit[l] = any slot of raw[l] holds an OSD in the changed set —
    the incremental-remap affected-lane scan, as one fused pass over
    the stored raw rows.  Returns fn(raw [L,S] i32, changed [D] bool)
    -> hit [L] bool."""
    from jax.experimental import pallas as pl
    from ...models.crushmap import ITEM_NONE

    HI = -(-D // 16)
    i8, i32 = jnp.int8, jnp.int32
    c32 = np.int32
    interp = _interpret()

    def kern(cp_ref, *refs):
        raw_refs = refs[:S]
        hit_ref = refs[S]
        iota_hi = jax.lax.broadcasted_iota(i32, (HI, GW), 0)
        iota_16 = jax.lax.broadcasted_iota(i32, (16, GW), 0)
        for s in range(8):
            acc = jnp.zeros((1, GW), jnp.bool_)
            for r_ref in raw_refs:
                rj = r_ref[s:s + 1, :]
                idx = jnp.clip(rj, c32(0), c32(D - 1))
                oh = (iota_hi == (idx >> 4)).astype(i8)
                kf = jax.lax.dot_general(
                    cp_ref[...], oh, (((1,), (0,)), ((), ())),
                    preferred_element_type=i32)       # [16, GW]
                klo = jnp.sum(
                    jnp.where(iota_16 == (idx & 15), kf, c32(0)),
                    axis=0, keepdims=True, dtype=i32) + 128
                acc = acc | ((rj != c32(ITEM_NONE)) & (rj < c32(D))
                             & (klo > 0))
            hit_ref[s:s + 1, :] = acc.astype(i32)

    @jax.jit
    def run(raw, changed):
        L = raw.shape[0]
        G = L // TL
        W = L // 8
        cp = ((changed.astype(jnp.int32) - 128).astype(jnp.int8))
        cp = jnp.pad(cp, (0, HI * 16 - D)).reshape(HI, 16).T
        z2 = lambda i: (jnp.int32(0), jnp.int32(0))  # noqa: E731
        lane = pl.BlockSpec((8, GW),
                            lambda i: (jnp.int32(0), jnp.int32(i)))
        cols = [raw[:, j].reshape(8, W) for j in range(S)]
        out = pl.pallas_call(
            kern,
            grid=(G,),
            in_specs=[pl.BlockSpec((16, HI), z2)] + [lane] * S,
            out_specs=lane,
            out_shape=jax.ShapeDtypeStruct((8, W), jnp.int32),
            interpret=interp,
        )(cp, *cols)
        return out.reshape(L) != 0

    return run


def make_rowcompact_kernel(n_lanes: int, row: int, kt: int,
                           pg_num: int):
    """Stream compaction of a sparse boolean mask without cumsum or
    dynamic stores — the jnp.nonzero replacement for the incremental
    remap's affected-lane gather (XLA's 10M-lane nonzero costs ~0.9s
    on this platform; see BENCH notes).

    The mask is viewed as NR = n_lanes/row row groups, 8 groups per
    grid step.  Per group, an MXU triangular-matmul computes hit
    positions (a block-diagonal strict-lower matrix keeps the prefix
    inside each group), a one-hot selection matrix compacts the hit
    lane indices into KT fixed slots (two bf16 limb matmuls reassemble
    indices exactly — single-term sums, so bf16 is lossless), and a
    group-membership matmul folds sublane partials per group.  All
    reads and writes are static blocks: out[g, j] = index of the j-th
    hit in group g, valid[g, j] = j < count(g) and index < pg_num.
    Pad slots carry the group base lane (a real, harmless duplicate
    for the resolve gather/scatter downstream).  Rows with count > KT
    overflow — detected via the cnt output's max, never silent.

    Returns fn(hit [n_lanes] bool) ->
      (idx [NR*kt] int32, valid [NR*kt] bool, cnt [NR] int32).
    """
    from jax.experimental import pallas as pl

    if n_lanes % (8 * row) or row % 128 or kt % 128:
        raise ValueError("rowcompact: n_lanes %d / row %d / kt %d "
                         "misaligned" % (n_lanes, row, kt))
    r2 = row // 128          # sublane rows per group
    s8 = 8 * r2              # sublane rows per grid step (8 groups)
    nr = n_lanes // row
    interp = _interpret()
    i32 = jnp.int32
    f32 = jnp.float32
    bf16 = jnp.bfloat16

    # U[j, i] = 1 for j <= i: h @ U = inclusive prefix along lanes
    U128 = np.triu(np.ones((128, 128), np.float32))
    # block-diagonal strict-lower: exclusive prefix over sublane rows
    # WITHIN each group of r2 rows
    LxB = np.zeros((s8, s8), np.float32)
    for g in range(8):
        LxB[g * r2:(g + 1) * r2, g * r2:(g + 1) * r2] = \
            np.tril(np.ones((r2, r2)), k=-1)
    # group membership: G[g, q] = 1 iff sublane row q is in group g
    Gm = np.zeros((8, s8), np.float32)
    for g in range(8):
        Gm[g, g * r2:(g + 1) * r2] = 1.0

    def kern(h_ref, u_ref, lx_ref, gm_ref, idx_ref, val_ref,
             cnt_ref):
        step = pl.program_id(0)
        h = h_ref[...].astype(f32)                       # (s8, 128)
        # hits in the padded lane region [pg_num, n_lanes) must not
        # occupy slots or counts (they would inflate rowmax and waste
        # settle work); mask them at the source
        glane = (jax.lax.broadcasted_iota(i32, (s8, 128), 0)
                 + step * np.int32(s8)) * np.int32(128) \
            + jax.lax.broadcasted_iota(i32, (s8, 128), 1)
        h = jnp.where(glane < np.int32(pg_num), h, 0.0)
        hb = h > 0.0
        p1 = jax.lax.dot_general(
            h, u_ref[...], (((1,), (0,)), ((), ())),
            preferred_element_type=f32)                  # (s8, 128)
        rsum = jnp.broadcast_to(p1[:, 127:128], (s8, 128))
        roff = jax.lax.dot_general(
            lx_ref[...], rsum, (((1,), (0,)), ((), ())),
            preferred_element_type=f32)                  # (s8, 128)
        roffv = roff[:, 0:1]                             # (s8, 1)
        rsumv = p1[:, 127:128]                           # (s8, 1)
        totals = jax.lax.dot_general(
            gm_ref[...], rsum, (((1,), (0,)), ((), ())),
            preferred_element_type=f32)[:, 0:1]          # (8, 1)
        # D[r, jr] = lane of the (jr+1)-th hit in sublane row r (a row
        # of 128 lanes holds at most 128 hits, so 128 columns always
        # suffice); built as 128 masked lane-reductions — single-term
        # sums, exact in f32
        lane_f = jax.lax.broadcasted_iota(
            i32, (s8, 128), 1).astype(f32)
        cols = [jnp.sum(jnp.where((p1 - 1.0 == np.float32(jr)) & hb,
                                  lane_f, 0.0),
                        axis=1, keepdims=True)
                for jr in range(128)]
        D = jnp.concatenate(cols, axis=1)                # (s8, 128)
        if kt > 128:
            D = jnp.concatenate(
                [D, jnp.zeros((s8, kt - 128), f32)], axis=1)
        # place row r's hits at group slots [roff[r], roff[r]+rsum[r]):
        # a per-row roll by roff[r], decomposed into static
        # conditional rolls (Mosaic has no per-row dynamic shift);
        # wrapped-around junk lands outside the row's slot interval
        # and is masked by rowsel (capacity overflow is caught via
        # cnt > kt, never silent)
        roffi = roffv.astype(i32)
        sh = D
        b = 1
        while b < kt:
            cond = ((roffi // np.int32(b)) % np.int32(2)) == 1
            sh = jnp.where(cond, jnp.roll(sh, b, axis=1), sh)
            b *= 2
        slot_f = jax.lax.broadcasted_iota(
            i32, (s8, kt), 1).astype(f32)
        rowsel = (slot_f >= roffv) & (slot_f < roffv + rsumv)
        sub_f = (jax.lax.broadcasted_iota(i32, (s8, kt), 0)
                 % np.int32(r2)).astype(f32)
        # fold sublane and lane components through SEPARATE matmuls:
        # the MXU's default precision multiplies in bf16, which is
        # only exact below 256 — sub (< r2) and lane (< 128) each
        # qualify, their 128-scaled sum would not
        sub_m = jnp.where(rowsel, sub_f, 0.0)
        lane_m = jnp.where(rowsel, sh, 0.0)
        fold = lambda x: jax.lax.dot_general(  # noqa: E731
            gm_ref[...], x, (((1,), (0,)), ((), ())),
            preferred_element_type=f32)
        gbase = (step * np.int32(8)
                 + jax.lax.broadcasted_iota(i32, (8, kt), 0)) \
            * np.int32(row)
        idx = (fold(sub_m).astype(i32) * np.int32(128)
               + fold(lane_m).astype(i32) + gbase)       # (8, kt)
        slot8 = jax.lax.broadcasted_iota(
            i32, (8, kt), 1).astype(f32)
        valid = ((slot8 < totals)
                 & (idx < np.int32(pg_num))).astype(i32)
        idx_ref[...] = idx
        val_ref[...] = valid
        cnt_ref[...] = jnp.broadcast_to(totals.astype(i32), (8, 128))

    @jax.jit
    def run(hit):
        h2 = hit.astype(i32).reshape(n_lanes // 128, 128)
        z2 = lambda i: (i32(0), i32(0))  # noqa: E731
        o8 = lambda i: (i32(i), i32(0))  # noqa: E731
        idx, val, cnt = pl.pallas_call(
            kern,
            grid=(nr // 8,),
            in_specs=[
                pl.BlockSpec((s8, 128), o8),
                pl.BlockSpec((128, 128), z2),
                pl.BlockSpec((s8, s8), z2),
                pl.BlockSpec((8, s8), z2),
            ],
            out_specs=[
                pl.BlockSpec((8, kt), o8),
                pl.BlockSpec((8, kt), o8),
                pl.BlockSpec((8, 128), o8),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((nr, kt), jnp.int32),
                jax.ShapeDtypeStruct((nr, kt), jnp.int32),
                jax.ShapeDtypeStruct((nr, 128), jnp.int32),
            ],
            interpret=interp,
        )(h2, jnp.asarray(U128), jnp.asarray(LxB), jnp.asarray(Gm))
        return (idx.reshape(-1), val.reshape(-1) != 0, cnt[:, 0])

    return run
