"""Fused Pallas TPU kernel for the CRUSH bucket descent.

The XLA formulation of the f32 certainty draw (device.py `_straw2_choose`
/ `_descend`) materialises ~15 [L, S]-shaped f32/i32 temporaries per
draw in HBM — measured ~37 KB of HBM traffic per PG for the bulk-map
fast pass, which makes the 10M-PG remap bandwidth-bound (XLA cost
analysis: ~37 GB written per 1M-lane chunk).  This kernel runs the whole
multi-level descent — rjenkins hash, the f32 log approximation, the
per-item certainty intervals, winner select, child-bucket walk
(mapper.c:438-520 descent structure) — inside VMEM, so HBM traffic per
descend drops to the lane vectors themselves (~20 B/lane).

Layout: lanes ride the 128-wide lane axis in tiles of TL; bucket items
ride the sublane axis ([S_d, TL] per level).  Per-lane bucket rows are
fetched with one int8 one-hot MXU matmul per level from transposed limb
tables ([R_d, n_pos*B] int8, the same 8-bit-limb packing as
device.FlatMap) — gathers run at scalar rate on TPU, one-hot matmuls at
MXU rate, and integer matmuls are exact.

Semantics match device._descend with resolve=False bit-for-bit at the
*logic* level; the f32 draw values may differ across backends by FMA /
reassociation, which the doubled _G_DELTA headroom in the certainty
bound absorbs — an uncertain winner is flagged either way and settled
by the exact resolve pass, so end results stay bit-identical to the
host engine (verified by tests/test_crush_device.py on golden vectors).
"""

from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp

GW = 512           # lanes per sublane group (128-multiple)
TL = 8 * GW        # lanes per tile: 8 sublane rows of GW
_MAX_TABLE_BYTES = 6 << 20   # VMEM budget for the per-level limb tables
_S_BIG = 0x7FFF              # > any slot index; argmin-tiebreak sentinel


def pallas_enabled() -> bool:
    """Mosaic lowering needs a real TPU; tests force interpret mode via
    CEPH_TPU_PALLAS_INTERPRET=1 to cover the kernel logic on CPU."""
    if os.environ.get("CEPH_TPU_NO_PALLAS_CRUSH"):
        return False
    if os.environ.get("CEPH_TPU_PALLAS_INTERPRET"):
        return True
    return jax.default_backend() == "tpu"


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _limb_planes(vals: np.ndarray, n_limbs: int, offset: int = 0
                 ) -> np.ndarray:
    """[B, S] int -> [n_limbs*S, B] int8 limb planes (limb-major blocks,
    biased by -128), transposed for the [R, B] @ [B, TL] fetch."""
    v = vals.astype(np.int64) - offset
    assert (v >= 0).all() and (v < (1 << (8 * n_limbs))).all()
    planes = [(((v >> (8 * j)) & 0xFF) - 128).astype(np.int8)
              for j in range(n_limbs)]
    return np.concatenate([p.T for p in planes], axis=0)


def _unpack_rows(f, S: int, n_limbs: int, base: int, offset: int = 0):
    """[R, TL] i32 matmul result -> [S, TL] i32 from limb-plane rows
    starting at `base`."""
    acc = f[base:base + S, :] + 128
    for j in range(1, n_limbs):
        acc = acc + ((f[base + j * S:base + (j + 1) * S, :] + 128)
                     << (8 * j))
    if offset:
        acc = acc + offset
    return acc


class _LevelTables:
    """Static per-level fetch tables for one (fm, depth_sizes) pair."""

    def __init__(self, fm, depth_sizes):
        self.nl = nl = fm.nl_id
        self.dup = dup = 0 if fm.ids_equal_items else nl
        self.n_pos = n_pos = fm.n_pos
        self.B = B = fm.B
        self.tables = []
        nbytes = 0
        for S_d in depth_sizes:
            blocks = []
            ids = np.tile(fm._ids_np[:, :S_d], (n_pos, 1))
            blocks.append(_limb_planes(ids, nl, fm.id_offset))
            if dup:
                items = np.tile(fm._items_np[:, :S_d], (n_pos, 1))
                blocks.append(_limb_planes(items, nl, fm.id_offset))
            rb = fm._recipbits_np.reshape(n_pos * B, -1)[:, :S_d]
            blocks.append(_limb_planes(rb, 3))
            size = np.tile(fm._size_np[:, None], (n_pos, 1))
            blocks.append(_limb_planes(size, 2))
            tbl = np.concatenate(blocks, axis=0)
            nbytes += tbl.nbytes
            self.tables.append(tbl)
        # [4, B]: rows = [size limb0, size limb1, btype limb0, limb1]
        self.meta = np.concatenate(
            [_limb_planes(fm._size_np[:, None], 2),
             _limb_planes(fm._btype_np[:, None], 2)], axis=0)
        self.nbytes = nbytes + self.meta.nbytes

    def row_count(self, S_d: int) -> int:
        return (self.nl + self.dup + 3) * S_d + 2


def _hash_mix(a, b, c):
    u = np.uint32
    a = a - b; a = a - c; a = a ^ (c >> u(13))
    b = b - c; b = b - a; b = b ^ (a << u(8))
    c = c - a; c = c - b; c = c ^ (b >> u(13))
    a = a - b; a = a - c; a = a ^ (c >> u(12))
    b = b - c; b = b - a; b = b ^ (a << u(16))
    c = c - a; c = c - b; c = c ^ (b >> u(5))
    a = a - b; a = a - c; a = a ^ (c >> u(3))
    b = b - c; b = b - a; b = b ^ (a << u(10))
    c = c - a; c = c - b; c = c ^ (b >> u(15))
    return a, b, c


def _hash32_3(a, b, c, seed):
    u = np.uint32
    h = u(seed) ^ a ^ b ^ c
    x, y = u(231232), u(1232)
    a, b, h = _hash_mix(a, b, h)
    c, x, h = _hash_mix(c, x, h)
    y, a, h = _hash_mix(y, a, h)
    b, x, h = _hash_mix(b, x, h)
    y, c, h = _hash_mix(y, c, h)
    return h


def _g_poly(u, coef):
    """f32 approximation of 2^48 - crush_ln(u); mirrors device._g_f32."""
    x = (u + 1).astype(jnp.int32)
    xf = x.astype(jnp.float32)
    b = jax.lax.bitcast_convert_type(xf, jnp.int32)
    e = ((b >> 23) - 127).astype(jnp.float32)
    mm = jax.lax.bitcast_convert_type(
        (b & 0x7FFFFF) | 0x3F800000, jnp.float32) - np.float32(1.0)
    acc = jnp.full_like(mm, np.float32(coef[-1]))
    for c in coef[-2::-1]:
        acc = acc * mm + np.float32(c)
    return np.float32(2.0 ** 44) * ((np.float32(16.0) - e) - acc)


def make_descend_kernel(fm, depth_sizes: tuple, want_type: int):
    """Compiled fused descent: fn(x, r, bid, pos) -> (item, status) with
    x/r/bid/pos int32 [L] (L % TL == 0) and status bits
    ok=1 | perm=2 | flag=4.  Returns None when the map doesn't fit the
    kernel's budget (caller falls back to the XLA path)."""
    from jax.experimental import pallas as pl
    from . import device as dev
    from ...models.crushmap import ITEM_NONE

    lt = _LevelTables(fm, depth_sizes)
    if lt.nbytes > _MAX_TABLE_BYTES or lt.n_pos * lt.B > 4096:
        return None
    nl, dup, n_pos, B = lt.nl, lt.dup, lt.n_pos, lt.B
    max_devices = int(fm.max_devices)
    coef = dev._LOG2_COEF
    g_delta = float(dev._G_DELTA)
    eps_q = float(dev._EPS_Q)
    e_const = float(dev._E_CONST)
    big = float(3.0e38)
    seed = dev.HASH_SEED
    i8, i32, f32, u32 = jnp.int8, jnp.int32, jnp.float32, jnp.uint32
    c32, cf32, cu32 = np.int32, np.float32, np.uint32
    # keep tables as host numpy: make_descend_kernel is lazily reached
    # inside jit traces, where jnp.asarray would bind the constant to
    # the live trace and leak it into later traces (cf. FlatMap row
    # cache) — numpy inputs become ordinary jit constants instead
    tbls = [np.asarray(t) for t in lt.tables]
    meta_t = np.asarray(lt.meta)
    n_lvl = len(depth_sizes)

    def group(d, S_d, tbl_ref, meta_ref, xg, rg, posg, st):
        """One level advance for one GW-lane sublane group.
        xg/rg/posg [1, GW]; st = (cur, done, ok, perm, flag, item)."""
        cur, done, ok, perm, flag, item = st
        col = cur if n_pos == 1 else posg * c32(B) + cur
        iota_b = jax.lax.broadcasted_iota(i32, (n_pos * B, GW), 0)
        oh = (iota_b == col).astype(i8)
        f = jax.lax.dot_general(
            tbl_ref[...], oh, (((1,), (0,)), ((), ())),
            preferred_element_type=i32)            # [R_d, GW]
        ids = _unpack_rows(f, S_d, nl, 0, fm.id_offset)
        if dup:
            items_a = _unpack_rows(f, S_d, nl, nl * S_d, fm.id_offset)
        else:
            items_a = ids
        rbits = _unpack_rows(f, S_d, 3, (nl + dup) * S_d)
        recipf = jax.lax.bitcast_convert_type(rbits << 8, f32)
        size = _unpack_rows(f, 1, 2, (nl + dup + 3) * S_d)   # [1, GW]
        iota_s = jax.lax.broadcasted_iota(i32, (S_d, GW), 0)
        valid = (iota_s < size) & (recipf > 0)
        u = (_hash32_3(xg, ids.astype(u32), rg, seed)
             & cu32(0xFFFF)).astype(i32)
        g = _g_poly(u, coef)
        q = jnp.where(valid, g * recipf, cf32(big))
        E = cf32(g_delta) * recipf + q * cf32(eps_q) + cf32(e_const)
        hi = jnp.where(valid, q + E, cf32(big))
        low = jnp.where(valid, q - E, cf32(big))
        min_hi = jnp.min(hi, axis=0, keepdims=True)
        contend = valid & (low <= min_hi)
        ncont = jnp.sum(contend.astype(i32), axis=0, keepdims=True,
                        dtype=i32)
        certain = ncont <= 1
        minq = jnp.min(q, axis=0, keepdims=True)
        i1 = jnp.min(jnp.where(q == minq, iota_s, c32(_S_BIG)),
                     axis=0, keepdims=True)
        winc = jnp.min(jnp.where(contend, iota_s, c32(_S_BIG)),
                       axis=0, keepdims=True)
        win = jnp.where(ncont == 1, winc, i1)
        chosen = jnp.sum(jnp.where(iota_s == win, items_a, c32(0)),
                         axis=0, keepdims=True, dtype=i32)
        if d == 0:
            done = size == 0            # empty start bucket: retryable
        flag = flag | ((~done) & (~certain))
        is_bucket = chosen < 0
        cbid = jnp.where(is_bucket, c32(-1) - chosen, c32(0))
        iota_mb = jax.lax.broadcasted_iota(i32, (B, GW), 0)
        ohc = (iota_mb == cbid).astype(i8)
        fm2 = jax.lax.dot_general(
            meta_ref[...], ohc, (((1,), (0,)), ((), ())),
            preferred_element_type=i32)            # [4, GW]
        csize = _unpack_rows(fm2, 1, 2, 0)
        cbtype = _unpack_rows(fm2, 1, 2, 2)
        ctype = jnp.where(is_bucket, cbtype, c32(0))
        oob = (~is_bucket) & (chosen >= c32(max_devices))
        reach = (~done) & (ctype == c32(want_type)) & (~oob)
        wrongdev = (~done) & (~reach) & ((~is_bucket) | oob)
        empty_next = (~done) & (~reach) & is_bucket & (csize == 0)
        item = jnp.where(reach, chosen, item)
        ok = ok | reach
        perm = perm | wrongdev
        done = done | reach | wrongdev | empty_next
        cur = jnp.where((~done) & is_bucket, cbid, cur)
        return cur, done, ok, perm, flag, item

    def kern(x_ref, r_ref, bid_ref, pos_ref, *refs):
        item_ref, status_ref = refs[n_lvl + 1], refs[n_lvl + 2]
        tbl_refs = refs[:n_lvl]
        meta_ref = refs[n_lvl]
        x = x_ref[...].astype(u32)                  # [8, GW]
        r = r_ref[...].astype(u32)
        bid = bid_ref[...]
        pos = (jnp.minimum(pos_ref[...], c32(n_pos - 1))
               if n_pos > 1 else bid)
        z = jnp.zeros((1, GW), jnp.bool_)
        states = [
            (bid[s:s + 1, :], z, z, z, z,
             jnp.full((1, GW), ITEM_NONE, i32))
            for s in range(8)
        ]
        for d, S_d in enumerate(depth_sizes):
            for s in range(8):
                states[s] = group(d, S_d, tbl_refs[d], meta_ref,
                                  x[s:s + 1, :], r[s:s + 1, :],
                                  pos[s:s + 1, :], states[s])
        item_ref[...] = jnp.concatenate([st[5] for st in states],
                                        axis=0)
        status_ref[...] = jnp.concatenate(
            [st[2].astype(i32) | (st[3].astype(i32) << 1)
             | (st[4].astype(i32) << 2) for st in states], axis=0)

    interp = _interpret()

    @jax.jit
    def run(x, r, bid, pos):
        L = x.shape[0]
        G = L // TL
        W = L // 8
        # index maps must yield int32 — under x64 plain ints trace as
        # i64, which mosaic cannot legalize (cf. ec/kernels.py)
        z2 = lambda i: (jnp.int32(0), jnp.int32(0))  # noqa: E731
        shp = jax.ShapeDtypeStruct((8, W), jnp.int32)
        lane = pl.BlockSpec((8, GW),
                            lambda i: (jnp.int32(0), jnp.int32(i)))
        full = [pl.BlockSpec(t.shape, z2) for t in tbls]
        mspec = pl.BlockSpec(meta_t.shape, z2)
        item, status = pl.pallas_call(
            kern,
            grid=(G,),
            in_specs=[lane, lane, lane, lane] + full + [mspec],
            out_specs=(lane, lane),
            out_shape=(shp, shp),
            interpret=interp,
        )(x.reshape(8, W).astype(jnp.int32),
          r.reshape(8, W).astype(jnp.int32),
          bid.reshape(8, W).astype(jnp.int32),
          pos.reshape(8, W).astype(jnp.int32),
          *tbls, meta_t)
        return item.reshape(L), status.reshape(L)

    return run
