"""Vectorized CRUSH mapping on device: one jitted program maps millions
of PGs at once.

This is the TPU replacement for the reference's threaded bulk mapper
(src/osd/OSDMapMapping.h:18-120 ParallelPGMapper) and the inner loops it
shards (crush_do_rule / crush_choose_firstn / crush_choose_indep,
src/crush/mapper.c:438-821): the PG axis becomes the vector lane axis,
retries become masked lax.while_loop iterations, and the straw2
exponential draw (mapper.c:316-345) runs as int64 fixed-point math that
is bit-identical to the host engine (ceph_tpu.ops.crush.host) and the
reference golden vectors.

Device scope (the modern "optimal" tunables profile): straw2 buckets at
every level, choose_local_tries == choose_local_fallback_tries == 0,
rules of shape TAKE -> one CHOOSE/CHOOSELEAF step -> EMIT.  Anything
else falls back to the host interpreter, which remains the general spec.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ...models.crushmap import (
    CHOOSE_FIRSTN,
    CHOOSE_INDEP,
    CHOOSELEAF_FIRSTN,
    CHOOSELEAF_INDEP,
    EMIT,
    ITEM_NONE,
    ITEM_UNDEF,
    SET_CHOOSE_TRIES,
    SET_CHOOSELEAF_TRIES,
    SET_CHOOSELEAF_STABLE,
    SET_CHOOSELEAF_VARY_R,
    STRAW2,
    TAKE,
    CrushMap,
)
from ._ln_tables import LL_TBL, RH_LH_TBL

S64_MIN = -(1 << 63)
LN_ONE = 1 << 48  # 2^48: crush_ln scale at u=0xFFFF+1

HASH_SEED = 1315423911


# ---------------------------------------------------------------------------
# jnp primitives (bit-for-bit mirrors of hashes.py / host.crush_ln)
# ---------------------------------------------------------------------------

def _u32(v):
    return jnp.asarray(v, jnp.uint32)


def _mix(a, b, c):
    a = a - b; a = a - c; a = a ^ (c >> _u32(13))
    b = b - c; b = b - a; b = b ^ (a << _u32(8))
    c = c - a; c = c - b; c = c ^ (b >> _u32(13))
    a = a - b; a = a - c; a = a ^ (c >> _u32(12))
    b = b - c; b = b - a; b = b ^ (a << _u32(16))
    c = c - a; c = c - b; c = c ^ (b >> _u32(5))
    a = a - b; a = a - c; a = a ^ (c >> _u32(3))
    b = b - c; b = b - a; b = b ^ (a << _u32(10))
    c = c - a; c = c - b; c = c ^ (b >> _u32(15))
    return a, b, c


def hash32_3_j(a, b, c):
    a, b, c = _u32(a), _u32(b), _u32(c)
    h = _u32(HASH_SEED) ^ a ^ b ^ c
    x, y = _u32(231232), _u32(1232)
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h


def hash32_2_j(a, b):
    a, b = _u32(a), _u32(b)
    h = _u32(HASH_SEED) ^ a ^ b
    x, y = _u32(231232), _u32(1232)
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h


_RH_LH = jnp.asarray(np.array(RH_LH_TBL, dtype=np.int64))
_LL = jnp.asarray(np.array(LL_TBL, dtype=np.int64))


def crush_ln_j(xin):
    """Vector crush_ln: 2^44 * log2(xin+1) fixed point (mapper.c:226-268).
    xin int64 in [0, 0xFFFF]."""
    x = xin.astype(jnp.int64) + 1            # [1, 0x10000]
    bl = jnp.ones_like(x)                    # exact bit_length via compares
    for kbit in range(1, 17):
        bl = bl + (x >= (1 << kbit)).astype(jnp.int64)
    need_norm = (x & 0x18000) == 0
    bits = jnp.maximum(16 - bl, 0)
    x2 = jnp.where(need_norm, x << bits, x)
    iexpon = jnp.where(need_norm, 15 - bits, 15)
    index1 = (x2 >> 8) << 1
    rh = _RH_LH[index1 - 256]
    lh = _RH_LH[index1 + 1 - 256]
    xl64 = (x2 * rh) >> 48
    index2 = xl64 & 0xFF
    lh2 = (lh + _LL[index2]) >> 4
    return (iexpon << 44) + lh2


U64_MAX = (1 << 64) - 1


# ---------------------------------------------------------------------------
# gather-free table lookups
#
# TPU gathers are scalar-rate (~60M elem/s measured through the tunnel)
# while the mapping pipeline needs billions of small-table lookups per
# full-cluster remap.  Every lookup therefore runs as a one-hot int8
# matmul on the MXU: table values are split into 8-bit limbs offset by
# -128 (so they fit signed int8), the index becomes a one-hot row, and
# a single [N, K] @ [K, n_limbs] int8->int32 matmul fetches all limbs
# at MXU rate.  Exactness: one row is hot, so each output element IS a
# limb value (no summation error).
# ---------------------------------------------------------------------------


def pack_limbs(table: np.ndarray, n_limbs: int,
               offset: int = 0) -> np.ndarray:
    """[K] int -> [K, n_limbs] int8 of 8-bit limbs of (v - offset),
    biased by -128 into signed range."""
    t = table.astype(object) - offset
    out = np.zeros((len(t), n_limbs), dtype=np.int8)
    for i, v in enumerate(t):
        v = int(v)
        assert 0 <= v < (1 << (8 * n_limbs)), (v, n_limbs)
        for j in range(n_limbs):
            out[i, j] = ((v >> (8 * j)) & 0xFF) - 128
    return out


def unpack_limbs(l32, n_limbs: int, offset: int = 0,
                 dtype=jnp.int64):
    """[.., n_limbs] int32 (from the one-hot matmul) -> [..] dtype."""
    acc = jnp.zeros(l32.shape[:-1], jnp.int64)
    for j in range(n_limbs):
        limb = (l32[..., j] + 128).astype(jnp.int64)
        acc = acc + (limb << (8 * j))
    return (acc + offset).astype(dtype)


def onehot_fetch(idx, limb_table):
    """idx [..] int32 in [0, K); limb_table [K, C] int8.
    Returns [.., C] int32 via one MXU matmul."""
    K = limb_table.shape[0]
    shape = idx.shape
    flat = idx.reshape(-1)
    oh = (flat[:, None] == jnp.arange(K, dtype=jnp.int32)[None, :]
          ).astype(jnp.int8)
    out = jnp.matmul(oh, limb_table, preferred_element_type=jnp.int32)
    return out.reshape(*shape, limb_table.shape[1])


_RH_NP = np.array(RH_LH_TBL[0::2], dtype=np.uint64)   # 129 reciprocals
_LH_NP = np.array(RH_LH_TBL[1::2], dtype=np.uint64)
_LL_NP = np.array(LL_TBL, dtype=np.uint64)
_LN_NLIMB = 7  # values < 2^56
_RHLH_LIMBS_NP = np.concatenate(
    [pack_limbs(_RH_NP, _LN_NLIMB), pack_limbs(_LH_NP, _LN_NLIMB)], axis=1)
_LL_LIMBS_NP = pack_limbs(_LL_NP, _LN_NLIMB)


def neg_ln_mxu(u, rhlh_limbs, ll_limbs):
    """2^48 - crush_ln(u) for u int64 in [0, 0xFFFF], no gathers:
    the iexpon/normalisation arithmetic stays on the VPU and the three
    table fetches (RH, LH, LL — crush_ln's own structure, mapper.c:
    226-268) ride the MXU as one-hot matmuls."""
    x = u.astype(jnp.int64) + 1            # [1, 0x10000]
    bl = jnp.ones_like(x)
    for kbit in range(1, 17):
        bl = bl + (x >= (1 << kbit)).astype(jnp.int64)
    need = (x & 0x18000) == 0
    bits = jnp.maximum(16 - bl, 0)
    x2 = jnp.where(need, x << bits, x)
    iexpon = jnp.where(need, 15 - bits, 15)
    p = ((x2 >> 8) - 128).astype(jnp.int32)          # [0, 128]
    rl = onehot_fetch(p, rhlh_limbs)
    rh = unpack_limbs(rl[..., :_LN_NLIMB], _LN_NLIMB)
    lh = unpack_limbs(rl[..., _LN_NLIMB:], _LN_NLIMB)
    xl64 = (x2 * rh) >> 48
    i2 = (xl64 & 0xFF).astype(jnp.int32)
    ll = unpack_limbs(onehot_fetch(i2, ll_limbs), _LN_NLIMB)
    lh2 = (lh + ll) >> 4
    return (1 << 48) - ((iexpon << 44) + lh2)


def magic_for_divisor(d: int) -> tuple[int, int]:
    """(M, k) such that a*M >> k == a // d exactly for all a <= 2^48.

    Granlund-Montgomery: M = ceil(2^k / d) with k = 48 + bits(d); then
    e = M*d - 2^k < 2^bits(d), so the error term a*e/(d*2^k) stays below
    1/d for a <= 2^48 and the floor is exact.  M < 2^50 always fits."""
    if d <= 0:
        return 0, 0
    k = 48 + d.bit_length()
    M = -(-(1 << k) // d)
    return M, k


def _magic_divide(a, m_arr, k_arr):
    """Exact a // d via the per-item magic (a int64 <= 2^48, arrays of
    uint64 M and int32 k).  128-bit product by 32-bit limbs; TPU int64
    multiply is cheap, only division is emulated slowly."""
    a = a.astype(jnp.uint64)
    m = m_arr
    a0 = a & jnp.uint64(0xFFFFFFFF)
    a1 = a >> jnp.uint64(32)
    m0 = m & jnp.uint64(0xFFFFFFFF)
    m1 = m >> jnp.uint64(32)
    lo_lo = a0 * m0
    c1 = a0 * m1
    c2 = a1 * m0
    hi_hi = a1 * m1
    mid = (lo_lo >> jnp.uint64(32)) + (c1 & jnp.uint64(0xFFFFFFFF)) + \
        (c2 & jnp.uint64(0xFFFFFFFF))
    lo = (lo_lo & jnp.uint64(0xFFFFFFFF)) | (mid << jnp.uint64(32))
    hi = hi_hi + (c1 >> jnp.uint64(32)) + (c2 >> jnp.uint64(32)) + \
        (mid >> jnp.uint64(32))
    k = k_arr.astype(jnp.uint64)
    klo = jnp.minimum(k, jnp.uint64(63))
    km64 = jnp.where(k > 64, k - jnp.uint64(64), jnp.uint64(0))
    sh_up = jnp.where(k < 64, jnp.uint64(64) - k, jnp.uint64(0))
    q_low = (hi << sh_up) | (lo >> klo)
    q_high = hi >> km64
    return jnp.where(k < 64, q_low, q_high).astype(jnp.int64)


def _straw2_draw_q(x, ids, r, m_arr, k_arr, rhlh_limbs, ll_limbs):
    """Quotient of the exponential draw (mapper.c:312-345): the reference
    maximises trunc((ln-2^48)/w); we minimise q = (2^48-ln)//w, which is
    the same winner with the same first-index tie-break.  Zero-weight
    items (k==0) get q = S64_MAX."""
    u = (hash32_3_j(x, ids, r) & _u32(0xFFFF)).astype(jnp.int64)
    neg = neg_ln_mxu(u, rhlh_limbs, ll_limbs)
    q = _magic_divide(neg, m_arr, k_arr)
    return jnp.where(k_arr > 0, q, jnp.int64((1 << 63) - 1))


# ---------------------------------------------------------------------------
# flattened map
# ---------------------------------------------------------------------------


class FlatMap:
    """CrushMap flattened to dense arrays. Bucket index bid = -1 - id."""

    def __init__(self, m: CrushMap, choose_args_name: str | None = None):
        for b in m.buckets.values():
            if b.alg != STRAW2:
                raise ValueError(
                    "device mapper requires straw2 buckets (bucket %d has "
                    "alg %d)" % (b.id, b.alg))
        t = m.tunables
        if t.choose_local_tries or t.choose_local_fallback_tries:
            raise ValueError("device mapper requires local tries == 0")
        B = m.max_buckets or 1
        S = max((b.size for b in m.buckets.values()), default=1) or 1
        self.B, self.S = B, S
        self.max_devices = m.max_devices
        self.tunables = t
        size = np.zeros(B, np.int32)
        btype = np.zeros(B, np.int32)
        items = np.zeros((B, S), np.int32)
        ids = np.zeros((B, S), np.int32)
        cargs = (m.choose_args.get(choose_args_name)
                 if choose_args_name else None)
        n_pos = 1
        if cargs:
            n_pos = max((len(ws.weight_sets) for ws in cargs.values()
                         if ws.weight_sets), default=1) or 1
        pos_w = np.zeros((n_pos, B, S), np.int32)
        for b in m.buckets.values():
            bid = -1 - b.id
            size[bid] = b.size
            btype[bid] = b.type
            items[bid, :b.size] = b.items
            ids[bid, :b.size] = b.items
            for p in range(n_pos):
                pos_w[p, bid, :b.size] = b.item_weights
            if cargs and b.id in cargs:
                ws = cargs[b.id]
                if ws.ids is not None:
                    ids[bid, :b.size] = ws.ids
                if ws.weight_sets:
                    for p in range(n_pos):
                        src = ws.weight_sets[min(p, len(ws.weight_sets) - 1)]
                        pos_w[p, bid, :b.size] = src
        depth: dict[int, int] = {}

        def _depth(bid_id: int) -> int:
            if bid_id in depth:
                return depth[bid_id]
            b = m.buckets[bid_id]
            d = 1 + max((_depth(i) for i in b.items if i < 0), default=0)
            depth[bid_id] = d
            return d

        self.max_depth = max((_depth(i) for i in m.buckets), default=1)
        # magic-division constants per (pos, bucket, item) weight — the
        # divisors are map constants, so the slow emulated int64 divide
        # becomes a 128-bit multiply-shift on device
        magic_m = np.zeros((n_pos, B, S), np.uint64)
        magic_k = np.zeros((n_pos, B, S), np.int32)
        for p in range(n_pos):
            for bi in range(B):
                for si in range(S):
                    M, k = magic_for_divisor(int(pos_w[p, bi, si]))
                    magic_m[p, bi, si] = M
                    magic_k[p, bi, si] = k
        self.n_pos = n_pos
        self.rules = dict(m.rules)

        # -- gather-free lookup tables (see module comment) --------------
        # per-(pos,bucket) row: for each item slot s, 16 int8 limbs
        # [ids(4) | items(4) | magic_m(7) | magic_k(1)], then size(2) +
        # btype(2) at the tail.  Fetched with ONE one-hot matmul per
        # bucket visit.  Tables are built per requested item capacity
        # S' (row_limbs_for) so each descent level only pays for the
        # largest bucket actually reachable there.
        id_lo = min([0] + [int(v) for v in items.reshape(-1)]
                    + [int(v) for v in ids.reshape(-1)])
        self.id_offset = id_lo
        self._ids_np = ids
        self._items_np = items
        self._mm_np = magic_m
        self._mk_np = magic_k
        self._size_np = size
        self._btype_np = btype
        self._row_cache: dict[int, np.ndarray] = {}
        # per-bucket metadata fetch for arbitrary bucket ids (the child
        # bucket chosen during descent): size(2) + btype(2)
        meta = np.zeros((B, 4), np.int8)
        meta[:, 0:2] = pack_limbs(size, 2)
        meta[:, 2:4] = pack_limbs(btype, 2)
        self.meta_limbs = jnp.asarray(meta)
        self.rhlh_limbs = jnp.asarray(_RHLH_LIMBS_NP)
        self.ll_limbs = jnp.asarray(_LL_LIMBS_NP)

    def row_limbs_for(self, S: int) -> np.ndarray:
        """[n_pos*B, 16*S+4] int8 rows truncated to S item slots (only
        fetched for buckets whose size fits — callers pick S per level)."""
        tbl = self._row_cache.get(S)
        if tbl is not None:
            return tbl
        B, n_pos = self.B, self.n_pos
        rows = np.zeros((n_pos * B, 16 * S + 4), np.int8)
        for p in range(n_pos):
            for bi in range(B):
                row = np.zeros((S, 16), np.int8)
                row[:, 0:4] = pack_limbs(self._ids_np[bi, :S], 4,
                                         self.id_offset)
                row[:, 4:8] = pack_limbs(self._items_np[bi, :S], 4,
                                         self.id_offset)
                row[:, 8:15] = pack_limbs(self._mm_np[p, bi, :S], 7)
                row[:, 15:16] = pack_limbs(self._mk_np[p, bi, :S], 1)
                r = rows[p * B + bi]
                r[:16 * S] = row.reshape(-1)
                r[16 * S:16 * S + 2] = pack_limbs(
                    self._size_np[bi:bi + 1], 2)[0]
                r[16 * S + 2:] = pack_limbs(
                    self._btype_np[bi:bi + 1], 2)[0]
        # Cache as host numpy: this is lazily reached inside jit traces,
        # where jnp.asarray would bind the constant to the live trace and
        # the cached tracer would leak into later traces.
        self._row_cache[S] = rows
        return rows


# ---------------------------------------------------------------------------
# vector choose primitives
# ---------------------------------------------------------------------------


def _fetch_row(fm: FlatMap, bid, pos, S: int):
    """One one-hot matmul fetches a bucket's full choose row:
    (ids [L,S], items [L,S], magic_m [L,S], magic_k [L,S], size [L])."""
    if fm.n_pos == 1:
        idx = bid
    else:
        idx = jnp.minimum(pos, fm.n_pos - 1) * fm.B + bid
    r = onehot_fetch(idx, fm.row_limbs_for(S))        # [L, 16S+4] int32
    per = r[..., :16 * S].reshape(*bid.shape, S, 16)
    ids = unpack_limbs(per[..., 0:4], 4, fm.id_offset, jnp.int32)
    items = unpack_limbs(per[..., 4:8], 4, fm.id_offset, jnp.int32)
    m_arr = unpack_limbs(per[..., 8:15], 7, 0, jnp.uint64)
    k_arr = unpack_limbs(per[..., 15:16], 1, 0, jnp.int32)
    size = unpack_limbs(r[..., 16 * S:16 * S + 2], 2, 0, jnp.int32)
    return ids, items, m_arr, k_arr, size


def _fetch_meta(fm: FlatMap, bid):
    """(size [L], btype [L]) of arbitrary bucket indices."""
    r = onehot_fetch(bid, fm.meta_limbs)
    size = unpack_limbs(r[..., 0:2], 2, 0, jnp.int32)
    btype = unpack_limbs(r[..., 2:4], 2, 0, jnp.int32)
    return size, btype


def _straw2_choose(fm: FlatMap, bid, x, r, pos, S: int):
    """Winning item per lane. bid [L] bucket indices; pos [L] output
    positions (selects the choose_args weight-set, CrushWrapper.h:1500).
    S = item capacity for this level (>= size of every bucket that can
    appear in bid).  Returns item [L]."""
    idv, items, m_arr, k_arr, size = _fetch_row(fm, bid, pos, S)
    q = _straw2_draw_q(x[:, None], idv, r[:, None], m_arr, k_arr,
                       fm.rhlh_limbs, fm.ll_limbs)
    valid = jnp.arange(S)[None, :] < size[:, None]
    q = jnp.where(valid, q, jnp.int64((1 << 63) - 1))
    win = jnp.argmin(q, axis=1)
    # select column `win` without a gather
    sel = jnp.arange(S)[None, :] == win[:, None]
    item = jnp.sum(jnp.where(sel, items, 0), axis=1).astype(jnp.int32)
    return item


def _descend(fm: FlatMap, take_bid, x, r, want_type: int, pos,
             depth_sizes: tuple):
    """Walk bucket->bucket until an item of want_type.

    depth_sizes[d] = max bucket size reachable at depth d from the
    start set (static per rule), so each level's draw only pays for
    the buckets that can actually appear there.

    Returns (item, ok, perm_fail): ok = reached an item of the wanted
    type; perm_fail = hit a wrong-type device (host skips the replica
    permanently, mapper.c:516-520); neither = retryable (empty bucket).
    """
    L = x.shape[0]
    cur = take_bid
    item = jnp.full((L,), ITEM_NONE, jnp.int32)
    ok = jnp.zeros((L,), bool)
    perm = jnp.zeros((L,), bool)
    cur_size, _ = _fetch_meta(fm, cur)
    done = cur_size == 0                     # empty bucket: retryable
    for S_d in depth_sizes:
        chosen = _straw2_choose(fm, cur, x, r, pos, S_d)
        is_bucket = chosen < 0
        cbid = jnp.where(is_bucket, -1 - chosen, 0)
        csize, cbtype = _fetch_meta(fm, cbid)
        ctype = jnp.where(is_bucket, cbtype, 0)
        oob = (~is_bucket) & (chosen >= fm.max_devices)
        reach = (~done) & (ctype == want_type) & (~oob)
        wrongdev = (~done) & (~reach) & ((~is_bucket) | oob)
        empty_next = (~done) & (~reach) & is_bucket & (csize == 0)
        item = jnp.where(reach, chosen, item)
        ok = ok | reach
        perm = perm | wrongdev
        done = done | reach | wrongdev | empty_next
        cur = jnp.where((~done) & is_bucket, cbid, cur)
    return item, ok, perm


def _is_out(dev_weights, item, x):
    """Reweight rejection (mapper.c:402-416)."""
    idx = jnp.clip(item, 0, dev_weights.shape[0] - 1)
    w = dev_weights[idx]
    oob = (item >= dev_weights.shape[0]) | (item < 0)
    hh = (hash32_2_j(x, item) & _u32(0xFFFF)).astype(jnp.int32)
    return oob | (w == 0) | ((w < 0x10000) & (hh >= w))


# ---------------------------------------------------------------------------
# firstn / indep
# ---------------------------------------------------------------------------


def _choose_firstn_vec(fm: FlatMap, take_bid, xs, numrep: int,
                       result_max: int, want_type: int,
                       recurse_to_leaf: bool, dev_weights,
                       tries: int, recurse_tries: int, vary_r: int,
                       stable: int, outer_ds: tuple, inner_ds: tuple):
    """crush_choose_firstn (mapper.c:438-626) for local-tries==0: per
    replica, retry whole descents while collided/rejected (masked
    lanes); chooseleaf recursion selects one leaf per chosen bucket."""
    L = xs.shape[0]
    slots = min(numrep, result_max)
    out = jnp.full((L, slots), ITEM_NONE, jnp.int32)      # level items
    leaves = jnp.full((L, slots), ITEM_NONE, jnp.int32)   # devices
    outpos = jnp.zeros((L,), jnp.int32)

    result_slots = out.shape[1]

    def rep_body(rep, carry):
        out, leaves, outpos = carry

        def body(state):
            ftotal, active, out, leaves, outpos = state
            r = jnp.full((L,), 0, jnp.int32) + rep + ftotal
            item, ok, perm = _descend(fm, take_bid, xs, r, want_type,
                                      outpos, outer_ds)
            if recurse_to_leaf:
                if vary_r:
                    sub_r = r >> (vary_r - 1)
                else:
                    sub_r = jnp.zeros_like(r)
                rep_i = (jnp.zeros_like(outpos) if stable else outpos)
                bid_in = jnp.where(item < 0, -1 - item, 0)

                def inner_body(istate):
                    ift, iact, leaf, leaf_ok = istate
                    r_in = rep_i + sub_r + ift
                    cand, cok, _cperm = _descend(
                        fm, bid_in, xs, r_in, 0, outpos, inner_ds)
                    cok = cok & (item < 0)
                    # leaf collision: the recursive call checks candidates
                    # against leaves already placed in out2[0..outpos)
                    # (mapper.c:535-541 with out=out2)
                    cok = cok & ~jnp.any(leaves == cand[:, None], axis=1)
                    cok = cok & ~_is_out(dev_weights, cand, xs)
                    take = iact & cok
                    leaf = jnp.where(take, cand, leaf)
                    leaf_ok = leaf_ok | take
                    iact = iact & (~cok) & (ift + 1 < recurse_tries)
                    return ift + 1, iact, leaf, leaf_ok

                izero = jnp.zeros((L,), jnp.int32)
                leaf0 = jnp.full((L,), ITEM_NONE, jnp.int32)
                _, _, leaf, leaf_ok = jax.lax.while_loop(
                    lambda s: jnp.any(s[1]), inner_body,
                    (izero, active & ok, leaf0, jnp.zeros((L,), bool)))
                final, final_ok = leaf, ok & leaf_ok
            else:
                final = item
                final_ok = ok
                if want_type == 0:
                    final_ok = final_ok & ~_is_out(dev_weights, item, xs)
            collide = jnp.any(out == item[:, None], axis=1) & ok
            success = (active & final_ok & ~collide
                       & (outpos < result_slots))
            slot = jnp.arange(result_slots)[None, :] == outpos[:, None]
            put = slot & success[:, None]
            out = jnp.where(put, item[:, None], out)
            leaves = jnp.where(put, final[:, None], leaves)
            outpos = outpos + success.astype(jnp.int32)
            ftotal = ftotal + 1
            active = active & ~success & ~perm & (ftotal < tries)
            return ftotal, active, out, leaves, outpos

        z = jnp.zeros((L,), jnp.int32)
        act = jnp.ones((L,), bool)
        _, _, out, leaves, outpos = jax.lax.while_loop(
            lambda s: jnp.any(s[1]), body, (z, act, out, leaves, outpos))
        return out, leaves, outpos

    out, leaves, outpos = jax.lax.fori_loop(
        0, numrep, rep_body, (out, leaves, outpos))
    return (leaves if recurse_to_leaf else out), outpos


def _choose_indep_vec(fm: FlatMap, take_bid, xs, numrep: int,
                      result_max: int, want_type: int,
                      recurse_to_leaf: bool, dev_weights,
                      tries: int, recurse_tries: int,
                      outer_ds: tuple, inner_ds: tuple):
    """crush_choose_indep (mapper.c:633-821): positionally-stable, slots
    left UNDEF retry with r advanced by numrep per round (numrep is the
    full replica count even when fewer slots fit result_max)."""
    L = xs.shape[0]
    slots = min(numrep, result_max)
    out = jnp.full((L, slots), ITEM_UNDEF, jnp.int32)
    leaves = jnp.full((L, slots), ITEM_UNDEF, jnp.int32)
    pos0 = jnp.zeros((L,), jnp.int32)

    def body(state):
        ftotal, out, leaves = state

        def rep_body(rep, carry):
            out, leaves = carry
            undecided = out[:, rep] == ITEM_UNDEF
            r = jnp.full((L,), 0, jnp.int32) + rep + numrep * ftotal
            item, ok, perm = _descend(fm, take_bid, xs, r, want_type,
                                      pos0, outer_ds)
            collide = jnp.any(out == item[:, None], axis=1) & ok
            if recurse_to_leaf:
                bid_in = jnp.where(item < 0, -1 - item, 0)
                pos_r = jnp.full((L,), 0, jnp.int32) + rep

                def inner_body(istate):
                    ift, iact, leaf, leaf_ok = istate
                    r_in = r + rep + numrep * ift
                    cand, cok, _cp = _descend(fm, bid_in, xs, r_in, 0,
                                              pos_r, inner_ds)
                    cok = cok & (item < 0)
                    cok = cok & ~_is_out(dev_weights, cand, xs)
                    take = iact & cok
                    leaf = jnp.where(take, cand, leaf)
                    leaf_ok = leaf_ok | take
                    iact = iact & (~cok) & (ift + 1 < recurse_tries)
                    return ift + 1, iact, leaf, leaf_ok

                izero = jnp.zeros((L,), jnp.int32)
                leaf0 = jnp.full((L,), ITEM_NONE, jnp.int32)
                _, _, leaf, leaf_ok = jax.lax.while_loop(
                    lambda s: jnp.any(s[1]), inner_body,
                    (izero, undecided & ok & ~collide, leaf0,
                     jnp.zeros((L,), bool)))
                final, final_ok = leaf, ok & leaf_ok
            else:
                final = item
                final_ok = ok
                if want_type == 0:
                    final_ok = final_ok & ~_is_out(dev_weights, item, xs)
            success = undecided & final_ok & ~collide
            permfail = undecided & perm
            col = jnp.arange(slots)[None, :] == rep
            out = jnp.where(col & success[:, None], item[:, None], out)
            out = jnp.where(col & permfail[:, None], ITEM_NONE, out)
            leaves = jnp.where(col & success[:, None], final[:, None],
                               leaves)
            leaves = jnp.where(col & permfail[:, None], ITEM_NONE, leaves)
            return out, leaves

        out, leaves = jax.lax.fori_loop(0, slots, rep_body, (out, leaves))
        return ftotal + 1, out, leaves

    def cond(state):
        ftotal, out, _ = state
        return jnp.any(out == ITEM_UNDEF) & (ftotal < tries)

    z = jnp.zeros((), jnp.int32)
    _, out, leaves = jax.lax.while_loop(cond, body, (z, out, leaves))
    res = leaves if recurse_to_leaf else out
    return jnp.where(res == ITEM_UNDEF, ITEM_NONE, res)


# ---------------------------------------------------------------------------
# post-CRUSH mapping pipeline (fused on device)
# ---------------------------------------------------------------------------

CEPH_OSD_MAX_PRIMARY_AFFINITY = 0x10000
CEPH_OSD_DEFAULT_PRIMARY_AFFINITY = 0x10000


def _post_process(raw, seeds, exists_b, isup_b, aff, can_shift: bool,
                  use_aff: bool):
    """Fused _remove_nonexistent_osds + _raw_to_up_osds + _pick_primary +
    _apply_primary_affinity (OSDMap.cc:2626-2802) over the whole batch.

    raw [L,S] int32 with ITEM_NONE holes; seeds [L] uint32 pps values;
    exists_b/isup_b [D] bool; aff [D] int32 16.16 primary affinities.
    Only valid for PGs with no upmap/pg_temp exception (the bulk mapper
    recomputes exception rows on the host scalar path).
    """
    D = exists_b.shape[0]
    valid = raw != ITEM_NONE
    idx = jnp.clip(raw, 0, D - 1)
    keep = valid & (raw < D) & exists_b[idx] & isup_b[idx]
    up = jnp.where(keep, raw, ITEM_NONE)
    if can_shift:
        # stable compaction: surviving osds keep order, holes go last
        order = jnp.argsort(~keep, axis=1, stable=True)
        up = jnp.take_along_axis(up, order, axis=1)
    nonnone = up != ITEM_NONE
    has = jnp.any(nonnone, axis=1)
    first = jnp.argmax(nonnone, axis=1)
    prim = jnp.where(
        has, jnp.take_along_axis(up, first[:, None], 1)[:, 0], -1)
    if use_aff:
        a = aff[jnp.clip(up, 0, D - 1)]
        row_applies = jnp.any(
            nonnone & (a != CEPH_OSD_DEFAULT_PRIMARY_AFFINITY), axis=1)
        h = (hash32_2_j(seeds[:, None], up.astype(jnp.uint32))
             >> _u32(16)).astype(jnp.int32)
        rejected = (a < CEPH_OSD_MAX_PRIMARY_AFFINITY) & (h >= a)
        accept = nonnone & ~rejected
        has_acc = jnp.any(accept, axis=1)
        pos = jnp.where(has_acc, jnp.argmax(accept, axis=1), first)
        applies = row_applies & has
        new_prim = jnp.take_along_axis(up, pos[:, None], 1)[:, 0]
        prim = jnp.where(applies, new_prim, prim)
        if can_shift:
            # move the new primary to the front, shifting [0..pos) right
            S = up.shape[1]
            i = jnp.arange(S)[None, :]
            rotated = jnp.where(
                i == 0, new_prim[:, None],
                jnp.where(i <= pos[:, None], jnp.roll(up, 1, axis=1), up))
            up = jnp.where(applies[:, None], rotated, up)
    return up, prim


# ---------------------------------------------------------------------------
# rule driver
# ---------------------------------------------------------------------------


class DeviceMapper:
    """Bulk do_rule on device for straw2 maps with single-choose rules.

    do_rule_batch(ruleno, xs, result_max, dev_weights) mirrors
    CrushWrapper::do_rule over a whole batch of inputs; results carry
    ITEM_NONE holes exactly like the host engine.
    """

    def __init__(self, crushmap: CrushMap,
                 choose_args_name: str | None = None):
        self.fm = FlatMap(crushmap, choose_args_name)
        self.map = crushmap

    def _compile(self, ruleno: int, result_max: int):
        rule = self.fm.rules[ruleno]
        t = self.fm.tunables
        tries = t.choose_total_tries + 1     # historical off-by-one
        leaf_tries = 0
        vary_r = t.chooseleaf_vary_r
        stable = t.chooseleaf_stable
        take_id = None
        plan = None
        for op, arg1, arg2 in rule.steps:
            if op == TAKE:
                take_id = arg1
            elif op == SET_CHOOSE_TRIES:
                if arg1 > 0:
                    tries = arg1
            elif op == SET_CHOOSELEAF_TRIES:
                if arg1 > 0:
                    leaf_tries = arg1
            elif op == SET_CHOOSELEAF_VARY_R:
                if arg1 >= 0:
                    vary_r = arg1
            elif op == SET_CHOOSELEAF_STABLE:
                if arg1 >= 0:
                    stable = arg1
            elif op in (CHOOSE_FIRSTN, CHOOSELEAF_FIRSTN,
                        CHOOSE_INDEP, CHOOSELEAF_INDEP):
                if plan is not None:
                    raise ValueError(
                        "device mapper supports a single choose step")
                if take_id is None or take_id >= 0:
                    raise ValueError("choose without a bucket take")
                numrep = arg1
                if numrep <= 0:
                    numrep += result_max
                firstn = op in (CHOOSE_FIRSTN, CHOOSELEAF_FIRSTN)
                leaf = op in (CHOOSELEAF_FIRSTN, CHOOSELEAF_INDEP)
                plan = (take_id, numrep, arg2, firstn, leaf)
            elif op == EMIT:
                pass
        if plan is None:
            raise ValueError("rule has no choose step")
        take_id, numrep, want_type, firstn, leaf = plan
        if firstn:
            recurse = (leaf_tries if leaf_tries
                       else (1 if t.chooseleaf_descend_once else tries))
        else:
            recurse = leaf_tries if leaf_tries else 1
        fm = self.fm
        take_bid_val = -1 - take_id
        outer_ds = self._depth_sizes([take_id])
        if leaf:
            starts = [b.id for b in self.map.buckets.values()
                      if b.type == want_type]
            inner_ds = self._depth_sizes(starts)
        else:
            inner_ds = ()

        def core(xs, dev_weights):
            L = xs.shape[0]
            take_bid = jnp.full((L,), take_bid_val, jnp.int32)
            if firstn:
                res, _ = _choose_firstn_vec(
                    fm, take_bid, xs, numrep, result_max, want_type,
                    leaf, dev_weights, tries, recurse, vary_r, stable,
                    outer_ds, inner_ds)
            else:
                res = _choose_indep_vec(
                    fm, take_bid, xs, numrep, result_max, want_type,
                    leaf, dev_weights, tries, recurse,
                    outer_ds, inner_ds)
            return res

        return core

    def _depth_sizes(self, start_bucket_ids: list[int]) -> tuple:
        """depth_sizes[d] = max size of any bucket reachable at depth d
        by walking bucket children from the start set (static per
        rule/map)."""
        m = self.map
        sizes = []
        level = {b for b in start_bucket_ids if b in m.buckets}
        seen_levels = 0
        while level and seen_levels < 64:    # cycle guard
            sizes.append(max(
                (m.buckets[b].size for b in level), default=1) or 1)
            level = {c for b in level for c in m.buckets[b].items
                     if c < 0 and c in m.buckets}
            seen_levels += 1
        return tuple(sizes) if sizes else (1,)

    @functools.lru_cache(maxsize=None)
    def _compiled(self, ruleno: int, result_max: int):
        return jax.jit(self._compile(ruleno, result_max))

    @functools.lru_cache(maxsize=None)
    def _compiled_map(self, ruleno: int, result_max: int,
                      can_shift: bool, use_aff: bool):
        core = self._compile(ruleno, result_max)

        @jax.jit
        def run(xs, dev_weights, exists_b, isup_b, aff):
            raw = core(xs, dev_weights)
            return _post_process(raw, xs, exists_b, isup_b, aff,
                                 can_shift, use_aff)

        return run

    # per-dispatch PG cap: intermediates are [L, S] int64 (several live
    # temps inside the choose loops), so huge pools are chunked to bound
    # device memory — 512k lanes * 64 items * 8B ~ 256 MiB per temp
    CHUNK = 1 << 19

    def map_pgs_batch(self, ruleno: int, pps, result_max: int,
                      dev_weights, exists, isup, aff=None,
                      can_shift: bool = True):
        """Full do_rule -> up/up_primary pipeline for a batch of PGs
        with no upmap/pg_temp exceptions.  pps [L] placement seeds;
        exists/isup bool [max_osd]; aff int32 [max_osd] primary
        affinities or None.  Returns (up [L,S] int32, up_primary [L]
        int32) as numpy arrays."""
        use_aff = aff is not None
        fn = self._compiled_map(ruleno, result_max, bool(can_shift),
                                use_aff)
        pps = np.asarray(pps, dtype=np.int64) & 0xFFFFFFFF
        w = jnp.asarray(np.asarray(dev_weights, dtype=np.int32))
        ex = jnp.asarray(np.asarray(exists, dtype=bool))
        iu = jnp.asarray(np.asarray(isup, dtype=bool))
        if use_aff:
            af = jnp.asarray(np.asarray(aff, dtype=np.int32))
        else:
            af = jnp.zeros((ex.shape[0],), jnp.int32)
        L = pps.shape[0]
        if L <= self.CHUNK:
            up, prim = fn(jnp.asarray(pps, dtype=jnp.uint32),
                          w, ex, iu, af)
            # np.array (not asarray): device buffers are read-only views
            # and callers patch exception rows in place
            return np.array(up), np.array(prim)
        # fixed-size chunks (tail padded) so one compilation serves all
        ups, prims = [], []
        for off in range(0, L, self.CHUNK):
            part = pps[off:off + self.CHUNK]
            n = part.shape[0]
            if n < self.CHUNK:
                part = np.pad(part, (0, self.CHUNK - n))
            u, p = fn(jnp.asarray(part, dtype=jnp.uint32), w, ex, iu, af)
            ups.append(np.array(u[:n]))
            prims.append(np.array(p[:n]))
        return np.concatenate(ups), np.concatenate(prims)

    def do_rule_batch(self, ruleno: int, xs, result_max: int,
                      dev_weights) -> np.ndarray:
        """xs: int array [L] of inputs (pps values); dev_weights: int32
        [max_devices] 16.16 reweights.  Returns [L, numrep] int32 with
        ITEM_NONE holes."""
        fn = self._compiled(ruleno, result_max)
        xs = jnp.asarray(np.asarray(xs, dtype=np.int64) & 0xFFFFFFFF,
                         dtype=jnp.uint32)
        w = jnp.asarray(np.asarray(dev_weights, dtype=np.int32))
        return np.asarray(fn(xs, w))
