"""Vectorized CRUSH mapping on device: one jitted program maps millions
of PGs at once.

This is the TPU replacement for the reference's threaded bulk mapper
(src/osd/OSDMapMapping.h:18-120 ParallelPGMapper) and the inner loops it
shards (crush_do_rule / crush_choose_firstn / crush_choose_indep,
src/crush/mapper.c:438-821): the PG axis becomes the vector lane axis,
retries become masked lax.while_loop iterations, and the straw2
exponential draw (mapper.c:316-345) runs as int64 fixed-point math that
is bit-identical to the host engine (ceph_tpu.ops.crush.host) and the
reference golden vectors.

Device scope (the modern "optimal" tunables profile): straw2 buckets at
every level, choose_local_tries == choose_local_fallback_tries == 0,
rules of shape TAKE -> one CHOOSE/CHOOSELEAF step -> EMIT.  Anything
else falls back to the host interpreter, which remains the general spec.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ...models.crushmap import (
    CHOOSE_FIRSTN,
    CHOOSE_INDEP,
    CHOOSELEAF_FIRSTN,
    CHOOSELEAF_INDEP,
    EMIT,
    ITEM_NONE,
    ITEM_UNDEF,
    SET_CHOOSE_TRIES,
    SET_CHOOSELEAF_TRIES,
    SET_CHOOSELEAF_STABLE,
    SET_CHOOSELEAF_VARY_R,
    STRAW2,
    TAKE,
    CrushMap,
)
from ._ln_tables import LL_TBL, RH_LH_TBL

S64_MIN = -(1 << 63)
LN_ONE = 1 << 48  # 2^48: crush_ln scale at u=0xFFFF+1

HASH_SEED = 1315423911


# ---------------------------------------------------------------------------
# jnp primitives (bit-for-bit mirrors of hashes.py / host.crush_ln)
# ---------------------------------------------------------------------------

def _u32(v):
    return jnp.asarray(v, jnp.uint32)


def _mix(a, b, c):
    a = a - b; a = a - c; a = a ^ (c >> _u32(13))
    b = b - c; b = b - a; b = b ^ (a << _u32(8))
    c = c - a; c = c - b; c = c ^ (b >> _u32(13))
    a = a - b; a = a - c; a = a ^ (c >> _u32(12))
    b = b - c; b = b - a; b = b ^ (a << _u32(16))
    c = c - a; c = c - b; c = c ^ (b >> _u32(5))
    a = a - b; a = a - c; a = a ^ (c >> _u32(3))
    b = b - c; b = b - a; b = b ^ (a << _u32(10))
    c = c - a; c = c - b; c = c ^ (b >> _u32(15))
    return a, b, c


def hash32_3_j(a, b, c):
    a, b, c = _u32(a), _u32(b), _u32(c)
    h = _u32(HASH_SEED) ^ a ^ b ^ c
    x, y = _u32(231232), _u32(1232)
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h


def hash32_2_j(a, b):
    a, b = _u32(a), _u32(b)
    h = _u32(HASH_SEED) ^ a ^ b
    x, y = _u32(231232), _u32(1232)
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h


_RH_LH = jnp.asarray(np.array(RH_LH_TBL, dtype=np.int64))
_LL = jnp.asarray(np.array(LL_TBL, dtype=np.int64))


def crush_ln_j(xin):
    """Vector crush_ln: 2^44 * log2(xin+1) fixed point (mapper.c:226-268).
    xin int64 in [0, 0xFFFF]."""
    x = xin.astype(jnp.int64) + 1            # [1, 0x10000]
    bl = jnp.ones_like(x)                    # exact bit_length via compares
    for kbit in range(1, 17):
        bl = bl + (x >= (1 << kbit)).astype(jnp.int64)
    need_norm = (x & 0x18000) == 0
    bits = jnp.maximum(16 - bl, 0)
    x2 = jnp.where(need_norm, x << bits, x)
    iexpon = jnp.where(need_norm, 15 - bits, 15)
    index1 = (x2 >> 8) << 1
    rh = _RH_LH[index1 - 256]
    lh = _RH_LH[index1 + 1 - 256]
    xl64 = (x2 * rh) >> 48
    index2 = xl64 & 0xFF
    lh2 = (lh + _LL[index2]) >> 4
    return (iexpon << 44) + lh2


U64_MAX = (1 << 64) - 1


def _neg_ln_table() -> np.ndarray:
    """neg[u] = 2^48 - crush_ln(u) for every 16-bit u (the full domain of
    the straw2 hash draw)."""
    from .host import crush_ln

    return np.array([(1 << 48) - crush_ln(u) for u in range(1 << 16)],
                    dtype=np.int64)


_NEG_LN_NP: np.ndarray | None = None


def _neg_ln() -> jnp.ndarray:
    """Must be materialised OUTSIDE any jit trace (see FlatMap.__init__);
    inside a trace it would leak a tracer through the module global."""
    global _NEG_LN_NP
    if _NEG_LN_NP is None:
        _NEG_LN_NP = _neg_ln_table()
    return jnp.asarray(_NEG_LN_NP)


def magic_for_divisor(d: int) -> tuple[int, int]:
    """(M, k) such that a*M >> k == a // d exactly for all a <= 2^48.

    Granlund-Montgomery: M = ceil(2^k / d) with k = 48 + bits(d); then
    e = M*d - 2^k < 2^bits(d), so the error term a*e/(d*2^k) stays below
    1/d for a <= 2^48 and the floor is exact.  M < 2^50 always fits."""
    if d <= 0:
        return 0, 0
    k = 48 + d.bit_length()
    M = -(-(1 << k) // d)
    return M, k


def _magic_divide(a, m_arr, k_arr):
    """Exact a // d via the per-item magic (a int64 <= 2^48, arrays of
    uint64 M and int32 k).  128-bit product by 32-bit limbs; TPU int64
    multiply is cheap, only division is emulated slowly."""
    a = a.astype(jnp.uint64)
    m = m_arr
    a0 = a & jnp.uint64(0xFFFFFFFF)
    a1 = a >> jnp.uint64(32)
    m0 = m & jnp.uint64(0xFFFFFFFF)
    m1 = m >> jnp.uint64(32)
    lo_lo = a0 * m0
    c1 = a0 * m1
    c2 = a1 * m0
    hi_hi = a1 * m1
    mid = (lo_lo >> jnp.uint64(32)) + (c1 & jnp.uint64(0xFFFFFFFF)) + \
        (c2 & jnp.uint64(0xFFFFFFFF))
    lo = (lo_lo & jnp.uint64(0xFFFFFFFF)) | (mid << jnp.uint64(32))
    hi = hi_hi + (c1 >> jnp.uint64(32)) + (c2 >> jnp.uint64(32)) + \
        (mid >> jnp.uint64(32))
    k = k_arr.astype(jnp.uint64)
    klo = jnp.minimum(k, jnp.uint64(63))
    km64 = jnp.where(k > 64, k - jnp.uint64(64), jnp.uint64(0))
    sh_up = jnp.where(k < 64, jnp.uint64(64) - k, jnp.uint64(0))
    q_low = (hi << sh_up) | (lo >> klo)
    q_high = hi >> km64
    return jnp.where(k < 64, q_low, q_high).astype(jnp.int64)


def _straw2_draw_q(x, ids, r, m_arr, k_arr):
    """Quotient of the exponential draw (mapper.c:312-345): the reference
    maximises trunc((ln-2^48)/w); we minimise q = (2^48-ln)//w, which is
    the same winner with the same first-index tie-break.  Zero-weight
    items (k==0) get q = S64_MAX."""
    u = (hash32_3_j(x, ids, r) & _u32(0xFFFF)).astype(jnp.int64)
    neg = _neg_ln()[u]
    q = _magic_divide(neg, m_arr, k_arr)
    return jnp.where(k_arr > 0, q, jnp.int64((1 << 63) - 1))


# ---------------------------------------------------------------------------
# flattened map
# ---------------------------------------------------------------------------


class FlatMap:
    """CrushMap flattened to dense arrays. Bucket index bid = -1 - id."""

    def __init__(self, m: CrushMap, choose_args_name: str | None = None):
        for b in m.buckets.values():
            if b.alg != STRAW2:
                raise ValueError(
                    "device mapper requires straw2 buckets (bucket %d has "
                    "alg %d)" % (b.id, b.alg))
        t = m.tunables
        if t.choose_local_tries or t.choose_local_fallback_tries:
            raise ValueError("device mapper requires local tries == 0")
        B = m.max_buckets or 1
        S = max((b.size for b in m.buckets.values()), default=1) or 1
        self.B, self.S = B, S
        self.max_devices = m.max_devices
        self.tunables = t
        size = np.zeros(B, np.int32)
        btype = np.zeros(B, np.int32)
        items = np.zeros((B, S), np.int32)
        ids = np.zeros((B, S), np.int32)
        cargs = (m.choose_args.get(choose_args_name)
                 if choose_args_name else None)
        n_pos = 1
        if cargs:
            n_pos = max((len(ws.weight_sets) for ws in cargs.values()
                         if ws.weight_sets), default=1) or 1
        pos_w = np.zeros((n_pos, B, S), np.int32)
        for b in m.buckets.values():
            bid = -1 - b.id
            size[bid] = b.size
            btype[bid] = b.type
            items[bid, :b.size] = b.items
            ids[bid, :b.size] = b.items
            for p in range(n_pos):
                pos_w[p, bid, :b.size] = b.item_weights
            if cargs and b.id in cargs:
                ws = cargs[b.id]
                if ws.ids is not None:
                    ids[bid, :b.size] = ws.ids
                if ws.weight_sets:
                    for p in range(n_pos):
                        src = ws.weight_sets[min(p, len(ws.weight_sets) - 1)]
                        pos_w[p, bid, :b.size] = src
        depth: dict[int, int] = {}

        def _depth(bid_id: int) -> int:
            if bid_id in depth:
                return depth[bid_id]
            b = m.buckets[bid_id]
            d = 1 + max((_depth(i) for i in b.items if i < 0), default=0)
            depth[bid_id] = d
            return d

        self.max_depth = max((_depth(i) for i in m.buckets), default=1)
        # magic-division constants per (pos, bucket, item) weight — the
        # divisors are map constants, so the slow emulated int64 divide
        # becomes a 128-bit multiply-shift on device
        magic_m = np.zeros((n_pos, B, S), np.uint64)
        magic_k = np.zeros((n_pos, B, S), np.int32)
        for p in range(n_pos):
            for bi in range(B):
                for si in range(S):
                    M, k = magic_for_divisor(int(pos_w[p, bi, si]))
                    magic_m[p, bi, si] = M
                    magic_k[p, bi, si] = k
        self.size = jnp.asarray(size)
        self.btype = jnp.asarray(btype)
        self.items = jnp.asarray(items)
        self.ids = jnp.asarray(ids)
        self.magic_m = jnp.asarray(magic_m)
        self.magic_k = jnp.asarray(magic_k)
        self.neg_ln = _neg_ln()              # materialise outside jit
        self.n_pos = n_pos
        self.rules = dict(m.rules)


# ---------------------------------------------------------------------------
# vector choose primitives
# ---------------------------------------------------------------------------


def _straw2_choose(fm: FlatMap, bid, x, r, pos):
    """Winning item per lane. bid [L] bucket indices; pos [L] output
    positions (selects the choose_args weight-set, CrushWrapper.h:1500)."""
    idv = fm.ids[bid]                        # [L, S]
    if fm.n_pos == 1:
        m_arr = fm.magic_m[0][bid]
        k_arr = fm.magic_k[0][bid]
    else:
        p = jnp.minimum(pos, fm.n_pos - 1)
        m_arr = fm.magic_m[p, bid]
        k_arr = fm.magic_k[p, bid]
    q = _straw2_draw_q(x[:, None], idv, r[:, None], m_arr, k_arr)
    valid = jnp.arange(fm.S)[None, :] < fm.size[bid][:, None]
    q = jnp.where(valid, q, jnp.int64((1 << 63) - 1))
    win = jnp.argmin(q, axis=1)
    return fm.items[bid, win].astype(jnp.int32)


def _descend(fm: FlatMap, take_bid, x, r, want_type: int, pos):
    """Walk bucket->bucket until an item of want_type.

    Returns (item, ok, perm_fail): ok = reached an item of the wanted
    type; perm_fail = hit a wrong-type device (host skips the replica
    permanently, mapper.c:516-520); neither = retryable (empty bucket).
    """
    L = x.shape[0]
    cur = take_bid
    item = jnp.full((L,), ITEM_NONE, jnp.int32)
    ok = jnp.zeros((L,), bool)
    perm = jnp.zeros((L,), bool)
    done = fm.size[cur] == 0                 # empty bucket: retryable
    for _ in range(fm.max_depth):
        chosen = _straw2_choose(fm, cur, x, r, pos)
        is_bucket = chosen < 0
        cbid = jnp.where(is_bucket, -1 - chosen, 0)
        ctype = jnp.where(is_bucket, fm.btype[cbid], 0)
        oob = (~is_bucket) & (chosen >= fm.max_devices)
        reach = (~done) & (ctype == want_type) & (~oob)
        wrongdev = (~done) & (~reach) & ((~is_bucket) | oob)
        empty_next = (~done) & (~reach) & is_bucket & (fm.size[cbid] == 0)
        item = jnp.where(reach, chosen, item)
        ok = ok | reach
        perm = perm | wrongdev
        done = done | reach | wrongdev | empty_next
        cur = jnp.where((~done) & is_bucket, cbid, cur)
    return item, ok, perm


def _is_out(dev_weights, item, x):
    """Reweight rejection (mapper.c:402-416)."""
    idx = jnp.clip(item, 0, dev_weights.shape[0] - 1)
    w = dev_weights[idx]
    oob = (item >= dev_weights.shape[0]) | (item < 0)
    hh = (hash32_2_j(x, item) & _u32(0xFFFF)).astype(jnp.int32)
    return oob | (w == 0) | ((w < 0x10000) & (hh >= w))


# ---------------------------------------------------------------------------
# firstn / indep
# ---------------------------------------------------------------------------


def _choose_firstn_vec(fm: FlatMap, take_bid, xs, numrep: int,
                       result_max: int, want_type: int,
                       recurse_to_leaf: bool, dev_weights,
                       tries: int, recurse_tries: int, vary_r: int,
                       stable: int):
    """crush_choose_firstn (mapper.c:438-626) for local-tries==0: per
    replica, retry whole descents while collided/rejected (masked
    lanes); chooseleaf recursion selects one leaf per chosen bucket."""
    L = xs.shape[0]
    slots = min(numrep, result_max)
    out = jnp.full((L, slots), ITEM_NONE, jnp.int32)      # level items
    leaves = jnp.full((L, slots), ITEM_NONE, jnp.int32)   # devices
    outpos = jnp.zeros((L,), jnp.int32)

    result_slots = out.shape[1]

    def rep_body(rep, carry):
        out, leaves, outpos = carry

        def body(state):
            ftotal, active, out, leaves, outpos = state
            r = jnp.full((L,), 0, jnp.int32) + rep + ftotal
            item, ok, perm = _descend(fm, take_bid, xs, r, want_type,
                                      outpos)
            if recurse_to_leaf:
                if vary_r:
                    sub_r = r >> (vary_r - 1)
                else:
                    sub_r = jnp.zeros_like(r)
                rep_i = (jnp.zeros_like(outpos) if stable else outpos)
                bid_in = jnp.where(item < 0, -1 - item, 0)

                def inner_body(istate):
                    ift, iact, leaf, leaf_ok = istate
                    r_in = rep_i + sub_r + ift
                    cand, cok, _cperm = _descend(
                        fm, bid_in, xs, r_in, 0, outpos)
                    cok = cok & (item < 0)
                    # leaf collision: the recursive call checks candidates
                    # against leaves already placed in out2[0..outpos)
                    # (mapper.c:535-541 with out=out2)
                    cok = cok & ~jnp.any(leaves == cand[:, None], axis=1)
                    cok = cok & ~_is_out(dev_weights, cand, xs)
                    take = iact & cok
                    leaf = jnp.where(take, cand, leaf)
                    leaf_ok = leaf_ok | take
                    iact = iact & (~cok) & (ift + 1 < recurse_tries)
                    return ift + 1, iact, leaf, leaf_ok

                izero = jnp.zeros((L,), jnp.int32)
                leaf0 = jnp.full((L,), ITEM_NONE, jnp.int32)
                _, _, leaf, leaf_ok = jax.lax.while_loop(
                    lambda s: jnp.any(s[1]), inner_body,
                    (izero, active & ok, leaf0, jnp.zeros((L,), bool)))
                final, final_ok = leaf, ok & leaf_ok
            else:
                final = item
                final_ok = ok
                if want_type == 0:
                    final_ok = final_ok & ~_is_out(dev_weights, item, xs)
            collide = jnp.any(out == item[:, None], axis=1) & ok
            success = (active & final_ok & ~collide
                       & (outpos < result_slots))
            slot = jnp.arange(result_slots)[None, :] == outpos[:, None]
            put = slot & success[:, None]
            out = jnp.where(put, item[:, None], out)
            leaves = jnp.where(put, final[:, None], leaves)
            outpos = outpos + success.astype(jnp.int32)
            ftotal = ftotal + 1
            active = active & ~success & ~perm & (ftotal < tries)
            return ftotal, active, out, leaves, outpos

        z = jnp.zeros((L,), jnp.int32)
        act = jnp.ones((L,), bool)
        _, _, out, leaves, outpos = jax.lax.while_loop(
            lambda s: jnp.any(s[1]), body, (z, act, out, leaves, outpos))
        return out, leaves, outpos

    out, leaves, outpos = jax.lax.fori_loop(
        0, numrep, rep_body, (out, leaves, outpos))
    return (leaves if recurse_to_leaf else out), outpos


def _choose_indep_vec(fm: FlatMap, take_bid, xs, numrep: int,
                      result_max: int, want_type: int,
                      recurse_to_leaf: bool, dev_weights,
                      tries: int, recurse_tries: int):
    """crush_choose_indep (mapper.c:633-821): positionally-stable, slots
    left UNDEF retry with r advanced by numrep per round (numrep is the
    full replica count even when fewer slots fit result_max)."""
    L = xs.shape[0]
    slots = min(numrep, result_max)
    out = jnp.full((L, slots), ITEM_UNDEF, jnp.int32)
    leaves = jnp.full((L, slots), ITEM_UNDEF, jnp.int32)
    pos0 = jnp.zeros((L,), jnp.int32)

    def body(state):
        ftotal, out, leaves = state

        def rep_body(rep, carry):
            out, leaves = carry
            undecided = out[:, rep] == ITEM_UNDEF
            r = jnp.full((L,), 0, jnp.int32) + rep + numrep * ftotal
            item, ok, perm = _descend(fm, take_bid, xs, r, want_type, pos0)
            collide = jnp.any(out == item[:, None], axis=1) & ok
            if recurse_to_leaf:
                bid_in = jnp.where(item < 0, -1 - item, 0)
                pos_r = jnp.full((L,), 0, jnp.int32) + rep

                def inner_body(istate):
                    ift, iact, leaf, leaf_ok = istate
                    r_in = r + rep + numrep * ift
                    cand, cok, _cp = _descend(fm, bid_in, xs, r_in, 0,
                                              pos_r)
                    cok = cok & (item < 0)
                    cok = cok & ~_is_out(dev_weights, cand, xs)
                    take = iact & cok
                    leaf = jnp.where(take, cand, leaf)
                    leaf_ok = leaf_ok | take
                    iact = iact & (~cok) & (ift + 1 < recurse_tries)
                    return ift + 1, iact, leaf, leaf_ok

                izero = jnp.zeros((L,), jnp.int32)
                leaf0 = jnp.full((L,), ITEM_NONE, jnp.int32)
                _, _, leaf, leaf_ok = jax.lax.while_loop(
                    lambda s: jnp.any(s[1]), inner_body,
                    (izero, undecided & ok & ~collide, leaf0,
                     jnp.zeros((L,), bool)))
                final, final_ok = leaf, ok & leaf_ok
            else:
                final = item
                final_ok = ok
                if want_type == 0:
                    final_ok = final_ok & ~_is_out(dev_weights, item, xs)
            success = undecided & final_ok & ~collide
            permfail = undecided & perm
            col = jnp.arange(slots)[None, :] == rep
            out = jnp.where(col & success[:, None], item[:, None], out)
            out = jnp.where(col & permfail[:, None], ITEM_NONE, out)
            leaves = jnp.where(col & success[:, None], final[:, None],
                               leaves)
            leaves = jnp.where(col & permfail[:, None], ITEM_NONE, leaves)
            return out, leaves

        out, leaves = jax.lax.fori_loop(0, slots, rep_body, (out, leaves))
        return ftotal + 1, out, leaves

    def cond(state):
        ftotal, out, _ = state
        return jnp.any(out == ITEM_UNDEF) & (ftotal < tries)

    z = jnp.zeros((), jnp.int32)
    _, out, leaves = jax.lax.while_loop(cond, body, (z, out, leaves))
    res = leaves if recurse_to_leaf else out
    return jnp.where(res == ITEM_UNDEF, ITEM_NONE, res)


# ---------------------------------------------------------------------------
# rule driver
# ---------------------------------------------------------------------------


class DeviceMapper:
    """Bulk do_rule on device for straw2 maps with single-choose rules.

    do_rule_batch(ruleno, xs, result_max, dev_weights) mirrors
    CrushWrapper::do_rule over a whole batch of inputs; results carry
    ITEM_NONE holes exactly like the host engine.
    """

    def __init__(self, crushmap: CrushMap,
                 choose_args_name: str | None = None):
        self.fm = FlatMap(crushmap, choose_args_name)
        self.map = crushmap

    def _compile(self, ruleno: int, result_max: int):
        rule = self.fm.rules[ruleno]
        t = self.fm.tunables
        tries = t.choose_total_tries + 1     # historical off-by-one
        leaf_tries = 0
        vary_r = t.chooseleaf_vary_r
        stable = t.chooseleaf_stable
        take_id = None
        plan = None
        for op, arg1, arg2 in rule.steps:
            if op == TAKE:
                take_id = arg1
            elif op == SET_CHOOSE_TRIES:
                if arg1 > 0:
                    tries = arg1
            elif op == SET_CHOOSELEAF_TRIES:
                if arg1 > 0:
                    leaf_tries = arg1
            elif op == SET_CHOOSELEAF_VARY_R:
                if arg1 >= 0:
                    vary_r = arg1
            elif op == SET_CHOOSELEAF_STABLE:
                if arg1 >= 0:
                    stable = arg1
            elif op in (CHOOSE_FIRSTN, CHOOSELEAF_FIRSTN,
                        CHOOSE_INDEP, CHOOSELEAF_INDEP):
                if plan is not None:
                    raise ValueError(
                        "device mapper supports a single choose step")
                if take_id is None or take_id >= 0:
                    raise ValueError("choose without a bucket take")
                numrep = arg1
                if numrep <= 0:
                    numrep += result_max
                firstn = op in (CHOOSE_FIRSTN, CHOOSELEAF_FIRSTN)
                leaf = op in (CHOOSELEAF_FIRSTN, CHOOSELEAF_INDEP)
                plan = (take_id, numrep, arg2, firstn, leaf)
            elif op == EMIT:
                pass
        if plan is None:
            raise ValueError("rule has no choose step")
        take_id, numrep, want_type, firstn, leaf = plan
        if firstn:
            recurse = (leaf_tries if leaf_tries
                       else (1 if t.chooseleaf_descend_once else tries))
        else:
            recurse = leaf_tries if leaf_tries else 1
        fm = self.fm
        take_bid_val = -1 - take_id

        @jax.jit
        def run(xs, dev_weights):
            L = xs.shape[0]
            take_bid = jnp.full((L,), take_bid_val, jnp.int32)
            if firstn:
                res, _ = _choose_firstn_vec(
                    fm, take_bid, xs, numrep, result_max, want_type,
                    leaf, dev_weights, tries, recurse, vary_r, stable)
            else:
                res = _choose_indep_vec(
                    fm, take_bid, xs, numrep, result_max, want_type,
                    leaf, dev_weights, tries, recurse)
            return res

        return run

    @functools.lru_cache(maxsize=None)
    def _compiled(self, ruleno: int, result_max: int):
        return self._compile(ruleno, result_max)

    def do_rule_batch(self, ruleno: int, xs, result_max: int,
                      dev_weights) -> np.ndarray:
        """xs: int array [L] of inputs (pps values); dev_weights: int32
        [max_devices] 16.16 reweights.  Returns [L, numrep] int32 with
        ITEM_NONE holes."""
        fn = self._compiled(ruleno, result_max)
        xs = jnp.asarray(np.asarray(xs, dtype=np.int64) & 0xFFFFFFFF,
                         dtype=jnp.uint32)
        w = jnp.asarray(np.asarray(dev_weights, dtype=np.int32))
        return np.asarray(fn(xs, w))
