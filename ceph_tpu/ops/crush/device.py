"""Vectorized CRUSH mapping on device: one jitted program maps millions
of PGs at once.

This is the TPU replacement for the reference's threaded bulk mapper
(src/osd/OSDMapMapping.h:18-120 ParallelPGMapper) and the inner loops it
shards (crush_do_rule / crush_choose_firstn / crush_choose_indep,
src/crush/mapper.c:438-821): the PG axis becomes the vector lane axis
and the straw2 exponential draw (mapper.c:316-345) runs vectorized with
results bit-identical to the host engine (ceph_tpu.ops.crush.host) and
the reference golden vectors.

Bit-exactness strategy (the straw2 winner is argmax of
trunc((crush_ln(u)-2^48)/w), equivalently argmin of
q = floor((2^48-crush_ln(u))//w) with first-index tie-break):

* **f32 fast path**: q is approximated as g_f32(u) * (1/w) where g_f32
  is a degree-7 polynomial in the mantissa of u+1 fitted to the exact
  crush_ln table (max abs deviation DELTA, measured exhaustively over
  all 65536 inputs).  A per-item error bound
  E_i = DELTA/w_i + |q_i|*2^-14 + 4 makes the winner *provably* exact
  whenever the f32 gap between best and second-best exceeds E_1 + E_2.
  That covers ~99.4% of draws; no int64, no table lookups, fuses into
  a single XLA elementwise+reduce pass.
* **exact top-2 resolution**: in resolve mode the remaining draws are
  settled by computing the exact integer q for only the top-2
  candidates — crush_ln via one-hot MXU table fetches on an [L,2]
  slice (neg_ln_mxu) and an exact base-2^13 schoolbook division.
  Sound because any item outside the top-2 is > E away from the
  minimum (checked against the third-best).
* **host dust**: lanes where even the top-3 are inside the bound
  (~1e-5 of visits) fall back to the scalar host engine.

Retry control flow (collision/rejection retries, mapper.c:475-626) is
restructured for SIMD: each replica gets one optimistic full-width
"attempt" (the overwhelmingly common case), and the few lanes that
collide or get rejected are compacted (jnp.nonzero + gather) into a
small tail batch that replays the full retry semantics.  A first pass
runs the f32 path flagging uncertain lanes; a second pass re-runs only
flagged lanes (~0.5%) in resolve mode.

Device scope (the modern "optimal" tunables profile): straw2 buckets at
every level, choose_local_tries == choose_local_fallback_tries == 0,
rules of shape TAKE -> one CHOOSE/CHOOSELEAF step -> EMIT.  Anything
else falls back to the host interpreter, which remains the general spec.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from ...models.crushmap import (
    CHOOSE_FIRSTN,
    CHOOSE_INDEP,
    CHOOSELEAF_FIRSTN,
    CHOOSELEAF_INDEP,
    EMIT,
    ITEM_NONE,
    ITEM_UNDEF,
    SET_CHOOSE_TRIES,
    SET_CHOOSELEAF_TRIES,
    SET_CHOOSELEAF_STABLE,
    SET_CHOOSELEAF_VARY_R,
    STRAW2,
    TAKE,
    CrushMap,
)
from ._ln_tables import LL_TBL, RH_LH_TBL

S64_MAX = (1 << 63) - 1
LN_ONE = 1 << 48  # 2^48: crush_ln scale at u=0xFFFF+1

HASH_SEED = 1315423911


# ---------------------------------------------------------------------------
# jnp primitives (bit-for-bit mirrors of hashes.py / host.crush_ln)
# ---------------------------------------------------------------------------

def _u32(v):
    return jnp.asarray(v, jnp.uint32)


def _mix(a, b, c):
    a = a - b; a = a - c; a = a ^ (c >> _u32(13))
    b = b - c; b = b - a; b = b ^ (a << _u32(8))
    c = c - a; c = c - b; c = c ^ (b >> _u32(13))
    a = a - b; a = a - c; a = a ^ (c >> _u32(12))
    b = b - c; b = b - a; b = b ^ (a << _u32(16))
    c = c - a; c = c - b; c = c ^ (b >> _u32(5))
    a = a - b; a = a - c; a = a ^ (c >> _u32(3))
    b = b - c; b = b - a; b = b ^ (a << _u32(10))
    c = c - a; c = c - b; c = c ^ (b >> _u32(15))
    return a, b, c


def hash32_3_j(a, b, c):
    a, b, c = _u32(a), _u32(b), _u32(c)
    h = _u32(HASH_SEED) ^ a ^ b ^ c
    x, y = _u32(231232), _u32(1232)
    a, b, h = _mix(a, b, h)
    c, x, h = _mix(c, x, h)
    y, a, h = _mix(y, a, h)
    b, x, h = _mix(b, x, h)
    y, c, h = _mix(y, c, h)
    return h


def hash32_2_j(a, b):
    a, b = _u32(a), _u32(b)
    h = _u32(HASH_SEED) ^ a ^ b
    x, y = _u32(231232), _u32(1232)
    a, b, h = _mix(a, b, h)
    x, a, h = _mix(x, a, h)
    b, y, h = _mix(b, y, h)
    return h


# ---------------------------------------------------------------------------
# f32 certainty draw
#
# g_f32(u) ~ 2^48 - crush_ln(u): exponent via f32 bit tricks (u+1 <= 2^16
# is f32-exact), mantissa log via a degree-7 polynomial least-squares
# fitted to the exact table values (which themselves deviate from smooth
# log2 by ~2^29.6 — the table's own 16-bit-mantissa quantization noise,
# so a closer smooth fit is impossible).  _G_DELTA is the exhaustively
# measured max |g_f32(u) - (2^48-crush_ln(u))| over all 65536 inputs
# (f32-simulated Horner), doubled for device reassociation/FMA headroom.
# Regenerated + verified by tests/test_crush_device.py::TestF32Draw.
# ---------------------------------------------------------------------------

_LOG2_COEF = (
    5.405197953223251e-06, 1.4423911571502686, -0.7177810668945312,
    0.46077853441238403, -0.2956102788448334, 0.15550757944583893,
    -0.05415186285972595, 0.00885970052331686,
)
_G_DELTA = 825135650.0 * 2.0
_EPS_Q = 2.0 ** -21      # q = g*recipf relative error: recipf is the
                         # correctly-rounded f32 of 1/w (2^-24) plus one
                         # product rounding (2^-24), with 4x margin
_E_CONST = 4.0           # floor slack + crumbs
_BIG = jnp.float32(3.0e38)


def _g_f32(u):
    """f32 approximation of 2^48 - crush_ln(u), u int in [0, 0xFFFF]."""
    x = (u + 1).astype(jnp.int32)
    xf = x.astype(jnp.float32)
    b = jax.lax.bitcast_convert_type(xf, jnp.int32)
    e = ((b >> 23) - 127).astype(jnp.float32)
    mm = jax.lax.bitcast_convert_type(
        (b & 0x7FFFFF) | 0x3F800000, jnp.float32) - jnp.float32(1.0)
    acc = jnp.float32(_LOG2_COEF[-1])
    for c in _LOG2_COEF[-2::-1]:
        acc = acc * mm + jnp.float32(c)
    return jnp.float32(2.0 ** 44) * ((jnp.float32(16.0) - e) - acc)


# ---------------------------------------------------------------------------
# gather-free table lookups (for the exact top-2 resolution)
#
# TPU gathers are scalar-rate while the one-hot int8 matmul rides the
# MXU; table values are split into 8-bit limbs offset by -128, the index
# becomes a one-hot row, and a single [N, K] @ [K, n_limbs] int8->int32
# matmul fetches all limbs.  One row is hot, so each output element IS a
# limb value (no summation error).
# ---------------------------------------------------------------------------


def pack_limbs(table: np.ndarray, n_limbs: int,
               offset: int = 0) -> np.ndarray:
    """[K] int -> [K, n_limbs] int8 of 8-bit limbs of (v - offset),
    biased by -128 into signed range."""
    t = table.astype(object) - offset
    out = np.zeros((len(t), n_limbs), dtype=np.int8)
    for i, v in enumerate(t):
        v = int(v)
        assert 0 <= v < (1 << (8 * n_limbs)), (v, n_limbs)
        for j in range(n_limbs):
            out[i, j] = ((v >> (8 * j)) & 0xFF) - 128
    return out


def unpack_limbs(l32, n_limbs: int, offset: int = 0,
                 dtype=jnp.int64):
    """[.., n_limbs] int32 (from the one-hot matmul) -> [..] dtype."""
    acc = jnp.zeros(l32.shape[:-1], jnp.int64)
    for j in range(n_limbs):
        limb = (l32[..., j] + 128).astype(jnp.int64)
        acc = acc + (limb << (8 * j))
    return (acc + offset).astype(dtype)


def unpack_limbs32(l32, n_limbs: int, offset: int = 0):
    """int32 fast-path unpack for values that fit 31 bits (ids, recip
    bit patterns, sizes): int64 vector math halves TPU throughput and
    doubles HBM traffic, so the hot path avoids it."""
    acc = l32[..., 0] + 128
    for j in range(1, n_limbs):
        acc = acc + ((l32[..., j] + 128) << (8 * j))
    if offset:
        acc = acc + offset
    return acc


def onehot_fetch(idx, limb_table):
    """idx [..] int32 in [0, K); limb_table [K, C] int8.
    Returns [.., C] int32 via one MXU matmul."""
    K = limb_table.shape[0]
    shape = idx.shape
    flat = idx.reshape(-1)
    oh = (flat[:, None] == jnp.arange(K, dtype=jnp.int32)[None, :]
          ).astype(jnp.int8)
    out = jnp.matmul(oh, limb_table, preferred_element_type=jnp.int32)
    return out.reshape(*shape, limb_table.shape[1])


_RH_NP = np.array(RH_LH_TBL[0::2], dtype=np.uint64)   # 129 reciprocals
_LH_NP = np.array(RH_LH_TBL[1::2], dtype=np.uint64)
_LL_NP = np.array(LL_TBL, dtype=np.uint64)
_LN_NLIMB = 7  # values < 2^56
_RHLH_LIMBS_NP = np.concatenate(
    [pack_limbs(_RH_NP, _LN_NLIMB), pack_limbs(_LH_NP, _LN_NLIMB)], axis=1)
_LL_LIMBS_NP = pack_limbs(_LL_NP, _LN_NLIMB)


def neg_ln_mxu(u, rhlh_limbs, ll_limbs):
    """2^48 - crush_ln(u) for u int64 in [0, 0xFFFF], no gathers:
    the iexpon/normalisation arithmetic stays on the VPU and the three
    table fetches (RH, LH, LL — crush_ln's own structure, mapper.c:
    226-268) ride the MXU as one-hot matmuls."""
    x = u.astype(jnp.int64) + 1            # [1, 0x10000]
    bl = jnp.ones_like(x)
    for kbit in range(1, 17):
        bl = bl + (x >= (1 << kbit)).astype(jnp.int64)
    need = (x & 0x18000) == 0
    bits = jnp.maximum(16 - bl, 0)
    x2 = jnp.where(need, x << bits, x)
    iexpon = jnp.where(need, 15 - bits, 15)
    p = ((x2 >> 8) - 128).astype(jnp.int32)          # [0, 128]
    rl = onehot_fetch(p, rhlh_limbs)
    rh = unpack_limbs(rl[..., :_LN_NLIMB], _LN_NLIMB)
    lh = unpack_limbs(rl[..., _LN_NLIMB:], _LN_NLIMB)
    xl64 = (x2 * rh) >> 48
    i2 = (xl64 & 0xFF).astype(jnp.int32)
    ll = unpack_limbs(onehot_fetch(i2, ll_limbs), _LN_NLIMB)
    lh2 = (lh + ll) >> 4
    return (1 << 48) - ((iexpon << 44) + lh2)


def _exact_floordiv(neg, w64, recipf):
    """Exact floor(neg / w) for neg int64 in [0, 2^49), w64 int64 > 0:
    base-2^13 schoolbook long division with f32 digit estimation and
    +/-2-step correction (each digit < 2^13, so the f32 estimate of
    cur/w is within 2 of the true digit).  Replaces per-item
    magic-constant division: w arrives at runtime here."""
    q = jnp.zeros_like(neg)
    r = jnp.zeros_like(neg)
    for shift in (39, 26, 13, 0):
        d = (neg >> shift) & 0x1FFF
        cur = (r << 13) + d
        est = (cur.astype(jnp.float32) * recipf).astype(jnp.int64)
        est = jnp.clip(est, 0, 1 << 13)
        rem = cur - est * w64
        for _ in range(2):
            lo = rem < 0
            est = jnp.where(lo, est - 1, est)
            rem = jnp.where(lo, rem + w64, rem)
        for _ in range(2):
            hi = rem >= w64
            est = jnp.where(hi, est + 1, est)
            rem = jnp.where(hi, rem - w64, rem)
        q = (q << 13) + est
        r = rem
    return q


def _exact3_winner(fm, us, ws, ss):
    """Exact straw2 comparison among the three f32 front-runners:
    integer q = floor((2^48-crush_ln(u))/w) for each, lexicographic
    (q, slot) minimum — the first-slot tie-break mirrors mapper.c's
    strict-> draw comparison keeping the earliest maximum.  Resolving
    three (not two) candidates pushes the residual ambiguity (true
    winner outside the resolved set) from ~2.5e-5 per visit to ~1e-7,
    so retry-heavy lanes no longer shed host-fallback dust."""
    u = jnp.stack(us, axis=-1)
    neg = neg_ln_mxu(u, jnp.asarray(_RHLH_LIMBS_NP),
                     jnp.asarray(_LL_LIMBS_NP))
    w = jnp.stack(ws, axis=-1).astype(jnp.int64) & 0xFFFFFFFF
    wsafe = jnp.maximum(w, 1)
    recipf = jnp.float32(1.0) / wsafe.astype(jnp.float32)
    q = _exact_floordiv(neg, wsafe, recipf)
    q = jnp.where(w > 0, q, jnp.int64(S64_MAX))
    best_q, best_s = q[..., 0], ss[0]
    for j in range(1, len(ss)):
        qj, sj = q[..., j], ss[j]
        take = (qj < best_q) | ((qj == best_q) & (sj < best_s))
        best_q = jnp.where(take, qj, best_q)
        best_s = jnp.where(take, sj, best_s)
    return best_s


# ---------------------------------------------------------------------------
# flattened map
# ---------------------------------------------------------------------------


class _ConstRow:
    """Host-side row of one bucket (the static TAKE root): lets level-0
    draws skip the one-hot row fetch entirely (every lane shares the
    bucket, so ids/weights are jit-time constants)."""

    __slots__ = ("ids", "items", "recipf", "w", "size")

    def __init__(self, ids, items, recipf, w, size):
        self.ids = ids          # np [S] int32
        self.items = items      # np [S] int32
        self.recipf = recipf    # np [S] f32 (correctly-rounded 1/w)
        self.w = w              # np [S] int32
        self.size = size        # python int


class FlatMap:
    """CrushMap flattened to dense arrays. Bucket index bid = -1 - id."""

    def __init__(self, m: CrushMap, choose_args_name: str | None = None):
        for b in m.buckets.values():
            if b.alg != STRAW2:
                raise ValueError(
                    "device mapper requires straw2 buckets (bucket %d has "
                    "alg %d)" % (b.id, b.alg))
        t = m.tunables
        if t.choose_local_tries or t.choose_local_fallback_tries:
            raise ValueError("device mapper requires local tries == 0")
        B = m.max_buckets or 1
        S = max((b.size for b in m.buckets.values()), default=1) or 1
        self.B, self.S = B, S
        self.max_devices = m.max_devices
        self.tunables = t
        size = np.zeros(B, np.int32)
        btype = np.zeros(B, np.int32)
        items = np.zeros((B, S), np.int32)
        ids = np.zeros((B, S), np.int32)
        cargs = (m.choose_args.get(choose_args_name)
                 if choose_args_name else None)
        n_pos = 1
        if cargs:
            n_pos = max((len(ws.weight_sets) for ws in cargs.values()
                         if ws.weight_sets), default=1) or 1
        pos_w = np.zeros((n_pos, B, S), np.int64)
        for b in m.buckets.values():
            bid = -1 - b.id
            size[bid] = b.size
            btype[bid] = b.type
            items[bid, :b.size] = b.items
            ids[bid, :b.size] = b.items
            for p in range(n_pos):
                pos_w[p, bid, :b.size] = b.item_weights
            if cargs and b.id in cargs:
                ws = cargs[b.id]
                if ws.ids is not None:
                    ids[bid, :b.size] = ws.ids
                if ws.weight_sets:
                    for p in range(n_pos):
                        src = ws.weight_sets[min(p, len(ws.weight_sets) - 1)]
                        pos_w[p, bid, :b.size] = src
        depth: dict[int, int] = {}

        def _depth(bid_id: int) -> int:
            if bid_id in depth:
                return depth[bid_id]
            b = m.buckets[bid_id]
            d = 1 + max((_depth(i) for i in b.items if i < 0), default=0)
            depth[bid_id] = d
            return d

        self.max_depth = max((_depth(i) for i in m.buckets), default=1)
        self.n_pos = n_pos
        self.rules = dict(m.rules)

        # correctly-rounded f32 reciprocals of the 16.16 weights: full
        # mantissa keeps the q-product error inside the tight _EPS_Q
        with np.errstate(divide="ignore"):
            recipf = np.where(
                pos_w > 0,
                (np.float32(1.0)
                 / np.maximum(pos_w, 1).astype(np.float32)),
                np.float32(0.0)).astype(np.float32)
        self._recipbits_np = recipf.view(np.uint32).astype(np.int64)
        self._recipf_np = recipf
        self._w_np = pos_w

        # -- gather-free lookup tables -----------------------------------
        # per-(pos,bucket) row: for each item slot s, limbs
        # [ids(nl) | items(nl) | recip(4)], then size(2) + btype(2) at
        # the tail.  Fetched with ONE one-hot matmul per bucket visit.
        # Tables are built per requested item capacity S'
        # (row_limbs_for) so each descent level only pays for the
        # largest bucket actually reachable there.
        id_lo = min([0] + [int(v) for v in items.reshape(-1)]
                    + [int(v) for v in ids.reshape(-1)])
        id_hi = max([0] + [int(v) for v in items.reshape(-1)]
                    + [int(v) for v in ids.reshape(-1)])
        self.id_offset = id_lo
        self.nl_id = 3 if (id_hi - id_lo) < (1 << 24) else 4
        # without choose_args id remapping the ids ARE the items: rows
        # then carry one copy and the fetch/unpack does half the work
        self.ids_equal_items = bool(np.array_equal(ids, items))
        self._ids_np = ids
        self._items_np = items
        self._size_np = size
        self._btype_np = btype
        self._row_cache: dict[int, np.ndarray] = {}
        self._roww_cache: dict[int, np.ndarray] = {}
        self._wpair_cache: dict[int, np.ndarray] = {}
        # per-bucket metadata fetch for arbitrary bucket ids (the child
        # bucket chosen during descent): size(2) + btype(2)
        meta = np.zeros((B, 4), np.int8)
        meta[:, 0:2] = pack_limbs(size, 2)
        meta[:, 2:4] = pack_limbs(btype, 2)
        self.meta_limbs = jnp.asarray(meta)

    def row_limbs_for(self, S: int) -> np.ndarray:
        """[n_pos*B, (2*nl_id+3)*S+4] int8 rows truncated to S item
        slots (only fetched for buckets whose size fits — callers pick
        S per level)."""
        tbl = self._row_cache.get(S)
        if tbl is not None:
            return tbl
        B, n_pos, nl = self.B, self.n_pos, self.nl_id
        dup = 0 if self.ids_equal_items else nl
        pi = nl + dup + 4
        rows = np.zeros((n_pos * B, pi * S + 4), np.int8)
        for p in range(n_pos):
            for bi in range(B):
                row = np.zeros((S, pi), np.int8)
                row[:, 0:nl] = pack_limbs(self._ids_np[bi, :S], nl,
                                          self.id_offset)
                if dup:
                    row[:, nl:2 * nl] = pack_limbs(
                        self._items_np[bi, :S], nl, self.id_offset)
                row[:, nl + dup:pi] = pack_limbs(
                    self._recipbits_np[p, bi, :S], 4)
                r = rows[p * B + bi]
                r[:pi * S] = row.reshape(-1)
                r[pi * S:pi * S + 2] = pack_limbs(
                    self._size_np[bi:bi + 1], 2)[0]
                r[pi * S + 2:] = pack_limbs(
                    self._btype_np[bi:bi + 1], 2)[0]
        # Cache as host numpy: this is lazily reached inside jit traces,
        # where jnp.asarray would bind the constant to the live trace and
        # the cached tracer would leak into later traces.
        self._row_cache[S] = rows
        return rows

    def roww_limbs_for(self, S: int) -> np.ndarray:
        """[n_pos*B, 4*S] int8 weight rows (resolve mode only)."""
        tbl = self._roww_cache.get(S)
        if tbl is not None:
            return tbl
        B, n_pos = self.B, self.n_pos
        rows = np.zeros((n_pos * B, 4 * S), np.int8)
        for p in range(n_pos):
            for bi in range(B):
                rows[p * B + bi] = pack_limbs(
                    self._w_np[p, bi, :S], 4).reshape(-1)
        self._roww_cache[S] = rows
        return rows

    def wpair_limbs_for(self, S: int) -> np.ndarray | None:
        """[n_pos*B*S, 4] int8 per-(bucket,slot) weight limbs: lets the
        resolve path fetch just the top-3 candidates' weights instead of
        unpacking [L, S] int64 rows.  None when the flattened table is
        too large for a one-hot fetch."""
        if self.n_pos * self.B * S > 65536:
            return None
        tbl = self._wpair_cache.get(S)
        if tbl is None:
            w = np.ascontiguousarray(
                self._w_np[:, :, :S]).reshape(-1)
            tbl = pack_limbs(w, 4)
            self._wpair_cache[S] = tbl
        return tbl

    def const_row(self, bucket_id: int, S: int) -> _ConstRow | None:
        """Host row of a single static bucket (level-0 fetch skip);
        None when positional weight-sets make rows lane-dependent."""
        if self.n_pos != 1 or bucket_id >= 0:
            return None
        bi = -1 - bucket_id
        return _ConstRow(
            ids=self._ids_np[bi, :S].copy(),
            items=self._items_np[bi, :S].copy(),
            recipf=self._recipf_np[0, bi, :S].copy(),
            w=self._w_np[0, bi, :S].astype(np.int64),
            size=int(self._size_np[bi]))


# ---------------------------------------------------------------------------
# vector choose primitives
# ---------------------------------------------------------------------------


def _fetch_row(fm: FlatMap, bid, pos, S: int):
    """One one-hot matmul fetches a bucket's full choose row:
    (ids [L,S], items [L,S], recipf [L,S] f32, size [L])."""
    if fm.n_pos == 1:
        idx = bid
    else:
        idx = jnp.minimum(pos, fm.n_pos - 1) * fm.B + bid
    nl = fm.nl_id
    dup = 0 if fm.ids_equal_items else nl
    pi = nl + dup + 4
    r = onehot_fetch(idx, fm.row_limbs_for(S))       # [L, pi*S+4] int32
    per = r[..., :pi * S].reshape(*bid.shape, S, pi)
    ids = unpack_limbs32(per[..., 0:nl], nl, fm.id_offset)
    if dup:
        items = unpack_limbs32(per[..., nl:nl + dup], nl, fm.id_offset)
    else:
        items = ids
    rb = unpack_limbs32(per[..., nl + dup:pi], 4)
    recipf = jax.lax.bitcast_convert_type(rb, jnp.float32)
    size = unpack_limbs32(r[..., pi * S:pi * S + 2], 2)
    return ids, items, recipf, size


def _fetch_w(fm: FlatMap, bid, pos, S: int):
    """[L,S] int64 weights (resolve mode)."""
    if fm.n_pos == 1:
        idx = bid
    else:
        idx = jnp.minimum(pos, fm.n_pos - 1) * fm.B + bid
    r = onehot_fetch(idx, fm.roww_limbs_for(S))
    per = r.reshape(*bid.shape, S, 4)
    return unpack_limbs(per, 4, 0, jnp.int64)


def _fetch_meta(fm: FlatMap, bid):
    """(size [L], btype [L]) of arbitrary bucket indices."""
    r = onehot_fetch(bid, fm.meta_limbs)
    size = unpack_limbs32(r[..., 0:2], 2)
    btype = unpack_limbs32(r[..., 2:4], 2)
    return size, btype


def _pick(arr, sel):
    """Gather-free row select: arr [L,S], sel [L,S] one-hot bool."""
    return jnp.sum(jnp.where(sel, arr, jnp.zeros_like(arr)), axis=1)


def _straw2_choose_exact(fm: FlatMap, bid, x, r, pos, S: int,
                         crow: _ConstRow | None = None):
    """Fully exact straw2 draw: integer q for every slot (no f32
    shortcut, no flags).  Used for the dust lanes whose top-3 interval
    resolution stays ambiguous — replaces the scalar host fallback so
    the whole mapping pipeline can stay device-resident."""
    if crow is not None:
        ids = jnp.asarray(crow.ids)[None, :]
        items_a = jnp.asarray(crow.items)[None, :]
        recipf = jnp.asarray(crow.recipf)[None, :]
        size = jnp.int32(crow.size)
        valid = (jnp.arange(S) < size)[None, :] & (recipf > 0)
        wv = jnp.asarray(crow.w)[None, :] * jnp.ones(
            (x.shape[0], 1), jnp.int64)
    else:
        ids, items_a, recipf, size = _fetch_row(fm, bid, pos, S)
        valid = (jnp.arange(S)[None, :] < size[:, None]) & (recipf > 0)
        wv = _fetch_w(fm, bid, pos, S)
    u = (hash32_3_j(x[:, None], ids, r[:, None])
         & _u32(0xFFFF)).astype(jnp.int64)
    neg = neg_ln_mxu(u, jnp.asarray(_RHLH_LIMBS_NP),
                     jnp.asarray(_LL_LIMBS_NP))
    w = wv & 0xFFFFFFFF
    wsafe = jnp.maximum(w, 1)
    rf = jnp.float32(1.0) / wsafe.astype(jnp.float32)
    q = _exact_floordiv(neg, wsafe, rf)
    q = jnp.where(valid & (w > 0), q, jnp.int64(S64_MAX))
    win = jnp.argmin(q, axis=1).astype(jnp.int32)  # first-slot ties
    selw = jnp.arange(S)[None, :] == win[:, None]
    item = jnp.sum(jnp.where(selw, items_a, 0), axis=1).astype(jnp.int32)
    return item, jnp.zeros((x.shape[0],), bool)


def _straw2_choose(fm: FlatMap, bid, x, r, pos, S: int, resolve,
                   crow: _ConstRow | None = None):
    """Winning item per lane via the f32 certainty draw.

    bid [L] bucket indices (ignored when crow fixes the bucket); pos [L]
    output positions (selects the choose_args weight-set,
    CrushWrapper.h:1500).  S = item capacity for this level.

    resolve: False = fast mode (flag marks lanes whose winner is not
    certain, caller re-runs them in resolve mode); True = exact top-3
    resolution (flag marks only top-3-inside-bound dust); "all" =
    fully exact integer draw for every slot (never flags).
    """
    if resolve == "all":
        return _straw2_choose_exact(fm, bid, x, r, pos, S, crow)
    if crow is not None:
        ids = jnp.asarray(crow.ids)[None, :]
        items_a = jnp.asarray(crow.items)[None, :]
        recipf = jnp.asarray(crow.recipf)[None, :]
        size = jnp.int32(crow.size)
        valid = (jnp.arange(S) < size)[None, :] & (recipf > 0)
    else:
        ids, items_a, recipf, size = _fetch_row(fm, bid, pos, S)
        valid = (jnp.arange(S)[None, :] < size[:, None]) & (recipf > 0)
    u = (hash32_3_j(x[:, None], ids, r[:, None])
         & _u32(0xFFFF)).astype(jnp.int32)
    g = _g_f32(u)
    q = jnp.where(valid, g * recipf, _BIG)
    E = (jnp.float32(_G_DELTA) * recipf + q * jnp.float32(_EPS_Q)
         + jnp.float32(_E_CONST))
    # contender intervals: exact q_i provably lies in [q_i-E_i, q_i+E_i]
    # (per-item bound — E varies with 1/w_i, so gap tests against a
    # single E would be unsound under skewed weights).  An item can be
    # the exact winner only if its lower bound reaches the smallest
    # upper bound.  Exactly one contender => winner proven.
    hi = jnp.where(valid, q + E, _BIG)
    low = jnp.where(valid, q - E, _BIG)
    min_hi = jnp.min(hi, axis=1)
    contend = valid & (low <= min_hi[:, None])
    ncont = jnp.sum(contend.astype(jnp.int32), axis=1)
    certain = ncont <= 1   # 0 = all-invalid: collapses to slot 0 below
    i1 = jnp.argmin(q, axis=1).astype(jnp.int32)
    win_c = jnp.argmax(contend, axis=1).astype(jnp.int32)
    win1 = jnp.where(ncont == 1, win_c, i1)
    if not resolve:
        win = win1
        flag = ~certain
    else:
        sel1 = jnp.arange(S)[None, :] == i1[:, None]
        qm = jnp.where(sel1, _BIG, q)
        i2 = jnp.argmin(qm, axis=1).astype(jnp.int32)
        sel2 = jnp.arange(S)[None, :] == i2[:, None]
        qm2 = jnp.where(sel2, _BIG, qm)
        i3 = jnp.argmin(qm2, axis=1).astype(jnp.int32)
        sel3 = jnp.arange(S)[None, :] == i3[:, None]
        u1 = _pick(u, sel1)
        u2 = _pick(u, sel2)
        u3 = _pick(u, sel3)
        wp = fm.wpair_limbs_for(S)
        if wp is not None:
            # per-(bucket,slot) pair fetch for just the three
            # candidates — the [L,S] int64 row unpack the old path did
            # dominated resolve-mode HBM traffic
            if fm.n_pos == 1:
                base = bid * S
            else:
                base = (jnp.minimum(pos, fm.n_pos - 1) * fm.B + bid) * S

            def _wfetch(slot, sel):
                wl = onehot_fetch(base + slot, wp)          # [L, 4]
                wv = unpack_limbs(wl, 4, 0, jnp.int64)
                return jnp.where(jnp.any(valid & sel, axis=1), wv,
                                 jnp.int64(0))

            w1 = _wfetch(i1, sel1)
            w2 = _wfetch(i2, sel2)
            w3 = _wfetch(i3, sel3)
        else:
            if crow is not None:
                wvalid = jnp.where(valid, jnp.asarray(crow.w)[None, :],
                                   jnp.int64(0))
            else:
                wv = _fetch_w(fm, bid, pos, S)
                wvalid = jnp.where(valid, wv, jnp.int64(0))
            w1 = _pick(wvalid, sel1)
            w2 = _pick(wvalid, sel2)
            w3 = _pick(wvalid, sel3)
        win3 = _exact3_winner(fm, (u1, u2, u3), (w1, w2, w3),
                              (i1, i2, i3))
        win = jnp.where(certain, win1, win3)
        # sound only when every contender was resolved exactly
        outside = contend & ~(sel1 | sel2 | sel3)
        flag = (~certain) & jnp.any(outside, axis=1)
    selw = jnp.arange(S)[None, :] == win[:, None]
    item = jnp.sum(jnp.where(selw, items_a, 0), axis=1).astype(jnp.int32)
    return item, flag


def _get_pallas_descend(fm: FlatMap, depth_sizes: tuple,
                        want_type: int):
    """Cached fused-descent kernel for (fm, depth_sizes, want_type);
    None when pallas is unavailable or the map exceeds its budget."""
    from . import pallas_draw
    if not pallas_draw.pallas_enabled():
        return None
    cache = fm.__dict__.setdefault("_pallas_cache", {})
    key = (depth_sizes, want_type)
    if key not in cache:
        cache[key] = pallas_draw.make_descend_kernel(
            fm, depth_sizes, want_type)
    return cache[key]


def _descend(fm: FlatMap, take_bid, x, r, want_type: int, pos,
             depth_sizes: tuple, resolve: bool,
             crow0: _ConstRow | None = None):
    """Walk bucket->bucket until an item of want_type.

    depth_sizes[d] = max bucket size reachable at depth d from the
    start set (static per rule), so each level's draw only pays for
    the buckets that can actually appear there.  crow0, when given, is
    the static level-0 bucket row (fetch-free).

    Returns (item, ok, perm_fail, flag): ok = reached an item of the
    wanted type; perm_fail = hit a wrong-type device (host skips the
    replica permanently, mapper.c:516-520); neither = retryable (empty
    bucket).  flag accumulates draw uncertainty over the levels
    actually walked.
    """
    L = x.shape[0]
    if not resolve:
        from . import pallas_draw
        if L % pallas_draw.TL == 0:
            fn = _get_pallas_descend(fm, depth_sizes, want_type)
            if fn is not None:
                item, status = fn(x, r, take_bid, pos)
                return (item, (status & 1) != 0, (status & 2) != 0,
                        (status & 4) != 0)
    cur = take_bid
    item = jnp.full((L,), ITEM_NONE, jnp.int32)
    ok = jnp.zeros((L,), bool)
    perm = jnp.zeros((L,), bool)
    flag = jnp.zeros((L,), bool)
    if crow0 is not None:
        done = jnp.full((L,), crow0.size == 0)
    else:
        cur_size, _ = _fetch_meta(fm, cur)
        done = cur_size == 0                 # empty bucket: retryable
    for d, S_d in enumerate(depth_sizes):
        chosen, f = _straw2_choose(fm, cur, x, r, pos, S_d, resolve,
                                   crow0 if d == 0 else None)
        flag = flag | ((~done) & f)
        is_bucket = chosen < 0
        cbid = jnp.where(is_bucket, -1 - chosen, 0)
        csize, cbtype = _fetch_meta(fm, cbid)
        ctype = jnp.where(is_bucket, cbtype, 0)
        oob = (~is_bucket) & (chosen >= fm.max_devices)
        reach = (~done) & (ctype == want_type) & (~oob)
        wrongdev = (~done) & (~reach) & ((~is_bucket) | oob)
        empty_next = (~done) & (~reach) & is_bucket & (csize == 0)
        item = jnp.where(reach, chosen, item)
        ok = ok | reach
        perm = perm | wrongdev
        done = done | reach | wrongdev | empty_next
        cur = jnp.where((~done) & is_bucket, cbid, cur)
    return item, ok, perm, flag


_SF_LO = 16


def small_fetch(table_i32, idx, n_limbs: int):
    """Gather-free elementwise fetch from a small runtime [D] int table
    (values < 2^(8*n_limbs)): one-hot MXU fetch over ceil(D/16) row
    groups + a 16-way in-register column select.  TPU gathers run at
    scalar rate; for the [L]/[L,S]-shaped cluster-state lookups
    (device reweights, up/exists bits, affinities) this is far faster.
    idx must already be clipped to [0, D)."""
    D = table_i32.shape[0]
    HI = -(-D // _SF_LO)
    t = jnp.pad(table_i32.astype(jnp.int32), (0, HI * _SF_LO - D))
    t = t.reshape(HI, _SF_LO)
    planes = [((t >> (8 * j)) & 0xFF) - 128 for j in range(n_limbs)]
    tl = jnp.concatenate(planes, axis=1).astype(jnp.int8)
    hi = (idx >> 4).astype(jnp.int32)
    lo = (idx & 15).astype(jnp.int32)
    r = onehot_fetch(hi, tl).reshape(*idx.shape, n_limbs, _SF_LO)
    sel = lo[..., None] == jnp.arange(_SF_LO)
    pl = jnp.sum(jnp.where(sel[..., None, :], r, 0), axis=-1)
    return unpack_limbs32(pl, n_limbs)


def _is_out(dev_weights, item, x):
    """Reweight rejection (mapper.c:402-416).  Reweights are 16.16
    capped at 0x10000 (17 bits), so three limb planes suffice."""
    idx = jnp.clip(item, 0, dev_weights.shape[0] - 1)
    w = small_fetch(dev_weights, idx, 3)
    oob = (item >= dev_weights.shape[0]) | (item < 0)
    hh = (hash32_2_j(x, item) & _u32(0xFFFF)).astype(jnp.int32)
    return oob | (w == 0) | ((w < 0x10000) & (hh >= w))


# ---------------------------------------------------------------------------
# firstn / indep
# ---------------------------------------------------------------------------

# optimistic retries fused into the full-width attempt pass; lanes
# still failing after these land in the pass-2 resolve set, which the
# device-resident resolve chain settles cheaply — three rounds balance
# full-width dense cost against resolve-set size
_ATTEMPT_TRIES = 3

# below this lane count the optimistic attempt + compacted tail isn't
# worth its bookkeeping; run the full retry loops directly
_ATTEMPT_MIN_L = 16384


def _firstn_full(fm: FlatMap, take_bid, xs, out, leaves, outpos,
                 numrep: int, result_max: int, want_type: int,
                 recurse_to_leaf: bool, dev_weights,
                 tries: int, recurse_tries: int, vary_r: int,
                 stable: int, outer_ds: tuple, inner_ds: tuple,
                 resolve: bool, rootc: _ConstRow | None):
    """crush_choose_firstn (mapper.c:438-626) for local-tries==0: per
    replica, retry whole descents while collided/rejected (masked
    lanes); chooseleaf recursion selects one leaf per chosen bucket.
    Full retry semantics; every lane replays from ftotal=0."""
    L = xs.shape[0]
    result_slots = out.shape[1]
    flag0 = jnp.zeros((L,), bool)

    def rep_body(rep, carry):
        out, leaves, outpos, flag = carry

        def body(state):
            ftotal, active, out, leaves, outpos, flag = state
            r = jnp.full((L,), 0, jnp.int32) + rep + ftotal
            item, ok, perm, f1 = _descend(fm, take_bid, xs, r, want_type,
                                          outpos, outer_ds, resolve, rootc)
            flag = flag | (active & f1)
            if recurse_to_leaf:
                if vary_r:
                    sub_r = r >> (vary_r - 1)
                else:
                    sub_r = jnp.zeros_like(r)
                rep_i = (jnp.zeros_like(outpos) if stable else outpos)
                bid_in = jnp.where(item < 0, -1 - item, 0)

                def inner_body(istate):
                    ift, iact, leaf, leaf_ok, iflag = istate
                    r_in = rep_i + sub_r + ift
                    cand, cok, _cperm, f2 = _descend(
                        fm, bid_in, xs, r_in, 0, outpos, inner_ds,
                        resolve, None)
                    iflag = iflag | (iact & f2)
                    cok = cok & (item < 0)
                    # leaf collision: the recursive call checks candidates
                    # against leaves already placed in out2[0..outpos)
                    # (mapper.c:535-541 with out=out2)
                    cok = cok & ~jnp.any(leaves == cand[:, None], axis=1)
                    cok = cok & ~_is_out(dev_weights, cand, xs)
                    take = iact & cok
                    leaf = jnp.where(take, cand, leaf)
                    leaf_ok = leaf_ok | take
                    iact = iact & (~cok) & (ift + 1 < recurse_tries)
                    return ift + 1, iact, leaf, leaf_ok, iflag

                izero = jnp.zeros((L,), jnp.int32)
                leaf0 = jnp.full((L,), ITEM_NONE, jnp.int32)
                _, _, leaf, leaf_ok, iflag = jax.lax.while_loop(
                    lambda s: jnp.any(s[1]), inner_body,
                    (izero, active & ok, leaf0, jnp.zeros((L,), bool),
                     jnp.zeros((L,), bool)))
                final, final_ok = leaf, ok & leaf_ok
                flag = flag | iflag
            else:
                final = item
                final_ok = ok
                if want_type == 0:
                    final_ok = final_ok & ~_is_out(dev_weights, item, xs)
            collide = jnp.any(out == item[:, None], axis=1) & ok
            success = (active & final_ok & ~collide
                       & (outpos < result_slots))
            slot = jnp.arange(result_slots)[None, :] == outpos[:, None]
            put = slot & success[:, None]
            out = jnp.where(put, item[:, None], out)
            leaves = jnp.where(put, final[:, None], leaves)
            outpos = outpos + success.astype(jnp.int32)
            ftotal = ftotal + 1
            active = active & ~success & ~perm & (ftotal < tries)
            return ftotal, active, out, leaves, outpos, flag

        z = jnp.zeros((L,), jnp.int32)
        act = jnp.ones((L,), bool)
        _, _, out, leaves, outpos, flag = jax.lax.while_loop(
            lambda s: jnp.any(s[1]), body,
            (z, act, out, leaves, outpos, flag))
        return out, leaves, outpos, flag

    out, leaves, outpos, flag = jax.lax.fori_loop(
        0, numrep, rep_body, (out, leaves, outpos, flag0))
    return out, leaves, outpos, flag


def _choose_firstn_vec(fm: FlatMap, take_bid_val: int, xs, numrep: int,
                       result_max: int, want_type: int,
                       recurse_to_leaf: bool, dev_weights,
                       tries: int, recurse_tries: int, vary_r: int,
                       stable: int, outer_ds: tuple, inner_ds: tuple,
                       resolve: bool, full: bool,
                       rootc: _ConstRow | None):
    """Fast-path firstn: _ATTEMPT_TRIES optimistic full-width rounds
    per replica (ftotal = 0, 1, ...); a lane still unsatisfied after
    them is flagged for the resolve pass instead of driving a masked
    retry loop — data-dependent while loops, compaction gathers and
    result scatters all cost more on TPU than recomputing the few
    stragglers exactly in pass 2.  Resolve mode and small batches run
    the full retry loops."""
    L = xs.shape[0]
    slots = min(numrep, result_max)
    take_bid = jnp.full((L,), -1 - take_bid_val, jnp.int32)
    out0 = jnp.full((L, slots), ITEM_NONE, jnp.int32)
    leaves0 = jnp.full((L, slots), ITEM_NONE, jnp.int32)
    pos0 = jnp.zeros((L,), jnp.int32)
    if full or L < _ATTEMPT_MIN_L:
        out, leaves, outpos, flag = _firstn_full(
            fm, take_bid, xs, out0, leaves0, pos0, numrep, result_max,
            want_type, recurse_to_leaf, dev_weights, tries, recurse_tries,
            vary_r, stable, outer_ds, inner_ds, resolve, rootc)
        return (leaves if recurse_to_leaf else out), outpos, flag

    out, leaves, outpos = out0, leaves0, pos0
    flag = jnp.zeros((L,), bool)
    clean = jnp.ones((L,), bool)
    # an outer retry (ftotal+1) after a leaf failure only matches the
    # reference when the inner loop is single-try (chooseleaf_descend_
    # once, the modern default); otherwise the inner retries first, so
    # the optimistic pass stops at one round and defers to pass 2
    n_attempts = min(_ATTEMPT_TRIES, tries)
    if recurse_to_leaf and recurse_tries > 1:
        n_attempts = 1
    for rep in range(numrep):
        done_rep = jnp.zeros((L,), bool)
        for ft in range(n_attempts):
            r = jnp.full((L,), rep + ft, jnp.int32)
            item, ok, perm, f1 = _descend(fm, take_bid, xs, r,
                                          want_type, outpos, outer_ds,
                                          resolve, rootc)
            if recurse_to_leaf:
                if vary_r:
                    sub_r = r >> (vary_r - 1)
                else:
                    sub_r = jnp.zeros_like(r)
                rep_i = (jnp.zeros_like(outpos) if stable else outpos)
                bid_in = jnp.where(item < 0, -1 - item, 0)
                r_in = rep_i + sub_r
                cand, cok, _cp, f2 = _descend(fm, bid_in, xs, r_in, 0,
                                              outpos, inner_ds, resolve,
                                              None)
                cok = cok & (item < 0)
                cok = cok & ~jnp.any(leaves == cand[:, None], axis=1)
                cok = cok & ~_is_out(dev_weights, cand, xs)
                final, final_ok = cand, ok & cok
                f1 = f1 | (f2 & ok & (item < 0))
            else:
                final = item
                final_ok = ok
                if want_type == 0:
                    final_ok = final_ok & ~_is_out(dev_weights, item,
                                                   xs)
            collide = jnp.any(out == item[:, None], axis=1) & ok
            act = ~done_rep
            success = act & final_ok & ~collide & (outpos < slots)
            slot = jnp.arange(slots)[None, :] == outpos[:, None]
            put = slot & success[:, None]
            out = jnp.where(put, item[:, None], out)
            leaves = jnp.where(put, final[:, None], leaves)
            outpos = outpos + success.astype(jnp.int32)
            flag = flag | (clean & act & f1)
            done_rep = done_rep | success | (act & perm)
        clean = clean & done_rep
    flag = flag | ~clean
    return (leaves if recurse_to_leaf else out), outpos, flag


def _indep_round(fm: FlatMap, take_bid, xs, ftotal, out, leaves, flag,
                 numrep: int, slots: int, want_type: int,
                 recurse_to_leaf: bool, dev_weights,
                 recurse_tries: int, outer_ds: tuple, inner_ds: tuple,
                 resolve: bool, rootc: _ConstRow | None):
    """One crush_choose_indep round (mapper.c:633-821): all UNDEF slots
    draw with r = rep + numrep*ftotal."""
    L = xs.shape[0]
    pos0 = jnp.zeros((L,), jnp.int32)

    def rep_body(rep, carry):
        out, leaves, flag = carry
        undecided = out[:, rep] == ITEM_UNDEF
        r = jnp.full((L,), 0, jnp.int32) + rep + numrep * ftotal
        item, ok, perm, f1 = _descend(fm, take_bid, xs, r, want_type,
                                      pos0, outer_ds, resolve, rootc)
        flag = flag | (undecided & f1)
        collide = jnp.any(out == item[:, None], axis=1) & ok
        if recurse_to_leaf:
            bid_in = jnp.where(item < 0, -1 - item, 0)
            pos_r = jnp.full((L,), 0, jnp.int32) + rep

            def inner_body(istate):
                ift, iact, leaf, leaf_ok, iflag = istate
                r_in = r + rep + numrep * ift
                cand, cok, _cp, f2 = _descend(fm, bid_in, xs, r_in, 0,
                                              pos_r, inner_ds, resolve,
                                              None)
                iflag = iflag | (iact & f2)
                cok = cok & (item < 0)
                cok = cok & ~_is_out(dev_weights, cand, xs)
                take = iact & cok
                leaf = jnp.where(take, cand, leaf)
                leaf_ok = leaf_ok | take
                iact = iact & (~cok) & (ift + 1 < recurse_tries)
                return ift + 1, iact, leaf, leaf_ok, iflag

            izero = jnp.zeros((L,), jnp.int32)
            leaf0 = jnp.full((L,), ITEM_NONE, jnp.int32)
            _, _, leaf, leaf_ok, iflag = jax.lax.while_loop(
                lambda s: jnp.any(s[1]), inner_body,
                (izero, undecided & ok & ~collide, leaf0,
                 jnp.zeros((L,), bool), jnp.zeros((L,), bool)))
            final, final_ok = leaf, ok & leaf_ok
            flag = flag | iflag
        else:
            final = item
            final_ok = ok
            if want_type == 0:
                final_ok = final_ok & ~_is_out(dev_weights, item, xs)
        success = undecided & final_ok & ~collide
        permfail = undecided & perm
        col = jnp.arange(slots)[None, :] == rep
        out = jnp.where(col & success[:, None], item[:, None], out)
        out = jnp.where(col & permfail[:, None], ITEM_NONE, out)
        leaves = jnp.where(col & success[:, None], final[:, None],
                           leaves)
        leaves = jnp.where(col & permfail[:, None], ITEM_NONE, leaves)
        return out, leaves, flag

    return jax.lax.fori_loop(0, slots, rep_body, (out, leaves, flag))


def _indep_full(fm: FlatMap, take_bid, xs, numrep: int, slots: int,
                want_type: int, recurse_to_leaf: bool, dev_weights,
                tries: int, recurse_tries: int, outer_ds: tuple,
                inner_ds: tuple, resolve: bool,
                rootc: _ConstRow | None):
    """Full positionally-stable retry loop: slots left UNDEF retry with
    r advanced by numrep per round."""
    L = xs.shape[0]
    out = jnp.full((L, slots), ITEM_UNDEF, jnp.int32)
    leaves = jnp.full((L, slots), ITEM_UNDEF, jnp.int32)
    flag = jnp.zeros((L,), bool)

    def body(state):
        ftotal, out, leaves, flag = state
        out, leaves, flag = _indep_round(
            fm, take_bid, xs, ftotal, out, leaves, flag, numrep, slots,
            want_type, recurse_to_leaf, dev_weights, recurse_tries,
            outer_ds, inner_ds, resolve, rootc)
        return ftotal + 1, out, leaves, flag

    def cond(state):
        ftotal, out, _, _ = state
        return jnp.any(out == ITEM_UNDEF) & (ftotal < tries)

    z = jnp.zeros((), jnp.int32)
    _, out, leaves, flag = jax.lax.while_loop(cond, body,
                                              (z, out, leaves, flag))
    res = leaves if recurse_to_leaf else out
    return jnp.where(res == ITEM_UNDEF, ITEM_NONE, res), flag


def _choose_indep_vec(fm: FlatMap, take_bid_val: int, xs, numrep: int,
                      result_max: int, want_type: int,
                      recurse_to_leaf: bool, dev_weights,
                      tries: int, recurse_tries: int,
                      outer_ds: tuple, inner_ds: tuple,
                      resolve: bool, full: bool,
                      rootc: _ConstRow | None):
    """Fast-path indep: _ATTEMPT_TRIES optimistic full-width rounds
    (each an exact crush_choose_indep round, so chaining them is the
    reference retry semantics verbatim); lanes with UNDEF slots left
    after them are flagged for the resolve pass."""
    L = xs.shape[0]
    slots = min(numrep, result_max)
    take_bid = jnp.full((L,), -1 - take_bid_val, jnp.int32)
    if full or L < _ATTEMPT_MIN_L:
        res, flag = _indep_full(fm, take_bid, xs, numrep, slots,
                                want_type, recurse_to_leaf, dev_weights,
                                tries, recurse_tries, outer_ds, inner_ds,
                                resolve, rootc)
        return res, flag

    out = jnp.full((L, slots), ITEM_UNDEF, jnp.int32)
    leaves = jnp.full((L, slots), ITEM_UNDEF, jnp.int32)
    flag = jnp.zeros((L,), bool)
    for ft in range(min(_ATTEMPT_TRIES, tries)):
        out, leaves, flag = _indep_round(
            fm, take_bid, xs, jnp.full((), ft, jnp.int32), out, leaves,
            flag, numrep, slots, want_type, recurse_to_leaf,
            dev_weights, recurse_tries, outer_ds, inner_ds, resolve,
            rootc)
    res = leaves if recurse_to_leaf else out
    flag = flag | jnp.any(out == ITEM_UNDEF, axis=1)
    return jnp.where(res == ITEM_UNDEF, ITEM_NONE, res), flag


# ---------------------------------------------------------------------------
# post-CRUSH mapping pipeline (fused on device)
# ---------------------------------------------------------------------------

CEPH_OSD_MAX_PRIMARY_AFFINITY = 0x10000
CEPH_OSD_DEFAULT_PRIMARY_AFFINITY = 0x10000


def _post_process(raw, seeds, exists_b, isup_b, aff, can_shift: bool,
                  use_aff: bool):
    """Fused _remove_nonexistent_osds + _raw_to_up_osds + _pick_primary +
    _apply_primary_affinity (OSDMap.cc:2626-2802) over the whole batch.

    raw [L,S] int32 with ITEM_NONE holes; seeds [L] uint32 pps values;
    exists_b/isup_b [D] bool; aff [D] int32 16.16 primary affinities.
    Only valid for PGs with no upmap/pg_temp exception (the bulk mapper
    recomputes exception rows on the host scalar path).
    """
    D = exists_b.shape[0]
    valid = raw != ITEM_NONE
    idx = jnp.clip(raw, 0, D - 1)
    keep_t = (exists_b & isup_b).astype(jnp.int32)
    st = small_fetch(keep_t, idx, 1)
    keep = valid & (raw < D) & (st > 0)
    up = jnp.where(keep, raw, ITEM_NONE)
    if can_shift:
        # stable compaction: surviving osds keep order, holes go last.
        # S is tiny, so an S^2 rank-select beats a sort by a mile.
        S = up.shape[1]
        rank = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
        slots = jnp.arange(S)
        hit = keep[:, None, :] & (rank[:, None, :] == slots[None, :, None])
        up = jnp.where(
            jnp.any(hit, axis=2),
            jnp.sum(jnp.where(hit, up[:, None, :], 0), axis=2),
            ITEM_NONE)
    S = up.shape[1]
    slots = jnp.arange(S)
    nonnone = up != ITEM_NONE
    has = jnp.any(nonnone, axis=1)
    first = jnp.argmax(nonnone, axis=1)

    def pick_col(arr, col):
        sel = slots[None, :] == col[:, None]
        return jnp.sum(jnp.where(sel, arr, 0), axis=1)

    prim = jnp.where(has, pick_col(up, first), -1)
    if use_aff:
        a = small_fetch(aff, jnp.clip(up, 0, D - 1), 3)
        row_applies = jnp.any(
            nonnone & (a != CEPH_OSD_DEFAULT_PRIMARY_AFFINITY), axis=1)
        h = (hash32_2_j(seeds[:, None], up.astype(jnp.uint32))
             >> _u32(16)).astype(jnp.int32)
        rejected = (a < CEPH_OSD_MAX_PRIMARY_AFFINITY) & (h >= a)
        accept = nonnone & ~rejected
        has_acc = jnp.any(accept, axis=1)
        pos = jnp.where(has_acc, jnp.argmax(accept, axis=1), first)
        applies = row_applies & has
        new_prim = pick_col(up, pos)
        prim = jnp.where(applies, new_prim, prim)
        if can_shift:
            # move the new primary to the front, shifting [0..pos) right
            i = slots[None, :]
            rotated = jnp.where(
                i == 0, new_prim[:, None],
                jnp.where(i <= pos[:, None], jnp.roll(up, 1, axis=1), up))
            up = jnp.where(applies[:, None], rotated, up)
    return up, prim


# ---------------------------------------------------------------------------
# rule driver
# ---------------------------------------------------------------------------


class MapState:
    """Device-resident result of a whole-pool mapping pass: the raw
    (pre-filter) rows, the up rows and primaries, plus the host-side
    inputs needed to validate incremental remaps.

    Incremental validity (remap): with the crush map fixed, a lane's
    draw sequence depends only on (x, r) and the reweight rejections
    (mapper.c:402-416).  A rejection outcome changes only for OSDs
    whose reweight changed; under a DECREASE every lane that ever
    accepted the OSD carries it in a raw result slot (a pick either
    lands in the row or collides with an earlier slot holding the same
    OSD), so lanes without a changed OSD in their raw row replay the
    identical sequence.  Up/down/affinity changes only affect the
    post-CRUSH filter, which also reads the raw row.  Reweight
    INCREASES flip previously-hash-rejected lanes that are not
    identifiable from the rows — those fall back to a full pass."""

    __slots__ = ("dm", "ruleno", "result_max", "pg_num", "pgp_num",
                 "pgp_mask", "pool_id", "hashps", "can_shift",
                 "use_aff", "raw", "up_full", "prim_full", "w_np",
                 "ex_np", "iu_np", "af_np", "npg")

    def __init__(self, dm, ruleno, result_max, pg_num, pgp_num,
                 pgp_mask, pool_id, hashps, can_shift, use_aff, raw,
                 up_full, prim_full, w_np, ex_np, iu_np, af_np, npg):
        self.dm = dm
        self.ruleno = ruleno
        self.result_max = result_max
        self.pg_num = pg_num
        self.pgp_num = pgp_num
        self.pgp_mask = pgp_mask
        self.pool_id = pool_id
        self.hashps = hashps
        self.can_shift = can_shift
        self.use_aff = use_aff
        self.raw = raw
        self.up_full = up_full
        self.prim_full = prim_full
        self.w_np = w_np
        self.ex_np = ex_np
        self.iu_np = iu_np
        self.af_np = af_np
        self.npg = npg

    @property
    def up(self):
        return self.up_full[:self.pg_num]

    @property
    def prim(self):
        return self.prim_full[:self.pg_num]

    def remap(self, dev_weights, exists, isup, aff=None) -> "MapState":
        """New MapState after a cluster-state change, recomputing only
        the affected lanes when the change qualifies (see class doc);
        otherwise a full pass."""
        use_aff = aff is not None
        w_np = np.asarray(dev_weights, dtype=np.int32)
        ex_np = np.asarray(exists, dtype=bool)
        iu_np = np.asarray(isup, dtype=bool)
        af_np = (np.asarray(aff, dtype=np.int32) if use_aff
                 else np.zeros((ex_np.shape[0],), np.int32))

        def full():
            return self.dm.map_pool_state(
                self.ruleno, self.result_max, self.pg_num,
                self.pgp_num, self.pgp_mask, self.pool_id, self.hashps,
                w_np, ex_np, iu_np, aff, self.can_shift)

        if (use_aff != self.use_aff
                or w_np.shape != self.w_np.shape):
            return full()
        changed = ((w_np != self.w_np) | (ex_np != self.ex_np)
                   | (iu_np != self.iu_np) | (af_np != self.af_np))
        if not changed.any():
            return self
        if (w_np > self.w_np).any():
            return full()        # reweight increase: not incremental
        w, ex = jnp.asarray(w_np), jnp.asarray(ex_np)
        iu, af = jnp.asarray(iu_np), jnp.asarray(af_np)
        cm = jnp.asarray(changed)
        K1 = max(8, min(1 << 13, 1 << max(
            1, (self.pg_num - 1).bit_length())))
        K2 = max(8, min(1 << 11, K1))
        K3 = max(8, min(1 << 10, K2))
        KA = 0
        KT = 0
        if self.dm._rc_ok(self.npg):
            # expected hits per 2048-lane row group: a lane is hit if
            # any of its S raw slots holds a changed osd; size the
            # compaction slots with a ~6-sigma margin (overflow is
            # detected and retried wider, never silent)
            D = max(1, ex_np.shape[0])
            frac = float(changed.sum()) / D
            # .shape is metadata — np.asarray here would drag the
            # whole device-resident raw table over the tunnel
            S = int(self.raw.shape[1])
            mu = self.dm.RC_ROW * min(1.0, S * frac)
            thresh = mu + 6.0 * (mu ** 0.5) + 16.0
            KT = 128 * int(-(-thresh // 128))
            if KT > 1024:
                KT = 0      # massive churn: XLA nonzero path
        if KT == 0:
            KA = max(64, min(
                1 << 19,
                1 << (max(1, self.pg_num - 1)).bit_length()))
        while True:
            rm = self.dm._compiled_remap(
                self.ruleno, self.result_max, self.can_shift,
                self.use_aff, self.pgp_num, self.pgp_mask,
                self.pool_id, self.hashps, KA, K1, K2, K3, self.npg,
                self.pg_num, KT)
            raw2, up2, prim2, counts = rm(self.raw, self.up_full,
                                          self.prim_full, w, ex, iu,
                                          af, cm)
            nA, nf, n2, n3, rowmax = (int(v)
                                      for v in np.asarray(counts))
            if KT and rowmax > KT:
                KT = 128 * (-(-int(rowmax * 2) // 128))
                if KT > 2048:
                    KT = 0
                    KA = max(64, min(
                        1 << 19,
                        1 << (max(1, self.pg_num - 1)).bit_length()))
                continue
            if (KA == 0 or nA <= KA) and nf <= K1 and n2 <= K2 \
                    and n3 <= K3:
                break
            if KA:
                KA = max(KA, 1 << (max(1, nA - 1)).bit_length())
            K1 = max(K1, min(1 << (max(1, nf - 1)).bit_length(),
                             KA or (1 << 19)))
            K2 = max(K2, min(1 << (max(1, n2 - 1)).bit_length(), K1))
            K3 = max(K3, min(1 << (max(1, n3 - 1)).bit_length(), K2))
        return MapState(
            self.dm, self.ruleno, self.result_max, self.pg_num,
            self.pgp_num, self.pgp_mask, self.pool_id, self.hashps,
            self.can_shift, self.use_aff, raw2, up2, prim2, w_np,
            ex_np, iu_np, af_np, self.npg)


class DeviceMapper:
    """Bulk do_rule on device for straw2 maps with single-choose rules.

    do_rule_batch(ruleno, xs, result_max, dev_weights) mirrors
    CrushWrapper::do_rule over a whole batch of inputs; results carry
    ITEM_NONE holes exactly like the host engine.  Internally a fast
    f32 pass flags uncertain lanes, a resolve pass recomputes them
    exactly, and top-3-ambiguous dust goes to the scalar host engine —
    so results are always bit-identical to the host.
    """

    def __init__(self, crushmap: CrushMap,
                 choose_args_name: str | None = None):
        self.fm = FlatMap(crushmap, choose_args_name)
        self.map = crushmap
        self._cargs = (crushmap.choose_args.get(choose_args_name)
                       if choose_args_name else None)

    def _compile(self, ruleno: int, result_max: int, resolve: bool,
                 full: bool = True):
        rule = self.fm.rules[ruleno]
        t = self.fm.tunables
        tries = t.choose_total_tries + 1     # historical off-by-one
        leaf_tries = 0
        vary_r = t.chooseleaf_vary_r
        stable = t.chooseleaf_stable
        take_id = None
        plan = None
        for op, arg1, arg2 in rule.steps:
            if op == TAKE:
                take_id = arg1
            elif op == SET_CHOOSE_TRIES:
                if arg1 > 0:
                    tries = arg1
            elif op == SET_CHOOSELEAF_TRIES:
                if arg1 > 0:
                    leaf_tries = arg1
            elif op == SET_CHOOSELEAF_VARY_R:
                if arg1 >= 0:
                    vary_r = arg1
            elif op == SET_CHOOSELEAF_STABLE:
                if arg1 >= 0:
                    stable = arg1
            elif op in (CHOOSE_FIRSTN, CHOOSELEAF_FIRSTN,
                        CHOOSE_INDEP, CHOOSELEAF_INDEP):
                if plan is not None:
                    raise ValueError(
                        "device mapper supports a single choose step")
                if take_id is None or take_id >= 0:
                    raise ValueError("choose without a bucket take")
                numrep = arg1
                if numrep <= 0:
                    numrep += result_max
                firstn = op in (CHOOSE_FIRSTN, CHOOSELEAF_FIRSTN)
                leaf = op in (CHOOSELEAF_FIRSTN, CHOOSELEAF_INDEP)
                plan = (take_id, numrep, arg2, firstn, leaf)
            elif op == EMIT:
                pass
        if plan is None:
            raise ValueError("rule has no choose step")
        take_id, numrep, want_type, firstn, leaf = plan
        if firstn:
            recurse = (leaf_tries if leaf_tries
                       else (1 if t.chooseleaf_descend_once else tries))
        else:
            recurse = leaf_tries if leaf_tries else 1
        fm = self.fm
        outer_ds = self._depth_sizes([take_id], want_type)
        rootc = fm.const_row(take_id, outer_ds[0])
        if leaf:
            starts = [b.id for b in self.map.buckets.values()
                      if b.type == want_type]
            inner_ds = self._depth_sizes(starts, 0)
        else:
            inner_ds = ()

        def core(xs, dev_weights):
            if firstn:
                res, _, flag = _choose_firstn_vec(
                    fm, take_id, xs, numrep, result_max, want_type,
                    leaf, dev_weights, tries, recurse, vary_r, stable,
                    outer_ds, inner_ds, resolve, full, rootc)
            else:
                res, flag = _choose_indep_vec(
                    fm, take_id, xs, numrep, result_max, want_type,
                    leaf, dev_weights, tries, recurse,
                    outer_ds, inner_ds, resolve, full, rootc)
            return res, flag

        return core

    def _depth_sizes(self, start_bucket_ids: list[int],
                     want_type: int) -> tuple:
        """depth_sizes[d] = max size of any bucket reachable at depth d
        by walking bucket children from the start set (static per
        rule/map).  The walk stops once no child bucket can continue
        the descent — children of the wanted type are terminal (the
        draw 'reach'es them), so e.g. a root->host chooseleaf descent
        costs one draw level, not the tree height."""
        m = self.map
        sizes = []
        level = {b for b in start_bucket_ids if b in m.buckets}
        seen_levels = 0
        while level and seen_levels < 64:    # cycle guard
            sizes.append(max(
                (m.buckets[b].size for b in level), default=1) or 1)
            level = {c for b in level for c in m.buckets[b].items
                     if c < 0 and c in m.buckets
                     and m.buckets[c].type != want_type}
            seen_levels += 1
        return tuple(sizes) if sizes else (1,)

    @staticmethod
    def _note_compile(what: str, key: tuple) -> None:
        """Register a distinct crush program with the device runtime's
        compile counter.  Keys carry only the program signature (rule,
        shape, K buckets) — NOT instance identity — so DeviceMapper
        rebuilds across map epochs do not count as fresh compiles:
        the counter tracks what the acceptance criteria assert, the
        number of distinct programs a steady-state workload needs."""
        from ...device.runtime import DeviceRuntime
        DeviceRuntime.get().note_program("crush", (what,) + key)

    @functools.lru_cache(maxsize=None)
    def _compiled(self, ruleno: int, result_max: int, resolve: bool,
                  full: bool = True):
        self._note_compile("rule", (ruleno, result_max, resolve, full))
        return jax.jit(self._compile(ruleno, result_max, resolve, full))

    @functools.lru_cache(maxsize=None)
    def _compiled_map(self, ruleno: int, result_max: int,
                      can_shift: bool, use_aff: bool, resolve: bool,
                      full: bool = True):
        self._note_compile("map", (ruleno, result_max, can_shift,
                                   use_aff, resolve, full))
        core = self._compile(ruleno, result_max, resolve, full)

        @jax.jit
        def run(xs, dev_weights, exists_b, isup_b, aff):
            raw, flag = core(xs, dev_weights)
            up, prim = _post_process(raw, xs, exists_b, isup_b, aff,
                                     can_shift, use_aff)
            return up, prim, flag

        return run

    # per-dispatch PG cap: bounds live [L, S] f32/int32 temps in HBM
    CHUNK = 1 << 20

    # -- whole-pool mapping with device-side pps -------------------------

    @functools.lru_cache(maxsize=None)
    def _compiled_pool(self, ruleno: int, result_max: int,
                       can_shift: bool, use_aff: bool, pgp_num: int,
                       pgp_mask: int, pool_id: int, hashps: bool,
                       n: int, n_chunks: int):
        """Whole pool in ONE dispatch: a lax.scan over fixed-size
        chunks (the chunking bounds the live [L,S] temps, the scan
        removes per-chunk dispatch/readback latency — significant over
        a remote-chip tunnel).  full=False: the dense pass runs the
        bounded optimistic-attempt structure; lanes needing deeper
        retries are flagged and settled by the resolve passes, so the
        dense cost is fixed at numrep×_ATTEMPT_TRIES descents instead
        of being dragged by the worst lane's retry count."""
        self._note_compile("pool", (ruleno, result_max, can_shift,
                                    use_aff, pgp_num, pgp_mask,
                                    pool_id, hashps, n, n_chunks))
        core = self._compile(ruleno, result_max, False, full=False)

        def chunk(start):
            ps = jnp.arange(n, dtype=jnp.uint32) + start
            masked = jnp.where((ps & _u32(pgp_mask)) < _u32(pgp_num),
                               ps & _u32(pgp_mask),
                               ps & _u32(pgp_mask >> 1))
            if hashps:
                xs = hash32_2_j(masked, _u32(pool_id))
            else:
                xs = masked + _u32(pool_id)
            return xs

        def post(raw, xs, exists_b, isup_b, aff):
            if not use_aff:
                from . import pallas_draw
                if (pallas_draw.pallas_enabled()
                        and raw.shape[0] % pallas_draw.TL == 0):
                    pk = self._post_kernel(int(exists_b.shape[0]),
                                           int(raw.shape[1]),
                                           can_shift)
                    return pk(raw, exists_b & isup_b)
            return _post_process(raw, xs, exists_b, isup_b, aff,
                                 can_shift, use_aff)

        @jax.jit
        def run(dev_weights, exists_b, isup_b, aff):
            def body(_, start):
                xs = chunk(start)
                raw, flag = core(xs, dev_weights)
                up, prim = post(raw, xs, exists_b, isup_b, aff)
                return 0, (raw, up, prim, flag)

            starts = (jnp.arange(n_chunks, dtype=jnp.uint32)
                      * _u32(n))
            _, (raws, ups, prims, flags) = jax.lax.scan(body, 0, starts)
            S = ups.shape[2]
            return (raws.reshape(-1, S), ups.reshape(-1, S),
                    prims.reshape(-1), flags.reshape(-1))

        return run

    def _post_kernel(self, D: int, S: int, can_shift: bool):
        """Cached fused post-CRUSH kernel (non-affinity path)."""
        from . import pallas_draw
        cache = self.__dict__.setdefault("_post_kernel_cache", {})
        key = (D, S, can_shift)
        if key not in cache:
            cache[key] = pallas_draw.make_post_kernel(D, S, can_shift)
        return cache[key]

    def _resolve_chain_parts(self, ruleno: int, result_max: int,
                             can_shift: bool, use_aff: bool,
                             pgp_num: int, pgp_mask: int, pool_id: int,
                             hashps: bool, K1: int, K2: int, K3: int):
        """Shared pieces of the device-resident resolve chain: the
        device pps seed computation, the settle-and-scatter helper and
        the three-stage compact/resolve cascade (exact-top3 attempt
        structure -> full retry loops -> fully exact integer draw).
        Used by both the full-map resolve and the incremental remap so
        the pad-masking subtleties live in one place."""
        acore_a = self._compile(ruleno, result_max, True, full=False)
        rcore = self._compile(ruleno, result_max, True, True)
        acore = self._compile(ruleno, result_max, "all", True)

        def pps(idx):
            ps = idx.astype(jnp.uint32)
            masked = jnp.where((ps & _u32(pgp_mask)) < _u32(pgp_num),
                               ps & _u32(pgp_mask),
                               ps & _u32(pgp_mask >> 1))
            if hashps:
                return hash32_2_j(masked, _u32(pool_id))
            return masked + _u32(pool_id)

        def settle(core_fn, raw_t, up, prim, lanes, w, ex, iu, af):
            xs = pps(lanes)
            rr, f = core_fn(xs, w)
            u2, p2 = _post_process(rr, xs, ex, iu, af, can_shift,
                                   use_aff)
            raw_t = raw_t.at[lanes].set(rr.astype(jnp.int32))
            up = up.at[lanes].set(u2.astype(jnp.int32))
            prim = prim.at[lanes].set(p2.astype(jnp.int32))
            return raw_t, up, prim, f

        def chain(raw_t, up, prim, flag, nflag, to_lane, w, ex, iu,
                  af):
            """flag: bool over the caller's index space; to_lane maps
            compacted positions to global lane ids.  Padding positions
            compact to index 0 whose resolved row is exact anyway, but
            their FLAGS must be masked (pads mirror position 0 — if it
            flags, every pad copy would flag with it)."""
            pos = jnp.nonzero(flag, size=K1, fill_value=0)[0]
            idx = to_lane(pos)
            # stage A: exact draws through the bounded attempt
            # structure (covers the f32-uncertainty majority)
            raw_t, up, prim, f2 = settle(acore_a, raw_t, up, prim,
                                         idx, w, ex, iu, af)
            f2 = f2 & (jnp.arange(K1, dtype=jnp.int32) < nflag)
            n2 = jnp.sum(f2, dtype=jnp.int32)
            # stage B: stragglers (unfinished retries + dust) through
            # the full retry loops, on a compacted subset
            lanesB = idx[jnp.nonzero(f2, size=K2, fill_value=0)[0]]
            raw_t, up, prim, f3 = settle(rcore, raw_t, up, prim,
                                         lanesB, w, ex, iu, af)
            f3 = f3 & (jnp.arange(K2, dtype=jnp.int32) < n2)
            n3 = jnp.sum(f3, dtype=jnp.int32)
            # stage C: residual top-3-ambiguous dust, fully exact
            lanesC = lanesB[jnp.nonzero(f3, size=K3, fill_value=0)[0]]
            raw_t, up, prim, _ = settle(acore, raw_t, up, prim,
                                        lanesC, w, ex, iu, af)
            return raw_t, up, prim, n2, n3

        return pps, settle, chain

    # rowcompact geometry: lanes per row group / default slot count
    RC_ROW = 2048
    RC_KT = 128

    def _rc_ok(self, npg: int) -> bool:
        """The pallas rowcompact path needs aligned lane counts and a
        mosaic-capable backend (or interpret mode in tests)."""
        from . import pallas_draw
        return (pallas_draw.pallas_enabled()
                and npg % (8 * self.RC_ROW) == 0)

    @functools.lru_cache(maxsize=None)
    def _compiled_device_resolve(self, ruleno: int, result_max: int,
                                 can_shift: bool, use_aff: bool,
                                 pgp_num: int, pgp_mask: int,
                                 pool_id: int, hashps: bool,
                                 K1: int, K2: int, K3: int, npg: int,
                                 pg_num: int, kt: int = 0):
        """Device-resident resolve for the full-map pass: compact the
        flagged lanes, settle them through the three-stage chain, and
        scatter back — the only host traffic is the overflow-guard
        counters (essential on a remote-chip tunnel that moves ~5 MB/s
        with ~100ms latency per readback).

        kt > 0 uses the pallas rowcompact kernel for the first
        compaction: XLA's nonzero over the full PG axis is the single
        most expensive op of the resolve on this platform (~0.9s at
        10M lanes, BENCH r4 notes); rowcompact reduces the nonzero to
        the npg/ROW*kt padded index space.  kt == 0 is the pure-XLA
        fallback."""
        self._note_compile("resolve", (ruleno, result_max, can_shift,
                                       use_aff, K1, K2, K3, npg,
                                       pg_num, kt))
        from . import pallas_draw
        _pps, _settle, chain = self._resolve_chain_parts(
            ruleno, result_max, can_shift, use_aff, pgp_num, pgp_mask,
            pool_id, hashps, K1, K2, K3)
        rc = (pallas_draw.make_rowcompact_kernel(
                  npg, self.RC_ROW, kt, pg_num) if kt else None)

        @jax.jit
        def run(raw_t, up, prim, flag, w, ex, iu, af):
            if rc is not None:
                idxp, validp, cnt = rc(flag)
                nflag = jnp.sum(validp, dtype=jnp.int32)
                rowmax = jnp.max(cnt)
                raw_t, up, prim, n2, n3 = chain(
                    raw_t, up, prim, validp, nflag,
                    lambda p: idxp[p], w, ex, iu, af)
                return raw_t, up, prim, jnp.stack(
                    [nflag, n2, n3, rowmax])
            flag2 = flag & (jnp.arange(npg, dtype=jnp.int32) < pg_num)
            nflag = jnp.sum(flag2, dtype=jnp.int32)
            raw_t, up, prim, n2, n3 = chain(
                raw_t, up, prim, flag2, nflag, lambda p: p, w, ex, iu,
                af)
            return raw_t, up, prim, jnp.stack(
                [nflag, n2, n3, jnp.int32(0)])

        return run

    def map_pool_batch(self, ruleno: int, result_max: int, pg_num: int,
                       pgp_num: int, pgp_num_mask: int, pool_id: int,
                       hashpspool: bool, dev_weights, exists, isup,
                       aff=None, can_shift: bool = True):
        """Whole-pool pg->up pipeline as dense numpy arrays; thin
        wrapper over map_pool_state (which keeps everything
        device-resident for consumers that chain incremental
        remaps)."""
        state = self.map_pool_state(
            ruleno, result_max, pg_num, pgp_num, pgp_num_mask, pool_id,
            hashpspool, dev_weights, exists, isup, aff, can_shift)
        return np.array(state.up), np.array(state.prim)

    def map_pool_state(self, ruleno: int, result_max: int, pg_num: int,
                       pgp_num: int, pgp_num_mask: int, pool_id: int,
                       hashpspool: bool, dev_weights, exists, isup,
                       aff=None, can_shift: bool = True) -> "MapState":
        """Full device pass returning a MapState (device-resident
        raw/up/prim + the host-side inputs needed to validate later
        incremental remaps)."""
        use_aff = aff is not None
        w_np = np.asarray(dev_weights, dtype=np.int32)
        ex_np = np.asarray(exists, dtype=bool)
        iu_np = np.asarray(isup, dtype=bool)
        af_np = (np.asarray(aff, dtype=np.int32) if use_aff
                 else np.zeros((ex_np.shape[0],), np.int32))
        w, ex = jnp.asarray(w_np), jnp.asarray(ex_np)
        iu, af = jnp.asarray(iu_np), jnp.asarray(af_np)
        C = min(self.CHUNK, max(8, -(-pg_num // 8) * 8))
        n_chunks = -(-pg_num // C)
        npg = C * n_chunks
        fn = self._compiled_pool(ruleno, result_max, bool(can_shift),
                                 use_aff, int(pgp_num),
                                 int(pgp_num_mask), int(pool_id),
                                 bool(hashpspool), C, n_chunks)
        raw, up, prim, flag = fn(w, ex, iu, af)
        K1 = max(64, min(1 << 16,
                         1 << (max(1, pg_num - 1)).bit_length()))
        K2 = max(8, min(1 << 13, K1))
        K3 = max(8, min(2048, K1))
        kt = self.RC_KT if self._rc_ok(npg) else 0
        while True:
            res = self._compiled_device_resolve(
                ruleno, result_max, bool(can_shift), use_aff,
                int(pgp_num), int(pgp_num_mask), int(pool_id),
                bool(hashpspool), K1, K2, K3, npg, pg_num, kt)
            raw2, up2, prim2, counts = res(raw, up, prim, flag,
                                           w, ex, iu, af)
            nflag, n2, ndust, rowmax = (int(v)
                                        for v in np.asarray(counts))
            if kt and rowmax > kt:
                # a row group overflowed its compaction slots: widen
                kt = 128 * (-(-int(rowmax * 2) // 128))
                if kt > 2048:
                    kt = 0      # absurd flag density: XLA fallback
                continue
            if nflag <= K1 and n2 <= K2 and ndust <= K3:
                break
            K1 = max(K1, 1 << (max(1, nflag - 1)).bit_length())
            K2 = max(K2, min(1 << (max(1, n2 - 1)).bit_length(), K1))
            K3 = max(K3, min(1 << (max(1, ndust - 1)).bit_length(),
                             K1))
        return MapState(
            self, ruleno, result_max, pg_num, pgp_num, pgp_num_mask,
            pool_id, bool(hashpspool), bool(can_shift), use_aff,
            raw2, up2, prim2, w_np, ex_np, iu_np, af_np, npg)

    @functools.lru_cache(maxsize=None)
    def _compiled_remap(self, ruleno: int, result_max: int,
                        can_shift: bool, use_aff: bool, pgp_num: int,
                        pgp_mask: int, pool_id: int, hashps: bool,
                        KA: int, K1: int, K2: int, K3: int, npg: int,
                        pg_num: int, KT: int = 0):
        """Incremental remap: find the lanes whose raw row touches a
        changed OSD (a hit-scan kernel over the stored raw rows),
        recompute only those through the fast pass, and settle their
        flagged residue through the shared resolve chain — all
        device-resident.  Sound because a lane's draw/rejection
        sequence is bit-identical under reweight DECREASES and
        up/down/affinity changes unless one of its raw result slots
        held a changed OSD (see MapState's validity argument)."""
        self._note_compile("remap", (ruleno, result_max, can_shift,
                                     use_aff, KA, K1, K2, K3, npg,
                                     pg_num, KT))
        from . import pallas_draw
        core = self._compile(ruleno, result_max, False, full=False)
        _pps, settle, chain = self._resolve_chain_parts(
            ruleno, result_max, can_shift, use_aff, pgp_num, pgp_mask,
            pool_id, hashps, K1, K2, K3)
        # KA == 0 selects the pallas rowcompact compaction (KT slots
        # per 2048-lane row group): the npg-wide jnp.nonzero this
        # replaces was ~70% of the whole remap on this platform
        rc = (pallas_draw.make_rowcompact_kernel(
                  npg, self.RC_ROW, KT, pg_num)
              if KA == 0 else None)

        @jax.jit
        def run(raw_t, up, prim, w, ex, iu, af, changed):
            D = changed.shape[0]
            if (pallas_draw.pallas_enabled()
                    and raw_t.shape[0] % pallas_draw.TL == 0):
                hs = pallas_draw.make_hitscan_kernel(
                    D, int(raw_t.shape[1]))
                hit = hs(raw_t, changed)
            else:
                idxc = jnp.clip(raw_t, 0, D - 1)
                cb = small_fetch(changed.astype(jnp.int32), idxc, 1)
                hit = jnp.any((raw_t != ITEM_NONE) & (raw_t < D)
                              & (cb > 0), axis=1)
            if rc is not None:
                # padded per-group compaction: pad slots duplicate the
                # group base lane (settle recomputes it harmlessly)
                # and the validity mask gates the flags
                idxA, validA, cnt = rc(hit)
                nA = jnp.sum(validA, dtype=jnp.int32)
                rowmax = jnp.max(cnt)
                raw_t, up, prim, flag = settle(core, raw_t, up, prim,
                                               idxA, w, ex, iu, af)
                flag = flag & validA
            else:
                hit = hit & (jnp.arange(npg, dtype=jnp.int32)
                             < pg_num)
                nA = jnp.sum(hit, dtype=jnp.int32)
                rowmax = jnp.int32(0)
                idxA = jnp.nonzero(hit, size=KA, fill_value=0)[0]
                raw_t, up, prim, flag = settle(core, raw_t, up, prim,
                                               idxA, w, ex, iu, af)
                flag = flag & (jnp.arange(KA, dtype=jnp.int32) < nA)
            nflag = jnp.sum(flag, dtype=jnp.int32)
            raw_t, up, prim, n2, n3 = chain(
                raw_t, up, prim, flag, nflag, lambda p: idxA[p],
                w, ex, iu, af)
            return raw_t, up, prim, jnp.stack(
                [nA, nflag, n2, n3, rowmax])

        return run

    def do_rule_batch(self, ruleno: int, xs, result_max: int,
                      dev_weights) -> np.ndarray:
        """xs: int array [L] of inputs (pps values); dev_weights: int32
        [max_devices] 16.16 reweights.  Returns [L, numrep] int32 with
        ITEM_NONE holes."""
        fast = self._compiled(ruleno, result_max, False, full=False)
        xs = np.asarray(xs, dtype=np.int64) & 0xFFFFFFFF
        w = jnp.asarray(np.asarray(dev_weights, dtype=np.int32))
        res, flag = fast(jnp.asarray(xs, dtype=jnp.uint32), w)
        res = np.array(res)
        flag = np.array(flag)
        flagged = np.nonzero(flag)[0]
        if flagged.size:
            rfn = self._compiled(ruleno, result_max, True)
            # pad to a pow2 bucket: a per-call exact size would recompile
            # the full retry pipeline for every distinct flagged count
            n2 = max(8, 1 << (int(flagged.size) - 1).bit_length())
            part = np.zeros((n2,), np.int64)
            part[:flagged.size] = xs[flagged]
            r2, f2 = rfn(jnp.asarray(part, dtype=jnp.uint32), w)
            res[flagged] = np.array(r2)[:flagged.size]
            f2 = np.array(f2)[:flagged.size]
            for lane in flagged[np.nonzero(f2)[0]]:
                row = self._host_raw(ruleno, int(xs[lane]), result_max,
                                     dev_weights)
                res[lane] = row[:res.shape[1]]
        return res

    # -- host dust (scalar exact fallback) ------------------------------

    def _host_raw(self, ruleno: int, x: int, result_max: int,
                  dev_weights) -> np.ndarray:
        from .host import Mapper
        weights = [int(v) for v in np.asarray(dev_weights)]
        raw = Mapper(self.map).do_rule(ruleno, x, result_max, weights,
                                       choose_args=self._cargs)
        row = np.full((result_max,), ITEM_NONE, np.int32)
        row[:len(raw)] = raw[:result_max]
        return row
