"""ops subpackage — see ceph_tpu/__init__.py for the layer map."""
