"""TLZ: the device-native LZ-class compressor ("tlz" in the registry).

The compression analog of the digest plane's split: the EXPENSIVE
phase — finding matches — is data-parallel and runs as batched device
dispatches (ceph_tpu.device.lzkernel: 4-byte-gram rolling hash,
match-candidate gather via composite-key sort, vectorized match-length
extension over fixed-size independent blocks); the CHEAP phase —
sequential token emission — stays on host and is a pure function of
the planned (candidate, match-length) arrays.  Because the device
kernel and the numpy host reference compute the identical plan (unique
integer sort keys, exact byte compares), the two paths produce
**byte-identical blobs** — the same bit-exact-fallback contract the
digest and EC planes hold, so a pool may flip between device and host
mid-flight (DeviceBusy, chip poison) without a reader ever noticing.

Container format (self-describing, decompressible by `decompress`
alone):

    magic  b"TLZ1"
    u32le  raw length
    u32le  block size (TLZ_BLOCK at write time)
    per block (ceil(raw/block) blocks, in order):
        u16le  csize
        csize == 0 -> the block is STORED: raw block bytes follow
                      (incompressible blocks never expand past 2B)
        csize  > 0 -> csize bytes of token stream follow

Token stream (LZ4-flavored, bounded by the block's raw length so no
end marker is needed):

    token byte: hi nibble = literal run length, lo nibble =
                match length - MIN_MATCH; value 15 in either nibble
                extends with 255-continuation bytes
    literal bytes
    u16le match offset (1..pos, within the block) — present unless
    the literals completed the block (the final literals-only token)

Matches never cross block boundaries (blocks are independent lanes of
one dispatch) and never exceed ``MAX_MATCH`` (the kernel's
vectorization depth — the cap is part of the format: host and device
emit identical tokens because both plan with the same cap).
"""

from __future__ import annotations

import struct

import numpy as np

from . import Compressor, CompressorError

MAGIC = b"TLZ1"
_HDR = struct.Struct("<II")
_CSIZE = struct.Struct("<H")
_OFF = struct.Struct("<H")


def _consts():
    """Format constants live with the kernel (lazy import keeps
    compress importable on host-only builds that never touch jax)."""
    from ..device.lzkernel import MAX_MATCH, MIN_MATCH, TLZ_BLOCK
    return TLZ_BLOCK, MIN_MATCH, MAX_MATCH


# -- token emission (host, cheap, identical for both plan paths) ----------


def _put_ext(out: bytearray, v: int) -> None:
    while v >= 255:
        out.append(255)
        v -= 255
    out.append(v)


def _emit_seq(out: bytearray, lits, offset: int, mlen: int,
              min_match: int) -> None:
    ll = len(lits)
    ml = (mlen - min_match) if offset else 0
    out.append((min(ll, 15) << 4) | min(ml, 15))
    if ll >= 15:
        _put_ext(out, ll - 15)
    out += lits
    if offset:
        out += _OFF.pack(offset)
        if ml >= 15:
            _put_ext(out, ml - 15)


def _emit_block(block: bytes, cand, mlen, min_match: int) -> bytes:
    """Greedy tokenization of one block from its planned
    (candidate, match-length) rows.  The literal-skip uses the plan's
    eligibility mask, so the loop iterates once per MATCH, not per
    byte — incompressible blocks degenerate to one stored check."""
    n = len(block)
    out = bytearray()
    elig = np.flatnonzero((cand[:n] >= 0) & (mlen[:n] >= min_match))
    i = 0
    anchor = 0
    while True:
        nxt = np.searchsorted(elig, i)
        if nxt >= elig.size:
            break
        i = int(elig[nxt])
        ln = min(int(mlen[i]), n - i)
        if ln < min_match:
            i += 1
            continue
        _emit_seq(out, block[anchor:i], i - int(cand[i]), ln,
                  min_match)
        i += ln
        anchor = i
    if anchor < n:
        _emit_seq(out, block[anchor:n], 0, 0, min_match)
    return bytes(out)


def _assemble(data: bytes, cand: np.ndarray,
              mlen: np.ndarray) -> bytes:
    """The container from the per-block plans: tokenize each block,
    store raw whenever tokens would not shrink it."""
    block, min_match, _ = _consts()
    out = bytearray(MAGIC)
    out += _HDR.pack(len(data), block)
    for bi, off in enumerate(range(0, len(data), block)):
        raw = data[off:off + block]
        tok = _emit_block(raw, cand[bi], mlen[bi], min_match)
        if len(tok) < len(raw):
            out += _CSIZE.pack(len(tok))
            out += tok
        else:
            out += _CSIZE.pack(0)
            out += raw
    return bytes(out)


def _blocks_of(data: bytes) -> list[bytes]:
    block, _, _ = _consts()
    return [data[off:off + block]
            for off in range(0, len(data), block)]


# -- compression entry points ---------------------------------------------


def compress_host(data: bytes) -> bytes:
    """The pure-numpy reference (and the degradation target): plans
    matches with `match_plan_host` and emits the identical container
    the device path produces."""
    from ..device.lzkernel import _stage_blocks, match_plan_host
    data = bytes(data)
    segs = _blocks_of(data)
    if not segs:
        return _assemble(data, np.zeros((0, 0), np.int32),
                         np.zeros((0, 0), np.int32))
    stage, lens = _stage_blocks(segs, len(segs))
    cand, mlen = match_plan_host(stage, lens)
    return _assemble(data, cand, mlen)


async def compress_async(data: bytes, chip: int | None = None,
                         klass: str | None = None
                         ) -> tuple[bytes, str]:
    """Device-planned compression on the caller's affinity chip under
    the background admission class; returns (blob, path).  Every
    degradation lands on `compress_host`, which emits the identical
    bytes — so the caller's stored blob is path-independent.  Device
    traffic is accounted on the chip's ``device_compress_bytes_in`` /
    ``device_compress_bytes_out`` gauges."""
    from ..device.lzkernel import K_BACKGROUND, match_batch
    from ..device.runtime import DeviceRuntime
    data = bytes(data)
    segs = _blocks_of(data)
    if not segs:
        return compress_host(data), "host"
    cand, mlen, path = await match_batch(
        segs, chip=chip, klass=klass or K_BACKGROUND)
    blob = _assemble(data, cand, mlen)
    if path == "device":
        target = DeviceRuntime.get().route(chip)
        if target is not None:
            target.note_compress(len(data), len(blob))
    return blob, path


def decompress(blob: bytes) -> bytes:
    """Sequential host decode; integrity-checked (magic, block
    structure, offsets, declared raw length) — a truncated or
    corrupted stream raises CompressorError, never returns short
    bytes."""
    blob = bytes(blob)
    if len(blob) < len(MAGIC) + _HDR.size or \
            blob[:len(MAGIC)] != MAGIC:
        raise CompressorError("tlz: bad magic")
    raw_len, block = _HDR.unpack_from(blob, len(MAGIC))
    if block <= 0:
        raise CompressorError("tlz: bad block size %d" % block)
    _, min_match, _ = _consts()
    p = len(MAGIC) + _HDR.size
    out = bytearray()
    while len(out) < raw_len:
        if p + _CSIZE.size > len(blob):
            raise CompressorError("tlz: truncated container")
        (csize,) = _CSIZE.unpack_from(blob, p)
        p += _CSIZE.size
        want = min(block, raw_len - len(out))
        if csize == 0:
            if p + want > len(blob):
                raise CompressorError("tlz: truncated stored block")
            out += blob[p:p + want]
            p += want
            continue
        tok = blob[p:p + csize]
        if len(tok) < csize:
            raise CompressorError("tlz: truncated token block")
        p += csize
        out += _decode_block(tok, want, min_match)
    if len(out) != raw_len or p != len(blob):
        raise CompressorError(
            "tlz: length mismatch (decoded %d of %d, %d trailing)"
            % (len(out), raw_len, len(blob) - p))
    return bytes(out)


def _decode_block(tok: bytes, raw_len: int, min_match: int) -> bytes:
    out = bytearray()
    p = 0
    n = len(tok)
    while len(out) < raw_len:
        if p >= n:
            raise CompressorError("tlz: token stream underrun")
        t = tok[p]
        p += 1
        ll = t >> 4
        if ll == 15:
            while True:
                if p >= n:
                    raise CompressorError("tlz: bad literal length")
                b = tok[p]
                p += 1
                ll += b
                if b != 255:
                    break
        if p + ll > n:
            raise CompressorError("tlz: literal overrun")
        out += tok[p:p + ll]
        p += ll
        if len(out) > raw_len:
            raise CompressorError("tlz: block overflow")
        if len(out) == raw_len:
            break
        if p + _OFF.size > n:
            raise CompressorError("tlz: missing match offset")
        (off,) = _OFF.unpack_from(tok, p)
        p += _OFF.size
        ml = t & 15
        if ml == 15:
            while True:
                if p >= n:
                    raise CompressorError("tlz: bad match length")
                b = tok[p]
                p += 1
                ml += b
                if b != 255:
                    break
        ml += min_match
        if off <= 0 or off > len(out):
            raise CompressorError("tlz: bad match offset %d at %d"
                                  % (off, len(out)))
        if len(out) + ml > raw_len:
            raise CompressorError("tlz: match overflows block")
        src = len(out) - off
        want = ml
        while want > 0:                 # overlap-safe chunked copy
            chunk = out[src:src + want]
            out += chunk
            want -= len(chunk)
    if p != n:
        raise CompressorError("tlz: %d trailing token bytes" % (n - p))
    return bytes(out)


class TlzCompressor(Compressor):
    """Registry plugin: the synchronous interface serves the host
    reference (wire compression, client-side callers); the OSD write
    path upgrades to `compress_async` for device planning — both
    produce the same bytes."""

    name = "tlz"

    def compress(self, data: bytes) -> bytes:
        return compress_host(data)

    def decompress(self, blob: bytes) -> bytes:
        return decompress(blob)
