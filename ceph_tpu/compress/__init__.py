"""Compression plugin framework (src/compressor/ tier).

Analog of Compressor.h:35 + the per-algorithm plugin directories
(src/compressor/{zlib,snappy,zstd,lz4}/): a registry of named
compressors behind one two-method interface, consumed by

* the OSD data path (pool-level compression of full-object writes,
  BlueStore blob-compression role — see osd/daemon.py), and
* the messenger (on-wire frame compression, the msgr2
  compression_onwire.cc role — see msg/messenger.py).

Algorithms ship from the stdlib (zlib, lzma, bz2) with optional
snappy/zstd/lz4 picked up when their modules exist in the image —
the same graceful-degradation contract the reference's plugin loader
has (missing .so = algorithm unavailable, not an error).

Every blob is self-describing: compress() returns the raw algorithm
output, and callers record the algorithm name beside it (pool xattr /
wire flag), mirroring how the reference stores the alg in the blob /
negotiates it per connection.
"""

from __future__ import annotations

import bz2
import lzma
import zlib


class CompressorError(Exception):
    pass


# xattr names marking a compressed object image (shared by the OSD
# write path and the cls MethodContext so both see one convention)
OBJ_ALGO_ATTR = "comp-alg"
OBJ_SIZE_ATTR = "comp-size"


class Compressor:
    """One algorithm (CompressionPlugin + Compressor instance)."""

    name = ""

    def compress(self, data: bytes) -> bytes:
        raise NotImplementedError

    def decompress(self, blob: bytes) -> bytes:
        raise NotImplementedError


class ZlibCompressor(Compressor):
    name = "zlib"

    # level 1: compression runs on the daemon's event loop, so the
    # default trades ratio for latency (heavier levels/algos are an
    # explicit operator choice via compression_algorithm)
    def __init__(self, level: int = 1):
        self.level = level

    def compress(self, data: bytes) -> bytes:
        return zlib.compress(data, self.level)

    def decompress(self, blob: bytes) -> bytes:
        try:
            return zlib.decompress(blob)
        except zlib.error as e:
            raise CompressorError("zlib: %s" % e) from None


class LzmaCompressor(Compressor):
    name = "lzma"

    def compress(self, data: bytes) -> bytes:
        return lzma.compress(data, preset=1)

    def decompress(self, blob: bytes) -> bytes:
        try:
            return lzma.decompress(blob)
        except lzma.LZMAError as e:
            raise CompressorError("lzma: %s" % e) from None


class Bz2Compressor(Compressor):
    name = "bz2"

    def compress(self, data: bytes) -> bytes:
        return bz2.compress(data, 1)

    def decompress(self, blob: bytes) -> bytes:
        try:
            return bz2.decompress(blob)
        except (OSError, ValueError) as e:
            raise CompressorError("bz2: %s" % e) from None


_REGISTRY: dict[str, Compressor] = {}


def register(comp: Compressor) -> None:
    _REGISTRY[comp.name] = comp


def create(name: str) -> Compressor:
    """Compressor::create: by-name factory; unknown = error."""
    c = _REGISTRY.get(name)
    if c is None:
        raise CompressorError("no compressor %r (have: %s)"
                              % (name, sorted(_REGISTRY)))
    return c


def available() -> list[str]:
    return sorted(_REGISTRY)


register(ZlibCompressor())
register(LzmaCompressor())
register(Bz2Compressor())

# the device-native LZ-class codec ("tlz", compress/tlz.py): match
# planning dispatches on the daemon's affinity chip, token emission is
# host-side, and the host reference emits byte-identical blobs — so
# it registers like any other algorithm and every consumer (pool
# compression, wire frames, recovery pushes) can decode it with the
# sync interface alone
from .tlz import TlzCompressor  # noqa: E402  (needs Compressor above)

register(TlzCompressor())

# optional third-party algorithms, loaded like dlopen'd plugins
try:                                    # pragma: no cover
    import snappy as _snappy

    class SnappyCompressor(Compressor):
        name = "snappy"

        def compress(self, data: bytes) -> bytes:
            return _snappy.compress(data)

        def decompress(self, blob: bytes) -> bytes:
            try:
                return _snappy.decompress(blob)
            except Exception as e:
                raise CompressorError("snappy: %s" % e) from None

    register(SnappyCompressor())
except ImportError:
    pass

try:                                    # pragma: no cover
    import zstandard as _zstd

    class ZstdCompressor(Compressor):
        name = "zstd"

        def compress(self, data: bytes) -> bytes:
            return _zstd.ZstdCompressor().compress(data)

        def decompress(self, blob: bytes) -> bytes:
            try:
                return _zstd.ZstdDecompressor().decompress(blob)
            except Exception as e:
                raise CompressorError("zstd: %s" % e) from None

    register(ZstdCompressor())
except ImportError:
    pass
