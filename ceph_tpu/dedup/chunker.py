"""Content-defined chunking + batched fingerprints: the data-reduction
plane's device kernels.

"GPUs as Storage System Accelerators" (arXiv:1202.3669, PAPERS.md)
names hashing/deduplication as the canonical storage offload, and the
two expensive phases of dedup are exactly the primitives this repo
already runs on-chip: a rolling hash over every byte position
(`device/lzkernel.py`'s gram machinery) and a digest per chunk
(`device/digest.py`'s CRC lanes).  This module composes them:

* **rolling-hash boundary candidates on-device** — every position i
  hashes the 8-byte window ending at i as two le32 grams mixed with
  the lzkernel multiplicative constant plus a second odd prime:
  ``mix = (le32(b[i-7:i-3]) * C1) ^ (le32(b[i-3:i+1]) * C2)``; a
  position is a CANDIDATE cut iff ``mix & (CHUNK_AVG-1) == MAGIC``.
  Fully parallel across positions and lanes — blobs split into
  fixed ``SEG``-byte body segments with an 8-byte left margin (the
  Ragged Paged Attention discipline: variable-length blobs inside
  fixed-geometry programs), lanes bucket pow2 between ``_MIN_LANES``
  and ``_MAX_LANES`` (3 programs), oversized batches chunk into more
  dispatches of the SAME programs.
* **sequential min/avg/max resolution on host in BOTH paths** — the
  candidate mask is the parallel 99%; walking it into actual cuts
  (first candidate >= start+CHUNK_MIN, forced cut at start+CHUNK_MAX)
  is a cheap O(cuts) host walk shared verbatim by the device and
  fallback paths, so bit-parity of the cut lists reduces to
  bit-parity of the masks — which is exact by construction (the host
  mask zero-pads the blob front exactly like the first segment's
  staged margin).
* **chunk fingerprints through the digest lanes** — one
  ``crc32_batch`` dispatch per chunk batch (``CHUNK_MAX`` ==
  digest.DEVICE_MAX_BYTES, so every chunk digests in one lane);
  fingerprints are ``"%08x-%x" % (crc32, len)`` and a chunk object's
  oid embeds its fingerprint — content addressing the deep scrub can
  verify against the stored bytes for free.
* **admission + degradation identical to the digest plane** — the
  ``background`` class, DeviceBusy / poisoned chip / offload-off /
  mid-dispatch failure (poisons THIS chip) all land on the numpy
  reference, which is the same function.

Bit-parity contract: `chunk_host` and the device path produce the
identical cut lists and fingerprints (pinned by tests/test_dedup.py
and the `bench.py --dedup` gate).
"""

from __future__ import annotations

import functools
import os

import numpy as np

from ..device.runtime import DeviceBusy, DeviceRuntime, K_BACKGROUND

# chunk-size policy: candidates fire at 1/CHUNK_AVG positions, the
# resolution walk enforces [CHUNK_MIN, CHUNK_MAX].  CHUNK_MAX equals
# digest.DEVICE_MAX_BYTES so every chunk fingerprints in one CRC lane.
CHUNK_MIN = 2048
CHUNK_AVG = 8192                # mask = CHUNK_AVG - 1 (pow2 required)
CHUNK_MAX = 16384

SEG = 8192                      # body bytes per device lane
MARGIN = 8                      # rolling-window left margin per lane

_MIX1 = np.uint32(2654435761)   # lzkernel's multiplicative hash prime
_MIX2 = np.uint32(0x85EBCA77)   # second odd prime (xxhash PRIME32_2)
_MAGIC = np.uint32(0x13AB)      # boundary residue (< CHUNK_AVG)

_MIN_LANES = 8                  # pow2 lane floor
_MAX_LANES = 32                 # lane cap: 3 programs total

CHUNK_OID_PREFIX = "chunk."


def device_dedup_enabled() -> bool:
    """Device chunking defaults to on where device EC offload is on
    (a real accelerator backend, or the CEPH_TPU_EC_OFFLOAD test
    override); CEPH_TPU_DEDUP_OFFLOAD=1/0 forces it independently —
    the same gate shape as the digest and compression planes."""
    v = os.environ.get("CEPH_TPU_DEDUP_OFFLOAD")
    if v is not None:
        return v not in ("0", "false", "no")
    from ..ec.batcher import device_offload_enabled
    return device_offload_enabled()


def _pow2_lanes(n: int) -> int:
    return 1 << max(int(n) - 1, _MIN_LANES - 1).bit_length()


# -- fingerprint / chunk-oid helpers (shared with scrub) -------------------


def fingerprint(crc: int, size: int) -> str:
    return "%08x-%x" % (crc & 0xFFFFFFFF, size)


def chunk_oid(fp: str) -> str:
    return CHUNK_OID_PREFIX + fp


def parse_chunk_oid(oid: str) -> tuple[int, int] | None:
    """(crc32, size) when ``oid`` is a content-addressed chunk oid,
    else None — the deep scrub uses this to verify stored bytes
    against the address they claim."""
    if not oid.startswith(CHUNK_OID_PREFIX):
        return None
    body = oid[len(CHUNK_OID_PREFIX):]
    crc_s, sep, size_s = body.partition("-")
    if not sep or len(crc_s) != 8:
        return None
    try:
        return int(crc_s, 16), int(size_s, 16)
    except ValueError:
        return None


# -- host reference (and the device kernel's parity oracle) ----------------


def candidate_mask_host(data) -> np.ndarray:
    """Boundary-candidate mask for one whole blob: mask[i] is True
    iff the 8-byte window ending at i (zero-padded off the front,
    exactly like the first device segment's staged margin) hits the
    boundary residue.  Pure numpy — this IS the host fallback's mask,
    and the device kernel below is this function transcribed to jax
    over fixed-geometry segments."""
    a = np.frombuffer(bytes(data), np.uint8)
    n = a.size
    if n == 0:
        return np.zeros(0, bool)
    p = np.zeros(n + MARGIN, np.uint8)
    p[MARGIN:] = a
    b = p.astype(np.uint32)
    i = np.arange(n, dtype=np.int64)
    w = [b[i + t + 1] for t in range(8)]
    g1 = w[0] | (w[1] << np.uint32(8)) | (w[2] << np.uint32(16)) \
        | (w[3] << np.uint32(24))
    g2 = w[4] | (w[5] << np.uint32(8)) | (w[6] << np.uint32(16)) \
        | (w[7] << np.uint32(24))
    mix = (g1 * _MIX1) ^ (g2 * _MIX2)
    return (mix & np.uint32(CHUNK_AVG - 1)) == _MAGIC


def _mask_lanes_host(stage: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """The staged-lane form of `candidate_mask_host`: identical
    arithmetic over a [lanes, MARGIN+SEG] stage — the per-dispatch
    host fallback, bit-identical to the device kernel."""
    idx = np.arange(SEG, dtype=np.int64)
    b = stage.astype(np.uint32)
    w = [b[:, idx + t + 1] for t in range(8)]
    g1 = w[0] | (w[1] << np.uint32(8)) | (w[2] << np.uint32(16)) \
        | (w[3] << np.uint32(24))
    g2 = w[4] | (w[5] << np.uint32(8)) | (w[6] << np.uint32(16)) \
        | (w[7] << np.uint32(24))
    mix = (g1 * _MIX1) ^ (g2 * _MIX2)
    hit = (mix & np.uint32(CHUNK_AVG - 1)) == _MAGIC
    return hit & (idx[None, :] < lens.astype(np.int64)[:, None])


def resolve_cuts(mask: np.ndarray, n: int) -> list[int]:
    """Walk a candidate mask into interior cut offsets: the next cut
    is one past the first candidate position >= start+CHUNK_MIN-1,
    forced at start+CHUNK_MAX when none fires, and the tail is never
    cut below CHUNK_MIN.  Cheap sequential host work shared by both
    paths — parity of cuts reduces to parity of masks."""
    cuts: list[int] = []
    pos = np.flatnonzero(mask)
    start = 0
    while n - start > CHUNK_MIN:
        lo = start + CHUNK_MIN - 1
        hi = min(start + CHUNK_MAX - 1, n - 2)
        j = int(np.searchsorted(pos, lo))
        if j < pos.size and pos[j] <= hi:
            c = int(pos[j]) + 1
        elif start + CHUNK_MAX < n:
            c = start + CHUNK_MAX
        else:
            break
        cuts.append(c)
        start = c
    return cuts


def chunk_host(data) -> list[int]:
    """Interior cut offsets for one blob — the host fallback AND the
    device path's parity oracle."""
    return resolve_cuts(candidate_mask_host(data), len(data))


def split(data: bytes, cuts: list[int]) -> list[bytes]:
    bounds = [0] + list(cuts) + [len(data)]
    return [bytes(data[bounds[i]:bounds[i + 1]])
            for i in range(len(bounds) - 1)]


# -- device kernel ---------------------------------------------------------


@functools.lru_cache(maxsize=8)
def _kernel(lanes: int):
    """One jitted boundary-candidate program per lane bucket (width is
    fixed at MARGIN+SEG): the exact arithmetic of
    `candidate_mask_host` over staged segments."""
    import jax
    import jax.numpy as jnp

    def run(data, lens):
        idx = jnp.arange(SEG, dtype=jnp.int32)
        b = data.astype(jnp.uint32)
        w = [b[:, idx + jnp.int32(t + 1)] for t in range(8)]
        g1 = w[0] | (w[1] << jnp.uint32(8)) \
            | (w[2] << jnp.uint32(16)) | (w[3] << jnp.uint32(24))
        g2 = w[4] | (w[5] << jnp.uint32(8)) \
            | (w[6] << jnp.uint32(16)) | (w[7] << jnp.uint32(24))
        mix = (g1 * jnp.uint32(_MIX1)) ^ (g2 * jnp.uint32(_MIX2))
        hit = (mix & jnp.uint32(CHUNK_AVG - 1)) == jnp.uint32(_MAGIC)
        return hit & (idx[None, :] < lens[:, None])

    return jax.jit(run)


def _segments(blobs) -> tuple[list[tuple[int, np.ndarray, np.ndarray]],
                              list[int]]:
    """(segments, blob lengths): each segment is (blob index, margin
    bytes, body bytes) with the margin the 8 bytes preceding the body
    in ITS blob (empty for a blob's first segment — the kernel's
    zero-filled margin is the host mask's zero front-pad)."""
    segs: list[tuple[int, np.ndarray, np.ndarray]] = []
    ns: list[int] = []
    for bi, blob in enumerate(blobs):
        a = np.frombuffer(bytes(blob), np.uint8)
        ns.append(a.size)
        for off in range(0, a.size, SEG):
            segs.append((bi, a[max(0, off - MARGIN):off],
                         a[off:off + SEG]))
    return segs, ns


def _stage_segments(segs, lanes: int, stage: np.ndarray) -> np.ndarray:
    lens = np.zeros(lanes, np.int32)
    for i, (_bi, margin, body) in enumerate(segs):
        stage[i, :MARGIN] = 0
        if margin.size:
            stage[i, MARGIN - margin.size:MARGIN] = margin
        stage[i, MARGIN:MARGIN + body.size] = body
        lens[i] = body.size
    return lens


async def boundary_batch(blobs, chip: int | None = None,
                         klass: str = K_BACKGROUND
                         ) -> tuple[list[list[int]], str]:
    """Cut lists for every blob, the candidate masks computed in
    background-class device dispatches on the caller's affinity chip;
    returns (cuts per blob, path).  Any degradation (offload
    disabled, chip lost, queue full, mid-dispatch failure — which
    poisons THIS chip) lands on the numpy reference, which computes
    the identical masks."""
    blobs = list(blobs)
    if not blobs:
        return [], "host"
    rt = DeviceRuntime.get()
    target = rt.route(chip)
    if target is None or not target.available \
            or not device_dedup_enabled():
        return [chunk_host(b) for b in blobs], "host"
    segs, ns = _segments(blobs)
    if not segs:
        return [[] for _ in blobs], "host"
    masks: list[np.ndarray | None] = [None] * len(segs)
    path = "device"
    width = MARGIN + SEG
    for lo in range(0, len(segs), _MAX_LANES):
        segs_c = segs[lo:lo + _MAX_LANES]
        lanes = min(_pow2_lanes(len(segs_c)), _MAX_LANES)
        total = sum(body.size for _bi, _m, body in segs_c)
        ticket = target.open_ticket(klass, lanes, total)
        try:
            await target.admit(ticket)
        except DeviceBusy:
            st = np.zeros((len(segs_c), width), np.uint8)
            lens = _stage_segments(segs_c, len(segs_c), st)
            m = _mask_lanes_host(st, lens)
            for i in range(len(segs_c)):
                masks[lo + i] = m[i]
            target.host_fallbacks += 1
            path = "host"
            continue
        stage = target.pool.lease((lanes, width), np.uint8)
        try:
            import jax.numpy as jnp
            lens = _stage_segments(segs_c, lanes, stage)
            target.launch(ticket)       # injected-fault hook
            m = np.asarray(_kernel(lanes)(
                target.place(jnp.asarray(stage)),
                target.place(jnp.asarray(lens))))
            target.note_program("cdc", (lanes, width))
            target.finish(ticket, ok=True)
            target.note_staging(total // 4, (lanes * width) // 4)
            for i in range(len(segs_c)):
                masks[lo + i] = m[i]
        except Exception as e:
            # device loss mid-chunk: poison THIS chip (per-chip
            # DEVICE_FALLBACK + probe heal), mask the rest on host
            target.finish(ticket, ok=False, error=e)
            target.poison(e)
            for i, seg in enumerate(segs[lo:]):
                st = np.zeros((1, width), np.uint8)
                lens = _stage_segments([seg], 1, st)
                masks[lo + i] = _mask_lanes_host(st, lens)[0]
            target.host_fallbacks += 1
            path = "host"
            break
        finally:
            target.pool.release(stage)
    cuts: list[list[int]] = []
    si = 0
    for n in ns:
        parts: list[np.ndarray] = []
        rem = n
        while rem > 0:
            body_len = min(SEG, rem)
            parts.append(masks[si][:body_len])
            si += 1
            rem -= body_len
        mask = (np.concatenate(parts) if parts
                else np.zeros(0, bool))
        cuts.append(resolve_cuts(mask, n))
    return cuts, path


async def fingerprint_batch(chunks, chip: int | None = None,
                            klass: str = K_BACKGROUND
                            ) -> tuple[list[str], str]:
    """Content fingerprints for a chunk batch through the digest
    plane's CRC lanes (one dispatch; host zlib.crc32 fallback):
    ``"%08x-%x" % (crc32, len)`` — the chunk store's address space.
    Chip-labeled fingerprint gauges account the device path."""
    from ..device import digest
    chunks = list(chunks)
    if not chunks:
        return [], "host"
    crcs, path = await digest.crc32_batch(chunks, chip=chip,
                                          klass=klass)
    if path == "device":
        rt = DeviceRuntime.get()
        target = rt.route(chip)
        if target is not None:
            target.note_fingerprint(
                len(chunks), sum(len(c) for c in chunks))
    return [fingerprint(c, len(b))
            for c, b in zip(crcs, chunks)], path
