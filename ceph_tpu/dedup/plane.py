"""DedupPlane: the OSD-side orchestrator of the data-reduction plane.

The primary of a dedup base pool routes every client op here (the
`_handle_op` hook, right after the compression hook).  Writes are
planned first — chunk boundaries in one device dispatch
(`chunker.boundary_batch`), fingerprints in one more
(`chunker.fingerprint_batch`), then one refcount get per unique
fingerprint against the chunk pool (ref-or-create; a zero committed
size means WE store the bytes) — and the synchronous base mutation
then rides a BACKGROUND admission grant exactly like a compression
op (the device dispatches pace themselves through ticket admission;
the grant is never held across them).  The planned manifest rides
into `_execute_write` as ``dedup_pre`` so the base mutation is one
ordinary replicated transaction; once it lands, refs the new
manifest no longer holds are put (the chunk store self-deletes on
the last put).

Chunk-pool I/O goes through `InternalObjecter` — the OSD acting as
its own minimal librados client: placement from its subscribed
OSDMap, self-primary ops looped back into `_handle_op` directly,
remote ops over the existing OSD mesh, and timeout resends with the
SAME tid so the reqid journal answers duplicates instead of
double-running a non-idempotent refcount put.

Failure policy is raw-first: any `ObjecterError` during planning
degrades the write to a RAW store (refs taken so far are rolled
back, best-effort) — an acked write never depends on the chunk pool
being healthy.
"""

from __future__ import annotations

import asyncio
import time

from ..msg.messages import MOSDOp, MOSDOpReply
from ..store.objectstore import NotFound, hobject_t
from ..utils import denc
from .chunker import (CHUNK_MIN, boundary_batch, chunk_oid,
                      fingerprint_batch, split)

# base-object xattrs (the dedup analog of compress.OBJ_*_ATTR):
# MANIFEST marks the object's data as a manifest blob; LOGICAL is the
# pre-dedup size so stat answers without materializing
OBJ_MANIFEST_ATTR = "dedup-manifest"
OBJ_LOGICAL_ATTR = "dedup-size"

# ops whose interpretation needs the raw bytes in place (a manifested
# object must be materialized before they run)
_RAW_MUTATORS = ("write", "truncate", "call", "omap-set", "omap-rm")


class ObjecterError(Exception):
    """An internal chunk-pool op could not be delivered (pool gone,
    no primary, resend budget exhausted) — distinct from a DELIVERED
    op returning a nonzero result, which the caller interprets."""


class _LoopbackConn:
    """The connection the primary hands `_handle_op` for its own
    internal ops: replies route straight back to the objecter, and
    `peer_entity` names an OSD so `_send_backoff` skips it (parked
    internal ops are requeued by the PG, never backed off)."""

    def __init__(self, objecter: "InternalObjecter"):
        self._objecter = objecter
        self.peer_entity = objecter.osd.msgr.entity
        self.peer_addr = "loopback/%s" % objecter.osd.msgr.entity
        self.is_open = True

    def send(self, msg) -> None:
        if isinstance(msg, MOSDOpReply):
            self._objecter.on_reply(msg)


class InternalObjecter:
    """Minimal Objecter for daemon-internal ops (the reference's
    cls_cas/dedup flows run client-side; here the primary IS the
    client of the chunk pool).  One op at a time per call: compute
    the target from the daemon's own OSDMap, loop back when this OSD
    is the primary, otherwise ride the OSD mesh; resend on timeout
    with the SAME tid so the reqid journal answers a duplicate of an
    already-committed (non-idempotent) refcount mutation."""

    def __init__(self, osd):
        self.osd = osd
        # tid base derived from wall clock: this daemon's reqid
        # journal rows survive a restart, so a restarted counter must
        # not collide with journaled tids of its previous life
        self._tid = (int(time.time()) & 0x7FFFFFFF) << 20
        self.inflight: dict[int, asyncio.Future] = {}
        self._loopback = _LoopbackConn(self)

    def on_reply(self, msg: MOSDOpReply) -> bool:
        fut = self.inflight.get(msg.tid)
        if fut is None:
            return False
        if not fut.done():
            fut.set_result(msg)
        return True

    async def op(self, pool_id: int, oid: str, ops: list[dict],
                 timeout: float = 5.0, attempts: int = 6
                 ) -> tuple[int, list]:
        """Execute one op list against (pool_id, oid); returns the
        reply's (result, outs).  Raises ObjecterError when the op
        cannot be delivered at all."""
        osd = self.osd
        self._tid += 1
        tid = self._tid
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self.inflight[tid] = fut
        try:
            for _ in range(max(1, attempts)):
                m = osd.osdmap
                pool = m.pools.get(pool_id) if m is not None else None
                if pool is None:
                    raise ObjecterError(
                        "pool %d gone from the map" % pool_id)
                pgid = pool.raw_pg_to_pg(
                    m.object_locator_to_pg(oid, pool_id))
                _up, _upp, _acting, primary = \
                    m.pg_to_up_acting_osds(pgid)
                if primary >= 0:
                    msg = MOSDOp(tid=tid, pool=pool_id, ps=pgid.ps,
                                 oid=oid, snapc=None, snapid=None,
                                 ops=ops, epoch=m.epoch, flags=0)
                    if primary == osd.whoami:
                        # Connection.send stamps src on the wire
                        # path; the loopback call must stamp it too
                        # (the reqid journal keys on it)
                        msg.src = osd.msgr.entity
                        osd._handle_op(self._loopback, msg)
                    else:
                        osd._send_osd(primary, msg)
                try:
                    rep = await asyncio.wait_for(
                        asyncio.shield(fut), timeout)
                    return rep.result, rep.outs
                except asyncio.TimeoutError:
                    continue    # same tid: a dup is journal-answered
            raise ObjecterError(
                "op on %d:%s undelivered after %d attempts"
                % (pool_id, oid, attempts))
        finally:
            self.inflight.pop(tid, None)


class DedupPlane:
    def __init__(self, osd):
        self.osd = osd
        self.objecter = InternalObjecter(osd)
        # per-base-pool dedup counters, shipped in osd_stats.dedup
        # and folded by the mgr digest into `dedup_pools`
        self.pool_stats: dict[int, dict[str, int]] = {}
        # write reqids currently being planned: the daemon's journal
        # dup check only covers COMMITTED ops, so a timeout resend
        # landing mid-plan must wait for the original instead of
        # planning (and accounting) the same write twice
        self._inflight: dict[tuple, asyncio.Event] = {}

    # -- stats -------------------------------------------------------------

    def _stats(self, pool_id: int) -> dict[str, int]:
        return self.pool_stats.setdefault(int(pool_id), {
            "chunks_stored": 0, "chunks_deduped": 0,
            "bytes_stored": 0, "bytes_saved": 0})

    def stats_row(self) -> dict[str, dict[str, int]]:
        return {str(pid): dict(row)
                for pid, row in self.pool_stats.items()}

    # -- manifest helpers --------------------------------------------------

    @staticmethod
    def ref_tag(base_pool: int, oid: str) -> str:
        """The refcount tag a base object holds on its chunks: tags
        are presence-based and per-base-object, so re-taking one is
        idempotent and releasing a stale one is benign."""
        return "%d:%s" % (base_pool, oid)

    def manifest_rows(self, pg, ho) -> list[list] | None:
        """The committed manifest rows ([fingerprint, size] in chunk
        order) of ``ho``, or None when the object is raw/absent."""
        store = self.osd.store
        try:
            if not store.getattr(pg.cid, ho, OBJ_MANIFEST_ATTR):
                return None
            return list(denc.decode(store.read(pg.cid, ho)))
        except NotFound:
            return None
        except Exception:
            return None     # torn/garbled manifest reads as raw

    def manifest_fps(self, pg, oid: str) -> list[str] | None:
        rows = self.manifest_rows(pg, hobject_t(oid))
        if rows is None:
            return None
        return [str(r[0]) for r in rows]

    async def materialize(self, pg, rows: list[list]) -> bytes:
        """Fetch a manifest's chunks from the chunk pool and
        reassemble the logical bytes; raises ObjecterError when the
        chunk store cannot serve them."""
        pool = self.osd.osdmap.pools.get(pg.pool_id)
        cpool = getattr(pool, "dedup_chunk_pool", -1)
        parts: list[bytes] = []
        for fp, size in rows:
            result, outs = await self.objecter.op(
                cpool, chunk_oid(str(fp)),
                [{"op": "read", "length": 0}])
            data = (outs[0].get("data") or b"") \
                if result == 0 and outs else b""
            if result != 0 or len(data) != int(size):
                raise ObjecterError(
                    "chunk %s unreadable (r=%d len=%d want=%d)"
                    % (fp, result, len(data), int(size)))
            parts.append(data)
        return b"".join(parts)

    def _reply_error(self, conn, msg, err: str, code: int = -5,
                     finish: str = "error_reply") -> None:
        conn.send(MOSDOpReply(
            tid=msg.tid, result=code,
            outs=[{"error": err} for _ in msg.ops],
            epoch=self.osd.osdmap.epoch, version=0))
        self.osd._op_finish(msg, finish)

    # -- op entry (spawned by the _handle_op hook) -------------------------

    async def handle_op(self, pg, conn, msg, writes: bool) -> None:
        """Plan async (device dispatches and chunk-store I/O pace
        themselves through ticket admission), then run the
        synchronous base mutation / read under a BACKGROUND admission
        grant like the compression path — a full queue degrades to
        unpaced execution; pacing never fails the op."""
        from ..device.runtime import (DeviceBusy, DeviceRuntime,
                                      K_BACKGROUND)
        osd = self.osd
        key = (str(msg.src), msg.tid)
        if writes:
            prior = self._inflight.get(key)
            if prior is not None:
                # in-flight duplicate: the original is still between
                # the daemon's journal dup check and its commit
                osd._op_event(msg, "waiting_for_inflight_dup")
                await prior.wait()
                dup = pg.lookup_reqid(msg.src, msg.tid)
                if dup is not None:
                    conn.send(MOSDOpReply(
                        tid=msg.tid, result=dup["result"],
                        outs=dup["outs"], epoch=osd.osdmap.epoch,
                        version=dup["version"]))
                    osd.perf.inc("dup_ops")
                    osd._op_finish(msg, "dup_answered_from_journal")
                else:
                    # the original error-replied without journaling;
                    # the client owns the retry
                    osd._op_finish(msg, "dropped_inflight_dup")
                return
            self._inflight[key] = asyncio.Event()
        chip = (osd.device_chip if osd.device_chip is not None
                else DeviceRuntime.get().chip_for(osd.whoami))
        cost = max(1.0, sum(len(op.get("data") or b"")
                            for op in msg.ops
                            if isinstance(op, dict)) / 65536.0)
        t0 = osd.optracker.now()
        granted = False
        try:
            plan = None
            if writes:
                plan = await self._plan_write(pg, conn, msg, chip)
                if plan is None:
                    return      # error reply already sent
            else:
                if await self._maybe_read_manifested(pg, conn, msg):
                    return
            try:
                await chip.queue.admit(K_BACKGROUND, cost)
                granted = True
                osd.perf.inc("dedup_paced_ops")
            except DeviceBusy:
                pass    # overloaded: run unpaced, never fail the op
            try:
                if writes:
                    osd._execute_write(pg, conn, msg,
                                       dedup_pre=plan["pre"])
                else:
                    osd._serve_read(pg, conn, msg)
            finally:
                if granted:
                    chip.queue.release()
                    granted = False
            if writes:
                await self._release_refs(pg, msg, plan)
        finally:
            if granted:
                chip.queue.release()
            if writes:
                ev = self._inflight.pop(key, None)
                if ev is not None:
                    ev.set()
            fr = getattr(osd.ctx, "flight_recorder", None)
            if fr is not None:
                fr.span("dedup_paced", t0,
                        meta={"pgid": str(pg.pgid),
                              "paced": granted})

    # -- read path ---------------------------------------------------------

    async def _maybe_read_manifested(self, pg, conn, msg) -> bool:
        """Serve the op list from materialized logical bytes when the
        read target is manifested; False delegates to the ordinary
        sync read path (raw objects, snapped reads resolving to raw
        clones, pgls-only lists)."""
        from ..osd import snaps as snapmod
        from ..store.objectstore import NOSNAP
        osd = self.osd
        snapid = getattr(msg, "snapid", None)
        ho = None
        if msg.oid:
            if snapid not in (None, NOSNAP):
                ho = snapmod.resolve_read_snap(
                    osd.store, pg, msg.oid, snapid)
            else:
                ho = hobject_t(msg.oid)
                if snapmod.is_whiteout(osd.store, pg.cid, ho):
                    ho = None
        rows = self.manifest_rows(pg, ho) if ho is not None else None
        if not rows:
            return False
        try:
            raw = await self.materialize(pg, rows)
        except ObjecterError as e:
            self._reply_error(conn, msg, str(e), finish="read_done")
            return True
        outs: list = []
        result = 0
        for op in msg.ops:
            name = op["op"]
            if name == "read":
                off = op.get("offset", 0)
                length = op.get("length", 0) or -1
                outs.append({"data": raw[off:] if length < 0
                             else raw[off:off + length]})
            elif name == "stat":
                outs.append({"size": len(raw)})
            else:
                o2, r2 = osd._do_read_ops(pg, msg.oid, [op], snapid,
                                          entity=msg.src)
                outs.extend(o2)
                if r2 != 0:
                    result = r2
        conn.send(MOSDOpReply(tid=msg.tid, result=result, outs=outs,
                              epoch=osd.osdmap.epoch, version=0))
        osd.perf.inc("ops")
        pg.stats.note_read(sum(len(o.get("data") or b"")
                               for o in outs if isinstance(o, dict)))
        osd._op_finish(msg, "read_done")
        return True

    # -- write path --------------------------------------------------------

    async def _plan_write(self, pg, conn, msg, chip) -> dict | None:
        """Build ``dedup_pre`` for `_execute_write`: chunk +
        fingerprint every manifestable writefull (one device dispatch
        batch each), ref-or-store each unique fingerprint, and stage
        a materialized raw image when an in-place mutator targets a
        manifested object.  Returns None when an error reply was
        already sent; otherwise the plan consumed by `_release_refs`
        after the mutation lands."""
        osd = self.osd
        pool = osd.osdmap.pools.get(pg.pool_id)
        cpool = getattr(pool, "dedup_chunk_pool", -1)
        tag = self.ref_tag(pg.pool_id, msg.oid)
        stats = self._stats(pg.pool_id)
        snapc = getattr(msg, "snapc", None)
        # snapshots and dedup do not compose: a clone would share the
        # head's chunks without holding refs of its own, so snapped
        # writes store raw — and a manifested object is converted
        # back to raw (one ordinary replicated writefull through the
        # objecter; the snappy guard below keeps IT raw) before its
        # first snapped mutation clones anything
        snappy = bool(getattr(pool, "snaps", None)) \
            or bool(snapc and list(snapc[1]))
        old_rows = self.manifest_rows(pg, hobject_t(msg.oid)) or []
        old_fps = {str(r[0]) for r in old_rows}
        if snappy and old_rows:
            try:
                raw = await self.materialize(pg, old_rows)
                r, _outs = await self.objecter.op(
                    pg.pool_id, msg.oid,
                    [{"op": "writefull", "data": raw}])
                if r != 0:
                    raise ObjecterError("raw conversion r=%d" % r)
            except ObjecterError as e:
                self._reply_error(conn, msg, str(e))
                return None
            old_rows, old_fps = [], set()
        manifest: dict[int, tuple[bytes, int] | None] = {}
        acquired: set[str] = set()
        # plan every manifestable writefull: boundaries + fingerprints
        # in ONE device dispatch batch each, then ref-or-store per
        # unique fingerprint; any chunk-store failure degrades THIS
        # op to a raw store (its refs rolled back by _release_refs)
        wf = [(i, op["data"]) for i, op in enumerate(msg.ops)
              if op.get("op") == "writefull"]
        plan = [(i, d) for i, d in wf
                if not snappy and len(d) >= CHUNK_MIN]
        for i, _d in wf:
            manifest[i] = None      # raw unless planning succeeds
        if plan and cpool >= 0:
            try:
                cuts, cpath = await boundary_batch(
                    [d for _i, d in plan], chip=chip.index)
                chunks = [split(d, c)
                          for (_i, d), c in zip(plan, cuts)]
                flat = [c for cl in chunks for c in cl]
                fps_flat, fpath = await fingerprint_batch(
                    flat, chip=chip.index)
                osd.perf.inc("dedup_chunk_device"
                             if cpath == "device"
                             else "dedup_chunk_host")
                osd.perf.inc("dedup_fp_device" if fpath == "device"
                             else "dedup_fp_host")
                osd._op_event(msg, "dedup_planned")
                # per-op fingerprint rows, then ref-or-store each
                # unique fingerprint once
                sizes: dict[str, int] = {}
                by_fp: dict[str, bytes] = {}
                per_op: list[list[str]] = []
                k = 0
                for (_i, _d), cl in zip(plan, chunks):
                    fps = fps_flat[k:k + len(cl)]
                    k += len(cl)
                    per_op.append(fps)
                    for fp, c in zip(fps, cl):
                        sizes[fp] = len(c)
                        by_fp[fp] = c
                for fp in sorted(by_fp):
                    c = by_fp[fp]
                    r, outs = await self.objecter.op(
                        cpool, chunk_oid(fp),
                        [{"op": "call", "cls": "refcount",
                          "method": "get", "input": {"tag": tag}}])
                    if r != 0:
                        raise ObjecterError(
                            "refcount.get %s r=%d" % (fp, r))
                    acquired.add(fp)
                    cls_out = outs[0].get("out") or {}
                    committed = int(cls_out.get("size", 0))
                    if committed == 0:
                        # every size-0 holder stores the bytes
                        # (idempotent: content-addressed, any racer
                        # writes the identical image)
                        r2, _o2 = await self.objecter.op(
                            cpool, chunk_oid(fp),
                            [{"op": "writefull", "data": c}])
                        if r2 != 0:
                            raise ObjecterError(
                                "chunk store %s r=%d" % (fp, r2))
                    if cls_out.get("created"):
                        # only the get that brought the chunk into
                        # existence accounts it as stored — the cls
                        # serializes on the chunk primary, so exactly
                        # one racer sees created (size alone would
                        # double-count ref-or-store races)
                        stats["chunks_stored"] += 1
                        stats["bytes_stored"] += len(c)
                        osd.perf.inc("dedup_chunks_stored")
                    else:
                        stats["chunks_deduped"] += 1
                        stats["bytes_saved"] += len(c)
                        osd.perf.inc("dedup_chunks_deduped")
                        osd.perf.inc("dedup_bytes_saved", len(c))
                for (i, d), fps in zip(plan, per_op):
                    blob = denc.encode(
                        [[fp, sizes[fp]] for fp in fps])
                    manifest[i] = (blob, len(d))
            except ObjecterError:
                # raw-first degradation: the acked write must not
                # depend on the chunk store; refs taken for THIS op
                # are rolled back by _release_refs (an orphan is
                # benign — presence-based, released on the next
                # successful rewrite or delete of this object)
                for i, _d in plan:
                    manifest[i] = None
        # a manifested object mutated in place (offset write,
        # truncate, cls call) needs its raw bytes staged first
        materialize = None
        if old_rows and any(op.get("op") in _RAW_MUTATORS
                            for op in msg.ops):
            try:
                materialize = await self.materialize(pg, old_rows)
            except ObjecterError as e:
                self._reply_error(conn, msg, str(e))
                return None
        return {"pre": {"manifest": manifest,
                        "materialize": materialize},
                "old_fps": old_fps, "acquired": acquired,
                "cpool": cpool, "tag": tag}

    async def _release_refs(self, pg, msg, plan: dict) -> None:
        """Release refs the committed state no longer holds: compare
        what IS stored now against everything previously held or
        acquired during planning — covers rewrites (old-new), deletes
        (all old), and failed/degraded writes (planning refs only).
        The chunk store self-deletes a chunk on its last put."""
        now_fps = set(self.manifest_fps(pg, msg.oid) or [])
        drop = (plan["old_fps"] | plan["acquired"]) - now_fps
        for fp in sorted(drop):
            try:
                await self.objecter.op(
                    plan["cpool"], chunk_oid(fp),
                    [{"op": "call", "cls": "refcount",
                      "method": "put", "input": {"tag": plan["tag"]}}])
                # ENOENT ("no such tag" / object gone) is benign:
                # tags are presence-based and this tag may have been
                # released by a racing rewrite of the same object
            except ObjecterError:
                pass    # unreachable chunk pool: orphaned ref, benign
