"""Data-reduction plane: content-defined chunking, batched chunk
fingerprints, and a refcounted chunk store.

A base pool opts in with ``osd pool set <base> dedup_chunk_pool
<chunks>`` (both plain replicated; the mon validates).  Full-object
writes on the base pool are chunked by a rolling-hash boundary kernel
(one background-class device dispatch per write batch — `chunker`),
each chunk fingerprinted through the digest plane's CRC lanes, and
stored at most once in the chunk pool as a content-addressed object
(``chunk.<crc32>-<size>``) whose refcount rides `osd.cls.refcount`
(get = ref-or-create returning the committed size; last put
self-deletes).  The base object keeps only a manifest — the ordered
``[fingerprint, size]`` rows — plus two xattrs:

* ``OBJ_MANIFEST_ATTR``: present (``b"1"``) iff the object's data is
  a manifest blob, not raw bytes;
* ``OBJ_LOGICAL_ATTR``: the logical (pre-dedup) size, so ``stat``
  answers without materializing.

Degradation is data-safety-first: any failure to reach the chunk
store (chunk pool degraded, internal op timeouts) stores the object
RAW — an acked write never depends on dedup machinery having worked.
Snapshots and dedup do not compose (a clone would share chunks
without holding refs), so writes carrying a snap context — or
touching a pool with snapshots — store raw, and a manifested object
is materialized back to raw before its first snapped mutation.
"""

from .chunker import (CHUNK_AVG, CHUNK_MAX, CHUNK_MIN,
                      CHUNK_OID_PREFIX, boundary_batch,
                      candidate_mask_host, chunk_host, chunk_oid,
                      device_dedup_enabled, fingerprint,
                      fingerprint_batch, parse_chunk_oid,
                      resolve_cuts, split)
from .plane import (OBJ_LOGICAL_ATTR, OBJ_MANIFEST_ATTR, DedupPlane,
                    InternalObjecter, ObjecterError)

__all__ = [
    "CHUNK_AVG", "CHUNK_MAX", "CHUNK_MIN", "CHUNK_OID_PREFIX",
    "DedupPlane", "InternalObjecter", "ObjecterError",
    "OBJ_LOGICAL_ATTR", "OBJ_MANIFEST_ATTR",
    "boundary_batch", "candidate_mask_host", "chunk_host",
    "chunk_oid", "device_dedup_enabled", "fingerprint",
    "fingerprint_batch", "parse_chunk_oid", "resolve_cuts", "split",
]
