"""Asyncio messenger: v2-style framed transport with policies.

The framework's L3 — the analog of AsyncMessenger + ProtocolV2
(src/msg/Messenger.cc:31, src/msg/async/ProtocolV2.cc,
src/msg/Policy.h), re-expressed on asyncio instead of epoll threads:

* one Messenger per daemon endpoint, bound to a TCP addr (DCN path;
  ICI never carries the RADOS protocol — it lives inside device
  kernels, see SURVEY §2.3);
* Connections perform a banner + identification handshake, then
  exchange CRC-checked frames (tag, length, crc32, payload);
* Policy decides lossy vs lossless semantics: lossy connections die
  with the socket (clients resend via Objecter epoch logic, as in the
  reference); lossless peers keep a session — unacked messages are
  replayed after reconnect and the receiver drops duplicates by seq
  (ProtocolV2 session reconnect, ProtocolV2.cc:2143 reuse path); a
  peer presenting a new nonce is a restarted daemon and gets a fresh
  session (reset_session semantics);
* Dispatchers receive ms_dispatch / ms_handle_reset callbacks.

Structure: every Connection is owned by ONE supervisor task that loops
{acquire transport -> run session (reader+writer subtasks) -> decide
redial/die} — no fire-and-forget task chains, so faults can't orphan
state.

Fault injection: set ``inject_socket_failures`` to N>0 to abort roughly
one in N frame writes (ms_inject_socket_failures,
src/common/options/global.yaml.in:1242), driven by each connection's
seeded RNG so a failure schedule replays.  For richer, per-peer-pair
faults (drop/delay/duplicate/reorder/partition) install a
``faults.FaultInjector`` on ``messenger.fault_injector`` — the
thrasher's lever.  On connections with a resend policy, drop and
reorder are escalated to transport aborts (the frame is withheld and
the session replay path redelivers it): silently losing a frame there
would break the lossless contract the session machinery guarantees.
"""

from __future__ import annotations

import asyncio
import random
import struct
import time
import zlib

from .message import (Message, UnknownMessage, decode_message,
                      encode_message)

BANNER = b"ceph-tpu v2\n"

# frame tags
TAG_MSG = 1
TAG_ACK = 2
TAG_CLOSE = 4

_HDR = struct.Struct(">BII")  # tag, length, crc32

# wire-accounting switch: bench.py --net A/Bs the cost of the
# telemetry plane, so disabling must short-circuit every hot-path
# accounting touch (per-type dicts, queue-wait stamps)
_ACCOUNTING = True


def set_net_accounting(on: bool) -> None:
    global _ACCOUNTING
    _ACCOUNTING = bool(on)


class WireStats:
    """Per-peer wire accounting for one Connection (folded into the
    Messenger's per-peer aggregate when the connection dies, so
    counters survive connection churn and session reconnects).

    Dump keys are registered in ``trace.registry.NET_STAGES`` and
    consumed by the mgr exporter, ``collect_diagnostics()`` and the
    ``bench.py --net`` leg.
    """

    __slots__ = ("tx_msgs", "tx_bytes", "rx_msgs", "rx_bytes",
                 "by_type_tx", "by_type_rx", "queue_wait_s",
                 "queue_wait_n", "queue_wait_max_s", "resends",
                 "replays", "mark_downs", "handshakes", "handshake_s",
                 "backoff_s")

    def __init__(self):
        self.tx_msgs = 0
        self.tx_bytes = 0
        self.rx_msgs = 0
        self.rx_bytes = 0
        self.by_type_tx: dict[str, list] = {}   # type -> [msgs, bytes]
        self.by_type_rx: dict[str, list] = {}
        self.queue_wait_s = 0.0
        self.queue_wait_n = 0
        self.queue_wait_max_s = 0.0
        self.resends = 0            # lossless payloads requeued
        self.replays = 0            # duplicate frames absorbed by seq
        self.mark_downs = 0
        self.handshakes = 0
        self.handshake_s = 0.0      # last completed handshake latency
        self.backoff_s = 0.0        # active redial ramp (0 = healthy)

    def note_tx(self, mtype: str, nbytes: int) -> None:
        self.tx_msgs += 1
        self.tx_bytes += nbytes
        row = self.by_type_tx.get(mtype)
        if row is None:
            row = self.by_type_tx[mtype] = [0, 0]
        row[0] += 1
        row[1] += nbytes

    def note_rx(self, mtype: str, nbytes: int) -> None:
        self.rx_msgs += 1
        self.rx_bytes += nbytes
        row = self.by_type_rx.get(mtype)
        if row is None:
            row = self.by_type_rx[mtype] = [0, 0]
        row[0] += 1
        row[1] += nbytes

    def note_queue_wait(self, wait_s: float) -> None:
        self.queue_wait_s += wait_s
        self.queue_wait_n += 1
        if wait_s > self.queue_wait_max_s:
            self.queue_wait_max_s = wait_s

    def note_handshake(self, latency_s: float) -> None:
        self.handshakes += 1
        self.handshake_s = latency_s

    def fold(self, other: "WireStats") -> None:
        self.tx_msgs += other.tx_msgs
        self.tx_bytes += other.tx_bytes
        self.rx_msgs += other.rx_msgs
        self.rx_bytes += other.rx_bytes
        for src, dst in ((other.by_type_tx, self.by_type_tx),
                         (other.by_type_rx, self.by_type_rx)):
            for mtype, (n, b) in src.items():
                row = dst.get(mtype)
                if row is None:
                    row = dst[mtype] = [0, 0]
                row[0] += n
                row[1] += b
        self.queue_wait_s += other.queue_wait_s
        self.queue_wait_n += other.queue_wait_n
        self.queue_wait_max_s = max(self.queue_wait_max_s,
                                    other.queue_wait_max_s)
        self.resends += other.resends
        self.replays += other.replays
        self.mark_downs += other.mark_downs
        self.handshakes += other.handshakes
        if other.handshakes:
            self.handshake_s = other.handshake_s
        self.backoff_s = max(self.backoff_s, other.backoff_s)

    def dump(self, queue_depth: int = 0) -> dict:
        return {
            "tx_msgs": self.tx_msgs,
            "tx_bytes": self.tx_bytes,
            "rx_msgs": self.rx_msgs,
            "rx_bytes": self.rx_bytes,
            "by_type_tx": {t: list(v)
                           for t, v in sorted(self.by_type_tx.items())},
            "by_type_rx": {t: list(v)
                           for t, v in sorted(self.by_type_rx.items())},
            "queue_depth": queue_depth,
            "queue_wait_s": self.queue_wait_s,
            "queue_wait_n": self.queue_wait_n,
            "queue_wait_max_s": self.queue_wait_max_s,
            "resends": self.resends,
            "replays": self.replays,
            "mark_downs": self.mark_downs,
            "handshakes": self.handshakes,
            "handshake_s": self.handshake_s,
            "backoff_s": self.backoff_s,
        }


def ms_compress_from_conf(conf) -> list[str]:
    """Wire-compression preference list from conf (ms_compress),
    filtered to locally-available algorithms — a node must never
    ADVERTISE what it cannot run, or the two ends of a connection
    would disagree about the frame format."""
    try:
        raw = conf["ms_compress"]
    except Exception:
        return []
    from ..compress import available

    have = set(available())
    return [a.strip() for a in raw.split(",")
            if a.strip() and a.strip() in have]


def _pick_compressor(acceptor_prefs, initiator_algos):
    """Common wire compressor, acceptor's preference order deciding
    (both sides compute the same answer from the exchanged idents).
    Returns a Compressor instance or None."""
    common = [a for a in acceptor_prefs if a in (initiator_algos or [])]
    if not common:
        return None
    from ..compress import CompressorError, create

    try:
        return create(common[0])
    except CompressorError:
        return None


class Policy:
    """Connection semantics per peer type (src/msg/Policy.h)."""

    __slots__ = ("lossy", "resend")

    def __init__(self, lossy: bool, resend: bool):
        self.lossy = lossy
        self.resend = resend

    @classmethod
    def lossy_client(cls) -> "Policy":
        return cls(lossy=True, resend=False)

    @classmethod
    def lossless_peer(cls) -> "Policy":
        return cls(lossy=False, resend=True)


class ConnectionError_(Exception):
    pass


class _PeerClosed(Exception):
    """Peer sent TAG_CLOSE: orderly teardown, not a fault."""


async def _write_frame(writer: asyncio.StreamWriter, tag: int,
                       payload: bytes) -> None:
    writer.write(_HDR.pack(tag, len(payload), zlib.crc32(payload)))
    writer.write(payload)
    await writer.drain()


async def _read_frame(reader: asyncio.StreamReader) -> tuple[int, bytes]:
    hdr = await reader.readexactly(_HDR.size)
    tag, length, crc = _HDR.unpack(hdr)
    payload = await reader.readexactly(length)
    if zlib.crc32(payload) != crc:
        raise ConnectionError_("frame crc mismatch (tag %d)" % tag)
    return tag, payload


class Connection:
    """One logical session with a peer entity.

    Survives TCP reconnects when the policy is lossless: out_seq /
    in_seq and the unacked replay queue persist across transports.
    """

    def __init__(self, msgr: "Messenger", peer_addr: str | None,
                 policy: Policy):
        self.msgr = msgr
        self.peer_addr = peer_addr      # dial address (None on inbound)
        self.peer_entity = ""           # learned in handshake
        self.peer_nonce = -1            # detects peer restarts
        self.policy = policy
        # per-connection seeded RNG: inject_socket_failures draws from
        # it so a failure schedule is replayable per peer pair
        self.rng = msgr._conn_rng(peer_addr or "inbound")
        self.out_seq = 0
        self.in_seq = 0
        self.stats = WireStats()
        self.unacked: list[tuple[int, bytes]] = []
        self.out_q: asyncio.Queue = asyncio.Queue()
        self._open = True
        self._transports: asyncio.Queue = asyncio.Queue()  # inbound only
        self._supervisor: asyncio.Task | None = None
        self._writer: asyncio.StreamWriter | None = None
        self._framer = None             # AEAD bound to live transport

    # -- public API --------------------------------------------------------

    def send(self, msg: Message) -> None:
        """Queue a message (fire and forget, like Messenger::
        send_message). Dropped silently once the connection is down
        (lossy semantics surface as resets, not send errors)."""
        if not self._open:
            return
        self.out_seq += 1
        msg.seq = self.out_seq
        msg.src = self.msgr.entity
        # frames carry the sender's monotonic clock so the receiver
        # can estimate this peer's clock offset (multi-host span merge)
        data = encode_message(msg, stamp=self.msgr.now())
        if self.policy.resend:
            self.unacked.append((msg.seq, data))
        if _ACCOUNTING:
            self.stats.note_tx(msg.TYPE, len(data))
            # queue-wait is SAMPLED 1-in-16: the clock-stamp pair
            # (monotonic at enqueue + at pop) is the most expensive
            # accounting instruction on this path, and the estimator
            # only ever reports averages and maxima — both survive
            # sampling.  Third element = enqueue stamp.
            if self.out_seq & 0xF == 0:
                self.out_q.put_nowait((TAG_MSG, data,
                                       time.monotonic()))
            else:
                self.out_q.put_nowait((TAG_MSG, data))
        else:
            self.out_q.put_nowait((TAG_MSG, data))

    def mark_down(self) -> None:
        """Administrative teardown: no reset callback fires."""
        if not self._open:
            return
        self._open = False
        self.stats.mark_downs += 1
        if self._writer is not None:
            # a partition must also block the graceful CLOSE: the peer
            # has to see a transport fault (dead host semantics, and
            # lossless replay stays armed), never an orderly shutdown
            # crossing a cut
            inj = self.msgr.fault_injector
            send_close = (inj is None or inj.on_control(
                self.msgr.entity, self.peer_entity or "?"))
            try:
                if send_close:
                    # best-effort graceful close so the peer resets
                    # promptly; sealed under the transport AEAD so a
                    # close is only believed when it came from the
                    # key holder
                    payload = b""
                    if self._framer is not None:
                        payload = self._framer.seal(
                            payload, bytes([TAG_CLOSE]))
                    self._writer.write(_HDR.pack(
                        TAG_CLOSE, len(payload), zlib.crc32(payload))
                        + payload)
                self._writer.close()
            except Exception:
                pass
        self._drain_transports()
        if self._supervisor is not None:
            self._supervisor.cancel()
        self.msgr._forget(self)

    def _drain_transports(self) -> None:
        """Close transports accepted for this session but never run —
        an abandoned open socket would wedge Server.wait_closed()."""
        while not self._transports.empty():
            try:
                _r, w = self._transports.get_nowait()[:2]
                w.close()
            except Exception:
                pass

    @property
    def is_open(self) -> bool:
        return self._open

    # -- supervisor --------------------------------------------------------

    def _start(self) -> None:
        runner = (self._run_outbound if self.peer_addr is not None
                  else self._run_inbound)
        self._supervisor = self.msgr.spawn(runner())

    async def _run_outbound(self) -> None:
        from ..utils.backoff import ExpBackoff

        # a dedicated RNG keyed off the peer: the redial jitter must
        # not perturb this connection's seeded failure schedule
        bo = ExpBackoff(base=0.02, cap=2.0,
                        rng=self.msgr._conn_rng(
                            "%s|backoff" % self.peer_addr))
        while self._open:
            writer = None
            try:
                t0 = time.monotonic()
                host, port = self.peer_addr.rsplit(":", 1)
                reader, writer = await asyncio.open_connection(
                    host, int(port))
                framer, comp = await self.msgr._handshake_out(
                    self, reader, writer)
            except asyncio.CancelledError:
                if writer is not None:
                    writer.close()
                return
            except Exception:
                if writer is not None:
                    writer.close()
                if self.policy.lossy:
                    await self._die()
                    return
                delay = bo.next_delay()
                # telemetry reads the ramp position off the stats
                # block while the dial is down (ExpBackoff.state())
                self.stats.backoff_s = bo.state()["interval_s"]
                await asyncio.sleep(delay)
                continue
            bo.reset()
            self.stats.backoff_s = 0.0
            self.stats.note_handshake(time.monotonic() - t0)
            closed = await self._session(reader, writer, framer, comp)
            if closed or self.policy.lossy:
                await self._die()
                return
            await asyncio.sleep(0.01)

    async def _run_inbound(self) -> None:
        try:
            while self._open:
                try:
                    reader, writer, framer, comp = \
                        await self._transports.get()
                except asyncio.CancelledError:
                    return
                closed = await self._session(reader, writer, framer,
                                             comp)
                if closed or self.policy.lossy:
                    await self._die()
                    return
        finally:
            self._drain_transports()

    async def _session(self, reader, writer, framer=None,
                       comp=None) -> bool:
        """Run one transport until it faults. Returns True when the
        peer closed gracefully (no replay should follow).  The AEAD
        framer is BOUND to this transport (derived from this
        handshake's nonces), so counters restart exactly when the
        peer's do."""
        self._writer = writer
        self._framer = framer
        if self.policy.resend:
            self._replay_unacked()
        rt = asyncio.ensure_future(
            self._read_frames(reader, framer, comp))
        wt = asyncio.ensure_future(
            self._write_frames(writer, framer, comp))
        try:
            done, pending = await asyncio.wait(
                {rt, wt}, return_when=asyncio.FIRST_COMPLETED)
        except asyncio.CancelledError:
            rt.cancel()
            wt.cancel()
            await asyncio.gather(rt, wt, return_exceptions=True)
            raise
        for t in (rt, wt):
            t.cancel()
        results = await asyncio.gather(rt, wt, return_exceptions=True)
        try:
            writer.close()
        except Exception:
            pass
        self._writer = None
        self._framer = None
        return any(isinstance(r, _PeerClosed) for r in results)

    async def _die(self) -> None:
        if not self._open:
            return
        self._open = False
        self.msgr._forget(self)
        await self.msgr._reset(self)

    # -- frame loops (subtasks of _session) ---------------------------------

    async def _write_frames(self, writer, framer=None,
                            comp=None) -> None:
        async def emit(tag: int, payload: bytes) -> None:
            if comp is not None and tag == TAG_MSG:
                # compress-then-encrypt; 1-byte flag says whether
                # this frame actually compressed (small or
                # incompressible payloads ride raw)
                if len(payload) >= 512:
                    blob = comp.compress(payload)
                    payload = (b"\x01" + blob
                               if len(blob) < len(payload)
                               else b"\x00" + payload)
                else:
                    payload = b"\x00" + payload
            if framer is not None:
                # the tag rides as AEAD associated data: relabeled
                # frames fail the MAC at the receiver
                payload = framer.seal(payload, bytes([tag]))
            await _write_frame(writer, tag, payload)

        held: list[tuple[int, bytes]] = []  # reordered frames
        while True:
            if held and self.out_q.empty():
                # nothing left to overtake the held frames: flush now
                # rather than strand them behind an idle queue
                try:
                    flush, held = held, []
                    for htag, hpayload in flush:
                        await emit(htag, hpayload)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    return
            item = await self.out_q.get()
            tag, payload = item[0], item[1]
            if len(item) > 2 and _ACCOUNTING:
                # queue wait: enqueue stamp -> pop (injected delays
                # and socket drain are wire time, not queue time)
                self.stats.note_queue_wait(time.monotonic() - item[2])
            try:
                act = None
                if tag == TAG_ACK:
                    inj = self.msgr.fault_injector
                    if inj is not None and not inj.on_control(
                            self.msgr.entity,
                            self.peer_entity or "?"):
                        # partitioned: the ACK is withheld (it would
                        # retire unacked lossless entries across the
                        # cut); it regenerates on the next delivered
                        # MSG after heal
                        continue
                if tag == TAG_MSG:
                    if (self.msgr.inject_socket_failures and
                            self.rng.randrange(
                                self.msgr.inject_socket_failures) == 0):
                        raise ConnectionError_(
                            "injected socket failure")
                    inj = self.msgr.fault_injector
                    if inj is not None:
                        act = inj.on_send(self.msgr.entity,
                                          self.peer_entity or "?")
                        if act.abort:
                            raise ConnectionError_("injected abort")
                        if act.drop or act.reorder:
                            if self.policy.resend:
                                # a lossless session may not silently
                                # lose or reorder a seq: withhold the
                                # frame and fault the transport — the
                                # reconnect replay redelivers it in
                                # order (ProtocolV2 semantics)
                                raise ConnectionError_(
                                    "injected drop (lossless: "
                                    "escalated to transport fault)")
                            if act.drop:
                                continue
                            held.append((tag, payload))
                            continue
                        if act.delay:
                            # head-of-line latency: later frames queue
                            # behind (a slow link, not a lost one)
                            await asyncio.sleep(act.delay)
                await emit(tag, payload)
                if act is not None and act.dup:
                    # re-seal: AEAD counters make byte-identical
                    # replays unverifiable, so a duplicate is a fresh
                    # frame carrying the same message (same seq — the
                    # receiver's dedup absorbs it)
                    await emit(tag, payload)
                if held:
                    flush, held = held, []
                    for htag, hpayload in flush:
                        await emit(htag, hpayload)
            except asyncio.CancelledError:
                raise
            except Exception:
                # resend policy: the popped payload is still in unacked
                # and will be replayed on the next transport
                return

    async def _read_frames(self, reader, framer=None,
                           comp=None) -> None:
        while True:
            try:
                tag, payload = await _read_frame(reader)
                if framer is not None:
                    # every tag is authenticated, TAG_CLOSE included:
                    # an unverifiable close is a transport fault (so
                    # lossless replay still runs), never an orderly
                    # shutdown an attacker could forge
                    payload = framer.open(payload, bytes([tag]))
                if comp is not None and tag == TAG_MSG:
                    flag, payload = payload[:1], payload[1:]
                    if flag == b"\x01":
                        payload = comp.decompress(payload)
            except asyncio.CancelledError:
                raise
            except Exception:
                return  # transport fault (incl. AEAD reject) -> ends
            if tag == TAG_MSG:
                inj = self.msgr.fault_injector
                if inj is not None and not inj.on_recv(
                        self.peer_entity or "?", self.msgr.entity):
                    # receive-side partition drop: a single injector
                    # enforces BOTH directions of a cut even when the
                    # peer has none installed
                    if self.policy.resend:
                        return      # transport fault: replay later
                    continue        # lossy: the frame vanishes
                msg = decode_message(payload)  # poison frame = fault
                # received payload size: the ingest bytes accounting
                # (mgr report telemetry) reads it off the message
                msg.wire_bytes = len(payload)
                if _ACCOUNTING:
                    self.stats.note_rx(msg.TYPE, len(payload))
                self.msgr.note_peer_clock(
                    msg.src, getattr(msg, "send_stamp", None))
                # dedup: a lossless session replays after reconnect,
                # so anything at-or-below in_seq is a replay dup.  A
                # lossy transport has no replay — its only duplicate
                # source is injected back-to-back dup frames, and a
                # window-based check would misread injected
                # REORDERING as duplication and silently drop frames
                dup = (msg.seq <= self.in_seq if self.policy.resend
                       else msg.seq == self.in_seq)
                if dup and self.policy.resend and _ACCOUNTING:
                    # a session-replay duplicate absorbed by seq
                    self.stats.replays += 1
                self.in_seq = max(self.in_seq, msg.seq)
                if self.policy.resend:
                    # ack duplicates too: the original ack may have
                    # been lost with the previous transport
                    self.out_q.put_nowait(
                        (TAG_ACK, struct.pack(">Q", self.in_seq)))
                if not dup:
                    if isinstance(msg, UnknownMessage):
                        continue  # acked + dropped (registry skew)
                    try:
                        await self.msgr._dispatch(self, msg)
                    except asyncio.CancelledError:
                        raise
                    except Exception:
                        # dispatcher bug: drop the transport so the
                        # fault is visible, but never silently
                        import traceback

                        traceback.print_exc()
                        return
            elif tag == TAG_ACK:
                inj = self.msgr.fault_injector
                if inj is not None and not inj.on_control(
                        self.peer_entity or "?", self.msgr.entity):
                    return      # partitioned: transport fault
                (seq,) = struct.unpack(">Q", payload)
                self.unacked = [(s, d) for s, d in self.unacked
                                if s > seq]
            elif tag == TAG_CLOSE:
                inj = self.msgr.fault_injector
                if inj is not None and not inj.on_control(
                        self.peer_entity or "?", self.msgr.entity):
                    # a CLOSE crossing a partition must read as a
                    # transport fault, not an orderly shutdown —
                    # lossless sessions keep their replay state
                    return
                raise _PeerClosed()

    def _replay_unacked(self) -> None:
        """Requeue unacked payloads ahead of pending traffic so the new
        transport replays them in seq order (receiver dedupes by seq)."""
        pending = []
        while not self.out_q.empty():
            item = self.out_q.get_nowait()
            if item[0] == TAG_MSG:
                pending.append(item)
        replay = {d: None for _, d in self.unacked}
        if replay and _ACCOUNTING:
            self.stats.resends += len(replay)
        for d in replay:
            if _ACCOUNTING:
                self.out_q.put_nowait((TAG_MSG, d, time.monotonic()))
            else:
                self.out_q.put_nowait((TAG_MSG, d))
        for item in pending:
            if item[1] not in replay:
                self.out_q.put_nowait(item)


class Messenger:
    """Endpoint owning connections + the dispatch path."""

    def __init__(self, entity: str, nonce: int = 0, auth=None,
                 compress: list[str] | None = None,
                 seed: int | None = None):
        self.entity = entity
        self.auth = auth            # AuthContext or None (DummyAuth)
        # on-wire compression preferences (msgr2 compression_onwire
        # role): advertised in the ident, the ACCEPTOR's order picks
        # the common algorithm; empty/None disables
        self.compress_algos = list(compress or [])
        # seeded mode: every RNG this messenger owns (nonce,
        # per-connection failure schedules) derives deterministically
        # from (seed, entity), so a fault run replays exactly
        self.seed = seed
        self.rng = (random.Random("%s|%s" % (seed, entity))
                    if seed is not None else random.Random())
        # the nonce identifies this messenger *instance*: a restarted
        # daemon must present a different one so peers reset sessions
        self.nonce = nonce if nonce else self.rng.getrandbits(63)
        self.addr: str | None = None
        self.dispatchers: list = []
        self.inject_socket_failures = 0
        # optional FaultInjector (msg.faults): per-peer-pair frame
        # drop/delay/dup/reorder rules + bidirectional partitions
        self.fault_injector = None
        self._server: asyncio.AbstractServer | None = None
        self._conns: dict[str, Connection] = {}     # by dial addr
        self._inbound: list[Connection] = []
        # strong refs: the event loop only weakly references tasks, so
        # fire-and-forget tasks would be GC'd mid-await
        self._tasks: set = set()
        # every accepted transport, so shutdown can force-close ones
        # still mid-handshake (weak: sessions own live writers)
        import weakref

        self._in_writers: weakref.WeakSet = weakref.WeakSet()
        self._shutting_down = False
        self.default_policy = Policy.lossy_client()
        self.peer_policy: dict[str, Policy] = {}    # by entity type
        # clock-offset estimation (the cephadm time-sync / OSD
        # heartbeat skew-check role, minimally): every received frame
        # carries the sender's monotonic send stamp; `stamp - now()`
        # underestimates (peer_clock - my_clock) by the network
        # latency, so new maxima are adopted immediately — but a pure
        # max never decays, so a peer whose clock DRIFTS back down
        # would stay pinned at its stale high-water mark.  Lower
        # estimates therefore blend in with an EWMA: fresh frames
        # pull the estimate down at CLOCK_DECAY per frame, bounded
        # below only by the (sub-ms on loopback) latency noise floor.
        # `clock_skew` shifts THIS daemon's advertised clock (test
        # hook for injected skew/drift).
        self.clock_skew = 0.0
        self.clock_offsets: dict[str, float] = {}   # peer entity -> s
        # per-peer wire accounting folded from dead connections (live
        # connections keep their own WireStats; net_dump merges both)
        self.net_folded: dict[str, WireStats] = {}
        # optional crash capture: when set, an exception escaping a
        # spawned task is handed here (the daemon writes a crash
        # report) instead of dying unobserved as an "exception was
        # never retrieved" warning at GC time
        self.crash_hook = None

    # per-frame EWMA weight for downward (drift) corrections; upward
    # corrections apply immediately (strictly better information)
    CLOCK_DECAY = 0.2

    def now(self) -> float:
        """This daemon's (possibly skewed) monotonic clock."""
        return time.monotonic() + self.clock_skew

    def note_peer_clock(self, src: str, stamp) -> None:
        if stamp is None or not src or src == self.entity:
            return
        est = float(stamp) - self.now()
        cur = self.clock_offsets.get(src)
        if cur is None or est > cur:
            self.clock_offsets[src] = est
        else:
            self.clock_offsets[src] = \
                cur + self.CLOCK_DECAY * (est - cur)

    # -- lifecycle ---------------------------------------------------------

    def _conn_rng(self, peer_key: str) -> random.Random:
        """A connection's RNG: deterministic per (seed, entity, peer)
        in seeded mode so each peer pair has an independent,
        replayable schedule; independent entropy otherwise."""
        if self.seed is not None:
            return random.Random("%s|%s|%s" % (self.seed, self.entity,
                                               peer_key))
        return random.Random(self.rng.getrandbits(64))

    def spawn(self, coro) -> asyncio.Task:
        """ensure_future with a strong reference held until done."""
        task = asyncio.ensure_future(coro)
        self._tasks.add(task)

        def _done(t: asyncio.Task) -> None:
            self._tasks.discard(t)
            if self.crash_hook is None or t.cancelled():
                return      # no hook: keep asyncio's GC-time warning
            exc = t.exception()
            if exc is not None:
                try:
                    self.crash_hook(exc)
                except Exception:
                    pass    # the crash path must never crash

        task.add_done_callback(_done)
        return task

    async def bind(self, host: str = "127.0.0.1", port: int = 0) -> str:
        self._server = await asyncio.start_server(
            self._accept, host=host, port=port)
        sock = self._server.sockets[0]
        self.addr = "%s:%d" % sock.getsockname()[:2]
        return self.addr

    async def shutdown(self) -> None:
        self._shutting_down = True
        if self._server is not None:
            self._server.close()
        # accept handlers may still complete concurrently and (before
        # _shutting_down was set) spawn supervisors: cancel in passes
        # until the task set drains
        for _pass in range(10):
            for conn in (list(self._conns.values())
                         + list(self._inbound)):
                conn.mark_down()
            for t in list(self._tasks):
                t.cancel()
            if not self._tasks:
                break
            await asyncio.gather(*list(self._tasks),
                                 return_exceptions=True)
        for w in list(self._in_writers):
            try:
                w.close()
            except Exception:
                pass
        if self._server is not None:
            # py3.12 wait_closed() waits for every accepted connection;
            # _accept closes on all refusal paths so this terminates —
            # the bound is a backstop so a leak can never hang a daemon
            try:
                await asyncio.wait_for(self._server.wait_closed(), 5.0)
            except asyncio.TimeoutError:
                pass

    def add_dispatcher(self, d) -> None:
        self.dispatchers.append(d)

    # -- policies ----------------------------------------------------------

    def policy_for(self, entity: str) -> Policy:
        etype = entity.split(".", 1)[0]
        return self.peer_policy.get(etype, self.default_policy)

    # -- outbound ----------------------------------------------------------

    def connect_to(self, addr: str, entity_hint: str = "") -> Connection:
        """Get (or create) the connection to addr. The TCP dial happens
        lazily in the supervisor; sends queue meanwhile."""
        conn = self._conns.get(addr)
        if conn is not None and conn.is_open:
            return conn
        policy = self.policy_for(entity_hint) if entity_hint \
            else self.default_policy
        conn = Connection(self, addr, policy)
        self._conns[addr] = conn
        conn._start()
        return conn

    def send_to(self, addr: str, msg: Message,
                entity_hint: str = "") -> None:
        self.connect_to(addr, entity_hint).send(msg)

    async def _handshake_out(self, conn, reader, writer) -> None:
        from ..utils import denc

        writer.write(BANNER)
        # "ack" mirrors ProtocolV2's reconnect msg_seq exchange
        # (ProtocolV2.cc ReconnectFrame): each side tells the other how
        # much it already received, so replay covers only the gap
        ident = denc.encode({"entity": self.entity, "nonce": self.nonce,
                             "addr": self.addr or "",
                             "ack": conn.in_seq,
                             "comp": self.compress_algos})
        writer.write(struct.pack(">I", len(ident)) + ident)
        await writer.drain()
        banner = await reader.readexactly(len(BANNER))
        if banner != BANNER:
            raise ConnectionError_("bad banner %r" % banner)
        (n,) = struct.unpack(">I", await reader.readexactly(4))
        peer_blob = await reader.readexactly(n)
        peer = denc.decode(peer_blob)
        if self.fault_injector is not None and \
                self.fault_injector.partitioned(
                    self.entity, peer.get("entity", "?")):
            # partitioned peers cannot complete a handshake: redials
            # during a cut fail like an unreachable host would
            raise ConnectionError_("partitioned from %s"
                                   % peer.get("entity"))
        # acceptor's preference order picks the wire compressor
        comp = _pick_compressor(peer.get("comp") or [],
                                self.compress_algos)
        # the idents are unauthenticated at this point: they travel as
        # transcript bind material in the key proofs, and NO session
        # state (nonce, in_seq, unacked purge) moves until the peer has
        # proven the cluster key — a forged ident must not be able to
        # drop queued lossless messages (mirror of the acceptor's
        # READ-ONLY session peek)
        framer = await self._auth_out(reader, writer,
                                      bind=ident + peer_blob)
        conn.peer_entity = peer["entity"]
        nonce = peer.get("nonce", 0)
        if conn.peer_nonce >= 0 and conn.peer_nonce != nonce:
            # peer restarted: its seq numbering starts over
            conn.in_seq = 0
        conn.peer_nonce = nonce
        ack = peer.get("ack", 0)
        conn.unacked = [(s, d) for s, d in conn.unacked if s > ack]
        return framer, comp

    @staticmethod
    async def _read_auth_blob(reader, cap: int = 4096,
                              timeout: float = 5.0) -> bytes:
        """Pre-auth reads are fully bounded (time AND size): this is
        attacker-reachable surface."""
        (n,) = struct.unpack(">I", await asyncio.wait_for(
            reader.readexactly(4), timeout))
        if n > cap:
            raise ConnectionError_("auth blob too large (%d)" % n)
        return await asyncio.wait_for(reader.readexactly(n), timeout)

    async def _auth_out(self, reader, writer, bind: bytes = b""):
        """Initiator side of the cluster-auth exchange (the cephx
        authorizer round): mutual HMAC challenge-response over the
        shared key, with the pre-auth ident transcript mixed into the
        proofs (``bind``) so ident tampering fails auth.  Returns the
        transport's AEAD framer (secure mode) or None."""
        if self.auth is None:
            return None
        from ..utils import denc
        from .auth import SecureFramer
        ncb, hello = self.auth.client_hello()
        blob = denc.encode(hello)
        writer.write(struct.pack(">I", len(blob)) + blob)
        await writer.drain()
        challenge = denc.decode(await self._read_auth_blob(reader))
        nsb, reply = self.auth.client_verify(ncb, challenge, bind)
        blob = denc.encode(reply)
        writer.write(struct.pack(">I", len(blob)) + blob)
        await writer.drain()
        if self.auth.secure:
            return SecureFramer(self.auth.session_key(ncb, nsb),
                                initiator=True)
        return None

    # -- inbound -----------------------------------------------------------

    async def _accept(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        """Inbound handler.  EVERY exit path must either hand the
        transport to a Connection or close the writer: an abandoned
        open socket makes Server.wait_closed() (which waits on all
        accepted connections in py3.12) hang shutdown forever."""
        handed_off = False
        self._in_writers.add(writer)
        try:
            handed_off = await self._accept_inner(reader, writer)
        finally:
            if not handed_off:
                # single close point: any refusal/exception path that
                # did not hand the transport to a Connection closes it
                # (an abandoned socket wedges Server.wait_closed)
                try:
                    writer.close()
                except Exception:
                    pass

    async def _accept_inner(self, reader, writer) -> bool:
        """Returns True only when the transport was handed off to a
        Connection; every other outcome is a refusal and _accept
        closes the writer."""
        from ..utils import denc

        t0 = time.monotonic()
        try:
            # pre-auth reads are time-bounded: an idle dialer must not
            # pin an accept handler (and thus shutdown) indefinitely
            banner = await asyncio.wait_for(
                reader.readexactly(len(BANNER)), 10.0)
            if banner != BANNER:
                return False
            peer_blob = await self._read_auth_blob(reader,
                                                   timeout=10.0)
            peer = denc.decode(peer_blob)
            entity = peer["entity"]
        except (ConnectionError, OSError, asyncio.IncompleteReadError,
                asyncio.TimeoutError, ValueError, KeyError,
                struct.error, RecursionError, ConnectionError_):
            return False
        if self.fault_injector is not None and \
                self.fault_injector.partitioned(self.entity, entity):
            return False    # partitioned: refuse like a dead host
        nonce = peer.get("nonce", 0)
        policy = self.policy_for(entity)
        # READ-ONLY session peek: the ident reply advertises the
        # session's in_seq, but NO session state may change before the
        # peer proves the cluster key (an unauthenticated ident could
        # otherwise tear down live sessions or purge replay queues)
        existing = None
        if not policy.lossy:
            for c in list(self._inbound):
                if c.peer_entity == entity and c.is_open:
                    existing = c
                    break
        ack_out = (existing.in_seq
                   if existing is not None
                   and existing.peer_nonce == nonce else 0)
        try:
            writer.write(BANNER)
            ident = denc.encode({"entity": self.entity,
                                 "nonce": self.nonce,
                                 "addr": self.addr or "",
                                 "ack": ack_out,
                                 "comp": self.compress_algos})
            writer.write(struct.pack(">I", len(ident)) + ident)
            await writer.drain()
        except (ConnectionError, OSError):
            return False
        comp = _pick_compressor(self.compress_algos,
                                peer.get("comp") or [])
        ok, framer = await self._auth_in(reader, writer,
                                         bind=peer_blob + ident)
        if not ok:
            return False    # unauthenticated peer: refused
        if self._shutting_down:
            # a handshake completing after shutdown()'s task snapshot
            # must not spawn a supervisor nobody will ever cancel
            return False
        # authenticated: now apply session-reuse semantics
        # (ProtocolV2 reconnect/reset_session)
        conn = None
        if not policy.lossy and existing is not None \
                and existing.is_open:
            if existing.peer_nonce == nonce:
                conn = existing
            else:
                existing.mark_down()
                await self._reset(existing)
        if conn is None:
            conn = Connection(self, None, policy)
            conn.peer_entity = entity
            conn.peer_nonce = nonce
            self._inbound.append(conn)
            conn._start()
        conn.unacked = [(s, d) for s, d in conn.unacked
                        if s > peer.get("ack", 0)]
        if not conn.is_open:
            return False    # raced mark_down: nobody will run this
        conn.stats.note_handshake(time.monotonic() - t0)
        conn._transports.put_nowait((reader, writer, framer, comp))
        return True

    async def _auth_in(self, reader, writer, bind: bytes = b""):
        """Acceptor side: refuse any peer that cannot prove the key
        (AuthRegistry's cephx_cluster_required gate).  Returns
        (authenticated, framer)."""
        if self.auth is None:
            return True, None
        from ..utils import denc
        from .auth import AuthError, SecureFramer
        try:
            hello = denc.decode(await self._read_auth_blob(reader))
            ncb, nsb, challenge = self.auth.server_challenge(
                hello, bind)
            blob = denc.encode(challenge)
            writer.write(struct.pack(">I", len(blob)) + blob)
            await writer.drain()
            self.auth.server_verify(ncb, nsb, denc.decode(
                await self._read_auth_blob(reader)), bind)
        except (AuthError, asyncio.TimeoutError, ConnectionError,
                ConnectionError_, OSError,
                asyncio.IncompleteReadError, ValueError, KeyError,
                struct.error, RecursionError):
            try:
                writer.close()
            except Exception:
                pass
            return False, None
        if self.auth.secure:
            return True, SecureFramer(
                self.auth.session_key(ncb, nsb), initiator=False)
        return True, None

    # -- dispatch ----------------------------------------------------------

    async def _dispatch(self, conn: Connection, msg: Message) -> None:
        try:
            for d in self.dispatchers:
                handler = getattr(d, "ms_dispatch", None)
                if handler is None:
                    continue
                res = handler(conn, msg)
                if asyncio.iscoroutine(res):
                    res = await res
                if res:
                    return
        except Exception as exc:
            # the SYNCHRONOUS dispatch path: an unhandled handler
            # exception here never reaches spawn()'s done callback, so
            # without this hook call it would drop the transport with
            # no post-mortem artifact (spawned-task exceptions already
            # route through the same hook)
            if self.crash_hook is not None:
                try:
                    self.crash_hook(exc)
                except Exception:
                    pass
            raise

    async def _reset(self, conn: Connection) -> None:
        for d in self.dispatchers:
            handler = getattr(d, "ms_handle_reset", None)
            if handler is not None:
                res = handler(conn)
                if asyncio.iscoroutine(res):
                    await res

    def _forget(self, conn: Connection) -> None:
        # fold the dying connection's wire accounting into the
        # per-peer aggregate (counters survive connection churn); the
        # stats block is replaced so a second _forget cannot
        # double-count
        key = conn.peer_entity or conn.peer_addr or "?"
        agg = self.net_folded.get(key)
        if agg is None:
            agg = self.net_folded[key] = WireStats()
        agg.fold(conn.stats)
        conn.stats = WireStats()
        if conn.peer_addr is not None:
            if self._conns.get(conn.peer_addr) is conn:
                del self._conns[conn.peer_addr]
        elif conn in self._inbound:
            self._inbound.remove(conn)

    # -- wire telemetry ------------------------------------------------------

    def net_dump(self, cap: int | None = None) -> dict:
        """Per-peer wire telemetry: folded dead-connection aggregates
        merged with live connections.  Keys per peer are the
        NET_STAGES-registered WireStats dump fields plus the live
        send-queue depth.  With ``cap``, only the busiest ``cap - 1``
        peers (by tx bytes) keep their own row and the tail folds
        into ``"other"`` — the tenant-label cardinality rule applied
        to peers (many short-lived clients must not grow the report
        without bound)."""
        merged: dict[str, WireStats] = {}
        for key, st in self.net_folded.items():
            agg = merged.setdefault(key, WireStats())
            agg.fold(st)
        depth: dict[str, int] = {}
        for conn in list(self._conns.values()) + list(self._inbound):
            key = conn.peer_entity or conn.peer_addr or "?"
            agg = merged.setdefault(key, WireStats())
            agg.fold(conn.stats)
            depth[key] = depth.get(key, 0) + conn.out_q.qsize()
        if cap is not None and len(merged) > cap:
            keep = sorted(merged, key=lambda k:
                          (-merged[k].tx_bytes, k))[:max(cap - 1, 1)]
            other = WireStats()
            other_depth = 0
            for key in list(merged):
                if key not in keep:
                    other.fold(merged.pop(key))
                    other_depth += depth.pop(key, 0)
            merged["other"] = other
            depth["other"] = other_depth
        return {key: st.dump(queue_depth=depth.get(key, 0))
                for key, st in sorted(merged.items())}

    def prune_peer_state(self, live, prefix: str = "osd.") -> None:
        """Drop dead peers' clock-offset and folded-wire entries.
        Both tables are keyed by peer entity and otherwise grow
        forever across thrash kill/revive cycles (every revived
        daemon dials back from a fresh nonce).  Only entities under
        ``prefix`` are considered — client/mon entries are someone
        else's liveness to judge."""
        live = set(live)
        for table in (self.clock_offsets, self.net_folded):
            for key in list(table):
                if key.startswith(prefix) and key not in live:
                    del table[key]
