"""Cluster authentication + secure wire mode.

Analog of src/auth/ (cephx) + ProtocolV2's secure mode
(src/msg/async/ProtocolV2.cc auth frames, crypto_onwire.cc AES-GCM):

* a shared cluster secret (the keyring role) gates every connection:
  after the banner/ident exchange both sides run a mutual
  challenge-response proving possession of the key (HMAC-SHA256 over
  fresh nonces — the role cephx's ticket/authorizer exchange plays;
  the mon-issued-ticket indirection is collapsed onto the shared key,
  like a cluster where every daemon holds the same keyring);
* a per-connection session key is derived from the key + both nonces
  (CephXTicketHandler session_key role), never reused across
  transports;
* optional secure mode encrypts every frame payload with an
  encrypt-then-MAC AEAD built from keyed BLAKE2b (keystream = keyed
  hash of a per-direction counter; MAC over header data + ciphertext).
  The reference uses AES-GCM; this image has no AES primitive, so the
  AEAD is an HMAC-style PRF construction with the same interface and
  guarantees (confidentiality + integrity + per-frame nonces), which
  is the honest equivalent rather than a hand-rolled block cipher.

`AuthContext.from_conf` reads:
    auth_cluster_required = "none" | "shared"   (cephx on/off)
    auth_key              = hex/utf8 shared secret
    ms_secure_mode        = 0 (crc) | 1 (encrypted frames)
A "none" context disables everything (DummyAuth).
"""

from __future__ import annotations

import hashlib
import hmac
import os


class AuthError(Exception):
    pass


def _hmac(key: bytes, *parts: bytes) -> bytes:
    h = hmac.new(key, digestmod=hashlib.sha256)
    for p in parts:
        h.update(len(p).to_bytes(4, "big"))
        h.update(p)
    return h.digest()


class AuthContext:
    """Immutable auth configuration shared by a daemon's messenger."""

    __slots__ = ("mode", "key", "secure")

    def __init__(self, mode: str = "none", key: bytes = b"",
                 secure: bool = False):
        self.mode = mode
        self.key = key
        self.secure = secure and mode != "none"

    @classmethod
    def from_conf(cls, conf) -> "AuthContext | None":
        try:
            mode = conf["auth_cluster_required"]
            key = conf["auth_key"]
            secure = bool(conf["ms_secure_mode"])
        except Exception:
            return None
        if mode == "none" or not key:
            return None
        return cls(mode, key.encode(), secure)

    # -- handshake ---------------------------------------------------------

    def client_hello(self) -> tuple[bytes, dict]:
        nc = os.urandom(16)
        return nc, {"nonce": nc.hex()}

    def server_challenge(self, hello: dict, bind: bytes = b"") \
            -> tuple[bytes, bytes, dict]:
        """``bind`` is transcript material (the pre-auth ident blobs):
        mixing it into the proofs makes the unauthenticated part of the
        handshake tamper-evident — a MITM that rewrites an ident (e.g.
        to forge a session ack) breaks both proofs even though it
        relays the auth frames untouched."""
        nc = bytes.fromhex(hello["nonce"])
        ns = os.urandom(16)
        proof = _hmac(self.key, b"srv", nc, ns, bind)
        return nc, ns, {"nonce": ns.hex(), "proof": proof.hex()}

    def client_verify(self, nc: bytes, reply: dict,
                      bind: bytes = b"") -> tuple[bytes, dict]:
        ns = bytes.fromhex(reply["nonce"])
        want = _hmac(self.key, b"srv", nc, ns, bind)
        if not hmac.compare_digest(want,
                                   bytes.fromhex(reply["proof"])):
            raise AuthError("server failed key proof")
        proof = _hmac(self.key, b"cli", nc, ns, bind)
        return ns, {"proof": proof.hex()}

    def server_verify(self, nc: bytes, ns: bytes, reply: dict,
                      bind: bytes = b"") -> None:
        want = _hmac(self.key, b"cli", nc, ns, bind)
        if not hmac.compare_digest(want,
                                   bytes.fromhex(reply["proof"])):
            raise AuthError("client failed key proof")

    def session_key(self, nc: bytes, ns: bytes) -> bytes:
        return _hmac(self.key, b"session", nc, ns)


_BLOCK = 64          # blake2b digest size = keystream block


def _xor(a: bytes, b: bytes) -> bytes:
    # bigint XOR: C-speed for multi-MB frames (a bytewise generator is
    # ~100x slower)
    n = len(a)
    return (int.from_bytes(a, "little")
            ^ int.from_bytes(b, "little")).to_bytes(n, "little") \
        if n else b""


class SecureFramer:
    """Per-connection AEAD (crypto_onwire.cc role).

    Directional: the connection initiator seals with the "a" label and
    opens with "b"; the acceptor mirrors.  Each direction keeps its own
    frame counter (the AEAD nonce), so reordering/replay within a
    transport fails the MAC; a reconnect re-derives fresh session keys
    so counters never repeat under one key.
    """

    __slots__ = ("_tx", "_rx", "_txn", "_rxn")

    def __init__(self, session_key: bytes, initiator: bool):
        a = _hmac(session_key, b"dir-a")
        b = _hmac(session_key, b"dir-b")
        self._tx, self._rx = (a, b) if initiator else (b, a)
        self._txn = 0
        self._rxn = 0

    @staticmethod
    def _stream(key: bytes, nonce: int, n: int) -> bytes:
        out = bytearray()
        ctr = 0
        base = nonce.to_bytes(8, "big")
        while len(out) < n:
            out += hashlib.blake2b(
                base + ctr.to_bytes(8, "big"), key=key,
                digest_size=_BLOCK).digest()
            ctr += 1
        return bytes(out[:n])

    def seal(self, payload: bytes, aad: bytes = b"") -> bytes:
        """``aad`` is authenticated-but-unencrypted associated data —
        the messenger passes the frame tag so an on-path attacker
        cannot relabel a frame (e.g. flip it to TAG_CLOSE to fake a
        graceful shutdown) without failing the MAC."""
        n = self._txn
        self._txn += 1
        ks = self._stream(self._tx, n, len(payload))
        ct = _xor(payload, ks)
        mac = hashlib.blake2b(
            n.to_bytes(8, "big")
            + len(aad).to_bytes(4, "big") + aad + ct,
            key=self._tx, digest_size=16).digest()
        return ct + mac

    def open(self, blob: bytes, aad: bytes = b"") -> bytes:
        if len(blob) < 16:
            raise AuthError("short secure frame")
        n = self._rxn
        self._rxn += 1
        ct, mac = blob[:-16], blob[-16:]
        want = hashlib.blake2b(
            n.to_bytes(8, "big")
            + len(aad).to_bytes(4, "big") + aad + ct,
            key=self._rx, digest_size=16).digest()
        if not hmac.compare_digest(mac, want):
            raise AuthError("secure frame MAC mismatch")
        ks = self._stream(self._rx, n, len(ct))
        return _xor(ct, ks)
