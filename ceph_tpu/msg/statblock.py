"""Packed columnar stat-row blocks (the telemetry fabric's wire unit).

The MPGStats slice of an MMgrReport used to ride as a list of python
dicts — one dict per PG — which forces the mgr to walk the rows one
`for st in pg_stats` iteration at a time.  At 500k-1M PGs the report
*ingest* becomes the control plane's hot path (the fold went columnar
in the scale plane; ingest was the remaining row-at-a-time loop), so
the rows now ship as ONE packed block of parallel typed arrays:

* ``pg_pool`` / ``pg_seed`` — the pgid split into its integer parts
  (every producer mints pgids as ``"%d.%x" % (pool, ps)``), so the
  mgr's merge is a ``searchsorted`` over int64 keys instead of a dict
  probe per string;
* ``ints`` — the per-PG int64 stat columns in ``STAT_INT_COLS`` order
  (object/byte counts, degraded/misplaced/unfound, log size, scrub
  errors);
* ``ctrs`` — the cumulative rate-counter columns in ``STAT_CTR_COLS``
  order (client IO + recovery), int64;
* ``floats`` — float64 columns (``STAT_FLOAT_COLS``: the scrub
  stamps);
* ``state`` — uint16 codes into ``state_names``, the per-report
  dictionary encoding of the PG state strings.

Every array serializes as raw little-endian bytes (explicit ``<``
dtypes, so the packed encoding is byte-stable across hosts — pinned
by the golden test), and the whole block is a plain denc-encodable
dict riding MMgrReport's ``pg_stats_cols`` field.  ``block_cols``
reopens the arrays zero-copy on the mgr side; ``unpack_stat_rows``
restores dict rows for legacy consumers and the fallback path.

Versioning: ``v`` bumps only if the column layout itself changes;
receivers reject unknown versions (the sender then has no columnar
peer and the mgr's legacy dict-row path still applies the report).
"""

from __future__ import annotations

import numpy as np

STATBLOCK_V = 1

# int64 stat columns, in wire order (mirrors mgr.pgmap._INT_COLS)
STAT_INT_COLS = ("pool", "num_objects", "num_bytes", "degraded",
                 "misplaced", "unfound", "log_size", "scrub_errors")

# cumulative counters the mgr derives rates from (mirrors
# mgr.pgmap.RATE_COUNTERS — pgmap asserts the two stay identical)
STAT_CTR_COLS = ("read_ops", "read_bytes", "write_ops", "write_bytes",
                 "recovery_ops", "recovery_bytes")

# float64 columns (scrub stamps ride the row but are not folded)
STAT_FLOAT_COLS = ("last_scrub_stamp", "last_deep_scrub_stamp")

# pg_seed must fit the low 32 bits of the merge key (pool rides the
# high bits); pg_num tops out far below this
_SEED_MAX = (1 << 32) - 1

# pool must fit the high 31 bits so ``pool << 32 | seed`` stays inside
# a signed int64 (negative keys are the mgr's synthetic string-key
# space); out-of-range pgids keep the legacy dict-row path
_POOL_MAX = (1 << 31) - 1


def _i64(vals) -> bytes:
    return np.asarray(vals, dtype="<i8").tobytes()


def pack_stat_rows(rows: list[dict]) -> dict:
    """Dict-shaped stat rows -> one packed columnar block (the
    producer side: OSDs and shell fleets call this once per report).
    Raises ValueError on a row whose pgid is not the canonical
    ``pool.seed-hex`` shape — producers always mint that shape; a
    caller with odd pgids keeps the legacy dict-row field."""
    n = len(rows)
    pg_pool = np.empty(n, "<i8")
    pg_seed = np.empty(n, "<i8")
    states: list[int] = []
    state_names: list[str] = []
    state_codes: dict[str, int] = {}
    for i, st in enumerate(rows):
        pool_s, dot, seed_s = str(st["pgid"]).partition(".")
        if not dot:
            raise ValueError("non-canonical pgid %r" % st["pgid"])
        pool = int(pool_s)
        seed = int(seed_s, 16)
        if not (0 <= pool <= _POOL_MAX and 0 <= seed <= _SEED_MAX):
            raise ValueError("pgid %r out of key range" % st["pgid"])
        pg_pool[i] = pool
        pg_seed[i] = seed
        s = st.get("state", "unknown")
        code = state_codes.get(s)
        if code is None:
            code = len(state_names)
            state_codes[s] = code
            state_names.append(s)
        states.append(code)
    if len(state_names) > 0xFFFF:
        raise ValueError("too many distinct states")
    # field order is the wire order — deterministic, golden-pinned
    return {
        "v": STATBLOCK_V,
        "n": n,
        "pg_pool": pg_pool.tobytes(),
        "pg_seed": pg_seed.tobytes(),
        "ints": [_i64([int(st.get(c, 0)) for st in rows])
                 for c in STAT_INT_COLS],
        "ctrs": [_i64([int(st.get(c, 0)) for st in rows])
                 for c in STAT_CTR_COLS],
        "floats": [np.asarray([float(st.get(c, 0.0)) for st in rows],
                              "<f8").tobytes()
                   for c in STAT_FLOAT_COLS],
        "state_names": state_names,
        "state": np.asarray(states, "<u2").tobytes(),
    }


def _col(raw: bytes, n: int, dtype: str) -> np.ndarray:
    arr = np.frombuffer(raw, dtype=dtype)
    if arr.size != n:
        raise ValueError("column carries %d values for %d rows"
                         % (arr.size, n))
    return arr


def block_cols(block: dict) -> dict:
    """Validate a wire block and reopen its arrays zero-copy (the mgr
    fast path's input).  Raises ValueError on version skew or any
    length/layout mismatch — the caller then falls back to the
    row-wise path via ``unpack_stat_rows``."""
    if block.get("v") != STATBLOCK_V:
        raise ValueError("unknown statblock version %r"
                         % block.get("v"))
    n = int(block["n"])
    ints = block["ints"]
    ctrs = block["ctrs"]
    floats = block["floats"]
    if (len(ints) != len(STAT_INT_COLS)
            or len(ctrs) != len(STAT_CTR_COLS)
            or len(floats) != len(STAT_FLOAT_COLS)):
        raise ValueError("column-count mismatch")
    names = [str(s) for s in (block.get("state_names") or [])]
    state = _col(block["state"], n, "<u2")
    if n and (not names or int(state.max()) >= len(names)):
        raise ValueError("state code outside the dictionary")
    return {
        "n": n,
        "pg_pool": _col(block["pg_pool"], n, "<i8"),
        "pg_seed": _col(block["pg_seed"], n, "<i8"),
        "ints": [_col(raw, n, "<i8") for raw in ints],
        "ctrs": [_col(raw, n, "<i8") for raw in ctrs],
        "floats": [_col(raw, n, "<f8") for raw in floats],
        "state_names": names,
        "state": state,
    }


def unpack_stat_rows(block: dict) -> list[dict]:
    """Packed block -> dict-shaped rows (legacy consumers, the mgr's
    malformed-block fallback, and the golden tests' normal form)."""
    cols = block_cols(block)
    n = cols["n"]
    names = cols["state_names"]
    rows: list[dict] = []
    for i in range(n):
        row = {
            "pgid": "%d.%x" % (cols["pg_pool"][i], cols["pg_seed"][i]),
            "state": names[cols["state"][i]] if names else "unknown",
        }
        for c, arr in zip(STAT_INT_COLS, cols["ints"]):
            row[c] = int(arr[i])
        for c, arr in zip(STAT_CTR_COLS, cols["ctrs"]):
            row[c] = int(arr[i])
        for c, arr in zip(STAT_FLOAT_COLS, cols["floats"]):
            row[c] = float(arr[i])
        rows.append(row)
    return rows


def block_nbytes(block: dict) -> int:
    """Approximate wire size of a packed block (the ingest bytes
    accounting): the raw column payloads plus the small framing."""
    total = 16
    for key in ("pg_pool", "pg_seed", "state"):
        total += len(block.get(key) or b"")
    for key in ("ints", "ctrs", "floats"):
        total += sum(len(raw) for raw in (block.get(key) or ()))
    total += sum(len(s) + 5 for s in (block.get("state_names") or ()))
    return total
