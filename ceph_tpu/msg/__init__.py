"""L3 communication: asyncio messenger with v2-style framing.

Analog of src/msg/ (Messenger/Connection/Dispatcher/Policy) — see
messenger.py for the transport and messages.py for the wire types.
"""

from .faults import FaultInjector, FaultRule
from .message import Message, decode_message, encode_message, register
from .messenger import Connection, Messenger, Policy

# importing .messages populates the wire registry as a side effect so
# any Messenger user can decode inbound frames
from . import messages  # noqa: F401  (registry side effect)

__all__ = [
    "Message", "register", "encode_message", "decode_message",
    "Messenger", "Connection", "Policy",
    "FaultInjector", "FaultRule",
]
