"""Message base class + wire codec.

The framework's analog of the reference's Message hierarchy
(src/msg/Message.h): every message is a typed record with a small,
declarative field list, encoded with the deterministic denc TLV format
into the payload segment of a v2-style frame (src/msg/async/frames_v2.h
puts header/payload segments inside a CRC-checked envelope; here the
envelope lives in ceph_tpu.msg.messenger).

A registry keyed by the wire TYPE string replaces the reference's
numeric message-type switch in decode_message (src/msg/Message.cc:256).
"""

from __future__ import annotations

from ..utils import denc

_REGISTRY: dict[str, type["Message"]] = {}


def register(cls: type["Message"]) -> type["Message"]:
    """Class decorator: adds the message type to the wire registry."""
    if not cls.TYPE:
        raise ValueError("message class %s has no TYPE" % cls.__name__)
    if cls.TYPE in _REGISTRY:
        raise ValueError("duplicate message TYPE %r" % cls.TYPE)
    _REGISTRY[cls.TYPE] = cls
    return cls


class Message:
    """Base message: subclasses declare TYPE and FIELDS.

    Fields must be denc-encodable values; messages carrying richer
    structures (pg_t, OSDMap) convert in to_wire/from_wire overrides.
    """

    TYPE = ""
    FIELDS: tuple[str, ...] = ()

    def __init__(self, **kw):
        for f in self.FIELDS:
            setattr(self, f, kw.pop(f, None))
        if kw:
            raise TypeError("%s: unknown fields %r"
                            % (type(self).__name__, sorted(kw)))
        # stamped by the messenger on send/receive
        self.seq = 0
        self.src = ""
        # optional trace id (reqid_t role): set by the sender to tie
        # this message into a cross-daemon op timeline; propagated in
        # the envelope, never interpreted by the transport
        self.trace = None
        # optional tenant key: the client stamps it on ops (and the
        # primary re-stamps sub-ops) so every layer — op tracking,
        # the mClock tag books, device admission, the flight
        # recorder — can attribute the work to a tenant.  Rides the
        # envelope like `trace`; never interpreted by the transport
        self.tenant = None

    def to_wire(self) -> dict:
        return {f: getattr(self, f) for f in self.FIELDS}

    @classmethod
    def from_wire(cls, d: dict) -> "Message":
        # drop fields from NEWER peers (mixed-version clusters: an
        # old daemon keeps the fields it knows; unknown message TYPES
        # are handled by UnknownMessage in decode_message)
        return cls(**{k: v for k, v in d.items() if k in cls.FIELDS})

    def __repr__(self) -> str:
        kv = ", ".join("%s=%r" % (f, getattr(self, f))
                       for f in self.FIELDS)
        return "%s(%s)" % (type(self).__name__, kv)


# message envelope version (frame-level ENCODE_START): bump compat
# only if the [type, seq, src, fields] layout itself changes
MSG_STRUCT_V = 1
MSG_STRUCT_COMPAT = 1


def encode_message(msg: Message, stamp: float | None = None) -> bytes:
    # the trace id rides as a 5th envelope element, the sender's
    # monotonic send stamp as a 6th, and the tenant key as a 7th: old
    # decoders slice row[:4] and ignore the tail, so no compat bump
    # is needed.  Untraced, unstamped, untenanted messages keep the
    # exact 4-element envelope (byte-stable for the pinned dencoder
    # corpus); the messenger passes `stamp` on live frames so
    # receivers can estimate per-peer clock offsets (the multi-host
    # span-merge prerequisite).
    row = [msg.TYPE, msg.seq, msg.src, msg.to_wire()]
    trace = getattr(msg, "trace", None)
    tenant = getattr(msg, "tenant", None)
    if trace is not None or stamp is not None or tenant is not None:
        row.append(trace)
    if stamp is not None or tenant is not None:
        row.append(stamp)
    if tenant is not None:
        row.append(tenant)
    return denc.encode_versioned(row, MSG_STRUCT_V, MSG_STRUCT_COMPAT)


class UnknownMessage(Message):
    """Placeholder for a type missing from the local registry (version
    skew): carries seq so the transport can ack + drop it instead of
    faulting the session into a replay livelock."""

    TYPE = "__unknown__"
    FIELDS = ("wire_type",)


def decode_message(data: bytes | memoryview) -> Message:
    trace = None
    stamp = None
    tenant = None
    if bytes(data[:1]) == b"V":
        _v, row = denc.decode_versioned(data, MSG_STRUCT_V)
        mtype, seq, src, fields = row[:4]
        if len(row) > 4:
            trace = row[4]
        if len(row) > 5:
            stamp = row[5]
        if len(row) > 6:
            tenant = row[6]
    else:                               # legacy unversioned frame
        mtype, seq, src, fields = denc.decode(data)
    cls = _REGISTRY.get(mtype)
    if cls is None:
        msg = UnknownMessage(wire_type=mtype)
    else:
        msg = cls.from_wire(fields)
    msg.seq = seq
    msg.src = src
    msg.trace = trace
    msg.send_stamp = stamp
    msg.tenant = tenant
    return msg
