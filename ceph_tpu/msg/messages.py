"""Concrete wire messages for the mon/osd/client protocol.

Analog of src/messages/* (MOSDOp.h, MOSDRepOp.h, MOSDMap.h,
MOSDBoot.h, MOSDFailure.h, MOSDPing.h, MMonCommand.h ...): the subset
the framework's daemons speak, with payloads as plain denc values.

Object-op lists inside MOSDOp/MOSDOpReply use dicts
{"op": name, ...} instead of the reference's numeric opcode union
(src/osd/osd_types.h OSDOp) — the OSD's do_osd_ops interpreter switches
on the name.
"""

from __future__ import annotations

from .message import Message, register


@register
class MPing(Message):
    TYPE = "ping"
    FIELDS = ("stamp",)


@register
class MPong(Message):
    TYPE = "pong"
    FIELDS = ("stamp",)


# -- monitor <-> monitor ---------------------------------------------------


@register
class MMonElection(Message):
    """Elector rounds (MMonElection.h): op = propose|defer|victory;
    scores gossips the sender's ConnectionTracker reports
    (connectivity strategy)."""
    TYPE = "mon_election"
    FIELDS = ("op", "epoch", "rank", "quorum", "scores")


@register
class MMonPaxos(Message):
    """Paxos phases (MMonPaxos.h): op = collect|last|begin|accept|
    commit|lease|catchup."""
    TYPE = "mon_paxos"
    FIELDS = ("op", "rank", "pn", "version", "blob", "last_committed",
              "first_committed", "lease_until", "uncommitted", "epoch",
              "accepted_pn")


# -- monitor <-> anyone ----------------------------------------------------


@register
class MMonGetMap(Message):
    """Request the cluster map: full if have < 0 else incrementals
    after `have` (MMonGetOSDMap.h)."""

    TYPE = "mon_get_map"
    FIELDS = ("have",)


@register
class MMonSubscribe(Message):
    """Subscribe to map publications from epoch `start` (MMonSubscribe.h)."""

    TYPE = "mon_subscribe"
    FIELDS = ("start",)


@register
class MOSDMapMsg(Message):
    """Map publication (MOSDMap.h): optional full map bytes plus a list
    of incremental bytes, each OSDMap/Incremental.encode() output."""

    TYPE = "osd_map"
    FIELDS = ("fsid", "full", "incrementals")


@register
class MOSDBoot(Message):
    """OSD -> mon: I'm up at this addr (MOSDBoot.h)."""

    TYPE = "osd_boot"
    FIELDS = ("osd", "addr", "epoch")


@register
class MOSDFailure(Message):
    """OSD -> mon failure report (MOSDFailure.h): target osd,
    seconds it has been unresponsive, reporter's map epoch."""

    TYPE = "osd_failure"
    FIELDS = ("target", "failed_for", "epoch")


@register
class MOSDBeacon(Message):
    """OSD -> mon liveness/health beacon (MOSDBeacon.h): periodic even
    while healthy; slow_ops carries the count of in-flight ops older
    than osd_op_complaint_time so the monitor can raise (and clear)
    the SLOW_OPS health warning; device_fallback reports whether the
    daemon's mesh chip is serving from the host paths and device_chip
    names that chip (the mon raises DEVICE_FALLBACK while any live
    daemon reports it, with the chip in the health detail — only the
    OSDs bound to a lost chip degrade).  slow_tenants is the
    per-tenant slice of slow_ops ({tenant: count}; tenant-less ops
    fold under "") so the SLOW_OPS health detail can name the worst
    tenant; legacy beacons without it read as no tenant attribution.
    net carries the daemon's heartbeat RTT slice ({"rtt_ms":
    {peer: ms}, "slow": [peers]}) feeding the mon's
    OSD_SLOW_PING_TIME edge; beacons without it encode
    byte-identically to the pre-net wire form.
    """

    TYPE = "osd_beacon"
    FIELDS = ("osd", "epoch", "slow_ops", "slow_tenants",
              "device_fallback", "device_chip", "net")

    def to_wire(self) -> dict:
        d = {f: getattr(self, f) for f in self.FIELDS}
        if d.get("net") is None:
            del d["net"]        # legacy beacons stay byte-stable
        return d


@register
class MOSDAlive(Message):
    """OSD -> mon: cancel my pending failure reports, and/or request
    an up_thru bump so a fresh primary can prove its interval could go
    read-write before activating (MOSDAlive.h want/version)."""

    TYPE = "osd_alive"
    FIELDS = ("osd", "epoch", "want_up_thru")


@register
class MConfig(Message):
    """mon -> daemon: the daemon's resolved centralized-config view
    (MConfig.h / ConfigMonitor push); values feed the config system's
    'mon' source layer."""

    TYPE = "config"
    FIELDS = ("values",)


@register
class MMgrReport(Message):
    """Daemon -> mgr perf/state report (MMgrReport.h via
    DaemonServer::handle_report): perf = the daemon's PerfCounters
    dump; pg_states = {state_name: count} for the PGs it is primary
    of; num_pgs/num_objects round out the health summary; pg_stats
    carries the per-PG stat rows of every PG the daemon is primary for
    (the MPGStats slice — object/byte counts, degraded/misplaced/
    unfound tallies, cumulative client-IO and recovery counters);
    osd_stats carries daemon-wide extras (the op-size histogram).

    pg_stats_cols is the packed columnar form of the same rows
    (msg.statblock: parallel typed arrays + dictionary-encoded pgids
    and states) — the telemetry-fabric wire format the mgr ingests
    as one vectorized merge.  A report carries EITHER pg_stats_cols
    (columnar producers) or pg_stats (legacy dict rows); the mgr
    accepts both, so mixed fleets converge to one digest.  Reports
    without the columnar field encode byte-identically to the
    pre-columnar wire form (legacy frames stay pinned)."""

    TYPE = "mgr_report"
    FIELDS = ("daemon", "epoch", "perf", "pg_states", "num_pgs",
              "num_objects", "pg_stats", "osd_stats", "pg_stats_cols")

    def to_wire(self) -> dict:
        d = {f: getattr(self, f) for f in self.FIELDS}
        if d.get("pg_stats_cols") is None:
            del d["pg_stats_cols"]      # legacy frames stay byte-stable
        return d


@register
class MMonMgrDigest(Message):
    """mgr -> mon PGMap digest (the reverse MMonMgrReport/
    MgrStatMonitor flow): per-pool usage + IO/recovery rates, the
    cluster pg-state summary, and the degraded/misplaced/unfound
    totals the mon folds into `status`, `df`, `osd pool stats` and
    the PG_DEGRADED / PG_AVAILABILITY health checks.  Broadcast to
    every mon (like beacons) so whichever mon leads next already
    holds the picture."""

    TYPE = "mon_mgr_digest"
    FIELDS = ("digest", "epoch")


@register
class MLog(Message):
    """Daemon -> mon cluster-log batch (MLog.h / LogClient flow):
    entries = [{seq, stamp, who, channel, level, message}, ...].
    Broadcast to every mon (like beacons); the leader commits unseen
    entries through paxos (LogMonitor dedups by (who, seq)) and the
    mon that observes the commit acks with MLogAck so the client can
    retire them.  Unacked entries are re-flushed periodically — a
    leader election between emit and commit loses nothing."""

    TYPE = "log"
    FIELDS = ("entries",)


@register
class MLogAck(Message):
    """mon -> daemon: entries of `who` up to seq `last` (of boot
    incarnation `inc`; absent = the daemon's only life) are
    paxos-committed (MLogAck.h)."""

    TYPE = "log_ack"
    FIELDS = ("who", "last", "inc")


@register
class MCrashReport(Message):
    """Daemon -> mon pending crash reports (the ceph-crash agent's
    POST, as a message): reports = [crash report dicts].  Broadcast to
    every mon; the leader commits unseen crash_ids into the
    paxos-committed crash table, and any mon that sees them committed
    acks their ids so the daemon can clear its store copy."""

    TYPE = "crash_report"
    FIELDS = ("reports",)


@register
class MCrashReportAck(Message):
    """mon -> daemon: these crash_ids are in the committed table."""

    TYPE = "crash_report_ack"
    FIELDS = ("crash_ids",)


@register
class MOSDPGTemp(Message):
    """OSD -> mon pg_temp request (MOSDPGTemp.h / OSDMonitor
    prepare_pgtemp): pgs = [[pool, ps, [osds...]], ...]; an empty osd
    list clears the mapping (PeeringState queue_want_pg_temp)."""

    TYPE = "osd_pg_temp"
    FIELDS = ("pgs", "epoch")


@register
class MMonWatchEvents(Message):
    """Client -> mon: subscribe to the committed event stream from
    cursor `start` (exclusive — the MMonSubscribe shape applied to
    the event bus).  Sent again with the current cursor to renew
    after a reconnect; the mon replies with any committed backlog
    past the cursor and pushes MMonEvents batches as commits land."""

    TYPE = "mon_watch_events"
    FIELDS = ("start",)


@register
class MMonEvents(Message):
    """mon -> watching client: committed event rows past the
    subscriber's cursor, seq-ascending ({seq, type, stamp, message,
    data?}); last_seq is the mon's committed top.  Seqs are assigned
    at paxos apply, so every mon streams the identical contiguous
    sequence — a client that re-subscribes elsewhere after an
    election resumes with no gaps and no duplicates."""

    TYPE = "mon_events"
    FIELDS = ("events", "last_seq")


@register
class MMonCommand(Message):
    """Generic admin command (MMonCommand.h): {"prefix": ..., args}."""

    TYPE = "mon_command"
    FIELDS = ("tid", "cmd")


@register
class MMonCommandAck(Message):
    TYPE = "mon_command_ack"
    FIELDS = ("tid", "result", "out")


# -- client <-> osd --------------------------------------------------------


@register
class MOSDOp(Message):
    """Client object op (MOSDOp.h): tid for reply matching; pgid the
    client computed; ops = [{"op": "write", "offset": o, "data": b}...];
    epoch = client's map epoch for gating."""

    TYPE = "osd_op"
    FIELDS = ("tid", "pool", "ps", "oid", "snapc", "snapid", "ops",
              "epoch", "flags")


@register
class MOSDOpReply(Message):
    TYPE = "osd_op_reply"
    FIELDS = ("tid", "result", "outs", "epoch", "version")


@register
class MOSDBackoff(Message):
    """OSD -> client PG backoff (MOSDBackoff.h / the osd_backoff
    machinery): op = "block" tells the client to stop re-sending ops
    that target the PG (it is peering / below min_size and the op is
    parked server-side); op = "unblock" releases it.  id is the OSD's
    monotonically increasing backoff id — an unblock releases only
    blocks with id <= its own, so a stale unblock cannot cancel a
    newer block.  oid narrows the backoff to ONE degraded object (the
    reference's hobject-ranged backoffs): ops on other objects of the
    PG keep flowing; oid=None blocks the whole PG."""

    TYPE = "osd_backoff"
    FIELDS = ("pool", "ps", "op", "id", "epoch", "oid")


@register
class MWatchNotify(Message):
    """OSD -> watching client: a notify fired on a watched object
    (MWatchNotify.h); the client acks by replying with ack=True."""
    TYPE = "watch_notify"
    FIELDS = ("pool", "ps", "oid", "notify_id", "payload", "ack")


# -- osd <-> osd (replication / peering / recovery) ------------------------


@register
class MOSDRepOp(Message):
    """Primary -> replica transaction (MOSDRepOp.h): serialized
    Transaction + the pg log entry it carries."""

    TYPE = "osd_repop"
    FIELDS = ("pool", "ps", "tid", "txn", "log_entry", "epoch",
              "min_epoch", "pg_trim_to")


@register
class MOSDRepOpReply(Message):
    TYPE = "osd_repop_reply"
    FIELDS = ("pool", "ps", "tid", "result", "epoch")


@register
class MOSDPing(Message):
    """Heartbeat (MOSDPing.h): op is "ping" or "reply"."""

    TYPE = "osd_ping"
    FIELDS = ("osd", "op", "stamp", "epoch")


@register
class MOSDPGQuery(Message):
    """Primary -> replica: send me your info+log for pgid
    (MOSDPGQuery.h)."""

    TYPE = "pg_query"
    # query: "info" (peer state only) or "log" (entries since `since` —
    # the bounded GetLog fetch; full logs never ride info rounds)
    FIELDS = ("pool", "ps", "epoch", "query", "since")


@register
class MOSDPGLog(Message):
    """Replica -> primary: my pg info + full log (MOSDPGLog.h);
    info = {last_update, last_complete, log: [entries]}."""

    TYPE = "pg_log"
    FIELDS = ("pool", "ps", "epoch", "info")


@register
class MOSDScrub(Message):
    """mon -> primary OSD: operator-requested scrub of one PG
    (MOSDScrub.h / the `ceph pg scrub|deep-scrub|repair` flow)."""

    TYPE = "osd_scrub"
    FIELDS = ("pool", "ps", "deep", "repair")


@register
class MOSDRepScrub(Message):
    """Primary -> replica: build a scrub map for these objects
    (MOSDRepScrub.h); fetch=True also returns the bytes (the repair
    pull); inventory=True returns the replica's full hobject key list
    instead (the stray-clone sweep)."""
    TYPE = "rep_scrub"
    FIELDS = ("pool", "ps", "tid", "oids", "fetch", "inventory")


@register
class MOSDRepScrubMap(Message):
    """Replica -> primary: the chunk's ScrubMap (MOSDRepScrubMap.h)."""
    TYPE = "rep_scrub_map"
    FIELDS = ("pool", "ps", "tid", "objects")


@register
class MOSDPGPush(Message):
    """Recovery push (MOSDPGPush.h): full-object pushes
    [{oid fields, data, attrs, omap, version}...]."""

    TYPE = "pg_push"
    FIELDS = ("pool", "ps", "epoch", "pushes")


@register
class MOSDPGPushReply(Message):
    TYPE = "pg_push_reply"
    FIELDS = ("pool", "ps", "epoch", "oids")


# -- osd <-> osd (EC sub-ops) ----------------------------------------------


@register
class MOSDECSubOpWrite(Message):
    """Primary -> shard k write (MOSDECSubOpWrite.h): the shard's
    serialized transaction for one EC op."""

    TYPE = "ec_sub_write"
    FIELDS = ("pool", "ps", "shard", "tid", "txn", "log_entry",
              "epoch")


@register
class MOSDECSubOpWriteReply(Message):
    TYPE = "ec_sub_write_reply"
    FIELDS = ("pool", "ps", "shard", "tid", "result", "epoch")


@register
class MOSDECSubOpRead(Message):
    """Primary -> shard read (MOSDECSubOpRead.h): extents to read from
    the shard object: [[oid_key, off, len]...]."""

    TYPE = "ec_sub_read"
    FIELDS = ("pool", "ps", "shard", "tid", "reads", "epoch")


@register
class MOSDECSubOpReadReply(Message):
    TYPE = "ec_sub_read_reply"
    FIELDS = ("pool", "ps", "shard", "tid", "buffers", "errors",
              "epoch")
