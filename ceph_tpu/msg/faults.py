"""Deterministic fault injection for the messenger (L3).

The teuthology/msgr-failures analog for this framework: a
``FaultInjector`` installed on a ``Messenger`` intercepts every
outbound and inbound MSG frame and, driven by an explicit
``random.Random(seed)``, applies per-peer-pair rules:

* ``drop``     — discard the frame silently (lossless peers replay it
                 on the next reconnect; lossy clients re-send via the
                 Objecter backoff ramp);
* ``delay``    — hold the frame for a bounded, seeded interval before
                 writing it (out-of-order delivery follows when later
                 frames overtake the held one);
* ``dup``      — write the frame twice (the receiver's seq dedup must
                 absorb it);
* ``reorder``  — hold the frame back and emit it after the NEXT frame
                 on the same connection;
* ``abort``    — kill the transport mid-write (the seeded successor of
                 the legacy ``inject_socket_failures`` knob).

Partitions are separate from probabilistic rules: ``partition(a, b)``
drops EVERY frame between the two entities in both directions until
``heal(a, b)`` — including ACK/CLOSE control frames (``on_control``),
so a cut looks like a dead host even to the session bookkeeping: no
stray ACK can retire unacked lossless entries across a partition, and
no CLOSE can masquerade as an orderly shutdown.  ``isolate(a)`` cuts
``a`` off from everyone.  Entity selectors accept exact names
("mon.1"), type wildcards ("osd.*") and "*".

Every decision consumes the injector's RNG in frame order, so a
failure schedule is replayed exactly by re-running with the same seed
(given the same frame sequence — the deterministic smoke tests in
tests/test_thrash.py pin both).
"""

from __future__ import annotations

import random


def _match(sel: str, entity: str) -> bool:
    if sel == "*" or sel == entity:
        return True
    if sel.endswith(".*"):
        return entity.split(".", 1)[0] == sel[:-2]
    return False


class FaultRule:
    """One probabilistic rule between two entity selectors.  All
    probabilities are per-frame; ``delay``/``delay_max`` bound the
    seeded hold interval in seconds."""

    __slots__ = ("src", "dst", "drop", "dup", "reorder", "abort",
                 "delay_p", "delay", "delay_max")

    def __init__(self, src: str = "*", dst: str = "*",
                 drop: float = 0.0, dup: float = 0.0,
                 reorder: float = 0.0, abort: float = 0.0,
                 delay_p: float = 0.0, delay: float = 0.0,
                 delay_max: float | None = None):
        self.src = src
        self.dst = dst
        self.drop = drop
        self.dup = dup
        self.reorder = reorder
        self.abort = abort
        self.delay_p = delay_p
        self.delay = delay
        self.delay_max = delay if delay_max is None else delay_max

    def matches(self, src: str, dst: str) -> bool:
        return _match(self.src, src) and _match(self.dst, dst)


class FrameAction:
    """The injector's verdict for one frame."""

    __slots__ = ("drop", "dup", "reorder", "abort", "delay")

    def __init__(self):
        self.drop = False
        self.dup = False
        self.reorder = False
        self.abort = False
        self.delay = 0.0

    @property
    def passthrough(self) -> bool:
        return not (self.drop or self.dup or self.reorder
                    or self.abort or self.delay)


_PASS = FrameAction()


class FaultInjector:
    """Seeded fault engine shared by one (or several) messengers.

    Install with ``messenger.fault_injector = FaultInjector(seed)``.
    The messenger consults :meth:`on_send` before writing each MSG
    frame and :meth:`on_recv` after reading one (receive-side checks
    make a single injector enforce BIDIRECTIONAL partitions even when
    the peer's messenger has no injector installed).
    """

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)
        self.rules: list[FaultRule] = []
        # frozenset({a, b}) pairs of entity selectors cut off from
        # each other; checked symmetrically
        self.partitions: set[frozenset] = set()
        self.frames_seen = 0
        self.frames_dropped = 0
        self.frames_duplicated = 0
        self.frames_delayed = 0
        self.frames_reordered = 0
        self.aborts = 0

    # -- configuration -----------------------------------------------------

    def add_rule(self, **kw) -> FaultRule:
        rule = FaultRule(**kw)
        self.rules.append(rule)
        return rule

    def clear_rules(self) -> None:
        self.rules = []

    def partition(self, a: str, b: str) -> None:
        """Bidirectional cut between the two selectors (e.g.
        ``partition("mon.1", "*")`` severs mon.1 from everyone)."""
        self.partitions.add(frozenset((a, b)))

    def heal(self, a: str, b: str) -> None:
        self.partitions.discard(frozenset((a, b)))

    def isolate(self, entity: str) -> None:
        self.partition(entity, "*")

    def rejoin(self, entity: str) -> None:
        self.heal(entity, "*")

    def heal_all(self) -> None:
        self.partitions = set()

    def partitioned(self, src: str, dst: str) -> bool:
        for pair in self.partitions:
            sels = tuple(pair)
            if len(sels) == 1:      # self-pair, e.g. {"mon.*"}
                sels = (sels[0], sels[0])
            a, b = sels
            if (_match(a, src) and _match(b, dst)) or \
                    (_match(b, src) and _match(a, dst)):
                return True
        return False

    # -- frame hooks -------------------------------------------------------

    def on_send(self, src: str, dst: str) -> FrameAction:
        """Verdict for an outbound MSG frame src -> dst.  Consumes RNG
        only when a probabilistic rule matches, so unrelated traffic
        does not perturb a pair's schedule."""
        self.frames_seen += 1
        if self.partitioned(src, dst):
            act = FrameAction()
            act.drop = True
            self.frames_dropped += 1
            return act
        act = None
        for rule in self.rules:
            if not rule.matches(src, dst):
                continue
            if act is None:
                act = FrameAction()
            r = self.rng.random()
            if rule.abort and r < rule.abort:
                act.abort = True
                self.aborts += 1
                return act
            if rule.drop and r < rule.drop:
                act.drop = True
                self.frames_dropped += 1
                return act
            if rule.dup and self.rng.random() < rule.dup:
                act.dup = True
                self.frames_duplicated += 1
            if rule.reorder and self.rng.random() < rule.reorder:
                act.reorder = True
                self.frames_reordered += 1
            if rule.delay_p and self.rng.random() < rule.delay_p:
                act.delay = rule.delay + self.rng.random() * max(
                    0.0, rule.delay_max - rule.delay)
                self.frames_delayed += 1
        return act if act is not None else _PASS

    def on_recv(self, src: str, dst: str) -> bool:
        """True = deliver, False = drop.  src is the remote peer, dst
        the local entity.  Only partitions apply on the receive side:
        probabilistic rules fire once, at the sender, so a schedule is
        a single RNG stream."""
        if self.partitioned(src, dst):
            self.frames_dropped += 1
            return False
        return True

    def on_control(self, src: str, dst: str) -> bool:
        """Gate for ACK/CLOSE control frames, both directions.  True =
        deliver.  Only partitions apply — probabilistic rules stay
        MSG-only (control frames carry no payload to lose; a partition
        however must block EVERYTHING, or a stray ACK crossing the cut
        retires unacked lossless entries and a stray CLOSE tears down
        a session whose peer should look dead, not departed)."""
        if self.partitioned(src, dst):
            self.frames_dropped += 1
            return False
        return True

    # -- reporting ---------------------------------------------------------

    def stats(self) -> dict:
        return {
            "seed": self.seed,
            "frames_seen": self.frames_seen,
            "dropped": self.frames_dropped,
            "duplicated": self.frames_duplicated,
            "delayed": self.frames_delayed,
            "reordered": self.frames_reordered,
            "aborts": self.aborts,
        }

    def __repr__(self) -> str:
        return ("FaultInjector(seed=%r, rules=%d, partitions=%d)"
                % (self.seed, len(self.rules), len(self.partitions)))
