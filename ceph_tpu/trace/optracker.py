"""Per-daemon op tracking: in-flight table + historic rings.

Reference analog: OpTracker (src/common/TrackedOp.h) as wired into
every daemon through OpRequest (src/osd/OpRequest.h) — ops register on
arrival, `mark_event` stamps each pipeline stage, completion moves the
op into a bounded historic ring (plus a separate slow-op ring when it
exceeded the complaint threshold), and the admin socket serves
`dump_ops_in_flight` / `dump_historic_ops` / `dump_historic_slow_ops`.

Cross-daemon correlation: every TrackedOp carries a `trace` id (the
reqid_t role) that the messenger envelope propagates into sub-ops, so
`find(trace)` across daemons rebuilds one client op's full timeline.
Stamps are `time.monotonic()` — comparable across the in-process
daemons of a LocalCluster (one clock), which is what the timeline
merge relies on.

Slow-op detection (`osd_op_complaint_time` analog): any in-flight op
older than the complaint threshold counts as slow; daemons report the
count in beacons and the monitor turns a nonzero cluster total into a
SLOW_OPS health warning that clears when the ops complete.
"""

from __future__ import annotations

import itertools
import time


class TrackedOp:
    """One tracked request on one daemon (TrackedOp/OpRequest)."""

    __slots__ = ("tracker", "seq", "trace", "desc", "daemon",
                 "initiated", "wall", "events", "finished", "meta",
                 "tenant")

    def __init__(self, tracker: "OpTracker", seq: int, desc: str,
                 trace: str | None, tenant: str | None = None):
        self.tracker = tracker
        self.seq = seq
        self.trace = trace
        self.tenant = tenant
        self.desc = desc
        self.daemon = tracker.daemon
        self.initiated = tracker.now()
        self.wall = time.time()
        self.events: list[tuple[float, str]] = [(self.initiated,
                                                 "initiated")]
        self.finished = False
        self.meta: dict | None = None

    def mark_event(self, event: str) -> None:
        if not self.finished:
            self.events.append((self.tracker.now(), event))

    def note(self, key: str, value) -> None:
        """Attach structured attribution to the op (e.g. the device
        DispatchTicket of the flush that carried its shards): rides
        the dump so timelines show exactly which dispatch served the
        op, not a sampled approximation."""
        if self.meta is None:
            self.meta = {}
        self.meta.setdefault(key, []).append(value)

    def finish(self, event: str = "done") -> None:
        """Completion: stamps the final event and retires the op into
        the tracker's historic ring (idempotent)."""
        if self.finished:
            return
        self.events.append((self.tracker.now(), event))
        self.finished = True
        self.tracker._retire(self)

    @property
    def age(self) -> float:
        """Seconds since arrival (in-flight) or total duration."""
        end = (self.events[-1][0] if self.finished
               else self.tracker.now())
        return end - self.initiated

    def dump(self) -> dict:
        out = {
            "trace": self.trace,
            "tenant": self.tenant,
            "desc": self.desc,
            "daemon": self.daemon,
            "initiated": self.initiated,
            "initiated_at": self.wall,
            "age": self.age,
            "in_flight": not self.finished,
            "events": [{"t": t, "rel": t - self.initiated,
                        "event": e} for t, e in self.events],
        }
        if self.meta:
            out["meta"] = self.meta
            tickets = self.meta.get("device_ticket")
            if tickets:
                # device-dispatched ops surface their attribution
                # first-class (not buried in meta): which chip served
                # the flush, and was the latency queue-wait or device
                # time — the dump_historic_ops answer to "where did
                # this op's milliseconds go"
                t = tickets[-1]
                out["device"] = {
                    "chip": t.get("chip"),
                    "klass": t.get("klass"),
                    "bucket": t.get("bucket"),
                    # continuous-dispatch slot vs legacy flush
                    "stream": t.get("stream"),
                    "queue_wait": t.get("queue_wait"),
                    "device_s": t.get("device_s"),
                    "dispatches": len(tickets),
                }
        return out


class OpTracker:
    """In-flight table + historic/slow rings for one daemon."""

    def __init__(self, ctx, daemon: str):
        self.ctx = ctx
        self.daemon = daemon
        self._seq = itertools.count(1)
        self.ops: dict[int, TrackedOp] = {}
        self.historic: list[TrackedOp] = []
        self.historic_slow: list[TrackedOp] = []
        # stamps read this daemon's clock: skewable (test hook) so the
        # timeline merge can prove its offset normalization against an
        # artificially skewed daemon
        self.clock_skew = 0.0
        # the context exposes the tracker so the admin socket's builtin
        # dump commands find it without plumbing (CephContext keeps the
        # same backref for its admin hooks)
        ctx.optracker = self
        # the daemon's flight-recorder ring rides the tracker: retired
        # ops feed it (sampled; slow ops always), and it shares this
        # tracker's skewable clock so recorder spans normalize with
        # the same offsets as op stamps
        from .recorder import FlightRecorder
        self.recorder = FlightRecorder(ctx, daemon, clock=self.now)
        # retire hook: the owning daemon hangs its per-tenant SLO
        # accounting here (stage histograms, good/bad op counts) —
        # fired for every retired op, after the recorder's feed
        self.on_retire = None

    def now(self) -> float:
        return time.monotonic() + self.clock_skew

    # -- configuration (live: re-read per call so `config set` acts) ---

    @property
    def complaint_time(self) -> float:
        return float(self.ctx.conf.get("osd_op_complaint_time", 30.0))

    # -- lifecycle -----------------------------------------------------

    def create(self, desc: str, trace: str | None = None,
               tenant: str | None = None) -> TrackedOp:
        op = TrackedOp(self, next(self._seq), desc, trace,
                       tenant=tenant)
        self.ops[op.seq] = op
        return op

    def _retire(self, op: TrackedOp) -> None:
        self.ops.pop(op.seq, None)
        self.historic.append(op)
        cap = int(self.ctx.conf.get("osd_op_history_size", 20))
        if len(self.historic) > cap:
            del self.historic[:len(self.historic) - cap]
        slow = op.age >= self.complaint_time
        if slow:
            self.historic_slow.append(op)
            scap = int(self.ctx.conf.get(
                "osd_op_history_slow_op_size", 20))
            if len(self.historic_slow) > scap:
                del self.historic_slow[:len(self.historic_slow) - scap]
        self.recorder.note_op(op, slow=slow)
        if self.on_retire is not None:
            try:
                self.on_retire(op)
            except Exception:
                pass    # observability must never sink the op path

    # -- slow-op detection ---------------------------------------------

    def slow_in_flight(self) -> list[TrackedOp]:
        """In-flight ops older than the complaint threshold — the
        count daemons report in beacons (SLOW_OPS feeds on it)."""
        limit = self.complaint_time
        now = time.monotonic()
        return [op for op in self.ops.values()
                if now - op.initiated >= limit]

    # -- queries -------------------------------------------------------

    def find(self, trace: str) -> list[dict]:
        """Every record (in-flight or historic) carrying `trace` —
        one daemon's slice of a cross-daemon timeline."""
        out = []
        seen = set()
        for op in list(self.ops.values()) + self.historic \
                + self.historic_slow:
            if op.trace == trace and id(op) not in seen:
                seen.add(id(op))
                out.append(op.dump())
        return out

    @staticmethod
    def _tenant_match(op: TrackedOp, tenant: str | None) -> bool:
        return tenant is None or op.tenant == tenant

    def dump_ops_in_flight(self, tenant: str | None = None) -> dict:
        """`tenant` narrows the dump to one tenant's ops (the
        noisy-neighbor triage surface: whose in-flight ops are these)."""
        ops = sorted((o for o in self.ops.values()
                      if self._tenant_match(o, tenant)),
                     key=lambda o: o.initiated)
        return {"num_ops": len(ops),
                "complaint_time": self.complaint_time,
                "tenant": tenant,
                "ops": [op.dump() for op in ops]}

    def dump_historic_ops(self, tenant: str | None = None) -> dict:
        ops = [op for op in self.historic
               if self._tenant_match(op, tenant)]
        return {"num_ops": len(ops), "tenant": tenant,
                "ops": [op.dump() for op in ops]}

    def dump_historic_slow_ops(self,
                               tenant: str | None = None) -> dict:
        ops = [op for op in self.historic_slow
               if self._tenant_match(op, tenant)]
        return {"num_ops": len(ops),
                "complaint_time": self.complaint_time,
                "tenant": tenant,
                "ops": [op.dump() for op in ops]}

    def slow_tenants(self) -> dict[str, int]:
        """tenant -> slow in-flight op count (ops with no tenant fold
        under "") — the per-tenant slice OSD beacons carry so the
        SLOW_OPS health detail can name the worst tenant."""
        out: dict[str, int] = {}
        for op in self.slow_in_flight():
            key = op.tenant or ""
            out[key] = out.get(key, 0) + 1
        return out

    # -- admin socket ---------------------------------------------------

    def register_admin(self, admin) -> None:
        admin.register(
            "dump_ops_in_flight",
            lambda a: self.dump_ops_in_flight(a.get("tenant")),
            "show in-flight tracked ops (optional tenant filter)")
        admin.register(
            "dump_historic_ops",
            lambda a: self.dump_historic_ops(a.get("tenant")),
            "show recently completed ops (optional tenant filter)")
        admin.register(
            "dump_historic_slow_ops",
            lambda a: self.dump_historic_slow_ops(a.get("tenant")),
            "show recently completed slow ops (optional tenant"
            " filter)")
        self.recorder.register_admin(admin)
