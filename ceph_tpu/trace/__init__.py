"""Request-level observability: tracked ops + cross-daemon spans.

Analog of src/common/TrackedOp.{h,cc} (OpTracker / TrackedOp /
OpRequest::mark_event) plus the trace-id propagation the reference
gets from reqid_t riding every sub-op: each daemon keeps an in-flight
table and a historic ring of per-op event timelines, and the trace id
travels in the message envelope so one client op's full cross-daemon
path (client -> mClock queue -> PG -> replicated/EC sub-ops -> device
EC batch -> commit) is reconstructable after the fact.
"""

from .logclient import LogClient
from .optracker import OpTracker, TrackedOp
from .recorder import FlightRecorder

__all__ = ["FlightRecorder", "LogClient", "OpTracker", "TrackedOp"]
