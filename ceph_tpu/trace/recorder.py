"""Cluster flight recorder: always-on, bounded cross-daemon timelines.

The profiling surface ROADMAP direction 4 (per-tenant SLO serving)
asserts against: OpTracker stamps and DispatchTickets already exist
per daemon, but nothing fused them into one wall-clock view.  Kim et
al. (arXiv:1709.05365, PAPERS.md) shows online-EC latency pathologies
are only diagnosable with cross-layer time attribution — is a slow
write queue wait, device time, or sub-op RTT? — and the TPU-side
methodology (arXiv:2112.09017) treats per-device busy/idle accounting
as the primary scaling signal.  This module is both:

* **per-daemon span ring** (`FlightRecorder`) — every daemon's
  OpTracker feeds retired ops into a bounded ring (sampling keeps it
  always-on: ALL slow ops are retained, plus every Nth trace by a
  trace-id hash, so the same client write is kept or dropped on
  every daemon consistently); background subsystems (scrub,
  recovery, compression pacing) record their own spans beside the
  ops they compete with.
* **process device ring** — every finished `DispatchTicket` lands in
  a process-wide ring (the mesh is shared by co-located daemons), so
  queue-wait vs device time per chip is replayable after the fact.
* **Chrome-trace / Perfetto exporter** (`chrome_trace`) — merges the
  rings through the cluster's clock-offset solver into one JSON
  document: daemons render as processes (ops packed onto
  non-overlapping lanes), mesh chips as device-lane threads, and
  flow arrows link one trace id's spans across daemons.  Open the
  file at https://ui.perfetto.dev or chrome://tracing.

Reachable via the admin socket (`dump_flight_recorder`),
`LocalCluster.export_trace()`, the `rados trace export` CLI verb, and
auto-dumped beside the diagnostics bundle on any failed thrash round.
Overhead is benched and gated (`bench.py --trace`: <= 5% on the EC
backend leg vs recorder-off).
"""

from __future__ import annotations

import os
import time
import zlib

# process-wide enable switch (bench.py --trace measures the recorder's
# overhead by flipping it); env CEPH_TPU_FLIGHT_RECORDER=0 disables at
# boot for A/B runs outside the bench
_ENABLED = os.environ.get("CEPH_TPU_FLIGHT_RECORDER", "1") \
    not in ("0", "false", "no")

_DEVICE_RING_CAP = 4096
_DEVICE_RING: list[dict] = []


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    global _ENABLED
    _ENABLED = bool(on)


def trace_sampled(trace: str | None, every: int) -> bool:
    """Deterministic 1-in-N sampling keyed on the trace id, so every
    daemon that sees the same client write makes the same keep/drop
    decision and sampled traces stay complete span trees."""
    if every <= 1:
        return True
    if not trace:
        return False
    return zlib.crc32(trace.encode()) % every == 0


class FlightRecorder:
    """One daemon's bounded span ring.  Constructed by the daemon's
    OpTracker (which owns the skewable clock the stamps read) and
    published on the context as ``ctx.flight_recorder`` so the admin
    socket's builtin `dump_flight_recorder` finds it."""

    def __init__(self, ctx, daemon: str, clock=None):
        self.ctx = ctx
        self.daemon = daemon
        self._clock = clock or time.monotonic
        self.records: list[dict] = []
        self.dropped = 0            # sampled-out op records
        ctx.flight_recorder = self

    def now(self) -> float:
        return self._clock()

    # -- configuration (live, like the tracker's) ----------------------

    @property
    def ring_cap(self) -> int:
        return int(self.ctx.conf.get("flight_recorder_ring", 2048))

    @property
    def sample_every(self) -> int:
        return int(self.ctx.conf.get("flight_recorder_sample", 4))

    # -- feeds ----------------------------------------------------------

    def _append(self, rec: dict) -> None:
        self.records.append(rec)
        cap = self.ring_cap
        if len(self.records) > cap:
            del self.records[:len(self.records) - cap]

    def note_op(self, op, slow: bool = False) -> None:
        """One retired TrackedOp -> one span record.  Retention:
        every slow op (the ops worth a post-mortem), plus every Nth
        trace (`flight_recorder_sample`); traceless ops ride the
        trace hash of their daemon+desc so they sample too."""
        if not _ENABLED:
            return
        if not slow and not trace_sampled(
                op.trace or "%s#%d" % (op.daemon, op.seq),
                self.sample_every):
            self.dropped += 1
            return
        rec = {
            "kind": "op",
            "daemon": op.daemon,
            "trace": op.trace,
            "tenant": op.tenant,
            "desc": op.desc,
            "slow": bool(slow),
            "t0": op.initiated,
            "t1": op.events[-1][0],
            "events": [[t, e] for t, e in op.events],
        }
        if op.meta and op.meta.get("device_ticket"):
            rec["tickets"] = [dict(t)
                              for t in op.meta["device_ticket"]]
        self._append(rec)

    def span(self, name: str, t0: float, t1: float | None = None,
             meta: dict | None = None) -> None:
        """One background-work span (scrub, recovery, compression
        pacing): the work the utilization integrals show competing
        with the data path, placed on the same timeline."""
        if not _ENABLED:
            return
        rec = {"kind": "background", "daemon": self.daemon,
               "name": name, "t0": t0,
               "t1": self.now() if t1 is None else t1}
        if meta:
            rec["meta"] = dict(meta)
        self._append(rec)

    # -- views -----------------------------------------------------------

    def dump(self) -> dict:
        return {"daemon": self.daemon,
                "num_records": len(self.records),
                "sample_every": self.sample_every,
                "dropped": self.dropped,
                "records": [dict(r) for r in self.records]}

    def clear(self) -> None:
        self.records.clear()
        self.dropped = 0

    def register_admin(self, admin) -> None:
        admin.register("dump_flight_recorder",
                       lambda a: self.dump(),
                       "dump the flight-recorder span ring")


# -- device ticket ring (process-wide: the mesh is shared) ---------------


def note_ticket(ticket) -> None:
    """Called by ChipRuntime.finish for every completed dispatch:
    the device-lane feed.  Duck-typed on the ticket so the trace
    package never imports the device package."""
    if not _ENABLED:
        return
    _DEVICE_RING.append({
        "seq": ticket.seq, "klass": ticket.klass,
        "bucket": ticket.bucket, "bytes": ticket.nbytes,
        "tenant": getattr(ticket, "tenant", None),
        # continuous-dispatch slot vs legacy/degradation flush: the
        # before/after is visible on the same Perfetto device lanes
        "stream": bool(getattr(ticket, "stream", False)),
        "chip": ticket.chip, "t_enqueue": ticket.t_enqueue,
        "t_admit": ticket.t_admit, "t_launch": ticket.t_launch,
        "t_done": ticket.t_done, "ok": ticket.ok,
        "queue_wait": ticket.queue_wait,
        "device_s": ticket.device_s})
    if len(_DEVICE_RING) > _DEVICE_RING_CAP:
        del _DEVICE_RING[:_DEVICE_RING_CAP // 2]


def device_records() -> list[dict]:
    return [dict(r) for r in _DEVICE_RING]


def clear_device_ring() -> None:
    _DEVICE_RING.clear()


# -- Chrome-trace / Perfetto export --------------------------------------


def _lane_for(lanes: list[float], t0: float) -> int:
    """Greedy interval coloring: the first lane whose previous span
    ended by t0 (concurrent ops on one daemon must not overlap on one
    Chrome-trace track — the viewer nests by containment)."""
    for i, end in enumerate(lanes):
        if t0 >= end:
            return i
    lanes.append(0.0)
    return len(lanes) - 1


def chrome_trace(rings: dict[str, list[dict]],
                 offsets: dict[str, float] | None = None,
                 device: list[dict] | None = None,
                 net: dict[str, list[dict]] | None = None,
                 meta: dict | None = None) -> dict:
    """Merge per-daemon flight-recorder rings (+ the device ticket
    ring) into one Chrome-trace JSON document.

    * each daemon is a **process** (pid); its op/background spans pack
      onto non-overlapping lane threads;
    * each op record renders as a complete (`ph:"X"`) slice with its
      stage transitions as nested sub-slices (stage `e_i` spans
      `[t_i, t_{i+1})`);
    * one trace id's records across >= 2 daemons are linked with flow
      events (`ph:"s"/"t"/"f"`) — the client write's arrow through
      the cluster;
    * the device ring is its own process with one base thread per
      chip (overlapping in-flight dispatches fan onto chip lanes);
    * `net` (daemon -> cumulative per-peer {"t","peer","tx","rx"}
      wire samples, osd/network.py's ring) renders as per-peer
      throughput counter tracks (`ph:"C"`) under each daemon's
      process — rates are clamped non-negative deltas, so a
      reconnect's counter reset shows as a zero, not a plunge;
    * `offsets` (entity -> seconds, the clock-offset solver's output)
      normalize every daemon's stamps onto one reference clock.

    Timestamps are microseconds from the earliest record (`ts`
    monotonic per track by construction — the schema property the
    tests pin)."""
    offsets = offsets or {}
    device = device or []
    net = net or {}
    events: list[dict] = []
    flows: list[dict] = []

    def t_of(daemon, t):
        return t - offsets.get(daemon, 0.0)

    # common epoch: earliest normalized stamp across every ring
    stamps = [t_of(d, r["t0"]) for d, recs in rings.items()
              for r in recs]
    stamps += [t["t_enqueue"] for t in device]
    stamps += [t_of(d, float(row.get("t") or 0.0))
               for d, rows in net.items() for row in rows]
    t_base = min(stamps) if stamps else 0.0

    def us(t):
        return round((t - t_base) * 1e6, 3)

    pid_of = {d: i + 1 for i, d in enumerate(sorted(rings))}
    by_trace: dict[str, list[tuple[str, dict]]] = {}
    for daemon in sorted(rings):
        pid = pid_of[daemon]
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": daemon}})
        lanes: list[float] = []
        for rec in sorted(rings[daemon], key=lambda r: r["t0"]):
            t0 = t_of(daemon, rec["t0"])
            t1 = max(t0, t_of(daemon, rec["t1"]))
            tid = _lane_for(lanes, t0)
            lanes[tid] = t1
            if rec["kind"] == "op":
                args = {"trace": rec.get("trace"),
                        "tenant": rec.get("tenant"),
                        "slow": rec.get("slow", False)}
                for t in rec.get("tickets") or []:
                    args["device_ticket_seq"] = t.get("seq")
                    args["device_chip"] = t.get("chip")
                events.append({
                    "ph": "X", "name": rec["desc"], "cat": "op",
                    "pid": pid, "tid": tid, "ts": us(t0),
                    "dur": max(0.0, round((t1 - t0) * 1e6, 3)),
                    "args": args})
                evs = rec.get("events") or []
                for (ta, name), (tb, _nb) in zip(evs, evs[1:]):
                    sa = t_of(daemon, ta)
                    sb = max(sa, t_of(daemon, tb))
                    events.append({
                        "ph": "X", "name": name, "cat": "stage",
                        "pid": pid, "tid": tid, "ts": us(sa),
                        "dur": max(0.0, round((sb - sa) * 1e6, 3)),
                        "args": {"trace": rec.get("trace")}})
                if rec.get("trace"):
                    by_trace.setdefault(rec["trace"], []).append(
                        (daemon, {"pid": pid, "tid": tid,
                                  "ts": us(t0)}))
            else:
                events.append({
                    "ph": "X", "name": rec.get("name", "background"),
                    "cat": "background", "pid": pid, "tid": tid,
                    "ts": us(t0),
                    "dur": max(0.0, round((t1 - t0) * 1e6, 3)),
                    "args": dict(rec.get("meta") or {})})
        for tid in range(len(lanes)):
            events.append({"ph": "M", "name": "thread_name",
                           "pid": pid, "tid": tid,
                           "args": {"name": "lane-%d" % tid}})

    # flow arrows: one per trace id spanning >= 2 records, start ->
    # step -> end in timeline order (the cross-daemon link)
    for trace, nodes in sorted(by_trace.items()):
        if len(nodes) < 2:
            continue
        nodes.sort(key=lambda n: n[1]["ts"])
        fid = "0x%08x" % (zlib.crc32(trace.encode()) & 0xFFFFFFFF)
        for i, (_daemon, where) in enumerate(nodes):
            ph = "s" if i == 0 else ("f" if i == len(nodes) - 1
                                     else "t")
            ev = {"ph": ph, "name": "trace", "cat": "flow",
                  "id": fid, **where}
            if ph == "f":
                ev["bp"] = "e"
            flows.append(ev)

    # device lanes: one process, base thread per chip, overlapping
    # in-flight dispatches fan onto per-chip sub-lanes
    if device:
        dpid = len(pid_of) + 1
        events.append({"ph": "M", "name": "process_name", "pid": dpid,
                       "tid": 0, "args": {"name": "device-mesh"}})
        chip_lanes: dict[int, list[float]] = {}
        named: set[int] = set()
        for t in sorted(device, key=lambda r: r["t_launch"]):
            if not t.get("t_launch") or not t.get("t_done"):
                continue
            chip = int(t.get("chip") or 0)
            lanes = chip_lanes.setdefault(chip, [])
            lane = _lane_for(lanes, t["t_launch"])
            lanes[lane] = t["t_done"]
            tid = chip * 16 + lane
            if tid not in named:
                named.add(tid)
                events.append({
                    "ph": "M", "name": "thread_name", "pid": dpid,
                    "tid": tid,
                    "args": {"name": "chip-%d lane-%d"
                             % (chip, lane)}})
            events.append({
                "ph": "X", "name": t.get("klass", "dispatch"),
                "cat": "device", "pid": dpid, "tid": tid,
                "ts": us(t["t_launch"]),
                "dur": max(0.0, round(t["device_s"] * 1e6, 3)),
                "args": {"seq": t.get("seq"), "chip": chip,
                         "bucket": t.get("bucket"),
                         "bytes": t.get("bytes"),
                         "tenant": t.get("tenant"),
                         "stream": t.get("stream"),
                         "queue_wait": t.get("queue_wait"),
                         "ok": t.get("ok")}})
        # counter tracks (ph:"C"): per-chip in-flight dispatches
        # (busy: +1 at launch, -1 at done) and queue depth (+1 at
        # enqueue, -1 at launch), edge-walked from the same tickets
        # — Perfetto renders them as the counter view of the
        # utilization integrals, beside the slices they explain
        for chip in sorted({int(t.get("chip") or 0) for t in device}):
            edges: list[tuple[float, str, int]] = []
            for t in device:
                if int(t.get("chip") or 0) != chip:
                    continue
                if t.get("t_enqueue") and t.get("t_launch"):
                    edges.append((t["t_enqueue"], "queue_depth", 1))
                    edges.append((t["t_launch"], "queue_depth", -1))
                if t.get("t_launch") and t.get("t_done"):
                    edges.append((t["t_launch"], "busy", 1))
                    edges.append((t["t_done"], "busy", -1))
            counts = {"busy": 0, "queue_depth": 0}
            for stamp, key, delta in sorted(edges):
                counts[key] += delta
                events.append({
                    "ph": "C", "name": "chip-%d %s" % (chip, key),
                    "cat": "device", "pid": dpid, "ts": us(stamp),
                    "args": {key: counts[key]}})

    # per-peer wire-throughput counter tracks (ph:"C"): rates walked
    # from the OSDs' cumulative tx/rx wire samples (osd/network.py's
    # heartbeat-paced ring), one counter per (daemon, peer) beside
    # the daemon's own op lanes — deltas clamped non-negative so a
    # reconnect's counter reset reads as a zero, not a plunge
    if net:
        next_pid = len(pid_of) + (2 if device else 1)
        for daemon in sorted(net):
            pid = pid_of.get(daemon)
            if pid is None:
                pid = next_pid
                next_pid += 1
                events.append({"ph": "M", "name": "process_name",
                               "pid": pid, "tid": 0,
                               "args": {"name": daemon}})
            prev: dict = {}
            for row in net[daemon]:
                t = t_of(daemon, float(row.get("t") or 0.0))
                peer = str(row.get("peer"))
                tx = int(row.get("tx") or 0)
                rx = int(row.get("rx") or 0)
                p = prev.get(peer)
                prev[peer] = (t, tx, rx)
                if p is None or t <= p[0]:
                    continue
                dt = t - p[0]
                events.append({
                    "ph": "C", "name": "net %s" % peer,
                    "cat": "net", "pid": pid, "ts": us(t),
                    "args": {
                        "tx_Bps": round(max(0, tx - p[1]) / dt, 1),
                        "rx_Bps": round(max(0, rx - p[2]) / dt, 1),
                    }})

    # stable order: metadata first, then slices sorted by ts (a
    # stable sort keeps a stage slice after its enclosing op slice at
    # equal ts, which is what makes per-track ts monotonic AND the
    # viewer's containment nesting deterministic), flows last
    mevents = [e for e in events if e["ph"] == "M"]
    xevents = sorted((e for e in events if e["ph"] != "M"),
                     key=lambda e: e["ts"])
    return {"traceEvents": mevents + xevents + flows,
            "displayTimeUnit": "ms",
            "otherData": dict(meta or {})}


_REQUIRED_KEYS = {
    "X": ("name", "ph", "ts", "dur", "pid", "tid"),
    "M": ("name", "ph", "pid", "args"),
    "s": ("id", "ph", "ts", "pid", "tid"),
    "t": ("id", "ph", "ts", "pid", "tid"),
    "f": ("id", "ph", "ts", "pid", "tid"),
    "C": ("name", "ph", "ts", "pid", "args"),
}


def validate_chrome_trace(doc) -> list[str]:
    """Chrome-trace schema lint (the test oracle, shaped like
    utils.exporter.validate_exposition): the document must carry a
    `traceEvents` list, every event its phase's required keys with
    numeric stamps and non-negative durations, complete (`X`) events
    in non-decreasing `ts` order per (pid, tid) track, and counter
    (`C`) events carrying numeric, never-negative sample values in
    non-decreasing `ts` order per (pid, name) counter track.
    Returns human-readable violations; empty means clean."""
    errors: list[str] = []
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        return ["document has no traceEvents list"]
    last_ts: dict[tuple, float] = {}
    for i, ev in enumerate(doc["traceEvents"]):
        if not isinstance(ev, dict):
            errors.append("event %d: not an object" % i)
            continue
        ph = ev.get("ph")
        req = _REQUIRED_KEYS.get(ph)
        if req is None:
            errors.append("event %d: unknown phase %r" % (i, ph))
            continue
        missing = [k for k in req if k not in ev]
        if missing:
            errors.append("event %d (%s): missing keys %r"
                          % (i, ph, missing))
            continue
        if ph == "M":
            continue
        try:
            ts = float(ev["ts"])
        except (TypeError, ValueError):
            errors.append("event %d: non-numeric ts %r"
                          % (i, ev.get("ts")))
            continue
        if ph == "X":
            try:
                if float(ev["dur"]) < 0:
                    errors.append("event %d: negative dur" % i)
            except (TypeError, ValueError):
                errors.append("event %d: non-numeric dur %r"
                              % (i, ev.get("dur")))
            track = (ev["pid"], ev["tid"])
            if ts < last_ts.get(track, float("-inf")):
                errors.append(
                    "event %d: ts %.3f regresses on track %r"
                    % (i, ts, track))
            last_ts[track] = ts
        elif ph == "C":
            args = ev.get("args")
            if not isinstance(args, dict) or not args:
                errors.append("event %d: counter without samples" % i)
                continue
            for k, v in args.items():
                if not isinstance(v, (int, float)):
                    errors.append(
                        "event %d: counter %r sample %r non-numeric"
                        % (i, k, v))
                elif v < 0:
                    errors.append(
                        "event %d: counter %r went negative (%g) — "
                        "unbalanced edge walk" % (i, k, v))
            ctrack = ("C", ev["pid"], ev["name"])
            if ts < last_ts.get(ctrack, float("-inf")):
                errors.append(
                    "event %d: counter ts %.3f regresses on %r"
                    % (i, ts, ctrack))
            last_ts[ctrack] = ts
    return errors
