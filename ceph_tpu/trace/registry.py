"""Stage/series name registries + the drift lint.

The flight recorder, `bench.py --trace`, and the trace tests all
reference OpTracker stage names and device exporter series by string
literal.  A renamed stage at its emission site (`mark_event("...")`)
would silently break every consumer — the timeline still renders, the
bench still prints, but the renamed stage just stops matching.  This
module makes that a tier-1 lint failure instead:

* ``OP_STAGES`` / ``OP_STAGE_PREFIXES`` — the canonical registry of
  every stage name the tracker can emit (prefixes cover the dynamic
  forms like ``sent_osd.<n>``);
* ``BACKGROUND_SPANS`` — the flight recorder's background span names;
* ``DEVICE_SERIES`` — the per-chip device metric names the exporter
  publishes (checked against a live ChipRuntime, so a metrics() key
  added without registration also fails);
* ``CONSUMER_STAGE_REFS`` — which stage names each consumer file
  (bench.py, the trace tests) is known to reference.

``lint_repo()`` closes the loop in both directions: every emitted
literal must be registered, every registered name must still be
emitted somewhere, and every consumer reference must be registered
AND still literally present in the consumer's source — so a rename
anywhere in the chain fails the lint until every link is updated.
"""

from __future__ import annotations

import os
import re

# every static stage literal the tracker emits (mark_event /
# _op_event / finish / _op_finish call sites across ceph_tpu), plus
# the two implicit stamps every op carries
OP_STAGES = frozenset({
    "initiated", "done",                      # implicit (ctor/default)
    # client (client/rados.py)
    "no_primary", "redirected", "redirected_inactive",
    # mon (mon/monitor.py)
    "proposal_queued", "proposal_timeout", "error",
    # osd queue/dispatch (osd/daemon.py)
    "queued", "reached_pg", "waiting_for_map", "waiting_for_active",
    "waiting_for_min_size", "waiting_for_degraded_object",
    "waiting_for_missing_object", "started_write", "started_apply",
    "sub_op_sent", "applied", "read_done", "watch_done",
    "done_no_replicas", "error_reply", "no_such_pool",
    "dropped_not_primary", "dropped_wrong_pg_after_split",
    "dropped_interval_change", "dropped_pool_deleted",
    "dup_answered_from_journal",
    # dedup plane (dedup/plane.py)
    "dedup_planned", "waiting_for_inflight_dup",
    "dropped_inflight_dup",
    "aborted_interval_change", "aborted_pool_deleted",
    # EC backend (osd/ecbackend.py)
    "ec_write_started", "ec_encode_start", "ec_encoded",
    "device_dispatched", "device_stream_retired",
    "ec_sub_write_sent", "ec_sub_write_acked",
    "ec_sub_write_timeout", "ec_write_done", "ec_read_done",
    "ec_shard_applied", "ec_delta_rmw", "ec_delta_done",
    "ec_error_reply",
})

# dynamic stage families: the literal carries a %-format tail
OP_STAGE_PREFIXES = ("sent_osd.", "commit_rec_osd.", "reply_r")

# flight-recorder background span names (FlightRecorder.span callers)
BACKGROUND_SPANS = frozenset({
    "scrub", "deep_scrub", "recovery", "compression_paced",
    "dedup_paced",
})

# per-chip device series (ChipRuntime.metrics keys + the families
# prom_lines adds beside them)
DEVICE_SERIES = frozenset({
    "device_queue_depth", "device_inflight",
    "device_bucket_hit_ratio", "device_bucket_waste_ratio",
    "device_compile_count", "device_dispatches",
    "device_host_fallbacks", "device_pool_hits",
    "device_pool_misses", "device_fallback",
    "device_fallback_count", "device_heal_count",
    "device_queue_rejected",
    "device_util_busy", "device_util_queue_wait", "device_util_idle",
    # continuous dispatch stream (device/stream.py): slot occupancy
    # (payload fraction of dispatched slot capacity), admission-loop
    # latency (mean arrival->slot-grant seconds), independent-retire
    # and pending-admission counts
    "device_slot_occupancy", "device_admission_wait",
    "device_stream_retires", "device_stream_pending",
    # repair-traffic plane (device/runtime.py note_repair): survivor
    # bytes read vs rebuilt bytes pushed by the recovery flows bound
    # to each chip — the figure the locality-aware codecs shrink
    "device_repair_bytes_read", "device_repair_bytes_moved",
    # compression plane (device/runtime.py note_compress): raw bytes
    # match-planned on each chip vs emitted container bytes — the
    # observable that force-mode pools stopped burning host CPU
    "device_compress_bytes_in", "device_compress_bytes_out",
    # dedup plane (device/runtime.py note_fingerprint): chunks/bytes
    # content-fingerprinted on each chip's CRC lanes
    "device_fingerprint_chunks", "device_fingerprint_bytes",
    # families prom_lines emits beside the metrics() gauges
    "device_chips", "device_dispatch_seconds",
})

# tenant SLO plane: the per-tenant stage-histogram names OSDs emit
# via note_tenant_stage (the mgr SLO engine's burn-rate input —
# mgr/slo.py re-exports the same tuple) and the tenant-labeled
# exporter families the mgr renders.  Both directions are linted:
# every emitted literal registered, every registered name emitted.
TENANT_STAGES = frozenset({
    "queue_wait", "subop_rtt", "ec_batch_wait", "device_dispatch",
    "total",
})

TENANT_SERIES = frozenset({
    "ceph_tpu_tenant_ops_total", "ceph_tpu_tenant_errors_total",
    "ceph_tpu_tenant_op_seconds", "ceph_tpu_tenant_slo_burn_fast",
    "ceph_tpu_tenant_slo_burn_slow", "ceph_tpu_tenant_p99_ms",
})

# telemetry fabric: the mgr's report-ingest exporter families
# (rendered by mgr/daemon.py ingest_prom_lines) — report rows/bytes
# per wire format, the apply-latency histogram, the row-loop
# fallback counter, and the visible stale/pool prune counters
MGR_SERIES = frozenset({
    "ceph_tpu_mgr_report_rows_total",
    "ceph_tpu_mgr_report_bytes_total",
    "ceph_tpu_mgr_ingest_seconds",
    "ceph_tpu_mgr_ingest_fallback_rows_total",
    "ceph_tpu_mgr_rows_pruned_total",
    # repair-traffic plane: per-codec recovery bytes (read from
    # survivors / moved to rebuilt shards) folded from the OSDs'
    # osd_stats.repair rows into the digest and rendered codec-labeled
    "ceph_tpu_repair_bytes_read_total",
    "ceph_tpu_repair_bytes_moved_total",
    # data-reduction plane: per-pool dedup counters folded from the
    # OSDs' osd_stats.dedup rows and rendered pool-labeled
    "ceph_tpu_dedup_chunks_stored_total",
    "ceph_tpu_dedup_chunks_deduped_total",
    "ceph_tpu_dedup_bytes_saved_total",
})

# history plane: the downsampled series names mgr/history.py's
# extract_samples emits from each digest tick (the `perf history`
# query namespace and the anomaly engine's watch list)
HISTORY_SERIES = frozenset({
    "io.read_ops_s", "io.write_ops_s",
    "io.read_bytes_s", "io.write_bytes_s",
    "recovery.ops_s", "recovery.bytes_s",
    "pg.degraded", "pg.misplaced",              # label: pool id
    "device.busy_frac", "device.queue_wait_frac",   # label: chip
    "tenant.p99_ms", "tenant.burn_fast",        # label: tenant
    "repair.bytes_read", "repair.bytes_moved",
    "dedup.bytes_stored", "dedup.bytes_saved",
    # network plane (label: daemon)
    "net.rtt_ms", "net.queue_depth", "net.resend_rate",
})

# network plane: the per-peer messenger telemetry fields WireStats
# dumps (msg/messenger.py — admin-socket `dump_osd_network`, the
# osd_stats net rows and collect_diagnostics all serve them) and the
# net exporter families the mgr renders.  Both directions linted.
NET_STAGES = frozenset({
    "queue_depth", "queue_wait_s", "resends", "replays",
    "mark_downs", "handshake_s", "backoff_s",
})

NET_SERIES = frozenset({
    "ceph_tpu_net_resends_total", "ceph_tpu_net_replays_total",
    "ceph_tpu_net_mark_downs_total", "ceph_tpu_net_queue_depth",
    "ceph_tpu_net_peer_tx_bytes_total",
    "ceph_tpu_net_peer_rx_bytes_total",
    "ceph_tpu_net_rtt_ms", "ceph_tpu_net_backoff_seconds",
    "ceph_tpu_net_handshake_seconds",
})

# event bus: the committed event types the mon emits (EventMonitor
# rows; `watch-events` / event_stream consumers switch on these)
EVENT_TYPES = frozenset({
    "health_edge", "clog", "osd_boot", "osd_down", "osd_out",
    "progress_start", "progress_finish",
})

# consumers referencing history series / event types by literal —
# every entry must be registered AND still present in the file
CONSUMER_HISTORY_REFS = {
    "bench.py": (
        "io.write_ops_s", "device.busy_frac",
    ),
    "tests/test_history.py": (
        "io.write_ops_s", "device.busy_frac", "tenant.p99_ms",
        "pg.degraded",
    ),
    "tests/test_net.py": (
        "net.rtt_ms", "net.resend_rate",
    ),
}

# consumers referencing the net plane (WireStats fields / exporter
# families) by literal — registered AND literally present, both ways
CONSUMER_NET_REFS = {
    "bench.py": (
        "ceph_tpu_net_rtt_ms", "ceph_tpu_net_peer_tx_bytes_total",
        "resends", "queue_depth",
    ),
    "tests/test_net.py": (
        "ceph_tpu_net_rtt_ms", "ceph_tpu_net_resends_total",
        "resends", "replays", "queue_wait_s",
    ),
}

CONSUMER_EVENT_REFS = {
    "tests/test_events.py": (
        "health_edge", "osd_boot", "osd_down",
        "progress_start", "progress_finish",
    ),
}

# consumers referencing the ingest families by literal (the bench
# ingest leg asserts its exposition render; the ingest tests pin the
# scrape surface) — every entry must be registered AND present
CONSUMER_MGR_REFS = {
    "bench.py": (
        "ceph_tpu_mgr_ingest_seconds",
        "ceph_tpu_mgr_report_rows_total",
    ),
    "tests/test_ingest.py": (
        "ceph_tpu_mgr_report_rows_total",
        "ceph_tpu_mgr_report_bytes_total",
        "ceph_tpu_mgr_ingest_seconds",
        "ceph_tpu_mgr_ingest_fallback_rows_total",
        "ceph_tpu_mgr_rows_pruned_total",
    ),
    "tests/test_ec_recovery_codecs.py": (
        "ceph_tpu_repair_bytes_read_total",
        "ceph_tpu_repair_bytes_moved_total",
    ),
    "tests/test_dedup.py": (
        "ceph_tpu_dedup_chunks_stored_total",
        "ceph_tpu_dedup_chunks_deduped_total",
        "ceph_tpu_dedup_bytes_saved_total",
    ),
}

# which stage names each consumer file references by literal; the
# lint demands every entry be registered AND literally present in the
# file, so a stage rename that misses a consumer fails here
CONSUMER_STAGE_REFS = {
    "bench.py": (
        "queued", "reached_pg", "sub_op_sent", "ec_sub_write_sent",
        "ec_sub_write_acked", "ec_encode_start", "ec_encoded",
    ),
    "tests/test_optracker.py": (
        "queued", "reached_pg", "started_write", "sub_op_sent",
        "started_apply", "applied", "ec_encode_start", "ec_encoded",
    ),
    "tests/test_flight_recorder.py": (
        "queued", "ec_encode_start", "ec_encoded", "ec_write_done",
        "device_dispatched",
    ),
    "tests/test_dispatch_stream.py": (
        "device_stream_retired",
    ),
    "tests/test_dedup.py": (
        "dedup_planned",
    ),
}

CONSUMER_SERIES_REFS = {
    "tests/test_flight_recorder.py": (
        "device_util_busy", "device_util_queue_wait",
        "device_util_idle",
    ),
    # the continuous-dispatch + repair-traffic + compression bench
    # legs and their tests consume these series by literal name
    "bench.py": (
        "device_slot_occupancy", "device_admission_wait",
        "device_repair_bytes_read", "device_repair_bytes_moved",
        "device_compress_bytes_in", "device_compress_bytes_out",
        "device_fingerprint_chunks", "device_fingerprint_bytes",
    ),
    "tests/test_tlz.py": (
        "device_compress_bytes_in", "device_compress_bytes_out",
    ),
    "tests/test_dispatch_stream.py": (
        "device_slot_occupancy", "device_admission_wait",
        "device_stream_retires", "device_stream_pending",
    ),
    "tests/test_ec_recovery_codecs.py": (
        "device_repair_bytes_read", "device_repair_bytes_moved",
    ),
    "tests/test_dedup.py": (
        "device_fingerprint_chunks", "device_fingerprint_bytes",
    ),
}

_EMIT_RES = (
    re.compile(r'\.mark_event\(\s*"([^"]+)"'),
    re.compile(r'_op_event\([^,()]+,\s*"([^"]+)"'),
    re.compile(r'\.finish\(\s*"([^"]+)"'),
    re.compile(r'_op_finish\([^,()]+,\s*"([^"]+)"'),
)

_EMIT_COND_RE = re.compile(
    r'\.mark_event\(\s*"([^"]+)"\s+if\s+.{0,120}?'
    r'else\s+"([^"]+)"\)', re.S)

_SPAN_RE = re.compile(r'\.span\(\s*\n?\s*"([^"]+)"')
_SPAN_COND_RE = re.compile(
    r'\.span\(\s*"([^"]+)"\s+if\s+.{0,120}?else\s+"([^"]+)"', re.S)


def _repo_root(root: str | None) -> str:
    return root or os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))


def _iter_sources(pkg_dir: str):
    for dirpath, _dirs, files in os.walk(pkg_dir):
        if "__pycache__" in dirpath:
            continue
        for fn in files:
            if fn.endswith(".py"):
                path = os.path.join(dirpath, fn)
                with open(path) as f:
                    yield path, f.read()


def emitted_stages(root: str | None = None
                   ) -> tuple[set[str], set[str], set[str]]:
    """(exact stage names, dynamic prefixes, span names) scanned from
    the ceph_tpu sources' emission call sites."""
    exact: set[str] = set()
    prefixes: set[str] = set()
    spans: set[str] = set()
    pkg = os.path.join(_repo_root(root), "ceph_tpu")
    for _path, src in _iter_sources(pkg):
        for rx in _EMIT_RES:
            for name in rx.findall(src):
                if "%" in name:
                    prefixes.add(name.split("%")[0])
                else:
                    exact.add(name)
        for a, b in _EMIT_COND_RE.findall(src):
            exact.update((a, b))
        for a, b in _SPAN_COND_RE.findall(src):
            spans.update((a, b))
        for name in _SPAN_RE.findall(src):
            if " if " not in name:
                spans.add(name)
    return exact, prefixes, spans


def stage_known(name: str) -> bool:
    if name in OP_STAGES:
        return True
    return any(name.startswith(p) for p in OP_STAGE_PREFIXES)


def lint_emissions(root: str | None = None) -> list[str]:
    """Both directions between the registry and the emission sites."""
    errors: list[str] = []
    exact, prefixes, spans = emitted_stages(root)
    for name in sorted(exact):
        if not stage_known(name):
            errors.append("emitted stage %r is not registered in"
                          " trace.registry.OP_STAGES" % name)
    for pref in sorted(prefixes):
        if pref not in OP_STAGE_PREFIXES:
            errors.append("emitted dynamic stage prefix %r is not in"
                          " OP_STAGE_PREFIXES" % pref)
    implicit = {"initiated", "done"}
    for name in sorted(OP_STAGES - exact - implicit):
        errors.append("registered stage %r is no longer emitted"
                      " anywhere" % name)
    for pref in sorted(set(OP_STAGE_PREFIXES) - prefixes):
        errors.append("registered stage prefix %r is no longer"
                      " emitted anywhere" % pref)
    for name in sorted(spans - BACKGROUND_SPANS):
        errors.append("background span %r is not registered in"
                      " BACKGROUND_SPANS" % name)
    for name in sorted(BACKGROUND_SPANS - spans):
        errors.append("registered background span %r is no longer"
                      " recorded anywhere" % name)
    return errors


def lint_device_series() -> list[str]:
    """DEVICE_SERIES must match what a live chip actually exports (a
    metrics() key added or renamed without registration fails)."""
    from ..device.runtime import DeviceRuntime
    live = set(DeviceRuntime(chips=1).chips[0].metrics())
    live |= {"device_chips", "device_dispatch_seconds"}
    errors = []
    for name in sorted(live - DEVICE_SERIES):
        errors.append("exported device series %r is not registered"
                      " in trace.registry.DEVICE_SERIES" % name)
    for name in sorted(DEVICE_SERIES - live):
        errors.append("registered device series %r is no longer"
                      " exported" % name)
    return errors


_TENANT_STAGE_RE = re.compile(
    r'note_tenant_stage\([^"]*?"([^"]+)"', re.S)


def lint_tenant_plane(root: str | None = None) -> list[str]:
    """Tenant SLO plane drift lint: every `note_tenant_stage` literal
    emitted anywhere in ceph_tpu must be registered in TENANT_STAGES
    (and vice versa — a renamed stage that still sits in the registry
    fails), the SLO engine's own stage tuple must match, and every
    registered tenant exporter family must literally appear in the
    mgr's renderer (so a family rename cannot silently drop a
    series)."""
    errors: list[str] = []
    base = _repo_root(root)
    pkg = os.path.join(base, "ceph_tpu")
    emitted: set[str] = set()
    for _path, src in _iter_sources(pkg):
        emitted.update(_TENANT_STAGE_RE.findall(src))
    for name in sorted(emitted - TENANT_STAGES):
        errors.append("emitted tenant stage %r is not registered in"
                      " trace.registry.TENANT_STAGES" % name)
    for name in sorted(TENANT_STAGES - emitted):
        errors.append("registered tenant stage %r is no longer"
                      " emitted anywhere" % name)
    try:
        from ..mgr.slo import TENANT_STAGES as ENGINE_STAGES
        if set(ENGINE_STAGES) != TENANT_STAGES:
            errors.append(
                "mgr.slo.TENANT_STAGES %r diverged from"
                " trace.registry.TENANT_STAGES %r"
                % (sorted(ENGINE_STAGES), sorted(TENANT_STAGES)))
    except Exception as e:
        errors.append("mgr.slo unimportable: %r" % e)
    mgr_path = os.path.join(pkg, "mgr", "daemon.py")
    try:
        with open(mgr_path) as f:
            mgr_src = f.read()
    except OSError:
        errors.append("ceph_tpu/mgr/daemon.py is missing")
        mgr_src = ""
    for fam in sorted(TENANT_SERIES):
        if fam not in mgr_src:
            errors.append(
                "registered tenant series %r is not rendered by"
                " ceph_tpu/mgr/daemon.py" % fam)
    return errors


def lint_mgr_plane(root: str | None = None) -> list[str]:
    """Telemetry-fabric drift lint: every registered mgr ingest
    family must literally appear in the mgr's renderer (a family
    rename cannot silently drop a series), and every consumer
    reference must be a registered family still literally present in
    the consumer's source."""
    errors: list[str] = []
    base = _repo_root(root)
    mgr_path = os.path.join(base, "ceph_tpu", "mgr", "daemon.py")
    try:
        with open(mgr_path) as f:
            mgr_src = f.read()
    except OSError:
        errors.append("ceph_tpu/mgr/daemon.py is missing")
        mgr_src = ""
    for fam in sorted(MGR_SERIES):
        if fam not in mgr_src:
            errors.append(
                "registered mgr ingest series %r is not rendered by"
                " ceph_tpu/mgr/daemon.py" % fam)
    for relpath, names in sorted(CONSUMER_MGR_REFS.items()):
        path = os.path.join(base, relpath)
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            errors.append("consumer %s is missing" % relpath)
            continue
        for name in names:
            if name not in MGR_SERIES:
                errors.append(
                    "%s references unregistered mgr series %r"
                    % (relpath, name))
            if name not in src:
                errors.append(
                    "%s no longer references mgr series %r (stale"
                    " CONSUMER_MGR_REFS entry?)" % (relpath, name))
    return errors


def lint_consumers(root: str | None = None) -> list[str]:
    """Every consumer reference must be a registered name AND still
    literally present in the consumer's source."""
    errors: list[str] = []
    base = _repo_root(root)
    for relpath, names in sorted(CONSUMER_STAGE_REFS.items()):
        path = os.path.join(base, relpath)
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            errors.append("consumer %s is missing" % relpath)
            continue
        for name in names:
            if not stage_known(name):
                errors.append("%s references unregistered stage %r"
                              % (relpath, name))
            if '"%s"' % name not in src:
                errors.append("%s no longer references stage %r"
                              " (stale CONSUMER_STAGE_REFS entry?)"
                              % (relpath, name))
    for relpath, names in sorted(CONSUMER_SERIES_REFS.items()):
        path = os.path.join(base, relpath)
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            errors.append("consumer %s is missing" % relpath)
            continue
        for name in names:
            if name not in DEVICE_SERIES:
                errors.append("%s references unregistered series %r"
                              % (relpath, name))
            if name not in src:
                errors.append("%s no longer references series %r"
                              % (relpath, name))
    return errors


_HISTORY_SERIES_RE = re.compile(r'"([a-z]+\.[a-z0-9_]+)"')

_EVENT_EMIT_RE = re.compile(r'\bemit(?:_event)?\(\s*"([a-z_]+)"')


def lint_history_plane(root: str | None = None) -> list[str]:
    """History-plane drift lint: every dotted series literal in
    mgr/history.py (the single emission module) must be registered
    in HISTORY_SERIES and vice versa, and every consumer reference
    must be a registered series still literally present in the
    consumer's source."""
    errors: list[str] = []
    base = _repo_root(root)
    hist_path = os.path.join(base, "ceph_tpu", "mgr", "history.py")
    try:
        with open(hist_path) as f:
            hist_src = f.read()
    except OSError:
        return ["ceph_tpu/mgr/history.py is missing"]
    emitted = set(_HISTORY_SERIES_RE.findall(hist_src))
    for name in sorted(emitted - HISTORY_SERIES):
        errors.append("history series %r emitted by mgr/history.py"
                      " is not registered in"
                      " trace.registry.HISTORY_SERIES" % name)
    for name in sorted(HISTORY_SERIES - emitted):
        errors.append("registered history series %r is no longer"
                      " emitted by mgr/history.py" % name)
    for relpath, names in sorted(CONSUMER_HISTORY_REFS.items()):
        path = os.path.join(base, relpath)
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            errors.append("consumer %s is missing" % relpath)
            continue
        for name in names:
            if name not in HISTORY_SERIES:
                errors.append(
                    "%s references unregistered history series %r"
                    % (relpath, name))
            if '"%s"' % name not in src:
                errors.append(
                    "%s no longer references history series %r"
                    " (stale CONSUMER_HISTORY_REFS entry?)"
                    % (relpath, name))
    return errors


def lint_net_plane(root: str | None = None) -> list[str]:
    """Network-plane drift lint: every registered WireStats field
    must still be a literal dump key in msg/messenger.py (the single
    emission module), every registered net exporter family must
    literally appear in the mgr's renderer, and every consumer
    reference must be registered AND still literally present in the
    consumer's source — so a rename anywhere in the
    counter->digest->exporter chain fails here."""
    errors: list[str] = []
    base = _repo_root(root)
    msgr_path = os.path.join(base, "ceph_tpu", "msg",
                             "messenger.py")
    try:
        with open(msgr_path) as f:
            msgr_src = f.read()
    except OSError:
        errors.append("ceph_tpu/msg/messenger.py is missing")
        msgr_src = ""
    for name in sorted(NET_STAGES):
        if '"%s"' % name not in msgr_src:
            errors.append(
                "registered net telemetry field %r is no longer"
                " dumped by ceph_tpu/msg/messenger.py" % name)
    mgr_path = os.path.join(base, "ceph_tpu", "mgr", "daemon.py")
    try:
        with open(mgr_path) as f:
            mgr_src = f.read()
    except OSError:
        errors.append("ceph_tpu/mgr/daemon.py is missing")
        mgr_src = ""
    for fam in sorted(NET_SERIES):
        if fam not in mgr_src:
            errors.append(
                "registered net series %r is not rendered by"
                " ceph_tpu/mgr/daemon.py" % fam)
    for relpath, names in sorted(CONSUMER_NET_REFS.items()):
        path = os.path.join(base, relpath)
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            errors.append("consumer %s is missing" % relpath)
            continue
        for name in names:
            if name not in NET_SERIES and name not in NET_STAGES:
                errors.append(
                    "%s references unregistered net name %r"
                    % (relpath, name))
            if '"%s"' % name not in src:
                errors.append(
                    "%s no longer references net name %r (stale"
                    " CONSUMER_NET_REFS entry?)" % (relpath, name))
    return errors


def lint_event_plane(root: str | None = None) -> list[str]:
    """Event-bus drift lint: every event type emitted in the mon
    package (`emit_event("...")` / the HealthMonitor's `emit("...")`
    funnel) must be registered in EVENT_TYPES and vice versa, and
    every consumer reference must be registered AND still literally
    present in the consumer's source."""
    errors: list[str] = []
    base = _repo_root(root)
    mon_pkg = os.path.join(base, "ceph_tpu", "mon")
    emitted: set[str] = set()
    for _path, src in _iter_sources(mon_pkg):
        emitted.update(_EVENT_EMIT_RE.findall(src))
    for name in sorted(emitted - EVENT_TYPES):
        errors.append("emitted event type %r is not registered in"
                      " trace.registry.EVENT_TYPES" % name)
    for name in sorted(EVENT_TYPES - emitted):
        errors.append("registered event type %r is no longer"
                      " emitted by the mon" % name)
    for relpath, names in sorted(CONSUMER_EVENT_REFS.items()):
        path = os.path.join(base, relpath)
        try:
            with open(path) as f:
                src = f.read()
        except OSError:
            errors.append("consumer %s is missing" % relpath)
            continue
        for name in names:
            if name not in EVENT_TYPES:
                errors.append(
                    "%s references unregistered event type %r"
                    % (relpath, name))
            if '"%s"' % name not in src:
                errors.append(
                    "%s no longer references event type %r (stale"
                    " CONSUMER_EVENT_REFS entry?)" % (relpath, name))
    return errors


def lint_repo(root: str | None = None) -> list[str]:
    """The tier-1 drift lint: emission sites vs registry vs consumer
    references, plus the live device-series check, the tenant SLO
    plane (stage histograms + exporter families), the mgr
    telemetry-fabric ingest families, and the history/event planes."""
    return (lint_emissions(root) + lint_device_series()
            + lint_consumers(root) + lint_tenant_plane(root)
            + lint_mgr_plane(root) + lint_history_plane(root)
            + lint_net_plane(root) + lint_event_plane(root))
