"""Cluster log client: every daemon's handle into the mon's LogMonitor.

Reference analog: LogClient/LogChannel (src/common/LogClient.h) — the
`clog` handle daemons use for `clog->error() << ...`: entries carry a
channel ("cluster" for operator-facing events, "audit" for command
provenance), a severity, and a per-daemon sequence number; they batch
into MLog messages to the monitors, the leader commits them through
paxos (so `log last` agrees on every mon and survives elections), and
the committing mon acks with MLogAck so the client can drop them.
Entries stay queued (and are periodically re-flushed) until acked —
a leader election or dropped frame between emit and commit loses
nothing.

The channel/severity registries double as the emit lint: an
unregistered channel or level raises at the call site, so a typo'd
`clog.queue("warning", ...)` is a unit-test failure, not a silently
unaggregatable log stream.
"""

from __future__ import annotations

import time

# registered channels (LogChannel names): "cluster" is the
# operator-facing event stream (`ceph -w`), "audit" records command
# provenance.  The emit lint rejects anything else.
CHANNELS = ("cluster", "audit")

# registered severities, lowest to highest (clog_to_monitors levels)
LEVELS = ("DBG", "INF", "WRN", "ERR")


class LogClient:
    """One daemon's cluster-log handle.

    ``send_fn(msg)`` delivers an MLog to the monitors (broadcast, like
    beacons, so whichever mon leads next sees it); the mon that
    observes the paxos commit acks back and ``handle_ack`` retires the
    entries.  ``flush()`` re-sends everything still unacked — callers
    wire it into their periodic loop so entries survive leader
    elections and dropped frames.
    """

    def __init__(self, ctx, daemon: str, send_fn=None):
        self.ctx = ctx
        self.daemon = daemon
        self.send_fn = send_fn
        self._seq = 0
        # boot incarnation: entries carry (inc, seq) and the
        # LogMonitor dedups on the PAIR, ordered lexicographically —
        # a daemon reborn on a WIPED store (its persisted seq floor
        # gone) mints a fresh, larger incarnation, so its seqs
        # restarting from 1 are new entries, not swallowed resends
        self.incarnation = 0
        # unacked entries, oldest first (the LogClient log_queue)
        self.pending: list[dict] = []
        # level -> total entries ever queued (the
        # ceph_tpu_log_messages_total{daemon,level} exporter source)
        self.counts: dict[str, int] = {lv: 0 for lv in LEVELS}
        # on_seq(seq) after every emit: daemons persist the last-used
        # seq into their own store so a restart resumes ABOVE it —
        # the LogMonitor dedups by (who, inc, seq), so a seq reset
        # under an unchanged incarnation would swallow the reborn
        # daemon's entries as resends and let pre-restart unacked
        # entries supersede them
        self.on_seq = None

    def resume_above(self, seq: int, incarnation: int = 0) -> None:
        """Adopt a persisted floor: the next entry's seq is at least
        `seq`+1 (restart path; no-op when the floor is behind us).
        `incarnation` is the persisted boot incarnation — a fresh
        (wiped) store passes a newly minted one instead."""
        self._seq = max(self._seq, int(seq))
        self.incarnation = max(self.incarnation, int(incarnation))

    # -- emit (the clog->error()/warn()/info() surface) -----------------

    def queue(self, level: str, message: str,
              channel: str = "cluster") -> dict:
        """Queue one entry.  Unregistered channel/severity raises —
        the emit lint every call site passes through."""
        if channel not in CHANNELS:
            raise ValueError("unregistered clog channel %r (have %s)"
                             % (channel, CHANNELS))
        if level not in LEVELS:
            raise ValueError("unregistered clog severity %r (have %s)"
                             % (level, LEVELS))
        self._seq += 1
        if self.on_seq is not None:
            try:
                self.on_seq(self._seq)
            except Exception:
                pass        # persistence must never sink the emit
        entry = {"seq": self._seq, "inc": self.incarnation,
                 "stamp": time.time(),
                 "who": self.daemon, "channel": channel,
                 "level": level, "message": str(message)}
        self.pending.append(entry)
        self.counts[level] = self.counts.get(level, 0) + 1
        # mirror into the local ring so a crash dump shows what the
        # daemon last told (or tried to tell) the cluster
        self.ctx.log.log("mon", 0 if level == "ERR" else 1,
                         "clog %s [%s] %s" % (channel, level, message))
        return entry

    def error(self, message: str, channel: str = "cluster") -> None:
        self.queue("ERR", message, channel)
        self.flush()

    def warn(self, message: str, channel: str = "cluster") -> None:
        self.queue("WRN", message, channel)
        self.flush()

    def info(self, message: str, channel: str = "cluster") -> None:
        self.queue("INF", message, channel)
        self.flush()

    def debug(self, message: str, channel: str = "cluster") -> None:
        self.queue("DBG", message, channel)
        self.flush()

    # -- delivery ---------------------------------------------------------

    def flush(self) -> None:
        """Send every unacked entry (idempotent on the mon side: the
        LogMonitor dedups by (who, seq) at apply, so a re-flush racing
        its own ack commits nothing twice)."""
        if not self.pending or self.send_fn is None:
            return
        from ..msg.messages import MLog
        self.send_fn(MLog(entries=[dict(e) for e in self.pending]))

    def handle_ack(self, who: str, last: int,
                   inc: int | None = None) -> None:
        """A mon observed the paxos commit through entry `last` (of
        incarnation `inc`; an ack naming an OLDER incarnation is a
        stale ack for a previous life and retires nothing here)."""
        if who != self.daemon:
            return
        if inc is not None and int(inc) != self.incarnation:
            return
        self.pending = [e for e in self.pending
                        if e["seq"] > int(last)]

    @property
    def num_pending(self) -> int:
        return len(self.pending)

    def counts_wire(self) -> dict:
        """Per-level totals for the MMgrReport / exporter path."""
        return {lv: n for lv, n in self.counts.items() if n}
