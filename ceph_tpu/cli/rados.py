"""rados: object-level CLI against a live cluster.

Analog of src/tools/rados (rados put/get/ls/rm/stat/df/bench):

    python -m ceph_tpu.cli.rados -m HOST:PORT[,HOST:PORT...] \\
        -p POOL put NAME FILE | get NAME FILE | ls | rm NAME \\
        | stat NAME | df | bench SECONDS write [--size N] \\
        | mksnap SNAP | rmsnap SNAP | lssnap | report [OUT.json] \\
        | trace export [OUT.json] | netstat

    Reads honor -s/--snap SNAPNAME (rados -s, snapshot reads).
    `report` writes the one-call diagnostics bundle (status, health,
    df, osd dump, recent cluster log, crash list) as JSON.
    `trace export` drives a few probe ops and writes the client's
    flight-recorder timeline as Chrome-trace / Perfetto JSON.
    `watch-events` streams the mon's committed cluster events live
    (the `ceph -w` analog; --from N resumes a cursor).
    `perf history SERIES [LABEL]` renders the mon's downsampled
    history rows for one series (--window seconds).
    `netstat` renders the cluster heartbeat RTT matrix, the slow
    peer pairs, and per-daemon wire rates (`net status`).
"""

from __future__ import annotations

import argparse
import asyncio
import sys
import time

from ..client.rados import RadosClient


async def _run(args) -> int:
    ctx = None
    if args.cmd == "trace":
        # the trace verb's probe ops must all be retained whatever
        # the production sampling default is
        from ..utils.context import Context
        ctx = Context("client.trace",
                      conf_overrides={"flight_recorder_sample": 1})
    client = RadosClient(args.mon.split(","), ctx=ctx)
    await client.connect()
    try:
        if args.cmd == "df":
            # real per-pool usage from the cluster's PGMap digest
            # (the reference's `rados df` table)
            out = await client.mon_command("df")
            cols = ("POOL_NAME", "OBJECTS", "BYTES", "DEGRADED",
                    "MISPLACED", "RD_OPS/S", "WR_OPS/S")
            fmt = "%-16s %10s %12s %9s %10s %9s %9s"
            print(fmt % cols)
            for row in out.get("pools") or []:
                print(fmt % (row["name"], row["objects"],
                             row["bytes"], row["degraded"],
                             row["misplaced"],
                             "%.1f" % row["read_ops_s"],
                             "%.1f" % row["write_ops_s"]))
            total = out.get("total") or {}
            print(fmt % ("TOTAL", total.get("objects", 0),
                         total.get("bytes", 0),
                         total.get("degraded", 0),
                         total.get("misplaced", 0), "", ""))
            osds = out.get("osds") or []
            if osds:
                # raw-capacity axis: per-OSD store statfs (bytes on
                # the device, not logical x replication)
                ofmt = "%-10s %14s %14s %14s %7s"
                print()
                print(ofmt % ("OSD", "USED", "AVAIL", "TOTAL",
                              "%USE"))
                for row in osds:
                    print(ofmt % (row["name"], row["used"],
                                  row["available"], row["total"],
                                  "%.2f" % (100.0 * row["util"])))
                print(ofmt % ("RAW TOTAL", out.get("raw_used", 0),
                              "", out.get("raw_total", 0), ""))
            if not out.get("stats_available"):
                print("(no mgr digest yet: counts read as zero "
                      "until a manager reports)")
            return 0
        if args.cmd == "report":
            # one-call diagnostics bundle (the `ceph report` role):
            # every mon-served surface in one JSON artifact — the
            # thing you attach to a bug
            import json

            rep = {"generated_at": time.time()}
            for key, prefix, kw in (
                    ("status", "status", {}),
                    ("health", "health", {}),
                    ("df", "df", {}),
                    ("osd_dump", "osd dump", {}),
                    ("log_last", "log last", {"n": 100}),
                    ("crashes", "crash ls", {})):
                try:
                    rep[key] = await client.mon_command(prefix, **kw)
                except Exception as e:
                    rep[key] = {"error": repr(e)}
            blob = json.dumps(rep, indent=2, default=str,
                              sort_keys=True)
            if args.args:
                with open(args.args[0], "w") as f:
                    f.write(blob + "\n")
                print("wrote report to %s" % args.args[0])
            else:
                print(blob)
            return 0
        if args.cmd == "trace":
            # `rados -p POOL trace export [OUT.json]`: drive a few
            # probe writes+reads through the cluster and export this
            # client's flight-recorder ring as Chrome-trace JSON (the
            # client-visible slice of each op's span; daemon-side
            # lanes come from the per-daemon admin sockets'
            # dump_flight_recorder or the harness's export_trace)
            import json

            sub = args.args[0] if args.args else "export"
            if sub != "export":
                print("unknown trace subcommand %r" % sub,
                      file=sys.stderr)
                return 2
            out_path = args.args[1] if len(args.args) > 1 else None
            io = client.io_ctx(args.pool)
            n_probe = 8
            payload = b"\x42" * 4096
            for i in range(n_probe):
                await io.write_full("trace-probe-%d" % i, payload)
                await io.read("trace-probe-%d" % i)
            await asyncio.gather(
                *[io.remove("trace-probe-%d" % i)
                  for i in range(n_probe)],
                return_exceptions=True)
            await asyncio.sleep(0.1)    # last replies retire
            from ..trace import recorder as flight
            fr = client.ctx.flight_recorder
            doc = flight.chrome_trace(
                {client.msgr.entity:
                 [dict(r) for r in fr.records]},
                device=flight.device_records())
            blob = json.dumps(doc)
            if out_path:
                with open(out_path, "w") as f:
                    f.write(blob + "\n")
                print("wrote %d trace events to %s (open in "
                      "https://ui.perfetto.dev)"
                      % (len(doc["traceEvents"]), out_path))
            else:
                print(blob)
            return 0
        if args.cmd == "watch-events":
            # live committed-event stream (`ceph -w`): each row once,
            # in seq order, surviving mon failover via the cursor
            def show(row):
                print("%d %.3f [%s] %s"
                      % (row.get("seq", 0), row.get("stamp", 0.0),
                         row.get("type"), row.get("message")))
            client.watch_events(show, start=args.from_seq)
            await asyncio.Event().wait()     # stream until ^C
            return 0
        if args.cmd == "netstat":
            # `rados netstat`: the cluster heartbeat RTT matrix +
            # per-daemon wire rates, served from the mon's beacon
            # soft state and mgr digest (`net status`)
            out = await client.mon_command("net status")
            matrix = out.get("rtt_ms") or {}
            names = sorted(set(matrix)
                           | {p for row in matrix.values()
                              for p in row})
            if names:
                fmt = "%-8s" + " %8s" * len(names)
                print(fmt % ("RTT_MS", *names))
                for src in sorted(matrix):
                    row = matrix[src]
                    print(fmt % (src, *[
                        ("%.2f" % row[d]) if d in row else "-"
                        for d in names]))
            else:
                print("(no heartbeat RTT reports yet)")
            slow = out.get("slow_pairs") or []
            if slow:
                print("slow pairs: %s" % ", ".join(slow))
            daemons = out.get("daemons") or {}
            if daemons:
                dfmt = "%-8s %10s %10s %8s %8s %7s %9s"
                print()
                print(dfmt % ("DAEMON", "TX/S", "RX/S", "RESEND",
                              "REPLAY", "QDEPTH", "RTTMAX_MS"))
                for name in sorted(daemons):
                    row = daemons[name]
                    print(dfmt % (
                        name,
                        "%.0f" % row.get("tx_Bps", 0.0),
                        "%.0f" % row.get("rx_Bps", 0.0),
                        row.get("resends", 0),
                        row.get("replays", 0),
                        row.get("queue_depth", 0),
                        "%.2f" % row.get("rtt_max_ms", 0.0)))
            elif not out.get("daemons_available"):
                print("(no mgr digest yet: per-daemon wire rates "
                      "unavailable)")
            return 0
        if args.cmd == "perf":
            if not args.args or args.args[0] != "history":
                print("unknown perf subcommand %r"
                      % (args.args[:1] or [""])[0], file=sys.stderr)
                return 2
            if len(args.args) < 2:
                out = await client.mon_command("perf history")
                for series, label in out.get("series") or []:
                    print("%s%s" % (series,
                                    "[%s]" % label if label else ""))
                return 0
            kw = {"series": args.args[1], "window": args.window}
            if len(args.args) > 2:
                kw["label"] = args.args[2]
            out = await client.mon_command("perf history", **kw)
            print("%s%s tier=%ss window=%ss"
                  % (out["series"],
                     "[%s]" % out["label"] if out["label"] else "",
                     out.get("tier_s"), out.get("window")))
            fmt = "%12s %5s %12s %12s %12s %12s"
            print(fmt % ("T", "N", "MIN", "MAX", "AVG", "LAST"))
            for t, n, lo, hi, avg, last in out.get("rows") or []:
                print(fmt % (t, n, lo, hi, avg, last))
            return 0
        io = client.io_ctx(args.pool)
        if args.snap:
            if args.cmd in ("put", "rm", "bench", "mksnap", "rmsnap"):
                print("error: cannot write with -s (snapshots are "
                      "read-only)", file=sys.stderr)
                return 2
            try:
                io.set_read_snap(io.snap_lookup(args.snap))
            except KeyError:
                print("error: no snapshot %r in pool %r"
                      % (args.snap, args.pool), file=sys.stderr)
                return 2
        if args.cmd == "mksnap":
            sid = await io.snap_create(args.args[0])
            print("created pool snapshot %r (snapid %d)"
                  % (args.args[0], sid))
            return 0
        if args.cmd == "rmsnap":
            await io.snap_remove(args.args[0])
            print("removed pool snapshot %r" % args.args[0])
            return 0
        if args.cmd == "lssnap":
            snaps = io.snap_list()
            for sid in sorted(snaps):
                print("%d\t%s" % (sid, snaps[sid]))
            print("%d snaps" % len(snaps))
            return 0
        if args.cmd == "put":
            with open(args.args[1], "rb") as f:
                data = f.read()
            await io.write_full(args.args[0], data)
            print("wrote %d bytes to %s" % (len(data), args.args[0]))
        elif args.cmd == "get":
            data = await io.read(args.args[0])
            with open(args.args[1], "wb") as f:
                f.write(data)
            print("read %d bytes from %s" % (len(data), args.args[0]))
        elif args.cmd == "ls":
            for name in await client.list_objects(io.pool_id):
                print(name)
        elif args.cmd == "rm":
            await io.remove(args.args[0])
        elif args.cmd == "stat":
            size = await io.stat(args.args[0])
            print("%s size %d" % (args.args[0], size))
        elif args.cmd == "bench":
            seconds = int(args.args[0])
            size = args.size
            payload = bytes(size)
            deadline = time.perf_counter() + seconds
            n = 0
            lat = []
            inflight = []
            while time.perf_counter() < deadline:
                t0 = time.perf_counter()
                inflight.append((t0, asyncio.ensure_future(
                    io.write_full("bench_%d" % n, payload))))
                n += 1
                if len(inflight) >= 16:
                    t0w, fut = inflight.pop(0)
                    await fut
                    lat.append(time.perf_counter() - t0w)
            for t0w, fut in inflight:
                await fut
                lat.append(time.perf_counter() - t0w)
            dur = seconds
            print("wrote %d x %dB objects in %ds: %.1f op/s, "
                  "%.2f MiB/s, avg lat %.1f ms"
                  % (n, size, dur, n / dur,
                     n * size / dur / (1 << 20),
                     1000 * sum(lat) / max(1, len(lat))))
            # cleanup
            await asyncio.gather(*[io.remove("bench_%d" % i)
                                   for i in range(n)],
                                 return_exceptions=True)
        else:
            print("unknown command %r" % args.cmd, file=sys.stderr)
            return 2
        return 0
    finally:
        await client.shutdown()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="rados")
    p.add_argument("-m", "--mon", required=True,
                   help="monitor address(es), comma separated")
    p.add_argument("-p", "--pool", default="rbd")
    p.add_argument("-s", "--snap", default=None,
                   help="read from this pool snapshot")
    p.add_argument("--size", type=int, default=4096)
    p.add_argument("--window", type=float, default=600.0,
                   help="perf history window, seconds")
    p.add_argument("--from", dest="from_seq", type=int, default=0,
                   help="watch-events: resume after this seq")
    p.add_argument("cmd")
    p.add_argument("args", nargs="*")
    args = p.parse_args(argv)
    return asyncio.run(_run(args))


if __name__ == "__main__":
    sys.exit(main())
