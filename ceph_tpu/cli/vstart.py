"""vstart: a one-command dev cluster (mons + N osds) in one process.

Analog of src/vstart.sh for this framework, now layered on the shared
``ceph_tpu.testing.LocalCluster`` harness: boots the monitor quorum
and N MemStore OSDs on loopback TCP, optionally creates pools, then
runs a put/get smoke workload, a seeded thrash run (the teuthology
thrasher analog), or stays up serving until interrupted.

    python -m ceph_tpu.cli.vstart --osds 3 --smoke
    python -m ceph_tpu.cli.vstart --osds 3 --pool data --serve
    python -m ceph_tpu.cli.vstart --osds 3 --mons 3 \\
        --thrash 5 --seed 42
"""

from __future__ import annotations

import argparse
import asyncio
import sys

from ..testing.cluster import LocalCluster


async def run(args) -> int:
    cluster = LocalCluster(n_osds=args.osds, n_mons=args.mons,
                           seed=args.seed)
    await cluster.start()
    for mon in cluster.mons:
        print("%s at %s" % (mon.name, mon.addr))
    for osd in cluster.osds:
        print("osd.%d at %s" % (osd.whoami, osd.msgr.addr))
    client = cluster.client
    print("cluster up at epoch %d" % client.osdmap.epoch)

    exporter = None
    if args.exporter_port:
        from ..utils.exporter import cluster_exporter

        mon0 = cluster.mons[0]
        exporter = cluster_exporter(mon0.ctx, mon0)
        eaddr = await exporter.start("127.0.0.1", args.exporter_port)
        print("prometheus exporter at http://%s/metrics" % eaddr)

    for name in args.pool or []:
        pid = await cluster.create_pool(name, pg_num=args.pg_num)
        print("pool %s id=%d" % (name, pid))

    rc = 0
    if args.smoke:
        pid = await cluster.create_pool("smoke", pg_num=8)
        io = client.io_ctx("smoke")
        payload = b"vstart smoke payload " * 64
        for i in range(16):
            await io.write_full("obj-%d" % i, payload + b"%d" % i)
        bad = 0
        for i in range(16):
            got = await io.read("obj-%d" % i)
            if got != payload + b"%d" % i:
                bad += 1
        status = await client.mon_command("status")
        print("smoke: 16 objects written+read, %d mismatches; "
              "status=%s" % (bad, status))
        rc = 1 if bad else 0
    elif args.thrash:
        from ..testing.thrasher import ClusterThrasher, Workload

        pid = await cluster.create_pool("thrash", pg_num=8)
        await cluster.wait_health(pid)
        wl = Workload(client.io_ctx("thrash"),
                      seed=args.seed or 0).start()
        thrasher = ClusterThrasher(cluster, seed=args.seed or 0,
                                   rounds=args.thrash)
        print("thrash plan (seed=%s): %s"
              % (args.seed, thrasher.plan))
        try:
            await thrasher.run(pid, wl)
            print("thrash: %d rounds clean, %d acked writes intact"
                  % (args.thrash, len(wl.acked)))
        except Exception as e:
            # self-reporting failure: the full diagnostics bundle
            # (per-daemon perf/ops/ring tails, mon health/log/crash
            # state, pgmap digest, merged op timelines) lands in a
            # temp file — the artifact to attach to the bug
            import json
            import os
            import tempfile

            fd, path = tempfile.mkstemp(prefix="ceph_tpu_diag_",
                                        suffix=".json")
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(cluster.collect_diagnostics(), f,
                              indent=2, default=str, sort_keys=True)
            except Exception as de:
                path = "(diagnostics collection failed: %r)" % de
            # the flight-recorder timeline rides beside the bundle:
            # the Perfetto-openable artifact showing WHERE the failed
            # round's time went (queue wait vs device vs sub-op RTT)
            tfd, tpath = tempfile.mkstemp(
                prefix="ceph_tpu_diag_", suffix="_trace.json")
            os.close(tfd)
            try:
                cluster.export_trace(path=tpath)
            except Exception as te:
                tpath = "(trace export failed: %r)" % te
            print("thrash FAILED (replay with --seed %s): %s\n"
                  "diagnostics bundle: %s\n"
                  "flight-recorder trace (open in Perfetto): %s"
                  % (args.seed, e, path, tpath))
            rc = 1
        finally:
            await wl.stop()
    elif args.serve:
        print("serving; ctrl-c to stop")
        try:
            while True:
                await asyncio.sleep(3600)
        except (KeyboardInterrupt, asyncio.CancelledError):
            pass

    if exporter is not None:
        await exporter.stop()
    await cluster.stop()
    return rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="vstart")
    p.add_argument("--osds", type=int, default=3)
    p.add_argument("--mons", type=int, default=1)
    p.add_argument("--pool", action="append")
    p.add_argument("--pg-num", type=int, default=32)
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--serve", action="store_true")
    p.add_argument("--thrash", type=int, default=0, metavar="ROUNDS",
                   help="run ROUNDS of seeded cluster thrashing "
                        "under a live workload")
    p.add_argument("--seed", type=int, default=None,
                   help="deterministic seed for fault injection / "
                        "thrash scheduling")
    p.add_argument("--exporter-port", type=int, default=0,
                   help="serve Prometheus metrics on this port")
    args = p.parse_args(argv)
    return asyncio.run(run(args))


if __name__ == "__main__":
    sys.exit(main())
