"""vstart: a one-command dev cluster (mon + N osds) in one process.

Analog of src/vstart.sh for this framework: boots the monitor and N
MemStore OSDs on loopback TCP, optionally creates pools, then either
runs a put/get smoke workload or stays up serving until interrupted.

    python -m ceph_tpu.cli.vstart --osds 3 --smoke
    python -m ceph_tpu.cli.vstart --osds 3 --pool data --serve
"""

from __future__ import annotations

import argparse
import asyncio
import sys

from ..client import RadosClient
from ..mon import Monitor
from ..osd.daemon import OSD
from ..utils.context import Context

FAST_CONF = {
    "heartbeat_interval": 0.5,
    "heartbeat_grace": 3.0,
    "mon_osd_down_out_interval": 10.0,
    "mon_osd_min_down_reporters": 1,
}


async def run(args) -> int:
    mon = Monitor(Context("mon", conf_overrides=FAST_CONF))
    addr = await mon.start()
    print("mon.0 at %s" % addr)
    osds = []
    for i in range(args.osds):
        osd = OSD(i, addr, Context("osd.%d" % i,
                                   conf_overrides=FAST_CONF))
        oaddr = await osd.start()
        osds.append(osd)
        print("osd.%d at %s" % (i, oaddr))
    for osd in osds:
        await osd.wait_for_boot()
    client = RadosClient(addr)
    await client.connect()
    print("cluster up at epoch %d" % client.osdmap.epoch)

    for name in args.pool or []:
        out = await client.mon_command("osd pool create", pool=name,
                                       pg_num=args.pg_num,
                                       size=min(3, args.osds))
        print("pool %s id=%d" % (name, out["pool_id"]))

    rc = 0
    if args.smoke:
        out = await client.mon_command("osd pool create", pool="smoke",
                                       pg_num=8,
                                       size=min(3, args.osds))
        await client.wait_for_epoch(mon.osdmap.epoch)
        io = client.io_ctx("smoke")
        payload = b"vstart smoke payload " * 64
        for i in range(16):
            await io.write_full("obj-%d" % i, payload + b"%d" % i)
        bad = 0
        for i in range(16):
            got = await io.read("obj-%d" % i)
            if got != payload + b"%d" % i:
                bad += 1
        status = await client.mon_command("status")
        print("smoke: 16 objects written+read, %d mismatches; "
              "status=%s" % (bad, status))
        rc = 1 if bad else 0
    elif args.serve:
        print("serving; ctrl-c to stop")
        try:
            while True:
                await asyncio.sleep(3600)
        except (KeyboardInterrupt, asyncio.CancelledError):
            pass

    await client.shutdown()
    for osd in osds:
        await osd.shutdown()
    await mon.shutdown()
    return rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="vstart")
    p.add_argument("--osds", type=int, default=3)
    p.add_argument("--pool", action="append")
    p.add_argument("--pg-num", type=int, default=32)
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--serve", action="store_true")
    args = p.parse_args(argv)
    return asyncio.run(run(args))


if __name__ == "__main__":
    sys.exit(main())
