"""vstart: a one-command dev cluster (mon + N osds) in one process.

Analog of src/vstart.sh for this framework: boots the monitor and N
MemStore OSDs on loopback TCP, optionally creates pools, then either
runs a put/get smoke workload or stays up serving until interrupted.

    python -m ceph_tpu.cli.vstart --osds 3 --smoke
    python -m ceph_tpu.cli.vstart --osds 3 --pool data --serve
"""

from __future__ import annotations

import argparse
import asyncio
import sys

from ..client import RadosClient
from ..mon import Monitor
from ..osd.daemon import OSD
from ..utils.context import Context

FAST_CONF = {
    "heartbeat_interval": 0.5,
    "heartbeat_grace": 3.0,
    "mon_osd_down_out_interval": 10.0,
    "mon_osd_min_down_reporters": 1,
}


def _free_ports(n):
    import socket

    socks = []
    for _ in range(n):
        so = socket.socket()
        so.bind(("127.0.0.1", 0))
        socks.append(so)
    ports = [so.getsockname()[1] for so in socks]
    for so in socks:
        so.close()
    return ports


async def run(args) -> int:
    mons = []
    if args.mons > 1:
        monmap = [("mon.%d" % i, "127.0.0.1:%d" % po)
                  for i, po in enumerate(_free_ports(args.mons))]
        for name, _a in monmap:
            mon = Monitor(Context(name, conf_overrides=FAST_CONF),
                          name=name, monmap=monmap)
            await mon.start()
            mons.append(mon)
            print("%s at %s" % (name, mon.addr))
        # wait for a leader before using the cluster
        import asyncio as _aio

        for _ in range(200):
            if any(m.is_leader() and m.mpaxos.active for m in mons):
                break
            await _aio.sleep(0.05)
        addr = [a for _n, a in monmap]
        mon = mons[0]
    else:
        mon = Monitor(Context("mon", conf_overrides=FAST_CONF))
        addr = await mon.start()
        mons = [mon]
        print("mon.0 at %s" % addr)
    osds = []
    for i in range(args.osds):
        osd = OSD(i, addr, Context("osd.%d" % i,
                                   conf_overrides=FAST_CONF))
        oaddr = await osd.start()
        osds.append(osd)
        print("osd.%d at %s" % (i, oaddr))
    for osd in osds:
        await osd.wait_for_boot()
    client = RadosClient(addr)
    await client.connect()
    print("cluster up at epoch %d" % client.osdmap.epoch)

    exporter = None
    if args.exporter_port:
        from ..utils.exporter import cluster_exporter

        exporter = cluster_exporter(mon.ctx, mon)
        eaddr = await exporter.start("127.0.0.1", args.exporter_port)
        print("prometheus exporter at http://%s/metrics" % eaddr)

    for name in args.pool or []:
        out = await client.mon_command("osd pool create", pool=name,
                                       pg_num=args.pg_num,
                                       size=min(3, args.osds))
        print("pool %s id=%d" % (name, out["pool_id"]))

    rc = 0
    if args.smoke:
        out = await client.mon_command("osd pool create", pool="smoke",
                                       pg_num=8,
                                       size=min(3, args.osds))
        await client.wait_for_epoch(mon.osdmap.epoch)
        io = client.io_ctx("smoke")
        payload = b"vstart smoke payload " * 64
        for i in range(16):
            await io.write_full("obj-%d" % i, payload + b"%d" % i)
        bad = 0
        for i in range(16):
            got = await io.read("obj-%d" % i)
            if got != payload + b"%d" % i:
                bad += 1
        status = await client.mon_command("status")
        print("smoke: 16 objects written+read, %d mismatches; "
              "status=%s" % (bad, status))
        rc = 1 if bad else 0
    elif args.serve:
        print("serving; ctrl-c to stop")
        try:
            while True:
                await asyncio.sleep(3600)
        except (KeyboardInterrupt, asyncio.CancelledError):
            pass

    if exporter is not None:
        await exporter.stop()
    await client.shutdown()
    for osd in osds:
        await osd.shutdown()
    for m in mons:
        await m.shutdown()
    return rc


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="vstart")
    p.add_argument("--osds", type=int, default=3)
    p.add_argument("--mons", type=int, default=1)
    p.add_argument("--pool", action="append")
    p.add_argument("--pg-num", type=int, default=32)
    p.add_argument("--smoke", action="store_true")
    p.add_argument("--serve", action="store_true")
    p.add_argument("--exporter-port", type=int, default=0,
                   help="serve Prometheus metrics on this port")
    args = p.parse_args(argv)
    return asyncio.run(run(args))


if __name__ == "__main__":
    sys.exit(main())
