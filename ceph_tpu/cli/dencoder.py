"""dencoder: encode/decode/inspect the framework's wire structs.

Analog of src/tools/ceph-dencoder (the corpus-checking tool the
reference uses to guarantee rolling-upgrade compatibility): a typed
registry of every versioned struct, with

    list                      every registered type
    type <name> encode <json> JSON value -> hex blob (stdout)
    type <name> decode <hex>  hex blob -> JSON dump
    type <name> version       writer version / compat floor
    corpus <dir>              decode every <type>.<n>.hex under dir
                              and fail on any change vs the pinned
                              .json dump beside it (the ceph-object-
                              corpus check)

Hex in/out so blobs survive shell pipes; '-' reads stdin.
"""

from __future__ import annotations

import json
import os
import sys


def _to_jsonable(v):
    if isinstance(v, bytes):
        return {"__hex__": v.hex()}
    if isinstance(v, dict):
        return {(k.hex() if isinstance(k, bytes) else k):
                _to_jsonable(val) for k, val in v.items()}
    if isinstance(v, (list, tuple)):
        return [_to_jsonable(x) for x in v]
    return v


def _from_jsonable(v):
    if isinstance(v, dict):
        if set(v) == {"__hex__"}:
            return bytes.fromhex(v["__hex__"])
        return {k: _from_jsonable(val) for k, val in v.items()}
    if isinstance(v, list):
        return [_from_jsonable(x) for x in v]
    return v


class _Type:
    def __init__(self, name, version, compat, enc, dec):
        self.name = name
        self.version = version
        self.compat = compat
        self.enc = enc          # jsonable-value -> bytes
        self.dec = dec          # bytes -> jsonable-value


def _registry() -> dict[str, _Type]:
    from ..osd.osdmap import Incremental, OSDMap
    from ..osd.pg import LogEntry, PGInfo
    from ..msg.message import (MSG_STRUCT_COMPAT, MSG_STRUCT_V,
                               decode_message, encode_message)
    from ..utils import denc

    types: dict[str, _Type] = {}

    def add(name, version, compat, enc, dec):
        types[name] = _Type(name, version, compat, enc, dec)

    add("osdmap", OSDMap.STRUCT_V, OSDMap.STRUCT_COMPAT,
        lambda v: OSDMap.from_dict(v).encode(),
        lambda b: OSDMap.decode(b).to_dict())
    add("osdmap_inc", Incremental.STRUCT_V, Incremental.STRUCT_COMPAT,
        lambda v: Incremental.from_dict(v).encode(),
        lambda b: Incremental.decode(b).to_dict())
    add("pg_info", 1, 1,
        lambda v: denc.encode(PGInfo.from_wire(v).to_wire()),
        lambda b: PGInfo.from_wire(denc.decode(b)).to_wire())
    add("pg_log_entry", 1, 1,
        lambda v: denc.encode(LogEntry.from_wire(v).to_wire()),
        lambda b: LogEntry.from_wire(denc.decode(b)).to_wire())
    add("message", MSG_STRUCT_V, MSG_STRUCT_COMPAT,
        lambda v: encode_message(_msg_from_dump(v)),
        lambda b: _msg_dump(decode_message(b)))
    add("denc", 1, 1, denc.encode, denc.decode)
    return types


def _msg_dump(m) -> dict:
    return {"type": m.TYPE, "seq": m.seq, "src": m.src,
            "fields": m.to_wire()}


def _msg_from_dump(d: dict):
    from ..msg.message import _REGISTRY

    cls = _REGISTRY[d["type"]]
    m = cls.from_wire(d["fields"])
    m.seq = d.get("seq", 0)
    m.src = d.get("src", "")
    return m


def _read_arg(arg: str) -> str:
    return sys.stdin.read() if arg == "-" else arg


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    types = _registry()
    if not argv or argv[0] in ("-h", "--help"):
        print(__doc__)
        return 0
    cmd = argv.pop(0)
    if cmd == "list":
        for t in sorted(types.values(), key=lambda t: t.name):
            print("%-14s v%d compat %d" % (t.name, t.version,
                                           t.compat))
        return 0
    if cmd == "corpus":
        if not argv:
            print("usage: dencoder corpus <dir>", file=sys.stderr)
            return 2
        return _corpus(types, argv[0])
    if cmd != "type" or len(argv) < 2:
        print("usage: dencoder list | corpus <dir> | "
              "type <name> encode|decode|version <arg>",
              file=sys.stderr)
        return 2
    t = types.get(argv[0])
    if t is None:
        print("unknown type %r (try: dencoder list)" % argv[0],
              file=sys.stderr)
        return 2
    action = argv[1]
    if action == "version":
        print("v%d compat %d" % (t.version, t.compat))
        return 0
    if action in ("encode", "decode") and len(argv) < 3:
        print("usage: dencoder type <name> %s <arg|->" % action,
              file=sys.stderr)
        return 2
    if action == "encode":
        value = _from_jsonable(json.loads(_read_arg(argv[2])))
        print(t.enc(value).hex())
        return 0
    if action == "decode":
        blob = bytes.fromhex(_read_arg(argv[2]).strip())
        print(json.dumps(_to_jsonable(t.dec(blob)), indent=2,
                         sort_keys=True))
        return 0
    print("unknown action %r" % action, file=sys.stderr)
    return 2


def _corpus(types, root: str) -> int:
    """Every pinned blob must still decode to its pinned dump AND
    re-encode deterministically — the rolling-upgrade guarantee."""
    failures = 0
    checked = 0
    for fn in sorted(os.listdir(root)):
        if not fn.endswith(".hex"):
            continue
        tname = fn.split(".")[0]
        t = types.get(tname)
        if t is None:
            print("SKIP %s (no type %r)" % (fn, tname))
            continue
        blob = bytes.fromhex(
            open(os.path.join(root, fn)).read().strip())
        jpath = os.path.join(root, fn[:-4] + ".json")
        checked += 1
        if not os.path.exists(jpath):
            failures += 1
            print("FAIL %s: missing pinned dump %s" % (fn, jpath))
            continue
        want = json.load(open(jpath))
        # JSON round-trip normalizes key types (int dict keys print
        # as strings) so the comparison is representation-stable
        got = json.loads(json.dumps(_to_jsonable(t.dec(blob))))
        if got != want:
            failures += 1
            print("FAIL %s: decode drifted" % fn)
            continue
        # re-encode determinism: the ENCODER half of the upgrade
        # guarantee — new code must still produce the pinned bytes
        # for the pinned logical value
        try:
            again = t.enc(_from_jsonable(want))
        except Exception as e:
            failures += 1
            print("FAIL %s: re-encode raised %s" % (fn, e))
            continue
        if again != blob:
            failures += 1
            print("FAIL %s: re-encode drifted (%d vs %d bytes)"
                  % (fn, len(again), len(blob)))
        else:
            print("OK   %s" % fn)
    print("%d checked, %d failed" % (checked, failures))
    return 1 if failures or not checked else 0


if __name__ == "__main__":
    raise SystemExit(main())
