"""monstore-tool: inspect and rescue a monitor's KV store.

Analog of the reference's ceph-monstore-tool (src/tools/
ceph_monstore_tool.cc): offline access to a mon store for debugging
and disaster recovery.

    python -m ceph_tpu.cli.monstore_tool <store.db> <cmd>

    dump              store overview: last map epoch, paxos bounds,
                      service-state sizes, key count
    get <key>         print one raw key (hex + best-effort decode)
    list [prefix]     list keys (optionally under a prefix)
    get-osdmap [-e N] print the stored full OSDMap (latest or epoch N)
    show-config       the centralized config service's state
    show-auth         auth registry entities (keys REDACTED)
    show-log [n]      last n cluster-log lines (default 20)

Works on the SQLite store files real monitors write (`store=` /
mon data dirs); read-only."""

from __future__ import annotations

import argparse
import json
import os
import sqlite3
import sys

from ..store.kv import SQLiteKV
from ..utils import denc


class _ROStore(SQLiteKV):
    """Truly read-only open: a forensic tool must neither create a
    fresh empty DB on a mistyped path (reporting 'store is empty' to
    an operator mid-disaster-recovery) nor touch WAL/journal state on
    a read-only-mounted host."""

    def open(self) -> None:
        if not os.path.exists(self.path):
            raise FileNotFoundError(self.path)
        self._conn = sqlite3.connect("file:%s?mode=ro" % self.path,
                                     uri=True,
                                     check_same_thread=False)


def _open(path: str) -> SQLiteKV:
    db = _ROStore(path)
    db.open()
    return db


def _decode_maybe(v: bytes):
    try:
        if v[:1] == b"V":
            from ..utils.denc import decode_versioned

            return decode_versioned(v, 255)[1]
        return denc.decode(v)
    except Exception:
        return {"__hex__": v[:64].hex() + ("..." if len(v) > 64
                                           else "")}


def _jsonable(v):
    if isinstance(v, bytes):
        return {"__hex__": v.hex()}
    if isinstance(v, dict):
        return {(k.hex() if isinstance(k, bytes) else str(k)):
                _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return v


def cmd_dump(db: SQLiteKV) -> dict:
    keys = [k for k, _v in db.iterate()]
    out: dict = {"keys": len(keys)}
    raw = db.get(b"osdmap:last_epoch")
    if raw is not None:
        out["osdmap_last_epoch"] = denc.decode(raw)
    # paxos.py key shape: b"paxos:v%016d"
    paxos_vers = sorted(int(k[len(b"paxos:v"):])
                        for k in keys
                        if k.startswith(b"paxos:v"))
    if paxos_vers:
        out["paxos_first"] = paxos_vers[0]
        out["paxos_last"] = paxos_vers[-1]
    for label, key in (("config", b"svc:config"),
                       ("auth", b"svc:auth"), ("log", b"svc:log"),
                       ("crash", b"svc:crash")):
        raw = db.get(key)
        if raw is not None:
            v = denc.decode(raw)
            if label == "log" and isinstance(v, dict):
                v = v.get("entries") or []
            out["svc_%s_entries" % label] = len(v)
    fulls = [k for k in keys if k.startswith(b"osdmap:full:")]
    incs = [k for k in keys if k.startswith(b"osdmap:inc:")]
    out["osdmap_fulls"] = len(fulls)
    out["osdmap_incs"] = len(incs)
    return out


def _build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="monstore_tool",
        description="inspect a monitor's KV store (read-only)")
    p.add_argument("store", help="path to the mon store .db file")
    sub = p.add_subparsers(dest="cmd", required=True)
    sub.add_parser("dump")
    lp = sub.add_parser("list")
    lp.add_argument("prefix", nargs="?", default="")
    gp = sub.add_parser("get")
    gp.add_argument("key")
    mp = sub.add_parser("get-osdmap")
    mp.add_argument("-e", "--epoch", type=int, default=None)
    sub.add_parser("show-config")
    sub.add_parser("show-auth")
    lg = sub.add_parser("show-log")
    lg.add_argument("n", nargs="?", type=int, default=20)
    sub.add_parser("show-crashes")
    return p


def main(argv=None) -> int:
    try:
        args = _build_parser().parse_args(argv)
    except SystemExit as e:
        return int(e.code or 0)
    try:
        db = _open(args.store)
    except FileNotFoundError:
        print("no such store: %s" % args.store, file=sys.stderr)
        return 1
    try:
        if args.cmd == "dump":
            print(json.dumps(cmd_dump(db), indent=2))
            return 0
        if args.cmd == "list":
            pref = args.prefix.encode()
            for k, v in db.iterate(pref,
                                   pref + b"\xff" if pref else None):
                print("%s  (%d bytes)" % (k.decode("latin1"),
                                          len(v)))
            return 0
        if args.cmd == "get":
            v = db.get(args.key.encode())
            if v is None:
                print("no such key", file=sys.stderr)
                return 1
            print(json.dumps(_jsonable(_decode_maybe(v)), indent=2))
            return 0
        if args.cmd == "get-osdmap":
            if args.epoch is not None:
                epoch = args.epoch
            else:
                raw = db.get(b"osdmap:last_epoch")
                if raw is None:
                    print("store has no osdmap", file=sys.stderr)
                    return 1
                epoch = denc.decode(raw)
            blob = db.get(b"osdmap:full:%016d" % epoch)
            if blob is None:
                print("no full map at epoch %d" % epoch,
                      file=sys.stderr)
                return 1
            from ..osd.osdmap import OSDMap

            print(json.dumps(_jsonable(OSDMap.decode(blob).to_dict()),
                             indent=2))
            return 0
        if args.cmd == "show-config":
            raw = db.get(b"svc:config")
            print(json.dumps(_jsonable(denc.decode(raw))
                             if raw else {}, indent=2))
            return 0
        if args.cmd == "show-auth":
            raw = db.get(b"svc:auth")
            ents = denc.decode(raw) if raw else {}
            red = {e: {"key": "REDACTED",
                       "caps": dict(v.get("caps") or {})}
                   for e, v in ents.items()}
            print(json.dumps(red, indent=2))
            return 0
        if args.cmd == "show-log":
            raw = db.get(b"svc:log")
            lines = denc.decode(raw) if raw else []
            if isinstance(lines, dict):     # clog-era format
                lines = lines.get("entries") or []
            for e in lines[-args.n:]:
                print("%(stamp).3f %(who)s %(level)s: %(message)s"
                      % e)
            return 0
        if args.cmd == "show-crashes":
            raw = db.get(b"svc:crash")
            reports = denc.decode(raw) if raw else {}
            for cid in sorted(reports):
                r = reports[cid]
                print("%s %s %s: %s%s"
                      % (cid, r.get("entity"), r.get("exc_type"),
                         r.get("exc_msg"),
                         " [archived]" if r.get("archived") else ""))
            return 0
        return 2
    finally:
        db.close()


if __name__ == "__main__":
    raise SystemExit(main())
