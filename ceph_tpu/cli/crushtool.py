"""crushtool: compile, decompile and test crush maps.

Analog of src/tools/crushtool.cc over the same text format:

    python -m ceph_tpu.cli.crushtool -c map.txt -o map.bin
    python -m ceph_tpu.cli.crushtool -d map.bin [-o map.txt]
    python -m ceph_tpu.cli.crushtool -i map.bin --test --rule 0 \\
        --num-rep 3 [--min-x 0 --max-x 1023] [--show-utilization]
    python -m ceph_tpu.cli.crushtool --build --num-osds 12 \\
        host straw2 4 root straw2 0 -o map.bin

The binary form is the framework's denc encoding of the map (the
to_dict schema), not the reference's wire format.
"""

from __future__ import annotations

import argparse
import json
import sys

from ..models.crushcompiler import ALG_BY_NAME, compile, decompile
from ..models.crushmap import (CHOOSELEAF_FIRSTN, EMIT, TAKE, CrushMap)
from ..models.crushtester import CrushTester
from ..utils import denc


def load_map(path: str) -> CrushMap:
    with open(path, "rb") as f:
        raw = f.read()
    try:
        return CrushMap.from_dict(denc.decode(raw))
    except Exception:
        return compile(raw.decode())


def save_map(m: CrushMap, path: str | None, text: bool = False) -> None:
    if text:
        data = decompile(m).encode()
    else:
        data = denc.encode(m.to_dict())
    if path is None or path == "-":
        sys.stdout.write(data.decode() if text else repr(data))
    else:
        with open(path, "wb") as f:
            f.write(data)


def build_map(num_osds: int, layers: list[tuple[str, str, int]]
              ) -> CrushMap:
    """--build: stack layers bottom-up (crushtool.cc --build).
    Each layer (name, alg, size): size children per bucket, 0 = one
    bucket holding everything."""
    m = CrushMap()
    m.types = {0: "osd"}
    lower = list(range(num_osds))
    lower_weights = [0x10000] * num_osds
    next_id = -1
    for depth, (tname, algname, size) in enumerate(layers, 1):
        m.types[depth] = tname
        alg = ALG_BY_NAME[algname]
        groups = []
        if size <= 0:
            groups = [list(range(len(lower)))]
        else:
            groups = [list(range(i, min(i + size, len(lower))))
                      for i in range(0, len(lower), size)]
        new_lower, new_weights = [], []
        for gi, g in enumerate(groups):
            items = [lower[i] for i in g]
            ws = [lower_weights[i] for i in g]
            b = m.add_bucket(alg, depth, items, ws, id=next_id,
                             name="%s%d" % (tname, gi))
            next_id -= 1
            new_lower.append(b.id)
            new_weights.append(b.weight)
        lower, lower_weights = new_lower, new_weights
    if len(lower) == 1:
        root = lower[0]
    else:
        m.types[len(layers) + 1] = "root"
        b = m.add_bucket(ALG_BY_NAME["straw2"], len(layers) + 1, lower,
                         lower_weights, id=next_id, name="root")
        root = b.id
    leaf_type = 1 if layers else 0
    m.add_rule([(TAKE, root, 0), (CHOOSELEAF_FIRSTN, 0, leaf_type),
                (EMIT, 0, 0)], id=0, name="replicated_rule")
    return m


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="crushtool")
    p.add_argument("-c", "--compile", metavar="SRC")
    p.add_argument("-d", "--decompile", metavar="SRC")
    p.add_argument("-i", "--input", metavar="SRC")
    p.add_argument("-o", "--output", metavar="DST")
    p.add_argument("--build", action="store_true")
    p.add_argument("--num-osds", type=int, default=0)
    p.add_argument("layers", nargs="*",
                   help="--build: name alg size triples")
    p.add_argument("--test", action="store_true")
    p.add_argument("--rule", type=int, default=0)
    p.add_argument("--num-rep", type=int, default=3)
    p.add_argument("--min-x", type=int, default=0)
    p.add_argument("--max-x", type=int, default=1023)
    p.add_argument("--show-utilization", action="store_true")
    p.add_argument("--show-bad-mappings", action="store_true")
    args = p.parse_args(argv)

    if args.compile:
        with open(args.compile) as f:
            m = compile(f.read())
        save_map(m, args.output or (args.compile + ".bin"))
        return 0
    if args.decompile:
        m = load_map(args.decompile)
        save_map(m, args.output or "-", text=True)
        return 0
    if args.build:
        if args.num_osds <= 0 or len(args.layers) % 3:
            p.error("--build needs --num-osds and name alg size triples")
        layers = [(args.layers[i], args.layers[i + 1],
                   int(args.layers[i + 2]))
                  for i in range(0, len(args.layers), 3)]
        m = build_map(args.num_osds, layers)
        save_map(m, args.output or "-",
                 text=(args.output in (None, "-")))
        return 0
    if args.test:
        if not args.input:
            p.error("--test needs -i MAP")
        m = load_map(args.input)
        tester = CrushTester(m)
        n = args.max_x - args.min_x + 1
        report = tester.test_rule(args.rule, args.num_rep, n,
                                  args.min_x)
        out = report.summary()
        if args.show_utilization:
            out["utilization"] = {
                "osd.%d" % d: round(r, 4)
                for d, r in sorted(report.utilization().items())}
            out["device_counts"] = {
                "osd.%d" % d: c
                for d, c in sorted(report.device_counts.items())}
        print(json.dumps(out, indent=1))
        return 0 if report.bad_mappings == 0 else 1
    p.error("one of -c, -d, --build, --test is required")
    return 2


if __name__ == "__main__":
    sys.exit(main())
