"""objectstore-tool: offline PG surgery on an OSD's store.

Analog of src/tools/ceph_objectstore_tool.cc — the offline
checkpoint/repair surgeon: list PGs and objects in a (un-mounted)
KStore, export a PG (objects + xattrs + omap + pgmeta log/info) to a
portable file, import it into another store, or remove it.

    python -m ceph_tpu.cli.objectstore_tool --data-path STORE.db --op list
    python -m ceph_tpu.cli.objectstore_tool --data-path STORE.db \\
        --pgid 1.0 --op export --file pg.export
    python -m ceph_tpu.cli.objectstore_tool --data-path STORE2.db \\
        --op import --file pg.export
    python -m ceph_tpu.cli.objectstore_tool --data-path STORE.db \\
        --pgid 1.0 --op remove
"""

from __future__ import annotations

import argparse
import sys

from ..store.kstore import KStore
from ..store.objectstore import Transaction, coll_t, hobject_t
from ..utils import denc

EXPORT_MAGIC = b"ceph-tpu-pg-export-v1"


def _parse_pgid(s: str):
    pool_s, ps_s = s.split(".")
    return int(pool_s), int(ps_s, 16)


def export_pg(store, pool: int, ps: int) -> bytes:
    cid = coll_t.pg(pool, ps)
    objs = []
    for ho in store.collection_list(cid):
        objs.append({
            "name": ho.name,
            "data": store.read(cid, ho),
            "attrs": dict(store.getattrs(cid, ho)),
            "omap": dict(store.omap_get(cid, ho)),
            "omap_header": store.omap_get_header(cid, ho),
        })
    return EXPORT_MAGIC + denc.encode(
        {"pool": pool, "ps": ps, "objects": objs})


def import_pg(store, blob: bytes, force: bool = False) -> tuple:
    if not blob.startswith(EXPORT_MAGIC):
        raise ValueError("not a pg export file")
    payload = denc.decode(blob[len(EXPORT_MAGIC):])
    pool, ps = payload["pool"], payload["ps"]
    cid = coll_t.pg(pool, ps)
    existing = {c for c in store.list_collections()}
    if cid in existing and not force:
        raise ValueError("pg %d.%x already exists (use --force)"
                         % (pool, ps))
    t = Transaction()
    if cid in existing:
        for ho in store.collection_list(cid):
            t.remove(cid, ho)
    else:
        t.create_collection(cid)
    for o in payload["objects"]:
        ho = hobject_t(o["name"])
        t.touch(cid, ho)
        data = bytes(o["data"])
        t.write(cid, ho, 0, len(data), data)
        t.setattrs(cid, ho, {
            (k if isinstance(k, str) else k.decode()): bytes(v)
            for k, v in o["attrs"].items()})
        if o["omap"]:
            t.omap_setkeys(cid, ho, {bytes(k): bytes(v)
                                     for k, v in o["omap"].items()})
        if o.get("omap_header"):
            t.omap_setheader(cid, ho, bytes(o["omap_header"]))
    store.apply_transaction(t)
    return pool, ps, len(payload["objects"])


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="objectstore-tool")
    p.add_argument("--data-path", required=True)
    p.add_argument("--op", required=True,
                   choices=["list", "export", "import", "remove",
                            "list-pgs"])
    p.add_argument("--pgid")
    p.add_argument("--file")
    p.add_argument("--force", action="store_true")
    args = p.parse_args(argv)

    store = KStore(args.data_path)
    store.mount()
    try:
        if args.op in ("list", "list-pgs"):
            for cid in sorted(store.list_collections(),
                              key=lambda c: c.name):
                if not cid.is_pg():
                    continue
                pool_s, ps_s = cid.name.split(".")
                pgid = "%s.%s" % (pool_s, ps_s)
                if args.op == "list-pgs":
                    print(pgid)
                else:
                    for ho in store.collection_list(cid):
                        if ho.name != "__pgmeta__":
                            print("%s %s" % (pgid, ho.name))
            return 0
        if args.op == "export":
            pool, ps = _parse_pgid(args.pgid)
            blob = export_pg(store, pool, ps)
            with open(args.file, "wb") as f:
                f.write(blob)
            print("exported %d.%x: %d bytes" % (pool, ps, len(blob)))
            return 0
        if args.op == "import":
            with open(args.file, "rb") as f:
                blob = f.read()
            pool, ps, n = import_pg(store, blob, force=args.force)
            print("imported %d.%x: %d objects" % (pool, ps, n))
            return 0
        if args.op == "remove":
            pool, ps = _parse_pgid(args.pgid)
            cid = coll_t.pg(pool, ps)
            t = Transaction()
            for ho in store.collection_list(cid):
                t.remove(cid, ho)
            t.remove_collection(cid)
            store.apply_transaction(t)
            print("removed %d.%x" % (pool, ps))
            return 0
        return 2
    finally:
        store.umount()


if __name__ == "__main__":
    sys.exit(main())
