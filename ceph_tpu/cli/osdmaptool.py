"""osdmaptool: create and test full OSD maps.

Analog of src/tools/osdmaptool.cc:

    python -m ceph_tpu.cli.osdmaptool --createsimple 12 map.bin
    python -m ceph_tpu.cli.osdmaptool map.bin --print
    python -m ceph_tpu.cli.osdmaptool map.bin --test-map-pgs \\
        [--pool N] [--bulk]
    python -m ceph_tpu.cli.osdmaptool map.bin --upmap out.bin \\
        [--upmap-deviation D] [--upmap-max N]

--test-map-pgs maps every PG of the pool(s) and prints the placement
histogram (the reference's per-osd count table); --bulk routes through
the vectorized device mapper (OSDMapMapping) instead of the scalar
pipeline — the ParallelPGMapper analog.  --upmap runs the upmap
balancer (calc_pg_upmaps, the reference's osdmaptool --upmap) and
writes the balanced map.
"""

from __future__ import annotations

import argparse
import json
import sys

from ..models.crushmap import (CHOOSE_FIRSTN, CHOOSE_INDEP, EMIT, STRAW2,
                               TAKE, CrushMap)
from ..osd.osdmap import (OSD_EXISTS, OSD_UP, Incremental, OSDMap,
                          PGPool, pg_t)


def create_simple(num_osds: int, pg_num: int = 256,
                  size: int = 3) -> OSDMap:
    crush = CrushMap()
    crush.types = {0: "osd", 1: "root"}
    crush.add_bucket(STRAW2, 1, list(range(num_osds)),
                     [0x10000] * num_osds, id=-1, name="default")
    crush.add_rule([(TAKE, -1, 0), (CHOOSE_FIRSTN, 0, 0), (EMIT, 0, 0)],
                   id=0, name="replicated_rule")
    crush.add_rule([(TAKE, -1, 0), (CHOOSE_INDEP, 0, 0), (EMIT, 0, 0)],
                   id=1, name="erasure_rule")
    m = OSDMap()
    inc = Incremental(epoch=1)
    inc.new_max_osd = num_osds
    inc.new_crush = crush
    inc.new_pools[1] = PGPool(id=1, name="rbd", pg_num=pg_num,
                              size=size, crush_rule=0)
    m.apply_incremental(inc)
    inc = m.new_incremental()
    for o in range(num_osds):
        inc.new_state[o] = OSD_EXISTS | OSD_UP
        inc.new_weight[o] = 0x10000
    m.apply_incremental(inc)
    return m


def test_map_pgs(m: OSDMap, pool_ids: list[int],
                 bulk: bool = False) -> dict:
    counts: dict[int, int] = {}
    primaries: dict[int, int] = {}
    total = 0
    size_hist: dict[int, int] = {}
    if bulk:
        from ..parallel.mapping import OSDMapMapping

        mapping = OSDMapMapping(m)
    for pid in pool_ids:
        pool = m.pools[pid]
        for ps in range(pool.pg_num):
            pg = pg_t(pid, ps)
            if bulk:
                up, upp, acting, actingp = mapping.get(pg)
            else:
                up, upp, acting, actingp = m.pg_to_up_acting_osds(pg)
            placed = [o for o in acting if 0 <= o < m.max_osd]
            size_hist[len(placed)] = size_hist.get(len(placed), 0) + 1
            total += 1
            for o in placed:
                counts[o] = counts.get(o, 0) + 1
            if actingp >= 0:
                primaries[actingp] = primaries.get(actingp, 0) + 1
    vals = list(counts.values()) or [0]
    return {
        "pg_total": total,
        "size_histogram": {str(k): v for k, v in sorted(size_hist.items())},
        "osd_count_min": min(vals),
        "osd_count_max": max(vals),
        "osd_count_avg": round(sum(vals) / max(len(vals), 1), 1),
        "per_osd": {"osd.%d" % o: c for o, c in sorted(counts.items())},
        "primaries": {"osd.%d" % o: c
                      for o, c in sorted(primaries.items())},
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="osdmaptool")
    p.add_argument("mapfile", nargs="?")
    p.add_argument("--createsimple", type=int, metavar="NUM_OSDS")
    p.add_argument("--pg-num", type=int, default=256)
    p.add_argument("--size", type=int, default=3)
    p.add_argument("--print", action="store_true", dest="do_print")
    p.add_argument("--test-map-pgs", action="store_true")
    p.add_argument("--pool", type=int, action="append")
    p.add_argument("--bulk", action="store_true",
                   help="use the vectorized bulk mapper")
    p.add_argument("--upmap", metavar="OUTFILE",
                   help="run the upmap balancer, write the result")
    p.add_argument("--upmap-deviation", type=float, default=1.0)
    p.add_argument("--upmap-max", type=int, default=100)
    args = p.parse_args(argv)

    if args.createsimple:
        if not args.mapfile:
            p.error("--createsimple needs an output mapfile")
        m = create_simple(args.createsimple, args.pg_num, args.size)
        with open(args.mapfile, "wb") as f:
            f.write(m.encode())
        print("wrote %s: %d osds, pool rbd pg_num=%d"
              % (args.mapfile, args.createsimple, args.pg_num))
        return 0
    if not args.mapfile:
        p.error("mapfile required")
    with open(args.mapfile, "rb") as f:
        m = OSDMap.decode(f.read())
    if args.do_print:
        info = {
            "epoch": m.epoch,
            "max_osd": m.max_osd,
            "num_up": sum(1 for o in range(m.max_osd) if m.is_up(o)),
            "pools": {str(pid): {"name": pl.name, "pg_num": pl.pg_num,
                                 "size": pl.size, "type": pl.type}
                      for pid, pl in m.pools.items()},
        }
        print(json.dumps(info, indent=1))
        return 0
    if args.test_map_pgs:
        pools = args.pool or sorted(m.pools)
        print(json.dumps(test_map_pgs(m, pools, bulk=args.bulk),
                         indent=1))
        return 0
    if args.upmap:
        from ..osd.balancer import calc_pg_upmaps

        inc = m.new_incremental()
        n = calc_pg_upmaps(m, inc, args.upmap_deviation,
                           args.upmap_max, args.pool)
        m.apply_incremental(inc)
        with open(args.upmap, "wb") as f:
            f.write(m.encode())
        print("calc_pg_upmaps: %d changes, %d pg_upmap_items; wrote %s"
              % (n, len(m.pg_upmap_items), args.upmap))
        return 0
    p.error("nothing to do")
    return 2


if __name__ == "__main__":
    sys.exit(main())
