"""Per-daemon admin socket: a unix-socket JSON command server.

Reference analog: AdminSocket (src/common/admin_socket.h) — every daemon
exposes `perf dump`, `config get/set/diff`, `dump_ops_in_flight`, plus
commands registered by subsystems.

Protocol: one JSON request per connection: {"prefix": "...", ...args},
one JSON reply, connection closes.  (The reference uses a similar
single-command-per-connect model.)
"""

from __future__ import annotations

import json
import os
import socket
import threading
from typing import Any, Callable

Handler = Callable[[dict], Any]


class AdminSocket:
    def __init__(self, path: str, context=None):
        self.path = path
        self._handlers: dict[str, tuple[Handler, str]] = {}
        self._thread: threading.Thread | None = None
        self._sock: socket.socket | None = None
        self._stop = threading.Event()
        if context is not None:
            self._register_builtin(context)

    # -- registration ----------------------------------------------------
    def register(self, prefix: str, handler: Handler, help: str = "") -> None:
        self._handlers[prefix] = (handler, help)

    def _register_builtin(self, ctx) -> None:
        self.register("help", lambda a: {
            p: h for p, (_, h) in sorted(self._handlers.items())
        }, "list commands")
        self.register("perf dump", lambda a: ctx.perf.dump(), "dump perf counters")
        self.register("config get", lambda a: {a["key"]: ctx.conf.get(a["key"])},
                      "get one config option")
        self.register("config set",
                      lambda a: (ctx.conf.set(a["key"], a["value"]), "ok")[1],
                      "set one config option at runtime")
        self.register("config diff", lambda a: ctx.conf.diff(),
                      "show non-default config values")
        self.register("config dump", lambda a: ctx.conf.dump(),
                      "show all resolved config values")
        self.register("log dump", lambda a: (ctx.log.dump_recent(), "ok")[1],
                      "dump recent log ring to the daemon log")

        # op-tracker dumps (TrackedOp/OpTracker admin commands): the
        # tracker registers itself on the context at construction, so
        # resolve lazily — daemons build their tracker after the
        # context (and some daemons have none)
        def tracker():
            tr = getattr(ctx, "optracker", None)
            if tr is None:
                raise RuntimeError("this daemon tracks no ops")
            return tr

        self.register(
            "dump_ops_in_flight",
            lambda a: tracker().dump_ops_in_flight(a.get("tenant")),
            "show in-flight tracked ops (optional tenant filter)")
        self.register(
            "dump_historic_ops",
            lambda a: tracker().dump_historic_ops(a.get("tenant")),
            "show recently completed ops (optional tenant filter)")
        self.register(
            "dump_historic_slow_ops",
            lambda a: tracker().dump_historic_slow_ops(
                a.get("tenant")),
            "show recently completed slow ops (optional tenant"
            " filter)")

        # flight-recorder ring (ceph_tpu.trace.recorder): the span
        # records the Perfetto export merges — same lazy-backref
        # pattern as the tracker dumps
        def recorder():
            fr = getattr(ctx, "flight_recorder", None)
            if fr is None:
                raise RuntimeError("this daemon records no spans")
            return fr

        self.register("dump_flight_recorder",
                      lambda a: recorder().dump(),
                      "dump the flight-recorder span ring")

        # heartbeat RTT matrix (osd.network.OsdNetwork): the
        # reference's `ceph daemon osd.N dump_osd_network` — same
        # lazy-backref pattern (only OSDs track peer pings)
        def network():
            net = getattr(ctx, "osd_network", None)
            if net is None:
                raise RuntimeError("this daemon tracks no peer pings")
            return net

        self.register("dump_osd_network",
                      lambda a: network().dump(),
                      "dump per-peer heartbeat RTT tracking")

    # -- server ----------------------------------------------------------
    def start(self) -> None:
        if not self.path:
            return
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(self.path)
        self._sock.listen(8)
        self._sock.settimeout(0.25)
        self._thread = threading.Thread(
            target=self._serve, name="admin-socket", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        if self._sock:
            self._sock.close()
            self._sock = None
        try:
            os.unlink(self.path)
        except (FileNotFoundError, OSError):
            pass

    def _serve(self) -> None:
        sock = self._sock  # local ref: stop() may null the attribute
        while not self._stop.is_set():
            try:
                conn, _ = sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            try:
                self._handle(conn)
            except OSError:
                pass  # client stalled or vanished; keep serving
            finally:
                conn.close()

    def _handle(self, conn: socket.socket) -> None:
        conn.settimeout(5)
        chunks = []
        while True:
            b = conn.recv(65536)
            if not b:
                break
            chunks.append(b)
            if _is_complete(b"".join(chunks)):
                break
        try:
            req = json.loads(b"".join(chunks) or b"{}")
            prefix = req.get("prefix", "help")
            entry = self._handlers.get(prefix)
            if entry is None:
                reply = {"error": f"unknown command {prefix!r}"}
            else:
                reply = {"ok": entry[0](req)}
        except Exception as e:  # command errors go to the client, not the daemon
            reply = {"error": f"{type(e).__name__}: {e}"}
        conn.sendall(json.dumps(reply, default=str).encode())


def _is_complete(buf: bytes) -> bool:
    try:
        json.loads(buf)
        return True
    except ValueError:
        return False


def admin_command(path: str, prefix: str, **args) -> Any:
    """Client helper: send one command to a daemon's admin socket."""
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(5)
    try:
        s.connect(path)
        s.sendall(json.dumps({"prefix": prefix, **args}).encode())
        s.shutdown(socket.SHUT_WR)
        chunks = []
        while True:
            b = s.recv(65536)
            if not b:
                break
            chunks.append(b)
        reply = json.loads(b"".join(chunks))
    finally:
        s.close()
    if "error" in reply:
        raise RuntimeError(reply["error"])
    return reply["ok"]
