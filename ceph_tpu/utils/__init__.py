"""L0 substrate: config, logging, perf counters, admin socket, context.

Reference analog: src/common/ (CephContext, md_config_t, dout, PerfCounters,
AdminSocket — see SURVEY.md §2.1 L0 row).
"""

from .config import Config, Option, OPT_BOOL, OPT_FLOAT, OPT_INT, OPT_STR
from .context import Context
from .log import Logger, LogRing
from .perf import PerfCounters, PerfCountersCollection

__all__ = [
    "Config",
    "Option",
    "OPT_BOOL",
    "OPT_FLOAT",
    "OPT_INT",
    "OPT_STR",
    "Context",
    "Logger",
    "LogRing",
    "PerfCounters",
    "PerfCountersCollection",
]
