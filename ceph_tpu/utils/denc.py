"""denc: deterministic binary encoding for plain Python values.

The framework's analog of the reference's encode/decode bufferlist
layer (src/include/encoding.h; checked by ceph-dencoder against the
object corpus): a small, versionless, deterministic TLV format for
None/bool/int/float/bytes/str/list/tuple/dict, used by the durable
KStore records, the wire protocol frames, and map (de)serialization.

Integers up to 64-bit signed encode fixed-width ('i'); larger ones fall
back to decimal text ('I').  Dicts encode in insertion order — callers
that need canonical bytes sort first.
"""

from __future__ import annotations

import struct

_I64_MIN = -(1 << 63)
_I64_MAX = (1 << 63) - 1


def encode(v, out: bytearray | None = None) -> bytes:
    buf = bytearray() if out is None else out
    _enc(v, buf)
    return bytes(buf)


def _enc(v, buf: bytearray) -> None:
    if v is None:
        buf += b"N"
    elif v is True:
        buf += b"T"
    elif v is False:
        buf += b"F"
    elif isinstance(v, int):
        if _I64_MIN <= v <= _I64_MAX:
            buf += b"i"
            buf += struct.pack(">q", v)
        else:
            s = str(v).encode()
            buf += b"I"
            buf += struct.pack(">I", len(s))
            buf += s
    elif isinstance(v, float):
        buf += b"f"
        buf += struct.pack(">d", v)
    elif isinstance(v, (bytes, bytearray, memoryview)):
        b = bytes(v)
        buf += b"b"
        buf += struct.pack(">I", len(b))
        buf += b
    elif isinstance(v, str):
        b = v.encode()
        buf += b"s"
        buf += struct.pack(">I", len(b))
        buf += b
    elif isinstance(v, list):
        buf += b"l"
        buf += struct.pack(">I", len(v))
        for item in v:
            _enc(item, buf)
    elif isinstance(v, tuple):
        buf += b"t"
        buf += struct.pack(">I", len(v))
        for item in v:
            _enc(item, buf)
    elif isinstance(v, dict):
        buf += b"d"
        buf += struct.pack(">I", len(v))
        for k, val in v.items():
            _enc(k, buf)
            _enc(val, buf)
    else:
        raise TypeError("denc: cannot encode %r" % type(v))


def decode(data: bytes | memoryview):
    v, off = _dec(memoryview(data), 0)
    if off != len(data):
        raise ValueError("denc: %d trailing bytes" % (len(data) - off))
    return v


def decode_prefix(data: bytes | memoryview, off: int = 0):
    """Decode one value starting at off; returns (value, next_off)."""
    return _dec(memoryview(data), off)


def _dec(mv: memoryview, off: int):
    tag = mv[off:off + 1].tobytes()
    off += 1
    if tag == b"N":
        return None, off
    if tag == b"T":
        return True, off
    if tag == b"F":
        return False, off
    if tag == b"i":
        return struct.unpack_from(">q", mv, off)[0], off + 8
    if tag == b"I":
        n = struct.unpack_from(">I", mv, off)[0]
        off += 4
        return int(mv[off:off + n].tobytes()), off + n
    if tag == b"f":
        return struct.unpack_from(">d", mv, off)[0], off + 8
    if tag == b"b":
        n = struct.unpack_from(">I", mv, off)[0]
        off += 4
        return mv[off:off + n].tobytes(), off + n
    if tag == b"s":
        n = struct.unpack_from(">I", mv, off)[0]
        off += 4
        return mv[off:off + n].tobytes().decode(), off + n
    if tag in (b"l", b"t"):
        n = struct.unpack_from(">I", mv, off)[0]
        off += 4
        items = []
        for _ in range(n):
            item, off = _dec(mv, off)
            items.append(item)
        return (items if tag == b"l" else tuple(items)), off
    if tag == b"d":
        n = struct.unpack_from(">I", mv, off)[0]
        off += 4
        d = {}
        for _ in range(n):
            k, off = _dec(mv, off)
            val, off = _dec(mv, off)
            d[k] = val
        return d, off
    raise ValueError("denc: bad tag %r at %d" % (tag, off - 1))


# -- versioned struct envelope (ENCODE_START/DECODE_START semantics) --


class IncompatibleEncoding(ValueError):
    """The blob requires a newer decoder (compat > supported) —
    the reference's buffer::malformed_input on DECODE_START."""


_VHDR = struct.Struct(">BBI")           # version, compat, payload len


def encode_versioned(value, version: int, compat: int = 1) -> bytes:
    """src/include/encoding.h ENCODE_START analog: a struct payload
    framed with (version, compat, length).

    * ``version`` — what this writer produced;
    * ``compat`` — the oldest decoder that can still make sense of it
      (bump only on breaking layout changes);
    * the LENGTH makes newer-minor payloads skippable by old readers
      (they decode what they understand and seek past the rest),
      which is what makes rolling upgrades possible.
    """
    payload = encode(value)
    return (b"V" + _VHDR.pack(version, compat, len(payload))
            + payload)


def decode_versioned(data: bytes | memoryview,
                     supported: int) -> tuple[int, object]:
    """DECODE_START analog: returns (writer_version, value).  Raises
    IncompatibleEncoding when the writer says even ``supported`` is
    too old (compat gate); tolerates payloads LONGER than one value
    (a newer writer's extra trailing fields are skipped via the
    length header)."""
    mv = memoryview(data)
    if mv[:1].tobytes() != b"V":
        raise ValueError("not a versioned encoding")
    version, compat, length = _VHDR.unpack_from(mv, 1)
    if compat > supported:
        raise IncompatibleEncoding(
            "encoding v%d requires decoder >= v%d (have v%d)"
            % (version, compat, supported))
    payload = mv[1 + _VHDR.size:1 + _VHDR.size + length]
    value, off = _dec(payload, 0)
    # bytes past the first value inside the framed payload belong to
    # a newer minor version: skipped by design
    return version, value
