"""Process-wide context object tying the substrate together.

Reference analog: CephContext — the per-process bundle of config, logging,
perf counter collection, and admin socket that every component receives.
"""

from __future__ import annotations

from typing import Iterable

from .admin import AdminSocket
from .config import Config, Option
from .log import Logger, LogRing
from .perf import PerfCountersCollection


class Context:
    def __init__(
        self,
        name: str = "ceph-tpu",
        schema: Iterable[Option] = (),
        conf_overrides: dict | None = None,
    ):
        self.name = name
        self.conf = Config(schema)
        for k, v in (conf_overrides or {}).items():
            self.conf.set(k, v, source="cli")
        self.log = Logger(
            name, ring=LogRing(self.conf.get("log_ring_size", 10000))
        )
        self.log.set_global_level(self.conf["log_level"])
        self.conf.add_observer(
            "log_level", lambda _k, v: self.log.set_global_level(v)
        )
        self.perf = PerfCountersCollection()
        self.admin: AdminSocket | None = None
        admin_path = self.conf.get("admin_socket", "")
        if admin_path:
            self.admin = AdminSocket(admin_path, self)
            self.admin.start()

    def shutdown(self) -> None:
        if self.admin:
            self.admin.stop()
            self.admin = None
