"""Shared bootstrap for the virtual multi-device CPU platform.

Multi-chip hardware is not available in CI: sharding correctness runs on
a virtual N-device CPU platform instead.  Both the test suite
(tests/conftest.py) and the driver dry-run (__graft_entry__.py) need the
same fragile recipe, kept here so they cannot drift:

  * JAX_PLATFORMS from the session (e.g. the real-TPU tunnel) must be
    DROPPED, not overridden — setting it to "cpu" does not reliably win;
    the platform is pinned via jax.config in-process instead.
  * any pre-existing xla_force_host_platform_device_count pin must be
    stripped (it may be smaller than the requested count) before adding
    ours.
  * JAX_ENABLE_X64 is required for bit-exact straw2 int64 math.
"""

from __future__ import annotations


def shard_map_compat(fn, *, mesh, in_specs, out_specs):
    """shard_map across JAX versions: new releases export
    ``jax.shard_map`` (replication checking flag ``check_vma``), older
    ones only ``jax.experimental.shard_map.shard_map``
    (``check_rep``).  Checking is disabled either way — pallas_call
    results carry no replication annotation."""
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(fn, mesh=mesh, in_specs=in_specs,
               out_specs=out_specs, check_rep=False)


def force_virtual_cpu_env(env: dict, n_devices: int) -> dict:
    """Mutate ``env`` (an os.environ-like mapping) so a JAX process
    started with it sees an ``n_devices``-device CPU platform once it
    also runs ``jax.config.update("jax_platforms", "cpu")``."""
    env.pop("JAX_PLATFORMS", None)
    flags = " ".join(
        f for f in env.get("XLA_FLAGS", "").split()
        if "xla_force_host_platform_device_count" not in f)
    env["XLA_FLAGS"] = (
        flags + f" --xla_force_host_platform_device_count={n_devices}"
    ).strip()
    env.setdefault("JAX_ENABLE_X64", "1")
    return env
