"""Crash reports: post-mortem context that survives the dead daemon.

Reference analog: the crash module (src/pybind/mgr/crash + the
ceph-crash agent): an unhandled daemon exception writes a crash report
— stack, the tail of the high-verbosity LogRing, daemon identity,
fsid/epoch — into the daemon's OWN object store (the one artifact that
survives the process).  On the next boot the daemon ships pending
reports to the monitors, which persist them in a paxos-committed crash
table (`crash ls` / `crash info` / `crash archive`) and raise
RECENT_CRASH until the operator archives them.

Reports live in the store's 'meta' collection as `crash_<id>` objects,
so PG loading (which only walks PG collections) never sees them and a
wiped store legitimately forgets its crashes (the disk is gone; so is
its post-mortem state).
"""

from __future__ import annotations

import os
import time
import traceback

from ..store.objectstore import NotFound, Transaction, coll_t, hobject_t
from . import denc

META_COLL = coll_t("meta")
CRASH_PREFIX = "crash_"


def new_crash_id(stamp: float | None = None) -> str:
    """Unique id, timestamp-prefixed so `crash ls` sorts by age."""
    ts = time.strftime("%Y-%m-%dT%H:%M:%S",
                       time.gmtime(stamp or time.time()))
    return "%s_%s" % (ts, os.urandom(6).hex())


def ring_tail(ring, tail: int = 100) -> list[str]:
    """The last `tail` LogRing entries, formatted — the post-mortem
    high-verbosity context (shared by crash reports and the
    diagnostics bundle)."""
    if ring is None:
        return []
    entries = list(getattr(ring, "_ring", []))[-tail:]
    return ["%0.6f %2d %s: %s" % (ts, level, subsys, msg)
            for ts, subsys, level, msg in entries]


def build_report(daemon: str, exc: BaseException, fsid: str = "",
                 epoch: int = 0, ring=None, tail: int = 100) -> dict:
    """One crash report dict: identity + stack + the LogRing tail (the
    high-verbosity context the daemon gathered but never emitted)."""
    bt = traceback.format_exception(type(exc), exc, exc.__traceback__)
    return {
        "crash_id": new_crash_id(),
        "timestamp": time.time(),
        "entity": daemon,
        "fsid": fsid,
        "epoch": int(epoch),
        "exc_type": type(exc).__name__,
        "exc_msg": str(exc),
        "backtrace": [ln.rstrip("\n") for ln in bt],
        "ring_tail": ring_tail(ring, tail),
    }


def _ho(crash_id: str) -> hobject_t:
    return hobject_t(CRASH_PREFIX + crash_id)


def save_crash(store, report: dict) -> None:
    """Persist one report into the store's meta collection (the only
    durable thing a dying daemon can still do)."""
    t = Transaction()
    if not store.collection_exists(META_COLL):
        t.create_collection(META_COLL)
    ho = _ho(report["crash_id"])
    blob = denc.encode(report)
    t.touch(META_COLL, ho)
    t.write(META_COLL, ho, 0, len(blob), blob)
    store.apply_transaction(t)


def pending_crashes(store) -> list[dict]:
    """Reports waiting to be shipped to the monitors (boot path)."""
    out: list[dict] = []
    try:
        if not store.collection_exists(META_COLL):
            return out
        for ho in store.collection_list(META_COLL):
            if not ho.name.startswith(CRASH_PREFIX):
                continue
            try:
                out.append(dict(denc.decode(
                    store.read(META_COLL, ho))))
            except Exception:
                continue        # torn write mid-crash: skip, not raise
    except NotFound:
        return out
    out.sort(key=lambda r: r.get("timestamp", 0.0))
    return out


def remove_crash(store, crash_id: str) -> None:
    """The monitors acked (paxos-committed) this report: drop it."""
    if not store.collection_exists(META_COLL):
        return
    ho = _ho(crash_id)
    if store.exists(META_COLL, ho):
        t = Transaction()
        t.remove(META_COLL, ho)
        store.apply_transaction(t)


# -- daemon meta values (the same meta collection crash reports use) --------

CLOG_SEQ_OBJ = hobject_t("clog_seq")


def load_clog_seq(store) -> int:
    """The last clog sequence number this daemon's previous
    incarnation used (0 when none was ever persisted)."""
    try:
        if not store.collection_exists(META_COLL):
            return 0
        return int(denc.decode(store.read(META_COLL, CLOG_SEQ_OBJ)))
    except Exception:       # missing / torn: start from zero
        return 0


def save_clog_seq(store, seq: int) -> None:
    """Persist the daemon's last-used clog seq into its own store so
    a restart resumes ABOVE it: the LogMonitor dedups by (who, seq),
    so a rebooted daemon that restarted from 1 would have its fresh
    entries silently swallowed as resends of already-committed seqs
    (and could never supersede its pre-restart unacked ones)."""
    t = Transaction()
    if not store.collection_exists(META_COLL):
        t.create_collection(META_COLL)
    blob = denc.encode(int(seq))
    t.touch(META_COLL, CLOG_SEQ_OBJ)
    t.truncate(META_COLL, CLOG_SEQ_OBJ, 0)
    t.write(META_COLL, CLOG_SEQ_OBJ, 0, len(blob), blob)
    store.apply_transaction(t)


CLOG_INC_OBJ = hobject_t("clog_incarnation")


def new_clog_incarnation() -> int:
    """A fresh boot incarnation, strictly greater than any minted by
    an earlier boot of this daemon (wall-clock nanoseconds): a WIPED
    store loses the persisted seq floor, so the reborn daemon re-keys
    its clog entries under a new incarnation instead of replaying seqs
    the LogMonitor's (who, inc, seq) dedup already committed."""
    return time.time_ns()


def load_clog_incarnation(store) -> int:
    """The persisted boot incarnation (0 when none — a fresh or wiped
    store, where the caller mints a new one)."""
    try:
        if not store.collection_exists(META_COLL):
            return 0
        return int(denc.decode(store.read(META_COLL, CLOG_INC_OBJ)))
    except Exception:       # missing / torn: treat as fresh
        return 0


def save_clog_incarnation(store, inc: int) -> None:
    t = Transaction()
    if not store.collection_exists(META_COLL):
        t.create_collection(META_COLL)
    blob = denc.encode(int(inc))
    t.touch(META_COLL, CLOG_INC_OBJ)
    t.truncate(META_COLL, CLOG_INC_OBJ, 0)
    t.write(META_COLL, CLOG_INC_OBJ, 0, len(blob), blob)
    store.apply_transaction(t)
