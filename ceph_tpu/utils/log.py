"""Leveled, per-subsystem logging with a crash-dump ring buffer.

Reference analog: Ceph's dout/ldout macros with per-subsystem debug levels
(src/common/dout.h) and the async Log thread keeping a bounded in-memory
ring of recent entries that is dumped on crash (src/log/Log.h).

Design: a `LogRing` always records (cheaply) at a high "gather" level;
entries at or below the subsystem's output level are also emitted to the
sink (stderr/file).  On fatal errors the ring is dumped, giving post-hoc
high-verbosity context without paying the IO cost up front.
"""

from __future__ import annotations

import collections
import os
import sys
import threading
import time
from typing import TextIO

# Per-subsystem (output_level, gather_level) defaults; subsystem names
# mirror the framework's package layout.
DEFAULT_SUBSYS_LEVELS: dict[str, tuple[int, int]] = {
    "none": (1, 5),
    "crush": (1, 5),
    "ec": (1, 5),
    "osd": (1, 5),
    "mon": (1, 5),
    "msg": (0, 5),
    "client": (1, 5),
    "store": (1, 5),
    "paxos": (1, 5),
    "heartbeat": (1, 5),
    "bench": (1, 5),
}


class LogRing:
    """Bounded ring of recent log entries, dumped on crash."""

    def __init__(self, capacity: int = 10000):
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()

    def append(self, entry: tuple) -> None:
        with self._lock:
            self._ring.append(entry)

    def dump(self, out: TextIO = sys.stderr) -> None:
        with self._lock:
            entries = list(self._ring)
        out.write(f"--- begin dump of recent events ({len(entries)}) ---\n")
        for ts, subsys, level, msg in entries:
            out.write(f"{_fmt_ts(ts)} {level:2d} {subsys}: {msg}\n")
        out.write("--- end dump of recent events ---\n")


def _fmt_ts(ts: float) -> str:
    frac = int((ts % 1) * 1e6)
    return time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(ts)) + f".{frac:06d}"


class Logger:
    """Entry point: `log = Logger(name); log.debug(subsys, msg, level=10)`.

    `dout(subsys, level)` returns True if the message would be emitted or
    gathered, letting callers skip expensive formatting — the analog of
    the reference's compile-time `dout` gating.
    """

    def __init__(
        self,
        name: str = "ceph-tpu",
        ring: LogRing | None = None,
        sink: TextIO | None = None,
        levels: dict[str, tuple[int, int]] | None = None,
    ):
        self.name = name
        self.ring = ring or LogRing()
        self._sink = sink if sink is not None else sys.stderr
        self._levels = dict(DEFAULT_SUBSYS_LEVELS)
        if levels:
            self._levels.update(levels)
        self._lock = threading.Lock()
        self._crash_hook_installed = False

    def set_level(self, subsys: str, output: int, gather: int | None = None) -> None:
        g = gather if gather is not None else max(output, 5)
        self._levels[subsys] = (output, g)

    def set_global_level(self, output: int, gather: int | None = None) -> None:
        """Raise/lower the output level of every subsystem at once (the
        `log_level` config option applies here)."""
        for subsys in list(self._levels):
            g = gather if gather is not None else max(output, self._levels[subsys][1])
            self._levels[subsys] = (output, g)

    def would_log(self, subsys: str, level: int) -> bool:
        out, gather = self._levels.get(subsys, self._levels["none"])
        return level <= max(out, gather)

    def log(self, subsys: str, level: int, msg: str) -> None:
        out_level, gather_level = self._levels.get(subsys, self._levels["none"])
        if level > out_level and level > gather_level:
            return
        ts = time.time()
        if level <= gather_level:
            self.ring.append((ts, subsys, level, msg))
        if level <= out_level:
            with self._lock:
                self._sink.write(
                    f"{_fmt_ts(ts)} {self.name} {level:2d} {subsys}: {msg}\n"
                )

    # convenience levels
    def error(self, subsys: str, msg: str) -> None:
        self.log(subsys, 0, msg)

    def info(self, subsys: str, msg: str) -> None:
        self.log(subsys, 1, msg)

    def debug(self, subsys: str, msg: str, level: int = 10) -> None:
        self.log(subsys, level, msg)

    def dump_recent(self, out: TextIO | None = None) -> None:
        self.ring.dump(out or self._sink)

    def install_crash_dump(self) -> None:
        """Dump the ring when the process dies on an unhandled exception."""
        if self._crash_hook_installed:
            return
        self._crash_hook_installed = True
        prev_hook = sys.excepthook

        def hook(exc_type, exc, tb):
            # let the previous hook print the traceback exactly once,
            # then append the high-verbosity ring
            prev_hook(exc_type, exc, tb)
            self.dump_recent()

        sys.excepthook = hook


_global_logger: Logger | None = None
_global_lock = threading.Lock()


def global_logger() -> Logger:
    global _global_logger
    with _global_lock:
        if _global_logger is None:
            _global_logger = Logger(f"ceph-tpu.{os.getpid()}")
        return _global_logger
