"""Exponential backoff with decorrelated jitter.

Analog of the reference's retry pacing knobs (mon client hunting
backoff ``mon_client_hunt_interval_backoff``, objecter op retry and
the osd_backoff ramp in src/common/options) folded into one reusable
primitive: a geometric ramp from ``base`` to ``cap`` where each step
is jittered across ``[interval/2, interval]`` so a thousand clients
kicked by the same map epoch do not resend in lockstep.

The RNG is injected so a seeded harness (FaultInjector / thrasher)
gets a replayable wait schedule; pass nothing for wall-clock use.
"""

from __future__ import annotations

import asyncio
import random


class ExpBackoff:
    """One retry ramp: ``next_delay()`` yields base, ~2*base, ...
    capped at ``cap``; ``reset()`` re-arms after a success."""

    __slots__ = ("base", "cap", "factor", "rng", "_interval",
                 "attempts")

    def __init__(self, base: float = 0.05, cap: float = 2.0,
                 factor: float = 2.0,
                 rng: random.Random | None = None):
        self.base = float(base)
        self.cap = float(cap)
        self.factor = float(factor)
        self.rng = rng or random
        self._interval = self.base
        self.attempts = 0

    def reset(self) -> None:
        self._interval = self.base
        self.attempts = 0

    def peek(self) -> float:
        """The un-jittered current interval (for tests/telemetry)."""
        return self._interval

    def state(self) -> dict:
        """Introspection hook for telemetry (the messenger's net
        plane renders the active redial ramp): current un-jittered
        interval plus how many steps the ramp has taken since the
        last reset — 0 attempts means the ramp is idle."""
        return {"interval_s": self._interval, "attempts": self.attempts}

    def next_delay(self) -> float:
        """Advance the ramp and return the jittered wait."""
        interval = self._interval
        self.attempts += 1
        self._interval = min(self._interval * self.factor, self.cap)
        return interval / 2.0 + self.rng.random() * (interval / 2.0)

    async def sleep(self) -> float:
        d = self.next_delay()
        await asyncio.sleep(d)
        return d


async def wait_for(pred, timeout: float, base: float = 0.01,
                   cap: float = 0.5,
                   rng: random.Random | None = None,
                   what: str = "condition") -> None:
    """Poll ``pred()`` under an exponential-backoff schedule until it
    holds or ``timeout`` elapses (raises TimeoutError).  Replaces the
    fixed-interval ``while: sleep(0.02)`` spins: early checks are
    tight (fast tests stay fast), steady-state polling decays toward
    ``cap`` so a wedged cluster is not busy-polled."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    bo = ExpBackoff(base=base, cap=cap, rng=rng)
    while not pred():
        left = deadline - loop.time()
        if left <= 0:
            raise TimeoutError("%s not reached in %.1fs"
                               % (what, timeout))
        await asyncio.sleep(min(bo.next_delay(), left))


async def event_wait_for(event: asyncio.Event, pred, timeout: float,
                         what: str = "condition") -> None:
    """Event-driven variant: wait on ``event`` (cleared after each
    wake) and re-check ``pred`` — for producers that signal every
    state change (e.g. the client's map event).  A small cap-bound
    timeout per wait guards against a lost signal."""
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while not pred():
        left = deadline - loop.time()
        if left <= 0:
            raise TimeoutError("%s not reached in %.1fs"
                               % (what, timeout))
        event.clear()
        if pred():      # signal raced the clear
            return
        try:
            await asyncio.wait_for(event.wait(), min(left, 0.5))
        except asyncio.TimeoutError:
            pass
