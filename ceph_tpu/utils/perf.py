"""Per-daemon performance counters.

Reference analog: PerfCounters (src/common/perf_counters.h) — typed
counters (u64 count, time, averages with count+sum, histograms) grouped
per subsystem, dumped over the admin socket (`perf dump`) and aggregated
by the manager.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Any


class _Counter:
    __slots__ = ("kind", "value", "count", "sum", "buckets", "desc")

    def __init__(self, kind: str, desc: str = ""):
        self.kind = kind
        self.value = 0
        self.count = 0
        self.sum = 0.0
        self.buckets: list[int] | None = None
        self.desc = desc


class PerfCounters:
    """A named group of counters (one per daemon subsystem).

    Kinds:
      u64   — monotonically increasing or gauge integer
      time  — accumulated seconds
      avg   — (count, sum) pair; dump reports mean
      hist  — power-of-two latency histogram in microseconds
    """

    HIST_BUCKETS = 32  # 2^0 .. 2^31 µs

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._counters: dict[str, _Counter] = {}

    # -- declaration -----------------------------------------------------
    def add_u64(self, name: str, desc: str = "") -> None:
        with self._lock:
            self._counters[name] = _Counter("u64", desc)

    def add_time(self, name: str, desc: str = "") -> None:
        with self._lock:
            self._counters[name] = _Counter("time", desc)

    def add_avg(self, name: str, desc: str = "") -> None:
        with self._lock:
            self._counters[name] = _Counter("avg", desc)

    def add_hist(self, name: str, desc: str = "") -> None:
        c = _Counter("hist", desc)
        c.buckets = [0] * self.HIST_BUCKETS
        with self._lock:
            self._counters[name] = c

    # -- mutation --------------------------------------------------------
    def inc(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counters[name].value += by

    def dec(self, name: str, by: int = 1) -> None:
        with self._lock:
            self._counters[name].value -= by

    def set(self, name: str, value: int) -> None:
        with self._lock:
            self._counters[name].value = value

    def tinc(self, name: str, seconds: float) -> None:
        with self._lock:
            c = self._counters[name]
            c.sum += seconds
            c.count += 1

    def avg_add(self, name: str, sample: float) -> None:
        with self._lock:
            c = self._counters[name]
            c.sum += sample
            c.count += 1

    def hist_sample(self, name: str, seconds: float) -> None:
        us = max(0.0, seconds * 1e6)
        bucket = min(self.HIST_BUCKETS - 1, int(math.log2(us)) if us >= 1 else 0)
        with self._lock:
            self._counters[name].buckets[bucket] += 1

    class _Timer:
        __slots__ = ("pc", "name", "t0")

        def __init__(self, pc: "PerfCounters", name: str):
            self.pc, self.name = pc, name

        def __enter__(self):
            self.t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self.pc.tinc(self.name, time.perf_counter() - self.t0)
            return False

    def timed(self, name: str) -> "_Timer":
        return self._Timer(self, name)

    # -- dump ------------------------------------------------------------
    def descriptions(self) -> dict[str, str]:
        """name -> declared help text (the exporter's # HELP source)."""
        with self._lock:
            return {name: c.desc for name, c in self._counters.items()}

    def dump(self) -> dict[str, Any]:
        out: dict[str, Any] = {}
        with self._lock:
            for name, c in self._counters.items():
                if c.kind == "u64":
                    out[name] = c.value
                elif c.kind in ("time", "avg"):
                    out[name] = {
                        "count": c.count,
                        "sum": c.sum,
                        "avg": (c.sum / c.count) if c.count else 0.0,
                    }
                elif c.kind == "hist":
                    out[name] = {"buckets_us_pow2": list(c.buckets)}
        return out


class PerfCountersCollection:
    """All counter groups in one process; `perf dump` walks this."""

    def __init__(self):
        self._lock = threading.Lock()
        self._groups: dict[str, PerfCounters] = {}

    def create(self, name: str) -> PerfCounters:
        with self._lock:
            pc = self._groups.get(name)
            if pc is None:
                pc = self._groups[name] = PerfCounters(name)
            return pc

    def remove(self, name: str) -> None:
        with self._lock:
            self._groups.pop(name, None)

    def dump(self) -> dict[str, Any]:
        with self._lock:
            groups = dict(self._groups)
        return {name: pc.dump() for name, pc in groups.items()}

    def descriptions(self) -> dict[str, dict[str, str]]:
        with self._lock:
            groups = dict(self._groups)
        return {name: pc.descriptions()
                for name, pc in groups.items()}
