"""Prometheus metrics exporter.

Analog of src/exporter/ (the standalone ceph-exporter scraping daemon
perf counters) + the mgr prometheus module's text surface: an asyncio
HTTP endpoint rendering the process's PerfCountersCollection — and any
registered gauge callables (cluster state: osd counts, pg states,
epoch) — in the Prometheus exposition format.

    exp = PrometheusExporter(ctx)
    exp.add_gauge("ceph_osd_up", lambda: n_up, "up osds")
    await exp.start("127.0.0.1", 9283)     # the mgr module's port
"""

from __future__ import annotations

import asyncio
import re
from typing import Callable

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _metric_name(*parts: str) -> str:
    return _NAME_RE.sub("_", "_".join(p for p in parts if p))


def hist_lines(base: str, buckets: list,
               labels: str = "", typed: set | None = None,
               desc: str = "") -> list[str]:
    """Prometheus histogram series from a PerfCounters power-of-two
    microsecond histogram (bucket i counts samples < 2^(i+1) µs).
    `labels` is an optional pre-rendered label body ('daemon="osd.0"')
    merged into each bucket's le label — the per-daemon form the mgr
    renders from MMgrReports.  `typed` is an optional cross-call set
    of family names that already emitted their `# HELP`/`# TYPE`
    header: the header is emitted exactly once even when the same
    base renders for many daemons (the exposition-format rule the
    lint pins)."""
    lines = []
    header = ["# HELP %s %s" % (base, desc or "pow2 histogram"),
              "# TYPE %s histogram" % base]
    if typed is not None:
        if base not in typed:
            typed.add(base)
            lines.extend(header)
    elif not labels:
        lines.extend(header)
    cum = 0
    sep = "," if labels else ""
    for i, n in enumerate(buckets):
        cum += n
        lines.append('%s_bucket{%s%sle="%g"} %d'
                     % (base, labels, sep, float(2 ** (i + 1)), cum))
    lines.append('%s_bucket{%s%sle="+Inf"} %d'
                 % (base, labels, sep, cum))
    lines.append("%s_count{%s} %d" % (base, labels, cum)
                 if labels else "%s_count %d" % (base, cum))
    return lines


class PrometheusExporter:
    def __init__(self, ctx, prefix: str = "ceph_tpu"):
        self.ctx = ctx
        self.prefix = prefix
        self._gauges: dict[str, tuple[Callable, str]] = {}
        self._server: asyncio.AbstractServer | None = None

    def add_gauge(self, name: str, fn: Callable[[], float],
                  desc: str = "") -> None:
        self._gauges[name] = (fn, desc)

    def add_renderer(self, fn: Callable[[], list]) -> None:
        """Custom line source appended to the exposition (labeled
        per-daemon series the flat gauge registry cannot express —
        the mgr's per-report metric families)."""
        self.__dict__.setdefault("_renderers", []).append(fn)

    def render(self) -> str:
        """The exposition document (text format 0.0.4)."""
        lines: list[str] = []
        for name, (fn, desc) in sorted(self._gauges.items()):
            try:
                v = float(fn())
            except Exception:
                continue
            lines.append("# HELP %s %s"
                         % (name, desc or "gauge %s" % name))
            lines.append("# TYPE %s gauge" % name)
            lines.append("%s %g" % (name, v))
        dump = self.ctx.perf.dump()
        descs = self.ctx.perf.descriptions()
        for group, counters in sorted(dump.items()):
            for cname, val in sorted(counters.items()):
                base = _metric_name(self.prefix, group, cname)
                desc = (descs.get(group) or {}).get(cname) \
                    or "perf counter %s.%s" % (group, cname)
                if isinstance(val, dict) \
                        and "buckets_us_pow2" in val:
                    lines.extend(hist_lines(base,
                                            val["buckets_us_pow2"],
                                            desc=desc))
                elif isinstance(val, dict):
                    # avg/time counters dump {avgcount, sum, ...}
                    for sub, sv in sorted(val.items()):
                        if isinstance(sv, (int, float)):
                            lines.append("# HELP %s_%s %s (%s)"
                                         % (base, sub, desc, sub))
                            lines.append("# TYPE %s_%s counter"
                                         % (base, sub))
                            lines.append("%s_%s %g" % (base, sub, sv))
                elif isinstance(val, (int, float)):
                    lines.append("# HELP %s %s" % (base, desc))
                    lines.append("# TYPE %s counter" % base)
                    lines.append("%s %g" % (base, val))
        for fn in self.__dict__.get("_renderers", []):
            try:
                lines.extend(fn())
            except Exception:
                pass
        return "\n".join(lines) + "\n"

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            req = await asyncio.wait_for(reader.readline(), 5.0)
            while True:
                line = await asyncio.wait_for(reader.readline(), 5.0)
                if line in (b"\r\n", b"\n", b""):
                    break
            path = req.split(b" ")[1] if len(req.split(b" ")) > 1 \
                else b"/"
            if path.rstrip(b"/") in (b"", b"/metrics"):
                body = self.render().encode()
                writer.write(
                    b"HTTP/1.1 200 OK\r\n"
                    b"Content-Type: text/plain; version=0.0.4\r\n"
                    b"Content-Length: %d\r\n\r\n" % len(body))
                writer.write(body)
            else:
                writer.write(b"HTTP/1.1 404 Not Found\r\n"
                             b"Content-Length: 0\r\n\r\n")
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError):
            pass
        finally:
            writer.close()

    async def start(self, host: str = "127.0.0.1",
                    port: int = 0) -> str:
        self._server = await asyncio.start_server(self._handle, host,
                                                  port)
        addr = self._server.sockets[0].getsockname()
        return "%s:%d" % (addr[0], addr[1])

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None


_SERIES_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?P<labels>\{[^{}]*\})?\s+(?P<value>\S+)$")


def validate_exposition(text: str,
                        max_label_card: int | None = 64
                        ) -> list[str]:
    """Lint an exposition document (text format 0.0.4): every emitted
    series must carry a valid metric name and belong to a family that
    declared BOTH a `# HELP` and a `# TYPE` line before its first
    sample (histogram `_bucket`/`_count`/`_sum` suffixes resolve to
    their base family).  Returns a list of human-readable violations
    — empty means clean.  Guards the growing series surface: a family
    added without its header breaks real Prometheus servers (or ships
    undocumented) only at scrape time; this makes it a unit-test
    failure instead.

    Cardinality guard: no (family, label) pair may carry more than
    `max_label_card` distinct label VALUES (None disables).  An
    unbounded label set — e.g. a tenant label fed raw tenant ids
    instead of the capped fold-into-"other" rows — is the classic
    Prometheus cardinality bomb; this makes it a lint failure before
    it becomes a TSDB incident."""
    errors: list[str] = []
    typed: set[str] = set()
    helped: set[str] = set()
    # (family, label name) -> set of observed label values
    label_vals: dict[tuple[str, str], set] = {}
    for ln, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split()
            if len(parts) >= 3 and parts[1] == "TYPE":
                if not _VALID_NAME_RE.match(parts[2]):
                    errors.append("line %d: bad family name %r"
                                  % (ln, parts[2]))
                typed.add(parts[2])
            elif len(parts) >= 3 and parts[1] == "HELP":
                helped.add(parts[2])
            continue
        m = _SERIES_RE.match(line)
        if m is None:
            errors.append("line %d: unparseable series %r"
                          % (ln, line))
            continue
        name = m.group("name")
        if not _VALID_NAME_RE.match(name):
            errors.append("line %d: bad metric name %r" % (ln, name))
            continue
        family = name
        for suffix in ("_bucket", "_count", "_sum"):
            if name.endswith(suffix) and name[:-len(suffix)] in typed:
                family = name[:-len(suffix)]
                break
        if family not in typed:
            errors.append("line %d: series %r has no # TYPE line"
                          % (ln, name))
        if family not in helped:
            errors.append("line %d: series %r has no # HELP line"
                          % (ln, name))
        if max_label_card is not None and m.group("labels"):
            for lm in _LABEL_RE.finditer(m.group("labels")):
                key = (family, lm.group(1))
                vals = label_vals.setdefault(key, set())
                vals.add(lm.group(2))
        try:
            float(m.group("value"))
        except ValueError:
            errors.append("line %d: non-numeric value %r"
                          % (ln, m.group("value")))
    if max_label_card is not None:
        for (family, label), vals in sorted(label_vals.items()):
            if len(vals) > max_label_card:
                errors.append(
                    "family %r label %r carries %d distinct values "
                    "(cap %d): unbounded label set"
                    % (family, label, len(vals), max_label_card))
    return errors


_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"')


_VALID_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def device_runtime_lines(prefix: str = "ceph_tpu") -> list[str]:
    """Device-runtime metric family (ceph_tpu.device): queue depth,
    bucket hit ratio, the ragged staging waste ratio
    (``device_bucket_waste_ratio`` — padded-but-empty over total
    staged words, the figure the bucket ladder exists to keep near
    zero), compile count, fallback state, the windowed utilization
    integrals (``device_util_busy`` / ``device_util_queue_wait`` /
    ``device_util_idle`` — the per-chip saturation signal the flight
    recorder's accounting derives), the continuous-dispatch stream
    gauges (``device_slot_occupancy`` — payload fraction of dispatched
    slot capacity, ``device_admission_wait`` — mean arrival->grant
    seconds of the admission loop, plus the independent-retire and
    pending counts), and the device_dispatch_seconds
    histogram — every dispatch ticket feeds these, so the
    accelerator's behavior is scrapeable beside the daemon counters.
    Every series carries a ``chip`` label (one per mesh chip, so a
    single lost chip is visible as ITS series flipping) plus the
    unlabeled mesh-size gauge."""
    from ..device.runtime import DeviceRuntime
    return DeviceRuntime.get().prom_lines(prefix)


def cluster_exporter(ctx, mon) -> PrometheusExporter:
    """Exporter pre-wired with the mgr prometheus module's core
    cluster gauges, fed from a monitor's map, plus the process's
    device-runtime series."""
    exp = PrometheusExporter(ctx)
    exp.add_renderer(device_runtime_lines)
    exp.add_gauge("ceph_osdmap_epoch", lambda: mon.osdmap.epoch,
                  "current osdmap epoch")
    exp.add_gauge("ceph_osd_count", lambda: mon.osdmap.max_osd,
                  "total osds")
    exp.add_gauge(
        "ceph_osd_up",
        lambda: sum(1 for o in range(mon.osdmap.max_osd)
                    if mon.osdmap.is_up(o)), "up osds")
    exp.add_gauge(
        "ceph_osd_in",
        lambda: sum(1 for o in range(mon.osdmap.max_osd)
                    if mon.osdmap.is_in(o)), "in osds")
    exp.add_gauge("ceph_pool_count", lambda: len(mon.osdmap.pools),
                  "pools")
    return exp
