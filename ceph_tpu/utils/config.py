"""Typed, layered configuration system.

Reference analog: Ceph's option framework — options declared with
type/level/default/min/max/enum/see_also in YAML
(src/common/options/*.yaml.in), merged from layered sources
(compiled defaults < conf file < centralized mon store < env < CLI <
runtime overrides) with change observers (md_config_obs_t).

This is a fresh design: options are declared in Python as `Option`
objects grouped into schemas; a `Config` instance resolves values through
an explicit source-priority stack and notifies observers on change.
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Iterable

OPT_STR = "str"
OPT_INT = "int"
OPT_FLOAT = "float"
OPT_BOOL = "bool"

_CASTS: dict[str, Callable[[Any], Any]] = {
    OPT_STR: str,
    OPT_INT: int,
    OPT_FLOAT: float,
    OPT_BOOL: lambda v: (
        v
        if isinstance(v, bool)
        else str(v).strip().lower() in ("1", "true", "yes", "on")
    ),
}

# Source priority, low to high.  Mirrors the reference's merge order:
# defaults < conf file < mon central store < env < cli < runtime.
SOURCES = ("default", "file", "mon", "env", "cli", "runtime")
_SOURCE_RANK = {s: i for i, s in enumerate(SOURCES)}


@dataclass(frozen=True)
class Option:
    """One declared configuration option."""

    name: str
    type: str = OPT_STR
    default: Any = None
    desc: str = ""
    level: str = "advanced"  # basic | advanced | dev
    min: Any = None
    max: Any = None
    enum_allowed: tuple = ()
    see_also: tuple = ()

    def cast(self, value: Any) -> Any:
        v = _CASTS[self.type](value)
        if self.min is not None and v < self.min:
            raise ValueError(f"{self.name}: {v} < min {self.min}")
        if self.max is not None and v > self.max:
            raise ValueError(f"{self.name}: {v} > max {self.max}")
        if self.enum_allowed and v not in self.enum_allowed:
            raise ValueError(f"{self.name}: {v!r} not in {self.enum_allowed}")
        return v


class Config:
    """Layered config resolver with observers.

    Values are stored per (option, source); lookup returns the value from
    the highest-priority source that has one, else the declared default.
    """

    def __init__(self, schema: Iterable[Option] = (), env_prefix: str = "CEPH_TPU_"):
        self._lock = threading.RLock()
        self._schema: dict[str, Option] = {}
        self._defaults: dict[str, Any] = {}  # pre-cast declared defaults
        self._values: dict[str, dict[str, Any]] = {}  # name -> source -> value
        self._observers: dict[str, list[Callable[[str, Any], None]]] = {}
        self._env_prefix = env_prefix
        self.register(DEFAULT_SCHEMA)
        self.register(schema)
        self._load_env()

    # -- schema ----------------------------------------------------------
    def register(self, options: Iterable[Option]) -> None:
        with self._lock:
            for opt in options:
                self._schema[opt.name] = opt
                if opt.default is not None:
                    self._defaults[opt.name] = opt.cast(opt.default)
        # late-registered options may have env overrides waiting
        if hasattr(self, "_env_prefix"):
            self._load_env()

    def option(self, name: str) -> Option:
        return self._schema[name]

    def schema(self) -> list[Option]:
        return sorted(self._schema.values(), key=lambda o: o.name)

    # -- sources ---------------------------------------------------------
    def load_file(self, path: str) -> None:
        """Load a JSON conf file ({option: value} or {section: {option: value}})."""
        with open(path) as f:
            data = json.load(f)
        flat: dict[str, Any] = {}
        for k, v in data.items():
            if isinstance(v, dict):
                flat.update(v)
            else:
                flat[k] = v
        # validate everything before committing anything, so a bad key or
        # value cannot leave the config half-applied
        casted = {}
        for k, v in flat.items():
            opt = self._schema.get(k)
            if opt is None:
                raise KeyError(f"unknown option {k!r} in {path}")
            casted[k] = opt.cast(v)
        for k, v in casted.items():
            self.set(k, v, source="file")

    def _load_env(self) -> None:
        for key, raw in os.environ.items():
            if key.startswith(self._env_prefix):
                name = key[len(self._env_prefix):].lower()
                if name in self._schema:
                    try:
                        self.set(name, raw, source="env")
                    except ValueError as e:
                        # a bad env var must not make the process
                        # unconstructable; warn and fall through
                        import sys

                        print(f"ceph-tpu: ignoring {key}: {e}", file=sys.stderr)

    def apply_mon_values(self, values: dict[str, Any]) -> None:
        """Apply the monitor config service's RESOLVED view: the push
        is authoritative for the whole 'mon' layer, so keys absent
        from it are cleared (a `config rm` must take effect on
        running daemons, not only after restart).  Unknown options or
        uncastable values are skipped — a newer cluster may push
        options this daemon's schema predates, and a poison value
        must never sever the dispatch loop."""
        with self._lock:
            stale = [n for n, per in self._values.items()
                     if "mon" in per and n not in values]
        for n in stale:
            try:
                self.rm(n, source="mon")
            except Exception:
                pass
        for k, v in dict(values).items():
            if k not in self._schema:
                continue
            try:
                self.set(k, v, source="mon")
            except (ValueError, TypeError, KeyError):
                continue
        return

    # -- get/set ---------------------------------------------------------
    def set(self, name: str, value: Any, source: str = "runtime") -> None:
        if source not in _SOURCE_RANK:
            raise ValueError(f"unknown config source {source!r}")
        opt = self._schema.get(name)
        if opt is None:
            raise KeyError(f"unknown option {name!r}")
        value = opt.cast(value)
        with self._lock:
            old = self.get(name)
            self._values.setdefault(name, {})[source] = value
            new = self.get(name)
            observers = list(self._observers.get(name, ()))
        if new != old:
            for fn in observers:
                fn(name, new)

    def rm(self, name: str, source: str = "runtime") -> None:
        with self._lock:
            old = self.get(name)
            self._values.get(name, {}).pop(source, None)
            new = self.get(name)
            observers = list(self._observers.get(name, ()))
        if new != old:
            for fn in observers:
                fn(name, new)

    def get(self, name: str, default: Any = None) -> Any:
        with self._lock:
            per_source = self._values.get(name)
            if per_source:
                for source in reversed(SOURCES):
                    if source in per_source:
                        return per_source[source]
        if name in self._defaults:
            return self._defaults[name]
        return default

    def __getitem__(self, name: str) -> Any:
        if name not in self._schema:
            raise KeyError(name)
        return self.get(name)

    # -- observers -------------------------------------------------------
    def add_observer(self, name: str, fn: Callable[[str, Any], None]) -> None:
        with self._lock:
            self._observers.setdefault(name, []).append(fn)

    # -- introspection ---------------------------------------------------
    def dump(self) -> dict[str, Any]:
        return {o.name: self.get(o.name) for o in self.schema()}

    def diff(self) -> dict[str, dict[str, Any]]:
        """Non-default values per source (admin `config diff` analog)."""
        with self._lock:
            return {n: dict(per) for n, per in self._values.items() if per}


DEFAULT_SCHEMA: list[Option] = [
    Option("log_level", OPT_INT, 1, "global log level (0-20)", min=0, max=20),
    Option("log_ring_size", OPT_INT, 10000, "crash-dump ring buffer entries"),
    Option("admin_socket", OPT_STR, "", "path for admin socket, empty=disabled"),
    Option("mon_addrs", OPT_STR, "", "comma-separated monitor host:port list"),
    Option("public_addr", OPT_STR, "", "daemon bind address"),
    Option("heartbeat_interval", OPT_FLOAT, 1.0, "osd peer heartbeat period (s)"),
    Option("heartbeat_grace", OPT_FLOAT, 6.0, "failure grace before reporting (s)"),
    Option("osd_slow_ping_time_ms", OPT_FLOAT, 0.0,
           "heartbeat RTT above this raises OSD_SLOW_PING_TIME for"
           " the peer pair; 0 derives 5 percent of heartbeat_grace"),
    Option("net_peer_max", OPT_INT, 32,
           "per-peer wire-stat rows an osd_stats net report keeps;"
           " the tail folds into an 'other' row"),
    Option("net_label_max", OPT_INT, 8,
           "peer labels per daemon the net exporter families keep;"
           " the tail folds into an 'other' label"),
    Option("mon_osd_down_out_interval", OPT_FLOAT, 30.0,
           "seconds before a down osd is auto-marked out"),
    Option("mon_osd_min_down_reporters", OPT_INT, 1,
           "distinct reporters required to mark an osd down"),
    Option("mon_lease", OPT_FLOAT, 5.0, "paxos lease duration (s)"),
    Option("mon_subscribe_renew_interval", OPT_FLOAT, 10.0,
           "map-subscription renewal period (s): repairs silently "
           "lost publications (partitions, dropped frames)"),
    Option("mon_election_strategy", OPT_STR, "classic",
           "leader election strategy (ElectionLogic modes)",
           enum_allowed=("classic", "disallow", "connectivity")),
    Option("mon_disallowed_leaders", OPT_STR, "",
           "comma-separated ranks that must never lead"
           " (disallow/connectivity strategies)"),
    Option("osd_pool_default_size", OPT_INT, 3, "default replica count"),
    Option("osd_pool_default_min_size", OPT_INT, 2, "min replicas to serve IO"),
    Option("osd_pool_default_pg_num", OPT_INT, 32, "default pg count"),
    Option("osd_op_num_shards", OPT_INT, 4, "op queue shards per osd"),
    Option("osd_mclock_capacity_iops", OPT_FLOAT, 10000.0,
           "assumed per-osd op capacity for mClock tag rates"),
    Option("osd_ec_subop_timeout", OPT_FLOAT, 10.0,
           "deadline for EC sub-op acks before marking peers behind"),
    Option("osd_op_complaint_time", OPT_FLOAT, 30.0,
           "age after which an in-flight tracked op counts as slow"
           " (feeds beacons and the SLOW_OPS health warning)"),
    Option("osd_op_history_size", OPT_INT, 20,
           "completed ops kept in the OpTracker historic ring"),
    Option("osd_op_history_slow_op_size", OPT_INT, 20,
           "completed slow ops kept in the slow historic ring"),
    Option("osd_beacon_report_interval", OPT_FLOAT, 1.0,
           "period of OSD->mon beacons carrying slow-op counts"),
    Option("auth_cluster_required", OPT_STR, "none",
           "cluster auth mode: none | shared (cephx analog)"),
    Option("auth_key", OPT_STR, "",
           "shared cluster secret (the keyring role)"),
    Option("ms_secure_mode", OPT_INT, 0,
           "1 = AEAD-encrypt every frame (ProtocolV2 secure mode)"),
    Option("ms_compress", OPT_STR, "",
           "comma-separated on-wire compression preferences"
           " (msgr2 compression_onwire role); empty = off"),
    Option("osd_recovery_max_active", OPT_INT, 8,
           "max concurrent recovery ops per osd"),
    Option("osd_max_pg_log_entries", OPT_INT, 2000,
           "pg log length before trimming (peers that fall behind the"
           " trimmed tail are backfilled instead of log-recovered)"),
    Option("ec_batch_max_stripes", OPT_INT, 4096,
           "max stripes aggregated into one device EC dispatch"),
    Option("ec_batch_flush_us", OPT_INT, 300,
           "flush-mode deadline before a partial EC batch is flushed"
           " (µs): the window the DEADLINE flush rides when"
           " device_dispatch_mode=flush (the continuous stream has no"
           " flush barrier and ignores it)"),
    Option("ec_batch_max_bytes", OPT_INT, 8 << 20,
           "flush-mode size trigger: a pending EC batch at or above"
           " this many staged bytes flushes immediately instead of"
           " waiting out ec_batch_flush_us"),
    Option("osd_objectstore", OPT_STR, "memstore",
           "backing store engine (src/common/options osd_objectstore)",
           enum_allowed=("memstore", "kstore", "extentstore")),
    Option("osd_data", OPT_STR, "",
           "store directory; empty = ephemeral (RAM engines)"),
    Option("extentstore_device_size", OPT_INT, 1 << 30,
           "initial (sparse) block device size in bytes"),
    Option("extentstore_deferred_threshold", OPT_INT, 65536,
           "writes at or under this many bytes take the deferred WAL"
           " path (bluestore_prefer_deferred_size role)"),
    Option("crush_backend", OPT_STR, "auto", "crush mapping backend",
           enum_allowed=("auto", "host", "jax", "native")),
    Option("ec_backend", OPT_STR, "auto", "erasure-code compute backend",
           enum_allowed=("auto", "host", "jax", "native")),
    # -- device runtime (ceph_tpu.device) -------------------------------
    Option("device_max_inflight", OPT_INT, 2,
           "max concurrent device dispatches (runtime admission bound)"),
    Option("device_queue_len", OPT_INT, 64,
           "dispatch-queue waiters before admission raises DeviceBusy"),
    Option("device_probe_interval", OPT_FLOAT, 1.0,
           "cap of the probe backoff while the device runtime is in"
           " host-fallback (ExpBackoff heal probes)"),
    Option("device_warmup", OPT_INT, 1,
           "pre-compile common EC shape buckets when a profile's codec"
           " is first built (0 disables)"),
    Option("device_dispatch_mode", OPT_STR, "stream",
           "EC dispatch architecture: 'stream' runs the persistent"
           " per-chip dispatch stream (continuous admission into"
           " fixed-geometry slots, independent retire — the"
           " continuous-batching recipe from LLM serving);"
           " 'flush' keeps the legacy accumulate-and-flush batcher"
           " (also the stream's host-fallback/DeviceBusy degradation"
           " route and the bench baseline)",
           enum_allowed=("stream", "flush")),
    Option("device_stream_interval_us", OPT_INT, 100,
           "admission-loop idle tick (µs) of the per-chip dispatch"
           " stream: the loop wakes immediately on arrivals and slot"
           " completions, and at most this long apart otherwise"),
    Option("device_stream_slot_words", OPT_INT, 1 << 19,
           "slot-ladder geometry cap: max words one stream slot group"
           " stages (a group covers its words with the pow2 bucket"
           " ladder, so slot programs are the same compiled family"
           " flush batching uses; ops larger than this mesh-shard"
           " like oversized flushes)"),
    Option("device_stream_max_slots", OPT_INT, 4,
           "concurrent slot dispatches a chip's stream keeps in"
           " flight; further admissions stay pending in the stream"
           " (where a later-arriving urgent class can still overtake)"
           " instead of parking deep in the device queue"),
    Option("device_shard_min_words", OPT_INT, 1 << 19,
           "EC flushes at or above this many words per chunk shard"
           " column-wise across every available mesh chip (the"
           " collective-free stripe-axis split); flushes below it"
           " stay on the caller's affinity chip"),
    Option("osd_pg_log_dups_tracked", OPT_INT, 128,
           "reqid (client,tid) dup-detection journal entries kept per"
           " PG (PrimaryLogPG osd_reqid_t dedup analog)"),
    Option("osd_mgr_report_interval", OPT_FLOAT, 2.0,
           "seconds between MMgrReports (perf counters + per-PG stat"
           " rows) to the active manager"),
    Option("mgr_stats_period", OPT_FLOAT, 1.0,
           "seconds between the mgr's PGMap digests to the monitors"
           " (feeds status/df/pool-stats and PG_* health checks)"),
    Option("mgr_stats_stale_after", OPT_FLOAT, 15.0,
           "per-PG stat rows older than this are dropped from the"
           " PGMap (a dead primary's last report must age out)"),
    Option("mgr_stats_prune_after", OPT_FLOAT, 60.0,
           "per-PG stat rows (and per-daemon report extras) with no"
           " refresh within this window are COMPACTED out of the"
           " mgr's column store, visibly counted"
           " (ceph_tpu_mgr_rows_pruned_total); folds already mask"
           " them at mgr_stats_stale_after, pruning reclaims the"
           " rows"),
    Option("osd_stats_columnar", OPT_BOOL, True,
           "ship per-PG stat rows as a packed columnar block"
           " (MMgrReport pg_stats_cols, the telemetry-fabric wire"
           " format the mgr ingests as one vectorized merge); off ="
           " legacy dict-shaped rows (mixed fleets converge to the"
           " same digest either way)"),
    Option("mon_crash_warn_age", OPT_FLOAT, 14 * 24 * 3600.0,
           "un-archived crash reports newer than this raise the"
           " RECENT_CRASH health warning (mgr/crash warn_recent_"
           "interval role)"),
    Option("mon_crash_retention", OPT_FLOAT, 30 * 24 * 3600.0,
           "ARCHIVED crash reports older than this are auto-pruned"
           " from the committed crash table at commit/tick time"
           " (mgr/crash retain_interval role); <= 0 disables"),
    Option("memstore_device_bytes", OPT_INT, 1 << 30,
           "nominal device size RAM stores report in statfs (the"
           " df raw-capacity denominator)"),
    Option("osd_crash_ring_tail", OPT_INT, 100,
           "LogRing entries captured into a crash report (the"
           " post-mortem high-verbosity context)"),
    # -- flight recorder (ceph_tpu.trace.recorder) -----------------------
    Option("flight_recorder_ring", OPT_INT, 2048,
           "span records kept in each daemon's flight-recorder ring"
           " (op spans, background-work spans)"),
    Option("flight_recorder_sample", OPT_INT, 4,
           "1-in-N trace sampling for retained op records (keyed on"
           " the trace id so a sampled write is complete on every"
           " daemon; slow ops are always retained; 1 keeps every"
           " trace)"),
    Option("device_util_window", OPT_FLOAT, 10.0,
           "window (s) of the per-chip utilization integrals"
           " (busy / queue-wait / idle fractions fed to the exporter,"
           " the mgr digest and `status`)"),
    # -- integrity plane (scrub scheduling + straggler handling) ---------
    Option("osd_scrub_interval", OPT_FLOAT, 24 * 3600.0,
           "seconds between automatic shallow scrubs of each PG"
           " (osd_scrub_min_interval role); <= 0 disables periodic"
           " scrubbing"),
    Option("osd_deep_scrub_interval", OPT_FLOAT, 7 * 24 * 3600.0,
           "seconds between automatic deep scrubs of each PG"
           " (byte digests vs the hinfo crc vote); <= 0 disables"),
    Option("osd_scrub_chunk_timeout", OPT_FLOAT, 5.0,
           "deadline for a replica's scrub map per chunk; a member"
           " that misses it (after one retry) is recorded"
           " unavailable — never conflated with object absence"),
    # -- scale plane (ceph_tpu.scale) ------------------------------------
    Option("mon_crush_osds_per_host", OPT_INT, 0,
           "group booting osds into straw2 host buckets of this size"
           " (chooseleaf-over-hosts rules, real failure domains, and"
           " O(hosts + size) placement draws instead of O(osds));"
           " 0 keeps the flat vstart root"),
    Option("mon_map_catchup_max", OPT_INT, 64,
           "a subscriber more than this many epochs behind is caught"
           " up with ONE full map instead of the whole incremental"
           " history (bounds late-joiner wire cost)"),
    Option("mon_propose_batch_window", OPT_FLOAT, 0.0,
           "seconds the mon folds storm-prone fire-and-forget"
           " mutations (boots, clog appends) into one proposal before"
           " committing; 0 = commit immediately (a 10k-shell boot"
           " storm would otherwise burn one epoch + full-map encode"
           " per boot)"),
    Option("shell_report_interval", OPT_FLOAT, 1.0,
           "period of a ShellOSD's beacon + synthetic-stats report"),
    Option("shell_objects_per_pg", OPT_INT, 8,
           "synthetic objects each shell PG reports (drives the"
           " misplaced/degraded accounting at scale)"),
    Option("shell_object_bytes", OPT_INT, 1 << 20,
           "synthetic bytes per shell object"),
    Option("shell_recovery_objects_per_s", OPT_FLOAT, 256.0,
           "simulated backfill drain rate per shell (misplaced"
           " objects recovered per second)"),
    Option("mgr_balancer_mode", OPT_STR, "batched",
           "upmap optimizer flavor: 'batched' scores thousands of"
           " candidate moves per tick in one device dispatch"
           " (scale.balancer); 'sequential' keeps the reference's"
           " greedy calc_pg_upmaps walk",
           enum_allowed=("batched", "sequential")),
    Option("mgr_balancer_max_changes", OPT_INT, 48,
           "upmap items committed per batched balancer tick (bounds"
           " the per-tick mon command fan-out)"),
    # -- tenant SLO plane (per-tenant QoS + mgr/slo.py burn engine) ------
    Option("osd_mclock_tenant_reservation", OPT_FLOAT, 0.05,
           "default per-tenant dmClock reservation (fraction of osd"
           " capacity) for tenants without an osd_mclock_tenant_qos"
           " row"),
    Option("osd_mclock_tenant_weight", OPT_FLOAT, 1.0,
           "default per-tenant dmClock weight"),
    Option("osd_mclock_tenant_limit", OPT_FLOAT, 1.0,
           "default per-tenant dmClock limit (fraction of osd"
           " capacity; the hard ceiling a bully tenant is throttled"
           " at)"),
    Option("osd_mclock_tenant_qos", OPT_STR, "",
           "per-tenant dmClock RWL rows:"
           " 'tenant:res_frac:weight:lim_frac,...' — e.g."
           " 'bully:0.05:0.5:0.15,victim:0.30:4:1.0'; tenants"
           " without a row take the osd_mclock_tenant_* defaults"),
    Option("tenant_tracking_max", OPT_INT, 64,
           "distinct tenants tracked per OSD (stage histograms, op"
           " counters, tag books); overflow tenants fold into the"
           " 'other' bucket so a tenant-id flood cannot grow daemon"
           " state without bound"),
    Option("tenant_label_max", OPT_INT, 32,
           "distinct tenant label values any exporter family may"
           " carry; overflow tenants fold into tenant=\"other\""
           " (Prometheus cardinality guard)"),
    Option("slo_latency_target_ms", OPT_FLOAT, 100.0,
           "per-tenant latency objective: the op duration a"
           " 'good' op must finish under (pow2-µs bucket"
           " resolution)"),
    Option("slo_latency_objective", OPT_FLOAT, 0.99,
           "fraction of a tenant's ops that must finish under the"
           " latency target (1 - objective is the error budget the"
           " burn rates divide by)"),
    Option("slo_fast_window", OPT_FLOAT, 60.0,
           "fast burn-rate window (s) of the multi-window SLO"
           " alerts (the page-now window)"),
    Option("slo_slow_window", OPT_FLOAT, 300.0,
           "slow burn-rate window (s) — both windows must burn for"
           " SLO_BURN to raise (one spike alone never pages)"),
    Option("slo_burn_fast", OPT_FLOAT, 14.4,
           "burn-rate threshold over the fast window (14.4 = the"
           " SRE-workbook 2%%-budget-in-1h rate)"),
    Option("slo_burn_slow", OPT_FLOAT, 6.0,
           "burn-rate threshold over the slow window"),
    Option("slo_min_ops", OPT_INT, 30,
           "minimum ops observed in the fast window before a"
           " tenant's SLO verdicts count (no alerts from noise)"),
    # -- history plane (downsampled metric rings + anomaly edges) --------
    Option("history_tiers", OPT_STR, "5:120,30:120,300:288",
           "downsampling ladder of the history rings as"
           " 'width_s:cells' pairs (default: ten minutes at 5s, an"
           " hour at 30s, a day at 5min — fixed memory by"
           " construction)"),
    Option("history_label_max", OPT_INT, 32,
           "distinct label values any history series may retain;"
           " overflow labels are dropped AND counted"
           " (dropped_labels), never silently folded"),
    Option("history_anomaly_series", OPT_STR,
           "device.busy_frac,device.queue_wait_frac,"
           "tenant.p99_ms,tenant.burn_fast,"
           "net.rtt_ms,net.resend_rate",
           "comma-separated HISTORY_SERIES names the anomaly engine"
           " watches for sustained upward shifts"),
    Option("history_anomaly_z", OPT_FLOAT, 6.0,
           "one-sided z-score a watched series must sustain to"
           " raise PERF_ANOMALY (deliberately deaf: routine load"
           " swings never page)"),
    Option("history_anomaly_clear_z", OPT_FLOAT, 2.0,
           "z-score a raised series must drop below (sustained) to"
           " clear; between raise and clear the baseline is frozen"),
    Option("history_anomaly_sustain", OPT_INT, 8,
           "consecutive hot ticks before a shifted series raises"),
    Option("history_anomaly_clear", OPT_INT, 4,
           "consecutive cooled ticks before a raised series clears"),
    Option("history_anomaly_min_samples", OPT_INT, 60,
           "warm-up samples before a series' z-scores count (a"
           " fresh baseline must settle before it can page)"),
    Option("history_anomaly_alpha", OPT_FLOAT, 0.05,
           "EWMA weight of the anomaly baseline's mean/variance"
           " once warmed up"),
]
