"""Coding-matrix generators matching the jerasure / ISA-L families.

The reference plugins delegate matrix construction to vendored C libraries
(src/erasure-code/jerasure/ErasureCodeJerasure.cc:203 reed_sol_vandermonde_
coding_matrix, :255 reed_sol_r6_coding_matrix, :323/:333 cauchy matrices;
src/erasure-code/isa/ErasureCodeIsa.cc gf_gen_rs_matrix / gf_gen_cauchy1_
matrix).  These generators re-derive the published algorithms (Plank's
jerasure 2.0 reed_sol.c / cauchy.c; intel isa-l gf_gen_* in ec_base.c) so
that coding matrices — and therefore encoded bytes — agree with the
reference plugins for the same profile.

All matrices are python int row-lists; the kernels consume numpy/jnp views.
"""

from __future__ import annotations

import functools

from .gf import gf_inv, gf_mul, matrix_invert

Matrix = list[list[int]]


# ---------------------------------------------------------------------------
# jerasure: reed_sol_van (reed_sol.c)
# ---------------------------------------------------------------------------

def extended_vandermonde_matrix(rows: int, cols: int, w: int) -> Matrix:
    """rows x cols extended Vandermonde: first row e_0, last row e_{cols-1},
    middle rows are geometric in the row index."""
    if w < 30 and ((1 << w) < rows or (1 << w) < cols):
        raise ValueError("field too small for %dx%d" % (rows, cols))
    vdm = [[0] * cols for _ in range(rows)]
    vdm[0][0] = 1
    if rows == 1:
        return vdm
    vdm[rows - 1][cols - 1] = 1
    for i in range(1, rows - 1):
        acc = 1
        for j in range(cols):
            vdm[i][j] = acc
            acc = gf_mul(acc, i, w)
    return vdm


def big_vandermonde_distribution_matrix(rows: int, cols: int, w: int) -> Matrix:
    """Column-eliminate the extended Vandermonde so the top cols x cols block
    is the identity, then normalise so row `cols` (the first coding row) is
    all ones. Elementary row/column scalings preserve the MDS property."""
    if cols >= rows:
        raise ValueError("rows must exceed cols")
    dist = extended_vandermonde_matrix(rows, cols, w)

    for i in range(1, cols):
        # pivot search downward in column i
        j = next((r for r in range(i, rows) if dist[r][i] != 0), None)
        if j is None:
            raise ValueError("could not build distribution matrix")
        if j != i:
            dist[i], dist[j] = dist[j], dist[i]
        # scale column i so the pivot is 1
        if dist[i][i] != 1:
            inv = gf_inv(dist[i][i], w)
            for r in range(rows):
                dist[r][i] = gf_mul(inv, dist[r][i], w)
        # zero the rest of row i via column operations
        for j in range(cols):
            t = dist[i][j]
            if j != i and t != 0:
                for r in range(rows):
                    dist[r][j] ^= gf_mul(t, dist[r][i], w)

    # make row `cols` all ones: scale each column by the inverse of its
    # row-`cols` entry, then rescale the identity row it disturbed
    for j in range(cols):
        t = dist[cols][j]
        if t == 0:
            raise ValueError("zero in first coding row")
        if t != 1:
            inv = gf_inv(t, w)
            for r in range(rows):
                dist[r][j] = gf_mul(inv, dist[r][j], w)
            t2 = dist[j][j]
            if t2 != 1:
                inv2 = gf_inv(t2, w)
                for c in range(cols):
                    dist[j][c] = gf_mul(inv2, dist[j][c], w)
    return dist


def reed_sol_vandermonde_coding_matrix(k: int, m: int, w: int) -> Matrix:
    """The m x k coding block of the systematic distribution matrix
    (jerasure reed_sol.c; row 0 is all ones)."""
    dist = big_vandermonde_distribution_matrix(k + m, k, w)
    return [row[:] for row in dist[k:]]


def reed_sol_r6_coding_matrix(k: int, w: int) -> Matrix:
    """RAID6: P row all ones, Q row powers of 2 (reed_sol.c)."""
    matrix = [[1] * k, [0] * k]
    acc = 1
    for j in range(k):
        matrix[1][j] = acc
        acc = gf_mul(acc, 2, w)
    return matrix


# ---------------------------------------------------------------------------
# jerasure: cauchy (cauchy.c)
# ---------------------------------------------------------------------------

def cauchy_original_coding_matrix(k: int, m: int, w: int) -> Matrix:
    """matrix[i][j] = 1 / (i XOR (m+j)) in GF(2^w)."""
    if w < 31 and (k + m) > (1 << w):
        raise ValueError("k+m too large for w")
    return [[gf_inv(i ^ (m + j), w) for j in range(k)] for i in range(m)]


@functools.lru_cache(maxsize=None)
def n_ones(val: int, w: int) -> int:
    """Number of ones in the w x w bitmatrix of `val`: sum over columns c of
    popcount(val * 2^c) (cauchy.c cauchy_n_ones)."""
    total = 0
    cur = val
    for _ in range(w):
        total += bin(cur).count("1")
        cur = gf_mul(cur, 2, w)
    return total


def cauchy_improve_coding_matrix(k: int, m: int, w: int, matrix: Matrix) -> None:
    """Normalise the first row to ones, then greedily divide each later row
    by whichever of its elements minimises the total bitmatrix ones."""
    for j in range(k):
        if matrix[0][j] != 1:
            inv = gf_inv(matrix[0][j], w)
            for i in range(m):
                matrix[i][j] = gf_mul(matrix[i][j], inv, w)
    for i in range(1, m):
        row = matrix[i]
        best_cost = sum(n_ones(x, w) for x in row)
        best_row = row[:]
        for j in range(k):
            if row[j] in (0, 1):
                continue
            inv = gf_inv(row[j], w)
            cand = [gf_mul(x, inv, w) for x in row]
            cost = sum(n_ones(x, w) for x in cand)
            if cost < best_cost:
                best_cost = cost
                best_row = cand
        matrix[i] = best_row
    return


@functools.lru_cache(maxsize=None)
def _cbest_values(w: int, count: int) -> tuple[int, ...]:
    """Elements of GF(2^w)\\{0} ordered by bitmatrix ones count (the
    precomputed cbest tables in cauchy_best_r6.c), ties by value."""
    vals = sorted(range(1, 1 << w), key=lambda v: (n_ones(v, w), v))
    return tuple(vals[:count])


def cauchy_good_general_coding_matrix(k: int, m: int, w: int) -> Matrix:
    """cauchy_good: special-cased RAID6 best-element row for m==2, else the
    original Cauchy matrix improved for XOR count."""
    if m == 2 and w <= 10 and k <= (1 << w) - 1:
        # jerasure serves this from precomputed cbest tables; computing the
        # ordering is only tractable for small w — larger w falls through
        # to the improved general matrix
        best = _cbest_values(w, k)
        return [[1] * k, list(best)]
    matrix = cauchy_original_coding_matrix(k, m, w)
    cauchy_improve_coding_matrix(k, m, w, matrix)
    return matrix


# ---------------------------------------------------------------------------
# jerasure: bit-matrix conversion (jerasure.c)
# ---------------------------------------------------------------------------

def matrix_to_bitmatrix(k: int, m: int, w: int, matrix: Matrix) -> list[list[int]]:
    """Expand each GF element into a w x w binary block: block column x is
    the bit-vector of elt * 2^x, bit l landing in block row l."""
    bits = [[0] * (k * w) for _ in range(m * w)]
    for i in range(m):
        for j in range(k):
            elt = matrix[i][j]
            for x in range(w):
                for l in range(w):
                    bits[i * w + l][j * w + x] = (elt >> l) & 1
                elt = gf_mul(elt, 2, w)
    return bits


def gf2_invert(rows: list[list[int]]) -> list[list[int]]:
    """Invert a square 0/1 matrix over GF(2)."""
    n = len(rows)
    a = [list(r) for r in rows]
    inv = [[1 if i == j else 0 for j in range(n)] for i in range(n)]
    for col in range(n):
        piv = next((r for r in range(col, n) if a[r][col]), None)
        if piv is None:
            raise ValueError("singular GF(2) matrix")
        if piv != col:
            a[col], a[piv] = a[piv], a[col]
            inv[col], inv[piv] = inv[piv], inv[col]
        for r in range(n):
            if r != col and a[r][col]:
                a[r] = [x ^ y for x, y in zip(a[r], a[col])]
                inv[r] = [x ^ y for x, y in zip(inv[r], inv[col])]
    return inv


def survivor_bitrows(k: int, w: int, bitmatrix, survivors) -> list[list[int]]:
    """Bit-level rows of the generator [I; B] for the first k surviving
    chunks — the system a bitmatrix decode inverts."""
    rows = []
    for cid in survivors[:k]:
        for l in range(w):
            if cid < k:
                row = [0] * (k * w)
                row[cid * w + l] = 1
            else:
                row = [int(v) for v in bitmatrix[(cid - k) * w + l]]
            rows.append(row)
    return rows


# ---------------------------------------------------------------------------
# ISA-L: ec_base.c generators
# ---------------------------------------------------------------------------

def isa_rs_vandermonde_matrix(k: int, m: int) -> Matrix:
    """gf_gen_rs_matrix coding block: row i (i>=0) is powers of 2^i —
    a[k+i][j] = (2^i)^j in GF(2^8). NOT always MDS for large m; the
    reference plugin restricts it (ErasureCodeIsa.cc applies it for the
    default profile and validates invertibility at decode time)."""
    rows = []
    gen = 1
    for _ in range(m):
        p = 1
        row = []
        for _ in range(k):
            row.append(p)
            p = gf_mul(p, gen, 8)
        gen = gf_mul(gen, 2, 8)
        rows.append(row)
    return rows


def isa_cauchy_matrix(k: int, m: int) -> Matrix:
    """gf_gen_cauchy1_matrix coding block: a[k+i][j] = 1/(i XOR j) for
    i in [k, k+m), j in [0, k)."""
    if k + m > 256:
        raise ValueError("k+m=%d exceeds GF(2^8) capacity" % (k + m))
    return [[gf_inv(i ^ j, 8) for j in range(k)] for i in range(k, k + m)]


# ---------------------------------------------------------------------------
# decode-side matrix assembly (shared by plugins)
# ---------------------------------------------------------------------------

def decoding_matrix(
    k: int, w: int, coding: Matrix, erased: list[int], surviving: list[int],
) -> tuple[Matrix, list[int]]:
    """Build the k x k matrix mapping k surviving chunks to the k data
    chunks: take rows of [I; C] for the first k surviving chunk ids,
    invert. Returns (inverse, chosen_ids). Mirrors the jerasure
    jerasure_make_decoding_matrix / isa-l invert flow
    (ErasureCodeIsa.cc:253-307)."""
    lost = set(erased)
    if lost & set(surviving):
        raise ValueError("erased chunks listed as surviving")
    chosen = surviving[:k]
    if len(chosen) < k:
        raise ValueError("not enough surviving chunks")
    rows = []
    for cid in chosen:
        if cid < k:
            rows.append([1 if j == cid else 0 for j in range(k)])
        else:
            rows.append(list(coding[cid - k]))
    return matrix_invert(rows, w), chosen
