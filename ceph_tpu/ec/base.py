"""Shared erasure-code behavior: padding, chunk mapping, read planning.

Re-derivation of the reference base class (src/erasure-code/
ErasureCode.cc): encode_prepare zero-pads the object tail so every data
chunk is exactly get_chunk_size(len) bytes (:150-185), encode trims to
want_to_encode (:187-203), _decode passes surviving chunks through and
fills the rest via decode_chunks (:205-241), minimum_to_decode returns
want_to_read when fully available else the first k available (:102-119),
and the "mapping" profile string (D=data) permutes chunk positions
(:260-279).
"""

from __future__ import annotations

from typing import Mapping, Sequence

from .interface import ErasureCodeInterface, ErasureCodeProfile


class ErasureCode(ErasureCodeInterface):
    """Base class: subclasses set self.k / self.m in init() and implement
    encode_chunks / decode_chunks and get_chunk_size."""

    def __init__(self):
        self.k = 0
        self.m = 0
        self.chunk_mapping: list[int] = []
        self._profile: ErasureCodeProfile = {}

    # -- profile helpers ---------------------------------------------------

    @staticmethod
    def _to_int(profile: dict, name: str, default: int) -> int:
        v = profile.get(name)
        if v is None or v == "":
            profile[name] = str(default)
            return default
        try:
            return int(v)
        except (TypeError, ValueError):
            raise ValueError("profile %s=%r is not an integer" % (name, v))

    @staticmethod
    def _to_bool(profile: dict, name: str, default: str) -> bool:
        v = profile.get(name)
        if v is None or v == "":
            profile[name] = default
            v = default
        return str(v) in ("yes", "true", "True", "1")

    def _parse_mapping(self, profile: dict) -> None:
        mapping = profile.get("mapping")
        if not mapping:
            return
        data_pos = [i for i, c in enumerate(mapping) if c == "D"]
        coding_pos = [i for i, c in enumerate(mapping) if c != "D"]
        self.chunk_mapping = data_pos + coding_pos

    def sanity_check_k_m(self) -> None:
        if self.k < 2:
            raise ValueError("k=%d must be >= 2" % self.k)
        if self.m < 1:
            raise ValueError("m=%d must be >= 1" % self.m)

    # -- interface basics --------------------------------------------------

    def get_profile(self) -> ErasureCodeProfile:
        return self._profile

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_chunk_mapping(self) -> Sequence[int]:
        return self.chunk_mapping

    def chunk_index(self, i: int) -> int:
        return self.chunk_mapping[i] if i < len(self.chunk_mapping) else i

    def _to_logical(self, chunks: Mapping[int, bytes]) -> dict[int, bytes]:
        """Translate physical chunk ids back to generator-row (logical)
        ids so codec math is mapping-transparent."""
        if not self.chunk_mapping:
            return dict(chunks)
        inv = {p: l for l, p in enumerate(self.chunk_mapping)}
        return {inv.get(i, i): v for i, v in chunks.items()}

    def _from_logical(self, chunks: dict[int, bytes]) -> dict[int, bytes]:
        if not self.chunk_mapping:
            return chunks
        return {self.chunk_index(i): v for i, v in chunks.items()}

    def _logical_ids(self, ids) -> set[int]:
        if not self.chunk_mapping:
            return set(ids)
        inv = {p: l for l, p in enumerate(self.chunk_mapping)}
        return {inv.get(i, i) for i in ids}

    # -- object-level encode/decode ---------------------------------------

    def encode_prepare(self, data: bytes) -> dict[int, bytes]:
        """Split into k chunks of get_chunk_size(len), zero-padding the
        tail chunks."""
        k = self.get_data_chunk_count()
        blocksize = self.get_chunk_size(len(data))
        if blocksize == 0:  # zero-length object: k+m empty chunks
            return {self.chunk_index(i): b"" for i in range(k)}
        chunks: dict[int, bytes] = {}
        full = len(data) // blocksize
        for i in range(full):
            chunks[self.chunk_index(i)] = data[i * blocksize:(i + 1) * blocksize]
        if full < k:
            rest = data[full * blocksize:]
            chunks[self.chunk_index(full)] = rest.ljust(blocksize, b"\0")
            zero = bytes(blocksize)
            for i in range(full + 1, k):
                chunks[self.chunk_index(i)] = zero
        return chunks

    def encode(self, want_to_encode: set[int], data: bytes) -> dict[int, bytes]:
        if len(data) == 0:
            return {i: b"" for i in want_to_encode}
        prepared = self.encode_prepare(data)
        encoded = self.encode_chunks(prepared)
        return {i: encoded[i] for i in want_to_encode}

    # -- device offload (TPU path) ------------------------------------

    def _device_matrix(self):
        """(matrix, w) when this codec is a plain GF(2^w) matrix code
        whose encode is a region matmul — the shape the device batcher
        offloads.  None keeps the sync host path for the base
        encode/decode routing (layered/shingled codes override the
        async entry points instead and dispatch their own step
        matrices through `_device_matmul`)."""
        return None

    def device_families(self) -> list[tuple]:
        """The (matrix, w) program families this codec's device
        dispatches ride — what `warmup_ec` should pre-compile at OSD
        boot so the first flush/repair after boot hits the compile
        cache.  Plain matrix codecs have exactly their coding matrix;
        layered/shingled codecs override with their per-step matrices
        (LRC layers, SHEC single-failure decode, CLAY MDS rows)."""
        dm = self._device_matrix()
        return [dm] if dm is not None else []

    async def _device_matmul(self, matrix, w: int, data,
                             klass: str | None = None,
                             on_ticket=None, chip: int | None = None,
                             tenant: str | None = None):
        """One batched GF(2^w) region matmul on the caller's affinity
        chip via the device batcher ([rows, k] x [k, n] words ->
        [rows, n]), or None when the device plane is unavailable
        (offload disabled / chip poisoned) so the caller takes its
        bit-identical host path.  Once admitted, DeviceBusy and
        mid-dispatch chip loss degrade INSIDE the batcher (host
        re-encode, futures retired exactly once), exactly like the RS
        flush path."""
        from ..device.runtime import DeviceRuntime, K_CLIENT_EC
        from .batcher import DeviceBatcher, device_offload_enabled
        if not device_offload_enabled() \
                or not DeviceRuntime.get().chip_available(chip):
            return None
        return await DeviceBatcher.get().encode(
            [list(r) for r in matrix], int(w), data,
            klass=klass or K_CLIENT_EC, on_ticket=on_ticket,
            chip=chip, tenant=tenant)

    @staticmethod
    def _word_dtype(w: int):
        import numpy as np
        return {8: np.uint8, 16: "<u2", 32: "<u4"}[w]

    async def encode_async(self, want_to_encode: set[int],
                           data: bytes, klass: str | None = None,
                           on_ticket=None, chip: int | None = None,
                           tenant: str | None = None
                           ) -> dict[int, bytes]:
        """encode() with the GF matmul batched onto the device across
        concurrent callers (ECBackend's hot call,
        src/osd/ECTransaction.cc:56 -> encode_chunks).  Falls back to
        the sync host path when offload is disabled, the codec has no
        plain matrix form, or the caller's mesh chip is in fallback.

        klass selects the device dispatch class (client-EC vs
        recovery-EC admission weights); chip is the caller's mesh
        affinity (OSDs pass their bound chip — a poisoned chip
        degrades only its own OSDs); on_ticket receives the flush's
        DispatchTicket for exact per-op attribution."""
        from ..device.runtime import DeviceRuntime, K_CLIENT_EC
        from .batcher import DeviceBatcher, device_offload_enabled
        dm = self._device_matrix()
        if dm is None or len(data) == 0 or not device_offload_enabled() \
                or not DeviceRuntime.get().chip_available(chip):
            return self.encode(want_to_encode, data)
        import numpy as np
        matrix, w = dm
        prepared = self.encode_prepare(data)
        arr = np.stack([
            np.frombuffer(prepared[self.chunk_index(i)],
                          dtype=self._word_dtype(w))
            for i in range(self.get_data_chunk_count())])
        parity = await DeviceBatcher.get().encode(
            matrix, w, arr, klass=klass or K_CLIENT_EC,
            on_ticket=on_ticket, chip=chip, tenant=tenant)
        out = dict(prepared)
        for i in range(len(matrix)):
            out[self.chunk_index(
                self.get_data_chunk_count() + i)] = parity[i].tobytes()
        return {i: out[i] for i in want_to_encode}

    def parity_delta(self, deltas: Mapping[int, bytes]
                     ) -> dict[int, bytes]:
        """Host parity updates for a partial overwrite (the
        XOR-delta formulation of arXiv:2108.02692): given
        ``delta_j = new_j XOR old_j`` for each touched data chunk j
        (logical/generator-row index; all values the same length),
        returns {parity row i: XOR-delta to apply to parity chunk i}:

            new_parity_i = old_parity_i XOR sum_j gfmul(M[i][j],
                                                        delta_j)

        Exact under GF linearity for any matrix codec.  This is the
        scalar numpy path — `delta_async` routes the same math through
        the device batcher and falls back here.

        Sub-word-aligned regions (w=16/32, length not a word
        multiple): the tail is zero-padded to the word boundary and
        the returned parity deltas carry the word-aligned length — a
        sub-word overwrite dirties its whole containing parity word
        (GF(2^w) products mix bits across the word), so callers must
        apply the delta over the word-aligned envelope of the region
        (the region's START must already be word-aligned; the OSD
        delta path floors/ceils its column intervals)."""
        dm = self._device_matrix()
        if dm is None:
            raise ValueError(
                "codec has no plain matrix form for parity deltas")
        import numpy as np

        from . import gf
        matrix, w = dm
        m = len(matrix)
        dtype = np.dtype(self._word_dtype(w))
        lengths = {len(d) for d in deltas.values()}
        if len(lengths) > 1:
            raise ValueError(
                "delta regions have differing lengths %s" % lengths)
        word = dtype.itemsize
        pad = (-(lengths.pop() if lengths else 0)) % word
        arrs = {int(j): np.frombuffer(
                    bytes(d) + b"\0" * pad if pad else d, dtype=dtype)
                for j, d in deltas.items()}
        n = next(iter(arrs.values())).shape[0] if arrs else 0
        out: dict[int, bytes] = {}
        for i in range(m):
            acc = np.zeros(n, dtype=dtype)
            for j, darr in arrs.items():
                c = int(matrix[i][j])
                if int(w) == 8:
                    gf.region_mad_u8(acc, darr, c)
                else:
                    gf.region_mad_words(acc, darr, c, int(w))
            out[i] = acc.tobytes()
        return out

    async def delta_async(self, deltas: Mapping[int, bytes],
                          klass: str | None = None,
                          on_ticket=None, chip: int | None = None,
                          tenant: str | None = None
                          ) -> dict[int, bytes]:
        """`parity_delta` with the GF products batched onto the device
        (the OSD partial-write hot call, osd/ecbackend.py
        `_try_delta_write`): concurrent small overwrites across
        PGs/objects aggregate their (coefficient column, delta words)
        products into one dispatch on the caller's affinity chip.

        The delta rides the codec's FULL coding matrix with zero rows
        for untouched data chunks — zero rows contribute nothing under
        GF linearity, so delta flushes share the encode streams and
        compiled bucket programs, and batch with ordinary full writes
        into the same device dispatch.  Sub-word-aligned regions on
        w=16/32 codecs are zero-padded to the word boundary and
        dispatch on device like any other delta (they used to fall
        back to host): the returned parity deltas carry the
        word-aligned length, identical to `parity_delta`'s host
        semantics, and callers apply them over the aligned envelope.
        Host fallback (offload off, chip poisoned) is `parity_delta`'s
        numpy path; DeviceBusy and mid-flush device loss degrade
        inside the batcher the same way encode flushes do.  `on_ticket`
        receives the flush's DispatchTicket (exact per-op
        `op_ec_device_dispatch` attribution); host-served deltas
        deliver none."""
        from ..device.runtime import DeviceRuntime, K_CLIENT_EC
        from .batcher import DeviceBatcher, device_offload_enabled
        if not deltas:
            return {}
        dm = self._device_matrix()
        if dm is None:
            raise ValueError(
                "codec has no plain matrix form for parity deltas")
        import numpy as np
        matrix, w = dm
        word = np.dtype(self._word_dtype(w)).itemsize
        lengths = {len(d) for d in deltas.values()}
        if len(lengths) != 1:
            raise ValueError(
                "delta regions have differing lengths %s" % lengths)
        nbytes = lengths.pop()
        if (nbytes == 0 or not device_offload_enabled()
                or not DeviceRuntime.get().chip_available(chip)):
            return self.parity_delta(deltas)
        pad = (-nbytes) % word
        k = self.get_data_chunk_count()
        arr = np.zeros((k, (nbytes + pad) // word),
                       dtype=self._word_dtype(w))
        for j, d in deltas.items():
            arr[int(j)] = np.frombuffer(
                bytes(d) + b"\0" * pad if pad else d,
                dtype=self._word_dtype(w))
        parity = await DeviceBatcher.get().encode(
            matrix, w, arr, klass=klass or K_CLIENT_EC,
            on_ticket=on_ticket, chip=chip, tenant=tenant)
        return {i: parity[i].tobytes() for i in range(len(matrix))}

    async def decode_async(self, want_to_read: set[int],
                           chunks: Mapping[int, bytes],
                           klass: str | None = None,
                           on_ticket=None,
                           chip: int | None = None) -> dict[int, bytes]:
        """decode() with the reconstruction matmul batched onto the
        device (the ECBackend degraded-read/recovery call,
        src/osd/ECUtil.cc:12-121).  Reconstruction is an encode with
        the inverted-survivor matrix, so it shares the encode queue
        (and the caller's chip affinity)."""
        from ..device.runtime import DeviceRuntime, K_CLIENT_EC
        from .batcher import (DeviceBatcher, device_offload_enabled,
                              reconstruct_matrix)
        dm = self._device_matrix()
        if (dm is None or not device_offload_enabled()
                or not DeviceRuntime.get().chip_available(chip)
                or self.chunk_mapping
                or want_to_read <= set(chunks)
                or any(len(c) == 0 for c in chunks.values())):
            return self.decode(want_to_read, chunks)
        if len(chunks) < self.get_data_chunk_count():
            raise IOError(
                "cannot decode: %d chunks available, %d needed"
                % (len(chunks), self.get_data_chunk_count()))
        lengths = {len(c) for c in chunks.values()}
        if len(lengths) != 1:
            raise ValueError(
                "surviving chunks have differing sizes %s" % lengths)
        import numpy as np
        matrix, w = dm
        k = self.get_data_chunk_count()
        have = tuple(sorted(chunks))
        erased = tuple(i for i in sorted(want_to_read)
                       if i not in chunks)
        rows, chosen = reconstruct_matrix(k, w, matrix, erased, have)
        arr = np.stack([
            np.frombuffer(chunks[c], dtype=self._word_dtype(w))
            for c in chosen])
        words = await DeviceBatcher.get().encode(
            rows, w, arr, klass=klass or K_CLIENT_EC,
            on_ticket=on_ticket, chip=chip)
        out = {}
        for j, e in enumerate(erased):
            out[e] = words[j].tobytes()
        for i in want_to_read:
            if i in chunks:
                out[i] = bytes(chunks[i])
        return out

    async def decode_concat_async(self, chunks: Mapping[int, bytes],
                                  klass: str | None = None,
                                  on_ticket=None,
                                  chip: int | None = None) -> bytes:
        k = self.get_data_chunk_count()
        want = {self.chunk_index(i) for i in range(k)}
        decoded = await self.decode_async(want, chunks, klass=klass,
                                          on_ticket=on_ticket,
                                          chip=chip)
        return b"".join(decoded[self.chunk_index(i)]
                        for i in range(k))

    # Locality-aware codes (LRC, SHEC) can repair from FEWER than k
    # chunks (a local group / shingle window); they clear this flag so
    # _decode skips the k-chunk floor while keeping the size check.
    REQUIRES_K_CHUNKS = True

    def _decode(
        self, want_to_read: set[int], chunks: Mapping[int, bytes],
    ) -> dict[int, bytes]:
        if want_to_read <= set(chunks):
            return {i: bytes(chunks[i]) for i in want_to_read}
        if self.REQUIRES_K_CHUNKS and \
                len(chunks) < self.get_data_chunk_count():
            raise IOError(
                "cannot decode: %d chunks available, %d needed"
                % (len(chunks), self.get_data_chunk_count()))
        lengths = {len(c) for c in chunks.values()}
        if len(lengths) != 1:
            raise ValueError("surviving chunks have differing sizes %s" % lengths)
        decoded = self.decode_chunks(want_to_read, chunks)
        out = {}
        for i in want_to_read:
            out[i] = bytes(chunks[i]) if i in chunks else decoded[i]
        return out

    def decode(
        self, want_to_read: set[int], chunks: Mapping[int, bytes],
        chunk_size: int = 0,
    ) -> dict[int, bytes]:
        return self._decode(want_to_read, chunks)

    def decode_concat(self, chunks: Mapping[int, bytes]) -> bytes:
        k = self.get_data_chunk_count()
        want = {self.chunk_index(i) for i in range(k)}
        decoded = self._decode(want, chunks)
        return b"".join(decoded[self.chunk_index(i)] for i in range(k))

    # -- read planning -----------------------------------------------------

    def _minimum_to_decode(
        self, want_to_read: set[int], available: set[int],
    ) -> set[int]:
        if want_to_read <= available:
            return set(want_to_read)
        k = self.get_data_chunk_count()
        if len(available) < k:
            raise IOError("cannot decode: only %d of %d chunks available"
                          % (len(available), k))
        return set(sorted(available)[:k])

    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int],
    ) -> dict[int, list[tuple[int, int]]]:
        ids = self._minimum_to_decode(want_to_read, available)
        whole = [(0, self.get_sub_chunk_count())]
        return {i: list(whole) for i in ids}

    def minimum_to_decode_with_cost(
        self, want_to_read: set[int], available: Mapping[int, int],
    ) -> set[int]:
        return self._minimum_to_decode(want_to_read, set(available))
