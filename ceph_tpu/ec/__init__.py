"""Erasure coding: GF math, codec plugins, TPU kernels.

Public surface:
    new_codec(profile)            — build a codec from a profile dict
    ErasureCodePluginRegistry     — the plugin registry singleton
    ErasureCodeInterface          — codec contract
"""

from .interface import ErasureCodeInterface, ErasureCodeProfile
from .plugin import ErasureCodePluginRegistry, register_plugin


def new_codec(profile: ErasureCodeProfile) -> ErasureCodeInterface:
    """Instantiate a codec: profile must carry plugin=<name> (default
    jerasure) plus plugin-specific keys (k, m, technique, ...)."""
    plugin = profile.get("plugin", "jerasure")
    return ErasureCodePluginRegistry.instance().factory(plugin, profile)


__all__ = [
    "ErasureCodeInterface",
    "ErasureCodeProfile",
    "ErasureCodePluginRegistry",
    "register_plugin",
    "new_codec",
]
