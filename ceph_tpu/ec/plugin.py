"""Erasure-code plugin registry.

The reference gates every codec behind a dlopen plugin registry
(src/erasure-code/ErasureCodePlugin.cc:36-180: singleton, factory(),
load(), preload()).  Here plugins are python entry modules registered
under `ceph_tpu.ec.plugins.<name>` — same boundary (codecs are looked
up by name + profile at pool creation, never linked directly), without
the dynamic-linker failure modes.  The loader still reproduces the
observable failure handling the reference tests exercise
(src/test/erasure-code/ErasureCodePluginFailToInitialize.cc etc.):
missing entry point, version mismatch, failing factory.
"""

from __future__ import annotations

import importlib
import threading
from typing import Callable

from .interface import ErasureCodeInterface, ErasureCodeProfile

PLUGIN_API_VERSION = 1


class ErasureCodePlugin:
    """A named codec factory. Modules register one via register_plugin."""

    def __init__(self, name: str,
                 factory: Callable[[ErasureCodeProfile], ErasureCodeInterface],
                 version: int = PLUGIN_API_VERSION):
        self.name = name
        self.factory = factory
        self.version = version


class ErasureCodePluginRegistry:
    """Process-wide name -> plugin table with lazy module loading."""

    _instance: "ErasureCodePluginRegistry | None" = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._lock = threading.Lock()
        self._plugins: dict[str, ErasureCodePlugin] = {}
        self.disable_dlclose = False  # parity knob; no-op here

    @classmethod
    def instance(cls) -> "ErasureCodePluginRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = ErasureCodePluginRegistry()
            return cls._instance

    def add(self, name: str, plugin: ErasureCodePlugin) -> None:
        with self._lock:
            if name in self._plugins:
                raise KeyError("plugin %s already registered" % name)
            self._plugins[name] = plugin

    def get(self, name: str) -> ErasureCodePlugin | None:
        with self._lock:
            return self._plugins.get(name)

    def load(self, name: str, module_path: str | None = None) -> ErasureCodePlugin:
        """Import the plugin module (which must call register_plugin) and
        return the registered plugin."""
        plugin = self.get(name)
        if plugin is None:
            path = module_path or ("ceph_tpu.ec.plugins." + name)
            try:
                importlib.import_module(path)
            except ImportError as e:
                raise IOError("erasure-code plugin %s: load failed: %s"
                              % (name, e))
            plugin = self.get(name)
            if plugin is None:
                raise IOError(
                    "erasure-code plugin %s: module %s loaded but did not "
                    "register" % (name, path))
        if plugin.version != PLUGIN_API_VERSION:
            raise IOError("erasure-code plugin %s: API version %d != %d"
                          % (name, plugin.version, PLUGIN_API_VERSION))
        return plugin

    def factory(self, name: str,
                profile: ErasureCodeProfile) -> ErasureCodeInterface:
        """Instantiate a codec: load plugin, build, init with profile."""
        plugin = self.load(name)
        codec = plugin.factory(dict(profile))
        return codec

    def preload(self, names: list[str]) -> None:
        for name in names:
            self.load(name)


def register_plugin(name: str,
                    factory: Callable[[ErasureCodeProfile], ErasureCodeInterface],
                    version: int = PLUGIN_API_VERSION) -> None:
    ErasureCodePluginRegistry.instance().add(
        name, ErasureCodePlugin(name, factory, version))
