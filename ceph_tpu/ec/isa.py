"""ISA-L-style GF(2^8) Reed-Solomon codec with decode-table cache.

Behavioral re-derivation of src/erasure-code/isa/ErasureCodeIsa.cc:
chunk size = ceil(object/k) aligned to 32 bytes (:66-78), m==1 single
parity served by plain region XOR (:119-126), Vandermonde profile
limits k<=32, m<=4, (m==4 -> k<=21) (:322-360), decode via inversion
of the surviving-rows matrix with erased-parity rows composed from the
inverse and the encode coefficients (:253-307), and an LRU cache of
decode tables keyed by the erasure signature
(ErasureCodeIsaTableCache.cc).  Encode math runs as a vectorized
GF(2^8) matmul (numpy host path / TPU kernels) rather than ec_encode_data.
"""

from __future__ import annotations

from collections import OrderedDict

import numpy as np

from . import gf, matrices
from .base import ErasureCode

EC_ISA_ADDRESS_ALIGNMENT = 32
DECODE_TABLE_LRU_LENGTH = 2516


class IsaTableCache:
    """LRU of inverted decode matrices keyed by erasure signature, per
    (matrixtype, k, m) — the analog of ErasureCodeIsaTableCache."""

    def __init__(self, capacity: int = DECODE_TABLE_LRU_LENGTH):
        self.capacity = capacity
        self._lru: OrderedDict[tuple, np.ndarray] = OrderedDict()

    def get(self, key: tuple) -> np.ndarray | None:
        tbl = self._lru.get(key)
        if tbl is not None:
            self._lru.move_to_end(key)
        return tbl

    def put(self, key: tuple, tbl: np.ndarray) -> None:
        self._lru[key] = tbl
        self._lru.move_to_end(key)
        while len(self._lru) > self.capacity:
            self._lru.popitem(last=False)


_shared_cache = IsaTableCache()


class ErasureCodeIsa(ErasureCode):
    VANDERMONDE = "reed_sol_van"
    CAUCHY = "cauchy"
    DEFAULT_K = 7
    DEFAULT_M = 3

    def __init__(self, technique: str = VANDERMONDE,
                 cache: IsaTableCache | None = None):
        super().__init__()
        self.technique = technique
        self.tcache = cache or _shared_cache
        self.matrix: list[list[int]] = []

    def init(self, profile: dict) -> None:
        profile.setdefault("plugin", "isa")
        profile.setdefault("technique", self.technique)
        self.technique = profile["technique"]
        if self.technique not in (self.VANDERMONDE, self.CAUCHY):
            raise ValueError("isa: technique %r is not a valid coding technique"
                             % self.technique)
        self.parse(profile)
        self.prepare()
        self._profile = profile

    def parse(self, profile: dict) -> None:
        self.k = self._to_int(profile, "k", self.DEFAULT_K)
        self.m = self._to_int(profile, "m", self.DEFAULT_M)
        self._parse_mapping(profile)
        self.sanity_check_k_m()
        if self.technique == self.VANDERMONDE:
            # verified-safe envelope for the non-MDS-in-general
            # Vandermonde construction
            if self.k > 32:
                raise ValueError("isa Vandermonde: k=%d must be <= 32" % self.k)
            if self.m > 4:
                raise ValueError("isa Vandermonde: m=%d must be <= 4" % self.m)
            if self.m == 4 and self.k > 21:
                raise ValueError("isa Vandermonde: k=%d must be <= 21 for m=4"
                                 % self.k)

    def prepare(self) -> None:
        if self.technique == self.VANDERMONDE:
            self.matrix = matrices.isa_rs_vandermonde_matrix(self.k, self.m)
        else:
            self.matrix = matrices.isa_cauchy_matrix(self.k, self.m)

    def get_alignment(self) -> int:
        return EC_ISA_ADDRESS_ALIGNMENT

    def _device_matrix(self):
        return self.matrix, 8

    def get_chunk_size(self, object_size: int) -> int:
        chunk = -(-object_size // self.k)
        mod = chunk % self.get_alignment()
        if mod:
            chunk += self.get_alignment() - mod
        return chunk

    # -- chunk-level -------------------------------------------------------

    def encode_chunks(self, chunks: dict[int, bytes]) -> dict[int, bytes]:
        k, m = self.k, self.m
        data = np.stack([np.frombuffer(chunks[self.chunk_index(i)],
                                       dtype=np.uint8) for i in range(k)])
        out = dict(chunks)
        if m == 1:
            # single-parity fast path: pure region XOR (xor_op.cc analog)
            out[self.chunk_index(k)] = np.bitwise_xor.reduce(
                data, axis=0).tobytes()
            return out
        parity = gf.matmul_u8(np.array(self.matrix, dtype=np.uint8), data)
        for i in range(m):
            out[self.chunk_index(k + i)] = parity[i].tobytes()
        return out

    def decode_chunks(self, want_to_read, chunks) -> dict[int, bytes]:
        k, m = self.k, self.m
        chunks = self._to_logical(chunks)
        erased = [i for i in range(k + m) if i not in chunks]
        decode_index = sorted(chunks)[:k]
        if len(erased) > m:
            raise IOError("isa: %d erasures exceed m=%d" % (len(erased), m))
        # XOR fast paths (ErasureCodeIsa.cc:195-216): m==1 always, and a
        # single missing data chunk / first parity under Vandermonde whose
        # first coding row is all ones
        if m == 1 or (self.technique == self.VANDERMONDE
                      and len(erased) == 1 and erased[0] < k + 1):
            src = np.stack([np.frombuffer(chunks[c], dtype=np.uint8)
                            for c in decode_index])
            return self._from_logical(
                {erased[0]: np.bitwise_xor.reduce(src, axis=0).tobytes()})
        signature = (self.technique, k, m,
                     tuple(decode_index), tuple(erased))
        ctbl = self.tcache.get(signature)
        if ctbl is None:
            inv, _ = matrices.decoding_matrix(
                k, 8, self.matrix, erased, decode_index)
            # rows of the "c" matrix: for erased data chunk e, the inverse
            # row; for erased parity, coefficients composed through the
            # inverse so parity rebuilds straight from survivors
            rows = []
            for e in erased:
                if e < k:
                    rows.append(inv[e])
                else:
                    coeff = self.matrix[e - k]
                    rows.append([
                        _dot_gf(coeff, [inv[j][i] for j in range(k)])
                        for i in range(k)])
            ctbl = np.array(rows, dtype=np.uint8)
            self.tcache.put(signature, ctbl)
        src = np.stack([np.frombuffer(chunks[c], dtype=np.uint8)
                        for c in decode_index])
        rec = gf.matmul_u8(ctbl, src)
        return self._from_logical(
            {e: rec[i].tobytes() for i, e in enumerate(erased)})


def _dot_gf(a: list[int], b: list[int]) -> int:
    acc = 0
    for x, y in zip(a, b):
        acc ^= gf.gf_mul(x, y, 8)
    return acc


def make_codec(profile: dict) -> ErasureCodeIsa:
    codec = ErasureCodeIsa()
    codec.init(profile)
    return codec
