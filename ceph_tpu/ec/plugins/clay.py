"""clay plugin entry (ErasureCodePluginClay.cc analog)."""

from ..clay import ErasureCodeClay
from ..plugin import register_plugin


def make_codec(profile: dict):
    codec = ErasureCodeClay()
    codec.init(profile)
    return codec


register_plugin("clay", make_codec)
