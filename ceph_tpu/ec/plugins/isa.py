"""isa plugin entry (ErasureCodePluginIsa.cc analog)."""

from ..isa import make_codec
from ..plugin import register_plugin

register_plugin("isa", make_codec)
