"""lrc plugin registration (the dlopen entry point analog)."""

from ..lrc import ErasureCodeLrc
from ..plugin import register_plugin


def _factory(profile):
    codec = ErasureCodeLrc()
    codec.init(profile)
    return codec


register_plugin("lrc", _factory)
