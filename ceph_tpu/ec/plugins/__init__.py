"""Erasure-code plugin modules. Importing a module registers its codec
factory with the ErasureCodePluginRegistry (the dlopen-directory analog,
src/erasure-code/ErasureCodePlugin.cc:120-178)."""
