"""jerasure plugin entry (ErasureCodePluginJerasure.cc analog)."""

from ..jerasure import make_codec
from ..plugin import register_plugin

register_plugin("jerasure", make_codec)
