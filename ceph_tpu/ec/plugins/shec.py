"""shec plugin registration (ErasureCodePluginShec.cc analog)."""

from ..plugin import register_plugin
from ..shec import ErasureCodeShec, ErasureCodeShecSingle


def _factory(profile):
    technique = profile.get("technique", "multiple")
    cls = (ErasureCodeShecSingle if technique == "single"
           else ErasureCodeShec)
    codec = cls()
    codec.init(profile)
    return codec


register_plugin("shec", _factory)
