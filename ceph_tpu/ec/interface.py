"""Erasure-code plugin contract.

Mirrors the reference's abstract API (src/erasure-code/
ErasureCodeInterface.h:170-462): chunk counts, sub-chunks for array
codes, chunk-size math, encode/decode at both the object level (with
padding) and the chunk level, minimum_to_decode with per-chunk
sub-chunk ranges, cost-aware selection, and chunk remapping.

Chunks are `bytes`; chunk maps are plain dicts {chunk_id: bytes}.
Errors are raised as exceptions rather than -errno returns.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Mapping, Sequence

ErasureCodeProfile = dict


class ErasureCodeInterface(ABC):
    """Abstract erasure codec. One instance per (plugin, profile)."""

    @abstractmethod
    def init(self, profile: ErasureCodeProfile) -> None:
        """Parse the profile and precompute coding state. Raises
        ValueError on malformed profiles."""

    @abstractmethod
    def get_profile(self) -> ErasureCodeProfile:
        """The profile as completed by init (defaults filled in)."""

    @abstractmethod
    def get_chunk_count(self) -> int:
        """k + m: total chunks an object is encoded into."""

    @abstractmethod
    def get_data_chunk_count(self) -> int:
        """k: chunks that concatenate back into the object."""

    def get_coding_chunk_count(self) -> int:
        return self.get_chunk_count() - self.get_data_chunk_count()

    def get_sub_chunk_count(self) -> int:
        """Array codes (CLAY) address sub-chunks for repair-bandwidth
        savings; scalar codes have exactly one."""
        return 1

    @abstractmethod
    def get_chunk_size(self, object_size: int) -> int:
        """Chunk size (with alignment padding) for an object_size-byte
        object; object_size <= k * chunk_size."""

    @abstractmethod
    def get_chunk_mapping(self) -> Sequence[int]:
        """Optional remapping of logical chunk i to physical position."""

    # -- object-level (pads, splits, encodes) -----------------------------

    @abstractmethod
    def encode(self, want_to_encode: set[int], data: bytes) -> dict[int, bytes]:
        """Split + pad `data` into k chunks, compute m parity chunks, and
        return those requested in want_to_encode."""

    @abstractmethod
    def decode(
        self, want_to_read: set[int], chunks: Mapping[int, bytes],
        chunk_size: int = 0,
    ) -> dict[int, bytes]:
        """Reconstruct the requested chunks from any sufficient subset."""

    # -- chunk-level (backend hot path, already-padded buffers) ------------

    @abstractmethod
    def encode_chunks(self, chunks: dict[int, bytes]) -> dict[int, bytes]:
        """Compute parity for k equal-length data chunks; returns the full
        k+m chunk map."""

    @abstractmethod
    def decode_chunks(
        self, want_to_read: set[int], chunks: Mapping[int, bytes],
    ) -> dict[int, bytes]:
        """Reconstruct missing chunks from surviving equal-length ones."""

    # -- read planning -----------------------------------------------------

    @abstractmethod
    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int],
    ) -> dict[int, list[tuple[int, int]]]:
        """Smallest chunk set (with (offset, count) sub-chunk ranges) that
        can serve want_to_read. Raises IOError when undecodable."""

    @abstractmethod
    def minimum_to_decode_with_cost(
        self, want_to_read: set[int], available: Mapping[int, int],
    ) -> set[int]:
        """Like minimum_to_decode but choosing by retrieval cost."""

    @abstractmethod
    def decode_concat(self, chunks: Mapping[int, bytes]) -> bytes:
        """Reconstruct and concatenate the data chunks (reads the whole
        object)."""
