"""CLAY (Coupled LAYer) MSR regenerating codes.

Behavioral re-derivation of src/erasure-code/clay/ErasureCodeClay.{h,cc}
(the Clay-codes FAST'18 construction): an (k+m, k) scalar MDS code is
lifted onto a q x t node grid (q = d-k+1, t = (k+m+nu)/q) whose chunks
split into q^t sub-chunks ("planes"); pairwise coupling between a node
(x, y) in plane z and its partner (z_y, y) in the plane with digit y
swapped to x makes single-node repair read only q^(t-1) sub-chunks from
each of d helpers — repair bandwidth d/(d-k+1) sub-chunks per chunk
instead of k whole chunks.

Structure mirrored from the reference (cited by line where the
semantics are pinned):

* parameters/layout: q, t, nu padding, sub_chunk_no = q^t
  (ErasureCodeClay.cc:271-296); chunk alignment sub_chunk_no*k*pft
  (:93);
* encode = decode_layered with the parity nodes erased (:140-152);
* decode_layered: planes ordered by intersection score (erased
  hole-dot count, :762-773), per plane the surviving nodes' uncoupled
  symbols come from pairwise transforms of the coupled pairs
  (decode_erasures, :712-739), the erased nodes' uncoupled symbols from
  the scalar MDS decode (decode_uncoupled, :741-760), and the coupled
  symbols back out of the pair relations (recover_type1_erasure /
  get_coupled_from_uncoupled, :775-838);
* the pairwise transform IS a (4, 2) instance of the same scalar MDS
  code over [C_xy, C_sw, U_xy, U_sw] with the lower-x symbol first
  (the i0..i3 swap, :848-855) — byte-compat therefore follows from the
  k=2,m=2 coding matrix of the chosen scalar_mds plugin;
* single-node repair reads only the repair planes {z : z_{y_lost} =
  x_lost} from every helper (minimum_to_decode sub-chunk ranges,
  :310-392); implemented here for the no-aloof case (d = #survivors,
  e.g. the default d = k+m-1 with one failure) — other layouts fall
  back to the full-chunk layered decode.
"""

from __future__ import annotations

import numpy as np

from . import gf
from .base import ErasureCode
from .plugin import ErasureCodePluginRegistry


class ErasureCodeClay(ErasureCode):
    DEFAULT_K = 4
    DEFAULT_M = 2

    def __init__(self):
        super().__init__()
        self.d = 0
        self.q = 0
        self.t = 0
        self.nu = 0
        self.sub_chunk_no = 1
        self.scalar_mds = "jerasure"
        self.technique = "reed_sol_van"

    # -- profile -----------------------------------------------------------

    def init(self, profile: dict) -> None:
        profile.setdefault("plugin", "clay")
        self.parse(profile)
        self.prepare()
        self._profile = profile

    def parse(self, profile: dict) -> None:
        self.k = self._to_int(profile, "k", self.DEFAULT_K)
        self.m = self._to_int(profile, "m", self.DEFAULT_M)
        self.d = self._to_int(profile, "d", self.k + self.m - 1)
        if not (self.k + 1 <= self.d <= self.k + self.m - 1):
            raise ValueError(
                "clay: d=%d must satisfy k+1 <= d <= k+m-1" % self.d)
        self.scalar_mds = profile.get("scalar_mds", "jerasure")
        if self.scalar_mds not in ("jerasure", "isa"):
            raise ValueError("clay: scalar_mds %r not supported"
                             % self.scalar_mds)
        self.technique = profile.get("technique", "reed_sol_van")
        if self.technique not in ("reed_sol_van", "cauchy"):
            raise ValueError("clay: technique %r not supported"
                             % self.technique)
        self._parse_mapping(profile)
        self.sanity_check_k_m()

    def prepare(self) -> None:
        self.q = self.d - self.k + 1
        self.nu = (self.q - (self.k + self.m) % self.q) % self.q
        self.t = (self.k + self.m + self.nu) // self.q
        self.sub_chunk_no = self.q ** self.t
        if self.k + self.m + self.nu > 254:
            raise ValueError("clay: k+m+nu too large for GF(256)")
        reg = ErasureCodePluginRegistry.instance()

        def mk(k, m):
            prof = {"plugin": self.scalar_mds, "k": str(k),
                    "m": str(m), "w": "8",
                    "technique": self.technique}
            return reg.factory(self.scalar_mds, prof)

        self.mds = mk(self.k + self.nu, self.m)
        self.pft = mk(2, 2)
        # (4,2) pairwise-transform generator: rows 0,1 = identity
        # (the coupled pair), rows 2,3 = the k=2,m=2 coding matrix
        # (the uncoupled pair).  Symbol order is the reference's
        # i0..i3 canonicalisation (ErasureCodeClay.cc:848-855):
        # sym0/sym2 = C/U of the LARGER-x pair member, sym1/sym3 of
        # the smaller.  Solves for any 2-of-4 are 2x2 GF inverts.
        P = [list(r) for r in self.pft.matrix]
        self._pft_gen = [[1, 0], [0, 1], list(P[0]), list(P[1])]
        self._pft_solves: dict[tuple, tuple] = {}

    # -- geometry ----------------------------------------------------------

    def get_sub_chunk_count(self) -> int:
        return self.sub_chunk_no

    def get_alignment(self) -> int:
        return self.sub_chunk_no * self.k * self.pft.get_chunk_size(1)

    def get_chunk_size(self, object_size: int) -> int:
        a = self.get_alignment()
        padded = object_size + (a - object_size % a) % a
        return padded // self.k

    def _zvec(self, z: int) -> list[int]:
        v = [0] * self.t
        for i in range(self.t):
            v[self.t - 1 - i] = z % self.q
            z //= self.q
        return v

    def _zsw(self, z: int, zv: list[int], x: int, y: int) -> int:
        return z + (x - zv[y]) * (self.q ** (self.t - 1 - y))

    # -- pairwise transform -------------------------------------------------

    def _pft_solve(self, known: tuple[int, int]):
        """(A, B): unknowns = A @ [known0, known1] where unknowns are
        the complementary pair in index order."""
        key = known
        cached = self._pft_solves.get(key)
        if cached is not None:
            return cached
        G = self._pft_gen
        i, j = known
        unk = tuple(r for r in range(4) if r not in known)
        # [g_i; g_j] @ [d0,d1]^T = [known0, known1]^T
        inv = gf.matrix_invert([list(G[i]), list(G[j])], 8)
        rows = gf.matrix_mul([list(G[u]) for u in unk], inv, 8)
        self._pft_solves[key] = (unk, np.array(rows, dtype=np.uint8))
        return self._pft_solves[key]

    def _pair(self, a: np.ndarray, b: np.ndarray, known: tuple):
        """Apply the 2-of-4 solve: returns the two unknown symbols (in
        index order) from known symbols a, b (arrays)."""
        _unk, rows = self._pft_solve(known)
        data = np.stack([a, b])
        out = gf.matmul_u8(rows, data)
        return out[0], out[1]

    # -- layered decode (the engine behind encode AND decode) --------------

    def _decode_layered(self, erasures: set[int], C: list, sc: int):
        """C: list of q*t numpy [sub_chunk_no, sc] uint8 arrays
        (erased entries are written in place)."""
        q, t = self.q, self.t
        er = set(erasures)
        for i in range(self.k + self.nu, q * t):
            if len(er) >= self.m:
                break
            er.add(i)
        assert len(er) == self.m
        U = [np.zeros((self.sub_chunk_no, sc), np.uint8)
             for _ in range(q * t)]
        order = []
        for z in range(self.sub_chunk_no):
            zv = self._zvec(z)
            order.append(sum(1 for i in er if i % q == zv[i // q]))
        max_score = max(order) if order else 0
        dec_rows = self._mds_decode_rows(er)
        for score in range(max_score + 1):
            planes = [z for z in range(self.sub_chunk_no)
                      if order[z] == score]
            for z in planes:
                self._fill_uncoupled(er, z, C, U)
                self._decode_uncoupled(er, z, U, dec_rows)
            for z in planes:
                zv = self._zvec(z)
                for node in sorted(er):
                    x, y = node % q, node // q
                    sw = y * q + zv[y]
                    if zv[y] == x:       # hole-dot: C = U
                        C[node][z] = U[node][z]
                    elif sw not in er:
                        # type-1 (recover_type1_erasure): solve the
                        # erased node's C from (partner C, own U)
                        z_sw = self._zsw(z, zv, x, y)
                        if x < zv[y]:
                            # node is the smaller member (sym1):
                            # knowns sym0=C_sw, sym3=U_node
                            out = self._pair(C[sw][z_sw], U[node][z],
                                             (0, 3))
                            C[node][z] = out[0]     # sym1
                        else:
                            # node is the larger member (sym0):
                            # knowns sym1=C_sw, sym2=U_node
                            out = self._pair(C[sw][z_sw], U[node][z],
                                             (1, 2))
                            C[node][z] = out[0]     # sym0
                    elif zv[y] < x:
                        # both erased (get_coupled_from_uncoupled,
                        # larger side drives): C pair from U pair
                        z_sw = self._zsw(z, zv, x, y)
                        c_hi, c_lo = self._pair(U[node][z],
                                                U[sw][z_sw], (2, 3))
                        C[node][z] = c_hi           # sym0 (larger)
                        C[sw][z_sw] = c_lo          # sym1
        return C

    def _fill_uncoupled(self, er: set[int], z: int, C, U) -> None:
        """decode_erasures' first pass: U for every surviving node of
        plane z from the coupled pairs."""
        q, t = self.q, self.t
        zv = self._zvec(z)
        for y in range(t):
            for x in range(q):
                node = q * y + x
                if node in er:
                    continue
                sw = q * y + zv[y]
                if zv[y] == x:
                    U[node][z] = C[node][z]
                elif zv[y] < x:
                    # node is the larger member: compute both U from
                    # the C pair (this also pre-fills the partner's U
                    # at the later plane z_sw — planes iterate
                    # ascending, and z_sw > z here; for an erased sw
                    # its C at z_sw was recovered in an earlier
                    # iscore round)
                    z_sw = self._zsw(z, zv, x, y)
                    u_hi, u_lo = self._pair(C[node][z], C[sw][z_sw],
                                            (0, 1))
                    U[node][z] = u_hi
                    U[sw][z_sw] = u_lo
                elif sw in er:
                    # node smaller, partner erased: partner's C at
                    # z_sw (< z, one fewer erased dot) is recovered
                    z_sw = self._zsw(z, zv, x, y)
                    u_hi, u_lo = self._pair(C[sw][z_sw], C[node][z],
                                            (0, 1))
                    U[sw][z_sw] = u_hi
                    U[node][z] = u_lo

    def _mds_decode_rows(self, er: set[int]):
        """Decoding rows for the scalar MDS over the q*t grid: rows
        that rebuild the erased nodes' uncoupled symbols from the
        surviving ones (cached per erasure signature upstream)."""
        from .batcher import reconstruct_matrix
        n = self.q * self.t
        have = tuple(i for i in range(n) if i not in er)
        erased = tuple(sorted(er))
        rows, chosen = reconstruct_matrix(
            self.k + self.nu, 8, [list(r) for r in self.mds.matrix],
            erased, have)
        return erased, chosen, np.array(rows, dtype=np.uint8)

    def _decode_uncoupled(self, er, z, U, dec_rows) -> None:
        erased, chosen, rows = dec_rows
        data = np.stack([U[c][z] for c in chosen])
        out = gf.matmul_u8(rows, data)
        for idx, node in enumerate(erased):
            U[node][z] = out[idx]

    # -- chunk API ----------------------------------------------------------

    def _grid(self, chunks: dict[int, bytes], sc: int):
        """chunks (logical external ids) -> grid arrays with the nu
        zero nodes spliced in at k..k+nu-1."""
        n = self.q * self.t
        C = [np.zeros((self.sub_chunk_no, sc), np.uint8)
             for _ in range(n)]
        for i, buf in chunks.items():
            node = i if i < self.k else i + self.nu
            C[node] = np.frombuffer(buf, np.uint8).reshape(
                self.sub_chunk_no, sc).copy()
        return C

    def encode_chunks(self, chunks: dict[int, bytes]) -> dict[int, bytes]:
        chunk_size = len(chunks[self.chunk_index(0)])
        assert chunk_size % self.sub_chunk_no == 0
        sc = chunk_size // self.sub_chunk_no
        logical = {i: chunks[self.chunk_index(i)]
                   for i in range(self.k)}
        C = self._grid(logical, sc)
        parities = set(range(self.k + self.nu, self.q * self.t))
        self._decode_layered(parities, C, sc)
        out = dict(chunks)
        for i in range(self.m):
            out[self.chunk_index(self.k + i)] = \
                C[self.k + self.nu + i].tobytes()
        return out

    def decode_chunks(self, want_to_read, chunks) -> dict[int, bytes]:
        chunks = self._to_logical(chunks)
        chunk_size = len(next(iter(chunks.values())))
        assert chunk_size % self.sub_chunk_no == 0
        sc = chunk_size // self.sub_chunk_no
        n_ext = self.k + self.m
        erased_ext = [i for i in range(n_ext) if i not in chunks]
        C = self._grid(chunks, sc)
        er = {i if i < self.k else i + self.nu for i in erased_ext}
        self._decode_layered(er, C, sc)
        out = {}
        for i in erased_ext:
            node = i if i < self.k else i + self.nu
            out[i] = C[node].tobytes()
        return self._from_logical(out)

    # -- repair-bandwidth API ----------------------------------------------

    def _repair_planes(self, lost: int) -> list[int]:
        """Plane indices every helper must send to repair `lost`
        (z with z_{y_lost} == x_lost), ascending."""
        q, t = self.q, self.t
        x, y = lost % q, lost // q
        step = q ** (t - 1 - y)
        planes = []
        for z in range(self.sub_chunk_no):
            if (z // step) % q == x:
                planes.append(z)
        return planes

    def minimum_to_decode(self, want_to_read, available):
        want = set(want_to_read)
        avail = set(available)
        # the sub-chunk repair plan applies only to the no-aloof
        # layout repair() supports: a single loss with d = k+m-1, so
        # the d helpers ARE every surviving node
        if (len(want) == 1 and not (want & avail)
                and not self.chunk_mapping
                and self.d == self.k + self.m - 1):
            lost_ext = next(iter(want))
            helpers = avail - want
            if helpers == set(range(self.k + self.m)) - want:
                lost = (lost_ext if lost_ext < self.k
                        else lost_ext + self.nu)
                planes = self._repair_planes(lost)
                # contiguous (offset, count) runs in sub-chunk units
                runs = []
                for z in planes:
                    if runs and runs[-1][0] + runs[-1][1] == z:
                        runs[-1] = (runs[-1][0], runs[-1][1] + 1)
                    else:
                        runs.append((z, 1))
                chosen = sorted(helpers)[:self.d]
                return {c: list(runs) for c in chosen}
        return super().minimum_to_decode(want_to_read, available)

    def repair(self, lost_ext: int,
               helper_subchunks: dict[int, bytes]) -> bytes:
        """Rebuild chunk `lost_ext` from d helpers' repair sub-chunks
        (each helper contributes only the q^(t-1) repair planes —
        bandwidth d/(d-k+1) sub-chunks vs k*sub_chunk_no for a full
        decode).  Helpers must be every other node (no aloof nodes);
        otherwise use decode()."""
        q, t = self.q, self.t
        lost = lost_ext if lost_ext < self.k else lost_ext + self.nu
        x_l, y_l = lost % q, lost // q
        planes = self._repair_planes(lost)
        plane_ind = {z: i for i, z in enumerate(planes)}
        sc = len(next(iter(helper_subchunks.values()))) // len(planes)
        n = q * t
        H: dict[int, np.ndarray] = {}
        for ext, buf in helper_subchunks.items():
            node = ext if ext < self.k else ext + self.nu
            H[node] = np.frombuffer(buf, np.uint8).reshape(
                len(planes), sc)
        for i in range(self.k, self.k + self.nu):   # zero nodes help
            H[i] = np.zeros((len(planes), sc), np.uint8)
        missing_helpers = set(range(n)) - set(H) - {lost}
        if missing_helpers:
            raise IOError("clay repair needs every surviving node "
                          "(aloof nodes unsupported; use decode)")
        U = {node: np.zeros((len(planes), sc), np.uint8)
             for node in range(n)}
        # the erased row for the uncoupled decode: lost's whole y-row
        er = {y_l * q + xx for xx in range(q)}
        dec = self._mds_decode_rows(er)
        out = np.zeros((self.sub_chunk_no, sc), np.uint8)
        for z in planes:
            zi = plane_ind[z]
            zv = self._zvec(z)
            for y in range(t):
                for x in range(q):
                    node = y * q + x
                    if node in er:
                        continue
                    sw = y * q + zv[y]
                    if zv[y] == x:
                        U[node][zi] = H[node][zi]
                    elif zv[y] < x:
                        z_sw = self._zsw(z, zv, x, y)
                        u_hi, u_lo = self._pair(
                            H[node][zi], H[sw][plane_ind[z_sw]],
                            (0, 1))
                        U[node][zi] = u_hi
                        U[sw][plane_ind[z_sw]] = u_lo
            # MDS-decode the lost row's uncoupled symbols
            erased, chosen, rows = dec
            data = np.stack([U[c][zi] for c in chosen])
            dec_out = gf.matmul_u8(rows, data)
            for idx, node in enumerate(erased):
                U[node][zi] = dec_out[idx]
            # back to coupled: the dot gives lost's own plane, the
            # other row members give lost's swapped planes
            out[z] = U[lost][zi]
            for xx in range(q):
                if xx == x_l:
                    continue
                node = y_l * q + xx
                z_sw = self._zsw(z, zv, xx, y_l)
                if xx < x_l:
                    # helper is the smaller member: knowns sym1=C,
                    # sym3=U; lost (larger) C is sym0
                    o = self._pair(H[node][zi], U[node][zi], (1, 3))
                    out[z_sw] = o[0]       # sym0
                else:
                    # helper larger: knowns sym0=C, sym2=U; lost
                    # (smaller) C is sym1
                    o = self._pair(H[node][zi], U[node][zi], (0, 2))
                    out[z_sw] = o[0]       # sym1
        return out.tobytes()
