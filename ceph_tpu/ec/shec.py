"""SHEC: shingled erasure code (k, m, c).

Re-derivation of src/erasure-code/shec/ErasureCodeShec.{h,cc}: a
Reed-Solomon Vandermonde coding matrix whose parity rows are "shingled"
— each parity covers only a sliding window of the data chunks (the
rest of the row is zeroed, shec_reedsolomon_coding_matrix,
ErasureCodeShec.cc:465-532) — trading storage efficiency for recovery
bandwidth: a lost chunk is rebuilt from the small window of chunks its
parities cover.  c is the target durability (erasures any layout must
survive); the MULTIPLE technique splits the m parities into two
shingle trains (m1/c1, m2/c2) chosen by the recovery-efficiency search
(shec_calc_recovery_efficiency1, :424-463).

Decoding searches the 2^m parity subsets for the smallest invertible
recovery system (shec_make_decoding_matrix, :535-697) — that search
also powers minimum_to_decode, which is SHEC's selling point.
"""

from __future__ import annotations

from itertools import combinations
from typing import Mapping

import numpy as np

from . import gf, matrices
from .base import ErasureCode
from .interface import ErasureCodeProfile

DEFAULT_K, DEFAULT_M, DEFAULT_C, DEFAULT_W = 4, 3, 2, 8


def calc_recovery_efficiency1(k: int, m1: int, m2: int, c1: int,
                              c2: int) -> float:
    """Port of shec_calc_recovery_efficiency1 (ErasureCodeShec.cc:424)."""
    if m1 < c1 or m2 < c2:
        return -1.0
    if (m1 == 0 and c1 != 0) or (m2 == 0 and c2 != 0):
        return -1.0
    r_eff_k = [100000000] * k
    r_e1 = 0.0
    for rr in range(m1):
        start = ((rr * k) // m1) % k
        end = (((rr + c1) * k) // m1) % k
        cc = start
        first = True
        while first or cc != end:
            first = False
            r_eff_k[cc] = min(r_eff_k[cc],
                              ((rr + c1) * k) // m1 - (rr * k) // m1)
            cc = (cc + 1) % k
        r_e1 += ((rr + c1) * k) // m1 - (rr * k) // m1
    for rr in range(m2):
        start = ((rr * k) // m2) % k
        end = (((rr + c2) * k) // m2) % k
        cc = start
        first = True
        while first or cc != end:
            first = False
            r_eff_k[cc] = min(r_eff_k[cc],
                              ((rr + c2) * k) // m2 - (rr * k) // m2)
            cc = (cc + 1) % k
        r_e1 += ((rr + c2) * k) // m2 - (rr * k) // m2
    r_e1 += sum(r_eff_k)
    return r_e1 / (k + m1 + m2)


def shec_coding_matrix(k: int, m: int, c: int, w: int,
                       single: bool) -> list[list[int]]:
    """shec_reedsolomon_coding_matrix (ErasureCodeShec.cc:465): RS
    Vandermonde rows with circular shingle windows zeroed."""
    if not single:
        c1_best, m1_best = -1, -1
        min_r_e1 = 100.0
        for c1 in range(c // 2 + 1):
            for m1 in range(m + 1):
                c2, m2 = c - c1, m - m1
                if m1 < c1 or m2 < c2:
                    continue
                if (m1 == 0 and c1 != 0) or (m2 == 0 and c2 != 0):
                    continue
                if (m1 != 0 and c1 == 0) or (m2 != 0 and c2 == 0):
                    continue
                r_e1 = calc_recovery_efficiency1(k, m1, m2, c1, c2)
                if min_r_e1 - r_e1 > 1e-12 and r_e1 < min_r_e1:
                    min_r_e1 = r_e1
                    c1_best, m1_best = c1, m1
        m1, c1 = m1_best, c1_best
        m2, c2 = m - m1, c - c1
    else:
        m1, c1 = 0, 0
        m2, c2 = m, c

    matrix = [row[:] for row in
              matrices.reed_sol_vandermonde_coding_matrix(k, m, w)]
    for rr in range(m1):
        end = ((rr * k) // m1) % k
        cc = (((rr + c1) * k) // m1) % k
        while cc != end:
            matrix[rr][cc] = 0
            cc = (cc + 1) % k
    for rr in range(m2):
        end = ((rr * k) // m2) % k
        cc = (((rr + c2) * k) // m2) % k
        while cc != end:
            matrix[rr + m1][cc] = 0
            cc = (cc + 1) % k
    return matrix


class ErasureCodeShec(ErasureCode):
    """Multiple-shingle SHEC (the reference's default technique)."""

    TECHNIQUE_SINGLE = False

    def __init__(self):
        super().__init__()
        self.c = 0
        self.w = DEFAULT_W
        self.matrix: list[list[int]] = []
        # (want, avail) -> decoding plan: the 2^m subset search with a
        # GF inversion per candidate is hot on degraded pools; the
        # reference caches it too (ErasureCodeShecTableCache)
        self._decoding_cache: dict[tuple, tuple] = {}

    def init(self, profile: ErasureCodeProfile) -> None:
        k = self._to_int(profile, "k", DEFAULT_K)
        m = self._to_int(profile, "m", DEFAULT_M)
        c = self._to_int(profile, "c", DEFAULT_C)
        w = self._to_int(profile, "w", DEFAULT_W)
        if w not in (8, 16, 32):
            raise ValueError("w=%d must be 8, 16 or 32" % w)
        if k <= 0 or m <= 0 or c <= 0:
            raise ValueError("k, m, c must be positive")
        if m < c:
            raise ValueError("m=%d must be >= c=%d" % (m, c))
        self.k, self.m, self.c, self.w = k, m, c, w
        self.matrix = shec_coding_matrix(k, m, c, w,
                                         self.TECHNIQUE_SINGLE)
        self._profile = dict(profile)

    # -- geometry ------------------------------------------------------------

    def get_chunk_size(self, object_size: int) -> int:
        alignment = self.k * self.w * 4
        padded = object_size + (-object_size) % alignment
        return padded // self.k

    # -- device offload ----------------------------------------------------

    def _device_matrix(self):
        """SHEC's encode IS a plain GF(2^w) matmul — the shingled
        matrix just carries zero coefficients outside each parity's
        window — so encode/delta ride the base class's device path
        unchanged (zero coefficients contribute nothing under GF
        linearity, exactly like `delta_async`'s zero rows)."""
        return self.matrix, self.w

    def device_families(self) -> list[tuple]:
        """Encode family + the most common repair shape (first data
        chunk lost, everything else surviving): the decoding-matrix
        rows the first post-boot repair will dispatch."""
        fams = [(self.matrix, self.w)]
        try:
            avail = set(range(1, self.k + self.m))
            rows, _cols, inv, _min = self._make_decoding({0}, avail)
            if rows:
                fams.append((inv, self.w))
        except Exception:
            pass            # unrecoverable layouts just skip warmup
        return fams

    # -- encode ----------------------------------------------------------

    def _word_view(self, buf: bytes) -> np.ndarray:
        # explicit little-endian so chunk bytes are identical across
        # host endianness (matches jerasure._MatrixTechnique._word_view)
        dt = {8: np.uint8, 16: np.dtype("<u2"),
              32: np.dtype("<u4")}[self.w]
        return np.frombuffer(buf, dtype=dt)

    def encode_chunks(self, chunks: dict[int, bytes]) -> dict[int, bytes]:
        k, m, w = self.k, self.m, self.w
        data = np.stack([self._word_view(chunks[i]) for i in range(k)])
        parity = gf.matmul_words(
            np.array(self.matrix, dtype=np.uint32), data, w)
        out = {i: bytes(chunks[i]) for i in range(k)}
        for i in range(m):
            out[k + i] = parity[i].tobytes()
        return out

    # -- recovery planning (shec_make_decoding_matrix) --------------------

    def _make_decoding(self, want: set[int], avail: set[int]):
        """Returns (dm_rows, dm_cols, inverse) for the smallest
        invertible recovery system, plus the minimum chunk set.
        Raises IOError when unrecoverable."""
        key = (frozenset(want), frozenset(avail))
        cached = self._decoding_cache.get(key)
        if cached is not None:
            return cached
        result = self._make_decoding_uncached(want, avail)
        if len(self._decoding_cache) > 256:
            self._decoding_cache.clear()
        self._decoding_cache[key] = result
        return result

    def _make_decoding_uncached(self, want: set[int], avail: set[int]):
        k, m = self.k, self.m
        want_vec = [1 if i in want else 0 for i in range(k + m)]
        # wanting an erased parity forces wanting its data window
        for i in range(m):
            if want_vec[k + i] and (k + i) not in avail:
                for j in range(k):
                    if self.matrix[i][j]:
                        want_vec[j] = 1
        mindup = k + 1
        minp = k + 1
        best = None
        for ek in range(m + 1):
            for p in combinations(range(m), ek):
                if ek > minp:
                    continue
                if any((k + pi) not in avail for pi in p):
                    continue
                tmprow = [0] * (k + m)
                tmpcol = [0] * k
                for i in range(k):
                    if want_vec[i] and i not in avail:
                        tmpcol[i] = 1
                for pi in p:
                    tmprow[k + pi] = 1
                    for j in range(k):
                        if self.matrix[pi][j]:
                            tmpcol[j] = 1
                            if j in avail:
                                tmprow[j] = 1
                dup_row = sum(tmprow)
                dup_col = sum(tmpcol)
                if dup_row != dup_col:
                    continue
                dup = dup_row
                if dup == 0:
                    return [], [], [], self._minimum_set(
                        [], want_vec, avail)
                if dup >= mindup:
                    continue
                rows = [i for i in range(k + m) if tmprow[i]]
                cols = [j for j in range(k) if tmpcol[j]]
                tmpmat = [[(1 if r == c else 0) if r < k
                           else self.matrix[r - k][c] for c in cols]
                          for r in rows]
                try:
                    inv = gf.matrix_invert(tmpmat, self.w)
                except (ValueError, ZeroDivisionError):
                    continue  # singular: try another parity subset
                mindup = dup
                minp = ek
                best = (rows, cols, inv)
        if best is None:
            raise IOError("shec: can't find recover matrix for want=%s "
                          "avail=%s" % (sorted(want), sorted(avail)))
        rows, cols, inv = best
        return rows, cols, inv, self._minimum_set(rows, want_vec, avail)

    def _minimum_set(self, rows, want_vec, avail) -> set[int]:
        k, m = self.k, self.m
        minimum = set(rows)
        for i in range(k):
            if want_vec[i] and i in avail:
                minimum.add(i)
        for i in range(m):
            if want_vec[k + i] and (k + i) in avail \
                    and (k + i) not in minimum:
                if any(self.matrix[i][j] and not want_vec[j]
                       for j in range(k)):
                    minimum.add(k + i)
        return minimum

    def _minimum_to_decode(self, want_to_read, available) -> set[int]:
        want = set(want_to_read)
        avail = set(available)
        if want <= avail:
            return want
        _, _, _, minimum = self._make_decoding(want, avail)
        return minimum

    # -- decode ------------------------------------------------------------

    def decode_chunks(self, want_to_read, chunks: Mapping[int, bytes]
                      ) -> dict[int, bytes]:
        k, m, w = self.k, self.m, self.w
        want = set(want_to_read)
        avail = set(chunks)
        rows, cols, inv, _ = self._make_decoding(want, avail)
        buffers = {i: self._word_view(chunks[i]) for i in chunks}
        out: dict[int, bytes] = {}
        recovered: dict[int, np.ndarray] = {}
        if rows:
            srcs = np.stack([buffers[r] for r in rows])
            rec = gf.matmul_words(
                np.array(inv, dtype=np.uint32), srcs, w)
            for i, c in enumerate(cols):
                if c not in avail:
                    recovered[c] = rec[i]
                    if c in want:
                        out[c] = rec[i].tobytes()
        # re-encode erased wanted parity from its shingle window only:
        # data chunks with a zero coefficient may themselves be erased
        # (and unneeded)
        for i in range(m):
            if (k + i) not in want or (k + i) in avail:
                continue
            cols = [j for j in range(k) if self.matrix[i][j]]
            data = np.stack([
                buffers[j] if j in buffers else recovered[j]
                for j in cols])
            mat = np.array([[self.matrix[i][j] for j in cols]],
                           dtype=np.uint32)
            out[k + i] = gf.matmul_words(mat, data, w)[0].tobytes()
        return out

    async def decode_async(self, want_to_read, chunks,
                           klass: str | None = None,
                           on_ticket=None,
                           chip: int | None = None) -> dict[int, bytes]:
        """`decode_chunks` with both matmuls batched onto the device
        (the recovery/degraded-read hot call): the smallest-invertible
        recovery system's inverse rides one dispatch, and erased
        wanted parities re-encode as selected rows of the full coding
        matrix — zero-padded outside their shingle windows, exactly
        like `delta_async`'s zero rows — in a second.  The base
        class's decode_async demands k survivors (the MDS floor);
        SHEC's selling point is repairing from a shingle window of
        fewer, so this override keeps the locality property on
        device."""
        from ..device.runtime import DeviceRuntime
        from .batcher import device_offload_enabled
        want = set(want_to_read)
        chunks = dict(chunks)
        if (want <= set(chunks)
                or not device_offload_enabled()
                or not DeviceRuntime.get().chip_available(chip)
                or any(len(c) == 0 for c in chunks.values())):
            return self.decode(want, chunks)
        lengths = {len(c) for c in chunks.values()}
        if len(lengths) != 1:
            raise ValueError(
                "surviving chunks have differing sizes %s" % lengths)
        k, m, w = self.k, self.m, self.w
        rows, cols, inv, _ = self._make_decoding(want, set(chunks))
        buffers = {i: self._word_view(chunks[i]) for i in chunks}
        out: dict[int, bytes] = {}
        recovered: dict[int, np.ndarray] = {}
        if rows:
            srcs = np.stack([buffers[r] for r in rows])
            rec = await self._device_matmul(
                inv, w, srcs, klass=klass, on_ticket=on_ticket,
                chip=chip)
            if rec is None:     # gate flipped mid-call: host matmul
                rec = gf.matmul_words(
                    np.array(inv, dtype=np.uint32), srcs, w)
            for i, c in enumerate(cols):
                if c not in chunks:
                    recovered[c] = np.ascontiguousarray(rec[i])
                    if c in want:
                        out[c] = recovered[c].tobytes()
        par_rows = [i for i in range(m)
                    if (k + i) in want and (k + i) not in chunks]
        if par_rows:
            n = next(iter(buffers.values())).shape[0] if buffers \
                else 0
            data = np.zeros((k, n), dtype=self._word_view(b"").dtype)
            for j in range(k):
                if any(self.matrix[i][j] for i in par_rows):
                    data[j] = (buffers[j] if j in buffers
                               else recovered[j])
            sel = [[self.matrix[i][j] for j in range(k)]
                   for i in par_rows]
            par = await self._device_matmul(
                sel, w, data, klass=klass, on_ticket=on_ticket,
                chip=chip)
            if par is None:
                par = gf.matmul_words(
                    np.array(sel, dtype=np.uint32), data, w)
            for x, i in enumerate(par_rows):
                out[k + i] = np.ascontiguousarray(par[x]).tobytes()
        for i in want:
            if i in chunks:
                out[i] = bytes(chunks[i])
        return out

    # a shingle window (possibly fewer than k chunks) can repair its
    # member — drop the base class's k-chunk floor
    REQUIRES_K_CHUNKS = False


class ErasureCodeShecSingle(ErasureCodeShec):
    TECHNIQUE_SINGLE = True
