"""Galois-field GF(2^w) arithmetic for erasure coding, w in {8, 16, 32}.

Semantics follow the jerasure/gf-complete conventions the reference links
against (src/erasure-code/jerasure/ErasureCodeJerasure.cc:22-28 pulls in
galois.h): the classic jerasure primitive polynomials

    w=8  : x^8 + x^4 + x^3 + x^2 + 1          (0x11d)
    w=16 : x^16 + x^12 + x^3 + x + 1          (0x1100b)
    w=32 : x^32 + x^22 + x^2 + x + 1          (0x400007)

ISA-L's GF(2^8) (src/erasure-code/isa/ErasureCodeIsa.cc) uses the same
0x11d field, so one table set serves both plugin families.

Host-side bulk region math is vectorized with numpy (the reference uses
SIMD in gf-complete/isa-l); the TPU device path lives in
ceph_tpu/ec/kernels.py and shares the tables built here.
"""

from __future__ import annotations

import functools

import numpy as np

PRIM_POLY = {
    2: 0x7, 3: 0xB, 4: 0x13, 5: 0x25, 6: 0x43, 7: 0x89,
    8: 0x11D, 9: 0x211, 10: 0x409, 11: 0x805, 12: 0x1053,
    13: 0x201B, 14: 0x4443, 15: 0x8003, 16: 0x1100B, 32: 0x400007,
}


# ---------------------------------------------------------------------------
# scalar arithmetic (python ints — exact for any w)
# ---------------------------------------------------------------------------

def mul_slow(a: int, b: int, w: int) -> int:
    """Carry-less multiply then reduce by the primitive polynomial."""
    if w not in PRIM_POLY:
        raise ValueError("unsupported GF word size w=%d" % w)
    prod = 0
    while b:
        if b & 1:
            prod ^= a
        b >>= 1
        a <<= 1
    poly = PRIM_POLY[w] | (1 << w)  # ensure the x^w term is present
    top = 1 << (2 * w - 1)
    for shift in range(w - 1, -1, -1):
        if prod & (top >> (w - 1 - shift)):
            prod ^= poly << shift
    return prod


@functools.lru_cache(maxsize=4)
def _tables(w: int) -> tuple[np.ndarray, np.ndarray]:
    """(log, exp) tables. exp has 2*(2^w-1) entries so log[a]+log[b] indexes
    directly without a modulo."""
    n = (1 << w) - 1
    exp = np.zeros(2 * n, dtype=np.uint32)
    log = np.zeros(n + 1, dtype=np.uint32)
    x = 1
    for i in range(n):
        exp[i] = x
        log[x] = i
        x = mul_slow(x, 2, w)
    exp[n:] = exp[:n]
    return log, exp


def gf_mul(a: int, b: int, w: int) -> int:
    if a == 0 or b == 0:
        return 0
    if w == 32:
        return mul_slow(a, b, w)
    log, exp = _tables(w)
    return int(exp[int(log[a]) + int(log[b])])


def gf_inv(a: int, w: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF inverse of 0")
    if w == 32:
        # a^(2^32-2) by square-and-multiply
        result, base, e = 1, a, (1 << 32) - 2
        while e:
            if e & 1:
                result = mul_slow(result, base, w)
            base = mul_slow(base, base, w)
            e >>= 1
        return result
    log, exp = _tables(w)
    n = (1 << w) - 1
    return int(exp[(n - int(log[a])) % n])


def gf_div(a: int, b: int, w: int) -> int:
    if a == 0:
        return 0
    return gf_mul(a, gf_inv(b, w), w)


def gf_pow(a: int, e: int, w: int) -> int:
    result = 1
    base = a
    while e:
        if e & 1:
            result = gf_mul(result, base, w)
        base = gf_mul(base, base, w)
        e >>= 1
    return result


# ---------------------------------------------------------------------------
# GF(2^8) dense tables (shared with the TPU kernels)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=1)
def mul_table_u8() -> np.ndarray:
    """Full 256x256 GF(2^8) product table (64 KiB)."""
    log, exp = _tables(8)
    a = np.arange(256, dtype=np.uint32)
    la = log[a][:, None].astype(np.int64)
    lb = log[a][None, :].astype(np.int64)
    t = exp[la + lb].astype(np.uint8)
    t[0, :] = 0
    t[:, 0] = 0
    return t


@functools.lru_cache(maxsize=1)
def nibble_tables_u8() -> tuple[np.ndarray, np.ndarray]:
    """(lo, hi): lo[c, x] = c*x for x<16; hi[c, x] = c*(x<<4).

    ISA-L's own trick (gf_vect_mul_init): a byte product c*b splits into
    c*(b&0xf) ^ c*(b>>4 << 4) — two 16-entry lookups per coefficient.
    Shapes: (256, 16) each.
    """
    t = mul_table_u8()
    lo = t[:, :16].copy()
    hi = t[:, [x << 4 for x in range(16)]].copy()
    return lo, hi


# ---------------------------------------------------------------------------
# vectorized region ops (numpy host path)
# ---------------------------------------------------------------------------

def region_mul_u8(region: np.ndarray, c: int) -> np.ndarray:
    """Multiply every byte of `region` by constant c in GF(2^8)."""
    if c == 0:
        return np.zeros_like(region)
    if c == 1:
        return region.copy()
    return mul_table_u8()[c][region]


def region_mad_u8(dst: np.ndarray, region: np.ndarray, c: int) -> None:
    """dst ^= c * region (in place), GF(2^8)."""
    if c == 0:
        return
    if c == 1:
        np.bitwise_xor(dst, region, out=dst)
    else:
        np.bitwise_xor(dst, mul_table_u8()[c][region], out=dst)


def matmul_u8(matrix: np.ndarray, data: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix-vector product over byte regions.

    matrix: (m, k) uint8 coefficients; data: (k, n) uint8 regions.
    Returns (m, n) uint8: out[i] = xor_j matrix[i, j] * data[j].

    Routes through the native C kernel (ceph_tpu.native libgfec —
    ISA-L's PSHUFB region-multiply technique) when available; the
    numpy path below is the bit-identical fallback and the reference
    for tests."""
    m, k = matrix.shape
    n = data.shape[1]
    if n >= 1024:
        import ctypes

        from ..native import lib

        L = lib()
        if L is not None:
            mat = np.ascontiguousarray(matrix, dtype=np.uint8)
            dat = np.ascontiguousarray(data, dtype=np.uint8)
            out = np.zeros((m, n), dtype=np.uint8)
            L.gfec_matmul(
                mat.ctypes.data_as(ctypes.c_char_p), k, m,
                dat.ctypes.data_as(ctypes.c_char_p),
                out.ctypes.data_as(ctypes.c_char_p), n)
            return out
    out = np.zeros((m, n), dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            region_mad_u8(out[i], data[j], int(matrix[i, j]))
    return out


def _words_mul_w(words: np.ndarray, c: int, w: int) -> np.ndarray:
    """Multiply an array of w-bit words by constant c (w=16 via tables,
    w=32 via shift-and-add with vectorized reduction)."""
    if c == 0:
        return np.zeros_like(words)
    if c == 1:
        return words.copy()
    if w == 16:
        log, exp = _tables(16)
        out = np.zeros_like(words)
        nz = words != 0
        idx = log[words[nz].astype(np.uint32)].astype(np.int64) + int(log[c])
        out[nz] = exp[idx].astype(words.dtype)
        return out
    # w == 32: Russian-peasant over the constant's bits, vectorized on words
    acc = np.zeros(words.shape, dtype=np.uint64)
    cur = words.astype(np.uint64)
    poly = np.uint64(PRIM_POLY[32] & 0xFFFFFFFF)
    top = np.uint64(1 << 31)
    mask = np.uint64(0xFFFFFFFF)
    cc = c
    while cc:
        if cc & 1:
            acc ^= cur
        cc >>= 1
        carry = (cur & top) != 0
        cur = (cur << np.uint64(1)) & mask
        cur[carry] ^= poly
    return acc.astype(words.dtype)


def region_mad_words(dst: np.ndarray, region: np.ndarray, c: int, w: int) -> None:
    """dst ^= c * region for w-bit word arrays (w in {16, 32})."""
    if c == 0:
        return
    np.bitwise_xor(dst, _words_mul_w(region, c, w), out=dst)


def matmul_words(matrix: np.ndarray, data: np.ndarray, w: int) -> np.ndarray:
    """GF(2^w) region matmul for w=16/32 word-views of chunks."""
    if w == 8:
        return matmul_u8(matrix, data)
    m, k = matrix.shape
    out = np.zeros((m, data.shape[1]), dtype=data.dtype)
    for i in range(m):
        for j in range(k):
            region_mad_words(out[i], data[j], int(matrix[i, j]), w)
    return out


# ---------------------------------------------------------------------------
# GF matrix algebra (decode-side)
# ---------------------------------------------------------------------------

def matrix_invert(mat: list[list[int]], w: int) -> list[list[int]]:
    """Invert a square matrix over GF(2^w) by Gauss-Jordan elimination.

    Raises ValueError when singular (the caller treats that as -EIO, like
    the reference's gf_invert_matrix use at ErasureCodeIsa.cc:263).
    """
    n = len(mat)
    a = [row[:] for row in mat]
    inv = [[1 if i == j else 0 for j in range(n)] for i in range(n)]
    for col in range(n):
        pivot = next((r for r in range(col, n) if a[r][col] != 0), None)
        if pivot is None:
            raise ValueError("singular matrix over GF(2^%d)" % w)
        if pivot != col:
            a[col], a[pivot] = a[pivot], a[col]
            inv[col], inv[pivot] = inv[pivot], inv[col]
        p = a[col][col]
        if p != 1:
            pinv = gf_inv(p, w)
            a[col] = [gf_mul(x, pinv, w) for x in a[col]]
            inv[col] = [gf_mul(x, pinv, w) for x in inv[col]]
        for r in range(n):
            if r != col and a[r][col]:
                f = a[r][col]
                a[r] = [x ^ gf_mul(f, y, w) for x, y in zip(a[r], a[col])]
                inv[r] = [x ^ gf_mul(f, y, w) for x, y in zip(inv[r], inv[col])]
    return inv


def matrix_mul(a: list[list[int]], b: list[list[int]], w: int) -> list[list[int]]:
    rows, inner, cols = len(a), len(b), len(b[0])
    out = [[0] * cols for _ in range(rows)]
    for i in range(rows):
        for j in range(cols):
            acc = 0
            for t in range(inner):
                acc ^= gf_mul(a[i][t], b[t][j], w)
            out[i][j] = acc
    return out
