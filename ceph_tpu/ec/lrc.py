"""LRC: layered locally-repairable erasure code.

Re-derivation of src/erasure-code/lrc/ErasureCodeLrc.{h,cc}: the code
is a stack of layers, each a (chunks_map, sub-profile) pair where the
map string assigns global chunk positions roles per layer — 'D' data,
'c' coding, '_' untouched (ErasureCodeLrc.h:61,127-134).  Encoding
runs the layers top-down so later (local) layers treat earlier global
parities as data (encode_chunks, ErasureCodeLrc.cc:736); decoding runs
bottom-up, each layer repairing what it can so upper layers see the
improved chunk set (decode_chunks, :776).  minimum_to_decode walks the
same bottom-up order so a single lost chunk is repaired from its local
group of l+1 chunks instead of k remote ones — the locality property
(_minimum_to_decode cases 1-3, :565).

The k/m/l shorthand generates the same mapping and layer strings as
the reference's parse_kml (:290-370): per local group,
k/groups data chunks, m/groups global parities, one local parity.
"""

from __future__ import annotations

import json
from typing import Mapping

from .base import ErasureCode
from .interface import ErasureCodeProfile

ERROR_LRC = -22


class LrcError(ValueError):
    pass


class Layer:
    __slots__ = ("chunks_map", "profile", "data", "coding", "chunks",
                 "chunks_set", "codec")

    def __init__(self, chunks_map: str, profile: dict):
        self.chunks_map = chunks_map
        self.profile = dict(profile)
        self.data = [i for i, c in enumerate(chunks_map) if c == "D"]
        self.coding = [i for i, c in enumerate(chunks_map) if c == "c"]
        self.chunks = self.data + self.coding
        self.chunks_set = set(self.chunks)
        self.codec = None


class ErasureCodeLrc(ErasureCode):
    """Layered code wrapping per-layer sub-codecs from the registry."""

    def __init__(self):
        super().__init__()
        self.layers: list[Layer] = []
        self.mapping = ""

    # -- profile parsing ---------------------------------------------------

    def init(self, profile: ErasureCodeProfile) -> None:
        profile = dict(profile)
        self._parse_kml(profile)
        if "mapping" not in profile:
            raise LrcError("the 'mapping' profile is missing")
        self.mapping = profile["mapping"]
        self.k = self.mapping.count("D")
        self.m = len(self.mapping) - self.k
        self._parse_mapping(profile)
        self._layers_parse(profile.get("layers", ""))
        self._layers_init()
        self._layers_sanity()
        self._profile = profile

    def _parse_kml(self, profile: dict) -> None:
        """k/m/l shorthand -> generated mapping + layers
        (ErasureCodeLrc::parse_kml)."""
        k = int(profile.get("k", -1))
        m = int(profile.get("m", -1))
        lv = int(profile.get("l", -1))
        if (k, m, lv) == (-1, -1, -1):
            return
        if -1 in (k, m, lv):
            raise LrcError("all of k, m, l must be set or none")
        for name in ("mapping", "layers"):
            if name in profile:
                raise LrcError(
                    "%s cannot be set when k, m, l are" % name)
        if lv == 0 or (k + m) % lv:
            raise LrcError("k + m must be a multiple of l")
        groups = (k + m) // lv
        if k % groups or m % groups:
            raise LrcError("k and m must be multiples of (k + m) / l")
        kg, mg = k // groups, m // groups
        profile["mapping"] = ("D" * kg + "_" * mg + "_") * groups
        layers = [[("D" * kg + "c" * mg + "_") * groups, ""]]
        for i in range(groups):
            row = ""
            for j in range(groups):
                row += ("D" * lv + "c") if i == j else "_" * (lv + 1)
            layers.append([row, ""])
        profile["layers"] = json.dumps(layers)

    def _layers_parse(self, description) -> None:
        if isinstance(description, str):
            if not description:
                raise LrcError("could not find 'layers' in profile")
            description = json.loads(description)
        if not isinstance(description, list) or not description:
            raise LrcError("layers must be a non-empty array")
        for entry in description:
            if not isinstance(entry, (list, tuple)) or not entry:
                raise LrcError("each layer must be an array")
            chunks_map = entry[0]
            prof = entry[1] if len(entry) > 1 else ""
            if isinstance(prof, str):
                prof = self._parse_str_profile(prof)
            elif not isinstance(prof, dict):
                raise LrcError("layer profile must be str or object")
            self.layers.append(Layer(chunks_map, prof))

    @staticmethod
    def _parse_str_profile(s: str) -> dict:
        out = {}
        for part in s.replace(",", " ").split():
            if "=" in part:
                key, val = part.split("=", 1)
                out[key] = val
        return out

    def _layers_init(self) -> None:
        from .plugin import ErasureCodePluginRegistry

        registry = ErasureCodePluginRegistry.instance()
        for layer in self.layers:
            prof = dict(layer.profile)
            prof.setdefault("k", str(len(layer.data)))
            prof.setdefault("m", str(len(layer.coding)))
            prof.setdefault("plugin", "jerasure")
            prof.setdefault("technique", "reed_sol_van")
            layer.codec = registry.factory(prof["plugin"], prof)

    def _layers_sanity(self) -> None:
        n = len(self.mapping)
        for layer in self.layers:
            if len(layer.chunks_map) != n:
                raise LrcError(
                    "layer map %r length != mapping length %d"
                    % (layer.chunks_map, n))

    # -- geometry ----------------------------------------------------------

    def get_chunk_size(self, object_size: int) -> int:
        return self.layers[0].codec.get_chunk_size(object_size)

    # -- encode ------------------------------------------------------------

    def encode_chunks(self, chunks: dict[int, bytes]) -> dict[int, bytes]:
        """chunks: the k data buffers, keyed either by physical 'D'
        position (what encode_prepare yields under the mapping) or by
        logical index 0..k-1; returns all k+m chunks keyed by
        position."""
        data_positions = [i for i, c in enumerate(self.mapping)
                          if c == "D"]
        if set(chunks) <= set(data_positions):
            out = dict(chunks)
        else:
            out = {data_positions[i]: chunks[i] for i in range(self.k)}
        size = len(next(iter(out.values())))
        for layer in self.layers:
            local = {j: out[c] for j, c in enumerate(layer.data)}
            enc = layer.codec.encode_chunks(local)
            nd = len(layer.data)
            for idx, c in enumerate(layer.coding):
                out[c] = enc[nd + idx]
        for i in range(len(self.mapping)):
            out.setdefault(i, bytes(size))
        return out

    # -- device offload ----------------------------------------------------

    def device_families(self) -> list[tuple]:
        """Distinct per-layer coding matrices (the encode program
        families: one global RS + one shared local-group family under
        the k/m/l shorthand) plus the hot repair shape — a single
        data loss reconstructed inside its local group."""
        from .batcher import reconstruct_matrix
        fams: list[tuple] = []
        seen: set = set()
        for ly in self.layers:
            dm = getattr(ly.codec, "_device_matrix", lambda: None)()
            if dm is None:
                continue
            key = (tuple(tuple(r) for r in dm[0]), dm[1])
            if key not in seen:
                seen.add(key)
                fams.append(dm)
        for ly in reversed(self.layers):
            dm = getattr(ly.codec, "_device_matrix", lambda: None)()
            if dm is None or not ly.data:
                continue
            k = ly.codec.get_data_chunk_count()
            n = k + len(ly.coding)
            try:
                rows, _chosen = reconstruct_matrix(
                    k, dm[1], dm[0], (0,), tuple(range(1, n)))
                fams.append((rows, dm[1]))
            except Exception:
                pass
            break
        return fams

    async def encode_async(self, want_to_encode: set[int],
                           data: bytes, klass: str | None = None,
                           on_ticket=None, chip: int | None = None,
                           tenant: str | None = None
                           ) -> dict[int, bytes]:
        """Layered encode with each layer's GF matmul batched onto
        the device: layers dispatch in dependency waves (a local
        layer waits for the global parities it treats as data), and
        the independent local-group layers of one wave issue
        concurrently so they share a flush/slot on the caller's
        affinity chip.  Host fallback per layer under offload-off /
        chip poison is `encode_chunks`' exact math."""
        import asyncio

        from ..device.runtime import DeviceRuntime
        from .batcher import device_offload_enabled, host_encode
        if (len(data) == 0 or not device_offload_enabled()
                or not DeviceRuntime.get().chip_available(chip)):
            return self.encode(want_to_encode, data)
        import numpy as np
        out = dict(self.encode_prepare(data))
        size = len(next(iter(out.values())))

        async def layer_encode(ly) -> None:
            dm = getattr(ly.codec, "_device_matrix", lambda: None)()
            if dm is None:
                local = {j: out[c] for j, c in enumerate(ly.data)}
                enc = ly.codec.encode_chunks(local)
                nd = len(ly.data)
                for idx, c in enumerate(ly.coding):
                    out[c] = enc[nd + idx]
                return
            matrix, w = dm
            arr = np.stack([
                np.frombuffer(out[c], dtype=self._word_dtype(w))
                for c in ly.data])
            parity = await self._device_matmul(
                matrix, w, arr, klass=klass, on_ticket=on_ticket,
                chip=chip, tenant=tenant)
            if parity is None:      # gate flipped mid-call
                parity = host_encode(matrix, w, arr)
            for idx, c in enumerate(ly.coding):
                out[c] = np.ascontiguousarray(parity[idx]).tobytes()

        pending = list(self.layers)
        while pending:
            ready = [ly for ly in pending
                     if all(c in out for c in ly.data)]
            if not ready:           # defensive: keep declared order
                ready = pending[:1]
            await asyncio.gather(*[layer_encode(ly) for ly in ready])
            pending = [ly for ly in pending if ly not in ready]
        for i in range(len(self.mapping)):
            out.setdefault(i, bytes(size))
        return {i: out[i] for i in want_to_encode}

    async def _layer_decode(self, layer, local_want: set,
                            local_avail: dict, klass, chip,
                            on_ticket) -> dict[int, bytes]:
        """One layer's repair as a device matmul: the layer's erased
        chunks rebuild directly from its survivors through the cached
        reconstruction rows (decode-as-encode, the same reformulation
        the RS device path uses) — bit-identical to the layer codec's
        host decode_chunks."""
        import numpy as np

        from .batcher import host_encode, reconstruct_matrix
        dm = getattr(layer.codec, "_device_matrix", lambda: None)()
        if dm is None:
            return layer.codec.decode_chunks(local_want, local_avail)
        matrix, w = dm
        k = layer.codec.get_data_chunk_count()
        erased = tuple(sorted(local_want))
        have = tuple(sorted(local_avail))
        rows, chosen = reconstruct_matrix(k, w, matrix, erased, have)
        arr = np.stack([
            np.frombuffer(local_avail[c], dtype=self._word_dtype(w))
            for c in chosen])
        words = await self._device_matmul(
            rows, w, arr, klass=klass, on_ticket=on_ticket, chip=chip)
        if words is None:
            words = host_encode(rows, w, arr)
        return {e: np.ascontiguousarray(words[i]).tobytes()
                for i, e in enumerate(erased)}

    async def decode_async(self, want_to_read: set[int],
                           chunks: Mapping[int, bytes],
                           klass: str | None = None,
                           on_ticket=None,
                           chip: int | None = None) -> dict[int, bytes]:
        """`decode_chunks`' bottom-up layered repair with every layer
        step batched onto the device — a single lost chunk repairs
        from its local group of l+1 chunks (the locality property) as
        ONE small dispatch on the caller's chip instead of a k-wide
        host decode."""
        from ..device.runtime import DeviceRuntime
        from .batcher import device_offload_enabled
        want = set(want_to_read)
        chunks = dict(chunks)
        if (want <= set(chunks)
                or not device_offload_enabled()
                or not DeviceRuntime.get().chip_available(chip)
                or any(len(c) == 0 for c in chunks.values())):
            return self.decode(want, chunks)
        lengths = {len(c) for c in chunks.values()}
        if len(lengths) != 1:
            raise ValueError(
                "surviving chunks have differing sizes %s" % lengths)
        decoded = dict(chunks)
        erasures = set(range(self.get_chunk_count())) - set(chunks)
        progressed = True
        while progressed and (want & erasures):
            progressed = False
            for layer in reversed(self.layers):
                layer_erasures = layer.chunks_set & erasures
                if not layer_erasures:
                    continue
                if len(layer_erasures) > len(layer.coding):
                    continue
                local_avail = {}
                local_want = set()
                for j, c in enumerate(layer.chunks):
                    if c not in erasures:
                        local_avail[j] = decoded[c]
                    else:
                        local_want.add(j)
                rec = await self._layer_decode(
                    layer, local_want, local_avail, klass, chip,
                    on_ticket)
                for j, c in enumerate(layer.chunks):
                    if j in rec:
                        decoded[c] = rec[j]
                    erasures.discard(c)
                progressed = True
                if not (want & erasures):
                    break
        missing = want & erasures
        if missing:
            raise IOError("unable to read chunks %s" % sorted(missing))
        return {i: bytes(decoded[i]) for i in want if i in decoded}

    # -- decode ------------------------------------------------------------

    def decode_chunks(self, want_to_read, chunks: Mapping[int, bytes]
                      ) -> dict[int, bytes]:
        """Bottom-up layered repair (ErasureCodeLrc::decode_chunks)."""
        want = set(want_to_read)
        decoded = dict(chunks)
        erasures = set(range(self.get_chunk_count())) - set(chunks)
        # the reference makes one bottom-up pass; iterating to fixpoint
        # additionally recovers chains (e.g. a global repair enabling a
        # local-parity rebuild) — a strict superset of its successes
        progressed = True
        while progressed and (want & erasures):
            progressed = False
            for layer in reversed(self.layers):
                layer_erasures = layer.chunks_set & erasures
                if not layer_erasures:
                    continue
                if len(layer_erasures) > len(layer.coding):
                    continue  # too many for this layer
                local_avail = {}
                local_want = set()
                for j, c in enumerate(layer.chunks):
                    if c not in erasures:
                        local_avail[j] = decoded[c]
                    else:
                        local_want.add(j)
                rec = layer.codec.decode_chunks(local_want, local_avail)
                for j, c in enumerate(layer.chunks):
                    if j in rec:
                        decoded[c] = rec[j]
                    erasures.discard(c)
                progressed = True
                if not (want & erasures):
                    break
        missing = want & erasures
        if missing:
            raise IOError("unable to read chunks %s" % sorted(missing))
        return {i: decoded[i] for i in want if i in decoded}

    # a single local group (l+1 chunks, possibly fewer than k) can
    # repair its member — drop the base class's k-chunk floor
    REQUIRES_K_CHUNKS = False

    # -- read planning (the locality property) -----------------------------

    def _minimum_to_decode(self, want_to_read, available) -> set[int]:
        """Cases 1-3 of ErasureCodeLrc::_minimum_to_decode."""
        want = set(want_to_read)
        avail = set(available)
        n = self.get_chunk_count()
        erasures_total = {i for i in range(n) if i not in avail}
        erasures_not_recovered = set(erasures_total)
        erasures_want = want & erasures_total

        # case 1: nothing wanted is missing
        if not erasures_want:
            return set(want)

        # case 2: bottom-up recovery with as few chunks as possible
        minimum: set[int] = set()
        for layer in reversed(self.layers):
            layer_want = want & layer.chunks_set
            if not layer_want:
                continue
            layer_erasures = layer_want & erasures_want
            if not layer_erasures:
                minimum |= layer_want
                continue
            erasures = layer.chunks_set & erasures_not_recovered
            if len(erasures) > len(layer.coding):
                continue  # hope an upper layer does better
            minimum |= layer.chunks_set - erasures_not_recovered
            erasures_not_recovered -= erasures
            erasures_want -= erasures
        if not erasures_want:
            out = minimum | want
            return out - erasures_total

        # case 3: recover as much as possible from every layer
        remaining = set(erasures_total)
        for layer in reversed(self.layers):
            layer_erasures = layer.chunks_set & remaining
            if not layer_erasures:
                continue
            if len(layer_erasures) <= len(layer.coding):
                remaining -= layer_erasures
        if not remaining:
            return set(avail)
        raise IOError("not enough chunks in %s to read %s"
                      % (sorted(avail), sorted(want)))

    def get_sub_chunk_count(self) -> int:
        return 1
