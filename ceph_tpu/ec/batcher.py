"""Device EC offload with cross-object batching.

SURVEY.md "hard parts": 4KiB stripes are tiny against dispatch/HBM
latency — the TPU win only materialises when many in-flight stripes
ride one dispatch.  This is the aggregation layer the reference doesn't
need (ISA-L encodes synchronously per call inside the OSD thread,
src/erasure-code/isa/ErasureCodeIsa.cc:129).

Two dispatch architectures share this module's staging/encode path:

* **stream** (``device_dispatch_mode=stream``, the default):
  `encode` is a thin enqueue shim onto the caller chip's persistent
  dispatch stream (ceph_tpu.device.stream) — continuous admission
  into fixed-geometry slots, independent per-slot retire, no flush
  barrier.  The stream's slot dispatches call back into
  `stream_dispatch` below, so staging, mesh sharding, tickets and
  host degradation are identical in both modes.
* **flush** (the legacy architecture, kept as the bench baseline and
  the degradation route): concurrent `encode_async` calls from any
  number of PGs/objects in the same event loop are queued per
  (coding-matrix, w, service-class) key and flushed as ONE device
  matmul batch — either when the pending payload reaches
  `max_batch_bytes` (conf ``ec_batch_max_bytes``) or when the oldest
  entry has waited `window_us` (conf ``ec_batch_flush_us``; the
  deadline flush keeps p99 bounded, the way the reference bounds
  batching with per-op deadlines elsewhere).

Every flush routes through the shared device runtime
(ceph_tpu.device.runtime) onto a mesh **chip** — the caller's
affinity chip (OSDs pass `chip=`; chip-less callers take the first
available chip):

* the batch is **ragged**: items of heterogeneous width pack
  contiguously along the column axis with per-item segment offsets,
  and the flush TOTAL stages across a pow2 **bucket ladder**
  (``DeviceRuntime.ragged_plan`` — the Ragged Paged Attention recipe,
  arXiv:2604.15464), so only the ladder's tail rounds up: per-item
  padding is zero, mixed-size workloads stop burning bucket-ceiling
  bandwidth, and steady state still re-dispatches a handful of
  compiled bucket programs (zero padding is exact under GF linearity
  — parity columns of the pad are zeros that are sliced off, so
  ladder parity is bit-identical to the unpadded host encode, pinned
  by tests/test_device_runtime.py + tests/test_ec_ragged.py);
* admission is weighted-fair across classes (client-EC, recovery-EC,
  mapping) with bounded in-flight dispatches per chip; queue-full
  degrades THIS flush to the host codepath rather than stacking
  device work;
* an **oversized flush shards column-wise across every available
  chip** (the stripe-axis split MULTICHIP_SCALING.json proves
  collective-free: GF parity is column-independent) and reassembles
  bit-identically; a shard failure poisons only its chip and that
  shard is re-encoded on the host;
* a failed dispatch poisons ITS chip (host fallback for the OSDs
  bound there + per-chip DEVICE_FALLBACK health via the OSD beacon)
  and the flush is re-encoded on the host, so awaiting OSD ops never
  observe the loss — the rest of the mesh keeps serving on-device;
* each device flush carries a DispatchTicket delivered to per-item
  `on_ticket` callbacks — the exact per-op device-dispatch
  attribution the OpTracker stage histograms consume.

Decode/reconstruct rides the same queue: a reconstruction is an encode
with the cached inverted matrix (ErasureCodeIsaTableCache's trick), so
degraded reads and recovery batch with ordinary writes.
"""

from __future__ import annotations

import asyncio
import functools

import numpy as np

from . import matrices
from ..device.runtime import (DeviceBusy, DeviceRuntime, K_CLIENT_EC)

_WORD_DTYPE = {8: np.uint8, 16: np.uint16, 32: np.uint32}


def device_offload_enabled() -> bool:
    """Device EC offload defaults to on only where it pays: a real
    accelerator backend.  CEPH_TPU_EC_OFFLOAD=1/0 forces it (tests
    force 1 to exercise the batcher on the CPU backend)."""
    import os
    v = os.environ.get("CEPH_TPU_EC_OFFLOAD")
    if v is not None:
        return v not in ("0", "false", "no")
    try:
        import jax
        return jax.default_backend() == "tpu"
    except Exception:       # pragma: no cover - jax always present
        return False


def host_encode(matrix, w: int, data: np.ndarray) -> np.ndarray:
    """Synchronous host GF matmul — the fallback codepath when the
    device is lost or admission pushes back.  [k, n] words -> [m, n]."""
    from . import gf
    m = np.asarray(matrix, dtype=np.int64)
    if int(w) == 8:
        return gf.matmul_u8(m.astype(np.uint8),
                            np.ascontiguousarray(data, np.uint8))
    return gf.matmul_words(m, data, int(w))


def tenant_label(tenants) -> str | None:
    """A dispatch's tenant attribution: the one tenant every batched
    item agreed on, "mixed" when several tenants' stripes share the
    dispatch, None for tenant-less work."""
    distinct = {t for t in tenants if t is not None}
    if not distinct:
        return None
    if len(distinct) == 1:
        return next(iter(distinct))
    return "mixed"


class _PendingBatch:
    __slots__ = ("arrays", "futures", "tickets", "tenants", "n_words",
                 "timer", "t_first")

    def __init__(self):
        import time
        self.arrays: list[np.ndarray] = []   # each [k, n_i] words
        self.futures: list[asyncio.Future] = []
        self.tickets: list = []              # per-item on_ticket cbs
        self.tenants: list = []              # per-item tenant keys
        self.n_words = 0
        self.timer = None
        # first item's arrival: the flush ticket's t_enqueue, so
        # queue_wait honestly includes the batch-window wait (the
        # figure the dispatch stream is gated against)
        self.t_first = time.monotonic()

    def tenant_label(self) -> str | None:
        return tenant_label(self.tenants)


class DeviceBatcher:
    """Batches GF(2^w) region matmuls across concurrent callers.

    One instance per event loop (get() is loop-local); keys are
    (matrix-tuple, w, klass) so every profile/erasure-signature gets
    its own stream per service class but shares the flush machinery.
    """

    def __init__(self, window_us: int = 300,
                 max_batch_bytes: int = 8 << 20):
        # flush-mode tunables; conf-backed (ec_batch_flush_us /
        # ec_batch_max_bytes, adopted via DeviceRuntime.configure) so
        # the bench can sweep them
        self.window_us = window_us
        self.max_batch_bytes = max_batch_bytes
        self._pending: dict[tuple, _PendingBatch] = {}
        self.batches_flushed = 0
        self.items_encoded = 0
        self.host_flushes = 0        # flushes served by the host path
        self.sharded_flushes = 0     # flushes split across the mesh
        # device-dispatch telemetry: per-flush wall time of the device
        # call.  Kept for bench --trace and back-compat; per-OP
        # attribution now rides the dispatch ticket instead of
        # sampling these.
        self.last_flush_s = 0.0
        self.flush_seconds = 0.0
        self.flush_history: list[float] = []   # bounded ring

    @classmethod
    def get(cls) -> "DeviceBatcher":
        """Per-event-loop instance, stored ON the loop object so its
        lifetime tracks the loop's (an id(loop)-keyed registry would
        hand a recycled address a stale instance whose dead timer
        blocks the deadline flush forever)."""
        loop = asyncio.get_event_loop()
        inst = getattr(loop, "_ceph_tpu_ec_batcher", None)
        if inst is None:
            inst = cls()
            loop._ceph_tpu_ec_batcher = inst
        return inst

    @staticmethod
    @functools.lru_cache(maxsize=256)
    def _encoder(matrix_key: tuple, w: int):
        import os

        import jax

        from .kernels import DeviceEncoder, FusedEncoder
        matrix = [list(row) for row in matrix_key]
        if jax.default_backend() == "tpu" and w == 8 \
                and os.environ.get("CEPH_TPU_EC_FUSED") != "0":
            # the HBM-bandwidth path: XOR schedule with the planes8
            # bit transpose fused in VMEM, byte layout in/out — the
            # fast kernel IS the cluster write path (measured 391
            # GiB/s payload at this tile, k=8,m=3, round 4).  Tile
            # bounded for wide profiles so ~(2k+2m+buffering) x tile
            # stays inside VMEM.
            k, m = len(matrix[0]), len(matrix)
            tile = 262144 if k + m <= 11 else 131072
            return FusedEncoder(matrix, tile_bytes=tile)
        # the pallas matmul path keeps the w-fold bit-plane expansion
        # in VMEM; w=8 only — wider words use the XLA path
        use_pallas = jax.default_backend() == "tpu" and w == 8
        return DeviceEncoder(matrix, w, use_pallas=use_pallas,
                             tile=4096)

    async def encode(self, matrix: list[list[int]], w: int,
                     data: np.ndarray, klass: str = K_CLIENT_EC,
                     on_ticket=None, chip: int | None = None,
                     tenant: str | None = None) -> np.ndarray:
        """data [k, n] words -> [m, n] parity words, batched with any
        concurrent callers using the same (matrix, w, klass, chip).

        `chip` is the caller's mesh affinity (OSDs pass their bound
        chip; None routes to the first available chip) — batches are
        keyed per chip so each chip runs its own stream and a
        poisoned chip degrades only its own callers.

        `on_ticket` (if given) receives the flush's DispatchTicket
        after the device call — exact per-op dispatch attribution
        (the primary shard's ticket when the flush sharded across the
        mesh).  Host-fallback flushes deliver no ticket (there was no
        device dispatch to attribute).

        Dispatch architecture: under ``device_dispatch_mode=stream``
        (the default) this call is a thin enqueue shim onto the
        caller's chip's persistent dispatch stream (device.stream) —
        continuous admission, independent retire.  The accumulate-
        and-flush path below survives as the ``flush`` mode (bench
        baseline) and as the stream's degradation route."""
        rt = DeviceRuntime.get()
        if rt.dispatch_mode == "stream":
            target = rt.route(chip)
            if target is not None:
                return await target.stream.encode(
                    matrix, int(w), np.ascontiguousarray(data),
                    klass, on_ticket=on_ticket, tenant=tenant)
        key = (tuple(tuple(r) for r in matrix), int(w), klass,
               None if chip is None else int(chip))
        loop = asyncio.get_event_loop()
        pb = self._pending.get(key)
        if pb is None:
            pb = _PendingBatch()
            self._pending[key] = pb
        fut = loop.create_future()
        pb.arrays.append(np.ascontiguousarray(data))
        pb.futures.append(fut)
        pb.tickets.append(on_ticket)
        pb.tenants.append(tenant)
        pb.n_words += data.shape[1]
        word_bytes = _WORD_DTYPE[int(w)]().itemsize
        if (pb.n_words * data.shape[0] * word_bytes
                >= self.max_batch_bytes):
            self._flush(key)
        elif pb.timer is None:
            pb.timer = loop.call_later(self.window_us / 1e6,
                                       self._flush, key)
        return await fut

    def _flush(self, key) -> None:
        """Detach the pending batch and dispatch it as a task (the
        device path awaits admission, so the flush body is async —
        call_later fires this sync shim)."""
        pb = self._pending.pop(key, None)
        if pb is None:
            return
        if pb.timer is not None:
            pb.timer.cancel()
        asyncio.get_event_loop().create_task(self._flush_async(key, pb))

    async def _device_dispatch(self, rt, target, matrix_key, w: int,
                               klass: str, parts: list[np.ndarray],
                               n: int, tenant: str | None,
                               t_enqueue: float | None,
                               stream: bool):
        """The shared device attempt both architectures ride: shard
        plan -> single-chip or mesh-sharded encode, flush timing
        recorded.  Returns (out, ticket) — (None, None) when the
        device pushed back or was lost (caller degrades to the host
        codec)."""
        if target is None or not target.available:
            return None, None
        import time
        t0 = time.perf_counter()
        plan = rt.shard_plan(target, n)
        if len(plan) == 1:
            out, ticket = await self._encode_shard(
                target, matrix_key, int(w), klass, parts, n,
                solo=True, tenant=tenant, t_enqueue=t_enqueue,
                stream=stream)
        else:
            out, ticket = await self._encode_sharded(
                rt, plan, matrix_key, int(w), klass, parts,
                tenant=tenant, t_enqueue=t_enqueue, stream=stream)
        if out is not None:
            dt = time.perf_counter() - t0
            self.last_flush_s = dt
            self.flush_seconds += dt
            self.flush_history.append(dt)
            if len(self.flush_history) > 512:
                del self.flush_history[:256]
        return out, ticket

    def _host_dispatch(self, rt, target, chip_idx, matrix_key, w: int,
                       parts: list[np.ndarray]) -> np.ndarray:
        """Host-codec degradation route (device lost / DeviceBusy):
        bit-parity with the device path by construction.  Raises on a
        real codec error — the caller must fail the awaiting futures,
        never hang them."""
        flat = (parts[0] if len(parts) == 1
                else np.concatenate(parts, axis=1))
        out = host_encode([list(r) for r in matrix_key], w, flat)
        (target if target is not None
         else rt.chip(chip_idx)).host_fallbacks += 1
        self.host_flushes += 1
        return out

    async def stream_dispatch(self, chip, matrix_key, w: int,
                              klass: str, parts: list[np.ndarray],
                              n: int, tenant: str | None = None,
                              t_enqueue: float | None = None):
        """One stream slot's dispatch (device.stream DispatchStream):
        the same device path flushes ride — ragged bucket-ladder
        staging on the slot's chip, mesh sharding for oversized
        groups — with the host codec as the degradation route.
        Returns (out, ticket-or-None); raises only on a host-codec
        failure."""
        rt = chip.rt
        out, ticket = await self._device_dispatch(
            rt, chip if chip.available else None, matrix_key, w,
            klass, parts, n, tenant, t_enqueue, stream=True)
        if out is None:
            out = self._host_dispatch(rt, chip, chip.index,
                                      matrix_key, w, parts)
        self.batches_flushed += 1
        self.items_encoded += len(parts)
        return out, ticket

    async def _flush_async(self, key, pb: _PendingBatch) -> None:
        matrix_key, w, klass, chip_idx = key
        rt = DeviceRuntime.get()
        target = rt.route(chip_idx)
        out, ticket = await self._device_dispatch(
            rt, target, matrix_key, int(w), klass, pb.arrays,
            pb.n_words, pb.tenant_label(), pb.t_first, stream=False)
        if out is None:
            try:
                out = self._host_dispatch(rt, target, chip_idx,
                                          matrix_key, w, pb.arrays)
            except Exception as e:
                # a host-path failure is a real codec error: it must
                # reach the awaiting OSD ops (they would otherwise
                # hang forever — submit_write's sub-op timeout sits
                # AFTER the encode await)
                for fut in pb.futures:
                    if not fut.cancelled():
                        fut.set_exception(
                            IOError("EC encode failed: %r" % e))
                return
        self.batches_flushed += 1
        self.items_encoded += len(pb.arrays)
        self._deliver(pb, out, ticket)

    @staticmethod
    def _deliver(pb: _PendingBatch, out: np.ndarray, ticket) -> None:
        off = 0
        for arr, fut, cb in zip(pb.arrays, pb.futures, pb.tickets):
            ni = arr.shape[1]
            if not fut.cancelled():
                fut.set_result(out[:, off:off + ni])
            if cb is not None and ticket is not None:
                try:
                    cb(ticket)
                except Exception:
                    pass    # attribution must never sink the flush
            off += ni

    async def _encode_shard(self, chip, matrix_key, w: int,
                            klass: str, parts: list[np.ndarray],
                            n: int, solo: bool,
                            tenant: str | None = None,
                            t_enqueue: float | None = None,
                            stream: bool = False):
        """One chip's slice of a flush: admit on the chip's queue,
        stage the ragged total into its pooled bucket-ladder buffers,
        dispatch on its device.  Returns (parity [m, n], ticket).

        Ragged staging: the flush's heterogeneous-width items pack
        contiguously along the column axis; the packed total covers a
        **bucket ladder** (``DeviceRuntime.ragged_plan``) of pow2
        segments, each staged in its own pooled buffer and encoded by
        an already-compiled bucket program, so only the ladder's tail
        rounds up — per-item widths never pad, and a mixed-size flush
        stops burning bucket-ceiling bandwidth (GF parity is
        column-independent, so the segment split is exact).  Items may
        span segment boundaries; per-item offsets stay global column
        offsets, so `_deliver`'s slicing is unchanged.

        `solo=True` is the whole-flush single-chip path: DeviceBusy
        and device loss return (None, None) so the caller degrades
        the WHOLE flush to the host codec (the pre-mesh behavior).
        Shards of a mesh-split flush (`solo=False`) instead degrade
        THEMSELVES to the host inline — a lost chip costs its shard,
        not the flush — so reassembly is unconditional."""
        dtype = _WORD_DTYPE[int(w)]
        k = parts[0].shape[0]
        plan = chip.rt.ragged_plan(n)
        padded = sum(seg for _lo, seg in plan)
        ticket = chip.open_ticket(klass, padded,
                                  n * k * dtype().itemsize,
                                  tenant=tenant, t_enqueue=t_enqueue,
                                  stream=stream)
        try:
            await chip.admit(ticket)
        except DeviceBusy:
            if solo:
                return None, None
            return self._host_shard(chip, matrix_key, w, parts), None
        bufs: list[np.ndarray] = []
        try:
            for _lo, seg in plan:
                bufs.append(chip.pool.lease((k, seg), dtype))
            # pack items contiguously across the ladder (an item can
            # straddle two segments); leased buffers come back zeroed
            # so segment tails are exact GF zero columns
            si, soff = 0, 0
            for arr in parts:
                ni, pos = arr.shape[1], 0
                while pos < ni:
                    take = min(plan[si][1] - soff, ni - pos)
                    bufs[si][:, soff:soff + take] = \
                        arr[:, pos:pos + take]
                    soff += take
                    pos += take
                    if soff == plan[si][1]:
                        si += 1
                        soff = 0
            chip.launch(ticket)         # injected-fault hook
            enc = self._encoder(matrix_key, int(w))
            outs = []
            used = n
            for (_lo, seg), buf in zip(plan, bufs):
                chip.note_program("ec", (matrix_key, int(w), seg))
                u = min(seg, used)
                outs.append(np.asarray(
                    enc(chip.place(buf)))[:, :u])
                used -= u
            out = (outs[0] if len(outs) == 1
                   else np.concatenate(outs, axis=1))
            chip.finish(ticket, ok=True)
            chip.note_staging(n, padded)
            return out, ticket
        except Exception as e:
            # device loss: poison THIS chip (host fallback + per-chip
            # DEVICE_FALLBACK health for the OSDs bound to it); the
            # rest of the mesh keeps serving
            chip.finish(ticket, ok=False, error=e)
            chip.poison(e)
            if solo:
                return None, None
            return self._host_shard(chip, matrix_key, w, parts), None
        finally:
            for buf in bufs:
                chip.pool.release(buf)

    def _host_shard(self, chip, matrix_key, w: int,
                    parts: list[np.ndarray]) -> np.ndarray:
        """Host-encode one shard of a mesh-split flush (its chip was
        lost or pushed back): correctness never depends on the mesh."""
        flat = (parts[0] if len(parts) == 1
                else np.concatenate(parts, axis=1))
        chip.host_fallbacks += 1
        self.host_flushes += 1
        return host_encode([list(r) for r in matrix_key], w, flat)

    async def _encode_sharded(self, rt, plan, matrix_key, w: int,
                              klass: str, arrays: list[np.ndarray],
                              tenant: str | None = None,
                              t_enqueue: float | None = None,
                              stream: bool = False):
        """Mesh-shard one oversized flush across the plan's chips:
        contiguous column slices encode concurrently (proven
        collective-free over the stripe axis) and reassemble
        bit-identically.  Returns (parity, primary ticket)."""
        flat = (arrays[0] if len(arrays) == 1
                else np.concatenate(arrays, axis=1))
        self.sharded_flushes += 1
        parts = await asyncio.gather(*[
            self._encode_shard(chip, matrix_key, w, klass,
                               [flat[:, lo:hi]], hi - lo, solo=False,
                               tenant=tenant, t_enqueue=t_enqueue,
                               stream=stream)
            for chip, lo, hi in plan])
        out = np.concatenate([p for p, _t in parts], axis=1)
        ticket = next((t for _p, t in parts if t is not None), None)
        return out, ticket


def reconstruct_matrix(k: int, w: int, matrix: list[list[int]],
                       erased: tuple[int, ...],
                       have: tuple[int, ...]):
    """(rows, chosen): rows rebuild `erased` chunks directly from the
    `chosen` survivors — the decode-as-encode reformulation both
    device paths share (invert surviving rows, compose parity rows
    through the inverse).  Cached per erasure signature so a recovery
    sweep pays the O(k^3) GF inversion once, like
    ErasureCodeIsaTableCache."""
    key = (k, w, tuple(tuple(r) for r in matrix), erased, have)
    return _reconstruct_matrix_cached(key)


@functools.lru_cache(maxsize=512)
def _reconstruct_matrix_cached(key):
    k, w, matrix_t, erased, have = key
    matrix = [list(r) for r in matrix_t]
    inv, chosen = matrices.decoding_matrix(k, w, matrix, list(erased),
                                           list(have))
    rows = []
    for e in erased:
        if e < k:
            rows.append(list(inv[e]))
        else:
            coef = matrix[e - k]
            rows.append([
                functools.reduce(
                    lambda a, t: a ^ t,
                    (matrices.gf_mul(coef[j], inv[j][i], w)
                     for j in range(k)), 0)
                for i in range(k)])
    return rows, chosen
