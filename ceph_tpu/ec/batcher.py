"""Device EC offload with cross-object batching.

SURVEY.md "hard parts": 4KiB stripes are tiny against dispatch/HBM
latency — the TPU win only materialises when many in-flight stripes
ride one dispatch.  This is the aggregation layer the reference doesn't
need (ISA-L encodes synchronously per call inside the OSD thread,
src/erasure-code/isa/ErasureCodeIsa.cc:129): concurrent `encode_async`
calls from any number of PGs/objects in the same event loop are queued
per (coding-matrix, w) key and flushed as ONE device matmul batch —
either when the pending payload reaches `max_batch_bytes` or when the
oldest entry has waited `window_us` (deadline flush keeps p99 bounded,
the way the reference bounds batching with per-op deadlines elsewhere).

Bit-parity: the device path consumes the same coding matrices as the
numpy host path and the GF(2) bit-plane matmul is exact, so outputs are
byte-identical (pinned by tests/test_ec_batcher.py against the host
codecs and transitively by the non-regression corpus).

Decode/reconstruct rides the same queue: a reconstruction is an encode
with the cached inverted matrix (ErasureCodeIsaTableCache's trick), so
degraded reads and recovery batch with ordinary writes.
"""

from __future__ import annotations

import asyncio
import functools

import numpy as np

from . import matrices


def device_offload_enabled() -> bool:
    """Device EC offload defaults to on only where it pays: a real
    accelerator backend.  CEPH_TPU_EC_OFFLOAD=1/0 forces it (tests
    force 1 to exercise the batcher on the CPU backend)."""
    import os
    v = os.environ.get("CEPH_TPU_EC_OFFLOAD")
    if v is not None:
        return v not in ("0", "false", "no")
    try:
        import jax
        return jax.default_backend() == "tpu"
    except Exception:       # pragma: no cover - jax always present
        return False


class _PendingBatch:
    __slots__ = ("arrays", "futures", "n_words", "timer")

    def __init__(self):
        self.arrays: list[np.ndarray] = []   # each [k, n_i] words
        self.futures: list[asyncio.Future] = []
        self.n_words = 0
        self.timer = None


class DeviceBatcher:
    """Batches GF(2^w) region matmuls across concurrent callers.

    One instance per event loop (get() is loop-local); keys are
    (matrix-tuple, w) so every profile/erasure-signature gets its own
    stream but shares the flush machinery.
    """

    def __init__(self, window_us: int = 300,
                 max_batch_bytes: int = 8 << 20):
        self.window_us = window_us
        self.max_batch_bytes = max_batch_bytes
        self._pending: dict[tuple, _PendingBatch] = {}
        self.batches_flushed = 0
        self.items_encoded = 0
        # device-dispatch telemetry: per-flush wall time of the encode
        # call (the "device dispatch" stage of an op's timeline).
        # last_flush_s is what an awaiting OSD op samples into its
        # stage histogram right after encode_async resolves; the ring
        # feeds bench --trace percentiles
        self.last_flush_s = 0.0
        self.flush_seconds = 0.0
        self.flush_history: list[float] = []   # bounded ring

    @classmethod
    def get(cls) -> "DeviceBatcher":
        """Per-event-loop instance, stored ON the loop object so its
        lifetime tracks the loop's (an id(loop)-keyed registry would
        hand a recycled address a stale instance whose dead timer
        blocks the deadline flush forever)."""
        loop = asyncio.get_event_loop()
        inst = getattr(loop, "_ceph_tpu_ec_batcher", None)
        if inst is None:
            inst = cls()
            loop._ceph_tpu_ec_batcher = inst
        return inst

    @staticmethod
    @functools.lru_cache(maxsize=256)
    def _encoder(matrix_key: tuple, w: int):
        import os

        import jax

        from .kernels import DeviceEncoder, FusedEncoder
        matrix = [list(row) for row in matrix_key]
        if jax.default_backend() == "tpu" and w == 8 \
                and os.environ.get("CEPH_TPU_EC_FUSED") != "0":
            # the HBM-bandwidth path: XOR schedule with the planes8
            # bit transpose fused in VMEM, byte layout in/out — the
            # fast kernel IS the cluster write path (measured 391
            # GiB/s payload at this tile, k=8,m=3, round 4).  Tile
            # bounded for wide profiles so ~(2k+2m+buffering) x tile
            # stays inside VMEM.
            k, m = len(matrix[0]), len(matrix)
            tile = 262144 if k + m <= 11 else 131072
            return FusedEncoder(matrix, tile_bytes=tile)
        # the pallas matmul path keeps the w-fold bit-plane expansion
        # in VMEM; w=8 only — wider words use the XLA path
        use_pallas = jax.default_backend() == "tpu" and w == 8
        return DeviceEncoder(matrix, w, use_pallas=use_pallas,
                             tile=4096)

    async def encode(self, matrix: list[list[int]], w: int,
                     data: np.ndarray) -> np.ndarray:
        """data [k, n] words -> [m, n] parity words, batched with any
        concurrent callers using the same (matrix, w)."""
        key = (tuple(tuple(r) for r in matrix), int(w))
        loop = asyncio.get_event_loop()
        pb = self._pending.get(key)
        if pb is None:
            pb = _PendingBatch()
            self._pending[key] = pb
        fut = loop.create_future()
        pb.arrays.append(np.ascontiguousarray(data))
        pb.futures.append(fut)
        pb.n_words += data.shape[1]
        word_bytes = {8: 1, 16: 2, 32: 4}[int(w)]
        if (pb.n_words * data.shape[0] * word_bytes
                >= self.max_batch_bytes):
            self._flush(key)
        elif pb.timer is None:
            pb.timer = loop.call_later(self.window_us / 1e6,
                                       self._flush, key)
        return await fut

    def _flush(self, key) -> None:
        pb = self._pending.pop(key, None)
        if pb is None:
            return
        if pb.timer is not None:
            pb.timer.cancel()
        matrix_key, w = key
        import time
        t0 = time.perf_counter()
        try:
            enc = self._encoder(matrix_key, w)
            flat = (pb.arrays[0] if len(pb.arrays) == 1
                    else np.concatenate(pb.arrays, axis=1))
            out = np.asarray(enc(flat))
        except Exception as e:
            # a device/compile failure must reach the awaiting OSD ops
            # (they would otherwise hang forever — submit_write's
            # sub-op timeout sits AFTER the encode await)
            for fut in pb.futures:
                if not fut.cancelled():
                    fut.set_exception(
                        IOError("device EC encode failed: %r" % e))
            return
        dt = time.perf_counter() - t0
        self.batches_flushed += 1
        self.items_encoded += len(pb.arrays)
        self.last_flush_s = dt
        self.flush_seconds += dt
        self.flush_history.append(dt)
        if len(self.flush_history) > 512:
            del self.flush_history[:256]
        off = 0
        for arr, fut in zip(pb.arrays, pb.futures):
            n = arr.shape[1]
            if not fut.cancelled():
                fut.set_result(out[:, off:off + n])
            off += n


def reconstruct_matrix(k: int, w: int, matrix: list[list[int]],
                       erased: tuple[int, ...],
                       have: tuple[int, ...]):
    """(rows, chosen): rows rebuild `erased` chunks directly from the
    `chosen` survivors — the decode-as-encode reformulation both
    device paths share (invert surviving rows, compose parity rows
    through the inverse).  Cached per erasure signature so a recovery
    sweep pays the O(k^3) GF inversion once, like
    ErasureCodeIsaTableCache."""
    key = (k, w, tuple(tuple(r) for r in matrix), erased, have)
    return _reconstruct_matrix_cached(key)


@functools.lru_cache(maxsize=512)
def _reconstruct_matrix_cached(key):
    k, w, matrix_t, erased, have = key
    matrix = [list(r) for r in matrix_t]
    inv, chosen = matrices.decoding_matrix(k, w, matrix, list(erased),
                                           list(have))
    rows = []
    for e in erased:
        if e < k:
            rows.append(list(inv[e]))
        else:
            coef = matrix[e - k]
            rows.append([
                functools.reduce(
                    lambda a, t: a ^ t,
                    (matrices.gf_mul(coef[j], inv[j][i], w)
                     for j in range(k)), 0)
                for i in range(k)])
    return rows, chosen
