"""jerasure-family codecs (Reed-Solomon + bitmatrix XOR codes).

Behavioral re-derivation of src/erasure-code/jerasure/
ErasureCodeJerasure.{h,cc}: technique subclasses with the same
profiles, defaults, chunk-size/alignment math (:80-103,:174-184,
:278-292) and coding matrices (via ceph_tpu.ec.matrices).  The encode
itself is a GF(2^w) region matmul (numpy host path; the TPU device
path in ceph_tpu.ec.kernels consumes the same matrices) instead of the
vendored jerasure C library.

Word order: chunks are interpreted as native little-endian w-bit words,
matching the x86 layout the reference produces.
"""

from __future__ import annotations

import math

import numpy as np

from . import gf, matrices
from .base import ErasureCode

LARGEST_VECTOR_WORDSIZE = 16  # bytes; SIMD width the reference aligns for


def _align_up(n: int, a: int) -> int:
    return n + (a - n % a) % a


class ErasureCodeJerasure(ErasureCode):
    """Common profile parsing for every jerasure technique."""

    technique = ""
    DEFAULT_K = 2
    DEFAULT_M = 1
    DEFAULT_W = 8

    def __init__(self):
        super().__init__()
        self.w = 8
        self.per_chunk_alignment = False

    def init(self, profile: dict) -> None:
        profile["technique"] = self.technique
        profile.setdefault("plugin", "jerasure")
        self.parse(profile)
        self.prepare()
        self._profile = profile

    def parse(self, profile: dict) -> None:
        self.k = self._to_int(profile, "k", self.DEFAULT_K)
        self.m = self._to_int(profile, "m", self.DEFAULT_M)
        self.w = self._to_int(profile, "w", self.DEFAULT_W)
        # opt-in gate for techniques whose parity layout is NOT
        # bit-identical to the reference (liber8tion search tables and
        # the legacy blaum_roth w=7 construction are unavailable here)
        self.allow_nonreference_layout = self._to_bool(
            profile, "jerasure-allow-nonreference-layout", "false")
        self._parse_mapping(profile)
        if self.chunk_mapping and len(self.chunk_mapping) != self.k + self.m:
            raise ValueError("mapping %r maps %d chunks, expected %d" % (
                profile.get("mapping"), len(self.chunk_mapping), self.k + self.m))
        self.sanity_check_k_m()

    def prepare(self) -> None:
        raise NotImplementedError

    def get_alignment(self) -> int:
        raise NotImplementedError

    def get_chunk_size(self, object_size: int) -> int:
        alignment = self.get_alignment()
        if self.per_chunk_alignment:
            chunk_size = -(-object_size // self.k)
            if chunk_size % alignment:
                chunk_size = _align_up(chunk_size, alignment)
            return chunk_size
        padded = _align_up(object_size, alignment)
        assert padded % self.k == 0
        return padded // self.k


class _MatrixTechnique(ErasureCodeJerasure):
    """Plain GF(2^w) matrix encode over w-bit words (reed_sol family)."""

    def __init__(self):
        super().__init__()
        self.matrix: list[list[int]] = []

    def _device_matrix(self):
        return self.matrix, self.w

    def get_alignment(self) -> int:
        if self.per_chunk_alignment:
            return self.w * LARGEST_VECTOR_WORDSIZE
        alignment = self.k * self.w * 4
        if (self.w * 4) % LARGEST_VECTOR_WORDSIZE:
            alignment = self.k * self.w * LARGEST_VECTOR_WORDSIZE
        return alignment

    def _word_view(self, chunk: bytes) -> np.ndarray:
        if self.w == 8:
            return np.frombuffer(chunk, dtype=np.uint8)
        if self.w == 16:
            return np.frombuffer(chunk, dtype="<u2")
        return np.frombuffer(chunk, dtype="<u4")

    def encode_chunks(self, chunks: dict[int, bytes]) -> dict[int, bytes]:
        data = np.stack([self._word_view(chunks[self.chunk_index(i)])
                         for i in range(self.k)])
        mat = np.array(self.matrix, dtype=np.uint32)
        parity = gf.matmul_words(mat, data, self.w)
        out = dict(chunks)
        for i in range(self.m):
            out[self.chunk_index(self.k + i)] = parity[i].tobytes()
        return out

    def decode_chunks(self, want_to_read, chunks) -> dict[int, bytes]:
        k, m, w = self.k, self.m, self.w
        chunks = self._to_logical(chunks)
        have = sorted(chunks)
        erased = [i for i in range(k + m) if i not in chunks]
        inv, chosen = matrices.decoding_matrix(k, w, self.matrix, erased, have)
        rows = np.stack([self._word_view(chunks[c]) for c in chosen])
        # recover all data words, then re-encode any erased parity
        data_mat = gf.matmul_words(np.array(inv, dtype=np.uint32), rows, w)
        out: dict[int, bytes] = {}
        for i in erased:
            if i < k:
                out[i] = data_mat[i].tobytes()
            else:
                coef = np.array([self.matrix[i - k]], dtype=np.uint32)
                out[i] = gf.matmul_words(coef, data_mat, w)[0].tobytes()
        return self._from_logical(out)


class ReedSolomonVandermonde(_MatrixTechnique):
    technique = "reed_sol_van"
    DEFAULT_K, DEFAULT_M, DEFAULT_W = 7, 3, 8

    def parse(self, profile: dict) -> None:
        super().parse(profile)
        if self.w not in (8, 16, 32):
            raise ValueError("reed_sol_van: w=%d must be 8, 16 or 32" % self.w)
        self.per_chunk_alignment = self._to_bool(
            profile, "jerasure-per-chunk-alignment", "false")

    def prepare(self) -> None:
        self.matrix = matrices.reed_sol_vandermonde_coding_matrix(
            self.k, self.m, self.w)


class ReedSolomonRAID6(_MatrixTechnique):
    technique = "reed_sol_r6_op"
    DEFAULT_K, DEFAULT_M, DEFAULT_W = 7, 2, 8

    def parse(self, profile: dict) -> None:
        super().parse(profile)
        if self.m != 2:
            raise ValueError("reed_sol_r6_op: m=%d must be 2" % self.m)
        if self.w not in (8, 16, 32):
            raise ValueError("reed_sol_r6_op: w=%d must be 8, 16 or 32" % self.w)

    def prepare(self) -> None:
        self.matrix = matrices.reed_sol_r6_coding_matrix(self.k, self.w)


class _BitmatrixTechnique(ErasureCodeJerasure):
    """Bit-sliced XOR encode driven by a (m*w) x (k*w) bitmatrix.

    Chunk layout (jerasure schedule encode): a chunk is a sequence of
    windows of w packets x packetsize bytes; bit-row l of a chunk within
    a window is packet l. Coding packet (i,l) = XOR of data packets
    (j,x) where bitmatrix[i*w+l][j*w+x] is set.
    """

    DEFAULT_PACKETSIZE = 2048

    def __init__(self):
        super().__init__()
        self.packetsize = self.DEFAULT_PACKETSIZE
        self.bitmatrix: list[list[int]] = []
        self.matrix: list[list[int]] | None = None  # GF form when known

    supports_per_chunk_alignment = True  # cauchy only, like the reference

    def parse(self, profile: dict) -> None:
        super().parse(profile)
        self.packetsize = self._to_int(
            profile, "packetsize", self.DEFAULT_PACKETSIZE)
        if self.supports_per_chunk_alignment:
            self.per_chunk_alignment = self._to_bool(
                profile, "jerasure-per-chunk-alignment", "false")
        if (self.per_chunk_alignment
                and (self.w * self.packetsize) % LARGEST_VECTOR_WORDSIZE):
            # chunk sizes would not be whole w*packetsize windows; reject
            # at profile parse (the _packets guard stays as a backstop)
            raise ValueError(
                "%s: per-chunk alignment requires w*packetsize (%d) to be "
                "a multiple of %d; chunks would contain a partial window"
                % (self.technique, self.w * self.packetsize,
                   LARGEST_VECTOR_WORDSIZE))

    def get_alignment(self) -> int:
        if self.per_chunk_alignment:
            # ErasureCodeJerasureCauchy::get_alignment: w*packetsize
            # rounded UP to the SIMD width (not the lcm) — chunk sizes
            # must match the reference byte-for-byte.  When the result
            # is not a whole number of w*packetsize windows the encode
            # path rejects the profile loudly (the reference would feed
            # jerasure a partial window).
            return _align_up(self.w * self.packetsize,
                             LARGEST_VECTOR_WORDSIZE)
        alignment = self.k * self.w * self.packetsize * 4
        if (self.w * self.packetsize * 4) % LARGEST_VECTOR_WORDSIZE:
            alignment = self.k * self.w * self.packetsize * \
                LARGEST_VECTOR_WORDSIZE
        return alignment

    def _packets(self, chunk: bytes) -> np.ndarray:
        """(n_windows, w, packetsize) uint8 view."""
        window = self.w * self.packetsize
        if len(chunk) % window:
            raise ValueError(
                "%s: chunk of %d bytes is not a whole number of "
                "w*packetsize=%d windows (profile would feed the "
                "reference a partial window)"
                % (self.technique, len(chunk), window))
        a = np.frombuffer(chunk, dtype=np.uint8)
        return a.reshape(-1, self.w, self.packetsize)

    def _bm(self) -> np.ndarray:
        return np.array(self.bitmatrix, dtype=bool)

    def encode_chunks(self, chunks: dict[int, bytes]) -> dict[int, bytes]:
        k, m, w = self.k, self.m, self.w
        data = np.stack([self._packets(chunks[self.chunk_index(i)])
                         for i in range(k)])  # (k, nw, w, ps)
        nw, ps = data.shape[1], data.shape[3]
        flat = data.transpose(0, 2, 1, 3).reshape(k * w, nw * ps)
        bm = self._bm()
        out = dict(chunks)
        for i in range(m):
            cpk = np.zeros((w, nw * ps), dtype=np.uint8)
            for l in range(w):
                sel = flat[bm[i * w + l]]
                if len(sel):
                    cpk[l] = np.bitwise_xor.reduce(sel, axis=0)
            chunk = cpk.reshape(w, nw, ps).transpose(1, 0, 2)
            out[self.chunk_index(k + i)] = np.ascontiguousarray(chunk).tobytes()
        return out

    def decode_chunks(self, want_to_read, chunks) -> dict[int, bytes]:
        """Invert the bit-level generator restricted to surviving chunks."""
        k, m, w = self.k, self.m, self.w
        chunks = self._to_logical(chunks)
        erased = [i for i in range(k + m) if i not in chunks]
        have = sorted(chunks)[:k]
        rows = matrices.survivor_bitrows(k, w, self.bitmatrix, have)
        inv = matrices.gf2_invert(rows)
        data_flat = np.stack([self._packets(chunks[c]) for c in have])
        nw, ps = data_flat.shape[1], data_flat.shape[3]
        flat = data_flat.transpose(0, 2, 1, 3).reshape(k * w, nw * ps)
        inv_b = np.array(inv, dtype=bool)
        rec = np.zeros((k * w, nw * ps), dtype=np.uint8)
        for r in range(k * w):
            sel = flat[inv_b[r]]
            if len(sel):
                rec[r] = np.bitwise_xor.reduce(sel, axis=0)
        out: dict[int, bytes] = {}
        for i in erased:
            if i < k:
                chunk = rec[i * w:(i + 1) * w].reshape(w, nw, ps)
                out[i] = np.ascontiguousarray(
                    chunk.transpose(1, 0, 2)).tobytes()
        if any(i >= k for i in erased):
            bm = self._bm()
            for i in erased:
                if i >= k:
                    cpk = np.zeros((w, nw * ps), dtype=np.uint8)
                    for l in range(w):
                        sel = rec[bm[(i - k) * w + l]]
                        if len(sel):
                            cpk[l] = np.bitwise_xor.reduce(sel, axis=0)
                    out[i] = np.ascontiguousarray(
                        cpk.reshape(w, nw, ps).transpose(1, 0, 2)).tobytes()
        return self._from_logical(out)


class CauchyOrig(_BitmatrixTechnique):
    technique = "cauchy_orig"
    DEFAULT_K, DEFAULT_M, DEFAULT_W = 7, 3, 8

    def prepare(self) -> None:
        self.matrix = matrices.cauchy_original_coding_matrix(
            self.k, self.m, self.w)
        self.bitmatrix = matrices.matrix_to_bitmatrix(
            self.k, self.m, self.w, self.matrix)


class CauchyGood(_BitmatrixTechnique):
    technique = "cauchy_good"
    DEFAULT_K, DEFAULT_M, DEFAULT_W = 7, 3, 8

    def prepare(self) -> None:
        self.matrix = matrices.cauchy_good_general_coding_matrix(
            self.k, self.m, self.w)
        self.bitmatrix = matrices.matrix_to_bitmatrix(
            self.k, self.m, self.w, self.matrix)


class Liberation(_BitmatrixTechnique):
    """RAID-6 liberation codes (Plank): w prime, k <= w, minimal-density
    bitmatrix = rotation blocks plus one extra bit per column."""

    technique = "liberation"
    DEFAULT_K, DEFAULT_M, DEFAULT_W = 2, 2, 7
    supports_per_chunk_alignment = False

    def parse(self, profile: dict) -> None:
        super().parse(profile)
        if self.m != 2:
            raise ValueError("%s: m must be 2" % self.technique)
        self.check_kw()
        if self.packetsize == 0:
            raise ValueError("%s: packetsize must be set" % self.technique)
        if self.packetsize % 4:
            raise ValueError("%s: packetsize %d must be a multiple of 4"
                             % (self.technique, self.packetsize))

    def check_kw(self) -> None:
        if self.k > self.w:
            raise ValueError("liberation: k=%d must be <= w=%d"
                             % (self.k, self.w))
        if self.w <= 2 or not _is_prime(self.w):
            raise ValueError("liberation: w=%d must be prime > 2" % self.w)

    def prepare(self) -> None:
        k, w = self.k, self.w
        bits = [[0] * (k * w) for _ in range(2 * w)]
        for j in range(k):
            for r in range(w):
                bits[r][j * w + r] = 1                    # P: identity blocks
                bits[w + r][j * w + (r + j) % w] = 1      # Q: rotation by j
        for j in range(1, k):
            y = (j * ((w - 1) // 2)) % w                  # the extra "jay" bit
            bits[w + y][j * w + (y + j - 1) % w] ^= 1
        self.bitmatrix = bits


def _is_prime(v: int) -> bool:
    if v < 2:
        return False
    f = 2
    while f * f <= v:
        if v % f == 0:
            return False
        f += 1
    return True


class BlaumRoth(Liberation):
    """RAID-6 over the ring GF(2)[x]/M_p(x), p = w+1 prime: Q block for
    column j is the multiply-by-x^j matrix in the ring."""

    technique = "blaum_roth"

    def check_kw(self) -> None:
        if self.k > self.w:
            raise ValueError("blaum_roth: k=%d must be <= w=%d"
                             % (self.k, self.w))
        # w=7 tolerated for backward compatibility with old default
        if self.w != 7 and (self.w <= 2 or not _is_prime(self.w + 1)):
            raise ValueError("blaum_roth: w+1=%d must be prime" % (self.w + 1))
        if self.w == 7 and not self.allow_nonreference_layout:
            raise ValueError(
                "blaum_roth w=7: the legacy reference construction is not "
                "implemented bit-identically; chunks written by a "
                "reference cluster would decode WRONG.  Set "
                "jerasure-allow-nonreference-layout=true to accept a "
                "self-consistent (but non-interoperable) layout, or use "
                "a w with w+1 prime.")

    def prepare(self) -> None:
        k, w = self.k, self.w
        if w == 7:
            # w+1=8 is not prime, so the ring construction is not MDS; the
            # reference tolerates 7 for legacy pools. Serve it with a
            # GF(2^7) RAID6 generator bitmatrix (decodable; documented
            # divergence from the legacy layout).
            mat = matrices.reed_sol_r6_coding_matrix(k, 7)
            self.matrix = mat
            self.bitmatrix = matrices.matrix_to_bitmatrix(k, 2, 7, mat)
            return
        p = w + 1

        def mulx_pow(vec: list[int], times: int) -> list[int]:
            # multiply polynomial (deg < w) by x^times mod M_p(x) where
            # M_p(x) = 1 + x + ... + x^(p-1); representation deg < w
            v = list(vec)
            for _ in range(times):
                carry = v[w - 1]
                v = [0] + v[:-1]
                if carry:  # x^w = sum_{i<w} x^i  (since M_p(x) = 0)
                    v = [b ^ 1 for b in v]
            return v

        bits = [[0] * (k * w) for _ in range(2 * w)]
        for j in range(k):
            for r in range(w):
                bits[r][j * w + r] = 1
                basis = [1 if t == r else 0 for t in range(w)]
                col = mulx_pow(basis, j)
                for l in range(w):
                    if col[l]:
                        bits[w + l][j * w + r] = 1
        self.bitmatrix = bits


class Liber8tion(Liberation):
    """m=2, w=8 search-derived minimal-density code.  The reference uses
    matrices found by exhaustive search (liber8tion.c tables); this build
    uses the RAID6 generator expanded to a bitmatrix — same profile and
    layout, not bit-identical parity (documented divergence)."""

    technique = "liber8tion"
    DEFAULT_K, DEFAULT_M, DEFAULT_W = 2, 2, 8

    def check_kw(self) -> None:
        if self.w != 8:
            raise ValueError("liber8tion: w must be 8")
        if self.k > self.w:
            raise ValueError("liber8tion: k=%d must be <= 8" % self.k)
        if not self.allow_nonreference_layout:
            raise ValueError(
                "liber8tion: the reference's search-derived liber8tion.c "
                "bitmatrices are not available; parity would not be "
                "bit-identical and chunks written by a reference cluster "
                "would decode WRONG.  Set "
                "jerasure-allow-nonreference-layout=true to accept a "
                "self-consistent (but non-interoperable) layout.")

    def prepare(self) -> None:
        mat = matrices.reed_sol_r6_coding_matrix(self.k, 8)
        self.matrix = mat
        self.bitmatrix = matrices.matrix_to_bitmatrix(self.k, 2, 8, mat)


TECHNIQUES = {
    cls.technique: cls for cls in (
        ReedSolomonVandermonde, ReedSolomonRAID6, CauchyOrig, CauchyGood,
        Liberation, BlaumRoth, Liber8tion)
}


def make_codec(profile: dict):
    technique = profile.get("technique", "reed_sol_van")
    cls = TECHNIQUES.get(technique)
    if cls is None:
        raise ValueError("jerasure: unknown technique %r" % technique)
    codec = cls()
    codec.init(profile)
    return codec
